// Fast SQL tokenizer — native backend for fugue_tpu/sql/parser.py.
//
// Parity story: the reference ships an optional C++ ANTLR parser
// ("cpp_sql_parser" extra, reference setup.py:50) for FugueSQL parsing
// speed; this is the equivalent native layer for the in-tree SQL stack.
// Exposed through a minimal C ABI consumed via ctypes (no pybind11 in the
// build image).
//
// Token kinds (must match fugue_tpu/sql/parser.py):
//   0 IDENT, 1 QIDENT, 2 STRING, 3 NUMBER, 4 OP, 5 PUNCT

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cctype>

extern "C" {

struct FtToken {
    int kind;
    int pos;   // byte offset of the token in the source
    int len;   // byte length INCLUDING quotes for STRING/QIDENT
};

// Returns 0 on success; negative = error code, err holds a message.
// On success *out_tokens is a malloc'd array of *out_count tokens the
// caller must release with ft_free.
int ft_tokenize(const char* sql, int n, FtToken** out_tokens, int* out_count,
                char* err, int errcap) {
    int cap = 256;
    int count = 0;
    FtToken* toks = (FtToken*)malloc(sizeof(FtToken) * cap);
    if (toks == nullptr) return -1;

    auto push = [&](int kind, int pos, int len) -> bool {
        if (count == cap) {
            cap *= 2;
            FtToken* nt = (FtToken*)realloc(toks, sizeof(FtToken) * cap);
            if (nt == nullptr) {
                free(toks);  // realloc failure leaves the old block live
                toks = nullptr;
                return false;
            }
            toks = nt;
        }
        toks[count].kind = kind;
        toks[count].pos = pos;
        toks[count].len = len;
        ++count;
        return true;
    };

    auto fail = [&](const char* msg, int pos) -> int {
        if (err != nullptr && errcap > 0) {
            snprintf(err, (size_t)errcap, "%s at %d", msg, pos);
        }
        free(toks);
        return -2;
    };

    int i = 0;
    while (i < n) {
        unsigned char c = (unsigned char)sql[i];
        if (isspace(c)) { ++i; continue; }
        // comments
        if (c == '-' && i + 1 < n && sql[i + 1] == '-') {
            while (i < n && sql[i] != '\n') ++i;
            continue;
        }
        if (c == '/' && i + 1 < n && sql[i + 1] == '*') {
            int j = i + 2;
            while (j + 1 < n && !(sql[j] == '*' && sql[j + 1] == '/')) ++j;
            i = (j + 1 < n) ? j + 2 : n;
            continue;
        }
        // strings (' or "), '' escapes
        if (c == '\'' || c == '"') {
            char q = (char)c;
            int j = i + 1;
            while (j < n) {
                if (sql[j] == q) {
                    if (j + 1 < n && sql[j + 1] == q) { j += 2; continue; }
                    break;
                }
                ++j;
            }
            if (j >= n) return fail("unterminated string", i);
            if (!push(2, i, j - i + 1)) return -1;
            i = j + 1;
            continue;
        }
        // backtick identifiers
        if (c == '`') {
            int j = i + 1;
            while (j < n && sql[j] != '`') ++j;
            if (j >= n) return fail("unterminated identifier", i);
            if (!push(1, i, j - i + 1)) return -1;
            i = j + 1;
            continue;
        }
        // numbers
        if (isdigit(c) || (c == '.' && i + 1 < n && isdigit((unsigned char)sql[i + 1]))) {
            int j = i;
            bool seen_dot = false;
            while (j < n && (isdigit((unsigned char)sql[j]) ||
                             (sql[j] == '.' && !seen_dot))) {
                if (sql[j] == '.') seen_dot = true;
                ++j;
            }
            if (j < n && (sql[j] == 'e' || sql[j] == 'E')) {
                int k = j + 1;
                if (k < n && (sql[k] == '+' || sql[k] == '-')) ++k;
                if (k < n && isdigit((unsigned char)sql[k])) {
                    while (k < n && isdigit((unsigned char)sql[k])) ++k;
                    j = k;
                }
            }
            if (!push(3, i, j - i)) return -1;
            i = j;
            continue;
        }
        // identifiers
        if (isalpha(c) || c == '_') {
            int j = i;
            while (j < n && (isalnum((unsigned char)sql[j]) || sql[j] == '_')) ++j;
            if (!push(0, i, j - i)) return -1;
            i = j;
            continue;
        }
        // two-char operators
        if (i + 1 < n) {
            char a = sql[i], b = sql[i + 1];
            if ((a == '<' && (b == '>' || b == '=')) ||
                (a == '>' && b == '=') ||
                (a == '!' && b == '=') ||
                (a == '=' && b == '=')) {
                if (!push(4, i, 2)) return -1;
                i += 2;
                continue;
            }
        }
        if (strchr("+-*/%<>=", c) != nullptr) {
            if (!push(4, i, 1)) return -1;
            ++i;
            continue;
        }
        if (strchr("(),.;[]{}:?", c) != nullptr) {
            if (!push(5, i, 1)) return -1;
            ++i;
            continue;
        }
        return fail("unexpected character", i);
    }
    *out_tokens = toks;
    *out_count = count;
    return 0;
}

void ft_free(FtToken* tokens) { free(tokens); }

}  // extern "C"
