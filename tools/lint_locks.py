#!/usr/bin/env python
"""Repo concurrency lint (``make lint-locks``) — ISSUE 11 satellite.

Extends the ISSUE 10 shared-engine concurrency audit into a repeatable
AST check: inside classes that own a lock (``self._lock`` / ``self._rlock``
/ ``self._cv`` assigned in ``__init__``), every write to a shared mutable
attribute (``self.x = ...`` / ``self.x += 1``) must happen lexically
under ``with self.<lock>:`` — the audited narrow-lock pattern
(``PlanStats.inc``, ``CacheStats``, ``ShuffleStats``, ``AnalysisStats``,
the engine's double-checked lazy singletons). A bare ``+=`` on one of
these is exactly the lost-update class of bug the ISSUE 10 hammer caught.

Heuristic, not a proof — so it is wired into ``make test`` as a
NON-blocking report. Conventions it understands:

- ``__init__`` writes are construction-time (single-threaded) — skipped;
- methods named ``reset``/``clear`` that open with a lock are fine
  (covered by the lexical check anyway);
- methods whose name ends in ``_locked`` are called under the caller's
  lock — skipped;
- attributes in PER_CLASS_ALLOW are audited-safe (e.g. deliberate
  lock-free idioms documented in the code, like JitCache's racing
  compile-insert where both winners are identical).

Run ``python tools/lint_locks.py --strict`` to exit non-zero on findings.
"""

import argparse
import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_ROOT = os.path.join(REPO, "fugue_tpu")

LOCK_ATTRS = {"_lock", "_rlock", "_cv"}

# (class, attr) writes that are audited-safe by design. Keep this SHORT —
# every entry should correspond to a comment in the source explaining why
# the lock-free write is sound.
PER_CLASS_ALLOW: Set[Tuple[str, str]] = {
    # JitCache: the key-not-in-cache compile idiom deliberately stays
    # lock-free — racing compiles are identical and the 2nd insert
    # replaces the 1st (ISSUE 10 audit note)
    ("JitCache", "_cache"),
}

# attribute-name prefixes that are configuration/identity set once at
# construction or under external orchestration, not shared counters
SKIP_PREFIXES = ("_lock", "_rlock", "_cv", "__")


def _lock_names(cls: ast.ClassDef) -> Set[str]:
    """Lock attributes assigned in __init__ (self._lock = Lock() style)."""
    names: Set[str] = set()
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and t.attr in LOCK_ATTRS
                        ):
                            names.add(t.attr)
    return names


def _with_holds_lock(w: ast.With, locks: Set[str]) -> bool:
    for item in w.items:
        for sub in ast.walk(item.context_expr):
            if (
                isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and sub.attr in locks
            ):
                return True
    return False


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking whether the current node is inside a
    ``with self.<lock>:`` block."""

    def __init__(self, cls: str, method: str, locks: Set[str], findings: list):
        self.cls = cls
        self.method = method
        self.locks = locks
        self.findings = findings
        self.depth = 0  # with-lock nesting

    def visit_With(self, node: ast.With) -> None:
        held = _with_holds_lock(node, self.locks)
        if held:
            self.depth += 1
        self.generic_visit(node)
        if held:
            self.depth -= 1

    def _check_target(self, t: ast.expr, lineno: int) -> None:
        if (
            isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
            and not any(t.attr.startswith(p) for p in SKIP_PREFIXES)
            and (self.cls, t.attr) not in PER_CLASS_ALLOW
            and self.depth == 0
        ):
            self.findings.append((self.cls, self.method, t.attr, lineno))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    # nested defs get their own checker scope skipped (closures run later,
    # possibly under different locking); keep the lint focused
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass


def lint_file(path: str) -> List[Tuple[str, str, str, str, int]]:
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return []
    rel = os.path.relpath(path, REPO)
    out: List[Tuple[str, str, str, str, int]] = []
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        locks = _lock_names(cls)
        if not locks:
            continue
        for m in cls.body:
            if not isinstance(m, ast.FunctionDef):
                continue
            if m.name == "__init__" or m.name.endswith("_locked"):
                continue
            findings: list = []
            checker = _MethodChecker(cls.name, m.name, locks, findings)
            for stmt in m.body:
                checker.visit(stmt)
            out.extend((rel, c, meth, attr, ln) for c, meth, attr, ln in findings)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--strict", action="store_true", help="exit 1 when findings exist"
    )
    ap.add_argument("paths", nargs="*", help="files to lint (default: fugue_tpu/)")
    args = ap.parse_args()
    files: List[str] = []
    if args.paths:
        files = args.paths
    else:
        for root, _dirs, names in os.walk(SCAN_ROOT):
            if "__pycache__" in root:
                continue
            files.extend(
                os.path.join(root, n) for n in sorted(names) if n.endswith(".py")
            )
    findings = []
    for p in files:
        findings.extend(lint_file(p))
    for rel, cls, meth, attr, ln in findings:
        print(
            f"{rel}:{ln}: {cls}.{meth} writes shared attribute "
            f"'self.{attr}' outside 'with self.<lock>:'"
        )
    n = len(findings)
    print(
        f"lint-locks: {n} unguarded shared-attribute write(s) in "
        f"{len(files)} file(s)"
        + ("" if n == 0 else " -- audit each or add to PER_CLASS_ALLOW")
    )
    return 1 if (args.strict and n > 0) else 0


if __name__ == "__main__":
    sys.exit(main())
