#!/usr/bin/env python
"""Render a cluster post-mortem timeline from a flight-recorder dir.

ISSUE 18 tentpole, piece 2: every recovery-ladder event the dist/serve
tiers take (lease steal, heartbeat expiry, re-dispatch, orphan
invalidation, speculative twin, fleet failover, journal replay) lands as
one typed JSON line in ``<events_dir>/<host>-<pid>.events.jsonl``
(:mod:`fugue_tpu.obs.events`). This CLI merges every process's file and
prints the human-readable timeline — the "what actually happened"
reconstruction after a chaos run or a production incident::

    python tools/fugue_timeline.py /tmp/events
    python tools/fugue_timeline.py /tmp/events --trace 3f2a9c...   # one run
    python tools/fugue_timeline.py /tmp/events --view hourly_agg   # one view
    python tools/fugue_timeline.py /tmp/events --json              # raw records

``--view`` reconstructs one continuous view's full history (ISSUE 20,
docs/views.md) from the log alone: registration, every lease
acquire/steal, every refresh with its delta/full mode and partition
counts, every published generation with its priority, SLO breaches, and
unregistration — the ``view.*`` event types.

Exit codes: 0 = rendered, 2 = no events found (wrong dir, or
``fugue.tpu.events.enabled`` was never on).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events_dir", help="the fugue.tpu.events.dir to read")
    ap.add_argument(
        "--trace",
        default=None,
        help="keep only one run's events (a 16-hex trace id; "
        "trace-less records like chaos injections are kept)",
    )
    ap.add_argument(
        "--view",
        default=None,
        help="keep only one continuous view's history (the view id): "
        "its view.* lifecycle events, reconstructed from the log alone",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="print the merged raw records as JSON lines instead",
    )
    args = ap.parse_args(argv)

    from fugue_tpu.obs.events import read_events, render_timeline

    events = read_events(args.events_dir)
    if args.trace is not None:
        events = [e for e in events if e.get("trace") in (args.trace, None)]
    if args.view is not None:
        events = [
            e
            for e in events
            if e.get("type", "").startswith("view.")
            and e.get("view") == args.view
        ]
    if not events:
        print(f"no events found under {args.events_dir}", file=sys.stderr)
        return 2
    if args.json:
        for e in events:
            print(json.dumps(e, sort_keys=True))
        return 0
    print(render_timeline(events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
