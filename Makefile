.PHONY: install test bench dryrun native

# editable install so examples/notebooks import fugue_tpu without PYTHONPATH
# (--no-build-isolation: the env is offline; the baked-in setuptools builds it)
install:
	pip install -e . --no-deps --no-build-isolation

test:
	python -m pytest tests/ -q

bench:
	python bench.py

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 python -c "import jax; jax.config.update('jax_platforms','cpu'); import __graft_entry__ as g; g.dryrun_multichip(8)"

native:
	python -c "from fugue_tpu.native import build; assert build(force=True)"
