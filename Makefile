.PHONY: install test test-multihost test-resilience test-obs test-plan test-lowering test-cache test-delta test-shuffle test-exchange test-serve test-dist test-views test-analysis test-tuning lint-locks cache-clean trace-smoke telemetry-smoke timeline-smoke serve-smoke fleet-smoke dist-smoke view-smoke bench bench-smoke dryrun native

# editable install so examples/notebooks import fugue_tpu without PYTHONPATH
# (--no-build-isolation: the env is offline; the baked-in setuptools builds it)
install:
	pip install -e . --no-deps --no-build-isolation

# the three smoke gates below are non-blocking in `make test` (their
# dedicated targets stay blocking) — but a failure must never be SILENT:
# each emits a one-line WARNING so a regressed chaos/perf gate is visible
# in CI logs instead of scrolling past as an ignored make error.
# dist-smoke is BLOCKING (ISSUE 16): workflow.run now routes through the
# dist tier, so its chaos ladder is tier-1 behavior; set
# DIST_SMOKE_NONBLOCKING=1 to demote it back to a report while iterating
# on a known dist change
test:
	python -m pytest tests/ -q
	python tools/lint_locks.py --strict         # concurrency audit; BLOCKING (ISSUE 12)
	-@$(MAKE) --no-print-directory bench-smoke  || echo "WARNING: bench-smoke FAILED (non-blocking in 'make test'); run 'make bench-smoke' to reproduce"
	-@$(MAKE) --no-print-directory serve-smoke  || echo "WARNING: serve-smoke FAILED (non-blocking in 'make test'); run 'make serve-smoke' to reproduce"
	-@$(MAKE) --no-print-directory fleet-smoke  || echo "WARNING: fleet-smoke FAILED (non-blocking in 'make test'); run 'make fleet-smoke' to reproduce"
	-@$(MAKE) --no-print-directory view-smoke   || echo "WARNING: view-smoke FAILED (non-blocking in 'make test'); run 'make view-smoke' to reproduce"
	-@$(MAKE) --no-print-directory timeline-smoke || echo "WARNING: timeline-smoke FAILED (non-blocking in 'make test'); run 'make timeline-smoke' to reproduce"
	@if [ "$$DIST_SMOKE_NONBLOCKING" = "1" ]; then \
	  $(MAKE) --no-print-directory dist-smoke || echo "WARNING: dist-smoke FAILED (demoted by DIST_SMOKE_NONBLOCKING=1); run 'make dist-smoke' to reproduce"; \
	else \
	  $(MAKE) --no-print-directory dist-smoke; \
	fi

# downsized perf gate (≤~30s): device-aggregate worker only, fails when the
# oracle-normalized groupby_aggregate vs_baseline drops >20% below the
# recorded value (BENCH_SMOKE_BASELINE.json for this env, else BENCH_r05).
# --compare is a BLOCKING gate (exit 8 on any metric regression vs the
# committed smoke baseline); set BENCH_COMPARE_NONBLOCKING=1 to demote it
# back to a report while iterating on a known perf change
bench-smoke:
	python bench.py --smoke
	@if [ "$$BENCH_COMPARE_NONBLOCKING" = "1" ]; then \
	  python bench.py --compare BENCH_SMOKE_BASELINE.json || true; \
	else \
	  python bench.py --compare BENCH_SMOKE_BASELINE.json; \
	fi

# large-scale proofs (100M-row streaming, 100Mx1M join) — excluded from the
# default run by addopts='-m "not slow"'; the explicit -m here overrides it
test-slow:
	python -m pytest tests/ -q -m slow

# the multihost job: engine verbs + collectives across a REAL 2-process
# jax.distributed mesh (each worker is its own OS process)
test-multihost:
	python -m pytest tests/core/test_multihost.py -q -m "slow or not slow"

# fault-injection suite (docs/resilience.md): worker SIGKILL recovery,
# chunk deadlines, poison quarantine, RPC retry, checkpoint-aware replay.
# not marked slow — tier-1 runs it too; this target is the focused loop
test-resilience:
	JAX_PLATFORMS=cpu python -m pytest tests/core/test_resilience.py -q -m "not slow"

# observability suite (docs/observability.md): span-tree shape, Chrome
# trace export, disabled-path overhead guard, fork-boundary round trip
test-obs:
	JAX_PLATFORMS=cpu python -m pytest tests/obs -q -m "not slow"

# plan-optimizer suite (docs/plan.md): optimized-vs-unoptimized parity
# (bit-identical), pruning-reaches-producer spies, fusion span shape,
# UDF no-op guard, conf gates. Part of `make test` (tests/ includes it)
test-plan:
	JAX_PLATFORMS=cpu python -m pytest tests/plan -q -m "not slow"

# segment-lowering suite (docs/plan.md): lowered-vs-unlowered parity
# across aggregate/take/distinct/join/SQL (bounded + streaming), refusal
# fallback span/result identity, plan.segment span shape + one jit entry
# per segment, conf gate, explain rendering
test-lowering:
	JAX_PLATFORMS=cpu python -m pytest tests/plan/test_lowering.py -q -m "not slow"

# out-of-core shuffle suite (docs/shuffle.md): in-device exchange tests
# plus the spill path — spill-vs-legacy join parity (dup/NULL keys, all
# hash-partitionable types), bounded peak_device_bytes at 10x the budget,
# hash-repartition round trip, torn-spill recovery, conf gates
test-shuffle:
	JAX_PLATFORMS=cpu python -m pytest tests/jax_engine/test_shuffle.py -q -m "not slow"

# device-resident staged exchange suite (docs/shuffle.md
# "device_exchange"): rung parity vs spill and the legacy ladder across
# dup/NULL/-0.0/tz-aware keys, kill-switch bit-identity with identical
# engine-verb span multisets, over-budget forced spill fallback, the
# staged-schedule peak-stage-bytes bound from the high-water gauge, and
# the mem-bucket decoded-form ingest cache
test-exchange:
	JAX_PLATFORMS=cpu python -m pytest tests/jax_engine/test_device_exchange.py -q -m "not slow"

# result-cache suite (docs/cache.md): cached-hit parity, invalidation
# (mutated files / edited UDFs / partition specs), poisoned-subtree
# refusal, publish races, torn artifacts, persist-across-restart — plus
# the partition-level delta suite (test-delta below)
test-cache:
	JAX_PLATFORMS=cpu python -m pytest tests/cache -q -m "not slow"

# partition-level incremental recompute suite (docs/cache.md "Incremental
# recompute"): grown-source delta parity across fused-chain / filter /
# dense-aggregate shapes × jax/native engines × optimizer on/off, the
# refusal ladder (changed contents, reordered partitions, non-row-local
# verbs), grown single-file append detection, manifest/eviction
# consistency, two-process append races, persist of delta-merged frames
test-delta:
	JAX_PLATFORMS=cpu python -m pytest tests/cache/test_delta_cache.py -q -m "not slow"

# UDF static-analysis suite (docs/analysis.md): translated-vs-interpreted
# parity across engines × optimizer on/off × bounded/streaming, the
# refusal matrix (globals, mutable closures, .apply, loops, unknown
# methods, non-determinism — each bit-identical with the reason rendered
# in explain()), pruning-reaches-producer under analyzed UDFs, delta
# serving of analyzed row-local chains, fingerprint invalidation on edit,
# workflow.lint() diagnostics, analysis counters + /metrics exposition
test-analysis:
	JAX_PLATFORMS=cpu python -m pytest tests/analysis -q -m "not slow"

# cost-based adaptive execution suite (docs/tuning.md): the _tuned.json
# lifecycle (atomic publish under a two-process race, corrupt file →
# defaults with ONE warning, stale-fingerprint eviction), the adjustment
# policy units, kill-switch bit-identity, per-stream pipeline stats,
# explain()/stats()/metrics rendering, and warm-run convergence
test-tuning:
	JAX_PLATFORMS=cpu python -m pytest tests/tuning -q -m "not slow"

# repo concurrency lint (ISSUE 10 audit as a repeatable AST check): flags
# writes to shared-engine mutable attributes outside the audited lock
# helpers. Zero findings since ISSUE 12 — `make test` enforces it with
# --strict (blocking); this target stays the report-only loop
lint-locks:
	python tools/lint_locks.py

# multi-tenant serving suite (docs/serving.md): admission queue + tenant
# budgets + priority aging, plan-fingerprint single-flight (one shared
# execution, cancel-safe waiters), the /serve/* RPC surface with
# idempotency keys, /healthz-vs-/readyz split, and the shared-engine
# concurrency regression hammer (bit-identical results, coherent counters)
test-serve:
	JAX_PLATFORMS=cpu python -m pytest tests/serve -q -m "not slow"

# serving load gate (ISSUE 10 acceptance, exit 12): 8 concurrent clients
# × 4 tenants × mixed workloads (cached hit / broadcast join / streaming
# aggregate / delta append) through ONE EngineServer — zero failed
# submissions, dedup_hits >= 1 with shared executions, per-tenant
# p50/p99 + rows/s, results bit-identical to serial cache-off runs
serve-smoke:
	JAX_PLATFORMS=cpu python bench.py --serve-smoke

# fleet chaos gate (ISSUE 13 acceptance, exit 15): 3 EngineServer
# processes sharing a store + journal dir behind a FleetClient; one
# replica SIGKILLed mid-execution — every submission completes (failover
# under the same idempotency key), the journal audit shows ZERO duplicate
# completed executions, >= 1 cross-replica dedup hit and >= 1 claim-lease
# steal observed, results bit-identical to a serial cache-off oracle
fleet-smoke:
	JAX_PLATFORMS=cpu python bench.py --fleet-smoke

# distributed worker-tier suite (docs/distributed.md): heartbeat
# freshness/staleness, lease acquire/renew/steal (expiry + heartbeat +
# pid-fallback matrix), end-to-end dist-vs-serial bit-identity, lease
# expiry mid-task re-dispatch, speculative duplicate publish (one done
# record, one artifact), supervisor restart over in-flight leases,
# remote fragment fetch + orphaned-output recovery, fault sites
test-dist:
	JAX_PLATFORMS=cpu python -m pytest tests/distributed -q -m "not slow"

# worker-tier chaos gate (ISSUE 14 acceptance, exit 16): 3 DistWorker
# processes + supervisor run a distributed load→shuffle-join→aggregate;
# the worker holding the straggler map lease is SIGKILLed mid-shuffle —
# all partitions complete via heartbeat-proven lease re-dispatch, the
# bucket audit shows ZERO lost/double-counted rows, and the result is
# bit-identical to the single-process cache-off oracle (the
# fugue.tpu.dist.enabled=false kill-switch path)
dist-smoke:
	JAX_PLATFORMS=cpu python bench.py --dist-smoke

# continuous-view suite (docs/views.md): registration WAL replay after a
# SIGKILLed registrar, per-generation bit-identity, delta refusal
# degrading to full recompute, watch-lease steal to a survivor replica,
# unregister tombstones, freshness-SLO admission boost, typed-event
# counter parity, and the fleet LRU pinning each view's latest generation
test-views:
	JAX_PLATFORMS=cpu python -m pytest tests/views -q -m "not slow"

# continuous-view chaos gate (ISSUE 20 acceptance, exit 20): 2 replicas
# share a store + journal; a view over a source grown one partition per
# round for 5 rounds is maintained while the lease-holding replica is
# SIGKILLed mid-refresh — the survivor steals the watch lease, every
# generation publishes exactly once with correct as_of, the final result
# is bit-identical to a cold cache-off oracle, and the delta path keeps
# steady-state skip_fraction >= 0.9 (no silent full recomputes)
view-smoke:
	JAX_PLATFORMS=cpu python bench.py --view-smoke

# wipe a result-cache directory's artifacts: make cache-clean CACHE_DIR=...
# (defaults to $FUGUE_TPU_CACHE_DIR)
cache-clean:
	python -c "import os; from fugue_tpu.cache import clean_cache_dir; \
	  print(clean_cache_dir('$(CACHE_DIR)' or os.environ.get('FUGUE_TPU_CACHE_DIR', '')))"

# end-to-end trace proof: run the traced smoke workflow, then assert the
# exported file is valid Chrome trace-event JSON (Perfetto-loadable)
trace-smoke:
	python bench.py --smoke --trace /tmp/fugue_trace_smoke
	python -c "from fugue_tpu.obs import validate_chrome_trace; \
	  s = validate_chrome_trace('/tmp/fugue_trace_smoke/trace.json'); \
	  print('trace OK:', s['spans'], 'spans,', s['events'], 'events')"

# live-telemetry round trip (docs/observability.md): run a small traced +
# sampled streaming workflow with /metrics bound to the engine, scrape it
# while the run is in flight, validate the Prometheus exposition, and
# assert the exported trace carries device_bytes/overlap_fraction
# Perfetto counter tracks
telemetry-smoke:
	JAX_PLATFORMS=cpu python bench.py --telemetry-smoke /tmp/fugue_telemetry_smoke

# cluster-tracing chaos gate (ISSUE 18 acceptance, exit 19): the dist
# chaos shape (3 workers, straggler's holder SIGKILLed mid-shuffle) with
# tracing + span spools + the flight recorder ON — the spools assemble
# into ONE validated Perfetto trace with >= 4 named process tracks whose
# worker spans share the run's trace id, and the kill is reconstructed
# FROM THE EVENT LOG ALONE (chaos.inject → hb.expired → lease.steal →
# task.redispatch, in order) by tools/fugue_timeline.py
timeline-smoke:
	JAX_PLATFORMS=cpu python bench.py --timeline-smoke /tmp/fugue_timeline_smoke

bench:
	python bench.py

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 python -c "import jax; jax.config.update('jax_platforms','cpu'); import __graft_entry__ as g; g.dryrun_multichip(8)"

native:
	python -c "from fugue_tpu.native import build; assert build(force=True)"
