"""Plan-fingerprint single-flight (ISSUE 10 satellite): two sessions
submitting the identical workflow concurrently share EXACTLY ONE
execution — span/count proof — both receive identical results, and a
canceled waiter never cancels the shared execution.
"""

import threading

import pandas as pd
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.obs import get_span_metrics, get_tracer
from fugue_tpu.serve import EngineServer, SubmissionCanceled


def _dag(rows: int = 256) -> FugueWorkflow:
    dag = FugueWorkflow()
    (
        dag.df(
            pd.DataFrame(
                {"k": [i % 8 for i in range(rows)], "v": [float(i) for i in range(rows)]}
            )
        )
        .filter(col("v") >= 16)
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n"))
        .yield_dataframe_as("r", as_local=True)
    )
    return dag


class _Hold:
    """Holds the single worker so identical submissions pile up queued."""

    def __init__(self) -> None:
        self.release = threading.Event()
        self.entered = threading.Event()

    def dag(self) -> FugueWorkflow:
        hold = self

        def make() -> pd.DataFrame:
            hold.entered.set()
            assert hold.release.wait(30)
            return pd.DataFrame({"a": [1]})

        dag = FugueWorkflow()
        dag.create(make, schema="a:long").yield_dataframe_as("h", as_local=True)
        return dag


@pytest.fixture
def tracing():
    tr = get_tracer()
    tr.clear()
    get_span_metrics().clear()
    tr.enable()
    yield tr
    tr.disable()
    tr.clear()
    get_span_metrics().clear()


def test_identical_concurrent_submissions_share_one_execution(tracing):
    eng = NativeExecutionEngine({FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT: 1})
    hold = _Hold()
    with EngineServer(eng) as srv:
        blocker = srv.submit(hold.dag())
        assert hold.entered.wait(30)
        # two "sessions" race identical submissions while the worker is held
        subs = []
        errs = []

        def session(i: int) -> None:
            try:
                subs.append(srv.submit(_dag, tenant=f"tenant{i}"))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=session, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        hold.release.set()
        blocker.result(timeout=60)
        results = [s.result(timeout=60) for s in subs]
    # count proof: one admitted execution served both sessions
    st = srv.stats()
    assert st["submitted"] == 3  # blocker + 2 sessions
    assert st["executions"] == 2  # blocker + ONE shared run
    assert st["dedup_hits"] == 1
    assert {subs[0].deduped, subs[1].deduped} == {True, False}
    # span proof: exactly two serve.run spans total (blocker + shared)
    runs = [r for r in tracing.records() if r["name"] == "serve.run"]
    assert len(runs) == 2, [r["args"] for r in runs]
    shared = [r for r in runs if r["args"].get("waiters", 0) >= 2]
    assert len(shared) == 1 and shared[0]["args"]["waiters"] == 2
    # identical results: the very same live frames, like a cache mem hit
    a, b = (res.yields["r"].result for res in results)
    assert a is b
    pdf = a.as_pandas()
    assert len(pdf) == 8 and pdf["n"].sum() == 256 - 16


def test_canceled_waiter_does_not_cancel_shared_execution(tracing):
    eng = NativeExecutionEngine({FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT: 1})
    hold = _Hold()
    with EngineServer(eng) as srv:
        blocker = srv.submit(hold.dag())
        assert hold.entered.wait(30)
        keeper = srv.submit(_dag, tenant="keeper")
        quitter = srv.submit(_dag, tenant="quitter")
        assert quitter.deduped
        assert quitter.cancel() is True
        assert quitter.cancel() is False  # idempotent
        hold.release.set()
        blocker.result(timeout=60)
        # the shared execution survived the waiter's cancellation
        res = keeper.result(timeout=60)
        assert len(res.yields["r"].result.as_pandas()) == 8
        with pytest.raises(SubmissionCanceled):
            quitter.result(timeout=5)
    st = srv.stats()
    assert st["canceled"] == 1
    assert st["canceled_executions"] == 0  # the execution itself never died
    assert st["executions"] == 2 and st["completed"] == 2


def test_last_waiter_cancel_drops_queued_execution(tracing):
    eng = NativeExecutionEngine({FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT: 1})
    hold = _Hold()
    with EngineServer(eng) as srv:
        blocker = srv.submit(hold.dag())
        assert hold.entered.wait(30)
        only = srv.submit(_dag, tenant="only")
        assert only.cancel() is True
        hold.release.set()
        blocker.result(timeout=60)
        # the canceled work never ran; a fresh identical submission gets
        # a NEW execution (the in-flight key was cleaned up with it)
        again = srv.submit(_dag, tenant="only")
        assert not again.deduped
        again.result(timeout=60)
    st = srv.stats()
    assert st["canceled_executions"] == 1
    assert st["executions"] == 2  # blocker + the fresh resubmission


def test_post_completion_submissions_do_not_share_in_flight(tracing):
    """Single-flight is an IN-FLIGHT property: after the shared run
    finishes, a new identical submission is a new execution (whether it
    recomputes or is served by the result cache is the cache layer's
    business, not the dedup map's)."""
    eng = NativeExecutionEngine()
    with EngineServer(eng) as srv:
        first = srv.submit(_dag, tenant="a")
        first.result(timeout=60)
        second = srv.submit(_dag, tenant="b")
        second.result(timeout=60)
        assert not second.deduped
    assert srv.stats()["executions"] == 2
