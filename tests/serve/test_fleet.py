"""Fleet-grade serving resilience (ISSUE 13, docs/serving.md "Fleet").

Covers the cross-replica claim/lease protocol (atomic acquire, lease
expiry, dead-pid steal), the shared-store single-flight where server B
serves a plan server A executed — including across real processes and
after A is SIGKILLed mid-execution — the crash-safe submission journal's
replay, the run-scoped tenant conf overlay (the lifted ROADMAP 3a
restriction, with the no-leak regression), the /readyz store-health
drain, and the LRU bounds on per-tenant server state.
"""

import json
import multiprocessing as mp
import os
import shutil
import signal
import time
import urllib.error
import urllib.request

import pandas as pd
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.cache.store import ArtifactStore
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_CACHE_DIR,
    FUGUE_TPU_CONF_SERVE_FLEET_ENABLED,
    FUGUE_TPU_CONF_SERVE_JOURNAL_DIR,
    FUGUE_TPU_CONF_SERVE_MAX_TENANTS,
    FUGUE_TPU_CONF_SERVE_REPLICA_ID,
)
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.serve import (
    EngineServer,
    FleetClient,
    ServeRejected,
    ServeStats,
    SubmissionJournal,
)


def _agg_factory(seed: int = 0, rows: int = 64):
    def build() -> FugueWorkflow:
        dag = FugueWorkflow()
        (
            dag.df(
                pd.DataFrame(
                    {
                        "k": [i % 4 for i in range(rows)],
                        "v": [float(i + seed) for i in range(rows)],
                    }
                )
            )
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n"))
            .yield_dataframe_as("r", as_local=True)
        )
        return dag

    return build


def _frames(result) -> pd.DataFrame:
    return (
        result.yields["r"].result.as_pandas().sort_values("k").reset_index(drop=True)
    )


def _conf(store, jdir=None, rid=None, **extra):
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(store)}
    if jdir is not None:
        conf[FUGUE_TPU_CONF_SERVE_JOURNAL_DIR] = str(jdir)
    if rid is not None:
        conf[FUGUE_TPU_CONF_SERVE_REPLICA_ID] = rid
    conf.update(extra)
    return conf


# ---------------------------------------------------------------------------
# the claim/lease protocol (cache/store.py)
# ---------------------------------------------------------------------------


def test_claim_acquire_hold_release(tmp_path):
    st = ArtifactStore(str(tmp_path), 0)
    owned, holder = st.try_claim("k1", "A", 30.0)
    assert owned and holder["owner"] == "A"
    # a second owner is held off and told who holds it
    owned, holder = st.try_claim("k1", "B", 30.0)
    assert not owned and holder["owner"] == "A"
    # re-entrant: the same owner (a restarted replica replaying its
    # journal) re-enters its own claim
    owned, _ = st.try_claim("k1", "A", 30.0)
    assert owned
    # release is owner-checked: a steal victim's late release must not
    # drop the current holder's claim
    assert not st.release_claim("k1", "B")
    assert st.release_claim("k1", "A")
    assert st.read_claim("k1") is None


def test_claim_lease_expiry_steal(tmp_path):
    st = ArtifactStore(str(tmp_path), 0)
    assert st.try_claim("k", "A", 0.05)[0]
    time.sleep(0.12)
    owned, holder = st.try_claim("k", "B", 30.0)
    assert owned and holder["owner"] == "B"


def test_claim_dead_pid_steal_and_torn_claim(tmp_path):
    import socket

    st = ArtifactStore(str(tmp_path), 0)
    # same-host holder with a dead pid: stealable immediately, no lease wait
    with open(st._claim("k"), "w") as f:
        json.dump(
            {
                "owner": "ghost",
                "pid": 2 ** 22 + 12345,  # beyond pid_max on this box
                "host": socket.gethostname(),
                "ts": time.time(),
                "lease_s": 9999.0,
            },
            f,
        )
    owned, holder = st.try_claim("k", "B", 30.0)
    assert owned and holder["owner"] == "B"
    # a torn claim file reads as absent (stealable), never a wedge
    with open(st._claim("torn"), "w") as f:
        f.write('{"owner": "gho')
    assert st.read_claim("torn") is None
    assert st.try_claim("torn", "B", 30.0)[0]


# ---------------------------------------------------------------------------
# cross-replica single-flight (same process: two servers, one store)
# ---------------------------------------------------------------------------


def test_second_server_serves_first_servers_result(tmp_path):
    store = tmp_path / "store"
    a = NativeExecutionEngine(_conf(store, rid="A"))
    with EngineServer(a) as sa:
        ra = _frames(sa.submit(_agg_factory(3)).result(timeout=60))
        assert sa.stats()["fleet_publishes"] == 1
    b = NativeExecutionEngine(_conf(store, rid="B"))
    with EngineServer(b) as sb:
        rb = _frames(sb.submit(_agg_factory(3)).result(timeout=60))
        st = sb.stats()
    # B answered from A's published artifact: a fleet hit, zero dag runs
    assert st["fleet_result_hits"] >= 1 and st["executions"] == 0
    assert ra.equals(rb)  # bit-identical across the store round trip
    assert ra["s"].tolist() == rb["s"].tolist()


def test_fleet_kill_switch_restores_single_server_behavior(tmp_path):
    store = tmp_path / "store"
    a = NativeExecutionEngine(
        _conf(store, rid="A", **{FUGUE_TPU_CONF_SERVE_FLEET_ENABLED: False})
    )
    with EngineServer(a) as sa:
        _frames(sa.submit(_agg_factory(3)).result(timeout=60))
        st = sa.stats()
    assert st["fleet_enabled"] is False
    assert st["fleet_publishes"] == 0 and st["fleet_claims"] == 0
    # nothing was written to the fleet surfaces of the shared store
    assert not os.path.exists(str(store / "serve")) or not os.listdir(
        str(store / "serve")
    )
    assert os.listdir(str(store / "claims")) == []
    # a second, fleet-enabled server misses (nothing published) and runs
    b = NativeExecutionEngine(_conf(store, rid="B"))
    with EngineServer(b) as sb:
        _frames(sb.submit(_agg_factory(3)).result(timeout=60))
        assert sb.stats()["executions"] == 1


# ---------------------------------------------------------------------------
# two real processes
# ---------------------------------------------------------------------------


def _exec_worker(args):
    """Fork worker: run one EngineServer over the shared store, execute
    one submission, return its frames + counters."""
    store, jdir, rid, seed = args
    eng = NativeExecutionEngine(_conf(store, jdir=jdir, rid=rid))
    with EngineServer(eng) as srv:
        res = srv.submit(_agg_factory(seed), tenant="t").result(timeout=60)
        out = _frames(res)
        st = srv.stats()
    return out.values.tolist(), st["executions"], st["fleet_publishes"]


def test_two_process_cross_server_dedup(tmp_path):
    """Server B (fresh process) serves a plan server A (another process)
    executed — the ISSUE 13 cross-process dedup satellite."""
    store, jdir = str(tmp_path / "store"), str(tmp_path / "journal")
    ctx = mp.get_context("fork")
    with ctx.Pool(1) as pool:
        (rows_a, exec_a, pub_a) = pool.map(
            _exec_worker, [(store, jdir, "A", 11)]
        )[0]
    assert exec_a == 1 and pub_a == 1
    eng = NativeExecutionEngine(_conf(store, jdir=jdir, rid="B"))
    with EngineServer(eng) as srv:
        res = srv.submit(_agg_factory(11), tenant="t2").result(timeout=60)
        rows_b = _frames(res).values.tolist()
        st = srv.stats()
    assert st["fleet_result_hits"] >= 1 and st["executions"] == 0
    assert rows_a == rows_b


def _slow_factory(marker: str, sleep_s: float):
    def build() -> FugueWorkflow:
        def crawl(df: pd.DataFrame) -> pd.DataFrame:
            with open(marker, "w") as f:
                f.write("running")
            time.sleep(sleep_s)
            return df.assign(v=df["v"] * 2.0)

        dag = FugueWorkflow()
        (
            dag.df(
                pd.DataFrame(
                    {"k": [i % 4 for i in range(32)], "v": [float(i) for i in range(32)]}
                )
            )
            .transform(crawl, schema="*")
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )
        return dag

    return build


def test_claim_steal_completes_bit_identical(tmp_path):
    """End to end with a short runtime: A dies holding the claim, B
    steals, executes, and B's result matches a serial no-fleet oracle."""
    store, jdir = str(tmp_path / "store"), str(tmp_path / "journal")
    marker = str(tmp_path / "marker")
    factory = _slow_factory(marker, 0.8)
    ctx = mp.get_context("fork")
    p = ctx.Process(target=_victim_main_short, args=(store, jdir, marker))
    p.start()
    deadline = time.monotonic() + 30
    while not os.path.exists(marker) and time.monotonic() < deadline:
        time.sleep(0.02)
    assert os.path.exists(marker)
    os.kill(p.pid, signal.SIGKILL)
    p.join(10)
    eng = NativeExecutionEngine(_conf(store, jdir=jdir, rid="B"))
    with EngineServer(eng) as srv:
        res = srv.submit(factory).result(timeout=60)
        got = _frames(res)
        st = srv.stats()
    assert st["fleet_claim_steals"] >= 1 and st["executions"] == 1
    # serial oracle: same dag, fleet and cache off entirely
    oracle_eng = NativeExecutionEngine()
    dag = factory()
    dag.run(oracle_eng)
    want = (
        dag.yields["r"].result.as_pandas().sort_values("k").reset_index(drop=True)
    )
    assert got.equals(want)


def _victim_main_short(store, jdir, marker):
    eng = NativeExecutionEngine(_conf(store, jdir=jdir, rid="victim"))
    srv = EngineServer(eng).start()
    sub = srv.submit(_slow_factory(marker, 0.8))
    sub.wait(60)


# ---------------------------------------------------------------------------
# the serve fault sites (docs/resilience.md)
# ---------------------------------------------------------------------------


def test_serve_journal_fault_site_fails_admission_once(tmp_path):
    from fugue_tpu.resilience.policy import InjectedFaultError

    eng = NativeExecutionEngine({"fugue.tpu.fault.plan": "serve.journal=error"})
    with EngineServer(eng) as srv:
        with pytest.raises(InjectedFaultError):
            srv.submit(_agg_factory(1))
        # budget spent: the retry (a client resend) admits cleanly
        assert len(_frames(srv.submit(_agg_factory(1)).result(timeout=60))) == 4


def test_serve_claim_fault_site_releases_claim(tmp_path):
    """An injected failure between claim write and execution start must
    release the claim — a wedged claim would stall every identical
    submission fleet-wide until the lease expires."""
    from fugue_tpu.resilience.policy import InjectedFaultError

    store = tmp_path / "store"
    eng = NativeExecutionEngine(
        _conf(store, rid="A", **{"fugue.tpu.fault.plan": "serve.claim=error"})
    )
    with EngineServer(eng) as srv:
        with pytest.raises(InjectedFaultError):
            srv.submit(_agg_factory(2)).result(timeout=60)
        assert os.listdir(str(store / "claims")) == []
        # the failure was NOT cached fleet-wide: the retry executes
        assert len(_frames(srv.submit(_agg_factory(2)).result(timeout=60))) == 4


# ---------------------------------------------------------------------------
# the crash-safe journal
# ---------------------------------------------------------------------------


def test_journal_records_and_unfinished(tmp_path):
    j = SubmissionJournal(str(tmp_path / "r1.jsonl"), "r1")
    j.admit("s1", "idem-1", "t", 5, 0, _agg_factory(1))
    j.admit("s2", None, "t", 5, 0, _agg_factory(2))
    j.exec_start("s1", "key1")
    j.done("s1", "done")
    j.close()
    un = j.unfinished()
    assert [r["sid"] for r in un] == ["s2"]
    dag = j.decode_dag(un[0])
    assert dag is not None and callable(dag)
    # a torn trailing line (the crash window) is skipped, not fatal
    with open(j.path, "ab") as f:
        f.write(b'{"op": "admit", "sid": "s3"')
    assert [r["sid"] for r in j.unfinished()] == ["s2"]


def test_journal_replay_on_restart(tmp_path):
    """A journaled-but-unfinished admission (the replica died before the
    run completed) replays on restart under its idempotency key."""
    store, jdir = str(tmp_path / "store"), str(tmp_path / "journal")
    # simulate the dead replica's WAL: admit fsync'd, no done record
    j = SubmissionJournal(os.path.join(jdir, "R1.jsonl"), "R1")
    j.admit("dead-sid", "idem-9", "acme", 5, 0, _agg_factory(7))
    j.close()
    eng = NativeExecutionEngine(_conf(store, jdir=jdir, rid="R1"))
    with EngineServer(eng) as srv:  # start() replays
        st = srv.stats()
        assert st["journal_replays"] == 1
        # the replayed submission is live under the original key: a
        # client retry maps onto it instead of double-submitting
        sub = srv.submit(_agg_factory(7), tenant="acme", idempotency_key="idem-9")
        assert srv.stats()["idempotent_replays"] == 1
        res = sub.result(timeout=60)
        assert len(_frames(res)) == 4
    # the pre-crash record is retired: a second restart replays nothing
    eng2 = NativeExecutionEngine(_conf(store, jdir=jdir, rid="R1"))
    with EngineServer(eng2) as srv2:
        assert srv2.stats()["journal_replays"] == 0


# ---------------------------------------------------------------------------
# run-scoped tenant conf (the lifted ROADMAP 3a restriction)
# ---------------------------------------------------------------------------


def test_tenant_overlay_arbitrary_tpu_keys_no_cross_tenant_leak():
    """Tenant overlays accept ANY fugue.tpu.* key, the key is visible to
    that tenant's run (through the engine's run-scoped conf), and it
    NEVER leaks into the shared engine conf or another tenant's run."""
    eng = NativeExecutionEngine(
        {
            # an arbitrary non-plan, non-tuning key: previously dropped
            "fugue.tpu.serve.tenant.acme.conf.fugue.tpu.stream.chunk_rows": 777,
        }
    )
    seen = {}

    def probe_factory(tag):
        def build() -> FugueWorkflow:
            def probe() -> pd.DataFrame:
                from fugue_tpu.execution.factory import (
                    try_get_context_execution_engine,
                )

                e = try_get_context_execution_engine()
                seen[tag] = e.conf.get("fugue.tpu.stream.chunk_rows", -1)
                return pd.DataFrame({"a": [1]})

            dag = FugueWorkflow()
            dag.create(probe, schema="a:long").yield_dataframe_as(
                "r", as_local=True
            )
            return dag

        return build

    with EngineServer(eng) as srv:
        srv.submit(probe_factory("acme"), tenant="acme").result(timeout=60)
        srv.submit(probe_factory("other"), tenant="other").result(timeout=60)
    assert seen["acme"] == 777  # the overlay reached acme's run
    assert seen["other"] == -1  # ...and nobody else's
    # and the shared engine conf never saw it
    assert "fugue.tpu.stream.chunk_rows" not in eng.conf
    assert "fugue.tpu.stream.chunk_rows" not in eng.base_conf


def test_run_conf_scope_restores_after_run():
    eng = NativeExecutionEngine()
    dag = FugueWorkflow({"fugue.tpu.cache.enabled": False})
    dag.df(pd.DataFrame({"a": [1, 2]})).yield_dataframe_as("r", as_local=True)
    dag.run(eng)
    assert "fugue.tpu.cache.enabled" not in eng.conf


# ---------------------------------------------------------------------------
# bounded per-tenant state (hostile tenant-id minting)
# ---------------------------------------------------------------------------


def test_serve_stats_tenant_breakdown_is_lru_bounded():
    st = ServeStats(max_tenants=4)
    for i in range(10):
        st.inc_tenant(f"t{i}", "submitted")
    d = st.as_dict()
    assert len(d["tenants"]) == 4
    assert set(d["tenants"]) == {"t6", "t7", "t8", "t9"}  # oldest rotated
    assert d["tenant_evictions"] == 6


def test_server_policy_and_warn_maps_bounded():
    eng = NativeExecutionEngine({FUGUE_TPU_CONF_SERVE_MAX_TENANTS: 3})
    with EngineServer(eng) as srv:
        for i in range(8):
            srv.submit(_agg_factory(i), tenant=f"mint{i}").result(timeout=60)
        assert len(srv._policies) <= 3
        assert len(srv._overlay_warned) <= 3
        assert len(srv.stats()["tenants"]) <= 3


# ---------------------------------------------------------------------------
# /readyz store health (the drain signal)
# ---------------------------------------------------------------------------


def _get(rpc, path):
    url = f"http://{rpc.host}:{rpc.port}{path}"
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}")


def test_readyz_store_unwritable_503_and_balancer_drain(tmp_path):
    store = tmp_path / "store"
    eng = NativeExecutionEngine(
        _conf(
            store,
            rid="sick",
            **{"fugue.rpc.server": "fugue_tpu.rpc.http.HttpRPCServer"},
        )
    )
    rpc = eng.rpc_server
    rpc.start()
    srv = EngineServer(eng).start()
    rpc.bind_serve(srv)
    try:
        code, ready = _get(rpc, "/readyz")
        assert code == 200 and ready["status"] == "ready"
        assert ready["store"]["writable"] is True and ready["replica_id"] == "sick"
        # the disk dies under the replica: the fleet results dir vanishes
        shutil.rmtree(str(store / "serve"))
        with srv._lock:
            srv._store_health_ts = 0.0  # expire the 5s probe cache
        code, ready = _get(rpc, "/readyz")
        assert code == 503 and ready["status"] == "store_unwritable"
        assert ready["store"]["writable"] is False
        # the balancer drains it: no candidates, fleet-wide shed
        fc = FleetClient([(rpc.host, rpc.port)])
        with pytest.raises(ServeRejected) as ei:
            fc.submit(_agg_factory(1))
        assert ei.value.reason == "fleet_unavailable"
        # liveness is untouched: a sick-disk server is not restarted
        code, live = _get(rpc, "/healthz")
        assert code == 200 and live["status"] == "ok"
    finally:
        srv.stop()
        rpc.stop()
