"""Multi-tenant serving layer (``fugue_tpu/serve``, docs/serving.md) —
ISSUE 10.

Covers admission (queue depth, tenant byte budgets), priority scheduling
with aging, tenant conf overlays and attribution, the liveness/readiness
split, the /serve/* RPC surface with idempotency keys, and the serve
stats/probe observability contract.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pandas as pd
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_SERVE_DEFAULT_PRIORITY,
    FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT,
    FUGUE_TPU_CONF_SERVE_QUEUE_DEPTH,
)
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.obs import get_sampler, get_span_metrics, get_tracer
from fugue_tpu.serve import (
    EngineServer,
    ServeHttpClient,
    ServeRejected,
    SubmissionCanceled,
    submission_key,
    tenant_policy,
)


def _agg_dag(seed: int = 0, rows: int = 64) -> FugueWorkflow:
    dag = FugueWorkflow()
    (
        dag.df(
            pd.DataFrame(
                {"k": [i % 4 for i in range(rows)], "v": [float(i + seed) for i in range(rows)]}
            )
        )
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n"))
        .yield_dataframe_as("r", as_local=True)
    )
    return dag


class _Gate:
    """A submission whose execution blocks until released — the knob that
    makes queue states deterministic in tests."""

    def __init__(self) -> None:
        self.release = threading.Event()
        self.entered = threading.Event()

    def dag(self) -> FugueWorkflow:
        gate = self

        def make() -> pd.DataFrame:
            gate.entered.set()
            assert gate.release.wait(30), "gate never released"
            return pd.DataFrame({"a": [1]})

        dag = FugueWorkflow()
        dag.create(make, schema="a:long").yield_dataframe_as("g", as_local=True)
        return dag


def test_submit_result_roundtrip():
    eng = NativeExecutionEngine()
    with EngineServer(eng) as srv:
        sub = srv.submit(_agg_dag(), tenant="t0")
        res = sub.result(timeout=60)
        df = res.yields["r"].result.as_pandas()
        assert sorted(df["n"]) == [16, 16, 16, 16]
        assert sub.status == "done" and sub.queue_wait_s is not None
    st = srv.stats()
    assert st["submitted"] == 1 and st["completed"] == 1 and st["failed"] == 0
    assert st["tenants"]["t0"]["completed"] == 1


def test_factory_and_built_dag_both_accepted():
    eng = NativeExecutionEngine()
    with EngineServer(eng) as srv:
        a = srv.submit(lambda: _agg_dag(seed=1), tenant="t0")
        b = srv.submit(_agg_dag(seed=2), tenant="t0")
        ra = a.result(timeout=60).yields["r"].result.as_pandas()
        rb = b.result(timeout=60).yields["r"].result.as_pandas()
        assert not ra.equals(rb)  # different seeds: genuinely distinct runs


def test_failed_run_raises_to_the_waiter_only():
    def boom() -> pd.DataFrame:
        raise RuntimeError("kaboom")

    eng = NativeExecutionEngine()
    with EngineServer(eng) as srv:
        bad = FugueWorkflow()
        bad.create(boom, schema="a:int").yield_dataframe_as("g", as_local=True)
        sub = srv.submit(bad)
        with pytest.raises(Exception, match="kaboom"):
            sub.result(timeout=60)
        assert sub.status == "failed"
        ok = srv.submit(_agg_dag())  # the server survives a failed run
        assert len(ok.result(timeout=60).yields["r"].result.as_pandas()) == 4
    st = srv.stats()
    assert st["failed"] == 1 and st["completed"] == 1


def test_queue_full_rejection_and_peak_depth():
    eng = NativeExecutionEngine(
        {
            FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT: 1,
            FUGUE_TPU_CONF_SERVE_QUEUE_DEPTH: 1,
        }
    )
    gate = _Gate()
    with EngineServer(eng) as srv:
        blocker = srv.submit(gate.dag())
        assert gate.entered.wait(30)
        queued = srv.submit(_agg_dag(seed=1))
        with pytest.raises(ServeRejected) as ei:
            srv.submit(_agg_dag(seed=2))
        assert ei.value.reason == "queue_full"
        gate.release.set()
        blocker.result(timeout=60)
        queued.result(timeout=60)
    st = srv.stats()
    assert st["rejected_queue_full"] == 1
    assert st["peak_queue_depth"] == 1


def test_tenant_budget_gates_admission_and_releases_on_claim():
    eng = NativeExecutionEngine(
        {"fugue.tpu.serve.tenant.small.budget_bytes": 1000}
    )
    with EngineServer(eng) as srv:
        with pytest.raises(ServeRejected) as ei:
            srv.submit(_agg_dag(), tenant="small", reserve_bytes=2000)
        assert ei.value.reason == "tenant_budget"
        # within budget: admitted; after completion the charge is the
        # MEASURED result bytes; claiming the result releases it
        sub = srv.submit(_agg_dag(), tenant="small", reserve_bytes=900)
        sub.wait(60)
        charged = srv.stats()["charged_bytes"].get("small", 0)
        assert 0 < charged <= 1000  # restated to measured live bytes
        sub.result(timeout=60)
        assert srv.stats()["charged_bytes"].get("small", 0) == 0
        # other tenants were never gated
        free = srv.submit(_agg_dag(seed=5), tenant="big", reserve_bytes=10**9)
        free.result(timeout=60)
    assert srv.stats()["rejected_budget"] == 1


def test_priority_order_with_fifo_ties():
    eng = NativeExecutionEngine(
        {FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT: 1, FUGUE_TPU_CONF_SERVE_DEFAULT_PRIORITY: 5}
    )
    gate = _Gate()
    order = []
    done = []
    with EngineServer(eng) as srv:
        blocker = srv.submit(gate.dag())
        assert gate.entered.wait(30)
        # queued while the worker is held: low-urgency first, then urgent
        low1 = srv.submit(_agg_dag(seed=1), priority=8)
        low2 = srv.submit(_agg_dag(seed=2), priority=8)
        hi = srv.submit(_agg_dag(seed=3), priority=1)
        gate.release.set()
        for name, sub in (("hi", hi), ("low1", low1), ("low2", low2), ("blocker", blocker)):
            sub.wait(60)
            done.append(name)
        # completion ORDER proof: started_at of the priority-1 run
        # precedes both priority-8 runs; FIFO within the tied pair
        t = {n: s._execution.started_at for n, s in
             (("low1", low1), ("low2", low2), ("hi", hi))}
        assert t["hi"] < t["low1"] < t["low2"], t
        order.append(t)


def test_aging_promotes_starved_low_priority():
    eng = NativeExecutionEngine(
        {
            FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT: 1,
            "fugue.tpu.serve.aging_s": 0.05,
        }
    )
    gate = _Gate()
    with EngineServer(eng) as srv:
        blocker = srv.submit(gate.dag())
        assert gate.entered.wait(30)
        old_low = srv.submit(_agg_dag(seed=1), priority=9)
        time.sleep(0.6)  # ages >10 levels: beats any fresh priority-0
        fresh_hi = srv.submit(_agg_dag(seed=2), priority=0)
        gate.release.set()
        for s in (blocker, old_low, fresh_hi):
            s.wait(60)
        assert (
            old_low._execution.started_at < fresh_hi._execution.started_at
        ), "aged submission was starved by a fresh high-priority one"


def test_tenant_conf_overlay_plan_keys_only():
    eng = NativeExecutionEngine(
        {
            "fugue.tpu.serve.tenant.legacy.conf.fugue.tpu.plan.optimize": False,
            "fugue.tpu.serve.tenant.legacy.conf.fugue.workflow.concurrency": 4,
            "fugue.tpu.serve.tenant.legacy.priority": 2,
        }
    )
    pol = tenant_policy(eng.conf, "legacy")
    assert pol.priority == 2
    assert pol.conf_overlay == {"fugue.tpu.plan.optimize": False}
    assert pol.dropped_keys == ("fugue.workflow.concurrency",)
    with EngineServer(eng) as srv:
        dag = _agg_dag()
        sub = srv.submit(dag, tenant="legacy")
        sub.result(timeout=60)
        assert sub.priority == 2
        # the overlay landed on the workflow compile conf, and the run
        # honored it: the optimizer was off for this tenant's run
        assert dag._conf["fugue.tpu.plan.optimize"] is False
        assert dag.last_plan_report is not None
        assert not dag.last_plan_report.enabled
        # ...and did NOT leak into the shared engine conf
        assert "fugue.tpu.plan.optimize" not in eng.conf


def test_dedup_key_identity_and_refusal():
    eng = NativeExecutionEngine()
    k1 = submission_key(_agg_dag(seed=7), eng)
    k2 = submission_key(_agg_dag(seed=7), eng)
    k3 = submission_key(_agg_dag(seed=8), eng)
    assert k1 is not None and k1 == k2 and k1 != k3

    # a custom creator is "the outside world" to the fingerprinter
    # (docs/cache.md refusal ladder) => refused => NO dedup key: a
    # refusal can gate sharing off, never cause a wrong share
    def gen() -> pd.DataFrame:
        return pd.DataFrame({"a": [1]})

    dag = FugueWorkflow()
    dag.create(gen, schema="a:int").yield_dataframe_as("g", as_local=True)
    assert submission_key(dag, eng) is None


def test_serve_stats_mounted_on_engine_registry_and_probes():
    eng = NativeExecutionEngine()
    with EngineServer(eng) as srv:
        srv.submit(_agg_dag()).result(timeout=60)
        st = eng.stats()
        assert "serve" in st and st["serve"]["completed"] == 1
        names = get_sampler().probe_names()
        assert "serve_queue_depth" in names and "serve_active_runs" in names
        vals = get_sampler().sample_once()
        assert vals["serve_queue_depth"] == 0.0
        # keep-entries reset contract: counters zero, server state intact
        eng.reset_stats()
        assert eng.stats()["serve"]["completed"] == 0
        assert srv.running


def test_tenant_label_attribution_and_rotation():
    tr = get_tracer()
    sm = get_span_metrics()
    tr.clear()
    sm.clear()
    tr.enable()
    try:
        eng = NativeExecutionEngine()
        with EngineServer(eng) as srv:
            srv.submit(_agg_dag(), tenant="acme").result(timeout=60)
        series = sm.latency.series()
        acme = [lab for lab, _h in series if lab.get("tenant") == "acme"]
        assert acme, "no span-metric series carried the tenant label"
        # the run's own workflow/run labels nested INSIDE the tenant scope
        assert any(
            lab.get("span") == "workflow.run" and "run" in lab for lab in acme
        ), acme
        # bounded cardinality: > MAX_TENANT_SERIES distinct tenants rotate
        from fugue_tpu.obs.metrics import run_labels

        cap = sm.MAX_TENANT_SERIES
        for i in range(cap + 5):
            with run_labels(tenant=f"bulk{i}"), tr.span("serve.run"):
                pass
        tenants = {
            lab["tenant"]
            for lab, _h in sm.latency.series()
            if "tenant" in lab
        }
        assert len(tenants) <= cap
        assert "bulk0" not in tenants  # oldest rotated out
        assert f"bulk{cap + 4}" in tenants
    finally:
        tr.disable()
        tr.clear()
        sm.clear()


def test_stopped_server_rejects_and_drains():
    eng = NativeExecutionEngine({FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT: 1})
    gate = _Gate()
    srv = EngineServer(eng).start()
    blocker = srv.submit(gate.dag())
    assert gate.entered.wait(30)
    queued = srv.submit(_agg_dag())
    t = threading.Thread(target=lambda: (time.sleep(0.2), gate.release.set()))
    t.start()
    srv.stop()
    t.join()
    blocker.wait(60)
    assert blocker.status == "done"
    with pytest.raises(ServeRejected):
        queued.result(timeout=5)  # drained: failed with server_stopped
    with pytest.raises(ServeRejected):
        srv.submit(_agg_dag())


# --------------------------------------------------------------------------
# the HTTP surface
# --------------------------------------------------------------------------


@pytest.fixture
def http_serve():
    eng = NativeExecutionEngine(
        {
            "fugue.rpc.server": "fugue_tpu.rpc.http.HttpRPCServer",
            FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT: 1,
            FUGUE_TPU_CONF_SERVE_QUEUE_DEPTH: 2,
        }
    )
    rpc = eng.rpc_server
    rpc.start()
    srv = EngineServer(eng).start()
    rpc.bind_serve(srv)
    try:
        yield eng, rpc, srv
    finally:
        srv.stop()
        rpc.stop()


def _get(rpc, path):
    url = f"http://{rpc.host}:{rpc.port}{path}"
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}")


def test_rpc_submit_poll_result_cancel(http_serve):
    eng, rpc, srv = http_serve
    cl = ServeHttpClient(rpc.host, rpc.port)
    sub = cl.submit(lambda: _agg_dag(seed=3), tenant="acme")
    assert sub["tenant"] == "acme" and not sub["deduped"]
    frames = cl.result(sub["id"], timeout=60)
    assert sorted(frames["r"].columns) == ["k", "n", "s"]
    poll = cl.poll(sub["id"])
    assert poll["status"] == "done" and poll["run_s"] is not None
    # unknown id is a 404/KeyError, not a hang
    assert cl.poll("nope")["_http_status"] == 404
    with pytest.raises(KeyError):
        cl.result("nope")
    # cancel a queued submission behind a blocker
    gate = _Gate()
    blocker = srv.submit(gate.dag())
    assert gate.entered.wait(30)
    queued = cl.submit(lambda: _agg_dag(seed=4))
    out = cl.cancel(queued["id"])
    assert out["canceled"] is True and out["status"] == "canceled"
    gate.release.set()
    blocker.result(timeout=60)


def test_rpc_idempotency_key_replays_same_submission(http_serve):
    eng, rpc, srv = http_serve
    cl = ServeHttpClient(rpc.host, rpc.port)
    a = cl.submit(lambda: _agg_dag(seed=9), tenant="t", idempotency_key="job-1")
    b = cl.submit(lambda: _agg_dag(seed=9), tenant="t", idempotency_key="job-1")
    assert a["id"] == b["id"]
    assert srv.stats()["idempotent_replays"] == 1
    cl.result(a["id"], timeout=60)


def test_rpc_submit_rejection_is_429(http_serve):
    eng, rpc, srv = http_serve
    cl = ServeHttpClient(rpc.host, rpc.port)
    gate = _Gate()
    blocker = srv.submit(gate.dag())
    assert gate.entered.wait(30)
    subs = [cl.submit(lambda: _agg_dag(seed=s)) for s in (1, 2)]  # fills depth=2
    with pytest.raises(ServeRejected) as ei:
        cl.submit(lambda: _agg_dag(seed=3))
    assert ei.value.reason == "queue_full"
    gate.release.set()
    for s in subs:
        cl.result(s["id"], timeout=60)
    blocker.result(timeout=60)


def test_healthz_liveness_vs_readyz_readiness(http_serve):
    eng, rpc, srv = http_serve
    # liveness: the PRE-EXISTING contract, untouched and never load-aware
    code, live = _get(rpc, "/healthz")
    assert code == 200 and live["status"] == "ok" and "uptime_s" in live
    code, ready = _get(rpc, "/readyz")
    assert code == 200 and ready["status"] == "ready"
    assert ready["queue_capacity"] == 2 and ready["queue_free"] == 2
    # hold the worker and fill the queue: readiness flips 503, liveness not
    gate = _Gate()
    blocker = srv.submit(gate.dag())
    assert gate.entered.wait(30)
    subs = [srv.submit(_agg_dag(seed=s)) for s in (1, 2)]
    code, ready = _get(rpc, "/readyz")
    assert code == 503 and ready["status"] == "overloaded"
    assert ready["queue_free"] == 0
    code, live = _get(rpc, "/healthz")
    assert code == 200 and live["status"] == "ok"
    gate.release.set()
    blocker.result(timeout=60)
    for s in subs:
        s.result(timeout=60)
    code, ready = _get(rpc, "/readyz")
    assert code == 200 and ready["status"] == "ready"


def test_stats_endpoint_carries_serve_section(http_serve):
    eng, rpc, srv = http_serve
    srv.submit(_agg_dag()).result(timeout=60)
    code, st = _get(rpc, "/stats")
    assert code == 200
    assert st["serve"]["completed"] >= 1
    assert st["serve"]["queue_capacity"] == 2
