"""ISSUE 14 serve-side satellites: journal compaction (replay parity,
auto-threshold) and the structured ``worker_lost`` error taxonomy on the
/serve result channel and FleetClient failover set."""

import os
import socket

import pandas as pd
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.resilience import FailureCategory, WorkerLostError, classify_failure
from fugue_tpu.serve import (
    EngineServer,
    FleetClient,
    ServeHttpClient,
    ServeWorkerLost,
    SubmissionJournal,
)


def _fill(j: SubmissionJournal, n_done: int, n_open: int) -> None:
    for i in range(n_done):
        j.admit(f"d{i}", f"idem-d{i}", "t", 5, 0, None)
        j.exec_start(f"d{i}", f"key-{i}")
        j.done(f"d{i}", "done")
    for i in range(n_open):
        j.admit(f"o{i}", f"idem-o{i}", "t", 5, 0, None)


# ---------------------------------------------------------------------------
# journal compaction
# ---------------------------------------------------------------------------


def test_journal_compaction_replay_parity(tmp_path):
    path = str(tmp_path / "r0.jsonl")
    j = SubmissionJournal(path, "r0")
    _fill(j, n_done=20, n_open=3)
    before = j.unfinished()
    size_before = os.path.getsize(path)
    dropped = j.compact()
    assert dropped == 20 * 3  # admit+exec+done per finished sid
    assert os.path.getsize(path) < size_before
    # the ONLY contract: replay semantics are unchanged
    assert j.unfinished() == before
    assert [r["sid"] for r in before] == ["o0", "o1", "o2"]
    # appends keep working after the fd swap, into the compacted file
    j.done("o0", "done")
    assert [r["sid"] for r in j.unfinished()] == ["o1", "o2"]
    assert j.compactions == 1
    j.close()


def test_journal_compaction_noop_when_nothing_finished(tmp_path):
    j = SubmissionJournal(str(tmp_path / "r0.jsonl"), "r0")
    _fill(j, n_done=0, n_open=4)
    assert j.compact() == 0
    assert len(j.unfinished()) == 4
    j.close()


def test_journal_auto_compaction_past_threshold(tmp_path):
    path = str(tmp_path / "r0.jsonl")
    j = SubmissionJournal(path, "r0", max_bytes=2048)
    # lots of finished records blow past the threshold; the size check
    # runs every _COMPACT_CHECK_EVERY appends
    _fill(j, n_done=80, n_open=2)
    assert j.compactions >= 1
    assert os.path.getsize(path) <= 2048 + 1024  # shrunk back to ~open set
    assert [r["sid"] for r in j.unfinished()] == ["o0", "o1"]
    j.close()


def test_journal_crash_mid_compaction_keeps_old_file(tmp_path):
    """The compaction publish is atomic: a temp file dying before the
    rename leaves the complete original WAL."""
    path = str(tmp_path / "r0.jsonl")
    j = SubmissionJournal(path, "r0")
    _fill(j, n_done=5, n_open=2)
    before = j.unfinished()
    # simulate the crash window: a leftover temp file is just litter
    with open(path + ".__compact_999999", "w") as f:
        f.write('{"op": "admit"')
    assert j.unfinished() == before
    j.close()


# ---------------------------------------------------------------------------
# worker_lost taxonomy
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_result_on_dead_replica_raises_structured_worker_lost():
    cl = ServeHttpClient("127.0.0.1", _free_port(), connect_timeout=0.2)
    with pytest.raises(ServeWorkerLost) as ei:
        cl.result("sub-123", timeout=5)
    err = ei.value
    assert err.code == "worker_lost"
    assert err.submission_id == "sub-123"
    # the PR 1 taxonomy sees a retryable WORKER_LOST, never POISON
    assert classify_failure(err) is FailureCategory.WORKER_LOST
    # FleetClient fails these over (same idempotency key, new replica)
    assert isinstance(err, FleetClient._FAILOVER_ERRORS)
    # the pre-taxonomy unknown-id contract still holds
    assert isinstance(err, KeyError)
    with pytest.raises(ServeWorkerLost):
        cl.poll("sub-123")


def test_result_unknown_id_on_live_replica_is_worker_lost(tmp_path):
    eng = NativeExecutionEngine(
        {
            "fugue.rpc.server": "fugue_tpu.rpc.http.HttpRPCServer",
            "fugue.tpu.cache.enabled": False,
            "fugue.tpu.tuning.enabled": False,
        }
    )
    rpc = eng.rpc_server
    rpc.start()
    try:
        srv = EngineServer(eng).start()
        rpc.bind_serve(srv)
        cl = ServeHttpClient(rpc.host, rpc.port)
        with pytest.raises(ServeWorkerLost) as ei:
            cl.result("never-admitted")
        assert ei.value.code == "worker_lost"
        srv.stop()
    finally:
        rpc.stop()


def test_poll_payload_carries_error_code_taxonomy():
    eng = NativeExecutionEngine(
        {
            "fugue.rpc.server": "fugue_tpu.rpc.http.HttpRPCServer",
            "fugue.tpu.cache.enabled": False,
            "fugue.tpu.tuning.enabled": False,
        }
    )
    rpc = eng.rpc_server
    rpc.start()
    try:
        srv = EngineServer(eng).start()
        rpc.bind_serve(srv)
        cl = ServeHttpClient(rpc.host, rpc.port)

        def bad_dag():
            def boom(pdf: pd.DataFrame) -> pd.DataFrame:
                raise ValueError("deterministic")

            dag = FugueWorkflow()
            (
                dag.df(pd.DataFrame({"k": [1], "v": [1.0]}))
                .transform(boom, schema="*")
                .yield_dataframe_as("r", as_local=True)
            )
            return dag

        sub = cl.submit(bad_dag, tenant="t")
        with pytest.raises(ValueError):
            cl.result(sub["id"], timeout=60)
        poll = cl.poll(sub["id"])
        assert poll["status"] == "failed"
        # a deterministic user-code failure is POISON: a caller must NOT
        # retry it elsewhere (vs worker_lost, which it should)
        assert poll["error_code"] == "poison"
        srv.stop()
    finally:
        rpc.stop()


def test_worker_lost_is_retryable_poison_is_not():
    lost = ServeWorkerLost("replica died", submission_id="s")
    assert isinstance(lost, WorkerLostError)
    from fugue_tpu.resilience import RetryPolicy

    pol = RetryPolicy(max_attempts=3)
    assert pol.should_retry(classify_failure(lost), 1)
    assert not pol.should_retry(classify_failure(ValueError("poison")), 1)
