"""Shared-engine concurrency regression suite (ISSUE 10 satellite).

The audit found three real races for simultaneous sessions on one
engine: the ``JitCache`` hit/miss counters and the ``PlanStats``
counters were bare read-modify-writes (lost updates), and the engine's
lazily-created singletons (result cache, metrics registry, sub-engines)
could be built twice on first concurrent touch, silently splitting
state. These tests hammer two+ threads through ``workflow.run`` on ONE
engine and assert bit-identical results plus COHERENT counters — the
exact invariants those races broke.
"""

import threading
from typing import Any, Dict, List

import pandas as pd
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import FUGUE_TPU_CONF_CACHE_ENABLED
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.serve import EngineServer

THREADS = 2
RUNS_PER_THREAD = 4


def _frame(seed: int) -> pd.DataFrame:
    n = 2048
    return pd.DataFrame(
        {
            "k": [(i * 7 + seed) % 16 for i in range(n)],
            # integer-valued floats: every fold order sums exactly, so
            # bit-identity is meaningful rather than lucky
            "v": [float((i * 13 + seed) % 1000) for i in range(n)],
        }
    )


def _run_once(eng: Any, seed: int) -> pd.DataFrame:
    dag = FugueWorkflow()
    (
        dag.df(_frame(seed))
        .filter(col("v") > 50)
        .partition_by("k")
        .aggregate(
            ff.sum(col("v")).alias("s"),
            ff.count(col("v")).alias("n"),
            ff.avg(col("v")).alias("m"),
        )
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    return (
        dag.yields["r"].result.as_pandas().sort_values("k").reset_index(drop=True)
    )


@pytest.mark.parametrize("engine_cls", [NativeExecutionEngine, JaxExecutionEngine])
def test_two_threads_through_workflow_run_bit_identical_and_coherent(engine_cls):
    # cache OFF: every run must actually execute, so the expected counter
    # totals are exact (and the engine paths are genuinely exercised)
    eng = engine_cls({FUGUE_TPU_CONF_CACHE_ENABLED: False})
    # serial oracle per seed, on a FRESH engine
    oracle = {
        t: _run_once(engine_cls({FUGUE_TPU_CONF_CACHE_ENABLED: False}), t)
        for t in range(THREADS)
    }
    eng.reset_stats()
    results: Dict[int, List[pd.DataFrame]] = {t: [] for t in range(THREADS)}
    errors: List[BaseException] = []

    def hammer(t: int) -> None:
        try:
            for _ in range(RUNS_PER_THREAD):
                results[t].append(_run_once(eng, t))
        except BaseException as e:
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(THREADS)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors, errors
    # bit-identical: every concurrent run equals its serial oracle
    for t in range(THREADS):
        assert len(results[t]) == RUNS_PER_THREAD
        for df in results[t]:
            pd.testing.assert_frame_equal(df, oracle[t])
    # coherent counters: PlanStats.absorb runs once per workflow.run —
    # bare += lost updates here before the ISSUE 10 locks
    stats = eng.stats()
    assert stats["plan"]["runs"] == THREADS * RUNS_PER_THREAD
    assert eng.active_runs == 0


def test_jit_cache_counters_survive_a_counter_hammer():
    """The raw counter race, isolated: N threads driving __contains__ on
    one JitCache must account every probe (hits + misses == probes)."""
    from fugue_tpu.jax.pipeline import JitCache

    cache = JitCache()
    cache["warm"] = object()
    probes_per_thread = 20_000
    n_threads = 4

    def spin() -> None:
        for i in range(probes_per_thread):
            ("warm" if i % 2 else ("cold", i)) in cache

    threads = [threading.Thread(target=spin) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    st = cache.stats()
    assert st["hits"] + st["misses"] == n_threads * probes_per_thread
    assert st["hits"] == n_threads * probes_per_thread // 2


def test_lazy_engine_singletons_are_created_once_under_concurrency():
    """First concurrent touch of the engine's lazy singletons must yield
    ONE object per engine, not one per thread."""
    for _ in range(5):  # the race window is small — take a few shots
        eng = NativeExecutionEngine()
        seen: Dict[str, List[Any]] = {"cache": [], "metrics": [], "plan": []}
        barrier = threading.Barrier(4)

        def touch() -> None:
            barrier.wait()
            seen["cache"].append(eng.result_cache)
            seen["metrics"].append(eng.metrics)
            seen["plan"].append(eng.plan_stats)

        threads = [threading.Thread(target=touch) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for name, objs in seen.items():
            assert len({id(o) for o in objs}) == 1, f"{name} created twice"


def test_hammer_through_engine_server_matches_serial(tmp_path):
    """The end-to-end form: N sessions × M submissions through one
    EngineServer on one jax engine WITH the result cache on — results
    stay bit-identical to serial single-client runs and no submission
    fails (the serve_load acceptance shape, sized for CI)."""
    eng = JaxExecutionEngine(
        {
            "fugue.tpu.cache.enabled": True,
            "fugue.tpu.cache.dir": str(tmp_path / "cache"),
            "fugue.tpu.serve.max_concurrent": 3,
        }
    )
    oracle = {
        s: _run_once(JaxExecutionEngine({FUGUE_TPU_CONF_CACHE_ENABLED: False}), s)
        for s in range(3)
    }
    failures: List[BaseException] = []
    outs: List[Any] = []
    with EngineServer(eng) as srv:

        def session(i: int) -> None:
            seed = i % 3
            try:
                sub = srv.submit(
                    lambda: _mk_dag(seed), tenant=f"t{seed}"
                )
                res = sub.result(timeout=120)
                df = (
                    res.yields["r"].result.as_pandas()
                    .sort_values("k")
                    .reset_index(drop=True)
                )
                outs.append((seed, df))
            except BaseException as e:
                failures.append(e)

        def _mk_dag(seed: int) -> FugueWorkflow:
            dag = FugueWorkflow()
            (
                dag.df(_frame(seed))
                .filter(col("v") > 50)
                .partition_by("k")
                .aggregate(
                    ff.sum(col("v")).alias("s"),
                    ff.count(col("v")).alias("n"),
                    ff.avg(col("v")).alias("m"),
                )
                .yield_dataframe_as("r", as_local=True)
            )
            return dag

        threads = [threading.Thread(target=session, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not failures, failures
    assert len(outs) == 6
    for seed, df in outs:
        pd.testing.assert_frame_equal(df, oracle[seed])
    st = srv.stats()
    assert st["failed"] == 0 and st["submitted"] == 6
    # completed counts EXECUTIONS; every session's submission finished
    assert st["completed"] == st["executions"]
    assert sum(t["completed"] for t in st["tenants"].values()) == 6
    # 6 submissions over 3 distinct plans: sharing (in-flight dedup and/or
    # result-cache hits) means the engine never ran all 6 from scratch
    assert st["executions"] <= 6
