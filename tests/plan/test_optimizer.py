"""Logical plan optimizer (``fugue_tpu/plan``, docs/plan.md) — ISSUE 4.

The satellite checklist:

- parity suite: bit-identical results optimized vs
  ``fugue.tpu.plan.optimize=false`` across transform / filter / join /
  aggregate / SQL workflows (bounded AND streaming inputs);
- pruning-reaches-producer: the chunk producer / device ingest only ever
  carries the demanded columns (spies on ``_chunk_columns`` and
  ``JaxDataFrame._from_arrow``);
- fusion span-shape: the fused chain runs as ONE ``engine.fused`` span
  (no per-verb engine spans);
- no-op guard: UDF transformers (column usage not inferable)
  conservatively keep every column;
- ``workflow.explain()`` report + per-pass conf gates + result aliasing.
"""

from typing import Dict

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import fugue_tpu.jax.streaming as streaming_mod
from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_PLAN_FUSE,
    FUGUE_TPU_CONF_PLAN_OPTIMIZE,
    FUGUE_TPU_CONF_PLAN_PRUNE,
    FUGUE_TPU_CONF_PLAN_PUSHDOWN,
)
from fugue_tpu.dataframe import ArrowDataFrame, LocalDataFrameIterableDataFrame
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.jax.dataframe import JaxDataFrame
from fugue_tpu.obs import get_tracer


def _frame(n=4000, cols=8, groups=16, seed=0) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "k": rng.integers(0, groups, n),
            "v": rng.random(n),
            "w": rng.random(n),
            "s": rng.choice(["a", "b", "c", None], n),
            **{f"x{i}": rng.random(n) for i in range(cols)},
        }
    )


def _stream(pdf: pd.DataFrame, step: int = 512):
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(step, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, step)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


def _run_pair(build, engine_conf=None, sort=None):
    """Run the same workflow with the optimizer ON and OFF; assert
    bit-identical results (values AND dtypes); return the ON frame."""
    outs = []
    for opt in (True, False):
        conf = dict(engine_conf or {})
        conf[FUGUE_TPU_CONF_PLAN_OPTIMIZE] = opt
        eng = JaxExecutionEngine(conf)
        dag = FugueWorkflow()
        build(dag)
        dag.run(eng)
        res = dag.yields["r"].result.as_pandas()
        if sort:
            res = res.sort_values(sort).reset_index(drop=True)
        outs.append(res)
    pd.testing.assert_frame_equal(outs[0], outs[1])
    return outs[0]


# ---------------------------------------------------------------------------
# parity suite
# ---------------------------------------------------------------------------


def test_parity_aggregate_wide():
    pdf = _frame()

    def build(dag):
        (
            dag.df(pdf)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("sv"), ff.count(col("v")).alias("n"))
            .yield_dataframe_as("r", as_local=True)
        )

    res = _run_pair(build, sort=["k"])
    assert len(res) == 16


def test_parity_filter_select_chain():
    pdf = _frame()

    def build(dag):
        (
            dag.df(pdf)
            .rename({"v": "val"})
            .filter(col("val") > 0.25)
            .select(col("k"), col("val"), (col("val") * 2).alias("v2"))
            .yield_dataframe_as("r", as_local=True)
        )

    res = _run_pair(build)
    assert list(res.columns) == ["k", "val", "v2"]
    assert (res["val"] > 0.25).all()


def test_parity_assign_drop_string_filter():
    pdf = _frame()

    def build(dag):
        (
            dag.df(pdf)
            .assign(v3=col("v") * 3)
            .drop(["x0", "x1"])
            .filter(col("s") == "a")
            .yield_dataframe_as("r", as_local=True)
        )

    res = _run_pair(build)
    assert (res["s"] == "a").all()


def test_parity_join_pushdown():
    pdf = _frame()
    dim = pd.DataFrame({"k": np.arange(16), "label": np.arange(16) * 1.0})

    def build(dag):
        j = dag.df(pdf).inner_join(dag.df(dim), on=["k"]).filter(col("v") > 0.8)
        j.partition_by("k").aggregate(ff.count(col("v")).alias("n")).yield_dataframe_as(
            "r", as_local=True
        )

    _run_pair(build, sort=["k"])


def test_parity_transform_udf():
    pdf = _frame(cols=4)

    def add_one(df: pd.DataFrame) -> pd.DataFrame:
        df = df.copy()
        df["v"] = df["v"] + 1.0
        return df[["k", "v"]]

    def build(dag):
        (
            dag.df(pdf)
            .transform(add_one, schema="k:long,v:double")
            .filter(col("v") > 1.5)
            .yield_dataframe_as("r", as_local=True)
        )

    _run_pair(build)


def test_parity_sql_workflow():
    pdf = _frame(cols=2)

    def build(dag):
        a = dag.df(pdf)
        dag.select(
            "SELECT k, SUM(v) AS sv FROM ", a, " WHERE v > 0.2 GROUP BY k"
        ).yield_dataframe_as("r", as_local=True)

    _run_pair(build, sort=["k"])


def test_parity_streaming_filter_aggregate():
    pdf = _frame(cols=4)

    def build(dag):
        (
            dag.df(_stream(pdf))
            .filter(col("v") > 0.5)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("sv"), ff.count(col("v")).alias("n"))
            .yield_dataframe_as("r", as_local=True)
        )

    _run_pair(build, sort=["k"])


def test_parity_native_engine():
    """The optimizer is engine-agnostic: parity holds on the host engine."""
    pdf = _frame(cols=3)
    outs = []
    for opt in (True, False):
        eng = NativeExecutionEngine({FUGUE_TPU_CONF_PLAN_OPTIMIZE: opt})
        dag = FugueWorkflow()
        (
            dag.df(pdf)
            .filter(col("v") > 0.5)
            .select(col("k"), col("v"))
            .yield_dataframe_as("r", as_local=True)
        )
        dag.run(eng)
        outs.append(dag.yields["r"].result.as_pandas())
    pd.testing.assert_frame_equal(outs[0], outs[1])


# ---------------------------------------------------------------------------
# pruning reaches the producer
# ---------------------------------------------------------------------------


def test_pruning_reaches_bounded_ingest(monkeypatch):
    pdf = _frame(cols=20)
    seen = []
    orig = JaxDataFrame._from_arrow

    def spy(self, tbl):
        seen.append(list(tbl.column_names))
        return orig(self, tbl)

    monkeypatch.setattr(JaxDataFrame, "_from_arrow", spy)
    eng = JaxExecutionEngine()
    dag = FugueWorkflow()
    src = dag.df(pdf)
    (
        src.partition_by("k")
        .aggregate(ff.sum(col("v")).alias("sv"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    assert len(dag.yields["r"].result.as_pandas()) == 16
    # no ingested table ever carried the 20 x-columns
    assert seen and all(set(cols) <= {"k", "v"} for cols in seen), seen
    # the pruned source result is visible (aliased) and narrow
    assert set(src.result.schema.names) == {"k", "v"}


def test_pruning_reaches_chunk_producer(monkeypatch):
    pdf = _frame(cols=12)
    seen = []
    orig = streaming_mod._chunk_columns

    def spy(f, names):
        seen.append(list(f.schema.names))
        return orig(f, names)

    monkeypatch.setattr(streaming_mod, "_chunk_columns", spy)
    eng = JaxExecutionEngine()
    dag = FugueWorkflow()
    (
        dag.df(_stream(pdf))
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("sv"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    assert len(dag.yields["r"].result.as_pandas()) == 16
    # every chunk the streaming producer decoded was already pruned
    assert seen and all(set(cols) <= {"k", "v"} for cols in seen), seen


def test_noop_guard_udf_keeps_all_columns():
    """Transformer column usage can't be inferred -> NO pruning."""
    pdf = _frame(cols=6)

    def ident(df: pd.DataFrame) -> pd.DataFrame:
        return df

    eng = JaxExecutionEngine()
    dag = FugueWorkflow()
    src = dag.df(pdf)
    schema_str = ",".join(
        f"{n}:{'str' if n == 's' else ('long' if n == 'k' else 'double')}"
        for n in pdf.columns
    )
    src.transform(ident, schema=schema_str).yield_dataframe_as("r", as_local=True)
    dag.run(eng)
    assert set(src.result.schema.names) == set(pdf.columns)
    assert dag.last_plan_report.cols_pruned == 0


def test_pruning_keeps_one_column_for_row_count():
    from fugue_tpu.column import lit

    pdf = _frame(cols=3)
    eng = JaxExecutionEngine()
    dag = FugueWorkflow()
    src = dag.df(pdf)
    (
        src.aggregate(ff.count(lit(1)).alias("n"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    assert dag.yields["r"].result.as_pandas()["n"].iloc[0] == len(pdf)
    assert len(src.result.schema.names) >= 1


# ---------------------------------------------------------------------------
# fusion: span shape + single-jit path
# ---------------------------------------------------------------------------


def test_fusion_span_shape():
    tr = get_tracer()
    tr.clear()
    tr.enable()
    try:
        pdf = _frame(cols=2)
        eng = JaxExecutionEngine()
        dag = FugueWorkflow()
        (
            dag.df(pdf)
            .filter(col("v") > 0.25)
            .select(col("k"), (col("v") * 2).alias("v2"))
            .yield_dataframe_as("r", as_local=True)
        )
        dag.run(eng)
        names = [r["name"] for r in tr.records()]
        assert "plan.optimize" in names
        assert "engine.fused" in names
        # the fused chain replaced the separate verb executions
        assert "engine.filter" not in names
        assert "engine.select" not in names
        plan_span = next(r for r in tr.records() if r["name"] == "plan.optimize")
        assert plan_span["args"]["verbs_fused"] >= 2
        assert plan_span["args"]["cols_pruned"] >= 1
    finally:
        tr.disable()
        tr.clear()
    # single-jit proof: one fused cache entry, no per-verb compilations
    kinds = {k[0] for k in eng._jit_cache.keys()}
    assert "fused" in kinds and "filter3v" not in kinds and "project" not in kinds


def test_fused_sequential_fallback_matches():
    """A chain with a host-only step (LIKE on strings after rename) still
    fuses but runs the sequential engine-verb fallback — results equal."""
    pdf = _frame(cols=2)

    def build(dag):
        (
            dag.df(pdf)
            .filter(col("s").is_null() | (col("v") > 0.1))
            .select(col("k"), col("s"), col("v"))
            .yield_dataframe_as("r", as_local=True)
        )

    _run_pair(build)


# ---------------------------------------------------------------------------
# explain / conf gates / aliasing
# ---------------------------------------------------------------------------


def test_explain_report():
    pdf = _frame(cols=5)
    dag = FugueWorkflow()
    (
        dag.df(pdf)
        .filter(col("v") > 0.5)
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("sv"))
        .yield_dataframe_as("r", as_local=True)
    )
    text = dag.explain()
    assert "== logical plan ==" in text
    assert "== optimized plan" in text
    assert "pruned" in text
    disabled = dag.explain(conf={FUGUE_TPU_CONF_PLAN_OPTIMIZE: False})
    assert "optimizer disabled" in disabled


def test_per_pass_conf_gates():
    pdf = _frame(cols=5)

    def build(dag):
        (
            dag.df(pdf)
            .filter(col("v") > 0.5)
            .select(col("k"), col("v"))
            .yield_dataframe_as("r", as_local=True)
        )

    for key, counter in (
        (FUGUE_TPU_CONF_PLAN_PRUNE, "cols_pruned"),
        (FUGUE_TPU_CONF_PLAN_FUSE, "verbs_fused"),
    ):
        eng = JaxExecutionEngine({key: False})
        dag = FugueWorkflow()
        build(dag)
        dag.run(eng)
        report = dag.last_plan_report
        assert getattr(report, counter) == 0, key
    eng = JaxExecutionEngine({FUGUE_TPU_CONF_PLAN_PUSHDOWN: False})
    dag = FugueWorkflow()
    build(dag)
    dag.run(eng)
    assert dag.last_plan_report.filters_pushed == 0


def test_engine_plan_metrics():
    pdf = _frame(cols=5)
    eng = JaxExecutionEngine()
    dag = FugueWorkflow()
    (
        dag.df(pdf)
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("sv"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    st = eng.stats()["plan"]
    assert st["runs"] == 1
    assert st["cols_pruned"] >= 5
    assert st["bytes_skipped"] > 0
    eng.reset_stats()
    assert eng.stats()["plan"]["runs"] == 0


def test_result_alias_final_and_source():
    pdf = _frame(cols=4)
    eng = JaxExecutionEngine()
    dag = FugueWorkflow()
    src = dag.df(pdf)
    final = src.filter(col("v") > 0.5).select(col("k"), col("v"))
    final.yield_dataframe_as("r", as_local=True)
    dag.run(eng)
    # the fused tail aliases to the final handle
    out = final.result.as_pandas()
    assert list(out.columns) == ["k", "v"]
    # the pruned create aliases to the source handle
    assert set(src.result.schema.names) == {"k", "v"}


def test_pinned_tasks_disable_rewrites():
    """Checkpointed/broadcast tasks never get rewritten or fused away."""
    pdf = _frame(cols=4)
    eng = JaxExecutionEngine()
    dag = FugueWorkflow()
    src = dag.df(pdf)
    mid = src.filter(col("v") > 0.5).persist()  # weak checkpoint pins it
    mid.select(col("k"), col("v")).yield_dataframe_as("r", as_local=True)
    dag.run(eng)
    assert dag.last_plan_report.verbs_fused == 0
    # the persisted intermediate keeps its full width
    assert set(mid.result.schema.names) == set(pdf.columns)


def test_pushdown_rename_rewrites_condition():
    from fugue_tpu.plan import optimize_tasks

    pdf = _frame(cols=2)
    dag = FugueWorkflow()
    (
        dag.df(pdf)
        .rename({"v": "val"})
        .filter(col("val") > 0.5)
        .partition_by("k")
        .aggregate(ff.sum(col("val")).alias("sv"))
        .yield_dataframe_as("r", as_local=True)
    )
    text = dag.explain()
    assert "filters_pushed=1" in text


def test_pushdown_rewritten_filter_result_is_correct():
    """A pushdown-repositioned filter's handle must resolve to the new
    chain TAIL (same frame as unoptimized), never to the interior clone
    that filters before the verb it commuted past."""
    pdf = pd.DataFrame({"a": [1.0, None, 3.0, 4.0], "b": [1, 2, 3, 4]})
    dag0 = FugueWorkflow()
    ref_h = dag0.df(pdf).dropna().filter(col("b") > 1)
    dag0.run("native", {FUGUE_TPU_CONF_PLAN_OPTIMIZE: False})
    ref = ref_h.result.as_pandas().reset_index(drop=True)

    dag = FugueWorkflow()
    mid = dag.df(pdf).dropna()
    out = mid.filter(col("b") > 1)
    dag.run("native")
    assert dag.last_plan_report.filters_pushed == 1
    pd.testing.assert_frame_equal(ref, out.result.as_pandas().reset_index(drop=True))
    # the producer's own intermediate (dropna BEFORE the filter moved) is
    # no longer computed anywhere: descriptive error, not silent wrong data
    from fugue_tpu.exceptions import FugueWorkflowError

    with pytest.raises(FugueWorkflowError, match="optimized away"):
        mid.result


def test_fused_interior_result_raises_descriptive():
    """Accessing .result on an intermediate fused into a neighbor raises
    a descriptive error (was: bare KeyError) while the tail still works."""
    from fugue_tpu.exceptions import FugueWorkflowError

    pdf = _frame(cols=2)
    dag = FugueWorkflow()
    mid = dag.df(pdf).filter(col("v") > 0.5)
    tail = mid.select(col("k"), col("v"))
    tail.yield_dataframe_as("r", as_local=True)
    dag.run(JaxExecutionEngine())
    assert dag.last_plan_report.verbs_fused >= 2
    assert (tail.result.as_pandas()["v"] > 0.5).all()
    with pytest.raises(FugueWorkflowError, match="optimized away"):
        mid.result


def test_load_pruning_pushes_columns_into_reader(tmp_path):
    """A parquet Load with no explicit columns gets a columns override
    from demand analysis (schema sniffed from file metadata) — parity
    with the unoptimized path, fewer bytes read."""
    import pyarrow.parquet as pq

    pdf = _frame(n=1000, cols=10)
    path = str(tmp_path / "wide.parquet")
    pq.write_table(pa.Table.from_pandas(pdf, preserve_index=False), path)
    outs = []
    for opt in (True, False):
        dag = FugueWorkflow()
        (
            dag.load(path)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("sv"))
            .yield_dataframe_as("r", as_local=True)
        )
        dag.run("native", {FUGUE_TPU_CONF_PLAN_OPTIMIZE: opt})
        outs.append(
            dag.yields["r"].result.as_pandas().sort_values("k").reset_index(drop=True)
        )
        if opt:
            assert dag.last_plan_report.cols_pruned >= 10
            assert dag.last_plan_report.bytes_skipped > 0
            assert any("pruned" in s for s in dag.last_plan_report.after)
    pd.testing.assert_frame_equal(outs[0], outs[1])
    # explicit user columns are respected: no second pruning
    dag = FugueWorkflow()
    (
        dag.load(path, columns=["k", "v", "w"])
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("sv"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run("native")
    assert all("load" not in n or "pruned" not in n for n in dag.last_plan_report.after)


def test_compile_conf_gates_run_without_engine_leak():
    """plan.* switches in FugueWorkflow(compile_conf=...) gate run() AND
    explain() identically, and never leak into a shared engine's conf."""
    pdf = _frame(cols=2)
    eng = NativeExecutionEngine()
    dag = FugueWorkflow(compile_conf={FUGUE_TPU_CONF_PLAN_OPTIMIZE: False})
    dag.df(pdf).filter(col("v") > 0.5).select(col("k"), col("v")).yield_dataframe_as(
        "r", as_local=True
    )
    dag.run(eng)
    assert not dag.last_plan_report.enabled
    assert "optimizer disabled" in dag.explain()
    assert FUGUE_TPU_CONF_PLAN_OPTIMIZE not in eng.conf
    # a later workflow on the SAME engine still optimizes
    dag2 = FugueWorkflow()
    dag2.df(pdf).filter(col("v") > 0.5).select(col("k"), col("v")).yield_dataframe_as(
        "r", as_local=True
    )
    dag2.run(eng)
    assert dag2.last_plan_report.enabled


def test_pushdown_refused_fillna_overlap():
    pdf = _frame(cols=2)

    def build(dag):
        (
            dag.df(pdf)
            .fillna(0.0, subset=["v"])
            .filter(col("v") > 0.5)
            .yield_dataframe_as("r", as_local=True)
        )

    _run_pair(build)
    dag = FugueWorkflow()
    build(dag)
    text = dag.explain()
    assert "filters_pushed=0" in text
    assert any("fillna" in n for n in (dag.explain().splitlines()))
