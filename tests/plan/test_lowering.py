"""Segment lowering (``fugue_tpu/plan/lowering.py``, docs/plan.md) — ISSUE 7.

The satellite checklist:

- segment-boundary parity: bit-identical results for lowered vs
  ``fugue.tpu.plan.lower_segments=false`` across filter/transform chains,
  aggregates (bounded AND streaming), take, distinct, broadcast-join
  probes, SQL workflows and the native engine;
- refusal fallback: a UDF transformer breaks the chain (no segment
  forms), a host-only chain / unlowerable predicate forms a segment that
  falls back per-verb at execution — results AND engine-verb spans
  identical to today, no ``plan.segment`` span;
- span shape: a lowered segment runs under ONE ``plan.segment`` span
  (replacing ``engine.fused``/``engine.aggregate``), ``stream.chunk``
  spans nest under it, and the engine jit cache holds exactly ONE entry
  labeled ``segment:<fingerprint>`` for the pipeline segment;
- stats: ``engine.stats()["plan"]`` carries ``segments_lowered`` /
  ``verbs_absorbed`` / ``segments_executed`` / ``segments_fallback``;
  ``engine.stats()["jit_cache"]["by_label"]`` attributes entries by
  segment fingerprint (not first-verb name);
- conf gate + ``workflow.explain()`` rendering.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_PLAN_LOWER_SEGMENTS,
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
)
from fugue_tpu.dataframe import ArrowDataFrame, LocalDataFrameIterableDataFrame
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.obs import get_tracer

CHUNK = 2048


def _frame(n=20_000, groups=32, seed=0, strings=False) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    d = {
        "k": rng.integers(0, groups, n),
        "v": rng.random(n),
        "w": rng.random(n),
    }
    if strings:
        d["s"] = rng.choice(["a", "b", "c", None], n)
    return pd.DataFrame(d)


def _stream(pdf: pd.DataFrame, step: int = CHUNK):
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(step, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, step)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


def _run_pair(build, engine_conf=None, sort=None):
    """Run the same workflow with segment lowering ON and OFF (optimizer
    fully on both ways); assert bit-identical results; return the ON
    engine and frame."""
    outs = []
    for lower in (True, False):
        conf = dict(engine_conf or {})
        conf[FUGUE_TPU_CONF_PLAN_LOWER_SEGMENTS] = lower
        conf.setdefault(FUGUE_TPU_CONF_STREAM_CHUNK_ROWS, CHUNK)
        eng = JaxExecutionEngine(conf)
        dag = FugueWorkflow()
        build(dag)
        dag.run(eng)
        res = dag.yields["r"].result.as_pandas()
        if sort:
            res = res.sort_values(sort).reset_index(drop=True)
        outs.append((eng, res))
    pd.testing.assert_frame_equal(outs[0][1], outs[1][1])
    return outs[0]


# ---------------------------------------------------------------------------
# parity: lowered vs lower_segments=false, gate toggled both ways
# ---------------------------------------------------------------------------


def test_parity_streaming_fused_aggregate():
    pdf = _frame()

    def build(dag):
        (
            dag.df(_stream(pdf))
            .filter(col("v") > 0.25)
            .select(col("k"), (col("v") * col("w")).alias("z"))
            .partition_by("k")
            .aggregate(
                ff.sum(col("z")).alias("s"),
                ff.count(col("z")).alias("n"),
                ff.avg(col("z")).alias("m"),
                ff.min(col("z")).alias("lo"),
                ff.max(col("z")).alias("hi"),
            )
            .yield_dataframe_as("r", as_local=True)
        )

    eng, res = _run_pair(build, sort=["k"])
    assert len(res) == 32
    st = eng.stats()["plan"]
    assert st["segments_lowered"] == 1
    assert st["segments_executed"] == 1 and st["segments_fallback"] == 0


def test_parity_bounded_fused_aggregate():
    pdf = _frame()

    def build(dag):
        (
            dag.df(pdf)
            .filter(col("v") > 0.25)
            .select(col("k"), (col("v") + col("w")).alias("z"))
            .partition_by("k")
            .aggregate(ff.sum(col("z")).alias("s"), ff.count(col("z")).alias("n"))
            .yield_dataframe_as("r", as_local=True)
        )

    eng, res = _run_pair(build, sort=["k"])
    assert len(res) == 32
    assert eng.stats()["plan"]["segments_executed"] == 1


def test_parity_streaming_take():
    pdf = _frame()

    def build(dag):
        (
            dag.df(_stream(pdf))
            .filter(col("v") > 0.5)
            .select(col("k"), col("v"))
            .take(5, presort="v desc")
            .yield_dataframe_as("r", as_local=True)
        )

    eng, res = _run_pair(build, sort=["v"])
    assert len(res) == 5
    assert eng.stats()["plan"]["segments_executed"] == 1


def test_parity_streaming_distinct():
    pdf = _frame()

    def build(dag):
        (
            dag.df(_stream(pdf))
            .select(col("k"), (col("v") > 0.5).alias("hi"))
            .distinct()
            .yield_dataframe_as("r", as_local=True)
        )

    eng, res = _run_pair(build, sort=["k", "hi"])
    assert len(res) == 64
    assert eng.stats()["plan"]["segments_executed"] == 1


def test_parity_broadcast_join_probe():
    pdf = _frame()
    dim = pd.DataFrame({"k": np.arange(32), "label_v": np.arange(32) * 1.5})

    def build(dag):
        d = dag.df(dim)
        (
            dag.df(_stream(pdf))
            .filter(col("v") > 0.25)
            .select(col("k"), col("v"))
            .join(d, how="inner", on=["k"])
            .yield_dataframe_as("r", as_local=True)
        )

    eng, res = _run_pair(build, sort=["k", "v"])
    assert set(res.columns) == {"k", "v", "label_v"}
    assert eng.stats()["plan"]["segments_executed"] == 1


def test_parity_sql_workflow():
    pdf = _frame()

    def build(dag):
        a = dag.df(pdf)
        dag.select(
            "SELECT k, SUM(v) AS sv FROM ", a, " WHERE v > 0.2 GROUP BY k"
        ).yield_dataframe_as("r", as_local=True)

    _run_pair(build, sort=["k"])


def test_parity_native_engine():
    """LoweredSegment on a non-jax engine runs the base per-verb
    interpretation — bit-identical to the unlowered pair."""
    pdf = _frame()
    outs = []
    for lower in (True, False):
        eng = NativeExecutionEngine({FUGUE_TPU_CONF_PLAN_LOWER_SEGMENTS: lower})
        dag = FugueWorkflow()
        (
            dag.df(pdf)
            .filter(col("v") > 0.25)
            .select(col("k"), (col("v") * 2).alias("v2"))
            .partition_by("k")
            .aggregate(ff.sum(col("v2")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )
        dag.run(eng)
        outs.append(
            dag.yields["r"].result.as_pandas().sort_values("k").reset_index(drop=True)
        )
    pd.testing.assert_frame_equal(outs[0], outs[1])


# ---------------------------------------------------------------------------
# refusal fallback
# ---------------------------------------------------------------------------


def test_refusal_udf_transformer_breaks_chain():
    """A UDF transformer between the chain and the aggregate is not
    row-local-composable: no segment forms, results identical."""
    pdf = _frame()

    def bump(df: pd.DataFrame) -> pd.DataFrame:
        df = df.copy()
        df["v"] = df["v"] + 1.0
        return df

    def build(dag):
        (
            dag.df(pdf)
            .filter(col("v") > 0.25)
            .transform(bump, schema="*")
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    eng, _ = _run_pair(build, sort=["k"])
    assert eng.stats()["plan"]["segments_lowered"] == 0


def test_refusal_host_only_chain_falls_back_with_identical_spans():
    """A streaming chain carrying a string column can't lower to jnp: the
    segment forms but execution falls back per-verb — results AND the
    engine-verb span multiset match today's path, and no ``plan.segment``
    span is emitted."""
    pdf = _frame(strings=True)

    def build(dag):
        (
            dag.df(_stream(pdf))
            .filter(col("s").is_null() | (col("v") > 0.1))
            .select(col("k"), col("s"), col("v"))
            .partition_by("k")
            .aggregate(ff.count(col("v")).alias("n"))
            .yield_dataframe_as("r", as_local=True)
        )

    tr = get_tracer()
    span_sets = {}
    for lower in (True, False):
        tr.clear()
        tr.enable()
        try:
            eng = JaxExecutionEngine(
                {
                    FUGUE_TPU_CONF_PLAN_LOWER_SEGMENTS: lower,
                    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: CHUNK,
                }
            )
            dag = FugueWorkflow()
            build(dag)
            dag.run(eng)
            res = dag.yields["r"].result.as_pandas().sort_values("k")
            names = [r["name"] for r in tr.records()]
        finally:
            tr.disable()
            tr.clear()
        engine_spans = sorted(n for n in names if n.startswith("engine."))
        span_sets[lower] = (res.reset_index(drop=True), engine_spans, names)
        if lower:
            assert eng.stats()["plan"]["segments_lowered"] == 1
            assert eng.stats()["plan"]["segments_fallback"] == 1
            assert eng.stats()["plan"]["segments_executed"] == 0
    pd.testing.assert_frame_equal(span_sets[True][0], span_sets[False][0])
    # the per-verb fallback produces the same engine-verb spans as today
    assert "engine.fused" in span_sets[True][1]
    assert "engine.aggregate" in span_sets[True][1]
    assert span_sets[True][1] == span_sets[False][1]
    assert "plan.segment" not in span_sets[True][2]


def test_refusal_unlowerable_predicate_falls_back():
    """LIKE has no jnp lowering on raw stream columns — per-verb fallback,
    identical results."""
    from fugue_tpu.column.expressions import _LikeExpr

    pdf = _frame(strings=True)

    def build(dag):
        (
            dag.df(_stream(pdf))
            .filter(_LikeExpr(col("s"), "a%") | (col("v") > 0.9))
            .select(col("k"), col("v"))
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    eng, _ = _run_pair(build, sort=["k"])
    assert eng.stats()["plan"]["segments_fallback"] == 1


# ---------------------------------------------------------------------------
# span shape + single jit entry + chunk nesting
# ---------------------------------------------------------------------------


def test_span_shape_single_entry_and_chunk_nesting():
    pdf = _frame()
    tr = get_tracer()
    tr.clear()
    tr.enable()
    try:
        eng = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: CHUNK})
        dag = FugueWorkflow()
        (
            dag.df(_stream(pdf))
            .filter(col("v") > 0.25)
            .select(col("k"), (col("v") * col("w")).alias("z"))
            .partition_by("k")
            .aggregate(ff.sum(col("z")).alias("s"), ff.count(col("z")).alias("n"))
            .yield_dataframe_as("r", as_local=True)
        )
        dag.run(eng)
        records = tr.records()
    finally:
        tr.disable()
        tr.clear()
    names = [r["name"] for r in records]
    # ONE plan.segment span replaces the per-verb engine spans
    assert names.count("plan.segment") == 1
    assert "engine.fused" not in names
    assert "engine.aggregate" not in names
    assert "engine.filter" not in names and "engine.select" not in names
    # stream.chunk spans nest under plan.segment
    by_id = {r["id"]: r for r in records}
    seg_id = next(r["id"] for r in records if r["name"] == "plan.segment")
    chunks = [r for r in records if r["name"] == "stream.chunk"]
    assert len(chunks) > 1
    for c in chunks:
        anc = c.get("parent")
        seen = set()
        while anc is not None and anc in by_id and anc not in seen:
            seen.add(anc)
            if anc == seg_id:
                break
            anc = by_id[anc].get("parent")
        assert anc == seg_id, f"stream.chunk not nested under plan.segment: {c}"
    # single jit-cache entry for the whole pipeline segment, labeled by
    # segment fingerprint — checkable from stats alone
    jstats = eng.stats()["jit_cache"]
    seg_labels = {
        lab: n for lab, n in jstats["by_label"].items() if lab.startswith("segment:")
    }
    assert len(seg_labels) == 1 and set(seg_labels.values()) == {1}, jstats
    assert eng._jit_cache.segment_entries() != {}
    # and nothing else compiled for this workflow's hot path
    assert jstats["entries"] == 1, jstats


# ---------------------------------------------------------------------------
# conf gate / explain / stats
# ---------------------------------------------------------------------------


def test_conf_gate_off_keeps_per_verb_plan():
    pdf = _frame()
    eng = JaxExecutionEngine({FUGUE_TPU_CONF_PLAN_LOWER_SEGMENTS: False})
    dag = FugueWorkflow()
    (
        dag.df(pdf)
        .filter(col("v") > 0.25)
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("s"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    report = dag.last_plan_report
    assert report.segments_lowered == 0
    assert eng.stats()["plan"]["segments_lowered"] == 0
    assert eng.stats()["plan"]["segments_executed"] == 0


def test_explain_renders_segment():
    pdf = _frame()
    dag = FugueWorkflow()
    (
        dag.df(pdf)
        .filter(col("v") > 0.5)
        .select(col("k"), col("v"))
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("sv"))
        .yield_dataframe_as("r", as_local=True)
    )
    text = dag.explain()
    assert "lowered segment" in text
    assert "segments_lowered=1" in text
    off = dag.explain(conf={FUGUE_TPU_CONF_PLAN_LOWER_SEGMENTS: False})
    assert "lowered segment" not in off


def test_plan_stats_reset_contract():
    pdf = _frame(n=2000)
    eng = JaxExecutionEngine()
    dag = FugueWorkflow()
    (
        dag.df(pdf)
        .filter(col("v") > 0.5)
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("s"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    st = eng.stats()["plan"]
    assert st["segments_lowered"] == 1 and st["verbs_absorbed"] >= 2
    assert st["segments_executed"] + st["segments_fallback"] == 1
    eng.reset_stats()
    st = eng.stats()["plan"]
    assert st["segments_lowered"] == 0 and st["segments_executed"] == 0
    # jit-cache entries survive the reset (keep-entries contract), labels
    # included
    assert eng.stats()["jit_cache"]["entries"] >= 1
