"""Distributed workflow execution (ISSUE 16, fugue_tpu/plan/distribute.py).

The planner pass that routes workflow.run through the fault-tolerant dist
tier: fragment discovery over the post-optimization DAG, the refusal
ladder (everything the planner cannot prove bucket-local stays local with
the reason in explain()), end-to-end execution over in-process workers
bit-identical to the single-process oracle, the kill-switch contract
(fugue.tpu.dist.enabled=false -> planner inert -> identical engine-verb
span multisets), warm-rerun delta-skip, and the interior get_result error.
"""

import collections
import os
import threading

import pandas as pd
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col
from fugue_tpu.column import functions as ff
from fugue_tpu.dist import DistWorker
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.plan import plan_distribution
from fugue_tpu.workflow._tasks import FugueTask  # noqa: F401 (API surface)

BASE = {
    "fugue.tpu.cache.enabled": False,
    "fugue.tpu.tuning.enabled": False,
    "fugue.tpu.dist.heartbeat.interval_s": 0.1,
    "fugue.tpu.dist.heartbeat.stale_after_s": 0.6,
    "fugue.tpu.dist.poll_s": 0.01,
    "fugue.tpu.dist.buckets": 4,
}


def _sources(tmp_path, n_left=3, n_right=2):
    ldir = tmp_path / "left"
    rdir = tmp_path / "right"
    ldir.mkdir(exist_ok=True)
    rdir.mkdir(exist_ok=True)
    for i in range(n_left):
        pd.DataFrame(
            {
                "k": [(j * 3 + i) % 7 for j in range(40)],
                "v": [float(j + i * 40) for j in range(40)],
            }
        ).to_parquet(str(ldir / f"l{i}.parquet"))
    for i in range(n_right):
        pd.DataFrame(
            {"k": list(range(7)), "w": [float(i * 10 + j) for j in range(7)]}
        ).to_parquet(str(rdir / f"r{i}.parquet"))
    return str(ldir), str(rdir)


class _Pool:
    def __init__(self, board, n=2, conf=None):
        os.makedirs(str(board), exist_ok=True)
        self.stop_file = os.path.join(str(board), "_stop")
        self.workers = [
            DistWorker(str(board), f"w{i}", conf=dict(conf or BASE)).start()
            for i in range(n)
        ]
        self.threads = [
            threading.Thread(
                target=w.serve_forever,
                kwargs={"stop_file": self.stop_file},
                daemon=True,
            )
            for w in self.workers
        ]
        for t in self.threads:
            t.start()

    def close(self):
        with open(self.stop_file, "w") as f:
            f.write("stop")
        for t in self.threads:
            t.join(timeout=10)
        for w in self.workers:
            w.stop()


def _join_agg(dag, ldir, rdir):
    a = dag.load(ldir, fmt="parquet").filter(col("v") > 10)
    b = dag.load(rdir, fmt="parquet")
    (
        a.join(b, how="inner", on=["k"])
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("s"), ff.count(col("w")).alias("n"))
        .yield_dataframe_as("r", as_local=True)
    )


def _sql_wf(dag, ldir, rdir):
    a = dag.load(ldir, fmt="parquet")
    b = dag.load(rdir, fmt="parquet")
    dag.select(
        "SELECT a.k AS k, SUM(a.v * b.w) AS s, COUNT(*) AS n FROM ",
        a,
        " AS a INNER JOIN ",
        b,
        " AS b ON a.k = b.k WHERE a.v > 10 GROUP BY a.k",
    ).yield_dataframe_as("r", as_local=True)


def _canon(pdf):
    return (
        pdf.sort_values(list(pdf.columns))
        .reset_index(drop=True)
        .reindex(sorted(pdf.columns), axis=1)
    )


def _run(build, ldir, rdir, conf, engine=None):
    eng = engine if engine is not None else NativeExecutionEngine(dict(BASE))
    dag = FugueWorkflow()
    build(dag, ldir, rdir)
    dag.run(eng, conf=conf)
    return dag.yields["r"].result.as_pandas(), eng


# ---------------------------------------------------------------------------
# planner units (dry: plan_distribution / explain, no workers)
# ---------------------------------------------------------------------------


def _plan_of(build, ldir, rdir, board, extra=None):
    from fugue_tpu._utils.params import ParamDict
    from fugue_tpu.plan import optimize_tasks

    dag = FugueWorkflow()
    build(dag, ldir, rdir)
    conf = ParamDict(dict(BASE, **{"fugue.tpu.dist.board": board}))
    conf.update(extra or {})
    tasks, _, _, _ = optimize_tasks(dag._tasks, conf)
    return plan_distribution(tasks, conf)


def test_planner_inert_without_board_or_disabled(tmp_path):
    ldir, rdir = _sources(tmp_path)
    plan = _plan_of(_join_agg, ldir, rdir, "")
    assert not plan.active and not plan.fragments
    plan = _plan_of(
        _join_agg,
        ldir,
        rdir,
        str(tmp_path / "board"),
        {"fugue.tpu.dist.enabled": False},
    )
    assert not plan.active and not plan.fragments


def test_planner_finds_join_agg_fragment(tmp_path):
    """The canonical workflow lowers to one segment; the planner claims
    the whole subgraph (both loads, the segment, the tail aggregate)."""
    ldir, rdir = _sources(tmp_path)
    plan = _plan_of(_join_agg, ldir, rdir, str(tmp_path / "board"))
    assert plan.active and len(plan.fragments) == 1 and not plan.refusals
    frag = plan.fragments[0]
    assert frag.keys == ["k"]
    assert frag.terminal[0] == "join"
    assert len(frag.covered_ids) == 4
    assert [len(s["paths"]) for s in frag.sides] == [3, 2]
    # the filter rides the left map body
    assert any(st[0] == "filter" for st in frag.sides[0]["steps"])
    # the keyed tail aggregate rides the reduce
    assert frag.tail_ops and frag.tail_ops[-1][0] == "aggregate"


def test_planner_finds_sql_fragment(tmp_path):
    ldir, rdir = _sources(tmp_path)
    plan = _plan_of(_sql_wf, ldir, rdir, str(tmp_path / "board"))
    assert len(plan.fragments) == 1 and not plan.refusals
    frag = plan.fragments[0]
    assert frag.terminal[0] == "sql" and frag.keys == ["k"]
    assert frag.terminal[2] == ["_0", "_1"]


def test_refusal_non_parquet_source(tmp_path):
    ldir, rdir = _sources(tmp_path)
    csv = tmp_path / "csv_src"
    csv.mkdir()
    pd.DataFrame({"k": [1, 2], "v": [1.0, 2.0]}).to_csv(
        str(csv / "a.csv"), index=False
    )

    def build(dag, l, r):
        a = dag.load(str(csv), fmt="csv", columns="k:long,v:double")
        b = dag.load(r, fmt="parquet")
        a.join(b, how="inner", on=["k"]).yield_dataframe_as("r", as_local=True)

    plan = _plan_of(build, ldir, rdir, str(tmp_path / "board"))
    assert not plan.fragments and plan.refusals
    assert any("csv" in why for _, why in plan.refusals)


def test_refusal_non_row_local_interior(tmp_path):
    """A distinct() between load and join has no row-local step form —
    the fragment refuses and the subgraph stays local."""
    ldir, rdir = _sources(tmp_path)

    def build(dag, l, r):
        a = dag.load(l, fmt="parquet").distinct()
        b = dag.load(r, fmt="parquet")
        a.join(b, how="inner", on=["k"]).yield_dataframe_as("r", as_local=True)

    plan = _plan_of(build, ldir, rdir, str(tmp_path / "board"))
    assert not plan.fragments
    assert plan.refusals


def test_refusal_pinned_and_multi_consumer_interiors(tmp_path):
    """A yielded (pinned) side frame, or one consumed by two terminals,
    must materialize locally — both rungs show up as refusals."""
    ldir, rdir = _sources(tmp_path)

    def pinned(dag, l, r):
        a = dag.load(l, fmt="parquet")
        b = dag.load(r, fmt="parquet")
        a.join(b, how="inner", on=["k"]).yield_dataframe_as("r", as_local=True)
        a.yield_dataframe_as("a_too", as_local=True)

    plan = _plan_of(pinned, ldir, rdir, str(tmp_path / "board"))
    assert not plan.fragments
    assert any("pinned" in why for _, why in plan.refusals)

    def fan_out(dag, l, r):
        a = dag.load(l, fmt="parquet")
        b = dag.load(r, fmt="parquet")
        a.join(b, how="inner", on=["k"]).yield_dataframe_as("r", as_local=True)
        a.join(b, how="left_outer", on=["k"]).yield_dataframe_as(
            "r2", as_local=True
        )

    plan = _plan_of(fan_out, ldir, rdir, str(tmp_path / "board"))
    assert not plan.fragments
    assert any("consumer" in why for _, why in plan.refusals)


def test_refusal_sql_shapes(tmp_path):
    """ORDER BY / DISTINCT / global aggregates are not bucket-local."""
    ldir, rdir = _sources(tmp_path)
    shapes = {
        "order": (
            "SELECT a.k, a.v FROM ",
            " AS a INNER JOIN ",
            " AS b ON a.k = b.k ORDER BY a.v",
        ),
        "distinct": (
            "SELECT DISTINCT a.k FROM ",
            " AS a INNER JOIN ",
            " AS b ON a.k = b.k",
        ),
        "global_agg": (
            "SELECT SUM(a.v) AS s FROM ",
            " AS a INNER JOIN ",
            " AS b ON a.k = b.k",
        ),
    }
    for name, (head, mid, tail) in shapes.items():

        def build(dag, l, r, head=head, mid=mid, tail=tail):
            a = dag.load(l, fmt="parquet")
            b = dag.load(r, fmt="parquet")
            dag.select(head, a, mid, b, tail).yield_dataframe_as(
                "r", as_local=True
            )

        plan = _plan_of(build, ldir, rdir, str(tmp_path / "board"))
        assert not plan.fragments, name
        assert plan.refusals, name


def test_explain_renders_board_plan(tmp_path):
    ldir, rdir = _sources(tmp_path)
    board = str(tmp_path / "board")
    dag = FugueWorkflow()
    _join_agg(dag, ldir, rdir)
    out = dag.explain(conf=dict(BASE, **{"fugue.tpu.dist.board": board}))
    assert "== distributed workflows (board=" in out
    assert "1 fragment(s), 0 refused" in out
    assert "map[left]: 3 file(s)" in out
    # off / disabled renderings
    out_off = dag.explain(conf=dict(BASE))
    assert "distributed workflows: off" in out_off
    out_dis = dag.explain(
        conf=dict(
            BASE,
            **{
                "fugue.tpu.dist.board": board,
                "fugue.tpu.dist.enabled": False,
            },
        )
    )
    assert "distributed workflows: disabled" in out_dis


# ---------------------------------------------------------------------------
# end to end over in-process workers
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("build", [_join_agg, _sql_wf], ids=["functional", "sql"])
def test_workflow_run_distributed_bit_identical(tmp_path, build):
    """workflow.run with a board routes the fragment through the dist
    tier; the (canonicalized) result is identical to the dist-disabled
    single-process run and the workflow counters land in engine stats."""
    ldir, rdir = _sources(tmp_path)
    board = str(tmp_path / "board")
    oracle, _ = _run(
        build,
        ldir,
        rdir,
        {"fugue.tpu.dist.board": board, "fugue.tpu.dist.enabled": False},
    )
    pool = _Pool(board)
    try:
        got, eng = _run(build, ldir, rdir, {"fugue.tpu.dist.board": board})
        pd.testing.assert_frame_equal(_canon(oracle), _canon(got))
        d = eng.stats()["dist"]
        assert d["workflow_jobs"] == 1
        assert d["workflow_tasks_dispatched"] > 0
    finally:
        pool.close()


def test_workflow_warm_rerun_delta_skips_unchanged_partitions(tmp_path):
    """Warm distributed rerun over the SAME sources reuses every
    content-addressed done record; over an APPENDED source only the new
    partition's map (and downstream reduces) re-dispatch."""
    ldir, rdir = _sources(tmp_path)
    board = str(tmp_path / "board")
    pool = _Pool(board)
    try:
        eng = NativeExecutionEngine(dict(BASE))
        conf = {"fugue.tpu.dist.board": board}
        got1, _ = _run(_join_agg, ldir, rdir, conf, engine=eng)
        d1 = dict(eng.stats()["dist"])
        # warm: identical sources -> all 9 tasks (5 maps + 4 reduces) reused
        got2, _ = _run(_join_agg, ldir, rdir, conf, engine=eng)
        d2 = dict(eng.stats()["dist"])
        assert got2.equals(got1)
        assert (
            d2["workflow_partitions_delta_skipped"]
            - d1.get("workflow_partitions_delta_skipped", 0)
            == 9
        )
        assert d2["workflow_tasks_dispatched"] == d1["workflow_tasks_dispatched"]
        # append one file to the left source: its map is NEW, the other 5
        # maps are reused (reduces depend on the map set, so they rerun)
        pd.DataFrame(
            {"k": [1, 2, 3], "v": [500.0, 600.0, 700.0]}
        ).to_parquet(os.path.join(ldir, "l9.parquet"))
        got3, _ = _run(_join_agg, ldir, rdir, conf, engine=eng)
        d3 = dict(eng.stats()["dist"])
        skipped = (
            d3["workflow_partitions_delta_skipped"]
            - d2["workflow_partitions_delta_skipped"]
        )
        dispatched = (
            d3["workflow_tasks_dispatched"] - d2["workflow_tasks_dispatched"]
        )
        assert skipped == 5  # 3 old left maps + 2 right maps reused
        assert dispatched == 5  # 1 new map + a fresh wave of 4 reduces
        # the appended rows are in the result
        oracle, _ = _run(
            _join_agg,
            ldir,
            rdir,
            {"fugue.tpu.dist.board": board, "fugue.tpu.dist.enabled": False},
        )
        pd.testing.assert_frame_equal(_canon(oracle), _canon(got3))
    finally:
        pool.close()


def test_kill_switch_identical_span_multisets(tmp_path):
    """fugue.tpu.dist.enabled=false with a board set must be bit-identical
    to no board at all — including the MULTISET of engine-verb spans (the
    planner is inert, so the local path is byte-for-byte the same code)."""
    from fugue_tpu.obs import get_tracer

    ldir, rdir = _sources(tmp_path)
    board = str(tmp_path / "board")
    tracer = get_tracer()
    tracer.enable()
    try:

        def spans(conf):
            tracer.clear()
            got, _ = _run(_join_agg, ldir, rdir, conf)
            multiset = collections.Counter(
                r["name"]
                for r in tracer.records()
                if r.get("cat") in ("engine", "workflow")
            )
            return got, multiset

        got_off, spans_off = spans(
            {"fugue.tpu.dist.board": board, "fugue.tpu.dist.enabled": False}
        )
        got_none, spans_none = spans({})
        assert got_off.equals(got_none)
        assert spans_off == spans_none
    finally:
        tracer.disable()
        tracer.clear()


def test_interior_result_raises_descriptive_error(tmp_path):
    """Asking for a frame that executed remotely inside a fragment names
    the dist tier and the pin/kill-switch escape hatches."""
    from fugue_tpu.exceptions import FugueWorkflowError

    ldir, rdir = _sources(tmp_path)
    board = str(tmp_path / "board")
    pool = _Pool(board)
    try:
        eng = NativeExecutionEngine(dict(BASE))
        dag = FugueWorkflow()
        a = dag.load(ldir, fmt="parquet")
        b = dag.load(rdir, fmt="parquet")
        a.join(b, how="inner", on=["k"]).yield_dataframe_as("r", as_local=True)
        dag.run(eng, conf={"fugue.tpu.dist.board": board})
        with pytest.raises(FugueWorkflowError, match="REMOTELY|dist"):
            _ = a.result
    finally:
        pool.close()


def test_cache_hit_blocks_fragment_warm_local_wins(tmp_path):
    """With the result cache on, a warm run serves the terminal from the
    local cache and the planner must NOT claim the fragment (no board
    traffic at all on the second run)."""
    ldir, rdir = _sources(tmp_path)
    board = str(tmp_path / "board")
    cache_dir = str(tmp_path / "cache")
    conf = dict(
        BASE,
        **{
            "fugue.tpu.cache.enabled": True,
            "fugue.tpu.cache.dir": cache_dir,
            "fugue.tpu.dist.board": board,
        },
    )
    pool = _Pool(board)
    try:
        eng = NativeExecutionEngine(dict(conf))
        got1, _ = _run(_join_agg, ldir, rdir, {}, engine=eng)
        d1 = dict(eng.stats().get("dist", {}))
        got2, _ = _run(_join_agg, ldir, rdir, {}, engine=eng)
        d2 = dict(eng.stats().get("dist", {}))
        assert got2.equals(got1)
        # the warm run planned NO new workflow job: the cache cut won
        assert d2.get("workflow_jobs", 0) == d1.get("workflow_jobs", 0)
    finally:
        pool.close()
