"""UDF static analyzer (``fugue_tpu/analysis``, docs/analysis.md) — ISSUE 11.

The checklist:

- **parity matrix**: translated vs interpreted bit-identical across the
  jax AND native engines × optimizer on/off × bounded AND streaming
  inputs, over the recognized subset (arithmetic, comparisons, boolean
  masks, fillna/clip/where/mask/isin/astype, np.where conditionals,
  bound params + scalar closures, statically-decided ``if``);
- **column-set correctness**: pruning reaches the producer under an
  analyzed UDF (translated AND facts-only), spied on the producer;
- **refusal matrix**: globals, closures over mutables, ``.apply``, loops
  with break, unknown methods, non-determinism, data-dependent
  conditionals, partitioned transforms, star-schema passthrough writes —
  each refuses to the interpreted path bit-identically with its reason
  rendered in ``workflow.explain()``;
- **fingerprint**: an edited UDF translates to different steps (cache
  miss), an identical one re-uses its cached trace;
- **delta cache**: an analyzed row-local UDF chain over a grown source
  delta-serves (only appended partitions recompute);
- **surface**: ``workflow.lint()`` structured diagnostics,
  ``explain(lint=True)``, ``engine.stats()["analysis"]`` counters
  flattened onto a valid ``/metrics`` exposition, conf gates.
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_CACHE_DIR,
    FUGUE_TPU_CONF_CACHE_ENABLED,
    FUGUE_TPU_CONF_PLAN_ANALYZE_UDFS,
    FUGUE_TPU_CONF_PLAN_OPTIMIZE,
    FUGUE_TPU_CONF_PLAN_TRANSLATE_UDFS,
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
)
from fugue_tpu.dataframe import ArrowDataFrame, LocalDataFrameIterableDataFrame
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.obs import get_tracer

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _frame(n=4000, cols=6, seed=0) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    pdf = pd.DataFrame(
        {
            "k": rng.integers(0, 16, n),
            "v": rng.random(n),
            "w": rng.random(n),
            **{f"x{i}": rng.random(n) for i in range(cols)},
        }
    )
    pdf.loc[pdf.index % 9 == 0, "v"] = np.nan
    return pdf


def _stream(pdf: pd.DataFrame, step: int = 512):
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(step, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, step)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


def _run_once(build, conf, engine_cls=JaxExecutionEngine, sort=None):
    conf = dict(conf)
    conf.setdefault(FUGUE_TPU_CONF_CACHE_ENABLED, False)
    eng = engine_cls(conf)
    dag = FugueWorkflow()
    build(dag)
    dag.run(eng)
    res = dag.yields["r"].result.as_pandas()
    if sort:
        res = res.sort_values(sort).reset_index(drop=True)
    return res, eng, dag


def _assert_translated_parity(build, sort=None, engine_conf=None):
    """Translated (analysis ON) must be bit-identical to the pre-analysis
    engine (analysis OFF) on BOTH engines × optimizer on/off; returns the
    translated-path jax result and its engine/dag."""
    base = dict(engine_conf or {})
    ref = None
    out = None
    for engine_cls in (JaxExecutionEngine, NativeExecutionEngine):
        for opt in (True, False):
            for analyze in (True, False):
                conf = dict(base)
                conf[FUGUE_TPU_CONF_PLAN_OPTIMIZE] = opt
                conf[FUGUE_TPU_CONF_PLAN_ANALYZE_UDFS] = analyze
                res, eng, dag = _run_once(build, conf, engine_cls, sort=sort)
                if ref is None:
                    ref = res
                else:
                    pd.testing.assert_frame_equal(res, ref)
                if engine_cls is JaxExecutionEngine and opt and analyze:
                    out = (res, eng, dag)
    assert out is not None
    return out


# module-level UDFs (the analyzer reads their SOURCE; exec'd or REPL
# functions refuse with reason "source")


def udf_arith(df: pd.DataFrame) -> pd.DataFrame:
    df["z"] = df["v"].fillna(0.0) * 2.0 + df["w"]
    df = df[df["z"] > 0.3]
    return df


def udf_conditional(df: pd.DataFrame) -> pd.DataFrame:
    df["z"] = np.where(df["w"] > 0.5, df["w"] * 2.0, df["v"].fillna(0.25))
    mask = df["z"] > 0.4
    df = df[mask]
    return df


def udf_methods(df: pd.DataFrame) -> pd.DataFrame:
    df["c"] = df["v"].clip(0.1, 0.9)
    df["m"] = df["w"].where(df["w"] > 0.5, 0.5)
    df["r"] = df["v"].fillna(0.0).round(2).abs()
    df["kk"] = df["k"].isin([1, 2, 3])
    df["f"] = df["k"].astype("float64")
    return df


def _make_scaled_udf(scale: float):
    # a SCALAR closure cell — allowed (and part of the trace fingerprint)
    def udf_params(df: pd.DataFrame, lo: float, hi: float = 0.8) -> pd.DataFrame:
        df["z"] = (df["v"].fillna(lo) * scale).clip(lo, hi)
        df = df[df["z"] >= lo]
        return df

    return udf_params


def udf_overwrite(df: pd.DataFrame) -> pd.DataFrame:
    df["v"] = df["v"].fillna(0.0) * 2.5
    df["z"] = df["v"] + df["w"]
    return df


def udf_static_if(df: pd.DataFrame, mode: str = "double") -> pd.DataFrame:
    if mode == "double":
        df["z"] = df["v"].fillna(0.0) * 2.0
    else:
        df["z"] = df["v"].fillna(0.0) + 100.0
    return df


def udf_reduction(df: pd.DataFrame) -> pd.DataFrame:
    total = df["v"].fillna(0.0).sum()
    df["z"] = df["v"].fillna(0.0) / (total + 1.0)
    return df


# ---------------------------------------------------------------------------
# parity matrix
# ---------------------------------------------------------------------------


def test_parity_arith_star_bounded():
    pdf = _frame()

    def build(dag):
        (
            dag.transform(pdf.copy(), using=udf_arith, schema="*,z:double")
            .yield_dataframe_as("r", as_local=True)
        )

    res, eng, dag = _assert_translated_parity(build)
    assert (res["z"] > 0.3).all()
    assert eng.stats()["analysis"]["udfs_translated"] >= 1
    assert dag.last_plan_report.udfs_translated == 1


def test_parity_conditional_and_series_mask():
    pdf = _frame()

    def build(dag):
        (
            dag.transform(pdf.copy(), using=udf_conditional, schema="*,z:double")
            .yield_dataframe_as("r", as_local=True)
        )

    res, _, dag = _assert_translated_parity(build)
    assert len(res) > 0
    assert dag.last_plan_report.udfs_translated == 1


def test_parity_method_subset():
    pdf = _frame()

    def build(dag):
        (
            dag.transform(
                pdf.copy(),
                using=udf_methods,
                schema="*,c:double,m:double,r:double,kk:bool,f:double",
            ).yield_dataframe_as("r", as_local=True)
        )

    res, _, dag = _assert_translated_parity(build)
    assert dag.last_plan_report.udfs_translated == 1
    assert res["c"].dropna().between(0.1, 0.9).all()


def test_parity_params_and_closure():
    pdf = _frame()
    udf = _make_scaled_udf(3.0)

    def build(dag):
        (
            dag.transform(
                pdf.copy(),
                using=udf,
                schema="*,z:double",
                params=dict(lo=0.2),
            ).yield_dataframe_as("r", as_local=True)
        )

    res, _, dag = _assert_translated_parity(build)
    assert dag.last_plan_report.udfs_translated == 1
    assert (res["z"] >= 0.2).all()


def test_parity_explicit_schema_overwrite():
    """An explicit full schema may overwrite existing columns (declared
    dtypes are known) and narrows the output to the declared list."""
    pdf = _frame()

    def build(dag):
        (
            dag.transform(
                pdf.copy(), using=udf_overwrite, schema="k:long,v:double,z:double"
            ).yield_dataframe_as("r", as_local=True)
        )

    res, _, dag = _assert_translated_parity(build)
    assert list(res.columns) == ["k", "v", "z"]
    assert dag.last_plan_report.udfs_translated == 1


def test_parity_static_if_takes_bound_branch():
    pdf = _frame()
    for mode in ("double", "add"):

        def build(dag):
            (
                dag.transform(
                    pdf.copy(),
                    using=udf_static_if,
                    schema="*,z:double",
                    params=dict(mode=mode),
                ).yield_dataframe_as("r", as_local=True)
            )

        res, _, dag = _assert_translated_parity(build)
        assert dag.last_plan_report.udfs_translated == 1
        if mode == "add":
            assert (res["z"] >= 100.0).all()


def test_parity_streaming_single_segment():
    """Streaming source: the translated UDF chain + dense aggregate must
    compile into ONE segment program — exactly one segment jit entry,
    zero fallbacks, no engine.transform span — and stay bit-identical."""
    pdf = _frame(6000)

    def build(dag):
        (
            dag.df(_stream(pdf))
            .transform(using=udf_arith, schema="*,z:double")
            .partition_by("k")
            .aggregate(ff.sum(col("z")).alias("s"), ff.count(col("z")).alias("n"))
            .yield_dataframe_as("r", as_local=True)
        )

    conf = {FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 512}
    outs = []
    for analyze in (True, False):
        c = dict(conf)
        c[FUGUE_TPU_CONF_PLAN_ANALYZE_UDFS] = analyze
        res, eng, dag = _run_once(build, c, JaxExecutionEngine, sort=["k"])
        outs.append(res)
        if analyze:
            seg = eng._jit_cache.segment_entries()
            assert len(seg) == 1 and set(seg.values()) == {1}, seg
            st = eng.stats()["plan"]
            assert st["segments_executed"] >= 1 and st["segments_fallback"] == 0
    pd.testing.assert_frame_equal(outs[0], outs[1])


def test_translated_fuses_with_surrounding_verbs():
    """Workflow verbs around the UDF and the translated steps collapse
    into one fused chain (no standalone engine.transform execution)."""
    pdf = _frame()

    def build(dag):
        (
            dag.df(pdf.copy())
            .filter(col("w") < 0.95)
            .transform(using=udf_arith, schema="*,z:double")
            .select(col("k"), col("z"), (col("z") * 2).alias("z2"))
            .yield_dataframe_as("r", as_local=True)
        )

    tracer = get_tracer()
    was = tracer.enabled
    tracer.enable()
    tracer.clear()
    try:
        res, eng, dag = _run_once(
            build, {FUGUE_TPU_CONF_PLAN_ANALYZE_UDFS: True}, JaxExecutionEngine
        )
        names = {r["name"] for r in tracer.records()}
        assert "engine.fused" in names or any(
            n == "plan.segment" for n in names
        ), names
        # the whole chain is ONE task: no separate filter/select verbs
        assert "engine.filter" not in names and "engine.select" not in names
        rep = dag.last_plan_report
        assert rep.udfs_translated == 1 and rep.verbs_fused >= 4
    finally:
        if not was:
            tracer.disable()
        tracer.clear()
    # and parity for the same workflow
    _assert_translated_parity(build)


# ---------------------------------------------------------------------------
# column-set correctness (pruning reaches the producer)
# ---------------------------------------------------------------------------


def _pruned_columns_seen(build, conf):
    import fugue_tpu.plan.passes as passes

    seen = []
    passes.PRUNE_OBSERVER = seen.append
    try:
        res, eng, dag = _run_once(build, conf, JaxExecutionEngine, sort=None)
    finally:
        passes.PRUNE_OBSERVER = None
    return seen, res


def test_pruning_reaches_producer_translated():
    pdf = _frame(cols=8)

    def build(dag):
        (
            dag.df(pdf.copy())
            .transform(using=udf_arith, schema="*,z:double")
            .partition_by("k")
            .aggregate(ff.sum(col("z")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    seen, _ = _pruned_columns_seen(build, {})
    assert seen and all(set(s) == {"k", "v", "w"} for s in seen), seen[:3]


def test_pruning_reaches_producer_facts_only():
    """translate_udfs=false: the UDF stays interpreted but its EXACT
    column reads still narrow demand — the producer only carries what
    the UDF + downstream read."""
    pdf = _frame(cols=8)

    def build(dag):
        (
            dag.df(pdf.copy())
            .transform(using=udf_arith, schema="*,z:double")
            .partition_by("k")
            .aggregate(ff.sum(col("z")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    seen, _ = _pruned_columns_seen(
        build, {FUGUE_TPU_CONF_PLAN_TRANSLATE_UDFS: False}
    )
    assert seen and all(set(s) == {"k", "v", "w"} for s in seen), seen[:3]
    # parity for the facts-only path against fully-conservative
    _assert_translated_parity(
        build,
        sort=["k"],
        engine_conf={FUGUE_TPU_CONF_PLAN_TRANSLATE_UDFS: False},
    )


def test_pushdown_commutes_through_row_local_udf():
    """translate_udfs=false: a filter over a column the (row-local, pure,
    star-schema) UDF never writes commutes BELOW the interpreted UDF."""
    pdf = _frame()

    def build(dag):
        (
            dag.transform(pdf.copy(), using=udf_writes_passthrough_free, schema="*,z:double")
            .filter(col("x0") < 0.5)
            .select(col("k"), col("z"), col("x0"))
            .yield_dataframe_as("r", as_local=True)
        )

    res, eng, dag = _run_once(
        build, {FUGUE_TPU_CONF_PLAN_TRANSLATE_UDFS: False}, JaxExecutionEngine
    )
    assert dag.last_plan_report.filters_pushed >= 1
    assert (res["x0"] < 0.5).all()
    _assert_translated_parity(build)


def udf_writes_passthrough_free(df: pd.DataFrame) -> pd.DataFrame:
    df["z"] = df["v"].fillna(0.0) * 2.0 + df["w"]
    return df


def test_pruning_under_reduction_udf():
    """A per-partition reduction is pure-but-not-row-local: interpreted
    execution, exact reads — pruning still reaches the producer when the
    downstream demand narrows (star passthrough demands what consumers
    read plus what the UDF reads)."""
    pdf = _frame(cols=8)

    def build(dag):
        (
            dag.df(pdf.copy())
            .transform(using=udf_reduction, schema="*,z:double")
            .partition_by("k")
            .aggregate(ff.sum(col("z")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    seen, _ = _pruned_columns_seen(build, {})
    assert seen and all(set(s) == {"k", "v"} for s in seen), seen[:3]
    res, eng, dag = _run_once(build, {}, JaxExecutionEngine)
    assert eng.stats()["analysis"]["udfs_translated"] == 0
    d = dag.last_plan_report.udf_diags[0]
    assert d["code"] == "reduction" and not d["translated"]
    _assert_translated_parity(build, sort=["k"])


# ---------------------------------------------------------------------------
# refusal matrix — every case bit-identical with the reason rendered
# ---------------------------------------------------------------------------

_GLOBAL_OFFSET = 1.5


def udf_reads_global(df: pd.DataFrame) -> pd.DataFrame:
    df["z"] = df["v"].fillna(0.0) + _GLOBAL_OFFSET
    return df


_MUTABLE = [2.0]


def _make_closure_udf():
    lut = _MUTABLE

    def udf_mutable_closure(df: pd.DataFrame) -> pd.DataFrame:
        df["z"] = df["v"].fillna(0.0) * lut[0]
        return df

    return udf_mutable_closure


def udf_apply(df: pd.DataFrame) -> pd.DataFrame:
    df["z"] = df["v"].apply(lambda x: x * 2)
    return df


def udf_loop(df: pd.DataFrame) -> pd.DataFrame:
    for c in ["v", "w"]:
        df[c] = df[c] * 2
        if c == "v":
            break
    return df


def udf_unknown_method(df: pd.DataFrame) -> pd.DataFrame:
    df["z"] = df["v"].rolling(3).mean()
    return df


def udf_random(df: pd.DataFrame) -> pd.DataFrame:
    df["z"] = df["v"].fillna(0.0) + np.random.random()
    return df


def udf_data_dependent_if(df: pd.DataFrame) -> pd.DataFrame:
    if df["v"].mean() > 0.5:
        df["z"] = df["v"].fillna(1.0)
    else:
        df["z"] = df["w"]
    return df


REFUSALS = [
    (udf_reads_global, "globals"),
    (_make_closure_udf(), "mutable-closure"),
    (udf_apply, "apply"),
    (udf_loop, "loop"),
    (udf_unknown_method, "unknown-call"),
    (udf_random, "non-deterministic"),
    (udf_data_dependent_if, "conditional"),
]


@pytest.mark.parametrize(
    "udf,code", REFUSALS, ids=[c for _, c in REFUSALS]
)
def test_refusal_matrix(udf, code):
    pdf = _frame(1200)

    def build(dag):
        (
            dag.transform(pdf.copy(), using=udf, schema="*,z:double")
            .yield_dataframe_as("r", as_local=True)
        )

    if udf is udf_loop:

        def build(dag):  # noqa: F811 - loop UDF mutates, declares no new col
            (
                dag.transform(pdf.copy(), using=udf, schema="*")
                .yield_dataframe_as("r", as_local=True)
            )

    if udf is udf_random:
        # non-deterministic: can't compare two runs — assert refusal only
        res, eng, dag = _run_once(build, {}, JaxExecutionEngine)
    else:
        res, eng, dag = _assert_translated_parity(build)
    stats = eng.stats()["analysis"]
    assert stats["udfs_translated"] == 0
    assert stats["udfs_refused"] >= 1
    assert code in stats["refused"], stats["refused"]
    dag2 = FugueWorkflow()
    build(dag2)
    text = dag2.explain()
    assert "interpreted --" in text, text


def test_refusal_partitioned_transform():
    pdf = _frame()

    def build(dag):
        (
            dag.df(pdf.copy())
            .partition_by("k")
            .transform(using=udf_arith, schema="*,z:double")
            .yield_dataframe_as("r", as_local=True)
        )

    res, eng, dag = _assert_translated_parity(build, sort=["k", "v", "w"])
    assert eng.stats()["analysis"]["refused"].get("partitioned", 0) >= 1


def udf_writes_passthrough(df: pd.DataFrame) -> pd.DataFrame:
    df["v"] = df["v"].fillna(0.0) * 2.0
    return df


def test_refusal_star_passthrough_write():
    """Writing an existing column under a '*' schema: the enforced output
    dtype is the ORIGINAL input dtype (unknown at plan time) — refuse."""
    pdf = _frame()

    def build(dag):
        (
            dag.transform(pdf.copy(), using=udf_writes_passthrough, schema="*")
            .yield_dataframe_as("r", as_local=True)
        )

    res, eng, dag = _assert_translated_parity(build)
    d = dag.last_plan_report.udf_diags[0]
    assert not d["translated"] and "passthrough" in (d["reason"] or "")


def udf_stale_series(df: pd.DataFrame) -> pd.DataFrame:
    m = df["v"] > 0.5
    df = df[df["w"] > 0.1]
    df = df[m]
    return df


def test_refusal_stale_series_variable():
    """A mask bound BEFORE a frame mutation is pandas-aligned by the
    captured values — re-evaluating it later would see different rows, so
    the analyzer refuses (aliasing)."""
    pdf = _frame(800)

    def build(dag):
        (
            dag.transform(pdf.copy(), using=udf_stale_series, schema="*")
            .yield_dataframe_as("r", as_local=True)
        )

    res, eng, dag = _assert_translated_parity(build)
    assert eng.stats()["analysis"]["refused"].get("aliasing", 0) >= 1


# ---------------------------------------------------------------------------
# fingerprints, caching, delta
# ---------------------------------------------------------------------------


def udf_edit_v1(df: pd.DataFrame) -> pd.DataFrame:
    df["z"] = df["v"].fillna(0.0) + 1.0
    return df


def udf_edit_v2(df: pd.DataFrame) -> pd.DataFrame:
    df["z"] = df["v"].fillna(0.0) + 2.0
    return df


def test_fingerprint_invalidation_on_udf_edit(tmp_path):
    """With the result cache ON, a translated plan's identity follows the
    translated steps: the same UDF warm-hits, an edited one misses."""
    d = str(tmp_path / "cache")
    pdf = _frame(800)

    def build_with(udf):
        def build(dag):
            (
                dag.transform(pdf.copy(), using=udf, schema="*,z:double")
                .yield_dataframe_as("r", as_local=True)
            )

        return build

    conf = {FUGUE_TPU_CONF_CACHE_ENABLED: True, FUGUE_TPU_CONF_CACHE_DIR: d}
    r1, _, _ = _run_once(build_with(udf_edit_v1), conf)
    r1b, e1b, d1b = _run_once(build_with(udf_edit_v1), conf)
    assert d1b.last_cache_plan.summary()["executes"] == 0  # warm hit
    pd.testing.assert_frame_equal(r1, r1b)
    r2, _, d2 = _run_once(build_with(udf_edit_v2), conf)
    assert d2.last_cache_plan.summary()["executes"] >= 1  # edited: recompute
    assert not r1.equals(r2)


def test_delta_cache_serves_analyzed_udf_chain(tmp_path):
    """A row-local analyzed UDF chain over a grown parquet directory
    recomputes ONLY the appended partition on the warm run."""
    src = str(tmp_path / "src")
    os.makedirs(src)

    def write_part(i):
        rng = np.random.default_rng(500 + i)
        n = 700
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 8, n).astype("int64"),
                    "v": rng.random(n),
                    "w": rng.random(n),
                }
            ),
            os.path.join(src, f"part_{i:03d}.parquet"),
        )

    for i in range(3):
        write_part(i)

    def build(dag):
        (
            dag.load(src, fmt="parquet")
            .transform(using=udf_arith, schema="*,z:double")
            .yield_dataframe_as("r", as_local=True)
        )

    conf = {
        FUGUE_TPU_CONF_CACHE_ENABLED: True,
        FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache"),
    }
    r1, e1, _ = _run_once(build, conf)
    write_part(3)  # grow the source
    r2, e2, d2 = _run_once(build, conf)
    cs = e2.stats()["cache"]
    assert cs["partial_hits"] >= 1, cs
    # 3 partitions served from cache, exactly the 1 appended one fresh
    assert cs["delta_partitions_fresh"] == 1 and cs["delta_partitions"] == 3, cs
    # bit-identical to a cache-off full recompute
    ref, _, _ = _run_once(build, {FUGUE_TPU_CONF_CACHE_ENABLED: False})
    pd.testing.assert_frame_equal(r2, ref)


# ---------------------------------------------------------------------------
# surface: lint, counters, metrics, conf gates
# ---------------------------------------------------------------------------


def test_lint_structured_diagnostics():
    pdf = _frame()
    dag = FugueWorkflow()
    (
        dag.transform(pdf, using=udf_arith, schema="*,z:double")
        .partition_by("k")
        .aggregate(ff.sum(col("z")).alias("s"))
        .yield_dataframe_as("r", as_local=True)
    )
    rep = dag.lint()
    udfs = rep.udfs
    assert len(udfs) == 1 and udfs[0].status == "translated", rep.as_dict()
    assert any(d.kind == "segment" for d in rep.diagnostics), rep.as_dict()
    text = dag.explain(lint=True)
    assert "== lint ==" in text and "[udf]" in text
    # a refused UDF carries its reason code + message
    dag2 = FugueWorkflow()
    dag2.transform(pdf, using=udf_apply, schema="*,z:double").yield_dataframe_as(
        "r2", as_local=True
    )
    rep2 = dag2.lint()
    assert rep2.udfs[0].status == "apply", rep2.as_dict()
    assert "apply" in rep2.udfs[0].message or ".apply" in rep2.udfs[0].message


def test_counters_and_prometheus_exposition():
    from fugue_tpu.obs import to_prometheus_text, validate_prometheus_text

    pdf = _frame(800)

    def build(dag):
        (
            dag.transform(pdf.copy(), using=udf_arith, schema="*,z:double")
            .yield_dataframe_as("r", as_local=True)
        )

    res, eng, _ = _run_once(build, {}, JaxExecutionEngine)
    res2, eng2, _ = _run_once(
        lambda dag: dag.transform(
            pdf.copy(), using=udf_apply, schema="*,z:double"
        ).yield_dataframe_as("r", as_local=True),
        {},
        JaxExecutionEngine,
    )
    st = eng.stats()["analysis"]
    assert st == {
        "udfs_analyzed": 1,
        "udfs_translated": 1,
        "udfs_refused": 0,
        "refused": {},
    }
    text = to_prometheus_text(engine=eng2)
    validate_prometheus_text(text)
    for want in (
        "fugue_tpu_analysis_udfs_analyzed 1",
        "fugue_tpu_analysis_udfs_refused 1",
        "fugue_tpu_analysis_refused_apply 1",
    ):
        assert want in text, want
    # reset contract: counters zero, source object kept
    eng2.reset_stats()
    assert eng2.stats()["analysis"]["udfs_analyzed"] == 0


def test_conf_gates():
    pdf = _frame(800)

    def build(dag):
        (
            dag.transform(pdf.copy(), using=udf_arith, schema="*,z:double")
            .yield_dataframe_as("r", as_local=True)
        )

    # analyze_udfs=false: nothing analyzed, fully conservative
    res_off, eng_off, dag_off = _run_once(
        build, {FUGUE_TPU_CONF_PLAN_ANALYZE_UDFS: False}
    )
    assert eng_off.stats()["analysis"]["udfs_analyzed"] == 0
    assert dag_off.last_plan_report.udfs_analyzed == 0
    # translate_udfs=false: analyzed, refused with code "disabled"
    res_nt, eng_nt, dag_nt = _run_once(
        build, {FUGUE_TPU_CONF_PLAN_TRANSLATE_UDFS: False}
    )
    st = eng_nt.stats()["analysis"]
    assert st["udfs_analyzed"] == 1 and st["udfs_translated"] == 0
    assert st["refused"].get("disabled") == 1
    pd.testing.assert_frame_equal(res_off, res_nt)


def test_exec_udf_refuses_no_source():
    """A UDF with no retrievable source (exec'd) refuses conservatively."""
    ns = {"pd": pd}
    exec(
        "def bump(df: pd.DataFrame) -> pd.DataFrame:\n"
        "    return df.assign(z=df['v'] + 1.0)\n",
        ns,
    )
    pdf = _frame(600)

    def build(dag):
        (
            dag.transform(pdf.copy(), using=ns["bump"], schema="*,z:double")
            .yield_dataframe_as("r", as_local=True)
        )

    res, eng, _ = _run_once(build, {}, JaxExecutionEngine)
    st = eng.stats()["analysis"]
    assert st["udfs_translated"] == 0 and st["refused"].get("source") == 1
