"""WarehouseJaxExecutionEngine — the engine-level warehouse+device hybrid
(reference DuckDaskExecutionEngine, fugue_duckdb/dask.py:17-40): SQL verbs
push down to sqlite, map verbs run on the jax mesh, ONE engine end to end.
Includes the full execution contract suite."""

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.execution import ExecutionEngine
from fugue_tpu.warehouse import (
    WarehouseDataFrame,
    WarehouseJaxExecutionEngine,
    WarehouseJaxMapEngine,
)
from fugue_tpu_test import ExecutionEngineTests, WarehouseSuiteOverrides


class TestWarehouseJaxExecutionEngine(
    WarehouseSuiteOverrides, ExecutionEngineTests.Tests
):
    def make_engine(self) -> ExecutionEngine:
        return WarehouseJaxExecutionEngine(dict(test=True))


@pytest.fixture()
def eng():
    e = WarehouseJaxExecutionEngine()
    yield e
    e.stop_engine()


def test_engine_composition(eng):
    assert isinstance(eng.map_engine, WarehouseJaxMapEngine)
    assert eng.is_distributed and eng.map_engine.is_distributed
    assert eng.get_current_parallelism() == eng.jax_engine.get_current_parallelism()
    assert eng.get_current_parallelism() > 1  # the 8-device test mesh


def test_sql_stays_in_warehouse_map_runs_on_mesh(eng):
    """The defining property: relational verbs produce warehouse frames
    (no device detour), map verbs produce device results (no local-oracle
    roundtrip) — observed via the frame types each facet emits.
    engine_context keeps the engine alive across the api calls (reference
    lifecycle: context exit at refcount zero stops the engine)."""
    from fugue_tpu.column import col, functions as ff
    from fugue_tpu.execution.api import engine_context

    ctx = engine_context(eng)
    ctx.__enter__()
    try:
        _check_hybrid_facets(eng)
    finally:
        ctx.__exit__(None, None, None)


def _check_hybrid_facets(eng):
    from fugue_tpu.column import col, functions as ff

    pdf = pd.DataFrame({"k": [1, 2, 1, 3], "v": [1.0, 2.0, 3.0, 4.0]})
    wdf = eng.to_df(pdf)
    assert isinstance(wdf, WarehouseDataFrame)
    filtered = eng.filter(wdf, col("v") > 1.0)
    assert isinstance(filtered, WarehouseDataFrame)  # pushed-down SQL
    agg = eng.aggregate(
        filtered, PartitionSpec(by=["k"]), [ff.sum(col("v")).alias("s")]
    )
    assert isinstance(agg, WarehouseDataFrame)

    # the map side: jax-annotated UDF compiles onto the mesh
    calls = []
    orig = eng.jax_engine.map_engine.map_dataframe

    def spy(*a, **k):
        res = orig(*a, **k)
        calls.append(type(res).__name__)
        return res

    eng.jax_engine.map_engine.map_dataframe = spy
    try:
        from typing import Dict

        import jax

        def plus(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            return {"k": cols["k"], "v": cols["v"] + 10.0}

        out = fa.transform(wdf, plus, schema="k:long,v:double", engine=eng, as_fugue=True)
    finally:
        eng.jax_engine.map_engine.map_dataframe = orig
    assert calls == ["JaxDataFrame"]  # device-resident result, mesh-run
    assert sorted(r[1] for r in out.as_array()) == [11.0, 12.0, 13.0, 14.0]

    # engine-level map hands the result back into warehouse storage
    def m(cursor, local):
        return local

    direct = eng.map_engine.map_dataframe(
        wdf, m, wdf.schema, PartitionSpec(by=["k"])
    )
    assert isinstance(direct, WarehouseDataFrame)
    assert direct.count() == 4


def test_mixed_sql_transform_pipeline_one_engine(eng):
    """The VERDICT's done-bar: SELECT -> TRANSFORM -> SELECT runs on ONE
    engine, storage-side SQL + device-side compute."""
    df = pd.DataFrame({"k": [1, 1, 2, 2, 3], "v": [1.0, 2.0, 3.0, 4.0, 5.0]})

    def demean(pdf: pd.DataFrame) -> pd.DataFrame:
        pdf["v"] = pdf["v"] - pdf["v"].mean()
        return pdf

    res = fa.fugue_sql(
        """
        src = CREATE [[1,1.0],[1,2.0],[2,3.0],[2,4.0],[3,5.0]] SCHEMA k:long,v:double
        big = SELECT * FROM src WHERE v > 1.5
        centered = TRANSFORM big PREPARTITION BY k USING demean SCHEMA k:long,v:double
        SELECT k, COUNT(*) AS n FROM centered GROUP BY k
        """,
        demean=demean,
        engine=eng,
        as_fugue=True,
    )
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    assert got["k"].tolist() == [1, 2, 3] and got["n"].tolist() == [1, 2, 1]
    # oracle for the demean step itself
    exp = df[df.v > 1.5].groupby("k").size()
    assert got.set_index("k")["n"].to_dict() == exp.to_dict()


def test_engine_name_registration():
    from fugue_tpu.execution.factory import make_execution_engine

    e = make_execution_engine("sqlite_jax")
    try:
        assert isinstance(e, WarehouseJaxExecutionEngine)
    finally:
        e.stop_engine()
