"""Warehouse (sqlite) engine contract tests — the Ibis-role analog of the
reference's backend test dirs (SQL pushdown engines run the same
engine-op matrix, /root/reference/tests/fugue_ibis)."""

import datetime
import os

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.exceptions import FugueInvalidOperation
from fugue_tpu.warehouse import SQLiteExecutionEngine, WarehouseDataFrame


@pytest.fixture()
def eng():
    e = SQLiteExecutionEngine()
    yield e
    e.stop_engine()


@pytest.fixture()
def wdf(eng):
    return eng.to_df(
        pd.DataFrame(
            {
                "k": [1, 2, 1, 3, 2],
                "v": [1.0, 2.0, 3.0, 4.0, 5.0],
                "s": ["a", "b", "c", "d", "e"],
            }
        )
    )


def test_ingest_fetch_roundtrip(eng, wdf):
    assert str(wdf.schema) == "k:long,v:double,s:str"
    assert wdf.count() == 5
    assert not wdf.is_local and wdf.is_bounded
    assert wdf.as_array()[0] == [1, 1.0, "a"]
    assert wdf.peek_array() == [1, 1.0, "a"]


def test_nulls_and_types_roundtrip(eng):
    pdf = pd.DataFrame(
        {
            "b": pd.array([True, False, None], dtype="boolean"),
            "i": pd.array([1, None, 3], dtype="Int64"),
            "f": [1.5, None, 2.5],
            "s": ["x", None, "z"],
            "bin": [b"ab", None, b"cd"],
            "ts": pd.to_datetime(
                ["2024-01-01 10:00:00", None, "2025-02-03 04:05:06.123456"],
                format="mixed",
            ),
        }
    )
    w = eng.to_df(pdf)
    back = w.as_pandas()
    assert back["b"][0] == True and pd.isna(back["b"][2])  # noqa: E712
    assert back["i"][0] == 1 and pd.isna(back["i"][1])
    assert back["bin"][0] == b"ab" and back["bin"][1] is None
    assert back["ts"][2] == pd.Timestamp("2025-02-03 04:05:06.123456")


def test_nested_types_rejected(eng):
    pdf = pd.DataFrame({"a": [[1, 2], [3]]})
    with pytest.raises(FugueInvalidOperation):
        eng.to_df(fa.as_fugue_df(pdf, schema="a:[long]"))


def test_select_filter_assign_aggregate_pushdown(eng, wdf):
    # these verbs run as generated SQL in the warehouse (no local detour)
    agg = eng.aggregate(
        wdf,
        PartitionSpec(by=["k"]),
        [ff.sum(col("v")).alias("sv"), ff.count(col("v")).alias("n")],
    )
    assert isinstance(agg, WarehouseDataFrame)
    assert sorted(agg.as_array()) == [[1, 4.0, 2], [2, 7.0, 2], [3, 4.0, 1]]
    f = eng.filter(wdf, col("v") > 2.0)
    assert isinstance(f, WarehouseDataFrame) and f.count() == 3
    a = eng.assign(f, [(col("v") * 2).alias("v")])
    assert sorted(r[1] for r in a.as_array()) == [6.0, 8.0, 10.0]


def test_joins(eng, wdf):
    other = eng.to_df(pd.DataFrame({"k": [1, 2, 9], "w": ["x", "y", "z"]}))
    inner = eng.join(wdf, other, "inner", on=["k"])
    assert sorted(r[0] for r in inner.as_array()) == [1, 1, 2, 2]
    lo = eng.join(wdf, other, "left_outer", on=["k"])
    rows = {tuple(r[:1] + r[3:]) for r in lo.as_array()}
    assert (3, None) in rows
    ro = eng.join(wdf, other, "right_outer", on=["k"])
    assert sorted(r[0] for r in ro.as_array()) == [1, 1, 2, 2, 9]
    fo = eng.join(wdf, other, "full_outer", on=["k"])
    assert sorted(r[0] for r in fo.as_array()) == [1, 1, 2, 2, 3, 9]
    semi = eng.join(wdf, other, "semi", on=["k"])
    assert sorted(r[0] for r in semi.as_array()) == [1, 1, 2, 2]
    anti = eng.join(wdf, other, "anti", on=["k"])
    assert [r[0] for r in anti.as_array()] == [3]
    c1 = eng.to_df(pd.DataFrame({"a": [1, 2]}))
    c2 = eng.to_df(pd.DataFrame({"b": [3, 4]}))
    cross = eng.join(c1, c2, "cross")
    assert cross.count() == 4


def test_set_ops_and_distinct(eng):
    d1 = eng.to_df(pd.DataFrame({"x": [1, 1, 1, 2]}))
    d2 = eng.to_df(pd.DataFrame({"x": [1, 3]}))
    assert sorted(r[0] for r in eng.union(d1, d2, distinct=True).as_array()) == [1, 2, 3]
    assert eng.union(d1, d2, distinct=False).count() == 6
    assert sorted(r[0] for r in eng.subtract(d1, d2).as_array()) == [2]
    assert sorted(r[0] for r in eng.subtract(d1, d2, distinct=False).as_array()) == [1, 1, 2]
    assert sorted(r[0] for r in eng.intersect(d1, d2).as_array()) == [1]
    assert sorted(r[0] for r in eng.intersect(d1, d2, distinct=False).as_array()) == [1]
    assert eng.distinct(d1).count() == 2


def test_dropna_fillna(eng):
    d = eng.to_df(pd.DataFrame({"a": [1.0, None, 3.0], "b": [None, None, "x"]}))
    assert eng.dropna(d, how="any").count() == 1
    assert eng.dropna(d, how="all").count() == 2
    assert eng.dropna(d, how="any", thresh=1).count() == 2
    assert eng.dropna(d, how="any", subset=["a"]).count() == 2
    filled = eng.fillna(d, {"a": 0.0, "b": "?"}).as_array()
    assert [r[0] for r in filled] == [1.0, 0.0, 3.0]
    assert [r[1] for r in filled] == ["?", "?", "x"]
    with pytest.raises(ValueError):
        eng.fillna(d, None)


def test_take_and_sample(eng, wdf):
    t = eng.take(wdf, 1, presort="v desc", partition_spec=PartitionSpec(by=["k"]))
    assert sorted(t.as_array()) == [[1, 3.0, "c"], [2, 5.0, "e"], [3, 4.0, "d"]]
    t2 = eng.take(wdf, 2, presort="v")
    assert [r[1] for r in t2.as_array()] == [1.0, 2.0]
    s = eng.sample(wdf, frac=0.5)
    assert 0 <= s.count() <= 5
    s2 = eng.sample(wdf, n=3)
    assert s2.count() == 3
    with pytest.raises(NotImplementedError):
        eng.sample(wdf, n=2, replace=True)


def test_frame_ops(eng, wdf):
    r = wdf.rename({"v": "value"})
    assert str(r.schema) == "k:long,value:double,s:str"
    d = r.drop(["s"])
    assert str(d.schema) == "k:long,value:double"
    h = wdf.head(2)
    assert h.is_local and h.count() == 2
    alt = wdf.alter_columns("k:int")
    assert str(alt.schema["k"].type) == "int32"


def test_save_load_table_schema_fidelity(eng, tmp_path):
    path = str(tmp_path / "wh.db")
    e1 = SQLiteExecutionEngine({"fugue.sqlite.path": path})
    pdf = pd.DataFrame(
        {
            "b": pd.array([True, None], dtype="boolean"),
            "i": pd.array([5, None], dtype="Int32"),
            "ts": pd.to_datetime(["2024-06-01 01:02:03", None]),
        }
    )
    w = e1.to_df(pdf)
    e1.sql_engine.save_table(w, "t1")
    assert e1.sql_engine.table_exists("t1")
    # a NEW engine over the same file recovers the exact schema (sqlite's
    # own storage classes can't express bool/int32/timestamp)
    e2 = SQLiteExecutionEngine({"fugue.sqlite.path": path})
    back = e2.sql_engine.load_table("t1")
    assert str(back.schema) == str(w.schema)
    got = back.as_pandas()
    assert got["b"][0] is True or got["b"][0] == True  # noqa: E712
    assert got["ts"][0] == pd.Timestamp("2024-06-01 01:02:03")
    e1.stop_engine()
    e2.stop_engine()


def test_raw_sql_select(eng, wdf):
    from fugue_tpu.collections.sql import StructuredRawSQL
    from fugue_tpu.dataframe import DataFrames

    stmt = StructuredRawSQL(
        [(False, "SELECT k, SUM(v) AS s FROM"), (True, "t"), (False, "GROUP BY k")]
    )
    res = eng.sql_engine.select(DataFrames(t=wdf), stmt)
    assert sorted(res.as_array()) == [[1, 4.0], [2, 7.0], [3, 4.0]]


def test_transform_api_roundtrip():
    df = pd.DataFrame({"k": [1, 2, 1, 3, 2], "v": [1.0, 2.0, 3.0, 4.0, 5.0]})

    def demean(d: pd.DataFrame) -> pd.DataFrame:
        d["v"] = d["v"] - d["v"].mean()
        return d

    out = fa.transform(
        df, demean, schema="*", partition=PartitionSpec(by=["k"]), engine="sqlite"
    )
    out = out.as_pandas() if hasattr(out, "as_pandas") else out
    exp = df.copy()
    exp["v"] = exp["v"] - exp.groupby("k")["v"].transform("mean")
    a = out.sort_values(["k", "v"]).reset_index(drop=True)
    b = exp.sort_values(["k", "v"]).reset_index(drop=True)
    assert np.allclose(a["v"], b["v"]) and (a["k"] == b["k"]).all()


def test_fugue_sql_on_sqlite():
    df = pd.DataFrame({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
    res = fa.fugue_sql(
        "SELECT k, SUM(v) AS s FROM df GROUP BY k", df=df, engine="sqlite"
    )
    got = res.to_pandas() if hasattr(res, "to_pandas") else res
    assert sorted(got.values.tolist()) == [[1, 4.0], [2, 2.0]]


def test_engine_inference_from_warehouse_frame(eng, wdf):
    # passing a warehouse frame into fa.* without an engine spec must
    # infer this engine (reference fugue_ibis/registry pattern)
    out = fa.transform(
        wdf,
        lambda d: d,  # noqa: E731
        schema="*",
    ) if False else None
    # inference via the plugin directly (transform with a lambda lacks
    # annotations; the inference hook is what's under test)
    from fugue_tpu.execution.factory import infer_execution_engine

    assert infer_execution_engine([wdf]) is eng


def test_sqlite_connection_as_engine_spec():
    import sqlite3

    con = sqlite3.connect(":memory:", check_same_thread=False)
    df = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
    res = fa.fugue_sql(
        "SELECT k, COUNT(*) AS n FROM df GROUP BY k", df=df, engine=con
    )
    got = res.as_pandas() if hasattr(res, "as_pandas") else res
    assert sorted(got.values.tolist()) == [[1, 2], [2, 1]]


def test_fsql_connect_sqlite_engine_switch():
    # FugueSQL CONNECT runs the following statement on the sqlite SQL
    # engine while the workflow itself stays on another engine (the
    # reference's mixed-engine pattern, fugue_duckdb/dask.py:17-40)
    df = pd.DataFrame({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
    res = fa.fugue_sql(
        "CONNECT sqlite SELECT k, SUM(v) AS s FROM df GROUP BY k",
        df=df,
        engine="native",
    )
    if hasattr(res, "as_pandas"):
        got = res.as_pandas()
    elif hasattr(res, "to_pandas"):
        got = res.to_pandas()
    else:
        got = res
    assert sorted(r[1] for r in got.values.tolist()) == [2.0, 4.0]


def test_warehouse_to_device_interop(eng, wdf):
    import jax

    from fugue_tpu.jax import JaxExecutionEngine

    je = JaxExecutionEngine()
    jdf = je.to_df(wdf)
    r = je.aggregate(
        jdf, PartitionSpec(by=["k"]), [ff.sum(col("v")).alias("sv")]
    )
    assert sorted(r.as_pandas()[["k", "sv"]].values.tolist()) == [
        [1, 4.0],
        [2, 7.0],
        [3, 4.0],
    ]


def test_load_save_df_files(eng, tmp_path, wdf):
    p = str(tmp_path / "out.parquet")
    eng.save_df(wdf, p)
    back = eng.load_df(p)
    assert isinstance(back, WarehouseDataFrame)
    assert sorted(back.as_array()) == sorted(wdf.as_array())


def test_seeded_sample_is_deterministic(eng):
    pdf = pd.DataFrame({"a": range(200), "b": np.arange(200) * 0.5})
    d = eng.to_df(pdf)
    s1 = eng.sample(d, frac=0.3, seed=42).as_pandas().sort_values("a")
    s2 = eng.sample(d, frac=0.3, seed=42).as_pandas().sort_values("a")
    pd.testing.assert_frame_equal(s1.reset_index(drop=True), s2.reset_index(drop=True))
    assert 20 < len(s1) < 100  # roughly frac * 200
    s3 = eng.sample(d, frac=0.3, seed=7).as_pandas()
    assert set(s3["a"]) != set(s1["a"])  # different seed, different rows
    n1 = eng.sample(d, n=17, seed=5).as_pandas().sort_values("a")
    n2 = eng.sample(d, n=17, seed=5).as_pandas().sort_values("a")
    assert len(n1) == 17
    pd.testing.assert_frame_equal(n1.reset_index(drop=True), n2.reset_index(drop=True))


def test_count_memoized_single_query(eng, wdf):
    calls = []
    eng.connection.set_trace_callback(calls.append)
    try:
        assert wdf.count() == 5
        assert wdf.count() == 5
        assert not wdf.empty
    finally:
        eng.connection.set_trace_callback(None)
    count_queries = [s for s in calls if "COUNT(*)" in s]
    assert len(count_queries) <= 1


def test_seeded_sample_with_rowid_column_and_load_table_count(eng):
    # a user column named "rowid" must not shadow the sample's row hash
    pdf = pd.DataFrame({"rowid": [f"r{i}" for i in range(100)], "v": range(100)})
    d = eng.to_df(pdf)
    s = eng.sample(d, frac=0.3, seed=42).as_pandas()
    assert 10 < len(s) < 60
    assert set(s.columns) == {"rowid", "v"}
    n = eng.sample(d, n=10, seed=1).as_pandas()
    assert len(n) == 10 and sorted(n["v"]) != list(range(10))

    # load_table frames track overwrites (no stale memoized count)
    sql_eng = eng.sql_engine
    sql_eng.save_table(eng.to_df(pd.DataFrame({"a": [1, 2, 3]})), "t_mut")
    f = sql_eng.load_table("t_mut")
    assert f.count() == 3
    sql_eng.save_table(eng.to_df(pd.DataFrame({"a": [1, 2, 3, 4, 5]})), "t_mut")
    assert f.count() == 5


# full engine contract suite on the plain warehouse engine (previously
# only hand-rolled tests covered it); shares the documented skips with
# the hybrid suite
from fugue_tpu.execution import ExecutionEngine  # noqa: E402
from fugue_tpu_test import (  # noqa: E402
    ExecutionEngineTests,
    WarehouseSuiteOverrides,
)


class TestSQLiteExecutionEngineSuite(
    WarehouseSuiteOverrides, ExecutionEngineTests.Tests
):
    def make_engine(self) -> ExecutionEngine:
        return SQLiteExecutionEngine(dict(test=True))
