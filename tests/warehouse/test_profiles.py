"""Warehouse driver profiles (`fugue_tpu/warehouse/profile.py`).

Proves the DB-API layer generalizes past sqlite (VERDICT r4 #7): the
postgres profile's emitted SQL is pinned by golden tests (no live server
in this environment — the reference's ibis engine plays this role for
BigQuery/Trino, `/root/reference/fugue_ibis/execution_engine.py:30`),
and a fake DB-API connection exercises the engine's call pattern against
the postgres profile end to end. The sqlite profile runs live everywhere
else in tests/warehouse.
"""

from typing import Any, List, Optional, Tuple

import pyarrow as pa
import pytest

from fugue_tpu.exceptions import FugueInvalidOperation
from fugue_tpu.schema import Schema
from fugue_tpu.warehouse.profile import (
    PostgresProfile,
    SQLiteProfile,
    get_profile,
)

SCHEMA = Schema("a:long,b:double,c:str,d:bool,e:datetime,f:bytes,g:int")


# ---------------------------------------------------------------------------
# golden SQL per profile
# ---------------------------------------------------------------------------


def test_sqlite_golden_sql():
    p = SQLiteProfile()
    assert p.create_temp_table_sql("t1", SCHEMA) == (
        'CREATE TEMP TABLE "t1" ("a" INTEGER, "b" REAL, "c" TEXT, '
        '"d" INTEGER, "e" TEXT, "f" BLOB, "g" INTEGER)'
    )
    assert p.insert_sql("t1", 3) == 'INSERT INTO "t1" VALUES (?, ?, ?)'
    assert p.table_exists_sql(views=True) == (
        "SELECT name FROM sqlite_master WHERE type IN ('table','view') "
        "AND name = ?"
    )
    assert p.meta_upsert_sql() == (
        "INSERT OR REPLACE INTO __fugue_schemas__ VALUES (?, ?)"
    )
    assert p.decl_to_arrow("BIGINT") == pa.int64()
    assert p.decl_to_arrow("") is None  # dynamic: needs sampling


def test_postgres_golden_sql():
    p = PostgresProfile()
    assert p.create_temp_table_sql("t1", SCHEMA) == (
        'CREATE TEMPORARY TABLE "t1" ("a" BIGINT, "b" DOUBLE PRECISION, '
        '"c" TEXT, "d" BOOLEAN, "e" TIMESTAMP, "f" BYTEA, "g" INTEGER)'
    )
    assert p.insert_sql("t1", 3) == 'INSERT INTO "t1" VALUES (%s, %s, %s)'
    assert p.create_temp_table_as_sql("t2", "SELECT 1 AS x") == (
        'CREATE TEMPORARY TABLE "t2" AS SELECT 1 AS x'
    )
    assert p.table_exists_sql(views=True) == (
        "SELECT table_name FROM information_schema.tables "
        "WHERE table_name = %s"
    )
    assert p.meta_upsert_sql() == (
        "INSERT INTO __fugue_schemas__ VALUES (%s, %s) "
        "ON CONFLICT (tbl) DO UPDATE SET schema = EXCLUDED.schema"
    )
    # postgres types round-trip without sampling
    assert p.decl_to_arrow("DOUBLE PRECISION") == pa.float64()
    assert p.decl_to_arrow("TIMESTAMP WITHOUT TIME ZONE") == pa.timestamp("us")
    assert p.decl_to_arrow("BOOLEAN") == pa.bool_()


def test_profile_lookup_and_errors():
    assert get_profile(None).name == "sqlite"
    assert get_profile("postgres").name == "postgres"
    p = SQLiteProfile()
    assert get_profile(p) is p
    with pytest.raises(FugueInvalidOperation):
        get_profile("oracle9i")
    with pytest.raises(FugueInvalidOperation):
        PostgresProfile().storage_type(pa.list_(pa.int64()))


# ---------------------------------------------------------------------------
# engine-through-profile: a fake postgres DB-API connection records every
# statement; the engine must speak ONLY the profile's SQL
# ---------------------------------------------------------------------------


class _FakeCursor:
    def __init__(self, rows: List[Tuple]):
        self._rows = rows

    def fetchone(self) -> Optional[Tuple]:
        return self._rows[0] if self._rows else None

    def fetchall(self) -> List[Tuple]:
        return list(self._rows)


class _FakePostgresConn:
    """Answers the minimal surface the engine touches during ingest +
    introspection, recording statements for assertion."""

    def __init__(self) -> None:
        self.statements: List[str] = []
        self.tables: dict = {}

    def execute(self, sql: str, params: Any = None) -> _FakeCursor:
        self.statements.append(sql)
        if sql.startswith("CREATE TEMPORARY TABLE") and "(" in sql:
            return _FakeCursor([])
        if "information_schema.tables" in sql:
            name = params[0]
            return _FakeCursor([(name,)] if name in self.tables else [])
        return _FakeCursor([])

    def executemany(self, sql: str, rows: Any) -> None:
        self.statements.append(sql)

    def commit(self) -> None:
        pass

    def close(self) -> None:
        pass


def test_engine_ingest_speaks_postgres():
    import pandas as pd

    from fugue_tpu.warehouse.execution_engine import WarehouseExecutionEngine

    conn = _FakePostgresConn()
    eng = WarehouseExecutionEngine(connection=conn, profile="postgres")
    assert eng.encode_name("a b") == '"a b"'
    wdf = eng.ingest(
        eng._local_engine.to_df(pd.DataFrame({"a": [1], "b": [0.5]}))
    )
    create = [s for s in conn.statements if s.startswith("CREATE TEMPORARY")]
    insert = [s for s in conn.statements if s.startswith("INSERT INTO")]
    assert len(create) == 1 and '"a" BIGINT, "b" DOUBLE PRECISION' in create[0]
    assert len(insert) == 1 and insert[0].endswith("VALUES (%s, %s)")
    assert wdf.schema == Schema("a:long,b:double")
    # recorded schema wins over introspection
    assert eng.infer_table_schema(wdf.table) == wdf.schema


# ---------------------------------------------------------------------------
# empty-result schema inference (the round-3/4 TEXT-default degradation)
# ---------------------------------------------------------------------------


def test_empty_raw_sql_result_keeps_inferred_types():
    import pandas as pd

    from fugue_tpu.dataframe import DataFrames
    from fugue_tpu.collections.sql import StructuredRawSQL
    from fugue_tpu.warehouse.execution_engine import SQLiteExecutionEngine

    eng = SQLiteExecutionEngine()
    try:
        src = eng.to_df(
            pd.DataFrame({"k": [1, 2], "v": [0.5, 1.5], "s": ["a", "b"]})
        )
        stmt = StructuredRawSQL.from_expr(
            "SELECT k, SUM(v) AS total, COUNT(*) AS n, s "
            "FROM <tmpdf:src> WHERE v > 100.0 GROUP BY k, s",
            dialect="fugue",
        )
        res = eng.sql_engine.select(DataFrames(src=src), stmt)
        assert res.count() == 0
        # before the IR inference, computed cols degraded to str on empty
        # results; now the expression types survive
        assert res.schema == Schema("k:long,total:double,n:long,s:str")
    finally:
        eng.stop_engine()


def test_empty_result_inference_falls_back_safely():
    import pandas as pd

    from fugue_tpu.dataframe import DataFrames
    from fugue_tpu.collections.sql import StructuredRawSQL
    from fugue_tpu.warehouse.execution_engine import SQLiteExecutionEngine

    eng = SQLiteExecutionEngine()
    try:
        src = eng.to_df(pd.DataFrame({"k": [1, 2]}))
        # sqlite-specific syntax the in-tree parser can't read: inference
        # returns None and the sampling path still answers
        stmt = StructuredRawSQL.from_expr(
            "SELECT k FROM <tmpdf:src> WHERE k > 100", dialect="fugue"
        )
        res = eng.sql_engine.select(DataFrames(src=src), stmt)
        assert res.count() == 0
        assert res.schema == Schema("k:long")
    finally:
        eng.stop_engine()
