"""Per-verb roofline recording (ISSUE 18 satellite; docs/tuning.md) —
record-only groundwork for ROADMAP 5's cost-model replacement.

Covers the fold math (associative delta publishes), the TunedStore
"rooflines" document key (atomic publish, foreign-key preservation, the
shared LRU bound), the verb-observer gate (conf off → no observer
installed; tracing off → zero folds), and the ``engine.report()``
rendering.
"""

import json
import threading

import numpy as np
import pandas as pd
import pytest

from fugue_tpu.constants import FUGUE_TPU_CONF_TUNING_ROOFLINES
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.obs import get_tracer, set_verb_observer
from fugue_tpu.tuning import RooflineRecorder, rooflines_enabled
from fugue_tpu.tuning.store import TunedStore


class Stats:
    def __init__(self):
        self.d = {}

    def inc(self, k, n=1):
        self.d[k] = self.d.get(k, 0) + n


@pytest.fixture
def tracer():
    tr = get_tracer()
    tr.clear()
    tr.enable()
    yield tr
    tr.disable()
    tr.clear()


def test_fold_math_best_and_totals(tmp_path):
    store = TunedStore(str(tmp_path / "_tuned.json"))
    rec = RooflineRecorder(store)
    rec.observe("engine.filter", "float", 2, wall_s=0.25, rows=1_000_000,
                nbytes=8_000_000)
    rec.observe("engine.filter", "float", 2, wall_s=0.50, rows=1_000_000,
                nbytes=16_000_000)
    assert rec.pending_count() == 1
    (entry,) = rec.snapshot().values()
    assert entry["obs"] == 2
    assert entry["rows"] == 2_000_000 and entry["bytes"] == 24_000_000
    # best_* is the max ACHIEVED rate across observations, not an average
    assert entry["best_bytes_s"] == pytest.approx(16_000_000 / 0.5)
    assert entry["best_rows_s"] == pytest.approx(1_000_000 / 0.25)
    # last_* is the most recent observation's rate
    assert entry["last_bytes_s"] == pytest.approx(16_000_000 / 0.5)
    assert entry["last_rows_s"] == pytest.approx(1_000_000 / 0.5)


def test_flush_publishes_delta_and_preserves_foreign_keys(tmp_path):
    path = str(tmp_path / "_tuned.json")
    with open(path, "w") as f:
        json.dump({"tuning": {"version": 1, "plans": {"fp": {"x": 1}}}}, f)
    st = Stats()
    store = TunedStore(path, stats=st)
    rec = RooflineRecorder(store, stats=st)
    rec.observe("engine.take", "int", 4, wall_s=0.1, rows=1000, nbytes=32_000)
    assert rec.flush() and rec.pending_count() == 0
    with open(path) as f:
        doc = json.load(f)
    # the tuning document is intact next to the new rooflines key
    assert doc["tuning"]["plans"] == {"fp": {"x": 1}}
    assert doc["rooflines"]["entries"]["engine.take|int|w4"]["obs"] == 1
    assert st.d["roofline_publishes"] == 1
    # a SECOND process's delta folds in (associative read-merge-write)
    other = RooflineRecorder(TunedStore(path))
    other.observe("engine.take", "int", 4, wall_s=0.1, rows=1000, nbytes=32_000)
    assert other.flush()
    with open(path) as f:
        doc = json.load(f)
    assert doc["rooflines"]["entries"]["engine.take|int|w4"]["obs"] == 2
    # both stores converge on re-read
    assert store.rooflines()["engine.take|int|w4"]["obs"] == 2


def test_rooflines_share_the_lru_bound(tmp_path):
    st = Stats()
    store = TunedStore(str(tmp_path / "_tuned.json"), max_entries=3, stats=st)
    rec = RooflineRecorder(store)
    for i in range(5):
        rec.observe(f"engine.v{i}", "float", 1, wall_s=0.1, rows=10, nbytes=80)
        assert rec.flush()
    assert len(store.rooflines()) == 3
    assert st.d["evictions"] >= 2


def test_tiny_verbs_and_nonframes_are_skipped(tmp_path):
    from fugue_tpu.tuning.roofline import MIN_VERB_WALL_S

    rec = RooflineRecorder(TunedStore(str(tmp_path / "t.json")))
    rec.record("engine.take", MIN_VERB_WALL_S / 2, object())  # too fast
    rec.record("engine.take", 1.0, object())  # not a frame
    rec.record("engine.take", 1.0, None)
    assert rec.pending_count() == 0


def test_conf_gate_and_engine_end_to_end(tmp_path, tracer):
    assert rooflines_enabled({}) is True  # default ON (record-only, cheap)
    import fugue_tpu.obs.tracer as tmod

    set_verb_observer(None)  # shed any observer a prior test's engine left
    e = JaxExecutionEngine({FUGUE_TPU_CONF_TUNING_ROOFLINES: False})
    try:
        assert tmod._VERB_OBSERVER is None  # opted out: no hook at all
    finally:
        e.stop_engine()
        set_verb_observer(None)
    pdf = pd.DataFrame(
        {
            "k": np.arange(50_000) % 64,
            "v": np.random.default_rng(0).random(50_000),
        }
    )
    e = JaxExecutionEngine({"fugue.tpu.tuning.path": str(tmp_path / "t.json")})
    try:
        df = e.to_df(pdf)
        e.distinct(df).as_pandas()
        roof = e.tuner.roofline.snapshot()
        assert any(k.startswith("engine.distinct|") for k in roof), roof
        for entry in roof.values():
            assert entry["obs"] >= 1 and entry["best_bytes_s"] > 0
        rpt = e.report()
        assert "verb rooflines" in rpt and "engine.distinct" in rpt
    finally:
        e.stop_engine()
        set_verb_observer(None)


def test_observer_never_fires_with_tracing_disabled(tmp_path):
    tr = get_tracer()
    tr.disable()
    calls = []
    set_verb_observer(lambda name, wall, out: calls.append(name))
    try:
        e = JaxExecutionEngine({})
        try:
            e.to_df(pd.DataFrame({"a": [1, 2, 3]})).as_pandas()
        finally:
            e.stop_engine()
        assert calls == []  # disabled tracing: the hook is never consulted
    finally:
        set_verb_observer(None)


def test_concurrent_observe_is_consistent(tmp_path):
    rec = RooflineRecorder(TunedStore(str(tmp_path / "t.json")))

    def work():
        for _ in range(200):
            rec.observe("engine.take", "int", 1, wall_s=0.01, rows=10, nbytes=80)

    ts = [threading.Thread(target=work) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    (entry,) = rec.snapshot().values()
    assert entry["obs"] == 800 and entry["rows"] == 8000
