"""Cost-based adaptive execution (ISSUE 12, docs/tuning.md).

Covers the _tuned.json lifecycle (atomic publish under a two-process
race, corrupt/truncated file -> defaults with ONE warning, stale
fingerprint eviction), the conf kill-switch restoring static behavior
bit-identically, the bounded-multiplicative adjustment policy, warm-run
convergence + restart reload, per-stream pipeline stats, and the
decision surfaces (explain(), engine.stats()["tuning"], /metrics).
"""

import json
import logging
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_CACHE_ENABLED,
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
    FUGUE_TPU_CONF_STREAM_PREFETCH_DEPTH,
    FUGUE_TPU_CONF_TUNING_ENABLED,
    FUGUE_TPU_CONF_TUNING_MAX_ENTRIES,
    FUGUE_TPU_CONF_TUNING_PATH,
)
from fugue_tpu.dataframe import ArrowDataFrame, LocalDataFrameIterableDataFrame
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.tuning import (
    TunedStore,
    adjust_buckets,
    adjust_stream,
    describe_tuning,
)

ROWS = 300_000
CHUNK = 2048
GROUPS = 32


def _table(rows=ROWS, seed=5):
    rng = np.random.default_rng(seed)
    return pa.Table.from_pandas(
        pd.DataFrame(
            {"k": rng.integers(0, GROUPS, rows), "v": rng.random(rows)}
        ),
        preserve_index=False,
    )


_TBL = _table()


def _stream(tbl=_TBL, chunk=CHUNK):
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(chunk, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, chunk)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


def _engine(path, **extra):
    conf = {
        FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: CHUNK,
        FUGUE_TPU_CONF_CACHE_ENABLED: False,
        FUGUE_TPU_CONF_TUNING_PATH: str(path),
    }
    conf.update(extra)
    return JaxExecutionEngine(conf)


def _run_agg(eng, wf_conf=None):
    dag = FugueWorkflow(wf_conf)
    (
        dag.df(_stream())
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    res = (
        dag.yields["r"].result.as_pandas().sort_values("k").reset_index(drop=True)
    )
    return res, dag


# ---------------------------------------------------------------------------
# adjustment policy (pure functions)
# ---------------------------------------------------------------------------


def test_adjust_stream_grows_chunk_when_over_band():
    adj = adjust_stream(
        2048,
        0,
        {"chunks_prefetched": 128, "wall_s": 1.0, "rows": 262144, "bytes": 0},
        1 << 30,
    )
    assert adj is not None and not adj["converged"]
    # bounded multiplicative: at most 4x per generation
    assert 2048 < adj["chunk_rows"] <= 2048 * 4
    assert "chunk_rows 2048 ->" in adj["evidence"]


def test_adjust_stream_no_signal_on_tiny_runs():
    # fast runs and single chunks carry no signal -- tiny test workloads
    # must never perturb the store
    assert adjust_stream(2048, 0, {"chunks_prefetched": 128, "wall_s": 0.01}, 0) is None
    assert adjust_stream(2048, 0, {"chunks_prefetched": 0, "wall_s": 9.9}, 0) is None


def test_adjust_stream_in_band_converges():
    adj = adjust_stream(65536, 0, {"chunks_prefetched": 8, "wall_s": 1.0}, 0)
    assert adj is not None and adj["converged"]
    assert adj["chunk_rows"] == 65536


def test_adjust_stream_depth_responds_to_waits():
    # consumer starved -> deepen
    adj = adjust_stream(
        65536,
        2,
        {
            "chunks_prefetched": 12,
            "wall_s": 2.0,
            "producer_wait_s": 0.0,
            "consumer_wait_s": 1.0,
        },
        0,
    )
    assert adj["prefetch_depth"] == 4
    # producer starved -> shallower (floor 2)
    adj = adjust_stream(
        65536,
        8,
        {
            "chunks_prefetched": 12,
            "wall_s": 2.0,
            "producer_wait_s": 1.0,
            "consumer_wait_s": 0.0,
        },
        0,
    )
    assert adj["prefetch_depth"] == 4
    # serial path (depth 0): no wait data, depth stays put
    adj = adjust_stream(
        65536,
        0,
        {"chunks_prefetched": 12, "wall_s": 2.0, "consumer_wait_s": 1.0},
        0,
    )
    assert adj["prefetch_depth"] == 0


def test_adjust_stream_byte_cap_bounds_chunk():
    # 1 KiB/row, budget 8 MiB -> chunk capped at budget/8/bpr = 1024 rows
    # floor CHUNK_MIN_ROWS applies
    adj = adjust_stream(
        4096,
        0,
        {
            "chunks_prefetched": 256,
            "wall_s": 3.0,
            "rows": 1 << 20,
            "bytes": 1 << 30,
        },
        8 << 20,
    )
    assert adj["chunk_rows"] == 4096  # capped back to the floor == current


def test_adjust_buckets_shrinks_when_peak_far_under_budget():
    adj = adjust_buckets(
        256, {"peak_device_bytes": 1 << 20, "wall_s": 2.0}, 256 << 20
    )
    assert adj is not None and not adj["converged"]
    assert adj["buckets"] == 32  # bounded by MAX_BUCKET_FACTOR=8
    # over budget -> more buckets, regardless of wall
    adj = adjust_buckets(
        8, {"peak_device_bytes": 64 << 20, "wall_s": 0.05}, 16 << 20
    )
    assert adj["buckets"] > 8
    # near target -> converged
    adj = adjust_buckets(
        64, {"peak_device_bytes": 100 << 20, "wall_s": 2.0}, 256 << 20
    )
    assert adj["converged"] and adj["buckets"] == 64
    # small bucket counts are noise -- never adjusted
    assert (
        adjust_buckets(8, {"peak_device_bytes": 1 << 20, "wall_s": 2.0}, 256 << 20)
        is None
    )


# ---------------------------------------------------------------------------
# store lifecycle
# ---------------------------------------------------------------------------


def test_store_publish_atomic_and_preserves_foreign_keys(tmp_path):
    path = str(tmp_path / "_tuned.json")
    with open(path, "w") as f:
        json.dump({"dense_sum": {"cpu": "onehot"}}, f)
    store = TunedStore(path)
    assert store.publish("fp1", lambda e: dict(e, streams={"s": {"chunk_rows": 1}}))
    doc = json.load(open(path))
    assert doc["dense_sum"] == {"cpu": "onehot"}  # the A/B winner survives
    assert doc["tuning"]["plans"]["fp1"]["streams"]["s"]["chunk_rows"] == 1
    assert doc["tuning"]["plans"]["fp1"]["gen"] == 1
    # no temp litter
    assert [f for f in os.listdir(tmp_path) if f != "_tuned.json"] == []


def test_store_corrupt_file_defaults_with_one_warning(tmp_path, caplog):
    path = str(tmp_path / "_tuned.json")
    with open(path, "w") as f:
        f.write('{"tuning": {"plans": {"fp1"')  # truncated mid-write
    with caplog.at_level(logging.WARNING, logger="fugue_tpu.tuning"):
        s1 = TunedStore(path)
        assert s1.plan_entry("fp1") is None  # defaults, not a crash
        assert s1.plans() == {}
        s2 = TunedStore(path)  # a second store over the same path
        assert s2.plan_entry("fp1") is None
    warns = [r for r in caplog.records if "corrupt" in r.getMessage()]
    assert len(warns) == 1  # ONE warning per path per process
    # learning still works memory-side and repairs the file on publish
    assert s1.publish("fp2", lambda e: dict(e, streams={"s": {"chunk_rows": 2}}))
    assert json.load(open(path))["tuning"]["plans"]["fp2"]


def test_store_stale_fingerprint_eviction(tmp_path):
    path = str(tmp_path / "_tuned.json")
    store = TunedStore(path, max_entries=3)
    import time as _t

    for i in range(5):
        assert store.publish(
            f"fp{i}", lambda e: dict(e, streams={"s": {"chunk_rows": 1}})
        )
        _t.sleep(0.01)  # distinct last-used timestamps
    plans = json.load(open(path))["tuning"]["plans"]
    assert sorted(plans) == ["fp2", "fp3", "fp4"]  # LRU evicted fp0, fp1
    assert store.count() == 3


def _race_worker(args):
    path, wid = args
    from fugue_tpu.tuning import TunedStore

    store = TunedStore(path)
    for i in range(25):
        store.publish(
            f"fp_{wid}",
            lambda e: dict(e, streams={"s": {"chunk_rows": i + 1}}),
        )
        # concurrent reads must always see a complete document
        store.plans()
    return store.plan_entry(f"fp_{wid}")["streams"]["s"]["chunk_rows"]


def test_store_two_process_publish_race(tmp_path):
    """Two processes hammering publishes on one path: every intermediate
    read parses (temp-write+rename means no torn file is ever visible),
    and the final document is valid with well-formed entries."""
    import multiprocessing as mp

    path = str(tmp_path / "_tuned.json")
    ctx = mp.get_context("fork")
    with ctx.Pool(2) as pool:
        outs = pool.map(_race_worker, [(path, 0), (path, 1)])
    assert outs == [25, 25]
    doc = json.load(open(path))  # parses -- never torn
    plans = doc["tuning"]["plans"]
    assert set(plans) <= {"fp_0", "fp_1"} and len(plans) >= 1
    # the documented race contract (store.py): the LAST writer's own
    # entry is its final value; the other entry may lose at most its
    # newest few publishes to last-writer-wins — never its integrity
    assert any(e["streams"]["s"]["chunk_rows"] == 25 for e in plans.values())
    for e in plans.values():
        assert 1 <= e["streams"]["s"]["chunk_rows"] <= 25


# ---------------------------------------------------------------------------
# end-to-end: learning, convergence, restart, kill-switch
# ---------------------------------------------------------------------------


def test_warm_runs_converge_and_persist(tmp_path):
    path = tmp_path / "_tuned.json"
    eng = _engine(path)
    res0, dag0 = _run_agg(eng)
    fp = dag0.last_plan_fingerprint
    assert fp is not None
    t = eng.stats()["tuning"]
    assert t["decisions"] >= 1 and t["static"] >= 1 and t["observations"] >= 1
    # generation 2: same plan shape (fresh stream object) -> same
    # fingerprint -> adaptive chunk size, bit-identical result
    res1, dag1 = _run_agg(eng)
    assert dag1.last_plan_fingerprint == fp
    pd.testing.assert_frame_equal(res0, res1)
    t = eng.stats()["tuning"]
    assert t["adaptive"] >= 1
    last = [d for d in t["last_decisions"] if d["target"] == "stream"][-1]
    assert last["source"] == "adaptive"
    assert last["value"]["chunk_rows"] > CHUNK  # grew off the mis-conf
    # persisted: the store file holds the plan entry
    doc = json.load(open(path))
    entry = doc["tuning"]["plans"][fp]
    assert entry["streams"]["aggregate"]["chunk_rows"] > CHUNK
    # "restart": a FRESH engine (new tuner) over the same path reloads
    eng2 = _engine(path)
    res2, dag2 = _run_agg(eng2)
    pd.testing.assert_frame_equal(res0, res2)
    t2 = eng2.stats()["tuning"]
    assert t2["adaptive"] >= 1 and t2["loads"] >= 1


def test_kill_switch_restores_static_behavior(tmp_path):
    path = tmp_path / "_tuned.json"
    # learn an adaptive entry first
    eng = _engine(path)
    res_ref, _ = _run_agg(eng)
    _run_agg(eng)
    assert eng.stats()["tuning"]["adaptive"] >= 1
    # engine-level kill-switch: fresh engine, tuning off -- no decisions,
    # no store reads, static chunking, bit-identical result
    eng_off = _engine(path, **{FUGUE_TPU_CONF_TUNING_ENABLED: False})
    res_off, dag_off = _run_agg(eng_off)
    pd.testing.assert_frame_equal(res_ref, res_off)
    t = eng_off.stats()["tuning"]
    assert t["decisions"] == 0 and t["observations"] == 0 and t["loads"] == 0
    # per-workflow kill-switch on a TUNED engine: the workflow compile
    # conf disables tuning for this run only, without touching the
    # shared engine conf (the serve tenant-overlay contract)
    res_wf, _ = _run_agg(eng, wf_conf={FUGUE_TPU_CONF_TUNING_ENABLED: False})
    pd.testing.assert_frame_equal(res_ref, res_wf)
    assert FUGUE_TPU_CONF_TUNING_ENABLED not in eng.conf


def test_disabled_matches_never_enabled_chunking(tmp_path):
    """enabled=false reproduces the pre-tuning engine exactly: same chunk
    count through the stream as an engine that never had a store."""
    from fugue_tpu.jax import streaming as st

    path = tmp_path / "_tuned.json"
    eng = _engine(path)
    _run_agg(eng)
    _run_agg(eng)  # adaptive entry exists now
    st.last_run_stats = {}
    eng_off = _engine(path, **{FUGUE_TPU_CONF_TUNING_ENABLED: False})
    _run_agg(eng_off)
    off_chunks = st.last_run_stats.get("chunks")
    st.last_run_stats = {}
    eng_fresh = _engine(tmp_path / "other.json")
    _run_agg(eng_fresh)
    fresh_chunks = st.last_run_stats.get("chunks")
    assert off_chunks == fresh_chunks  # static chunking, bit-identical


def test_max_entries_conf(tmp_path):
    path = tmp_path / "_tuned.json"
    eng = _engine(path, **{FUGUE_TPU_CONF_TUNING_MAX_ENTRIES: 7})
    assert eng.tuner.store.max_entries == 7


# ---------------------------------------------------------------------------
# surfaces: per-stream stats, explain, engine.stats, /metrics, serve overlay
# ---------------------------------------------------------------------------


def test_per_stream_pipeline_stats(tmp_path):
    eng = _engine(tmp_path / "_tuned.json", **{FUGUE_TPU_CONF_STREAM_PREFETCH_DEPTH: 2})
    _run_agg(eng)
    ps = eng.stats()["pipeline"]
    assert "streams" in ps and len(ps["streams"]) >= 1
    sid, s = next(iter(ps["streams"].items()))
    assert "aggregate" in sid
    for k in (
        "runs",
        "chunks_prefetched",
        "producer_wait_s",
        "consumer_wait_s",
        "overlap_fraction",
    ):
        assert k in s
    assert s["runs"] >= 1 and s["chunks_prefetched"] >= 1


def test_explain_renders_decisions(tmp_path):
    path = tmp_path / "_tuned.json"
    eng = _engine(path)

    def dag():
        d = FugueWorkflow()
        (
            d.df(_stream())
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n"))
            .yield_dataframe_as("r", as_local=True)
        )
        return d

    cold = dag().explain(engine=eng)
    assert "Adaptive tuning" in cold
    assert "static: no observations" in cold
    res, d1 = _run_agg(eng)
    warm = dag().explain(engine=eng)
    assert d1.last_plan_fingerprint in warm
    assert "chunk_rows=" in warm and "obs=" in warm
    # disabled renders the refusal reason
    off = dag().explain(conf={FUGUE_TPU_CONF_TUNING_ENABLED: False}, engine=eng)
    assert "DISABLED (fugue.tpu.tuning.enabled=false)" in off


def test_stats_group_and_reset_contract(tmp_path):
    eng = _engine(tmp_path / "_tuned.json")
    _run_agg(eng)
    _run_agg(eng)
    t = eng.stats()["tuning"]
    assert t["decisions"] >= 2 and t["entries"] >= 1
    eng.reset_stats()
    t = eng.stats()["tuning"]
    assert t["decisions"] == 0 and t["observations"] == 0
    # learned entries are KEPT (the JitCache keep-entries contract)
    assert t["entries"] >= 1


def test_tuning_flattens_onto_metrics(tmp_path):
    from fugue_tpu.obs import validate_prometheus_text
    from fugue_tpu.obs.prom import to_prometheus_text

    eng = _engine(tmp_path / "_tuned.json")
    _run_agg(eng)
    text = to_prometheus_text(engine=eng)
    assert "fugue_tpu_tuning_decisions" in text
    assert "fugue_tpu_tuning_entries" in text
    validate_prometheus_text(text)


def test_tenant_overlay_allows_tuning_keys():
    from fugue_tpu.execution import NativeExecutionEngine
    from fugue_tpu.serve.tenant import tenant_policy

    eng = NativeExecutionEngine(
        {
            "fugue.tpu.serve.tenant.acme.conf.fugue.tpu.tuning.enabled": False,
            "fugue.tpu.serve.tenant.acme.conf.fugue.tpu.cache.enabled": False,
        }
    )
    pol = tenant_policy(eng.conf, "acme")
    # ISSUE 13 lifted the plan.*/tuning.*-only restriction: workflow.run
    # scopes conf per run, so ANY fugue.tpu.* key is a safe overlay now
    assert pol.conf_overlay == {
        "fugue.tpu.tuning.enabled": False,
        "fugue.tpu.cache.enabled": False,
    }
    assert pol.dropped_keys == ()


def test_describe_tuning_without_engine(tmp_path):
    lines = describe_tuning(
        {FUGUE_TPU_CONF_TUNING_PATH: str(tmp_path / "x.json")}, "deadbeef"
    )
    assert any("static: no observations" in ln for ln in lines)
