"""Apply the DataFrame contract suite to every local frame type."""

from typing import Any

from fugue_tpu.dataframe import (
    ArrayDataFrame,
    ArrowDataFrame,
    DataFrame,
    IterableDataFrame,
    LocalDataFrameIterableDataFrame,
    PandasDataFrame,
)
from fugue_tpu_test import DataFrameTests


class TestArrayDataFrame(DataFrameTests.Tests):
    def df(self, data: Any = None, schema: Any = None) -> DataFrame:
        return ArrayDataFrame(data, schema)


class TestArrowDataFrame(DataFrameTests.Tests):
    def df(self, data: Any = None, schema: Any = None) -> DataFrame:
        return ArrowDataFrame(data, schema)


class TestPandasDataFrame(DataFrameTests.Tests):
    def df(self, data: Any = None, schema: Any = None) -> DataFrame:
        return PandasDataFrame(data, schema)


class TestIterableDataFrame(DataFrameTests.Tests):
    def df(self, data: Any = None, schema: Any = None) -> DataFrame:
        return IterableDataFrame(data, schema)


class TestLocalDataFrameIterableDataFrame(DataFrameTests.Tests):
    def df(self, data: Any = None, schema: Any = None) -> DataFrame:
        inner = ArrayDataFrame(data, schema)
        return LocalDataFrameIterableDataFrame(iter([inner]), inner.schema)
