"""Apply engine + workflow contract suites to NativeExecutionEngine."""

from fugue_tpu.execution import ExecutionEngine, NativeExecutionEngine
from fugue_tpu_test import BuiltInTests, ExecutionEngineTests


class TestNativeExecutionEngine(ExecutionEngineTests.Tests):
    def make_engine(self) -> ExecutionEngine:
        return NativeExecutionEngine(dict(test=True))


class TestNativeBuiltIn(BuiltInTests.Tests):
    def make_engine(self) -> ExecutionEngine:
        return NativeExecutionEngine(dict(test=True))
