"""Fault-tolerant multi-host worker tier (ISSUE 14, docs/distributed.md).

The partial-failure matrix: heartbeat fresh vs stale, lease expiry
mid-task, speculative duplicate publish (both publish, one done record,
one artifact), supervisor restart over in-flight leases, remote fragment
fetch + orphaned-output recovery — each proven bit-identical to the
serial (kill-switch) oracle where a job result exists. Plus the
heartbeat adoption in the shared store's claim stealing and the new
``dist.lease`` / ``dist.heartbeat`` fault sites.
"""

import json
import os
import threading
import time

import pandas as pd
import pytest

from fugue_tpu.cache.store import ArtifactStore
from fugue_tpu.dist import (
    DistJobError,
    DistSupervisor,
    DistWorker,
    HeartbeatWriter,
    LeaseBoard,
    holder_alive,
    read_heartbeat,
    spec_fingerprint,
)
from fugue_tpu.resilience import FailureCategory, classify_failure

CONF = {
    "fugue.tpu.dist.heartbeat.interval_s": 0.1,
    "fugue.tpu.dist.heartbeat.stale_after_s": 0.6,
    "fugue.tpu.dist.lease_s": 2.0,
    "fugue.tpu.dist.poll_s": 0.01,
    "fugue.tpu.cache.enabled": False,
    "fugue.tpu.tuning.enabled": False,
}


def _write_inputs(tmp_path, n_left=3, n_right=2):
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    left, right = [], []
    for i in range(n_left):
        p = str(data / f"l{i}.parquet")
        pd.DataFrame(
            {
                "k": [(j * 3 + i) % 7 for j in range(40)],
                "v": [float(j + i * 40) for j in range(40)],
            }
        ).to_parquet(p)
        left.append(p)
    for i in range(n_right):
        p = str(data / f"r{i}.parquet")
        pd.DataFrame(
            {"k": list(range(7)), "w": [float(i * 10 + j) for j in range(7)]}
        ).to_parquet(p)
        right.append(p)
    return left, right


def _map_left(pdf):
    return pdf.assign(v2=pdf["v"] * 2.0)


def _reduce(l, r):
    m = l.merge(r, on="k", how="inner")
    m = m.assign(x=m["v2"] * m["w"])
    return m.groupby("k", as_index=False).agg(s=("x", "sum"), n=("x", "count"))


def _combine(parts):
    pdf = pd.concat(parts, ignore_index=True) if parts else pd.DataFrame()
    return (
        pdf.groupby("k", as_index=False)
        .agg(s=("s", "sum"), n=("n", "sum"))
        .sort_values("k")
        .reset_index(drop=True)
    )


def _serial(board, left, right, **kw):
    sup = DistSupervisor(
        str(board), conf=dict(CONF, **{"fugue.tpu.dist.enabled": False})
    )
    return sup.run_join_job(
        left, right, ["k"], _reduce, combine_fn=_combine, map_left=_map_left, **kw
    )


class _WorkerPool:
    """N in-process workers draining the board on daemon threads."""

    def __init__(self, board, n, conf=None, start_http=False):
        self.stop_file = os.path.join(str(board), "_stop")
        self.workers = [
            DistWorker(
                str(board), f"w{i}", conf=dict(conf or CONF), start_http=start_http
            ).start()
            for i in range(n)
        ]
        self.threads = [
            threading.Thread(
                target=w.serve_forever,
                kwargs={"stop_file": self.stop_file},
                daemon=True,
            )
            for w in self.workers
        ]
        for t in self.threads:
            t.start()

    def close(self):
        with open(self.stop_file, "w") as f:
            f.write("stop")
        for t in self.threads:
            t.join(timeout=10)
        for w in self.workers:
            w.stop()


# ---------------------------------------------------------------------------
# heartbeat liveness
# ---------------------------------------------------------------------------


def test_heartbeat_write_read_fresh_stale(tmp_path):
    hb = HeartbeatWriter(str(tmp_path), "w0", interval_s=0.1)
    assert hb.beat()
    payload = read_heartbeat(str(tmp_path), "w0")
    assert payload["name"] == "w0" and payload["pid"] == os.getpid()
    assert holder_alive("w0", str(tmp_path), stale_after_s=5.0) is True
    time.sleep(0.25)
    assert holder_alive("w0", str(tmp_path), stale_after_s=0.2) is False
    # no beat file / no dir configured = UNKNOWN, the pid-probe fallback
    assert holder_alive("nobody", str(tmp_path)) is None
    assert holder_alive("w0", None) is None
    # torn file reads as absent, never a crash
    with open(os.path.join(str(tmp_path), "torn.hb.json"), "w") as f:
        f.write('{"name": "torn"')
    assert holder_alive("torn", str(tmp_path)) is None


def test_heartbeat_writer_loop_and_orderly_stop(tmp_path):
    hb = HeartbeatWriter(str(tmp_path), "w1", interval_s=0.05).start()
    try:
        first = read_heartbeat(str(tmp_path), "w1")["seq"]
        time.sleep(0.3)
        assert read_heartbeat(str(tmp_path), "w1")["seq"] > first
    finally:
        hb.stop(remove=True)
    # an orderly departure removes the beat: UNKNOWN, not "dead"
    assert read_heartbeat(str(tmp_path), "w1") is None


def test_heartbeat_fault_site_skips_beats(tmp_path, monkeypatch):
    from fugue_tpu.resilience import FaultInjector

    hb = HeartbeatWriter(
        str(tmp_path),
        "w2",
        interval_s=0.05,
        injector=FaultInjector("dist.heartbeat=error@2"),
    )
    assert not hb.beat()  # injected partition: beat skipped
    assert not hb.beat()
    assert hb.beat()  # budget spent: beats resume
    assert hb.skipped == 2


# ---------------------------------------------------------------------------
# leases: expiry / heartbeat / pid-probe stealing matrix
# ---------------------------------------------------------------------------


def test_lease_acquire_renew_release(tmp_path):
    lb = LeaseBoard(str(tmp_path))
    owned, _ = lb.try_acquire("t1", "w0", lease_s=30.0)
    assert owned
    # held fresh by a live same-host pid: not stealable by another owner
    owned2, holder = lb.try_acquire("t1", "w1", lease_s=30.0)
    assert not owned2 and holder["owner"] == "w0"
    assert lb.renew("t1", "w0", 30.0)
    assert not lb.renew("t1", "w1", 30.0)  # non-owner renew is a no-op
    assert lb.release("t1", "w0")
    owned3, _ = lb.try_acquire("t1", "w1", lease_s=30.0)
    assert owned3


def test_lease_expiry_steal(tmp_path):
    lb = LeaseBoard(str(tmp_path))
    assert lb.try_acquire("t1", "w0", lease_s=0.1)[0]
    time.sleep(0.15)
    owned, cur = lb.try_acquire("t1", "w1", lease_s=5.0)
    assert owned and cur["owner"] == "w1"
    # the victim's late release must not drop the thief's lease
    assert not lb.release("t1", "w0")
    assert lb.read("t1")["owner"] == "w1"


def test_lease_heartbeat_liveness_matrix(tmp_path):
    hb_dir = str(tmp_path / "hb")
    lb = LeaseBoard(str(tmp_path / "leases"), hb_dir=hb_dir, hb_stale_s=0.3)
    writer = HeartbeatWriter(hb_dir, "w0", interval_s=0.05)
    # fresh heartbeat + unexpired lease: NOT stealable
    writer.beat()
    assert lb.try_acquire("t1", "w0", lease_s=30.0)[0]
    assert not lb.stealable(lb.read("t1"))
    assert not lb.try_acquire("t1", "w1", lease_s=30.0)[0]
    # stale heartbeat: provably dead — stealable IMMEDIATELY, mid-lease
    time.sleep(0.4)
    assert lb.stealable(lb.read("t1"))
    owned, cur = lb.try_acquire("t1", "w1", lease_s=30.0)
    assert owned and cur["owner"] == "w1"
    # fresh heartbeat never pins an EXPIRED lease (live-but-wedged owner)
    writer2 = HeartbeatWriter(hb_dir, "w1", interval_s=0.05)
    writer2.beat()
    lease = lb.read("t1")
    lease["ts"] = time.time() - 100.0
    with open(lb._lease("t1"), "w") as f:
        json.dump(lease, f)
    assert lb.stealable(lb.read("t1"))


def test_store_claim_steal_uses_heartbeat_liveness(tmp_path):
    """Satellite: fleet claim stealing (cache/store.py) judges a claim
    owner by its heartbeat when a heartbeat dir is configured, so the
    steal works cross-host; the pid probe stays as the fallback."""
    hb_dir = str(tmp_path / "hb")
    os.makedirs(hb_dir)
    store = ArtifactStore(
        str(tmp_path / "store"), cap_bytes=0, hb_dir=hb_dir, hb_stale_s=0.3
    )
    assert store.try_claim("key1", "r0", lease_s=30.0)[0]
    # no heartbeat for r0: UNKNOWN -> pid fallback; our own live pid
    # holds, so another replica cannot steal
    assert not store.try_claim("key1", "r1", lease_s=30.0)[0]
    # a STALE heartbeat is proof of death: stealable mid-lease, even
    # though the recorded pid (ours) is alive — the cross-host semantics
    HeartbeatWriter(hb_dir, "r0", interval_s=0.05).beat()
    time.sleep(0.4)
    owned, cur = store.try_claim("key1", "r1", lease_s=30.0)
    assert owned and cur["owner"] == "r1"
    # a FRESH heartbeat pins the claim for its lease
    HeartbeatWriter(hb_dir, "r1", interval_s=0.05).beat()
    assert not store.try_claim("key1", "r2", lease_s=30.0)[0]


# ---------------------------------------------------------------------------
# jobs: serial oracle, kill-switch, end-to-end bit-identity
# ---------------------------------------------------------------------------


def test_serial_path_matches_direct_pandas(tmp_path):
    left, right = _write_inputs(tmp_path)
    serial = _serial(tmp_path / "board", left, right, buckets=4)
    l = pd.concat([pd.read_parquet(p) for p in left], ignore_index=True)
    l = _map_left(l)
    r = pd.concat([pd.read_parquet(p) for p in right], ignore_index=True)
    m = l.merge(r, on="k", how="inner")
    m = m.assign(x=m["v2"] * m["w"])
    want = (
        m.groupby("k", as_index=False)
        .agg(s=("x", "sum"), n=("x", "count"))
        .sort_values("k")
        .reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(serial, want)


def test_dist_end_to_end_bit_identical_and_audit_zero(tmp_path):
    left, right = _write_inputs(tmp_path)
    board = tmp_path / "board"
    serial = _serial(tmp_path / "oracle", left, right, buckets=4)
    pool = _WorkerPool(board, 2)
    try:
        sup = DistSupervisor(str(board), conf=dict(CONF))
        jid = sup.plan_join_job(
            left, right, ["k"], _reduce, combine_fn=_combine,
            map_left=_map_left, buckets=4,
        )
        got = sup.wait_job(jid, timeout=60)
        assert got.equals(serial)
        audit = sup.audit_job(jid)
        assert audit["rows_lost"] == 0 and audit["rows_double_counted"] == 0
        assert audit["map_done"] == 5 and audit["reduce_done"] == 4
        d = sup.engine.stats()["dist"]
        assert d["jobs"] == 1 and d["map_tasks"] == 5 and d["reduce_tasks"] == 4
        # worker counters shipped home via heartbeats/done records (the
        # exact totals lag by up to one beat — presence is the contract)
        assert d["workers"]
        assert sum(
            s.get("tasks_completed", 0) for s in d["workers"].values()
        ) >= 1
    finally:
        pool.close()


def test_lease_expiry_mid_task_redispatched_worker_lost(tmp_path):
    """A 'worker' grabs a map lease, beats once, and dies (its heartbeat
    goes stale, its lease never renews): a live worker steals the lease,
    the supervisor classifies the owner change WORKER_LOST, and the job
    completes bit-identically."""
    left, right = _write_inputs(tmp_path)
    board = tmp_path / "board"
    serial = _serial(tmp_path / "oracle", left, right, buckets=4)
    sup = DistSupervisor(str(board), conf=dict(CONF))
    jid = sup.plan_join_job(
        left, right, ["k"], _reduce, combine_fn=_combine,
        map_left=_map_left, buckets=4,
    )
    ghost_lease = sup.leases
    tid = f"{jid}-m-left-0000"
    HeartbeatWriter(sup.board.hb_dir, "ghost", interval_s=0.05).beat()
    assert ghost_lease.try_acquire(tid, "ghost", lease_s=30.0)[0]
    time.sleep(0.7)  # the ghost's only beat goes stale
    pool = _WorkerPool(board, 2)
    try:
        got = sup.wait_job(jid, timeout=60)
        assert got.equals(serial)
        # the steal was classified WORKER_LOST at the steal site (stale
        # ghost heartbeat) and shipped home in the thief's counters
        assert sup.engine.stats()["dist"]["redispatch_worker_lost"] >= 1
    finally:
        pool.close()


def test_speculative_duplicate_publish_one_record_one_artifact(tmp_path):
    """Both the owner and the speculative twin execute the same reduce:
    both publish, the artifact dedups by content address, exactly one
    done record survives, the loser counts a speculative loss."""
    left, right = _write_inputs(tmp_path, n_left=1, n_right=1)
    board = tmp_path / "board"
    w0 = DistWorker(str(board), "w0", conf=dict(CONF), start_http=False)
    w1 = DistWorker(str(board), "w1", conf=dict(CONF), start_http=False)
    sup = DistSupervisor(str(board), conf=dict(CONF))
    jid = sup.plan_join_job(
        left, right, ["k"], _reduce, combine_fn=_combine,
        map_left=_map_left, buckets=1,
    )
    # complete the maps so the reduce is runnable
    for tid in sup.board.list_tasks():
        if "-m-" in tid:
            assert w0.run_task(tid)
    rtid = f"{jid}-r-0000"
    sup.board.mark_speculative(rtid)
    # the "slow owner": acquires the primary lease but hasn't finished
    assert w0.leases.try_acquire(rtid, "w0", lease_s=30.0)[0]
    w0.heartbeat.beat()
    # the volunteer twin runs under the speculative lease and WINS
    assert w1.run_task(rtid, speculative=True)
    assert w1.stats.get("speculative_wins") == 1
    # the owner finishes late: publishes the identical artifact, loses
    # the done record, and that's a counted non-event
    w0.leases.release(rtid, "w0")
    assert w0.run_task(rtid)
    assert w0.stats.get("duplicate_publishes") == 1
    done = [
        n for n in os.listdir(sup.board.done_dir) if n.startswith(rtid)
    ]
    assert len(done) == 1
    rec = sup.board.read_done(rtid)
    assert rec["worker"] == "w1" and rec["speculative"] is True
    store = ArtifactStore(sup.board.store_dir, cap_bytes=0)
    objs = [n for n in os.listdir(store.objs) if n == rec["fp"] + ".parquet"]
    assert len(objs) == 1
    got = sup.wait_job(jid, timeout=30)
    serial = _serial(
        tmp_path / "oracle", left, right, buckets=1
    )
    assert got.equals(serial)


def test_supervisor_restart_resumes_inflight_job(tmp_path):
    """All job state lives on the board: a NEW supervisor (the restart)
    picks up an in-flight job by id and completes it — in-flight leases
    keep running under the new watcher."""
    left, right = _write_inputs(tmp_path)
    board = tmp_path / "board"
    serial = _serial(tmp_path / "oracle", left, right, buckets=4)
    sup1 = DistSupervisor(str(board), conf=dict(CONF))
    jid = sup1.plan_join_job(
        left, right, ["k"], _reduce, combine_fn=_combine,
        map_left=_map_left, buckets=4,
    )
    pool = _WorkerPool(board, 2)
    try:
        # wait until SOME work is in flight/done, then "crash" sup1
        deadline = time.monotonic() + 30
        while sup1.board.done_count(sup1.board.list_tasks()) == 0:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        del sup1
        sup2 = DistSupervisor(str(board), conf=dict(CONF))
        got = sup2.wait_job(jid, timeout=60)
        assert got.equals(serial)
        audit = sup2.audit_job(jid)
        assert audit["rows_lost"] == 0 and audit["rows_double_counted"] == 0
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# the network-partitioned exchange: remote fetch + orphan recovery
# ---------------------------------------------------------------------------


def test_remote_fragment_fetch_over_http(tmp_path):
    """fetch=remote forces every foreign fragment over the producer's
    /dist/fetch route — the true multi-host shape — and the result stays
    bit-identical."""
    left, right = _write_inputs(tmp_path)
    board = tmp_path / "board"
    serial = _serial(tmp_path / "oracle", left, right, buckets=4)
    conf = dict(CONF, **{"fugue.tpu.dist.fetch": "remote"})
    producer = DistWorker(str(board), "wp", conf=conf, start_http=True).start()
    consumer = DistWorker(str(board), "wc", conf=conf, start_http=True).start()
    try:
        sup = DistSupervisor(str(board), conf=conf)
        jid = sup.plan_join_job(
            left, right, ["k"], _reduce, combine_fn=_combine,
            map_left=_map_left, buckets=4,
        )
        for tid in sup.board.list_tasks():
            if "-m-" in tid:
                assert producer.run_task(tid)
        for tid in sup.board.list_tasks():
            if "-r-" in tid:
                assert consumer.run_task(tid)
        got = sup.wait_job(jid, timeout=30)
        assert got.equals(serial)
        assert consumer.stats.get("fragments_remote") > 0
        assert consumer.stats.get("fragments_local") == 0
        audit = sup.audit_job(jid)
        assert audit["rows_lost"] == 0 and audit["rows_double_counted"] == 0
    finally:
        producer.stop()
        consumer.stop()


def test_orphaned_fragment_recovery_dead_producer(tmp_path):
    """The producer dies AFTER completing its maps but before consumers
    fetched: the consumer proves the fragments unreachable, invalidates
    the producer's done records (orphan recovery — the remote-fetch
    extension of PR 8 torn-bucket recovery), re-runs the maps itself and
    the job still completes bit-identically. A refused connection is
    proof the producer process is GONE, so the recorded category is
    WORKER_LOST (not TRANSIENT backoff against a dead peer)."""
    from fugue_tpu.resilience import WorkerLostError

    left, right = _write_inputs(tmp_path, n_left=2, n_right=1)
    board = tmp_path / "board"
    serial = _serial(tmp_path / "oracle", left, right, buckets=2)
    conf = dict(CONF, **{"fugue.tpu.dist.fetch": "remote"})
    producer = DistWorker(str(board), "wp", conf=conf, start_http=True).start()
    consumer = DistWorker(str(board), "wc", conf=conf, start_http=True)
    consumer.start()
    sup = DistSupervisor(str(board), conf=conf)
    jid = sup.plan_join_job(
        left, right, ["k"], _reduce, combine_fn=_combine,
        map_left=_map_left, buckets=2,
    )
    map_tids = [t for t in sup.board.list_tasks() if "-m-" in t]
    for tid in map_tids:
        assert producer.run_task(tid)
    # kill the producer the hard way: HTTP gone, heartbeat goes stale
    producer._rpc.stop_server()
    producer.heartbeat.stop(remove=False)
    time.sleep(0.7)
    rtid = f"{jid}-r-0000"
    with pytest.raises(WorkerLostError) as ei:
        consumer._execute_reduce(consumer.board.read_task(rtid))
    assert classify_failure(ei.value) is FailureCategory.WORKER_LOST
    assert consumer.stats.get("orphaned_outputs_recovered") >= 1
    # at least one producer done record was invalidated for re-dispatch
    assert any(sup.board.read_done(t) is None for t in map_tids)
    # the consumer (a live worker) re-runs the orphaned maps + reduces
    pool_stop = os.path.join(str(board), "_stop")
    t = threading.Thread(
        target=consumer.serve_forever, kwargs={"stop_file": pool_stop}, daemon=True
    )
    t.start()
    try:
        got = sup.wait_job(jid, timeout=60)
        assert got.equals(serial)
        audit = sup.audit_job(jid)
        assert audit["rows_lost"] == 0 and audit["rows_double_counted"] == 0
    finally:
        with open(pool_stop, "w") as f:
            f.write("stop")
        t.join(timeout=10)
        consumer.stop()
        producer.stop()


# ---------------------------------------------------------------------------
# failure taxonomy + fault sites
# ---------------------------------------------------------------------------


def test_dist_lease_fault_site_transient_retry(tmp_path):
    left, right = _write_inputs(tmp_path, n_left=1, n_right=1)
    board = tmp_path / "board"
    conf = dict(CONF, **{"fugue.tpu.fault.plan": "dist.lease=error@1"})
    w = DistWorker(str(board), "w0", conf=conf, start_http=False)
    sup = DistSupervisor(str(board), conf=dict(CONF))
    jid = sup.plan_join_job(
        left, right, ["k"], _reduce, combine_fn=_combine,
        map_left=_map_left, buckets=1,
    )
    tid = f"{jid}-m-left-0000"
    # first attempt eats the injected fault: failure recorded TRANSIENT,
    # lease released on unwind
    assert not w.run_task(tid)
    fails = sup.board.failures(tid)
    assert len(fails) == 1 and fails[0]["category"] == "transient"
    assert sup.leases.read(tid) is None
    # the budget is spent: the next scan retries and succeeds
    assert w.poll_once()
    assert sup.board.read_done(tid) is not None


def test_poison_task_aborts_job_with_report(tmp_path):
    left, right = _write_inputs(tmp_path, n_left=1, n_right=1)
    board = tmp_path / "board"

    def bad_map(pdf):
        raise ValueError("deterministically broken")

    pool = _WorkerPool(board, 1)
    try:
        sup = DistSupervisor(str(board), conf=dict(CONF))
        with pytest.raises(DistJobError) as ei:
            sup.run_join_job(
                left, right, ["k"], _reduce, combine_fn=_combine,
                map_left=bad_map, buckets=1, timeout=30,
            )
        assert "poison" in str(ei.value)
        assert any("ValueError" in "".join(v) for v in ei.value.report.values())
        # workers stop touching a poisoned task (no retry storm)
        time.sleep(0.2)
        n = len(
            [
                f
                for f in os.listdir(sup.board.fail_dir)
                if f.endswith(".json")
            ]
        )
        time.sleep(0.3)
        n2 = len(
            [
                f
                for f in os.listdir(sup.board.fail_dir)
                if f.endswith(".json")
            ]
        )
        assert n2 == n
    finally:
        pool.close()


def test_worker_spans_ship_home_with_worker_label(tmp_path):
    """With tracing on, each task's dist.task span (worker attr) rides
    its done record and the supervisor ingests it under dist.job — the
    fork-worker ship-home protocol, across real process boundaries."""
    from fugue_tpu.obs import get_tracer

    left, right = _write_inputs(tmp_path, n_left=1, n_right=1)
    board = tmp_path / "board"
    tracer = get_tracer()
    tracer.enable()
    try:
        tracer.clear()
        pool = _WorkerPool(board, 1)
        try:
            sup = DistSupervisor(str(board), conf=dict(CONF))
            sup.run_join_job(
                left, right, ["k"], _reduce, combine_fn=_combine,
                map_left=_map_left, buckets=2, timeout=60,
            )
        finally:
            pool.close()
        recs = tracer.records()
        jobs = [r for r in recs if r["name"] == "dist.job"]
        tasks = [r for r in recs if r["name"] == "dist.task"]
        assert len(jobs) == 1
        # 2 maps + 2 reduces, each labeled with the executing worker
        assert len(tasks) == 4
        assert all(t["args"]["worker"] == "w0" for t in tasks)
        assert {t["args"]["kind"] for t in tasks} == {"map", "reduce"}
    finally:
        tracer.disable()
        tracer.clear()


def test_engine_server_adopts_heartbeat_liveness(tmp_path):
    """Satellite: an EngineServer with fugue.tpu.dist.heartbeat.dir set
    beats under its replica_id (what fleet claim stealing reads), and an
    orderly stop removes the beat."""
    from fugue_tpu.execution import NativeExecutionEngine
    from fugue_tpu.serve import EngineServer

    hb_dir = str(tmp_path / "hb")
    eng = NativeExecutionEngine(
        {
            "fugue.tpu.dist.heartbeat.dir": hb_dir,
            "fugue.tpu.dist.heartbeat.interval_s": 0.05,
            "fugue.tpu.serve.replica_id": "rX",
            "fugue.tpu.cache.enabled": False,
            "fugue.tpu.tuning.enabled": False,
        }
    )
    srv = EngineServer(eng).start()
    try:
        assert holder_alive("rX", hb_dir, stale_after_s=5.0) is True
        assert srv.stats()["heartbeat_enabled"] is True
    finally:
        srv.stop()
    assert read_heartbeat(hb_dir, "rX") is None


def test_spec_fingerprint_deterministic():
    a = spec_fingerprint("j", "reduce", 3, ["m1", "m2"])
    b = spec_fingerprint("j", "reduce", 3, ["m1", "m2"])
    c = spec_fingerprint("j", "reduce", 4, ["m1", "m2"])
    assert a == b and a != c


def test_kill_switch_restores_single_process_bit_identically(tmp_path):
    """fugue.tpu.dist.enabled=false routes run_join_job through the
    serial path: no tasks on the board, no workers needed, result
    identical to the distributed one."""
    left, right = _write_inputs(tmp_path)
    board = tmp_path / "board"
    serial_board = tmp_path / "serial_board"
    pool = _WorkerPool(board, 2)
    try:
        sup = DistSupervisor(str(board), conf=dict(CONF))
        dist = sup.run_join_job(
            left, right, ["k"], _reduce, combine_fn=_combine,
            map_left=_map_left, buckets=4, timeout=60,
        )
    finally:
        pool.close()
    off = DistSupervisor(
        str(serial_board), conf=dict(CONF, **{"fugue.tpu.dist.enabled": False})
    )
    serial = off.run_join_job(
        left, right, ["k"], _reduce, combine_fn=_combine,
        map_left=_map_left, buckets=4,
    )
    assert dist.equals(serial)
    assert off.board.list_tasks() == []  # nothing ever hit the board

# ---------------------------------------------------------------------------
# workflow jobs on the board (ISSUE 16, fugue_tpu/plan/distribute.py)
# ---------------------------------------------------------------------------


def _serial_workflow(board, left, right, **kw):
    sup = DistSupervisor(
        str(board), conf=dict(CONF, **{"fugue.tpu.dist.enabled": False})
    )
    return sup.run_workflow_job(
        left, right, ["k"], _reduce, combine_fn=_combine, map_left=_map_left, **kw
    )


def test_workflow_job_bit_identical_and_warm_delta_skip(tmp_path):
    """run_workflow_job executes on the worker tier bit-identically to the
    kill-switch serial path, and a WARM rerun finds every content-addressed
    task already done on the board — zero re-dispatch, all partitions
    delta-skipped."""
    left, right = _write_inputs(tmp_path)
    board = tmp_path / "board"
    serial = _serial_workflow(tmp_path / "oracle", left, right, buckets=4)
    tokens = {"left": "assign v2", "reduce": "join+agg"}
    pool = _WorkerPool(board, 2)
    try:
        sup = DistSupervisor(str(board), conf=dict(CONF))
        got = sup.run_workflow_job(
            left, right, ["k"], _reduce, combine_fn=_combine,
            map_left=_map_left, buckets=4, tokens=tokens, timeout=60,
        )
        assert got.equals(serial)
        d1 = sup.stats.as_dict()
        assert d1["workflow_jobs"] == 1
        assert d1["workflow_tasks_dispatched"] == 9  # 5 maps + 4 reduces
        assert d1["workflow_partitions_delta_skipped"] == 0
        # warm rerun: same fragment logic + same source files -> same
        # content-addressed tids -> every done record reused
        got2 = sup.run_workflow_job(
            left, right, ["k"], _reduce, combine_fn=_combine,
            map_left=_map_left, buckets=4, tokens=tokens, timeout=60,
        )
        assert got2.equals(serial)
        d2 = sup.stats.as_dict()
        assert d2["workflow_partitions_delta_skipped"] == 9
        assert d2["workflow_tasks_dispatched"] == 9  # unchanged: 0 new
    finally:
        pool.close()


def test_workflow_job_supervisor_restart_mid_reduce_with_waiter(tmp_path):
    """Crash the supervisor AFTER the map wave completes (mid-REDUCE) and
    attach a NEW supervisor as the waiter: the job completes from board
    state alone, bit-identical, audit 0 lost / 0 double-counted."""
    left, right = _write_inputs(tmp_path)
    board = tmp_path / "board"
    serial = _serial_workflow(tmp_path / "oracle", left, right, buckets=4)
    sup1 = DistSupervisor(str(board), conf=dict(CONF))
    jid, tids = sup1.plan_workflow_job(
        left, right, ["k"], _reduce, combine_fn=_combine,
        map_left=_map_left, buckets=4,
    )
    map_tids = [t for t in tids if t.startswith("wfm-")]
    pool = _WorkerPool(board, 2)
    try:
        # wait until every map is done (reduces now in flight), then crash
        deadline = time.monotonic() + 30
        while sup1.board.done_count(map_tids) < len(map_tids):
            assert time.monotonic() < deadline
            time.sleep(0.01)
        del sup1
        sup2 = DistSupervisor(str(board), conf=dict(CONF))
        got = sup2.wait_job(jid, timeout=60)
        assert got.equals(serial)
        audit = sup2.audit_job(jid)
        assert audit["rows_lost"] == 0 and audit["rows_double_counted"] == 0
    finally:
        pool.close()


def test_dist_board_fault_site_transient_retry(tmp_path):
    """dist.board fires between the done-record write window and publish:
    the task's outputs are already durable, the failure is recorded
    TRANSIENT, and the retry republishes — one done record, no data loss."""
    left, right = _write_inputs(tmp_path, n_left=1, n_right=1)
    board = tmp_path / "board"
    conf = dict(CONF, **{"fugue.tpu.fault.plan": "dist.board=error@1"})
    w = DistWorker(str(board), "w0", conf=conf, start_http=False)
    sup = DistSupervisor(str(board), conf=dict(CONF))
    jid, tids = sup.plan_workflow_job(
        left, right, ["k"], _reduce, combine_fn=_combine,
        map_left=_map_left, buckets=1,
    )
    tid = [t for t in tids if t.startswith("wfm-")][0]
    # first attempt eats the injected fault AFTER executing (outputs
    # durable) but BEFORE publish: no done record yet, failure TRANSIENT
    assert not w.run_task(tid)
    assert sup.board.read_done(tid) is None
    fails = sup.board.failures(tid)
    assert len(fails) == 1 and fails[0]["category"] == "transient"
    assert sup.leases.read(tid) is None  # lease released on unwind
    # budget spent: subsequent scans retry and re-publish, ONE done
    # record. The board scan is tid-sorted and the job has a second map
    # task whose content-addressed id may sort first — drain scans until
    # THIS task's retry lands instead of assuming one scan suffices.
    for _ in range(len(tids) + 1):
        if sup.board.read_done(tid) is not None:
            break
        assert w.poll_once()
    assert sup.board.read_done(tid) is not None
    done = [n for n in os.listdir(sup.board.done_dir) if n.startswith(tid)]
    assert len(done) == 1
