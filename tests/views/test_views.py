"""Continuous views (ISSUE 20, docs/views.md).

Covers the full lifecycle on one and two replicas: multi-generation
append loops bit-identical to a cold full run at EVERY generation, the
delta-refusal degradation ladder (a mutated historical partition forces
a full recompute — correct result, reason recorded — never silent
staleness), WAL-journaled registration replay across a crash, the
per-view watch-lease steal, unregister semantics, the freshness-SLO
priority boost observable in the admission order, typed-event/counter
parity (the timeline CLI reconstructs a view's history from the log
alone), the fleet LRU pinning of each view's latest generation, and the
``fugue.tpu.views.enabled`` kill-switch (default OFF: no service, no
maintainer thread, no ``views`` stats group, no view.* events).

Determinism idiom: ``fugue.tpu.views.poll_s`` is set huge so the
maintainer thread parks after its initial (no-op) tick, and tests drive
``maintainer.tick_once()`` synchronously.
"""

import os
import threading
import time

import pandas as pd
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_CACHE_DIR,
    FUGUE_TPU_CONF_EVENTS_DIR,
    FUGUE_TPU_CONF_EVENTS_ENABLED,
    FUGUE_TPU_CONF_FAULT_PLAN,
    FUGUE_TPU_CONF_SERVE_JOURNAL_DIR,
    FUGUE_TPU_CONF_SERVE_REPLICA_ID,
    FUGUE_TPU_CONF_VIEWS_ENABLED,
    FUGUE_TPU_CONF_VIEWS_LEASE_S,
    FUGUE_TPU_CONF_VIEWS_POLL_S,
)
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.resilience import InjectedFaultError
from fugue_tpu.serve import EngineServer, parse_view_result_name, view_result_key


def _write_part(src: str, i: int, rows: int = 8, scale: float = 1.0) -> None:
    pd.DataFrame(
        {
            "k": [i % 4] * rows,
            "v": [float(i * 10 + j) * scale for j in range(rows)],
        }
    ).to_parquet(os.path.join(src, f"part-{i:05d}.parquet"))


def _factory(src: str):
    def build() -> FugueWorkflow:
        dag = FugueWorkflow()
        (
            dag.load(src, fmt="parquet")
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )
        return dag

    return build


def _oracle(src: str) -> pd.DataFrame:
    """Cold, cache-off full run over the source as it is RIGHT NOW."""
    dag = _factory(src)()
    dag.run(NativeExecutionEngine({"fugue.tpu.cache.enabled": False}))
    return (
        dag.yields["r"].result.as_pandas().sort_values("k").reset_index(drop=True)
    )


def _frames_of(res: dict) -> pd.DataFrame:
    return res["frames"]["r"].sort_values("k").reset_index(drop=True)


def _conf(store, jdir, rid, **extra):
    conf = {
        FUGUE_TPU_CONF_CACHE_DIR: str(store),
        FUGUE_TPU_CONF_SERVE_JOURNAL_DIR: str(jdir),
        FUGUE_TPU_CONF_SERVE_REPLICA_ID: rid,
        FUGUE_TPU_CONF_VIEWS_ENABLED: True,
        # park the loop after its initial (spec-less) tick; tests drive
        # tick_once() synchronously for determinism
        FUGUE_TPU_CONF_VIEWS_POLL_S: 3600.0,
        "fugue.tpu.tuning.enabled": False,
    }
    conf.update(extra)
    return conf


@pytest.fixture()
def src(tmp_path):
    d = str(tmp_path / "src")
    os.makedirs(d)
    for i in range(2):
        _write_part(d, i)
    return d


def _server(tmp_path, rid="A", **extra):
    eng = NativeExecutionEngine(
        _conf(tmp_path / "store", tmp_path / "journal", rid, **extra)
    )
    return EngineServer(eng).start()


def test_multi_generation_append_bit_identical(tmp_path, src):
    srv = _server(tmp_path)
    try:
        vs = srv.views
        m = vs.maintainer
        vs.register("agg", _factory(src), src, fmt="parquet", tenant="t1")
        m.tick_once()
        res = vs.result("agg")
        assert res is not None and res["generation"] == 1
        assert res["mode"] == "full"
        assert _frames_of(res).equals(_oracle(src))
        for i in range(2, 5):
            _write_part(src, i)
            m.tick_once()
            res = vs.result("agg")
            assert res["generation"] == i, res
            assert res["mode"] == "delta"
            assert res["staleness_s"] >= 0.0
            # bit-identical to a cold cache-off run at EVERY generation
            assert _frames_of(res).equals(_oracle(src))
        st = srv.engine.stats()["views"]
        assert st["generations_published"] == 4
        assert st["delta_refusals"] == 0
        # steady-state delta skipped everything but the appended file
        assert st["steady_partitions_fresh"] == 3  # one per append tick
        assert st["steady_partitions_total"] == 3 + 4 + 5
        # describe carries the staleness metadata any replica can serve
        d = vs.describe("agg")
        assert d["generation"] == 4 and d["partitions"] == 5
        assert d["staleness_s"] >= 0.0 and d["maintainer"] == "A"
    finally:
        srv.stop()


def test_unchanged_source_publishes_nothing(tmp_path, src):
    srv = _server(tmp_path)
    try:
        vs = srv.views
        vs.register("agg", _factory(src), src, fmt="parquet")
        vs.maintainer.tick_once()
        vs.maintainer.tick_once()
        vs.maintainer.tick_once()
        st = vs.stats.as_dict()
        assert st["refreshes"] == 1 and st["generations_published"] == 1
    finally:
        srv.stop()


def test_delta_refusal_degrades_to_full_recompute(tmp_path, src):
    """A mutated HISTORICAL partition is a delta refusal at steady state:
    the generation is rebuilt from scratch (correct result, reason
    recorded in the head and counted in stats) — never served stale."""
    srv = _server(tmp_path)
    try:
        vs = srv.views
        m = vs.maintainer
        vs.register("agg", _factory(src), src, fmt="parquet")
        m.tick_once()
        _write_part(src, 2)
        m.tick_once()
        assert vs.result("agg")["mode"] == "delta"
        # rewrite partition 0 in place with DIFFERENT content
        _write_part(src, 0, rows=16, scale=3.0)
        m.tick_once()
        res = vs.result("agg")
        assert res["generation"] == 3 and res["mode"] == "full"
        assert _frames_of(res).equals(_oracle(src))
        head = vs.registry.head("agg")
        assert "rewrite" in (head.get("reason") or "")
        st = srv.engine.stats()["views"]
        assert st["delta_refusals"] == 1 and st["full_recomputes"] == 1
    finally:
        srv.stop()


def test_registration_replays_from_wal_after_crash(tmp_path, src):
    """The register crash window: the WAL record lands, then the replica
    dies before the spec publishes. A restarted replica's replay closes
    the window — the view exists and is maintained as if the crash never
    happened."""
    srv = _server(
        tmp_path, **{FUGUE_TPU_CONF_FAULT_PLAN: "view.register=error@1"}
    )
    try:
        with pytest.raises(InjectedFaultError):
            srv.views.register("agg", _factory(src), src, fmt="parquet")
        assert srv.views.registry.get("agg") is None  # spec never published
    finally:
        srv.stop()
    # same journal dir + replica id, no fault plan: the restart
    srv2 = _server(tmp_path)
    try:
        vs = srv2.views
        spec = vs.registry.get("agg")
        assert spec is not None and spec.tenant == "default"
        vs.maintainer.tick_once()
        res = vs.result("agg")
        assert res["generation"] == 1
        assert _frames_of(res).equals(_oracle(src))
    finally:
        srv2.stop()


def test_lease_steal_moves_maintenance_to_survivor(tmp_path, src):
    """Two replicas over one store: A maintains, wedges holding the
    lease; B cannot advance the view until the lease expires, then
    steals it and publishes the next generation."""
    lease = {FUGUE_TPU_CONF_VIEWS_LEASE_S: 0.5}
    a = _server(tmp_path, rid="A", **lease)
    b = _server(tmp_path, rid="B", **lease)
    try:
        a.views.register("agg", _factory(src), src, fmt="parquet")
        a.views.maintainer.tick_once()
        assert a.views.result("agg")["generation"] == 1
        assert a.views.maintainer.holder("agg") == "A"
        # A wedges WITHOUT releasing (a SIGKILL's in-process analogue)
        a.views.maintainer.halt_for_test()
        _write_part(src, 2)
        # B serves the view it does not maintain, but cannot advance it
        # while A's lease is live
        assert b.views.result("agg")["generation"] == 1
        b.views.maintainer.tick_once()
        assert b.views.result("agg")["generation"] == 1
        time.sleep(0.7)  # A's lease expires
        b.views.maintainer.tick_once()
        res = b.views.result("agg")
        assert res["generation"] == 2 and _frames_of(res).equals(_oracle(src))
        assert b.views.maintainer.holder("agg") == "B"
        st = b.engine.stats()["views"]
        assert st["lease_steals"] == 1 and st["lease_acquires"] == 0
    finally:
        a.stop()
        b.stop()


def test_unregister_stops_maintenance_and_releases_everything(tmp_path, src):
    srv = _server(tmp_path)
    try:
        vs = srv.views
        m = vs.maintainer
        vs.register("agg", _factory(src), src, fmt="parquet")
        m.tick_once()
        key = view_result_key("agg", 1)
        assert vs._fleet.load_result(key) is not None
        assert vs.unregister("agg") is True
        assert vs.registry.get("agg") is None
        assert vs.list() == [] and vs.result("agg") is None
        # published generations are retired with the view
        assert vs._fleet.load_result(key) is None
        # the next tick drops the lease; the loop has nothing to maintain
        m.tick_once()
        assert m.holder("agg") is None
        assert m.health()["maintaining"] == []
        st = vs.stats.as_dict()
        assert st["unregistered"] == 1
        assert vs.unregister("agg") is False  # idempotent
    finally:
        srv.stop()
    # the tombstone outlives the restart: A's own WAL record must not
    # resurrect the view on replay
    srv2 = _server(tmp_path)
    try:
        assert srv2.views.registry.get("agg") is None
    finally:
        srv2.stop()


def test_reregister_after_unregister_is_a_fresh_view(tmp_path, src):
    srv = _server(tmp_path)
    try:
        vs = srv.views
        vs.register("agg", _factory(src), src, fmt="parquet")
        vs.maintainer.tick_once()
        assert vs.unregister("agg") is True
        vs.register("agg", _factory(src), src, fmt="parquet")
        assert vs.registry.get("agg") is not None
        vs.maintainer.tick_once()
        assert vs.result("agg")["generation"] == 1  # generations restart
    finally:
        srv.stop()
    # replay keeps exactly the second registration
    srv2 = _server(tmp_path)
    try:
        assert srv2.views.registry.get("agg") is not None
    finally:
        srv2.stop()


def test_register_validation_and_caps(tmp_path, src):
    srv = _server(tmp_path, **{"fugue.tpu.views.max": 1})
    try:
        vs = srv.views
        with pytest.raises(ValueError, match="view id"):
            vs.register("bad--id", _factory(src), src)
        with pytest.raises(ValueError, match="factory"):
            vs.register("built", _factory(src)(), src)  # a BUILT dag
        with pytest.raises(ValueError, match="yield"):
            vs.register("noyield", FugueWorkflow, src)
        vs.register("agg", _factory(src), src, fmt="parquet", tenant="t1")
        # idempotent re-register of the identical view is a no-op
        vs.register("agg", _factory(src), src, fmt="parquet", tenant="t1")
        assert len(vs.list()) == 1
        # same id, conflicting source: rejected
        with pytest.raises(ValueError, match="already registered"):
            vs.register("agg", _factory(src), src + "x", fmt="parquet", tenant="t1")
        with pytest.raises(ValueError, match="max"):
            vs.register("two", _factory(src), src)
    finally:
        srv.stop()


def test_slo_boost_observable_in_admission_order(tmp_path, src):
    """A refresh whose lag puts the tenant's freshness SLO at risk is
    boosted: with one worker busy, the boosted refresh and a plain
    submission queue together and the refresh is PICKED first."""
    srv = _server(
        tmp_path,
        **{
            "fugue.tpu.serve.max_concurrent": 1,
            "fugue.tpu.serve.aging_s": 1000.0,
            "fugue.tpu.serve.tenant.slo.freshness_s": 1.0,
            "fugue.tpu.views.refresh_timeout_s": 60.0,
        },
    )
    try:
        vs = srv.views
        m = vs.maintainer
        vs.register("agg", _factory(src), src, fmt="parquet", tenant="slo")
        m.tick_once()
        assert vs.result("agg")["generation"] == 1
        _write_part(src, 2)
        # a change observed long ago: the SLO is already breached
        with m._lock:
            m._pending_since["agg"] = time.time() - 100.0

        marker = str(tmp_path / "blocker.marker")

        def blocker_factory():
            def crawl(df: pd.DataFrame) -> pd.DataFrame:
                with open(marker, "w") as f:
                    f.write("running")
                time.sleep(0.8)
                return df

            dag = FugueWorkflow()
            (
                dag.df(pd.DataFrame({"k": [1], "v": [1.0]}))
                .transform(crawl, schema="*")
                .yield_dataframe_as("r", as_local=True)
            )
            return dag

        blocker = srv.submit(blocker_factory, tenant="other")
        deadline = time.monotonic() + 30
        while not os.path.exists(marker) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert os.path.exists(marker)  # the single worker is now busy
        t = threading.Thread(target=m.tick_once)  # blocks on the refresh
        t.start()
        deadline = time.monotonic() + 30
        refresh_ex = None
        while refresh_ex is None and time.monotonic() < deadline:
            with srv._lock:
                for ex in srv._queue:
                    if ex.tenant == "slo":
                        refresh_ex = ex
            time.sleep(0.01)
        assert refresh_ex is not None
        # the boost is visible before anything runs: default priority 5
        # minus fugue.tpu.views.slo_boost (2)
        assert refresh_ex.priority == srv.default_priority - 2

        def competitor_factory():  # a DIFFERENT plan: no single-flight dedup
            dag = FugueWorkflow()
            (
                dag.df(pd.DataFrame({"k": [2], "v": [4.0]}))
                .partition_by("k")
                .aggregate(ff.sum(col("v")).alias("s"))
                .yield_dataframe_as("r", as_local=True)
            )
            return dag

        comp = srv.submit(competitor_factory, tenant="other")  # priority 5
        t.join(60)
        comp.result(timeout=60)
        assert refresh_ex.started_at < comp._execution.started_at
        res = vs.result("agg")
        assert res["generation"] == 2 and _frames_of(res).equals(_oracle(src))
        head = vs.registry.head("agg")
        assert head["slo_boosted"] is True
        st = srv.engine.stats()["views"]
        assert st["slo_boosts"] >= 1 and st["slo_breaches"] >= 1
    finally:
        srv.stop()


def test_events_counter_parity_and_timeline(tmp_path, src, capsys):
    """Counter-exact parity between the typed view.* events and the
    stats counters, and the timeline CLI reconstructing one view's
    history from the log alone."""
    from fugue_tpu.obs.events import get_event_log, read_events

    d = str(tmp_path / "events")
    log = get_event_log()
    lease = {
        FUGUE_TPU_CONF_EVENTS_ENABLED: True,
        FUGUE_TPU_CONF_EVENTS_DIR: d,
        FUGUE_TPU_CONF_VIEWS_LEASE_S: 0.5,
    }
    try:
        a = _server(tmp_path, rid="A", **lease)
        b = _server(tmp_path, rid="B", **lease)
        try:
            a.views.register("agg", _factory(src), src, fmt="parquet")
            a.views.maintainer.tick_once()
            _write_part(src, 2)
            a.views.maintainer.tick_once()
            a.views.maintainer.halt_for_test()
            _write_part(src, 3)
            time.sleep(0.7)
            b.views.maintainer.tick_once()  # the steal + generation 3
            assert b.views.result("agg")["generation"] == 3
            b.views.unregister("agg")
            sa = a.views.stats.as_dict()
            sb = b.views.stats.as_dict()
        finally:
            a.stop()
            b.stop()
        events = read_events(d)
        by_type: dict = {}
        for e in events:
            if e["type"].startswith("view."):
                by_type.setdefault(e["type"], []).append(e)
        # counter-exact parity, fleet-wide (counters are per-replica)
        assert len(by_type["view.register"]) == sa["registered"] + sb.get(
            "registered", 0
        )
        assert len(by_type["view.lease.acquire"]) == sa["lease_acquires"]
        assert len(by_type["view.lease.steal"]) == sb["lease_steals"]
        assert len(by_type["view.refresh"]) == sa["refreshes"] + sb["refreshes"]
        assert (
            len(by_type["view.publish"])
            == sa["generations_published"] + sb["generations_published"]
        )
        assert len(by_type["view.unregister"]) == sb["unregistered"]
        # zero lost or duplicate generations, from the log alone
        gens = sorted(e["gen"] for e in by_type["view.publish"])
        assert gens == [1, 2, 3]
        steal = by_type["view.lease.steal"][0]
        assert steal["owner"] == "B" and steal["prev_owner"] == "A"
        # the CLI reconstructs the view's history from the log alone
        from tools.fugue_timeline import main as timeline_main

        assert timeline_main([d, "--view", "agg"]) == 0
        out = capsys.readouterr().out
        for needle in (
            "view agg registered",
            "lease",
            "refresh",
            "publish",
            "unregistered",
        ):
            assert needle.split()[0] in out or needle in out
        assert timeline_main([d, "--view", "nosuch"]) == 2
    finally:
        log.configure(d, False)
        log.close()


def test_kill_switch_default_off(tmp_path, src):
    """Views default OFF: no service object, no maintainer thread, no
    ``views`` stats group, no view.* events — the serve surface is
    exactly the pre-views one."""
    from fugue_tpu.obs.events import get_event_log

    eng = NativeExecutionEngine(
        {
            FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "store"),
            FUGUE_TPU_CONF_SERVE_JOURNAL_DIR: str(tmp_path / "journal"),
            FUGUE_TPU_CONF_SERVE_REPLICA_ID: "A",
            "fugue.tpu.tuning.enabled": False,
        }
    )
    srv = EngineServer(eng).start()
    try:
        assert srv.views is None
        assert "views" not in eng.stats()
        assert "views" not in srv.stats()
        assert not any(
            t.name == "fugue-view-maintainer" for t in threading.enumerate()
        )
        emitted_before = get_event_log().as_dict()["emitted"]
        srv.submit(_factory(src)).result(timeout=60)
        assert get_event_log().as_dict()["emitted"] == emitted_before
    finally:
        srv.stop()


def test_views_disabled_without_shared_store(tmp_path, src):
    """views.enabled without a shared store (fleet) degrades to OFF with
    a warning — there is nowhere to publish generations."""
    eng = NativeExecutionEngine(
        {
            FUGUE_TPU_CONF_VIEWS_ENABLED: True,
            "fugue.tpu.cache.enabled": False,
            "fugue.tpu.tuning.enabled": False,
        }
    )
    srv = EngineServer(eng).start()
    try:
        assert srv.views is None
    finally:
        srv.stop()


def test_fleet_lru_pins_latest_generation_per_view(tmp_path):
    """The ISSUE 20 small fix: request-scoped results age out of the
    fleet's mtime-LRU, but each view's LATEST generation is pinned —
    excluded from both the count and the eviction — while superseded
    generations age out like any request result."""
    from fugue_tpu.cache.store import ArtifactStore
    from fugue_tpu.serve.fleet import FleetCoordinator

    store = ArtifactStore(str(tmp_path / "store"), 0)
    fleet = FleetCoordinator(store, "A", max_results=2)
    frames = {"r": (pd.DataFrame({"x": [1]}), "x:long")}
    old = time.time() - 1000
    fleet.publish_result(view_result_key("agg", 1), frames)
    fleet.publish_result(view_result_key("agg", 2), frames)
    for p in (
        fleet._result_path(view_result_key("agg", 1)),
        fleet._result_path(view_result_key("agg", 2)),
    ):
        os.utime(p, (old, old))  # older than every request result
    for i in range(4):
        fleet.publish_result(f"req-{i}", frames)
    # the latest generation survived arbitrarily many request publishes;
    # the superseded one aged out of the LRU first (oldest mtime)
    assert fleet.load_result(view_result_key("agg", 2)) is not None
    assert fleet.load_result(view_result_key("agg", 1)) is None
    names = os.listdir(fleet.results_dir)
    assert sum(1 for n in names if parse_view_result_name(n) is None) == 2


def test_view_result_key_roundtrip():
    assert parse_view_result_name(
        view_result_key("hourly_agg.v2", 7) + ".result.pkl"
    ) == ("hourly_agg.v2", 7)
    assert parse_view_result_name("abcdef.result.pkl") is None
    assert parse_view_result_name("view--x--g0001.weird") is None


def test_watcher_classification(tmp_path):
    """classify_tokens: append vs rewrite vs unchanged, including the
    appendable-format grown-tail rule (csv/json boundary file may grow
    in place and still count as an append)."""
    from fugue_tpu.views.watcher import classify_tokens

    def tok(path, size, mtime):
        return {"path": path, "size": size, "mtime_ns": mtime}

    base = [tok("a", 10, 1), tok("b", 20, 2)]
    assert classify_tokens(base, list(base), "parquet") == ("unchanged", 0)
    grown = base + [tok("c", 5, 3)]
    assert classify_tokens(base, grown, "parquet") == ("append", 1)
    # historical partition mutated: rewrite, full recompute
    mut = [tok("a", 11, 9), tok("b", 20, 2)]
    assert classify_tokens(base, mut, "parquet")[0] == "rewrite"
    # shrunk source: rewrite
    assert classify_tokens(base, base[:1], "parquet")[0] == "rewrite"
    # csv boundary file grown in place: still an append (tail re-read)
    grown_tail = [tok("a", 10, 1), tok("b", 25, 9)]
    assert classify_tokens(base, grown_tail, "csv") == ("append", 1)
    # ...but for parquet that is a mutation: rewrite
    assert classify_tokens(base, grown_tail, "parquet")[0] == "rewrite"
