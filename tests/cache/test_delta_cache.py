"""Partition-level incremental recompute (``fugue_tpu/cache/delta.py``,
docs/cache.md "Incremental recompute") — ISSUE 9.

The checklist:

- **delta parity matrix**: over a GROWN parquet directory, the warm run
  serves cached partitions + recomputes only the new one, bit-identical
  to a cache-off full recompute, across fused-chain / filter /
  dense-aggregate (sum/count/avg/min/max) shapes, on the jax AND native
  engines, optimizer ON and OFF — including NULL values and group keys
  that first appear in the delta;
- **grown single files**: an appended-to csv with an unchanged prefix
  (stored digest) recomputes only the appended rows;
- **the refusal ladder**: changed partition contents, reordered/deleted
  partitions, non-row-local verbs, disabled conf — every refusal
  degrades to PR 5 whole-task semantics with the reason visible in
  ``workflow.explain()``, and results stay correct;
- **store consistency**: ``disk_max_entries`` mtime-LRU eviction keeps
  manifest + artifacts consistent (an evicted partition artifact
  invalidates ITS manifest, not the whole cache);
- **runtime fallback**: a delta recompute that fails mid-run falls back
  in place to a full recompute from the source;
- **persist / restart**: a delta-merged ``persist()`` publishes the
  MERGED artifact, so a later exact-match run on a FRESH engine takes
  the whole-task disk hit (STATUS.md PR 9 note);
- **observability**: delta counters flatten onto a valid Prometheus
  exposition; ``explain()`` renders ``DELTA[k/n partitions]``.

The two-process append race lives with its PR 5 siblings in
``test_result_cache.py``.
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_CACHE_DELTA_ENABLED,
    FUGUE_TPU_CONF_CACHE_DIR,
    FUGUE_TPU_CONF_CACHE_ENABLED,
    FUGUE_TPU_CONF_PLAN_OPTIMIZE,
)
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _write_part(src: str, i: int, n: int = 900, seed=None, lo=0, hi=12, nulls=False):
    rng = np.random.default_rng(1000 + i if seed is None else seed)
    v = rng.integers(0, 100, n).astype("float64")
    if nulls:
        v[rng.random(n) < 0.1] = np.nan
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(lo, hi, n).astype("int64"),
                "v": v,
                "w": rng.integers(0, 50, n).astype("int64"),
            }
        ),
        os.path.join(src, f"part_{i:03d}.parquet"),
    )


def _src_dir(tmp_path, name="src", files=3, **kw) -> str:
    src = str(tmp_path / name)
    os.makedirs(src, exist_ok=True)
    for i in range(files):
        _write_part(src, i, **kw)
    return src


BUILDS = {
    "chain": lambda dag, src: (
        dag.load(src, fmt="parquet")
        .filter(col("v") > 10)
        .select(col("k"), (col("v") * 2).alias("x"), col("w"))
        .yield_dataframe_as("r", as_local=True)
    ),
    "filter": lambda dag, src: (
        dag.load(src, fmt="parquet")
        .filter(col("v") > 50)
        .yield_dataframe_as("r", as_local=True)
    ),
    "agg": lambda dag, src: (
        dag.load(src, fmt="parquet")
        .filter(col("v") > 10)
        .partition_by("k")
        .aggregate(
            ff.sum(col("v")).alias("s"),
            ff.count(col("v")).alias("n"),
            ff.avg(col("v")).alias("m"),
            ff.min(col("v")).alias("lo"),
            ff.max(col("v")).alias("hi"),
        )
        .yield_dataframe_as("r", as_local=True)
    ),
}


def _run(build, src, conf, engine_cls=JaxExecutionEngine, engine=None):
    eng = engine if engine is not None else engine_cls(conf)
    dag = FugueWorkflow()
    build(dag, src)
    dag.run(eng)
    return dag.yields["r"].result.as_pandas(), eng, dag


def _stats(eng):
    return eng.stats()["cache"]


def _delta_cycle(build, src, conf, engine_cls, grow):
    """cold -> grow -> warm (must be a delta partial hit) -> cache-off
    reference; warm must equal the reference BIT-FOR-BIT."""
    off = dict(conf)
    off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
    cold, _, _ = _run(build, src, conf, engine_cls)
    grow()
    warm, we, wdag = _run(build, src, conf, engine_cls)
    ref, _, _ = _run(build, src, off, engine_cls)
    st = _stats(we)
    assert st["partial_hits"] >= 1, st
    assert st["delta_partitions_fresh"] >= 1, st
    assert st["bytes_skipped_delta"] > 0, st
    pd.testing.assert_frame_equal(warm, ref)
    return warm, we, wdag


# ---------------------------------------------------------------------------
# the delta parity matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", ["chain", "filter", "agg"])
@pytest.mark.parametrize("engine_cls", [JaxExecutionEngine, NativeExecutionEngine])
@pytest.mark.parametrize("opt", [True, False])
def test_delta_parity(tmp_path, shape, engine_cls, opt):
    src = _src_dir(tmp_path)
    conf = {
        FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache"),
        FUGUE_TPU_CONF_PLAN_OPTIMIZE: opt,
    }
    _delta_cycle(
        BUILDS[shape], src, conf, engine_cls, lambda: _write_part(src, 3)
    )


@pytest.mark.parametrize("engine_cls", [JaxExecutionEngine, NativeExecutionEngine])
def test_delta_aggregate_nulls_and_new_keys(tmp_path, engine_cls):
    """NULL values exercise the merge-identity semantics (an all-NULL
    group's sum stays NULL, avg recomposes as sum/count); the delta
    partition introduces keys the cached partial has never seen."""
    src = _src_dir(tmp_path, nulls=True)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}
    _delta_cycle(
        BUILDS["agg"],
        src,
        conf,
        engine_cls,
        lambda: _write_part(src, 3, lo=12, hi=16, nulls=True),
    )


def test_delta_multi_generation(tmp_path):
    """Append twice: the second warm run consumes the manifest the first
    one republished (multi-segment / re-published partial)."""
    src = _src_dir(tmp_path)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}
    for shape in ("chain", "agg"):
        sub = _src_dir(tmp_path, name=f"src_{shape}")
        _run(BUILDS[shape], sub, conf)
        _write_part(sub, 3)
        _run(BUILDS[shape], sub, conf)
        _write_part(sub, 4)
        warm, we, _ = _run(BUILDS[shape], sub, conf)
        off = dict(conf)
        off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
        ref, _, _ = _run(BUILDS[shape], sub, off)
        pd.testing.assert_frame_equal(warm, ref)
        assert _stats(we)["partial_hits"] >= 1


def test_grown_csv_single_file(tmp_path):
    """An appended-to csv with an unchanged prefix: the stored digest +
    row count prove the append, and only the appended rows recompute."""
    f = str(tmp_path / "data.csv")
    rng = np.random.default_rng(7)

    def append(n):
        pdf = pd.DataFrame(
            {"k": rng.integers(0, 8, n), "v": rng.integers(0, 50, n)}
        )
        pdf.to_csv(
            f, mode="a" if os.path.exists(f) else "w", header=False, index=False
        )

    append(2500)

    def build(dag, src):
        (
            dag.load(src, fmt="csv", columns="k:long,v:double", header=False)
            .filter(col("v") > 5)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"), ff.avg(col("v")).alias("m"))
            .yield_dataframe_as("r", as_local=True)
        )

    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}
    _, we, _ = _delta_cycle(build, f, conf, JaxExecutionEngine, lambda: append(40))
    # the skipped bytes are the old file prefix
    assert _stats(we)["bytes_skipped_delta"] > 0


# ---------------------------------------------------------------------------
# the refusal ladder — every refusal degrades to whole-task semantics
# ---------------------------------------------------------------------------


def _refusal_case(tmp_path, mutate, expect_reason):
    """cold -> mutate source -> warm must NOT delta-serve, must equal the
    cache-off reference, and the reason must render in explain()."""
    src = _src_dir(tmp_path)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}
    build = BUILDS["agg"]
    _run(build, src, conf)
    mutate(src)
    # dry-run explain BEFORE the warm run consults the live store
    probe = JaxExecutionEngine(conf)
    dag = FugueWorkflow()
    build(dag, src)
    exp = dag.explain(engine=probe)
    assert expect_reason in exp, exp
    warm, we, _ = _run(build, src, conf, engine=probe)
    off = dict(conf)
    off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
    ref, _, _ = _run(build, src, off)
    pd.testing.assert_frame_equal(warm, ref)
    st = _stats(we)
    assert st["partial_hits"] == 0, st
    assert st["delta_refusals"] >= 1, st


def test_changed_partition_contents_refuses(tmp_path):
    def mutate(src):
        _write_part(src, 1, seed=999)  # REWRITE partition 1 (not an append)

    _refusal_case(tmp_path, mutate, "partition contents changed (not an append)")


def test_new_partition_sorting_before_cached_refuses(tmp_path):
    def mutate(src):
        rng = np.random.default_rng(5)
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 12, 500).astype("int64"),
                    "v": rng.integers(0, 100, 500).astype("float64"),
                    "w": rng.integers(0, 50, 500).astype("int64"),
                }
            ),
            os.path.join(src, "aaa_first.parquet"),  # sorts before part_*
        )

    _refusal_case(tmp_path, mutate, "partition order changed")


def test_deleted_partition_refuses(tmp_path):
    def mutate(src):
        os.remove(os.path.join(src, "part_001.parquet"))

    _refusal_case(tmp_path, mutate, "cached partitions missing from source")


def test_non_row_local_verb_refuses_but_load_still_deltas(tmp_path):
    """A distinct in the chain has no delta form — but the LOAD beneath
    it is still delta-served, so the expensive decode of old partitions
    is skipped even when the consumer recomputes."""
    src = _src_dir(tmp_path)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}

    def build(dag, s):
        (
            dag.load(s, fmt="parquet")
            .filter(col("v") > 10)
            .distinct()
            .yield_dataframe_as("r", as_local=True)
        )

    _run(build, src, conf)
    _write_part(src, 3)
    probe = JaxExecutionEngine(conf)
    dag = FugueWorkflow()
    build(dag, src)
    exp = dag.explain(engine=probe)
    assert "not row-local" in exp or "not incrementally maintainable" in exp, exp
    assert "DELTA[" in exp, exp  # the Load's own partial hit
    warm, we, _ = _run(build, src, conf, engine=probe)
    off = dict(conf)
    off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
    ref, _, _ = _run(build, src, off)
    pd.testing.assert_frame_equal(warm, ref)
    st = _stats(we)
    assert st["partial_hits"] >= 1  # the load
    assert st["delta_partitions"] == 3


def test_edited_udf_downstream_recomputes_correctly(tmp_path):
    """An (edited) UDF transformer is never delta-eligible; the run still
    serves the Load's delta and recomputes the transform correctly."""
    src = _src_dir(tmp_path)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}

    def make(mult):
        ns = {"pd": pd}
        exec(
            "def scale(df: pd.DataFrame) -> pd.DataFrame:\n"
            f"    return df.assign(v=df['v'] * {mult}.0)\n",
            ns,
        )
        return ns["scale"]

    def build_with(udf):
        def build(dag, s):
            (
                dag.load(s, fmt="parquet")
                .transform(udf, schema="*")
                .yield_dataframe_as("r", as_local=True)
            )

        return build

    _run(build_with(make(2)), src, conf)
    _write_part(src, 3)
    warm, we, _ = _run(build_with(make(3)), src, conf)  # EDITED udf
    off = dict(conf)
    off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
    ref, _, _ = _run(build_with(make(3)), src, off)
    pd.testing.assert_frame_equal(warm, ref)
    assert _stats(we)["partial_hits"] >= 1  # the load's delta


def test_stream_input_refuses_delta(tmp_path):
    """A one-pass stream source refuses to fingerprint at all — the delta
    layer inherits the poisoned subtree and the run stays correct."""
    from fugue_tpu.dataframe import (
        ArrowDataFrame,
        LocalDataFrameIterableDataFrame,
    )

    pdf = pd.DataFrame(
        {"k": np.arange(2000) % 7, "v": np.arange(2000, dtype="float64")}
    )
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}

    def stream():
        tbl = pa.Table.from_pandas(pdf, preserve_index=False)
        return LocalDataFrameIterableDataFrame(
            (ArrowDataFrame(tbl.slice(s, 500)) for s in range(0, 2000, 500)),
            schema=ArrowDataFrame(tbl).schema,
        )

    def build(dag, _s):
        (
            dag.df(stream())
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    r1, e1, _ = _run(build, None, conf)
    r2, e2, _ = _run(build, None, conf)
    assert _stats(e2)["partial_hits"] == 0
    pd.testing.assert_frame_equal(
        r1.sort_values("k").reset_index(drop=True),
        r2.sort_values("k").reset_index(drop=True),
    )


def test_delta_disabled_conf_gate(tmp_path):
    src = _src_dir(tmp_path)
    conf = {
        FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache"),
        FUGUE_TPU_CONF_CACHE_DELTA_ENABLED: False,
    }
    _run(BUILDS["agg"], src, conf)
    _write_part(src, 3)
    warm, we, _ = _run(BUILDS["agg"], src, conf)
    off = dict(conf)
    off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
    ref, _, _ = _run(BUILDS["agg"], src, off)
    pd.testing.assert_frame_equal(warm, ref)
    st = _stats(we)
    assert st["partial_hits"] == 0 and st["manifest_publishes"] == 0


# ---------------------------------------------------------------------------
# store consistency: entry-count eviction and stale manifests
# ---------------------------------------------------------------------------


def test_disk_max_entries_evicts_lru(tmp_path):
    """The artifact store honors the COUNT cap alongside the byte cap,
    evicting oldest-mtime first, meta sidecars included."""
    import time

    from fugue_tpu.cache.store import ArtifactStore

    store = ArtifactStore(str(tmp_path / "store"), cap_bytes=0, cap_entries=2)
    eng = NativeExecutionEngine({})
    from fugue_tpu.dataframe import PandasDataFrame

    for i, fp in enumerate(["fp_a", "fp_b", "fp_c"]):
        df = PandasDataFrame(pd.DataFrame({"x": [i]}), "x:long")
        store.publish(fp, df, eng, "x:long")
        t = 1_000_000 + i  # deterministic mtime order
        os.utime(store._obj(fp), (t, t))
    assert store.evict_to_cap() == 1
    left = {f for f in os.listdir(store.objs) if f.endswith(".parquet")}
    assert left == {"fp_b.parquet", "fp_c.parquet"}
    assert not os.path.exists(store._meta("fp_a"))


def test_evicted_partition_artifact_invalidates_only_its_manifest(tmp_path):
    """Delete one chain's partial artifact: that chain degrades to a
    whole-task recompute (stale manifest self-deletes), while the OTHER
    chain keeps delta-serving — eviction never poisons the whole cache."""
    src_a = _src_dir(tmp_path, name="src_a")
    src_b = _src_dir(tmp_path, name="src_b")
    d = str(tmp_path / "cache")
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}
    _run(BUILDS["agg"], src_a, conf)
    _run(BUILDS["chain"], src_b, conf)
    _write_part(src_a, 3)
    _write_part(src_b, 3)
    # find chain A's manifest and delete the artifact it references
    import json

    manifests = os.path.join(d, "manifests")
    acc = [
        (f, json.load(open(os.path.join(manifests, f))))
        for f in os.listdir(manifests)
    ]
    victims = [(f, m) for f, m in acc if m["mode"] == "acc"]
    assert victims
    vf, vm = victims[0]
    os.remove(os.path.join(d, "objs", vm["partial"]["artifact"] + ".parquet"))
    warm_a, ea, _ = _run(BUILDS["agg"], src_a, conf)
    warm_b, eb, _ = _run(BUILDS["chain"], src_b, conf)
    off = dict(conf)
    off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
    ref_a, _, _ = _run(BUILDS["agg"], src_a, off)
    ref_b, _, _ = _run(BUILDS["chain"], src_b, off)
    pd.testing.assert_frame_equal(warm_a, ref_a)
    pd.testing.assert_frame_equal(warm_b, ref_b)
    # the aggregate's manifest could not apply (refusal counted); the
    # LOAD beneath it — and all of chain B — still delta-serve: losing
    # one artifact never poisons the rest of the cache
    assert _stats(ea)["delta_refusals"] >= 1
    assert _stats(eb)["partial_hits"] >= 1
    # the stale manifest deleted itself mid-run and the recompute then
    # REPUBLISHED a consistent one: it now covers the grown partition
    # set and references an artifact that actually exists
    m2 = json.load(open(os.path.join(manifests, vf)))
    assert len(m2["partitions"]) == 4
    assert os.path.exists(
        os.path.join(d, "objs", m2["partial"]["artifact"] + ".parquet")
    )


def test_runtime_failure_falls_back_to_full_recompute(tmp_path, monkeypatch):
    """A delta recompute that blows up mid-run (source mutated between
    plan and execution, schema drift...) degrades IN PLACE to a full
    recompute from the source — never an error, never wrong data."""
    import fugue_tpu.cache.delta as delta_mod

    src = _src_dir(tmp_path)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}
    _run(BUILDS["agg"], src, conf)
    _write_part(src, 3)

    def boom(engine, hit):
        raise RuntimeError("injected delta failure")

    monkeypatch.setattr(delta_mod, "_load_fresh", boom)
    warm, we, _ = _run(BUILDS["agg"], src, conf)
    monkeypatch.undo()
    off = dict(conf)
    off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
    ref, _, _ = _run(BUILDS["agg"], src, off)
    pd.testing.assert_frame_equal(warm, ref)


# ---------------------------------------------------------------------------
# persist / restart and observability
# ---------------------------------------------------------------------------


def test_persist_delta_merged_survives_restart(tmp_path):
    """persist() of a delta-merged frame publishes the MERGED artifact:
    a later exact-match run on a FRESH engine (a restarted process)
    takes the fast whole-task disk hit, never re-entering delta."""
    src = _src_dir(tmp_path)
    d = str(tmp_path / "cache")
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def build(dag, s):
        (
            dag.load(s, fmt="parquet")
            .filter(col("v") > 10)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"), ff.avg(col("v")).alias("m"))
            .persist()
            .yield_dataframe_as("r", as_local=True)
        )

    _run(build, src, conf)
    _write_part(src, 3)
    warm, we, _ = _run(build, src, conf)
    assert _stats(we)["partial_hits"] >= 1
    # "restart": a brand-new engine over the unchanged source must take
    # the whole-task hit for the merged fingerprint — zero delta work
    again, e3, _ = _run(build, src, conf)
    st = _stats(e3)
    assert st["hits_mem"] + st["hits_disk"] >= 1, st
    assert st["partial_hits"] == 0, st
    pd.testing.assert_frame_equal(warm, again)


def test_explain_renders_delta_partitions(tmp_path):
    src = _src_dir(tmp_path)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}
    _run(BUILDS["agg"], src, conf)
    _write_part(src, 3)
    probe = JaxExecutionEngine(conf)
    dag = FugueWorkflow()
    BUILDS["agg"](dag, src)
    exp = dag.explain(engine=probe)
    assert "DELTA[3/4 partitions]" in exp, exp
    # the optimizer marks eligible verbs
    assert "delta:source" in exp and "delta:accumulator" in exp, exp


def test_delta_counters_flatten_to_valid_prometheus(tmp_path):
    from fugue_tpu.obs import validate_prometheus_text
    from fugue_tpu.obs.prom import to_prometheus_text

    src = _src_dir(tmp_path)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: str(tmp_path / "cache")}
    _run(BUILDS["agg"], src, conf)
    _write_part(src, 3)
    _, we, _ = _run(BUILDS["agg"], src, conf)
    text = to_prometheus_text(engine=we)
    validate_prometheus_text(text)
    for want in (
        "fugue_tpu_cache_partial_hits",
        "fugue_tpu_cache_delta_partitions",
        "fugue_tpu_cache_bytes_skipped_delta",
    ):
        assert want in text, want
    assert "fugue_tpu_cache_partial_hits 1" in text, text
