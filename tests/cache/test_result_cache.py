"""Content-addressed result cache (``fugue_tpu/cache``, docs/cache.md) — ISSUE 5.

The checklist:

- bit-identical parity: every cached-hit workflow result equals the
  uncached run across transform / filter / join / aggregate / SQL /
  streaming paths, optimizer ON and OFF;
- invalidation: mutated Load file, edited UDF source, changed
  PartitionSpec, cache salt, optimizer-setting stability;
- refusal (poisoning): non-deterministic markers, streams, seedless
  sample — a refused node is a miss, never a wrong hit;
- frontier cut: warm runs execute ZERO producer tasks upstream of the
  cut (span absence + ``bytes_skipped``), interior results raise a
  descriptive error;
- durability: persist survives an engine restart via the artifact
  store; torn artifacts fall back to recompute; a two-process publish
  race leaves one valid artifact;
- lifecycle: ``reset_stats`` zeroes counters without evicting entries;
  disabled (`fugue.tpu.cache.enabled=false`) is the pre-cache path.
"""

import json
import os
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.cache import ResultCache, clean_cache_dir, non_deterministic
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_CACHE_DIR,
    FUGUE_TPU_CONF_CACHE_ENABLED,
    FUGUE_TPU_CONF_CACHE_SALT,
    FUGUE_TPU_CONF_PLAN_OPTIMIZE,
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
    FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH,
)
from fugue_tpu.dataframe import ArrowDataFrame, LocalDataFrameIterableDataFrame
from fugue_tpu.exceptions import FugueWorkflowError
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.obs import get_tracer


def _frame(n=3000, seed=0) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "k": rng.integers(0, 16, n),
            "v": rng.random(n),
            "w": rng.random(n),
            "s": rng.choice(["a", "b", "c", None], n),
        }
    )


def _stream(pdf: pd.DataFrame, step: int = 512):
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(step, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, step)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


def _run(build, conf, engine_cls=JaxExecutionEngine, engine=None, sort=None):
    eng = engine if engine is not None else engine_cls(conf)
    dag = FugueWorkflow()
    build(dag)
    dag.run(eng)
    res = dag.yields["r"].result.as_pandas()
    if sort:
        res = res.sort_values(sort).reset_index(drop=True)
    return res, eng, dag


def _cache_stats(eng):
    return eng.stats()["cache"]


# ---------------------------------------------------------------------------
# bit-identical parity: warm hit == cold run == cache-off run
# ---------------------------------------------------------------------------


def _parity_case(build, tmp_path, sort=None, engine_cls=JaxExecutionEngine):
    """cold (publishes) -> warm on a FRESH engine (disk hit) -> reference
    with the cache disabled; all three must be bit-identical, and with
    the optimizer ON and OFF the warm result must not change."""
    for opt in (True, False):
        d = str(tmp_path / f"cache_opt_{opt}")
        conf = {
            FUGUE_TPU_CONF_CACHE_DIR: d,
            FUGUE_TPU_CONF_PLAN_OPTIMIZE: opt,
        }
        off = dict(conf)
        off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
        cold, ce, _ = _run(build, conf, engine_cls, sort=sort)
        warm, we, _ = _run(build, conf, engine_cls, sort=sort)
        ref, _, _ = _run(build, off, engine_cls, sort=sort)
        assert _cache_stats(we)["hits_disk"] >= 1, _cache_stats(we)
        pd.testing.assert_frame_equal(cold, warm)
        pd.testing.assert_frame_equal(warm, ref)


def test_parity_aggregate(tmp_path):
    pdf = _frame()

    def build(dag):
        (
            dag.df(pdf)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n"))
            .yield_dataframe_as("r", as_local=True)
        )

    _parity_case(build, tmp_path, sort=["k"])


def test_parity_filter_select(tmp_path):
    pdf = _frame()

    def build(dag):
        (
            dag.df(pdf)
            .filter(col("v") > 0.4)
            .select(col("k"), col("v"), (col("v") * 2).alias("v2"))
            .yield_dataframe_as("r", as_local=True)
        )

    _parity_case(build, tmp_path)


def test_parity_join(tmp_path):
    left = _frame(800, seed=1)
    right = pd.DataFrame({"k": np.arange(16), "label": [f"g{i}" for i in range(16)]})

    def build(dag):
        a = dag.df(left)
        b = dag.df(right)
        a.join(b, how="inner", on=["k"]).yield_dataframe_as("r", as_local=True)

    _parity_case(build, tmp_path, sort=["k", "v"])


def test_parity_transform_udf(tmp_path):
    pdf = _frame(1000, seed=2)

    # schema: *,v2:double
    def demean(df: pd.DataFrame) -> pd.DataFrame:
        return df.assign(v2=df["v"] - df["v"].mean())

    def build(dag):
        (
            dag.df(pdf)
            .partition_by("k")
            .transform(demean)
            .yield_dataframe_as("r", as_local=True)
        )

    _parity_case(build, tmp_path, sort=["k", "v"])


def test_parity_sql(tmp_path):
    pdf = _frame(1200, seed=3)

    def build(dag):
        a = dag.df(pdf)
        dag.select(
            "SELECT k, SUM(v) AS s FROM", a, "GROUP BY k"
        ).yield_dataframe_as("r", as_local=True)

    _parity_case(build, tmp_path, sort=["k"])


def test_parity_native_engine(tmp_path):
    pdf = _frame(700, seed=4)

    def build(dag):
        (
            dag.df(pdf)
            .partition_by("k")
            .aggregate(ff.avg(col("w")).alias("m"))
            .yield_dataframe_as("r", as_local=True)
        )

    _parity_case(build, tmp_path, sort=["k"], engine_cls=NativeExecutionEngine)


def test_streaming_input_refuses_but_downstream_parity(tmp_path):
    """A one-pass stream CreateData poisons its subtree (hashing would
    consume it) — both runs recompute, results stay bit-identical, and
    the refusal is counted."""
    pdf = _frame(2000, seed=5)
    d = str(tmp_path / "cache_stream")

    def build(dag):
        (
            dag.df(_stream(pdf))
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    conf = {
        FUGUE_TPU_CONF_CACHE_DIR: d,
        FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 512,
    }
    cold, ce, _ = _run(build, conf, sort=["k"])
    warm, we, _ = _run(build, conf, sort=["k"])
    pd.testing.assert_frame_equal(cold, warm)
    assert _cache_stats(we)["hits_disk"] == 0
    assert _cache_stats(we)["refusals"] >= 1


# ---------------------------------------------------------------------------
# the frontier cut: producers upstream of a hit never run
# ---------------------------------------------------------------------------


def test_warm_run_skips_producers_zero_spans(tmp_path):
    """Span absence + counters: the warm run records NO engine verbs and
    NO workflow.task spans for the skipped Load/Filter producers, and
    bytes_skipped covers >=90% of the source file."""
    d = str(tmp_path / "cache")
    src = str(tmp_path / "src.parquet")
    rng = np.random.default_rng(7)
    n = 50_000
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(0, 32, n),
                "v": rng.random(n),
                **{f"x{i}": rng.random(n) for i in range(6)},
            }
        ),
        src,
    )

    def build(dag):
        (
            dag.load(src)
            .filter(col("v") > 0.25)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}
    cold, _, _ = _run(build, conf, sort=["k"])
    tr = get_tracer()
    was = tr.enabled
    tr.enable()
    tr.clear()
    try:
        warm, we, dag = _run(build, conf, sort=["k"])
        names = [r["name"] for r in tr.records()]
    finally:
        if not was:
            tr.disable()
        tr.clear()
    pd.testing.assert_frame_equal(cold, warm)
    # zero producer-side work: no load/filter/aggregate verbs, no chunk
    # spans, one task span (the served hit); rehydration (engine.to_df of
    # the small artifact) is the only engine activity allowed
    producer_spans = [
        n
        for n in names
        if n in ("engine.filter", "engine.aggregate", "stream.chunk")
        or n.startswith("engine.load")
    ]
    assert producer_spans == [], names
    assert names.count("workflow.task") == 1, names
    assert "cache.lookup" in names and "task.cache_hit" in names, names
    st = _cache_stats(we)
    # the optimized plan is load -> lowered (filter+aggregate) segment, so
    # the warm cut skips the load; with segment lowering off it would be
    # load + filter (2). Either way every producer is skipped (executes=0)
    assert st["tasks_skipped"] >= 1
    assert st["bytes_skipped"] >= 0.9 * os.path.getsize(src)
    plan = dag.last_cache_plan
    assert plan.summary()["executes"] == 0


def test_skipped_interior_result_raises_descriptive(tmp_path):
    d = str(tmp_path / "cache")
    pdf = _frame(500, seed=8)
    # segment lowering off: this test pins the CACHE-skip error for an
    # interior task that survives optimization (lowering would absorb the
    # filter into the aggregate segment and raise the optimizer's
    # optimized-away error at plan time instead)
    conf = {
        FUGUE_TPU_CONF_CACHE_DIR: d,
        "fugue.tpu.plan.lower_segments": False,
    }

    def run_once():
        eng = JaxExecutionEngine(conf)
        dag = FugueWorkflow()
        mid = dag.df(pdf).filter(col("v") > 0.5)
        mid.partition_by("k").aggregate(ff.sum(col("v")).alias("s")).yield_dataframe_as(
            "r", as_local=True
        )
        dag.run(eng)
        return dag, mid

    run_once()
    dag, mid = run_once()  # warm: create+filter skipped
    with pytest.raises(FugueWorkflowError, match="result-cache"):
        _ = mid.result


def test_explain_renders_cut_points(tmp_path):
    d = str(tmp_path / "cache")
    pdf = _frame(400, seed=9)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def build(dag):
        (
            dag.df(pdf)
            .filter(col("v") > 0.1)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    _, eng, _ = _run(build, conf)
    dag = FugueWorkflow()
    build(dag)
    text = dag.explain(engine=eng)
    assert "result cache" in text
    assert "HIT[" in text
    assert "skipped (downstream hit cuts the plan here)" in text


# ---------------------------------------------------------------------------
# invalidation
# ---------------------------------------------------------------------------


def test_mutated_load_file_invalidates(tmp_path):
    d = str(tmp_path / "cache")
    src = str(tmp_path / "src.parquet")
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def write(seed):
        rng = np.random.default_rng(seed)
        pq.write_table(
            pa.table({"k": rng.integers(0, 8, 2000), "v": rng.random(2000)}), src
        )

    def build(dag):
        (
            dag.load(src)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    write(0)
    r1, _, _ = _run(build, conf, sort=["k"])
    time.sleep(0.01)  # ensure a distinct mtime even on coarse filesystems
    write(1)  # same path, new content (size and/or mtime change)
    r2, e2, _ = _run(build, conf, sort=["k"])
    assert _cache_stats(e2)["hits_disk"] == 0
    assert not r1.equals(r2)
    off = dict(conf)
    off[FUGUE_TPU_CONF_CACHE_ENABLED] = False
    ref, _, _ = _run(build, off, sort=["k"])
    pd.testing.assert_frame_equal(r2, ref)


def test_edited_udf_source_invalidates(tmp_path):
    """Two UDFs with the SAME name/module but different bodies must not
    share a fingerprint (the task-uuid layer, which only hashes
    module+qualname, would false-hit here)."""
    d = str(tmp_path / "cache")
    pdf = _frame(600, seed=10)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def make_udf(version):
        ns = {"pd": pd}
        body = "+ 1.0" if version == 1 else "+ 2.0"
        exec(
            "def bump(df: pd.DataFrame) -> pd.DataFrame:\n"
            f"    return df.assign(v=df['v'] {body})\n",
            ns,
        )
        return ns["bump"]

    def build_with(udf):
        def build(dag):
            (
                dag.df(pdf)
                .partition_by("k")
                .transform(udf, schema="*")
                .yield_dataframe_as("r", as_local=True)
            )

        return build

    r1, _, _ = _run(build_with(make_udf(1)), conf, sort=["k", "v"])
    r1b, e1b, d1b = _run(build_with(make_udf(1)), conf, sort=["k", "v"])
    assert _cache_stats(e1b)["hits_disk"] >= 1  # same source: hit
    assert d1b.last_cache_plan.summary()["executes"] == 0
    pd.testing.assert_frame_equal(r1, r1b)
    r2, _, d2 = _run(build_with(make_udf(2)), conf, sort=["k", "v"])
    assert d2.last_cache_plan.summary()["executes"] >= 1  # edited: recompute
    assert not r1.equals(r2)


def test_closure_value_differentiates_udfs(tmp_path):
    d = str(tmp_path / "cache")
    pdf = _frame(400, seed=11)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def make(offset):
        # schema: *
        def shift(df: pd.DataFrame) -> pd.DataFrame:
            return df.assign(v=df["v"] + offset)

        return shift

    def build_with(udf):
        def build(dag):
            dag.df(pdf).transform(udf, schema="*").yield_dataframe_as(
                "r", as_local=True
            )

        return build

    r1, _, _ = _run(build_with(make(1.0)), conf, sort=["k", "v"])
    r2, _, d2 = _run(build_with(make(5.0)), conf, sort=["k", "v"])
    assert d2.last_cache_plan.summary()["executes"] >= 1  # not served
    assert not r1.equals(r2)


def test_partition_spec_and_salt_invalidate(tmp_path):
    d = str(tmp_path / "cache")
    pdf = _frame(500, seed=12)

    def build_by(key):
        def build(dag):
            (
                dag.df(pdf)
                .partition_by(key)
                .aggregate(ff.count(col("v")).alias("n"))
                .yield_dataframe_as("r", as_local=True)
            )

        return build

    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}
    _run(build_by("k"), conf)
    _, _, d2 = _run(build_by("s"), conf)  # different PartitionSpec: miss
    assert d2.last_cache_plan.summary()["executes"] >= 1
    _, e3, d3 = _run(build_by("k"), conf)  # same spec: hit
    assert _cache_stats(e3)["hits_disk"] >= 1
    assert d3.last_cache_plan.summary()["executes"] == 0
    salted = dict(conf)
    salted[FUGUE_TPU_CONF_CACHE_SALT] = "v2"
    _, e4, _ = _run(build_by("k"), salted)  # salt bump: global invalidation
    assert _cache_stats(e4)["hits_disk"] == 0


def test_optimizer_setting_stability(tmp_path):
    """Fingerprints are computed over the POST-optimization plan: the
    same setting twice -> warm hit; toggling the optimizer changes the
    executed plan -> safe miss, and results stay identical either way."""
    pdf = _frame(900, seed=13)

    def build(dag):
        (
            dag.df(pdf)
            .filter(col("v") > 0.3)
            .select(col("k"), col("v"))
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    d = str(tmp_path / "cache")
    on = {FUGUE_TPU_CONF_CACHE_DIR: d, FUGUE_TPU_CONF_PLAN_OPTIMIZE: True}
    off = {FUGUE_TPU_CONF_CACHE_DIR: d, FUGUE_TPU_CONF_PLAN_OPTIMIZE: False}
    r_on, _, _ = _run(build, on, sort=["k"])
    r_on2, e2, _ = _run(build, on, sort=["k"])
    assert _cache_stats(e2)["hits_disk"] >= 1  # stable across identical runs
    r_off, _, _ = _run(build, off, sort=["k"])
    pd.testing.assert_frame_equal(r_on, r_on2)
    pd.testing.assert_frame_equal(r_on, r_off)


# ---------------------------------------------------------------------------
# refusal / poisoning
# ---------------------------------------------------------------------------


def test_non_deterministic_marker_poisons_subtree(tmp_path):
    d = str(tmp_path / "cache")
    pdf = _frame(300, seed=14)
    calls = {"n": 0}

    @non_deterministic
    def jitter(df: pd.DataFrame) -> pd.DataFrame:
        calls["n"] += 1
        return df.assign(v=df["v"] + 0.0)

    def build(dag):
        (
            dag.df(pdf)
            .transform(jitter, schema="*")
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}
    _run(build, conf)
    _, e2, d2 = _run(build, conf)
    # the marked transform AND its downstream aggregate recompute
    assert calls["n"] >= 2
    st = _cache_stats(e2)
    assert st["refusals"] >= 2  # transform + poisoned aggregate
    assert d2.last_cache_plan.summary()["executes"] >= 2


def test_seedless_sample_refuses(tmp_path):
    d = str(tmp_path / "cache")
    pdf = _frame(500, seed=15)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def build(dag):
        dag.df(pdf).sample(frac=0.5).yield_dataframe_as("r", as_local=True)

    _run(build, conf)
    _, e2, d2 = _run(build, conf)
    assert d2.last_cache_plan.summary()["executes"] >= 1  # sample reruns
    assert _cache_stats(e2)["refusals"] >= 1

    def build_seeded(dag):
        dag.df(pdf).sample(frac=0.5, seed=42).yield_dataframe_as("r", as_local=True)

    r1, _, _ = _run(build_seeded, conf)
    r2, e4, d4 = _run(build_seeded, conf)
    assert _cache_stats(e4)["hits_disk"] >= 1
    assert d4.last_cache_plan.summary()["executes"] == 0
    pd.testing.assert_frame_equal(r1, r2)


# ---------------------------------------------------------------------------
# durability: persist across restart, torn artifacts, publish races
# ---------------------------------------------------------------------------


def test_persist_survives_engine_restart(tmp_path):
    """An explicit persist() publishes to the artifact store, so a FRESH
    engine (a new process in production) serves it without recomputing."""
    d = str(tmp_path / "cache")
    pdf = _frame(800, seed=16)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def build(dag):
        (
            dag.df(pdf)
            .filter(col("v") > 0.2)
            .persist()
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    r1, _, _ = _run(build, conf, sort=["k"])
    r2, e2, _ = _run(build, conf, sort=["k"])  # new engine = restart
    assert _cache_stats(e2)["hits_disk"] >= 1
    pd.testing.assert_frame_equal(r1, r2)


def test_strong_checkpoint_single_artifact_two_indexes(tmp_path):
    """A deterministic StrongCheckpoint file is INDEXED by the cache (a
    ref), never copied: one artifact on disk, addressable both by task
    uuid (checkpoint replay) and by fingerprint (memoization)."""
    d = str(tmp_path / "cache")
    cp = str(tmp_path / "checkpoints")
    pdf = _frame(600, seed=17)
    conf = {
        FUGUE_TPU_CONF_CACHE_DIR: d,
        FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH: cp,
    }

    def build(dag):
        (
            dag.df(pdf)
            .filter(col("v") > 0.4)
            .deterministic_checkpoint()
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    r1, e1, _ = _run(build, conf, sort=["k"])
    assert _cache_stats(e1)["links"] >= 1  # ref, not a copy
    objs = os.path.join(d, "objs")
    refs = [f for f in os.listdir(objs) if f.endswith(".ref.json")]
    assert len(refs) >= 1
    with open(os.path.join(objs, refs[0])) as f:
        target = json.load(f)["path"]
    assert os.path.dirname(os.path.abspath(target)) == os.path.abspath(cp)
    r2, e2, _ = _run(build, conf, sort=["k"])
    pd.testing.assert_frame_equal(r1, r2)


def test_torn_artifact_falls_back_to_recompute(tmp_path):
    d = str(tmp_path / "cache")
    pdf = _frame(700, seed=18)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def build(dag):
        (
            dag.df(pdf)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    r1, _, _ = _run(build, conf, sort=["k"])
    objs = os.path.join(d, "objs")
    for f in os.listdir(objs):
        if f.endswith(".parquet"):
            with open(os.path.join(objs, f), "r+b") as fh:  # tear every artifact
                fh.truncate(16)
    r2, e2, _ = _run(build, conf, sort=["k"])
    pd.testing.assert_frame_equal(r1, r2)
    assert _cache_stats(e2)["hits_disk"] == 0
    # the torn files were removed; a third run republishes and hits again
    r3, e3, _ = _run(build, conf, sort=["k"])
    assert _cache_stats(e3)["hits_disk"] >= 1
    pd.testing.assert_frame_equal(r1, r3)


def _race_worker(args):
    import numpy as np
    import pandas as pd

    from fugue_tpu import FugueWorkflow
    from fugue_tpu.column import col, functions as ff
    from fugue_tpu.constants import FUGUE_TPU_CONF_CACHE_DIR
    from fugue_tpu.execution import NativeExecutionEngine

    d, seed = args
    rng = np.random.default_rng(0)  # SAME data in both processes
    pdf = pd.DataFrame({"k": rng.integers(0, 8, 4000), "v": rng.random(4000)})
    eng = NativeExecutionEngine({FUGUE_TPU_CONF_CACHE_DIR: d})
    dag = FugueWorkflow()
    (
        dag.df(pdf)
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("s"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    return dag.yields["r"].result.as_pandas().sort_values("k").values.tolist()


def test_concurrent_two_process_publish_race(tmp_path):
    """Two processes publishing the same fingerprints concurrently: both
    succeed, the surviving artifacts are complete, and a warm third run
    hits them."""
    import multiprocessing as mp

    d = str(tmp_path / "cache")
    ctx = mp.get_context("fork")
    with ctx.Pool(2) as pool:
        outs = pool.map(_race_worker, [(d, 0), (d, 0)])
    assert outs[0] == outs[1]
    warm = _race_worker((d, 0))
    assert warm == outs[0]
    eng = NativeExecutionEngine({FUGUE_TPU_CONF_CACHE_DIR: d})
    cache = eng.result_cache
    objs = os.listdir(os.path.join(d, "objs"))
    assert any(f.endswith(".parquet") for f in objs)
    # every artifact loads cleanly
    for f in objs:
        if f.endswith(".parquet"):
            assert cache.disk.load(f[: -len(".parquet")], eng) is not None


def _delta_race_worker(args):
    import os

    from fugue_tpu import FugueWorkflow
    from fugue_tpu.column import col, functions as ff
    from fugue_tpu.constants import FUGUE_TPU_CONF_CACHE_DIR
    from fugue_tpu.execution import NativeExecutionEngine

    d, src = args
    eng = NativeExecutionEngine({FUGUE_TPU_CONF_CACHE_DIR: d})
    dag = FugueWorkflow()
    (
        dag.load(src, fmt="parquet")
        .filter(col("v") > 10)
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("s"), ff.avg(col("v")).alias("m"))
        .yield_dataframe_as("r", as_local=True)
    )
    dag.run(eng)
    st = eng.stats()["cache"]
    return (
        dag.yields["r"].result.as_pandas().values.tolist(),
        st["partial_hits"],
    )


def test_concurrent_two_process_append_race(tmp_path):
    """ISSUE 9 satellite: two engines warm-run the SAME grown directory
    concurrently. Both must succeed via the atomic publish (the fresh
    delta artifacts are content-addressed, so both processes compute the
    same ids and the rename dedupes), results are identical, and the
    store ends with exactly one artifact per fingerprint — no torn or
    duplicate files."""
    import multiprocessing as mp

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    d = str(tmp_path / "cache")
    src = str(tmp_path / "src")
    os.makedirs(src)

    def write_part(i):
        rng = np.random.default_rng(100 + i)
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 8, 1500).astype("int64"),
                    "v": rng.integers(0, 100, 1500).astype("float64"),
                }
            ),
            os.path.join(src, f"p_{i:02d}.parquet"),
        )

    for i in range(3):
        write_part(i)
    cold, _ = _delta_race_worker((d, src))  # publishes the manifest
    write_part(3)  # grow
    ctx = mp.get_context("fork")
    with ctx.Pool(2) as pool:
        outs = pool.map(_delta_race_worker, [(d, src), (d, src)])
    (r1, ph1), (r2, ph2) = outs
    assert r1 == r2
    assert ph1 >= 1 and ph2 >= 1  # both actually took the delta path
    # one artifact per fingerprint, every one complete, no temp leftovers
    objs = os.listdir(os.path.join(d, "objs"))
    assert not any("__tmp" in f for f in objs)
    fps = [f[: -len(".parquet")] for f in objs if f.endswith(".parquet")]
    assert len(fps) == len(set(fps))
    eng = NativeExecutionEngine({FUGUE_TPU_CONF_CACHE_DIR: d})
    for fp in fps:
        assert eng.result_cache.disk.load(fp, eng) is not None
    # a third, exact-match run takes the plain whole-task hit
    warm, ph3 = _delta_race_worker((d, src))
    assert warm == r1 and ph3 == 0


# ---------------------------------------------------------------------------
# lifecycle and the disabled path
# ---------------------------------------------------------------------------


def test_reset_stats_zeroes_counters_keeps_entries(tmp_path):
    """Mirrors the JitCache.reset contract: counters to zero, live
    entries untouched — a reset must never turn into a perf event."""
    d = str(tmp_path / "cache")
    pdf = _frame(400, seed=19)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def build(dag):
        (
            dag.df(pdf)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    _, eng, _ = _run(build, conf)
    assert _cache_stats(eng)["publishes"] >= 1
    entries_before = _cache_stats(eng)["mem_entries"]
    eng.reset_stats()
    st = _cache_stats(eng)
    assert st["publishes"] == 0 and st["lookups"] == 0
    assert st["mem_entries"] == entries_before  # entries survive the reset
    dag = FugueWorkflow()
    build(dag)
    dag.run(eng)  # memory-tier hit straight after the reset
    assert _cache_stats(eng)["hits_mem"] >= 1


def test_disabled_is_pre_cache_path(tmp_path):
    pdf = _frame(500, seed=20)
    # lowering off so the interior filter survives as its own task — the
    # assertion below is about interior addressability on the pre-cache
    # path, not about segment absorption
    conf = {
        FUGUE_TPU_CONF_CACHE_ENABLED: False,
        "fugue.tpu.plan.lower_segments": False,
    }

    def build(dag):
        mid = dag.df(pdf).filter(col("v") > 0.5)
        mid.partition_by("k").aggregate(ff.sum(col("v")).alias("s")).yield_dataframe_as(
            "r", as_local=True
        )
        return mid

    eng = JaxExecutionEngine(conf)
    for _ in range(2):
        dag = FugueWorkflow()
        mid = build(dag)
        dag.run(eng)
        _ = mid.result  # interior results stay addressable
    st = _cache_stats(eng)
    assert all(
        v in (0, False) for k, v in st.items() if k not in ("disk_enabled",)
    ), st
    assert dag.last_cache_plan is None


def test_disabled_overhead_under_2_percent():
    """The <2% contract, mirroring the tracer's disabled-path guard: with
    the cache disabled the per-run cost is one enabled check at plan time
    plus one plan-is-None check per task. Charge the measured worst-case
    cost of both against a small workflow's wall."""
    pdf = _frame(30_000, seed=21)
    conf = {FUGUE_TPU_CONF_CACHE_ENABLED: False}
    eng = JaxExecutionEngine(conf)
    cache = eng.result_cache

    def run():
        dag = FugueWorkflow()
        (
            dag.df(pdf)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )
        dag.run(eng)

    run()  # warmup (jit)
    t0 = time.perf_counter()
    run()
    wall = time.perf_counter() - t0
    # worst-case disabled site: reading cache.enabled + a dict get
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        if cache.enabled:
            raise AssertionError
    per_call = (time.perf_counter() - t0) / n
    sites = 3 * 10  # 3 tasks, generously 10 checks each
    assert per_call * sites < 0.02 * wall, (per_call, wall)


def test_unwritable_dir_degrades_to_memory_only(tmp_path):
    # a plain FILE at the conf'd path: makedirs fails even for root
    # (chmod-based unwritability is invisible to a root test runner)
    d = str(tmp_path / "ro")
    with open(d, "w") as f:
        f.write("not a directory")
    pdf = _frame(300, seed=22)
    conf = {FUGUE_TPU_CONF_CACHE_DIR: d}

    def build(dag):
        (
            dag.df(pdf)
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"))
            .yield_dataframe_as("r", as_local=True)
        )

    _, eng, _ = _run(build, conf, engine_cls=NativeExecutionEngine)
    st = _cache_stats(eng)
    assert st["disk_enabled"] is False  # degraded, not crashed
    # memory tier still works on the same engine
    dag = FugueWorkflow()
    build(dag)
    dag.run(eng)
    assert _cache_stats(eng)["hits_mem"] >= 1


def test_clean_cache_dir_helper(tmp_path):
    d = str(tmp_path / "cache")
    pdf = _frame(200, seed=23)

    def build(dag):
        dag.df(pdf).partition_by("k").aggregate(
            ff.sum(col("v")).alias("s")
        ).yield_dataframe_as("r", as_local=True)

    _run(build, {FUGUE_TPU_CONF_CACHE_DIR: d}, engine_cls=NativeExecutionEngine)
    assert any(f.endswith(".parquet") for f in os.listdir(os.path.join(d, "objs")))
    msg = clean_cache_dir(d)
    assert "removed" in msg
    assert not os.path.isdir(os.path.join(d, "objs"))
    assert "nothing cleaned" in clean_cache_dir("")
