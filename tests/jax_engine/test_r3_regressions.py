"""Round-3 review regressions: ingest-cache NaN semantics and the
compiled-map physical repartition."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from fugue_tpu.dataframe import ArrowDataFrame
from fugue_tpu.jax import JaxExecutionEngine


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


def test_literal_nan_surfaces_as_null_without_device_op(engine):
    # literal NaN (no arrow null bitmap) — the device convention is NaN ==
    # NULL, and the unmodified frame must agree with the post-op frame
    tbl = pa.table({"v": pa.array([1.0, float("nan"), 3.0], type=pa.float64())})
    jdf = engine.to_df(ArrowDataFrame(tbl))
    out = jdf.as_arrow()
    assert out.column("v").null_count == 1
    assert out.column("v").to_pylist() == [1.0, None, 3.0]


def test_null_only_float_ingest_roundtrip_fast(engine):
    # arrow NULLs (no literal NaN) keep the zero-cost ingest cache AND the
    # same NULL view either way
    tbl = pa.table({"v": pa.array([1.0, None, 3.0], type=pa.float64())})
    jdf = engine.to_df(ArrowDataFrame(tbl))
    assert jdf.as_arrow().column("v").to_pylist() == [1.0, None, 3.0]


def test_even_repartition_before_compiled_map(engine):
    # an even spec must still physically rebalance before a compiled
    # per-shard UDF (the processor no longer repartitions for this engine)
    import jax.numpy as jnp

    from fugue_tpu.collections import PartitionSpec
    from fugue_tpu.jax.dataframe import JaxDataFrame

    df = pd.DataFrame({"a": np.arange(64, dtype=np.float64)})
    jdf = engine.to_df(df)

    def shard_count(cols):
        # per-shard valid-row count, broadcast to every row of the shard
        v = cols["__valid__"]
        n = jnp.sum(v.astype(jnp.float64))
        return {"n": jnp.zeros_like(cols["a"]) + n}

    out = engine.map_engine.map_dataframe(
        jdf,
        _jax_func_marker(shard_count),
        "n:double",
        PartitionSpec(algo="even", num=8),
        map_func_format_hint="jax",
    )
    counts = out.as_pandas()["n"].tolist()
    # balanced: every shard reports the same count
    assert set(counts) == {8.0}, sorted(set(counts))


def _jax_func_marker(fn):
    """Mimic the transformer convert path's jax-annotated UDF wrapper."""
    from fugue_tpu.jax.execution_engine import _sniff_jax_func

    class _Wrapper:
        input_code = "j"
        output_code = "j"
        _func = staticmethod(fn)

    class _Transformer:
        using_callback = False
        _wrapper = _Wrapper()

    class _Runner:
        transformer = _Transformer()

        def run(self, cursor, df):  # pragma: no cover
            raise AssertionError("compiled path should not call run()")

    r = _Runner()
    assert _sniff_jax_func(r.run) is fn
    return r.run
