"""Device-resident encoded columns: dictionary strings, nullable ints,
datetimes — the VERDICT #4 goals, oracle-verified, with device-residency
asserted (not just correctness)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as f, lit
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxDataFrame, JaxExecutionEngine


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


@pytest.fixture(scope="module")
def oracle():
    e = NativeExecutionEngine()
    yield e
    e.stop()


class TestIngestion:
    def test_strings_are_dict_encoded_on_device(self, engine):
        pdf = pd.DataFrame({"s": ["a", "b", None, "a"], "v": [1.0, 2, 3, 4]})
        jdf = engine.to_df(pdf)
        assert isinstance(jdf, JaxDataFrame)
        assert "s" in jdf.device_cols and jdf.host_table is None
        assert jdf.encodings["s"]["kind"] == "dict"
        # round trip restores values and nulls
        back = jdf.as_pandas()
        assert back["s"].tolist()[:2] == ["a", "b"]
        assert back["s"].isna().tolist() == [False, False, True, False]

    def test_nullable_ints_on_device_with_mask(self, engine):
        pdf = pd.DataFrame({"a": pd.array([1, None, 3], dtype="Int64")})
        jdf = engine.to_df(pdf)
        assert "a" in jdf.device_cols and jdf.host_table is None
        assert "a" in jdf.null_masks
        back = jdf.as_pandas()
        assert back["a"].isna().tolist() == [False, True, False]
        assert back["a"].dropna().tolist() == [1, 3]

    def test_floats_with_arrow_nulls_on_device(self, engine):
        tbl = pa.table({"v": pa.array([1.0, None, 3.0], pa.float64())})
        jdf = engine.to_df(tbl)
        assert "v" in jdf.device_cols  # used to pin the frame to host
        back = jdf.as_pandas()
        assert back["v"].isna().tolist() == [False, True, False]

    def test_datetimes_on_device(self, engine):
        pdf = pd.DataFrame(
            {"t": pd.to_datetime(["2020-01-01", "2020-06-01", None])}
        )
        jdf = engine.to_df(pdf)
        assert "t" in jdf.device_cols
        assert jdf.encodings["t"]["kind"] == "datetime"
        back = jdf.as_pandas()
        assert back["t"].isna().tolist() == [False, False, True]
        assert str(back["t"].iloc[0])[:10] == "2020-01-01"


class TestStringGroupby:
    def test_groupby_string_key_on_device(self, engine, oracle):
        rng = np.random.default_rng(0)
        pdf = pd.DataFrame(
            {
                "s": rng.choice(["apple", "pear", "fig", None], 400).tolist(),
                "v": rng.random(400),
            }
        )
        jdf = engine.to_df(pdf)
        assert "s" in jdf.device_cols and jdf.host_table is None
        spec = PartitionSpec(by=["s"])
        aggs = [f.sum(col("v")).alias("t"), f.count(col("v")).alias("n")]
        got = (
            engine.aggregate(jdf, spec, aggs)
            .as_pandas()
            .sort_values("s", na_position="last")
            .reset_index(drop=True)
        )
        exp = (
            oracle.aggregate(oracle.to_df(pdf), spec, aggs)
            .as_pandas()
            .sort_values("s", na_position="last")
            .reset_index(drop=True)
        )
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_distinct_with_strings_and_nulls(self, engine, oracle):
        pdf = pd.DataFrame(
            {
                "s": ["x", "y", None, "x", None],
                "a": pd.array([1, 2, 3, 1, 3], dtype="Int64"),
            }
        )
        got = engine.distinct(engine.to_df(pdf)).as_pandas()
        exp = oracle.distinct(oracle.to_df(pdf)).as_pandas()
        key = lambda d: d.sort_values(  # noqa: E731
            ["s", "a"], na_position="last"
        ).reset_index(drop=True)
        pd.testing.assert_frame_equal(key(got), key(exp), check_dtype=False)


class TestStringFilter:
    def test_eq_and_like_on_device(self, engine, oracle):
        pdf = pd.DataFrame(
            {
                "s": ["apple", "pear", None, "apricot", "fig"],
                "v": [1.0, 2.0, 3.0, 4.0, 5.0],
            }
        )
        jdf = engine.to_df(pdf)
        assert jdf.host_table is None
        got = engine.filter(jdf, col("s") == "apple")
        assert isinstance(got, JaxDataFrame)  # stayed on device
        assert got.as_pandas()["v"].tolist() == [1.0]
        from fugue_tpu.column.expressions import _LikeExpr

        got2 = engine.filter(jdf, _LikeExpr(col("s"), "ap%"))
        assert sorted(got2.as_pandas()["v"].tolist()) == [1.0, 4.0]
        got3 = engine.filter(jdf, col("s").is_null())
        assert got3.as_pandas()["v"].tolist() == [3.0]
        # oracle agreement on a compound predicate
        cond = _LikeExpr(col("s"), "%p%") & (col("v") > 1)
        exp = oracle.filter(oracle.to_df(pdf), cond).as_pandas()
        g = engine.filter(jdf, cond).as_pandas()
        pd.testing.assert_frame_equal(
            g.reset_index(drop=True), exp.reset_index(drop=True), check_dtype=False
        )


class TestNullableIntFilter:
    def test_filter_nullable_int_on_device(self, engine, oracle):
        pdf = pd.DataFrame(
            {
                "a": pd.array([1, None, 3, 4, None, 6], dtype="Int64"),
                "v": np.arange(6, dtype=np.float64),
            }
        )
        jdf = engine.to_df(pdf)
        assert "a" in jdf.null_masks and jdf.host_table is None
        got = engine.filter(jdf, col("a") > 2)
        assert isinstance(got, JaxDataFrame)
        assert got.as_pandas()["v"].tolist() == [2.0, 3.0, 5.0]
        # NULL semantics: IS_NULL / COALESCE
        got2 = engine.filter(jdf, col("a").is_null())
        assert got2.as_pandas()["v"].tolist() == [1.0, 4.0]
        got3 = engine.filter(jdf, f.coalesce(col("a"), lit(0)) == 0)
        assert got3.as_pandas()["v"].tolist() == [1.0, 4.0]
        # oracle agreement
        cond = (col("a") >= 3) | col("a").is_null()
        exp = oracle.filter(oracle.to_df(pdf), cond).as_pandas()
        g = engine.filter(jdf, cond).as_pandas()
        assert g["v"].tolist() == exp["v"].tolist()

    def test_aggregate_nullable_int_values(self, engine, oracle):
        pdf = pd.DataFrame(
            {
                "k": [1, 1, 2, 2, 3],
                "a": pd.array([10, None, None, None, 5], dtype="Int32"),
            }
        )
        jdf = engine.to_df(pdf)
        assert "a" in jdf.null_masks
        spec = PartitionSpec(by=["k"])
        aggs = [
            f.sum(col("a")).alias("s"),
            f.count(col("a")).alias("n"),
            f.max(col("a")).alias("m"),
        ]
        got = engine.aggregate(jdf, spec, aggs).as_pandas().sort_values("k")
        assert got["n"].tolist() == [1, 0, 1]
        assert got["s"].tolist()[0] == 10 and got["s"].tolist()[2] == 5
        assert pd.isna(got["s"].tolist()[1])

    def test_groupby_nullable_int_key(self, engine, oracle):
        pdf = pd.DataFrame(
            {
                "k": pd.array([1, 1, None, None, 2], dtype="Int64"),
                "v": [1.0, 2.0, 3.0, 4.0, 5.0],
            }
        )
        spec = PartitionSpec(by=["k"])
        aggs = [f.sum(col("v")).alias("s")]
        got = (
            engine.aggregate(engine.to_df(pdf), spec, aggs)
            .as_pandas()
            .sort_values("k", na_position="last")
            .reset_index(drop=True)
        )
        # NULL key forms its own group, distinct from the 0 fill value
        assert got["s"].tolist() == [3.0, 5.0, 7.0]
        assert got["k"].isna().tolist() == [False, False, True]


class TestDatetime:
    def test_groupby_datetime_key(self, engine, oracle):
        pdf = pd.DataFrame(
            {
                "d": pd.to_datetime(
                    ["2020-01-01", "2020-01-01", "2021-05-05", None]
                ),
                "v": [1.0, 2.0, 3.0, 4.0],
            }
        )
        spec = PartitionSpec(by=["d"])
        aggs = [f.sum(col("v")).alias("s")]
        got = (
            engine.aggregate(engine.to_df(pdf), spec, aggs)
            .as_pandas()
            .sort_values("d", na_position="last")
            .reset_index(drop=True)
        )
        assert got["s"].tolist() == [3.0, 3.0, 4.0]
        assert str(got["d"].iloc[0])[:10] == "2020-01-01"
        assert got["d"].isna().tolist() == [False, False, True]


class TestShuffleWithEncodings:
    def test_repartition_carries_masks_and_dicts(self, engine):
        pdf = pd.DataFrame(
            {
                "k": np.arange(100, dtype=np.int64) % 7,
                "s": [f"v{i % 5}" for i in range(100)],
                "a": pd.array(
                    [i if i % 3 else None for i in range(100)], dtype="Int32"
                ),
            }
        )
        jdf = engine.to_df(pdf)
        res = engine.repartition(jdf, PartitionSpec(algo="hash", by=["k"]))
        got = res.as_pandas().sort_values(["k", "s", "a"]).reset_index(drop=True)
        exp = pdf.sort_values(["k", "s", "a"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)


class TestDatetimePredicates:
    def test_datetime_filter_on_device(self, engine, oracle):
        import datetime

        pdf = pd.DataFrame(
            {
                "t": pd.to_datetime(
                    ["2020-01-01", "2020-06-15", None, "2021-02-02"]
                ),
                "v": [1.0, 2.0, 3.0, 4.0],
            }
        )
        jdf = engine.to_df(pdf)
        assert "t" in jdf.device_cols
        got = engine.filter(jdf, col("t") > "2020-03-01")
        assert isinstance(got, JaxDataFrame)  # device path
        assert got.as_pandas()["v"].tolist() == [2.0, 4.0]
        # datetime.date literal + compound predicate; NULL dropped
        cond = (col("t") >= datetime.date(2020, 1, 1)) & (
            col("t") < datetime.datetime(2021, 1, 1)
        )
        got2 = engine.filter(jdf, cond)
        assert got2.as_pandas()["v"].tolist() == [1.0, 2.0]
        # oracle agreement incl. IS_NULL
        got3 = engine.filter(jdf, col("t").is_null())
        assert got3.as_pandas()["v"].tolist() == [3.0]
        exp = oracle.filter(
            oracle.to_df(pdf), col("t") > "2020-03-01"
        ).as_pandas()
        assert got.as_pandas()["v"].tolist() == exp["v"].tolist()


class TestSortedDictionaryOps:
    def test_string_min_max_aggregate_on_device(self, engine, oracle):
        rng = np.random.default_rng(3)
        pdf = pd.DataFrame(
            {
                "k": rng.integers(0, 5, 200),
                "s": rng.choice(["pear", "apple", "zebra", "fig"], 200).tolist(),
            }
        )
        pdf.loc[rng.integers(0, 200, 20), "s"] = None
        spec = PartitionSpec(by=["k"])
        aggs = [
            f.min(col("s")).alias("lo"),
            f.max(col("s")).alias("hi"),
            f.count(col("s")).alias("n"),
        ]
        jdf = engine.to_df(pdf)
        assert "s" in jdf.device_cols  # device path precondition
        got = (
            engine.aggregate(jdf, spec, aggs)
            .as_pandas()
            .sort_values("k")
            .reset_index(drop=True)
        )
        exp = (
            oracle.aggregate(oracle.to_df(pdf), spec, aggs)
            .as_pandas()
            .sort_values("k")
            .reset_index(drop=True)
        )
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_take_with_string_presort(self, engine):
        pdf = pd.DataFrame(
            {
                "s": ["pear", "apple", None, "zebra", "fig"],
                "v": [1.0, 2.0, 3.0, 4.0, 5.0],
            }
        )
        jdf = engine.to_df(pdf)
        res = engine.take(jdf, 2, presort="s")
        assert res.as_array() == [["apple", 2.0], ["fig", 5.0]]
        res2 = engine.take(jdf, 2, presort="s desc")
        assert res2.as_array() == [["zebra", 4.0], ["pear", 1.0]]
        # NULLs fill the tail
        res3 = engine.take(jdf, 5, presort="s")
        assert res3.as_array()[-1][0] is None

    def test_take_with_nullable_int_presort(self, engine):
        pdf = pd.DataFrame(
            {
                "a": pd.array([3, None, 1, 2], dtype="Int32"),
                "v": [1.0, 2.0, 3.0, 4.0],
            }
        )
        res = engine.take(engine.to_df(pdf), 3, presort="a")
        assert [r[0] for r in res.as_array()] == [1, 2, 3]
        res2 = engine.take(engine.to_df(pdf), 4, presort="a desc")
        assert [r[0] for r in res2.as_array()] == [3, 2, 1, None]

    def test_take_with_datetime_presort(self, engine):
        pdf = pd.DataFrame(
            {
                "t": pd.to_datetime(["2021-01-01", "2019-06-01", None, "2020-01-01"]),
                "v": [1.0, 2.0, 3.0, 4.0],
            }
        )
        res = engine.take(engine.to_df(pdf), 2, presort="t")
        assert [str(r[0])[:10] for r in res.as_array()] == [
            "2019-06-01",
            "2020-01-01",
        ]
