"""1:N/N:M device joins (expansion kernel), right/full outer, cross.

The reference runs duplicate-key joins on every backend
(fugue_test/execution_suite.py:379-544); these are the device-native
equivalents. The host engine's join is poisoned inside `_device_only` so a
silent fallback fails the test.
"""

import contextlib
import unittest.mock as mock

import numpy as np
import pandas as pd
import pytest

from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.jax.dataframe import JaxDataFrame


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


@pytest.fixture(scope="module")
def oracle():
    e = NativeExecutionEngine()
    yield e
    e.stop()


@contextlib.contextmanager
def _device_only(engine):
    def boom(*a, **k):  # pragma: no cover
        raise AssertionError("host join used")

    with mock.patch.object(engine._host_engine, "join", boom):
        yield


def _chk(engine, oracle, left, right, how, device_only=True):
    ctx = _device_only(engine) if device_only else contextlib.nullcontext()
    with ctx:
        d = engine.join(engine.to_df(left), engine.to_df(right), how=how)
        if device_only:
            assert isinstance(d, JaxDataFrame)
        got = d.as_pandas()
    exp = oracle.join(oracle.to_df(left), oracle.to_df(right), how=how).as_pandas()
    sc = list(exp.columns)
    g = got[sc].sort_values(sc).reset_index(drop=True)
    x = exp.sort_values(sc).reset_index(drop=True)
    pd.testing.assert_frame_equal(g, x, check_dtype=False)
    return got


def test_duplicate_right_keys_all_types(engine, oracle):
    left = pd.DataFrame({"k": [1, 2, 3, 4], "a": [10.0, 20.0, 30.0, 40.0]})
    right = pd.DataFrame(
        {"k": [1, 1, 2, 2, 2, 9], "b": [1.0, 2.0, 3.0, 4.0, 5.0, 9.0]}
    )
    for how in ("inner", "left_outer", "left_semi", "left_anti"):
        _chk(engine, oracle, left, right, how)


def test_n_to_m_duplicates(engine, oracle):
    left = pd.DataFrame({"k": [1, 1, 1, 2, 2], "a": range(5)})
    right = pd.DataFrame({"k": [1, 1, 2, 2, 2], "b": range(10, 15)})
    _chk(engine, oracle, left, right, "inner")
    _chk(engine, oracle, left, right, "left_outer")


def test_random_large_nm(engine, oracle):
    rng = np.random.default_rng(0)
    left = pd.DataFrame(
        {"k": rng.integers(0, 50, 5000), "a": rng.random(5000)}
    )
    right = pd.DataFrame(
        {"k": rng.integers(0, 60, 2000), "b": rng.random(2000)}
    )
    got = _chk(engine, oracle, left, right, "inner")
    assert len(got) > 100_000  # genuinely expanded


def test_multi_key_duplicates(engine, oracle):
    left = pd.DataFrame(
        {"x": [1, 1, 2, 2], "y": [0, 1, 0, 1], "a": [1.0, 2.0, 3.0, 4.0]}
    )
    right = pd.DataFrame(
        {"x": [1, 1, 2], "y": [0, 0, 1], "b": [9.0, 8.0, 7.0]}
    )
    _chk(engine, oracle, left, right, "inner")
    _chk(engine, oracle, left, right, "left_outer")


def test_null_keys_with_duplicates(engine, oracle):
    # NULL keys never match even when the right side has duplicates
    left = pd.DataFrame({"k": [1.0, np.nan, 2.0], "a": [1.0, 2.0, 3.0]})
    right = pd.DataFrame(
        {"k": [1.0, 1.0, np.nan, np.nan], "b": [5.0, 6.0, 7.0, 8.0]}
    )
    _chk(engine, oracle, left, right, "inner")
    _chk(engine, oracle, left, right, "left_outer")
    _chk(engine, oracle, left, right, "left_anti")


def test_right_outer_device(engine, oracle):
    left = pd.DataFrame({"k": [1, 2, 3], "a": [1.0, 2.0, 3.0]})
    right = pd.DataFrame({"k": [2, 2, 4], "b": [5.0, 6.0, 7.0]})
    _chk(engine, oracle, left, right, "right_outer")


def test_full_outer_device(engine, oracle):
    left = pd.DataFrame({"k": [1, 2], "s": ["a", "b"], "n": [100, 200]})
    right = pd.DataFrame({"k": [2, 3, 3], "w": [5.0, 6.0, 7.0]})
    got = _chk(engine, oracle, left, right, "full_outer")
    # right-only rows carry NULL left values in every representation
    only3 = got[got["k"] == 3]
    assert only3["s"].isna().all() and only3["n"].isna().all()


def test_full_outer_random(engine, oracle):
    rng = np.random.default_rng(7)
    left = pd.DataFrame(
        {"k": rng.integers(0, 30, 500), "a": rng.random(500)}
    )
    right = pd.DataFrame(
        {"k": rng.integers(10, 40, 400), "b": rng.random(400)}
    )
    _chk(engine, oracle, left, right, "full_outer")


def test_cross_join_device(engine, oracle):
    left = pd.DataFrame({"x": [1, 2, 3], "s": ["p", "q", "r"]})
    right = pd.DataFrame({"y": [10.0, 20.0], "m": [1, 2]})
    got = _chk(engine, oracle, left, right, "cross")
    assert len(got) == 6


def test_workflow_level_duplicate_join(engine, oracle):
    import fugue_tpu.api as fa

    left = pd.DataFrame({"k": [1, 2, 3], "a": [1.0, 2.0, 3.0]})
    right = pd.DataFrame({"k": [1, 1, 2], "b": [5.0, 6.0, 7.0]})
    with _device_only(engine):
        res = fa.fugue_sql(
            """
            SELECT df.k, a, b FROM df INNER JOIN other ON df.k = other.k
            """,
            df=left,
            other=right,
            engine=engine,
            as_local=True,
        )
    got = (res.to_pandas() if hasattr(res, "to_pandas") else res).sort_values(
        ["k", "b"]
    )
    assert got["b"].tolist() == [5.0, 6.0, 7.0]
