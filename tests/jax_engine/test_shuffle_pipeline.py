"""Pipelined out-of-core exchange suite (ISSUE 15, docs/shuffle.md
"Pipelined exchange"): write-behind spill, the memory-resident bucket
tier, bucket-pair prefetch + budget-bounded grouping — each proven
bit-identical against the ``fugue.tpu.shuffle.pipeline.enabled=false``
phase-barrier kill-switch, with the PR 2 poison/no-deadlock contracts
extended to the background writer."""

import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from fugue_tpu.constants import (
    FUGUE_TPU_CONF_FAULT_PLAN,
    FUGUE_TPU_CONF_SHUFFLE_BUCKET_BYTES,
    FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET,
    FUGUE_TPU_CONF_SHUFFLE_DIR,
    FUGUE_TPU_CONF_SHUFFLE_MEM_BUCKET_BYTES,
    FUGUE_TPU_CONF_SHUFFLE_PIPELINE_ENABLED,
    FUGUE_TPU_CONF_SHUFFLE_PREFETCH_DEPTH,
)
from fugue_tpu.dataframe import ArrowDataFrame, LocalDataFrameIterableDataFrame
from fugue_tpu.exceptions import FugueTPUError
from fugue_tpu.jax import JaxExecutionEngine

HOWS = ["inner", "left_outer", "left_semi", "left_anti", "right_outer", "full_outer"]


def _engine(tmp_path, budget=20_000, bucket=5_000, **conf):
    # pipelined-SPILL suite: pin the device_exchange rung off so small
    # budgets keep routing these joins through the spill path under test
    # (the exchange rung has its own suite, test_device_exchange.py)
    from fugue_tpu.constants import (
        FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED,
    )

    return JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET: budget,
            FUGUE_TPU_CONF_SHUFFLE_BUCKET_BYTES: bucket,
            FUGUE_TPU_CONF_SHUFFLE_DIR: str(tmp_path),
            FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED: False,
            **conf,
        }
    )


def _frames(n=4000, seed=0, nulls=True, right_keys=None, key_range=None):
    rng = np.random.default_rng(seed)
    lk = rng.integers(0, key_range or (n // 8), n).astype(object)
    rk = rng.integers(0, right_keys or key_range or (n // 8), n).astype(object)
    if nulls:
        lk[::97] = None
        rk[::89] = None
    left = pd.DataFrame({"k": pd.array(lk, dtype="Int64"), "a": rng.normal(size=n)})
    right = pd.DataFrame({"k": pd.array(rk, dtype="Int64"), "b": rng.normal(size=n)})
    return left, right


def _norm(res):
    tbl = res.as_arrow() if not isinstance(res, pa.Table) else res
    pdf = tbl.replace_schema_metadata(None).to_pandas()
    return pdf.sort_values(list(pdf.columns)).reset_index(drop=True)


def _ab(tmp_path, how, seed=0, on_conf=None, **frames_kw):
    """One join through the pipelined engine and the kill-switch engine;
    returns (normalized frames, pipelined stats)."""
    left, right = _frames(seed=seed, **frames_kw)
    on_conf = on_conf or {}
    eng = _engine(tmp_path, **on_conf)
    got = _norm(eng.join(eng.to_df(left), eng.to_df(right), how=how, on=["k"]))
    st = eng.stats()["shuffle"]
    off = _engine(tmp_path, **{FUGUE_TPU_CONF_SHUFFLE_PIPELINE_ENABLED: False})
    ref = _norm(off.join(off.to_df(left), off.to_df(right), how=how, on=["k"]))
    st_off = off.stats()["shuffle"]
    return got, ref[list(got.columns)], st, st_off


@pytest.mark.parametrize("how", HOWS)
def test_pipeline_parity_vs_kill_switch(tmp_path, how):
    """Every hash-partitionable join type: the pipelined path (mem tier +
    grouping + write-behind, default-on) is bit-identical to the
    phase-barrier kill-switch; the kill-switch engine touches none of
    the pipeline machinery."""
    got, ref, st, st_off = _ab(tmp_path, how)
    pd.testing.assert_frame_equal(got, ref)
    assert st["pipelined_joins"] == 1 and st["joins_spill"] == 1
    assert st_off["pipelined_joins"] == 0
    assert st_off["mem_buckets"] == 0 and st_off["group_joins"] == 0


def test_kill_switch_span_multiset_is_serial(tmp_path):
    """pipeline.enabled=false restores the PR 8 span shape exactly: one
    shuffle.partition per side and one shuffle.bucket span per bucket id
    0..P-1, in order."""
    from fugue_tpu.obs import get_tracer

    left, right = _frames(seed=3)
    tr = get_tracer()
    tr.clear()
    tr.enable()
    try:
        off = _engine(tmp_path, **{FUGUE_TPU_CONF_SHUFFLE_PIPELINE_ENABLED: False})
        off.join(off.to_df(left), off.to_df(right), how="inner", on=["k"]).as_pandas()
        recs = tr.records()
        parts = [r for r in recs if r["name"] == "shuffle.partition"]
        assert {r["args"]["side"] for r in parts} == {"left", "right"}
        buckets = [r["args"]["bucket"] for r in recs if r["name"] == "shuffle.bucket"]
        assert buckets == list(range(len(buckets))) and len(buckets) > 0
        assert all("pairs" not in r["args"] for r in recs if r["name"] == "shuffle.bucket")
    finally:
        tr.disable()
        tr.clear()


def test_mem_tier_serves_buckets_without_disk(tmp_path):
    """Under an ample ledger every bucket stays memory-resident: reads
    are mem hits, nothing flows through the write-behind writer, and
    bytes_spilled accounts the mem-resident payload."""
    got, ref, st, _ = _ab(tmp_path, "inner", seed=4)
    pd.testing.assert_frame_equal(got, ref)
    assert st["mem_buckets"] > 0
    assert st["mem_bucket_hits"] > 0
    assert st["mem_demotions"] == 0
    assert st["writebehind_batches"] == 0
    assert st["bytes_spilled"] == st["mem_bucket_bytes"] > 0


def test_mem_ledger_pressure_demotes_largest_first(tmp_path):
    """A deliberately tiny ledger forces demotions: demoted buckets take
    the write-behind disk path with the full publish discipline, results
    stay bit-identical, and the ledger bound holds (used <= cap)."""
    got, ref, st, _ = _ab(
        tmp_path,
        "inner",
        seed=5,
        on_conf={FUGUE_TPU_CONF_SHUFFLE_MEM_BUCKET_BYTES: 4096},
    )
    pd.testing.assert_frame_equal(got, ref)
    assert st["mem_demotions"] > 0
    assert st["writebehind_batches"] > 0
    assert not glob.glob(os.path.join(str(tmp_path), "shuffle-*")), "spill dir leaked"


def test_mem_tier_disabled_by_negative_conf(tmp_path):
    """mem_bucket_bytes < 0 turns the tier off: all buckets go through
    the write-behind writer, still pipelined, still bit-identical."""
    got, ref, st, _ = _ab(
        tmp_path,
        "left_outer",
        seed=6,
        on_conf={FUGUE_TPU_CONF_SHUFFLE_MEM_BUCKET_BYTES: -1},
    )
    pd.testing.assert_frame_equal(got, ref)
    assert st["mem_buckets"] == 0 and st["mem_bucket_bytes"] == 0
    assert st["writebehind_batches"] > 0 and st["pipelined_joins"] == 1


def test_grouped_pairs_share_kernel_launches(tmp_path):
    """With budget headroom, adjacent device-eligible pairs coalesce:
    fewer kernel launches (group_joins) than bucket pairs (bucket_joins),
    results bit-identical, and the measured peak stays under budget."""
    got, ref, st, _ = _ab(
        tmp_path,
        "inner",
        seed=7,
        n=20000,
        key_range=60000,  # mostly 1:1 matches: expansion stays near 1x
        on_conf={
            FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET: 400_000,
            FUGUE_TPU_CONF_SHUFFLE_BUCKET_BYTES: 4096,
        },
    )
    pd.testing.assert_frame_equal(got, ref)
    assert st["bucket_joins"] > st["group_joins"] > 0
    assert 0 < st["peak_device_bytes"] < 400_000


def test_dup_heavy_group_sizing_respects_budget(tmp_path):
    """8x-duplicate keys: the expansion output dwarfs the ingest bytes,
    so the measured per-pair peak must keep groups small — the budget
    bound holds even though the static ingest estimate says ~10 pairs
    would fit (regression for the guessed-margin sizing)."""
    got, ref, st, _ = _ab(
        tmp_path,
        "inner",
        seed=14,
        n=20000,
        on_conf={
            FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET: 400_000,
            FUGUE_TPU_CONF_SHUFFLE_BUCKET_BYTES: 4096,
        },
    )
    pd.testing.assert_frame_equal(got, ref)
    assert 0 < st["peak_device_bytes"] < 400_000, st["peak_device_bytes"]


def test_empty_side_buckets_interleave_with_groups(tmp_path):
    """Outer joins over skewed keys: many buckets exist only on the left
    side (host-joined singletons) and interleave with device groups —
    output order is bucket order either way and values match the
    kill-switch exactly."""
    got, ref, st, _ = _ab(tmp_path, "left_outer", seed=8, right_keys=40)
    pd.testing.assert_frame_equal(got, ref)
    assert st["pipelined_joins"] == 1


def test_pair_prefetch_depth_parity(tmp_path):
    """An explicit pair-prefetch depth exercises the threaded producer
    (read+decode+pad+ingest off-thread) — bit-identical, no deadlock,
    spill dir cleaned."""
    got, ref, st, _ = _ab(
        tmp_path,
        "inner",
        seed=9,
        on_conf={FUGUE_TPU_CONF_SHUFFLE_PREFETCH_DEPTH: 2},
    )
    pd.testing.assert_frame_equal(got, ref)
    assert st["pipelined_joins"] == 1
    assert not glob.glob(os.path.join(str(tmp_path), "shuffle-*")), "spill dir leaked"


def test_writebehind_poison_tears_and_recovers(tmp_path):
    """shuffle.spill faults fired FROM THE BACKGROUND WRITER (mem tier
    off, so every bucket publishes through it) tear individual buckets;
    the reader recovers exactly those from the replayable source and the
    join still matches the kill-switch."""
    left, right = _frames(seed=10, nulls=False)
    eng = _engine(
        tmp_path,
        **{
            FUGUE_TPU_CONF_SHUFFLE_MEM_BUCKET_BYTES: -1,
            FUGUE_TPU_CONF_FAULT_PLAN: "shuffle.spill=error@2",
        },
    )
    got = _norm(eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"]))
    off = _engine(tmp_path, **{FUGUE_TPU_CONF_SHUFFLE_PIPELINE_ENABLED: False})
    ref = _norm(off.join(off.to_df(left), off.to_df(right), how="inner", on=["k"]))
    pd.testing.assert_frame_equal(got, ref[list(got.columns)])
    st = eng.stats()["shuffle"]
    assert st["spill_faults"] == 2
    assert st["bucket_recoveries"] == 2
    assert not glob.glob(os.path.join(str(tmp_path), "shuffle-*")), "spill dir leaked"


def test_mem_tier_poison_drops_and_recovers(tmp_path):
    """The mem tier's form of a torn publish: an injected fault at
    retention DROPS the bucket and the reader repartitions it from the
    source — same recovery ladder, zero disk involvement."""
    left, right = _frames(seed=11, nulls=False)
    eng = _engine(
        tmp_path, **{FUGUE_TPU_CONF_FAULT_PLAN: "shuffle.spill=error@3"}
    )
    got = _norm(eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"]))
    off = _engine(tmp_path, **{FUGUE_TPU_CONF_SHUFFLE_PIPELINE_ENABLED: False})
    ref = _norm(off.join(off.to_df(left), off.to_df(right), how="inner", on=["k"]))
    pd.testing.assert_frame_equal(got, ref[list(got.columns)])
    st = eng.stats()["shuffle"]
    assert st["spill_faults"] == 3
    assert st["bucket_recoveries"] == 3


def test_writebehind_poison_surfaces_for_one_pass_stream(tmp_path):
    """Mirror of the PR 2 poison-chunk no-deadlock proof for the
    write-behind path: every publish torn (error@999), the source is a
    one-pass stream (not replayable) — the poison SURFACES in the
    consumer as the descriptive recovery error, nothing deadlocks, and
    the failure path leaves no spill dir or orphaned tmp file."""
    left, right = _frames(n=1000, seed=12, nulls=False)
    ltbl = pa.Table.from_pandas(left, preserve_index=False)
    eng = _engine(
        tmp_path, **{FUGUE_TPU_CONF_FAULT_PLAN: "shuffle.spill=error@999"}
    )
    stream = LocalDataFrameIterableDataFrame(
        (ArrowDataFrame(ltbl.slice(s, 200)) for s in range(0, 1000, 200)),
        schema=ArrowDataFrame(ltbl).schema,
    )
    with pytest.raises(FugueTPUError, match="one-pass stream"):
        res = eng.join(stream, eng.to_df(right), how="left_outer", on=["k"])
        res.as_pandas()
    assert not glob.glob(os.path.join(str(tmp_path), "shuffle-*")), "spill dir leaked"


def test_writer_failure_propagates_with_original_traceback(tmp_path):
    """A hard failure ON the writer thread (not an absorbed publish
    fault) re-raises from submit/finalize with the writer-thread frames
    intact, removes every tmp it created, and never deadlocks a blocked
    submitter."""
    import traceback

    from fugue_tpu.shuffle.pipeline import SpillWriter

    schema = pa.schema([("x", pa.int64())])
    w = SpillWriter(str(tmp_path), "left", schema, depth=2)
    w.submit(0, object())  # not a table: write_table raises on the thread
    with pytest.raises(Exception) as ei:
        for n in range(50):  # a dead writer must never block submitters
            w.submit(1, pa.table({"x": [n]}))
    frames = traceback.extract_tb(ei.value.__traceback__)
    assert any("_run" in f.name for f in frames), "writer-thread frames lost"
    assert not glob.glob(os.path.join(str(tmp_path), "*.tmp")), "tmp orphaned"
    with pytest.raises(Exception):
        w.finalize()  # the failure stays sticky


def test_spill_dir_bytes_excludes_tmp(tmp_path):
    """Regression (ISSUE 15 satellite): the sampler probe must not count
    ``*.tmp`` — during the temp-write+rename window (and for the whole
    write-behind pass) tmp and published bytes coexist and the probe
    double-counted the bucket."""
    from fugue_tpu.shuffle.partitioner import new_spill_dir, spill_dir_bytes

    d = new_spill_dir(str(tmp_path))
    with open(os.path.join(d, "left_00000.arrow"), "wb") as f:
        f.write(b"x" * 100)
    with open(os.path.join(d, "left_00001.arrow.tmp"), "wb") as f:
        f.write(b"y" * 5000)
    assert spill_dir_bytes([d]) == 100


def test_repartition_pipelined_keeps_keys_whole(tmp_path):
    """Pipelined spill repartition (read-ahead + mem tier) keeps the
    one-bucket-per-chunk contract: every key lives in exactly ONE chunk
    and the union round-trips."""
    from fugue_tpu.collections import PartitionSpec

    rng = np.random.default_rng(13)
    n = 5000
    pdf = pd.DataFrame({"k": rng.integers(0, 61, n), "v": rng.normal(size=n)})
    eng = _engine(tmp_path, **{FUGUE_TPU_CONF_SHUFFLE_PREFETCH_DEPTH: 2})
    res = eng.repartition(eng.to_df(pdf), PartitionSpec(algo="hash", by=["k"]))
    seen = set()
    parts = []
    for sub in res.native:
        tbl = sub.as_arrow()
        keys = set(tbl.column("k").to_pylist())
        assert not (keys & seen), "key split across chunks"
        seen |= keys
        parts.append(tbl.to_pandas())
    got = pd.concat(parts).sort_values(["k", "v"]).reset_index(drop=True)
    exp = pdf.sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp.astype(got.dtypes.to_dict()))
    st = eng.stats()["shuffle"]
    assert st["mem_bucket_hits"] > 0
    assert not glob.glob(os.path.join(str(tmp_path), "shuffle-*")), "spill dir leaked"


# ---------------------------------------------------------------------------
# adaptive tuning of the pipeline knobs (docs/tuning.md)
# ---------------------------------------------------------------------------


def test_adjust_pipeline_deepens_when_consumer_starved():
    from fugue_tpu.tuning.tuner import adjust_pipeline

    adj = adjust_pipeline(
        1,
        1 << 28,
        {
            "pipe_chunks": 40,
            "wall_s": 2.0,
            "pipe_producer_wait_s": 0.01,
            "pipe_consumer_wait_s": 1.0,
        },
    )
    assert adj["pair_depth"] == 2 and not adj["converged"]
    # producer starved -> shallower, down to serial consumption
    adj = adjust_pipeline(
        2,
        1 << 28,
        {
            "pipe_chunks": 40,
            "wall_s": 2.0,
            "pipe_producer_wait_s": 1.0,
            "pipe_consumer_wait_s": 0.01,
        },
    )
    assert adj["pair_depth"] == 1
    # too fast to measure -> no adjustment
    assert (
        adjust_pipeline(1, 1 << 28, {"pipe_chunks": 40, "wall_s": 0.01}) is None
    )


def test_adjust_pipeline_mem_budget_tracks_pressure():
    from fugue_tpu.tuning.tuner import MEM_BYTES_MAX, MEM_BYTES_MIN, adjust_pipeline

    grown = adjust_pipeline(
        0,
        1 << 27,
        {"pipe_chunks": 10, "wall_s": 1.0, "mem_demotions": 5, "mem_bytes_used": 1 << 27},
    )
    assert grown["mem_bytes"] == 1 << 28
    shrunk = adjust_pipeline(
        0,
        1 << 29,
        {"pipe_chunks": 10, "wall_s": 1.0, "mem_demotions": 0, "mem_bytes_used": 1 << 20},
    )
    assert MEM_BYTES_MIN <= shrunk["mem_bytes"] < 1 << 29
    capped = adjust_pipeline(
        0,
        MEM_BYTES_MAX,
        {"pipe_chunks": 10, "wall_s": 1.0, "mem_demotions": 3, "mem_bytes_used": MEM_BYTES_MAX},
    )
    assert capped["mem_bytes"] == MEM_BYTES_MAX and capped["converged"]


def test_learned_pipeline_params_resolve_and_render(tmp_path):
    """A seeded store entry supplies pair_depth/mem_bytes to the next
    run of the same plan; describe_tuning renders them."""
    from types import SimpleNamespace

    from fugue_tpu.constants import FUGUE_TPU_CONF_TUNING_PATH
    from fugue_tpu.tuning.tuner import Tuner, describe_tuning, run_scope

    conf = {FUGUE_TPU_CONF_TUNING_PATH: os.path.join(str(tmp_path), "t.json")}
    tuner = Tuner(conf)
    tuner.store.publish(
        "planfp0000000000",
        lambda e: dict(
            e,
            joins={
                "join": {
                    "pair_depth": 3,
                    "mem_bytes": 123456,
                    "obs": 2,
                    "pipe_evidence": "seeded",
                }
            },
        ),
    )
    engine = SimpleNamespace(tuner=tuner, conf=conf)
    with run_scope(engine, "planfp0000000000", conf):
        handle = tuner.join_params(None, None, None)[3]
        d, m = handle.pipeline_params(conf, 0, 999)
    assert (d, m) == (3, 123456)
    text = "\n".join(describe_tuning(conf, "planfp0000000000", engine))
    assert "pair_depth=3" in text and "mem_bytes=123456" in text
