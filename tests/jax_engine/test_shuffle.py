"""Device shuffle (repartition via all_to_all exchange) tests."""

import numpy as np
import pandas as pd
import pytest

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.jax import JaxDataFrame, JaxExecutionEngine
from fugue_tpu.parallel.mesh import ROW_AXIS, num_row_shards


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


def _shard_rows(jdf: JaxDataFrame):
    """Valid row count and key values per shard block."""
    import jax

    shards = num_row_shards(jdf.mesh)
    valid = np.asarray(jax.device_get(jdf.device_valid_mask()))
    per_shard = valid.reshape(shards, -1)
    return per_shard


def test_even_repartition_balances(engine):
    # skewed ingestion: all rows sit in the low shards after a filter
    pdf = pd.DataFrame({"a": np.arange(800, dtype=np.int64)})
    jdf = engine.to_df(pdf)
    from fugue_tpu.column import col

    skewed = engine.filter(jdf, col("a") < 100)  # only low shards populated
    res = engine.repartition(skewed, PartitionSpec(algo="even", num=8))
    assert isinstance(res, JaxDataFrame)
    per_shard = _shard_rows(res).sum(axis=1)
    assert per_shard.sum() == 100
    assert per_shard.max() - per_shard.min() <= np.ceil(100 / len(per_shard))
    # content preserved
    got = sorted(res.as_pandas()["a"].tolist())
    assert got == list(range(100))


def test_hash_repartition_colocates_keys(engine):
    import jax

    rng = np.random.default_rng(0)
    pdf = pd.DataFrame(
        {
            "k": rng.integers(0, 37, 1000),
            "v": rng.random(1000),
        }
    )
    jdf = engine.to_df(pdf)
    res = engine.repartition(jdf, PartitionSpec(algo="hash", by=["k"]))
    assert isinstance(res, JaxDataFrame)
    shards = num_row_shards(res.mesh)
    valid = np.asarray(jax.device_get(res.device_valid_mask())).reshape(
        shards, -1
    )
    keys = np.asarray(jax.device_get(res.device_cols["k"])).reshape(shards, -1)
    seen = {}
    for s in range(shards):
        for k in np.unique(keys[s][valid[s]]):
            assert seen.setdefault(int(k), s) == s, "key split across shards"
    # all rows preserved with their values
    got = res.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    exp = pdf.sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp)


def test_multi_key_hash_repartition(engine):
    rng = np.random.default_rng(1)
    pdf = pd.DataFrame(
        {
            "a": rng.integers(0, 5, 300),
            "b": rng.random(300).round(1),  # float key column
            "v": np.arange(300, dtype=np.int64),
        }
    )
    jdf = engine.to_df(pdf)
    res = engine.repartition(jdf, PartitionSpec(algo="hash", by=["a", "b"]))
    got = res.as_pandas().sort_values("v").reset_index(drop=True)
    exp = pdf.sort_values("v").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp)


def test_rand_repartition_preserves_rows(engine):
    pdf = pd.DataFrame({"a": np.arange(500, dtype=np.int64)})
    jdf = engine.to_df(pdf)
    res = engine.repartition(jdf, PartitionSpec(algo="rand", num=8))
    assert sorted(res.as_pandas()["a"].tolist()) == list(range(500))


def test_string_frames_exchange_and_host_frames_unchanged(engine):
    import pyarrow as pa

    # strings are dict-encoded on device → they move with the exchange
    pdf = pd.DataFrame({"a": [1, 2, 3], "s": ["x", "y", "z"]})
    jdf = engine.to_df(pdf)
    res = engine.repartition(jdf, PartitionSpec(algo="hash", by=["a"]))
    assert res is not jdf
    got = res.as_pandas().sort_values("a").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, pdf)
    # nested columns stay host-resident → layout unchanged, logged
    tbl = pa.table({"a": [1, 2, 3], "l": [[1], [2, 2], [3]]})
    hjdf = engine.to_df(tbl)
    assert engine.repartition(hjdf, PartitionSpec(algo="hash", by=["a"])) is hjdf
    num = engine.to_df(pd.DataFrame({"a": [1, 2, 3]}))
    assert engine.repartition(num, PartitionSpec(algo="coarse", num=4)) is num


def test_repartition_then_aggregate(engine):
    """The shuffle composes with the device aggregate."""
    rng = np.random.default_rng(2)
    pdf = pd.DataFrame({"k": rng.integers(0, 11, 400), "v": rng.random(400)})
    from fugue_tpu.column import col, functions as f

    jdf = engine.repartition(
        engine.to_df(pdf), PartitionSpec(algo="hash", by=["k"])
    )
    res = engine.aggregate(
        jdf, PartitionSpec(by=["k"]), [f.sum(col("v")).alias("s")]
    )
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    exp = pdf.groupby("k").agg(s=("v", "sum")).reset_index()
    assert np.allclose(got["s"], exp["s"])
