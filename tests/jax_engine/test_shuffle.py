"""Device shuffle (repartition via all_to_all exchange) tests."""

import numpy as np
import pandas as pd
import pytest

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.jax import JaxDataFrame, JaxExecutionEngine
from fugue_tpu.parallel.mesh import ROW_AXIS, num_row_shards


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


def _shard_rows(jdf: JaxDataFrame):
    """Valid row count and key values per shard block."""
    import jax

    shards = num_row_shards(jdf.mesh)
    valid = np.asarray(jax.device_get(jdf.device_valid_mask()))
    per_shard = valid.reshape(shards, -1)
    return per_shard


def test_even_repartition_balances(engine):
    # skewed ingestion: all rows sit in the low shards after a filter
    pdf = pd.DataFrame({"a": np.arange(800, dtype=np.int64)})
    jdf = engine.to_df(pdf)
    from fugue_tpu.column import col

    skewed = engine.filter(jdf, col("a") < 100)  # only low shards populated
    res = engine.repartition(skewed, PartitionSpec(algo="even", num=8))
    assert isinstance(res, JaxDataFrame)
    per_shard = _shard_rows(res).sum(axis=1)
    assert per_shard.sum() == 100
    assert per_shard.max() - per_shard.min() <= np.ceil(100 / len(per_shard))
    # content preserved
    got = sorted(res.as_pandas()["a"].tolist())
    assert got == list(range(100))


def test_hash_repartition_colocates_keys(engine):
    import jax

    rng = np.random.default_rng(0)
    pdf = pd.DataFrame(
        {
            "k": rng.integers(0, 37, 1000),
            "v": rng.random(1000),
        }
    )
    jdf = engine.to_df(pdf)
    res = engine.repartition(jdf, PartitionSpec(algo="hash", by=["k"]))
    assert isinstance(res, JaxDataFrame)
    shards = num_row_shards(res.mesh)
    valid = np.asarray(jax.device_get(res.device_valid_mask())).reshape(
        shards, -1
    )
    keys = np.asarray(jax.device_get(res.device_cols["k"])).reshape(shards, -1)
    seen = {}
    for s in range(shards):
        for k in np.unique(keys[s][valid[s]]):
            assert seen.setdefault(int(k), s) == s, "key split across shards"
    # all rows preserved with their values
    got = res.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    exp = pdf.sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp)


def test_multi_key_hash_repartition(engine):
    rng = np.random.default_rng(1)
    pdf = pd.DataFrame(
        {
            "a": rng.integers(0, 5, 300),
            "b": rng.random(300).round(1),  # float key column
            "v": np.arange(300, dtype=np.int64),
        }
    )
    jdf = engine.to_df(pdf)
    res = engine.repartition(jdf, PartitionSpec(algo="hash", by=["a", "b"]))
    got = res.as_pandas().sort_values("v").reset_index(drop=True)
    exp = pdf.sort_values("v").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp)


def test_rand_repartition_preserves_rows(engine):
    pdf = pd.DataFrame({"a": np.arange(500, dtype=np.int64)})
    jdf = engine.to_df(pdf)
    res = engine.repartition(jdf, PartitionSpec(algo="rand", num=8))
    assert sorted(res.as_pandas()["a"].tolist()) == list(range(500))


def test_string_frames_exchange_and_host_frames_unchanged(engine):
    import pyarrow as pa

    # strings are dict-encoded on device → they move with the exchange
    pdf = pd.DataFrame({"a": [1, 2, 3], "s": ["x", "y", "z"]})
    jdf = engine.to_df(pdf)
    res = engine.repartition(jdf, PartitionSpec(algo="hash", by=["a"]))
    assert res is not jdf
    got = res.as_pandas().sort_values("a").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, pdf)
    # nested columns stay host-resident → layout unchanged, logged
    tbl = pa.table({"a": [1, 2, 3], "l": [[1], [2, 2], [3]]})
    hjdf = engine.to_df(tbl)
    assert engine.repartition(hjdf, PartitionSpec(algo="hash", by=["a"])) is hjdf
    num = engine.to_df(pd.DataFrame({"a": [1, 2, 3]}))
    assert engine.repartition(num, PartitionSpec(algo="coarse", num=4)) is num


def test_repartition_then_aggregate(engine):
    """The shuffle composes with the device aggregate."""
    rng = np.random.default_rng(2)
    pdf = pd.DataFrame({"k": rng.integers(0, 11, 400), "v": rng.random(400)})
    from fugue_tpu.column import col, functions as f

    jdf = engine.repartition(
        engine.to_df(pdf), PartitionSpec(algo="hash", by=["k"])
    )
    res = engine.aggregate(
        jdf, PartitionSpec(by=["k"]), [f.sum(col("v")).alias("s")]
    )
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    exp = pdf.groupby("k").agg(s=("v", "sum")).reset_index()
    assert np.allclose(got["s"], exp["s"])


# ===========================================================================
# Out-of-core spill shuffle (fugue_tpu/shuffle, docs/shuffle.md): on-disk
# hash buckets + bucket-at-a-time joins past device memory
# ===========================================================================

import glob
import os

import pyarrow as pa

from fugue_tpu.constants import (
    FUGUE_TPU_CONF_FAULT_PLAN,
    FUGUE_TPU_CONF_JOIN_BROADCAST_MAX_ROWS,
    FUGUE_TPU_CONF_SHUFFLE_BUCKET_BYTES,
    FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET,
    FUGUE_TPU_CONF_SHUFFLE_DIR,
    FUGUE_TPU_CONF_SHUFFLE_ENABLED,
)
from fugue_tpu.dataframe import ArrowDataFrame, LocalDataFrameIterableDataFrame
from fugue_tpu.exceptions import FugueTPUError

SPILL_HOWS = ["inner", "left_outer", "left_semi", "left_anti", "right_outer", "full_outer"]


def _spill_engine(tmp_path, budget=20_000, bucket=5_000, **conf):
    # this suite exercises the SPILL rung: small budgets would otherwise
    # land these sizes in the device_exchange band (budget × shards), so
    # pin that rung off — its own suite is test_device_exchange.py
    from fugue_tpu.constants import (
        FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED,
    )

    return JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET: budget,
            FUGUE_TPU_CONF_SHUFFLE_BUCKET_BYTES: bucket,
            FUGUE_TPU_CONF_SHUFFLE_DIR: str(tmp_path),
            FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED: False,
            **conf,
        }
    )


def _join_frames(n=4000, seed=0, nulls=True):
    """Dup keys (N:M expansion) and NULL keys in one pair of frames."""
    rng = np.random.default_rng(seed)
    lk = rng.integers(0, n // 8, n).astype(object)
    rk = rng.integers(0, n // 8, n).astype(object)
    if nulls:
        lk[:: 97] = None
        rk[:: 89] = None
    left = pd.DataFrame({"k": pd.array(lk, dtype="Int64"), "a": rng.normal(size=n)})
    right = pd.DataFrame({"k": pd.array(rk, dtype="Int64"), "b": rng.normal(size=n)})
    return left, right


def _norm(res):
    """Declared-schema arrow bytes -> sorted pandas: representation-free
    comparison (the spill path emits arrow-backed chunks, the legacy path
    device frames; both must carry the SAME schema and values)."""
    tbl = res.as_arrow() if not isinstance(res, pa.Table) else res
    # drop embedded pandas-dtype hints: equality is judged on the DECLARED
    # arrow schema + values, not on which pandas dtype produced them
    pdf = tbl.replace_schema_metadata(None).to_pandas()
    return pdf.sort_values(list(pdf.columns)).reset_index(drop=True)


@pytest.mark.parametrize("how", SPILL_HOWS)
def test_spill_join_parity_vs_legacy(tmp_path, how):
    """Bit-identical (same declared arrow schema, same sorted values) to
    the legacy ladder, across dup keys + NULL keys, for every
    hash-partitionable join type."""
    left, right = _join_frames()
    eng = _spill_engine(tmp_path)
    res = eng.join(eng.to_df(left), eng.to_df(right), how=how, on=["k"])
    got = _norm(res)
    assert eng.stats()["shuffle"]["joins_spill"] == 1, "spill strategy not used"
    off = JaxExecutionEngine({FUGUE_TPU_CONF_SHUFFLE_ENABLED: False})
    ref = off.join(off.to_df(left), off.to_df(right), how=how, on=["k"])
    refn = _norm(ref)[list(got.columns)]
    assert off.stats()["shuffle"]["joins_spill"] == 0
    pd.testing.assert_frame_equal(got, refn)


def test_spill_join_multi_key_and_cross_refusal(tmp_path):
    rng = np.random.default_rng(3)
    n = 3000
    left = pd.DataFrame(
        {"k1": rng.integers(0, 40, n), "k2": rng.integers(0, 7, n), "a": rng.normal(size=n)}
    )
    right = pd.DataFrame(
        {"k1": rng.integers(0, 40, n), "k2": rng.integers(0, 7, n), "b": rng.normal(size=n)}
    )
    eng = _spill_engine(tmp_path)
    res = eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k1", "k2"])
    got = _norm(res)
    exp = left.merge(right, on=["k1", "k2"])
    pd.testing.assert_frame_equal(got, _norm(pa.Table.from_pandas(exp, preserve_index=False)))
    assert eng.stats()["shuffle"]["joins_spill"] == 1
    # cross joins can't hash-partition: refused, legacy ladder answers
    c = eng.join(
        eng.to_df(pd.DataFrame({"x": range(10)})),
        eng.to_df(pd.DataFrame({"y": range(7)})),
        how="cross",
    )
    assert c.count() == 70
    assert eng.stats()["shuffle"]["joins_spill"] == 1  # unchanged


def test_spill_join_bounded_device_memory(tmp_path):
    """BOTH sides ~10x the device budget; measured peak_device_bytes stays
    under it — the out-of-core proof at unit-test scale."""
    budget = 1 << 20
    rng = np.random.default_rng(1)
    n = 700_000  # ~11.2MB/side at 16B/row vs a 1MiB budget
    left = pd.DataFrame({"k": rng.integers(0, 2_000_000, n), "a": rng.normal(size=n)})
    right = pd.DataFrame({"k": rng.integers(0, 2_000_000, n), "b": rng.normal(size=n)})
    side_bytes = int(left.memory_usage(index=False).sum())
    assert side_bytes >= 10 * budget
    eng = _spill_engine(tmp_path, budget=budget, bucket=0)  # auto bucket sizing
    res = eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"])
    got = res.as_pandas()
    exp = left.merge(right, on="k")
    assert len(got) == len(exp)
    st = eng.stats()["shuffle"]
    assert st["joins_spill"] == 1
    assert 0 < st["peak_device_bytes"] < budget, st["peak_device_bytes"]
    assert st["bytes_spilled"] >= 2 * side_bytes * 0.5  # both sides really spilled


def test_spill_repartition_round_trip(tmp_path):
    """Hash repartition past the budget: a one-pass stream where every key
    lives in exactly ONE chunk, whose union is the input."""
    rng = np.random.default_rng(5)
    n = 5000
    pdf = pd.DataFrame({"k": rng.integers(0, 61, n), "v": rng.normal(size=n)})
    eng = _spill_engine(tmp_path)
    res = eng.repartition(eng.to_df(pdf), PartitionSpec(algo="hash", by=["k"]))
    assert isinstance(res, LocalDataFrameIterableDataFrame)
    seen_keys = set()
    parts = []
    for sub in res.native:
        tbl = sub.as_arrow()
        keys = set(tbl.column("k").to_pylist())
        assert not (keys & seen_keys), "key split across chunks"
        seen_keys |= keys
        parts.append(tbl.to_pandas())
    got = pd.concat(parts).sort_values(["k", "v"]).reset_index(drop=True)
    exp = pdf.sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp.astype(got.dtypes.to_dict()))
    assert eng.stats()["shuffle"]["repartitions_spill"] == 1
    assert not glob.glob(os.path.join(str(tmp_path), "shuffle-*")), "spill dir leaked"


def test_spill_repartition_composes_with_map(tmp_path):
    """transform()-style per-partition processing over the spill layout:
    per-chunk grouping is globally correct because keys never split."""
    rng = np.random.default_rng(6)
    pdf = pd.DataFrame({"k": rng.integers(0, 23, 4000), "v": rng.random(4000)})
    # each spill chunk holds a DISJOINT key subset, so the streaming
    # aggregate's first-chunk key-range probe can't see the full domain:
    # declare it (the documented contract for arbitrary one-pass streams)
    from fugue_tpu.constants import FUGUE_TPU_CONF_STREAM_KEY_RANGE

    eng = _spill_engine(tmp_path, **{FUGUE_TPU_CONF_STREAM_KEY_RANGE: "0,22"})
    part = eng.repartition(eng.to_df(pdf), PartitionSpec(algo="hash", by=["k"]))
    from fugue_tpu.column import col, functions as f

    res = eng.aggregate(part, PartitionSpec(by=["k"]), [f.sum(col("v")).alias("s")])
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    exp = pdf.groupby("k").agg(s=("v", "sum")).reset_index()
    assert np.allclose(got["s"], exp["s"])


def test_torn_spill_recovery(tmp_path):
    """shuffle.spill faults tear individual bucket publishes; the reader
    deletes + repartitions ONLY those buckets and the join still matches;
    the spill dir is cleaned up afterwards."""
    left, right = _join_frames(seed=7)
    eng = _spill_engine(
        tmp_path, **{FUGUE_TPU_CONF_FAULT_PLAN: "shuffle.spill=error@3"}
    )
    res = eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"])
    got = _norm(res)
    off = JaxExecutionEngine({FUGUE_TPU_CONF_SHUFFLE_ENABLED: False})
    ref = _norm(off.join(off.to_df(left), off.to_df(right), how="inner", on=["k"]))
    pd.testing.assert_frame_equal(got, ref[list(got.columns)])
    st = eng.stats()["shuffle"]
    assert st["spill_faults"] == 3
    assert st["bucket_recoveries"] == 3
    assert st["spill_dirs_cleaned"] >= 1
    assert not glob.glob(os.path.join(str(tmp_path), "shuffle-*")), "spill dir leaked"


def test_poisoned_bucket_without_replay_raises_and_cleans(tmp_path):
    """A torn bucket whose source is a one-pass stream (not replayable)
    must raise a descriptive error — and the spill dir is removed on that
    FAILURE path too."""
    from fugue_tpu.shuffle.partitioner import new_spill_dir, spill_partition

    pdf = pd.DataFrame({"k": np.arange(100) % 7, "v": np.arange(100, dtype=np.float64)})
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    d = new_spill_dir(str(tmp_path))
    side = spill_partition(
        iter([tbl]), tbl.schema, ["k"], ["i"], 4, d, "left", replay=None
    )
    # poison one non-empty bucket: truncate to a torn IPC prefix
    i = next(i for i, r in enumerate(side.bucket_rows) if r > 0)
    with open(side.path(i), "r+b") as f:
        f.truncate(10)
    with pytest.raises(FugueTPUError, match="one-pass stream"):
        side.read_bucket(i)
    # the partitioner-level API leaves cleanup to the caller
    from fugue_tpu.shuffle.partitioner import remove_spill_dir

    remove_spill_dir(d)
    # the engine-level failure path removes the dir itself (gen's
    # finally) — exercise it with a stream source + guaranteed-torn
    # buckets
    eng = _spill_engine(
        tmp_path, **{FUGUE_TPU_CONF_FAULT_PLAN: "shuffle.spill=error@999"}
    )
    left, right = _join_frames(n=1000, seed=8, nulls=False)
    ltbl = pa.Table.from_pandas(left, preserve_index=False)
    stream = LocalDataFrameIterableDataFrame(
        (ArrowDataFrame(ltbl.slice(s, 200)) for s in range(0, 1000, 200)),
        schema=ArrowDataFrame(ltbl).schema,
    )
    # string second key makes the STREAMING join plan ineligible (one
    # numeric key only) -> spill path consumes the stream; every bucket
    # publish is torn and the stream can't replay -> error + cleanup
    with pytest.raises(FugueTPUError, match="one-pass stream"):
        res = eng.join(stream, eng.to_df(right), how="left_outer", on=["k"])
        res.as_pandas()
    assert not glob.glob(os.path.join(str(tmp_path), "shuffle-*")), "spill dir leaked"


def test_stream_join_spill_fallback_parity(tmp_path):
    """A one-pass stream the STREAMING join can't plan (duplicate build
    keys) now spills instead of materializing; results match the host
    oracle and the stream is consumed exactly once."""
    left, right = _join_frames(n=2000, seed=9, nulls=False)
    ltbl = pa.Table.from_pandas(left, preserve_index=False)
    eng = _spill_engine(tmp_path)
    stream = LocalDataFrameIterableDataFrame(
        (ArrowDataFrame(ltbl.slice(s, 256)) for s in range(0, 2000, 256)),
        schema=ArrowDataFrame(ltbl).schema,
    )
    # duplicate right keys -> streaming plan refuses (build keys must be
    # unique) -> shuffle_spill consumes the stream chunk-by-chunk
    res = eng.join(stream, eng.to_df(right), how="inner", on=["k"])
    got = _norm(res)
    exp = left.merge(right, on="k")
    pd.testing.assert_frame_equal(
        got, _norm(pa.Table.from_pandas(exp, preserve_index=False))[list(got.columns)]
    )
    assert eng.stats()["shuffle"]["joins_spill"] == 1


def test_shuffle_conf_gates(tmp_path):
    """fugue.tpu.shuffle.enabled=false restores the legacy ladder even
    past the budget; broadcast_max_rows is conf-driven."""
    left, right = _join_frames(n=2000, seed=10, nulls=False)
    off = JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_SHUFFLE_ENABLED: False,
            FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET: 1,  # everything "past" it
        }
    )
    res = off.join(off.to_df(left), off.to_df(right), how="inner", on=["k"])
    assert res.count() > 0
    assert off.stats()["shuffle"]["joins_spill"] == 0
    # conf broadcast threshold: 10-row cap forces the copartition branch
    from fugue_tpu.shuffle.strategy import broadcast_max_rows

    small = JaxExecutionEngine({FUGUE_TPU_CONF_JOIN_BROADCAST_MAX_ROWS: 10})
    assert broadcast_max_rows(small.conf) == 10
    from fugue_tpu.ops.join import MAX_BROADCAST_ROWS

    assert broadcast_max_rows(JaxExecutionEngine().conf) == MAX_BROADCAST_ROWS


def test_join_span_strategy_attr(tmp_path):
    """engine.join spans carry the chosen strategy: shuffle_spill past the
    budget, broadcast under the row cap."""
    from fugue_tpu.obs import get_tracer

    tr = get_tracer()
    tr.clear()
    tr.enable()
    try:
        left, right = _join_frames(n=2000, seed=11, nulls=False)
        eng = _spill_engine(tmp_path)
        eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"]).as_pandas()
        joins = [r for r in tr.records() if r["name"] == "engine.join"]
        assert joins and joins[-1]["args"]["strategy"] == "shuffle_spill"
        sh = [r for r in tr.records() if r["name"] == "shuffle.partition"]
        assert {r["args"]["side"] for r in sh} == {"left", "right"}
        assert any(r["name"] == "shuffle.bucket" for r in tr.records())
        tr.clear()
        big = JaxExecutionEngine()
        big.join(big.to_df(left), big.to_df(right), how="inner", on=["k"]).as_pandas()
        joins = [r for r in tr.records() if r["name"] == "engine.join"]
        assert joins and joins[-1]["args"]["strategy"] == "broadcast"
    finally:
        tr.disable()
        tr.clear()


def test_explain_shows_join_strategy(tmp_path):
    """Plan-time strategy prediction in workflow.explain() uses the SAME
    decision rule as the engine."""
    from fugue_tpu import FugueWorkflow

    left, right = _join_frames(n=2000, seed=12, nulls=False)
    eng = _spill_engine(tmp_path)
    dag = FugueWorkflow()
    dag.df(left).inner_join(dag.df(right))
    text = dag.explain(engine=eng)
    assert "strategy=shuffle_spill" in text
    dag2 = FugueWorkflow()
    dag2.df(left).inner_join(dag2.df(right))
    assert "strategy=broadcast" in dag2.explain(engine=JaxExecutionEngine())


def test_shuffle_stats_reset_and_probe(tmp_path):
    """engine.stats()['shuffle'] follows the reset contract; the sampler
    probe reports live spill-dir bytes (0 when idle)."""
    left, right = _join_frames(n=2000, seed=13, nulls=False)
    eng = _spill_engine(tmp_path)
    eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"]).as_pandas()
    st = eng.stats()["shuffle"]
    assert st["joins_spill"] == 1 and st["bytes_spilled"] > 0
    probes = eng._resource_probe_fns()
    assert "shuffle_spill_bytes" in probes
    assert probes["shuffle_spill_bytes"](eng) == 0.0  # consumed -> dir removed
    eng.reset_stats()
    st = eng.stats()["shuffle"]
    # device_budget_bytes / device_budget_source describe configuration,
    # not activity — they survive reset so a mis-detected budget stays
    # visible; every activity counter must drop to zero
    assert all(
        v == 0 for k, v in st.items() if not k.startswith("device_budget")
    ), st
    assert st["device_budget_bytes"] > 0 and st["device_budget_source"]


def test_negative_zero_keys_cobucket_and_join(tmp_path):
    """0.0 and -0.0 compare equal in the join kernels, so they must hash
    into the same bucket (regression: bit-pattern hashing split them and
    the spill join silently dropped their matches)."""
    from fugue_tpu.constants import FUGUE_TPU_CONF_SHUFFLE_BUCKETS
    from fugue_tpu.shuffle.partitioner import bucket_ids

    pz = pa.Table.from_pandas(pd.DataFrame({"k": [0.0]}), preserve_index=False)
    nz = pa.Table.from_pandas(pd.DataFrame({"k": [-0.0]}), preserve_index=False)
    assert (bucket_ids(pz, ["k"], ["f"], 64) == bucket_ids(nz, ["k"], ["f"], 64)).all()
    # the end-to-end repro: every key matches, so both paths return 3 rows
    left = pd.DataFrame({"k": [0.0, 1.0, 2.0], "a": [1, 2, 3]})
    right = pd.DataFrame({"k": [-0.0, 1.0, 2.0], "b": [4, 5, 6]})
    eng = _spill_engine(tmp_path, budget=1, **{FUGUE_TPU_CONF_SHUFFLE_BUCKETS: 8})
    got = _norm(eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"]))
    assert eng.stats()["shuffle"]["joins_spill"] == 1
    off = JaxExecutionEngine({FUGUE_TPU_CONF_SHUFFLE_ENABLED: False})
    ref = _norm(off.join(off.to_df(left), off.to_df(right), how="inner", on=["k"]))
    assert len(got) == 3
    pd.testing.assert_frame_equal(got, ref[list(got.columns)])


def test_tz_aware_keys_cobucket_across_timezones():
    """Equal instants carried in different timezones must co-bucket (the
    hash sees the UTC instant, not local wall-clock time); tz-naive keys
    keep their wall-clock int64 view."""
    from fugue_tpu.shuffle.partitioner import bucket_ids

    utc = pd.DataFrame(
        {"k": pd.to_datetime(["2026-01-01 00:00", "2026-06-01 12:34"]).tz_localize("UTC")}
    )
    est = utc.assign(k=utc["k"].dt.tz_convert("US/Eastern"))
    tu = pa.Table.from_pandas(utc, preserve_index=False)
    te = pa.Table.from_pandas(est, preserve_index=False)
    assert (bucket_ids(tu, ["k"], ["t"], 64) == bucket_ids(te, ["k"], ["t"], 64)).all()
    naive = pa.Table.from_pandas(
        pd.DataFrame({"k": pd.to_datetime(["2026-01-01", "2026-06-01"])}),
        preserve_index=False,
    )
    ids = bucket_ids(naive, ["k"], ["t"], 64)
    assert len(ids) == 2 and (ids >= 0).all()


def test_recovery_casts_replayed_chunks(tmp_path):
    """Bucket recovery must apply the same schema cast as the main spill
    path — a replay source whose chunks need casting (int32 -> int64)
    otherwise breaks exactly the resilience path it backs."""
    from fugue_tpu.shuffle.partitioner import (
        new_spill_dir,
        remove_spill_dir,
        spill_partition,
    )

    pdf = pd.DataFrame(
        {
            "k": (np.arange(50) % 5).astype(np.int32),
            "v": np.arange(50, dtype=np.float32),
        }
    )
    raw = pa.Table.from_pandas(pdf, preserve_index=False)
    schema = pa.schema([("k", pa.int64()), ("v", pa.float64())])
    d = new_spill_dir(str(tmp_path))
    side = spill_partition(
        iter([raw]), schema, ["k"], ["i"], 4, d, "left", replay=lambda: iter([raw])
    )
    i = next(i for i, r in enumerate(side.bucket_rows) if r > 0)
    with open(side.path(i), "r+b") as f:
        f.truncate(10)  # torn IPC prefix
    tbl = side.read_bucket(i)
    assert tbl.schema == schema and tbl.num_rows == side.bucket_rows[i]
    remove_spill_dir(d)


def test_spill_dir_bytes_tolerates_concurrent_mutation(tmp_path):
    """The sampler probe iterates the engine's LIVE spill-dir set while
    join threads mutate it: a raced snapshot retries, a persistently
    racing one reports 0 instead of breaking the sampler."""
    from fugue_tpu.shuffle.partitioner import new_spill_dir, spill_dir_bytes

    d = new_spill_dir(str(tmp_path))
    with open(os.path.join(d, "x.arrow"), "wb") as f:
        f.write(b"abcd")

    class FlakyOnce:
        def __init__(self, items):
            self.items, self.raised = items, False

        def __iter__(self):
            if not self.raised:
                self.raised = True
                raise RuntimeError("Set changed size during iteration")
            return iter(self.items)

    assert spill_dir_bytes(FlakyOnce([d])) == 4

    class AlwaysRacing:
        def __iter__(self):
            raise RuntimeError("Set changed size during iteration")

    assert spill_dir_bytes(AlwaysRacing()) == 0
