"""Device window functions: OVER clauses lowered onto the device sort +
segment machinery (jax/window.py), oracle-verified against the native
engine, with the device plan proven used (the host evaluator is poisoned).
"""

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


@pytest.fixture(scope="module")
def oracle():
    e = NativeExecutionEngine()
    yield e
    e.stop()


def _pd(res):
    return res.to_pandas() if hasattr(res, "to_pandas") else res


def _run_both(sql, df, engine, oracle, poison=True):
    # the host evaluator is poisoned ONLY for the jax-engine run: falling
    # back to pandas there would hide a broken device plan
    import unittest.mock as mock

    import fugue_tpu.column.window as w

    def boom(*a, **k):  # pragma: no cover
        raise AssertionError("host window evaluator used on the jax engine")

    if poison:
        with mock.patch.object(w, "eval_window", boom):
            got = _pd(fa.fugue_sql(sql, df=df, engine=engine, as_local=True))
    else:
        got = _pd(fa.fugue_sql(sql, df=df, engine=engine, as_local=True))
    exp = _pd(fa.fugue_sql(sql, df=df, engine=oracle, as_local=True))
    sort_cols = list(exp.columns)
    g = got.sort_values(sort_cols).reset_index(drop=True)
    x = exp.sort_values(sort_cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(g, x, check_dtype=False)
    return got


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(13)
    n = 500
    v = rng.random(n)
    v[rng.random(n) < 0.15] = np.nan  # NULLs in the aggregate argument
    return pd.DataFrame(
        {
            "k": rng.integers(0, 9, n),
            "o": rng.integers(0, 50, n),
            # r: unique tiebreaker — ROW_NUMBER/LAG over tied order keys is
            # legitimately nondeterministic, so tests order by (o, r)
            "r": rng.permutation(n).astype("int64"),
            "v": v,
        }
    )


def test_row_number_rank_dense(engine, oracle, data):
    _run_both(
        """
        SELECT k, o, v,
          ROW_NUMBER() OVER (PARTITION BY k ORDER BY o, r) AS rn,
          RANK() OVER (PARTITION BY k ORDER BY o) AS r,
          DENSE_RANK() OVER (PARTITION BY k ORDER BY o) AS dr
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_lag_lead(engine, oracle, data):
    _run_both(
        """
        SELECT k, o, v,
          LAG(v) OVER (PARTITION BY k ORDER BY o, r) AS l1,
          LAG(v, 2, -1.0) OVER (PARTITION BY k ORDER BY o, r) AS l2,
          LEAD(v) OVER (PARTITION BY k ORDER BY o, r) AS f1,
          LEAD(o, 1, 999) OVER (PARTITION BY k ORDER BY o, r) AS f2
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_running_aggregates(engine, oracle, data):
    _run_both(
        """
        SELECT k, o, v,
          SUM(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rs,
          COUNT(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rc,
          MIN(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rmin,
          MAX(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rmax,
          AVG(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS ra
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_range_peers_default_frame(engine, oracle, data):
    # ORDER BY without an explicit frame = RANGE UNBOUNDED..CURRENT — peer
    # rows (tied order keys) share the running value
    _run_both(
        """
        SELECT k, o,
          SUM(v) OVER (PARTITION BY k ORDER BY o) AS s,
          COUNT(v) OVER (PARTITION BY k ORDER BY o) AS c
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_whole_partition_aggregates(engine, oracle, data):
    _run_both(
        """
        SELECT k, v,
          SUM(v) OVER (PARTITION BY k) AS s,
          AVG(v) OVER (PARTITION BY k) AS m,
          MIN(v) OVER (PARTITION BY k) AS lo,
          MAX(v) OVER (PARTITION BY k) AS hi,
          COUNT(v) OVER (PARTITION BY k) AS c
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_first_last(engine, oracle, engine_data_nonan):
    _run_both(
        """
        SELECT k, o, w,
          FIRST(w) OVER (PARTITION BY k ORDER BY o, w) AS fv,
          LAST(w) OVER (PARTITION BY k ORDER BY o, w
                        ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS lv
        FROM df
        """,
        engine_data_nonan,
        engine,
        oracle,
    )


@pytest.fixture(scope="module")
def engine_data_nonan():
    rng = np.random.default_rng(14)
    n = 300
    return pd.DataFrame(
        {
            "k": rng.integers(0, 7, n),
            "o": rng.integers(0, 40, n),
            "w": rng.random(n),
        }
    )


def test_bounded_rows_frames(engine, oracle, data):
    # r must be in the projection: rows tied on (k, o) with NULL v are
    # indistinguishable to the output sort otherwise, and their
    # frame-dependent results legitimately differ per r
    _run_both(
        """
        SELECT k, o, r, v,
          SUM(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS s3,
          AVG(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS m3,
          COUNT(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN 2 PRECEDING AND 2 FOLLOWING) AS c5
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_window_after_where(engine, oracle, data):
    _run_both(
        """
        SELECT k, o, ROW_NUMBER() OVER (PARTITION BY k ORDER BY o, r) AS rn
        FROM df WHERE o > 10
        """,
        data,
        engine,
        oracle,
    )


def test_desc_order_and_nan_order_keys(engine, oracle):
    rng = np.random.default_rng(15)
    n = 200
    o = rng.random(n)
    o[rng.random(n) < 0.1] = np.nan  # NULL order keys rank last
    df = pd.DataFrame(
        {"k": rng.integers(0, 5, n), "o": o, "v": rng.random(n)}
    )
    _run_both(
        """
        SELECT k, o,
          RANK() OVER (PARTITION BY k ORDER BY o DESC) AS r,
          SUM(v) OVER (PARTITION BY k ORDER BY o DESC) AS s
        FROM df
        """,
        df,
        engine,
        oracle,
    )


def test_host_fallback_for_global_window(engine, oracle, data):
    # no PARTITION BY spans shards — host fallback (must still be correct)
    _run_both(
        "SELECT o, ROW_NUMBER() OVER (ORDER BY o, r) AS rn FROM df",
        data,
        engine,
        oracle,
        poison=False,
    )


def test_unbounded_to_following_frame(engine, oracle, data):
    # UNBOUNDED PRECEDING .. n FOLLOWING (review regression: None offset)
    _run_both(
        """
        SELECT k, o, v,
          SUM(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN UNBOUNDED PRECEDING AND 1 FOLLOWING) AS s,
          COUNT(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN 1 PRECEDING AND UNBOUNDED FOLLOWING) AS c
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_negative_lag_offset_host_fallback(engine, oracle, data):
    # negative offsets flip direction — device plan must decline (review
    # regression: it used to read past the partition end)
    _run_both(
        """
        SELECT k, o,
          LAG(v, -1, -99.0) OVER (PARTITION BY k ORDER BY o, r) AS x
        FROM df
        """,
        data,
        engine,
        oracle,
        poison=False,
    )


def test_int_aggregate_schema_fidelity(engine, oracle, data):
    # SUM over an int column: host keeps long — the device plan declines
    # rather than emit double (review regression)
    got = _pd(
        fa.fugue_sql(
            "SELECT k, SUM(o) OVER (PARTITION BY k) AS s FROM df",
            df=data,
            engine=engine,
            as_local=True,
        )
    )
    exp = _pd(
        fa.fugue_sql(
            "SELECT k, SUM(o) OVER (PARTITION BY k) AS s FROM df",
            df=data,
            engine=oracle,
            as_local=True,
        )
    )
    assert str(got["s"].dtype) == str(exp["s"].dtype)
    g = got.sort_values(["k", "s"]).reset_index(drop=True)
    x = exp.sort_values(["k", "s"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(g, x)


def test_string_partition_keys_device(engine, oracle):
    rng = np.random.default_rng(21)
    n = 300
    df = pd.DataFrame(
        {
            "g": rng.choice(["alpha", "beta", "gamma", "delta"], n),
            "o": rng.permutation(n).astype("int64"),
            "v": rng.random(n),
        }
    )
    _run_both(
        """
        SELECT g, o,
          ROW_NUMBER() OVER (PARTITION BY g ORDER BY o) AS rn,
          SUM(v) OVER (PARTITION BY g ORDER BY o) AS rs
        FROM df
        """,
        df,
        engine,
        oracle,
    )


def test_string_order_keys_with_nulls_device(engine, oracle):
    rng = np.random.default_rng(22)
    n = 200
    s = rng.choice(["a", "bb", "ccc", None], n, p=[0.3, 0.3, 0.3, 0.1])
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 5, n),
            "s": pd.array(s, dtype="str"),
            "v": rng.random(n),
        }
    )
    _run_both(
        """
        SELECT k, s,
          RANK() OVER (PARTITION BY k ORDER BY s) AS r,
          DENSE_RANK() OVER (PARTITION BY k ORDER BY s) AS dr
        FROM df
        """,
        df,
        engine,
        oracle,
    )


def test_string_order_desc_device(engine, oracle):
    rng = np.random.default_rng(23)
    n = 150
    s = rng.choice(["a", "bb", "ccc", None], n, p=[0.3, 0.3, 0.3, 0.1])
    df = pd.DataFrame(
        {"k": rng.integers(0, 4, n), "s": pd.array(s, dtype="str"),
         "v": rng.random(n)}
    )
    _run_both(
        """
        SELECT k, s,
          DENSE_RANK() OVER (PARTITION BY k ORDER BY s DESC) AS dr
        FROM df
        """,
        df,
        engine,
        oracle,
    )


def test_nullable_int_order_key_device(engine, oracle):
    rng = np.random.default_rng(24)
    n = 200
    o = pd.array(
        np.where(rng.random(n) < 0.15, None, rng.integers(0, 40, n)),
        dtype="Int64",
    )
    df = pd.DataFrame(
        {"k": rng.integers(0, 5, n), "o": o, "v": rng.random(n)}
    )
    _run_both(
        """
        SELECT k, o,
          RANK() OVER (PARTITION BY k ORDER BY o) AS r,
          SUM(v) OVER (PARTITION BY k ORDER BY o) AS s
        FROM df
        """,
        df,
        engine,
        oracle,
    )


def test_nullable_int_aggregate_arg_device(engine, oracle):
    rng = np.random.default_rng(25)
    n = 150
    m = pd.array(
        np.where(rng.random(n) < 0.25, None, rng.integers(0, 100, n)),
        dtype="Int64",
    )
    df = pd.DataFrame(
        {"k": rng.integers(0, 4, n), "o": rng.permutation(n), "m": m}
    )
    _run_both(
        """
        SELECT k, o,
          SUM(m) OVER (PARTITION BY k ORDER BY o) AS rs,
          COUNT(m) OVER (PARTITION BY k ORDER BY o) AS rc,
          AVG(m) OVER (PARTITION BY k) AS a
        FROM df
        """,
        df,
        engine,
        oracle,
    )


def test_nullable_int_order_desc_device(engine, oracle):
    rng = np.random.default_rng(26)
    n = 160
    o = pd.array(
        np.where(rng.random(n) < 0.2, None, rng.integers(0, 30, n)),
        dtype="Int64",
    )
    df = pd.DataFrame(
        {"k": rng.integers(0, 4, n), "o": o, "v": rng.random(n)}
    )
    _run_both(
        """
        SELECT k, o,
          DENSE_RANK() OVER (PARTITION BY k ORDER BY o DESC) AS dr,
          SUM(v) OVER (PARTITION BY k ORDER BY o DESC) AS s
        FROM df
        """,
        df,
        engine,
        oracle,
    )


def test_range_current_row_nullable_order_key(engine, oracle):
    # host-evaluator regression: pd.NA through .all() in bounded RANGE peers
    df = pd.DataFrame(
        {
            "k": [1, 1, 1, 1],
            "o": pd.array([1, 1, None, 2], dtype="Int64"),
            "v": [50.0, 51.0, 100.0, 1.0],
        }
    )
    r = _run_both(
        """
        SELECT k, o, v,
          SUM(v) OVER (PARTITION BY k ORDER BY o
                       RANGE BETWEEN CURRENT ROW AND CURRENT ROW) AS s
        FROM df
        """,
        df,
        engine,
        oracle,
        poison=False,
    )
    got = r.sort_values("v")
    assert got[got["v"] == 100.0]["s"].iloc[0] == 100.0  # NULL is its own peer
    assert got[got["v"] == 1.0]["s"].iloc[0] == 1.0
