"""Collective wrappers (``ops/collectives.py``).

The single-chip TPU tunnel's compiler lowers ONLY Sum all-reduces, so the
kernels route every cross-shard collective through axis-size-aware
wrappers: at size 1 everything becomes ``psum``; on multi-device meshes
whose platform has the same restriction, ``FUGUE_TPU_SUM_ONLY_COLLECTIVES=1``
emulates min/max/gather/all-to-all via one-hot ``psum``. The emulation is
correctness-tested here on the 8-device CPU mesh in a SUBPROCESS — the
flag is read at trace time and compiled kernels are cached per-process,
so flipping it inside this process would test nothing.
"""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DRIVER = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
from typing import Dict
import numpy as np
import pandas as pd
import fugue_tpu.api as fa
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.jax import JaxExecutionEngine, group_ops as go

eng = JaxExecutionEngine()
rng = np.random.default_rng(0)
pdf = pd.DataFrame({"k": rng.integers(0, 50, 20000), "v": rng.random(20000)})
jdf = eng.to_df(pdf)

res = eng.aggregate(jdf, PartitionSpec(by=["k"]),
    [ff.sum(col("v")).alias("s"), ff.min(col("v")).alias("lo"),
     ff.max(col("v")).alias("hi")]).as_pandas().sort_values("k")
exp = pdf.groupby("k").agg(
    s=("v", "sum"), lo=("v", "min"), hi=("v", "max")).reset_index()
assert np.allclose(res[["s", "lo", "hi"]], exp[["s", "lo", "hi"]])

other = pd.DataFrame({"k": np.arange(50), "w": np.arange(50) * 2.0})
j = eng.join(jdf, eng.to_df(other), how="inner", on=["k"]).as_pandas()
ej = pdf.merge(other, on="k")
assert len(j) == len(ej) and abs(j["w"].sum() - ej["w"].sum()) < 1e-6

rp = eng.repartition(jdf, PartitionSpec(algo="even", num=8)).as_pandas()
assert sorted(rp["v"].round(9)) == sorted(pdf["v"].round(9))

def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    m = go.mean(cols, cols["v"])
    return {"k": cols["k"], "v": cols["v"] - go.per_row(cols, m)}

out = fa.transform(jdf, demean, schema="k:long,v:double",
                   partition=PartitionSpec(by=["k"]), engine=eng)
g = out.as_pandas().groupby("k")["v"].mean().abs().max()
assert g < 1e-12, g
print("COLLECTIVES_OK")
"""


def _run(extra_env):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(extra_env)
    env["PYTHONPATH"] = _REPO
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER],
        capture_output=True,
        timeout=600,
        env=env,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "COLLECTIVES_OK" in proc.stdout


def test_sum_only_emulation_mode():
    """One-hot psum emulation gives identical results to native collectives
    across aggregate/join/repartition/keyed-map (incl. bool-dtype exchange
    masks, which psum upcasts to int32 — must be cast back)."""
    _run({"FUGUE_TPU_SUM_ONLY_COLLECTIVES": "1"})
