"""The double-buffered streaming ingest pipeline — `fugue_tpu/jax/pipeline.py`.

Proves the ISSUE 2 contracts:

- every prefetched streaming path is BIT-IDENTICAL to the serial
  (`prefetch_depth=0`) path: aggregate, compiled map, keyed compiled map,
  take;
- producer-thread exceptions propagate to the consumer with the ORIGINAL
  traceback;
- the queue depth bound holds under a slow consumer (bounded read-ahead);
- a FaultInjector poison chunk (`stream.chunk=error`) raises cleanly —
  no deadlock, no hang;
- `engine.pipeline_stats` and `engine.jit_cache_stats` observe real runs;
- the pipelined bulk `to_df` ingest round-trips identically to serial.
"""

import time
import traceback
from typing import Dict

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import jax

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_FAULT_PLAN,
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
    FUGUE_TPU_CONF_STREAM_PREFETCH_DEPTH,
)
from fugue_tpu.dataframe import (
    ArrowDataFrame,
    LocalDataFrameIterableDataFrame,
    PandasDataFrame,
)
from fugue_tpu.jax import JaxExecutionEngine, pipeline, streaming
from fugue_tpu.resilience import InjectedFaultError

CHUNK = 2048

AGGS = [
    ff.sum(col("v")).alias("sv"),
    ff.count(col("v")).alias("n"),
    ff.avg(col("v")).alias("m"),
]


def _engine(depth: int, **conf):
    return JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: CHUNK,
            FUGUE_TPU_CONF_STREAM_PREFETCH_DEPTH: depth,
            **conf,
        }
    )


def _frame(n: int = 30_000, groups: int = 128, seed: int = 3) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {"k": rng.integers(0, groups, n), "v": rng.random(n)}
    )


def _stream(pdf: pd.DataFrame, n_chunks: int = 11) -> LocalDataFrameIterableDataFrame:
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    step = max(1, (tbl.num_rows + n_chunks - 1) // n_chunks)
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(step, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, step)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


# --------------------------------------------------------------------------
# bit-identical parity: prefetched vs serial, all four streaming paths
# --------------------------------------------------------------------------


def test_prefetch_aggregate_bit_identical():
    pdf = _frame()
    spec = PartitionSpec(by=["k"])
    frames = {}
    for depth in (0, 2):
        e = _engine(depth)
        try:
            res = e.aggregate(_stream(pdf), spec, AGGS)
            frames[depth] = (
                res.as_pandas().sort_values("k").reset_index(drop=True)
            )
            if depth > 0:
                run = e.pipeline_stats.last_run
                assert run["verb"] == "aggregate"
                assert run["chunks_prefetched"] >= 11
        finally:
            e.stop_engine()
    pd.testing.assert_frame_equal(frames[0], frames[2])  # exact, dtypes too
    assert streaming.last_run_stats["rows"] == len(pdf)


def test_prefetch_compiled_map_bit_identical():
    import fugue_tpu.api as fa

    pdf = _frame()

    def fn(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {"k": cols["k"], "v2": cols["v"] * 2.0 + cols["k"]}

    frames = {}
    for depth in (0, 2):
        e = _engine(depth)
        try:
            out = fa.transform(
                _stream(pdf),
                fn,
                schema="k:long,v2:double",
                engine=e,
                as_fugue=True,
            )
            assert isinstance(out, LocalDataFrameIterableDataFrame)
            frames[depth] = out.as_pandas()
            if depth > 0:
                assert e.pipeline_stats.last_run["verb"] == "map"
        finally:
            e.stop_engine()
    pd.testing.assert_frame_equal(frames[0], frames[2])


def test_prefetch_keyed_map_bit_identical():
    import fugue_tpu.api as fa

    from fugue_tpu.jax import group_ops as go

    rng = np.random.default_rng(9)
    pdf = pd.DataFrame(
        {"k": np.repeat(np.arange(40), rng.integers(5, 120, 40))}
    )
    pdf["v"] = rng.random(len(pdf))

    def stream():
        def gen():
            for s in range(0, len(pdf), 333):
                yield PandasDataFrame(pdf.iloc[s : s + 333], "k:long,v:double")

        return LocalDataFrameIterableDataFrame(gen(), schema="k:long,v:double")

    def fn(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {
            "k": cols["k"],
            "rn": go.row_number(cols),
            "rs": go.running_sum(cols, cols["v"]),
        }

    frames = {}
    for depth in (0, 2):
        e = _engine(depth)
        try:
            out = fa.transform(
                stream(),
                fn,
                schema="k:long,rn:long,rs:double",
                partition=PartitionSpec(by=["k"], presort="v"),
                engine=e,
                as_fugue=True,
            )
            frames[depth] = out.as_pandas()
            if depth > 0:
                assert e.pipeline_stats.last_run["verb"] == "keyed_map"
        finally:
            e.stop_engine()
    pd.testing.assert_frame_equal(frames[0], frames[2])


def test_prefetch_take_bit_identical_and_early_stop():
    pdf = _frame()
    frames = {}
    pulled = {0: 0, 2: 0}
    for depth in (0, 2):
        e = _engine(depth)

        def counting_stream(d=depth):
            def gen():
                for s in range(0, len(pdf), CHUNK):
                    pulled[d] += 1
                    yield PandasDataFrame(
                        pdf.iloc[s : s + CHUNK], "k:long,v:double"
                    )

            return LocalDataFrameIterableDataFrame(
                gen(), schema="k:long,v:double"
            )

        try:
            # presorted take: full consumption, order-deterministic output
            res = e.take(counting_stream(), n=7, presort="v desc")
            frames[depth] = res.as_pandas().reset_index(drop=True)
            # unsorted global take: early stop must bound read-ahead
            before = pulled[depth]
            e.take(counting_stream(), n=5, presort=None)
            consumed = pulled[depth] - before
            # 5 rows fit in the first chunk; serial pulls 1, prefetch may
            # read ahead at most depth+1 chunks beyond it
            assert consumed <= 1 + depth + 2
        finally:
            e.stop_engine()
    pd.testing.assert_frame_equal(frames[0], frames[2])


# --------------------------------------------------------------------------
# prefetcher unit contracts
# --------------------------------------------------------------------------


def test_producer_exception_propagates_with_original_traceback():
    def poisoned_source():
        yield 1
        yield 2
        raise ValueError("poison chunk #3")

    pf = pipeline.maybe_prefetch(poisoned_source(), depth=2)
    assert next(pf) == 1
    assert next(pf) == 2
    with pytest.raises(ValueError, match="poison chunk #3") as ei:
        next(pf)
    # the producer-side frame must be visible in the traceback
    frames = traceback.extract_tb(ei.value.__traceback__)
    assert any(f.name == "poisoned_source" for f in frames)


def test_bounded_queue_depth_under_slow_consumer():
    produced = []

    def src():
        for i in range(40):
            produced.append(i)
            yield i

    depth = 2
    pf = pipeline.maybe_prefetch(src(), depth=depth)
    got = []
    try:
        for x in pf:
            time.sleep(0.003)  # slow consumer: the producer must NOT run away
            got.append(x)
            # queue(depth) + one handed to consumer + one mid-produce
            assert len(produced) <= len(got) + depth + 2
    finally:
        pf.close()
    assert got == list(range(40))


def test_serial_mode_is_threadless_passthrough():
    it = pipeline.maybe_prefetch(iter([1, 2, 3]), depth=0)
    assert isinstance(it, pipeline._SerialChunks)
    assert list(it) == [1, 2, 3]
    it.close()  # no-op, must not raise


def test_abandoned_consumer_stops_producer():
    def src():
        for i in range(10_000):
            yield i

    pf = pipeline.maybe_prefetch(src(), depth=2)
    assert next(pf) == 0
    pf.close()  # consumer walks away mid-stream
    deadline = time.time() + 5
    while pf._thread.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    assert not pf._thread.is_alive(), "producer thread must terminate"


def test_poison_chunk_fault_injection_no_deadlock():
    """`stream.chunk=error` fires inside the producer thread; the consumer
    must see InjectedFaultError promptly — the bounded queue never hangs."""
    pdf = _frame(10_000)
    e = _engine(2, **{FUGUE_TPU_CONF_FAULT_PLAN: "stream.chunk=error@1"})
    try:
        t0 = time.time()
        with pytest.raises(InjectedFaultError, match="stream.chunk"):
            e.aggregate(_stream(pdf), PartitionSpec(by=["k"]), AGGS)
        assert time.time() - t0 < 30  # raised, not hung
    finally:
        e.stop_engine()
    # same engine conf minus the plan: the stream works fine
    e2 = _engine(2)
    try:
        res = e2.aggregate(_stream(pdf), PartitionSpec(by=["k"]), AGGS)
        assert res.as_pandas()["n"].sum() == len(pdf)
    finally:
        e2.stop_engine()


# --------------------------------------------------------------------------
# observability: pipeline_stats + jit cache counters
# --------------------------------------------------------------------------


def test_pipeline_stats_measures_overlap():
    stats = pipeline.PipelineStats()

    def slow_src():
        for i in range(20):
            time.sleep(0.004)  # host decode stand-in
            yield i

    pf = pipeline.maybe_prefetch(slow_src(), depth=2, stats=stats, verb="x")
    try:
        for _ in pf:
            time.sleep(0.004)  # device compute stand-in
    finally:
        pf.close()
    run = stats.last_run
    assert run["verb"] == "x"
    assert run["chunks_prefetched"] == 20
    assert run["producer_busy_s"] > 0
    # both sides busy ~80ms each, wall ≪ 160ms serial → real overlap
    assert 0.0 < run["overlap_fraction"] <= 1.0
    total = stats.as_dict()
    assert total["runs"] == 1
    assert total["chunks_prefetched"] == 20
    assert total["last_run"]["verb"] == "x"


def test_jit_cache_hit_miss_counters():
    pdf = _frame(8_192, groups=32)
    e = _engine(2)
    try:
        spec = PartitionSpec(by=["k"])
        e.aggregate(_stream(pdf, 4), spec, AGGS)
        s1 = e.jit_cache_stats
        assert s1["misses"] >= 1 and s1["entries"] >= 1
        e.aggregate(_stream(pdf, 4), spec, AGGS)
        s2 = e.jit_cache_stats
        assert s2["hits"] > s1["hits"]  # second run reuses the compiled step
        assert s2["entries"] == s1["entries"]
    finally:
        e.stop_engine()


# --------------------------------------------------------------------------
# pipelined bulk to_df ingest
# --------------------------------------------------------------------------


def test_pipelined_ingest_round_trip_identical():
    n = 1_500_000  # > the 8MB pipeline threshold
    rng = np.random.default_rng(11)
    v = rng.random(n)
    v[:100] = np.nan
    pdf = pd.DataFrame(
        {
            "k": rng.integers(0, 1000, n),
            "v": v,
            "s": pd.array(
                np.where(rng.random(n) < 0.5, "alpha", "beta"), dtype=object
            ),
        }
    )
    tables = {}
    for depth in (0, 3):
        e = _engine(depth)
        try:
            jdf = e.to_df(PandasDataFrame(pdf, "k:long,v:double,s:str"))
            assert len(jdf.device_cols) == 3  # forces (pipelined) ingest
            tables[depth] = jdf.as_arrow()
            if depth > 0:
                run = e.pipeline_stats.last_run
                assert run["verb"] == "ingest"
                assert run["chunks_prefetched"] == 3  # one per column
        finally:
            e.stop_engine()
    assert tables[0].schema == tables[3].schema
    assert tables[0].equals(tables[3])
