"""Device-resident dense aggregates: the result frame must stay on device
(no host materialization) and remain a first-class input to later device
ops. Mirrors the reference's aggregate contract
(/root/reference/fugue/execution/execution_engine.py:898-939) with the
finish running on the mesh instead of a backend SQL engine."""

import numpy as np
import pandas as pd
import pytest

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.jax import JaxExecutionEngine

SPEC = PartitionSpec(by=["k"])


@pytest.fixture(scope="module")
def eng():
    return JaxExecutionEngine()


def test_dense_aggregate_stays_on_device(eng):
    rng = np.random.default_rng(7)
    pdf = pd.DataFrame(
        {"k": rng.integers(0, 500, 50_000), "v": rng.random(50_000)}
    )
    res = eng.aggregate(
        eng.to_df(pdf),
        SPEC,
        [
            ff.sum(col("v")).alias("s"),
            ff.count(col("v")).alias("n"),
            ff.avg(col("v")).alias("m"),
            ff.min(col("v")).alias("lo"),
            ff.max(col("v")).alias("hi"),
        ],
    )
    # the proof of device residency: no host table, explicit valid mask
    assert res.host_table is None
    assert res.valid_mask is not None
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    exp = (
        pdf.groupby("k")
        .agg(
            s=("v", "sum"),
            n=("v", "count"),
            m=("v", "mean"),
            lo=("v", "min"),
            hi=("v", "max"),
        )
        .reset_index()
    )
    assert np.allclose(got[["s", "m", "lo", "hi"]], exp[["s", "m", "lo", "hi"]])
    assert (got["n"].to_numpy() == exp["n"].to_numpy()).all()


def test_all_null_group_and_sparse_range(eng):
    pdf = pd.DataFrame(
        {
            "k": np.array([5, 5, 900, 900, 42], dtype=np.int32),
            "v": [1.0, 2.0, np.nan, np.nan, 7.0],
        }
    )
    res = eng.aggregate(
        eng.to_df(pdf),
        SPEC,
        [ff.sum(col("v")).alias("s"), ff.avg(col("v")).alias("m")],
    )
    assert res.host_table is None
    assert res.count() == 3  # lazy count over the valid mask
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    # int32 key dtype survives the device finish
    assert str(res.schema["k"].type) == "int32"
    assert got["k"].tolist() == [5, 42, 900]
    assert got["s"].tolist()[:2] == [3.0, 7.0] and np.isnan(got["s"][2])
    assert np.isnan(got["m"][2])


def test_aggregate_of_filtered_frame_then_downstream_filter(eng):
    pdf = pd.DataFrame(
        {"k": np.arange(100) % 7, "v": np.arange(100, dtype=float)}
    )
    f = eng.filter(eng.to_df(pdf), col("v") < 50)
    r = eng.aggregate(
        f, SPEC, [ff.count(col("v")).alias("n"), ff.sum(col("v")).alias("s")]
    )
    assert r.host_table is None
    # the aggregate result is itself a valid device input to later ops
    r2 = eng.filter(r, col("s") > 100.0)
    exp = (
        pdf.query("v<50")
        .groupby("k")
        .agg(n=("v", "count"), s=("v", "sum"))
        .reset_index()
        .query("s>100")
        .reset_index(drop=True)
    )
    got = r2.as_pandas().sort_values("k").reset_index(drop=True)
    assert (got["k"].to_numpy() == exp["k"].to_numpy()).all()
    assert np.allclose(got["s"], exp["s"])


def test_int_sum_min_max_dtypes(eng):
    pdf = pd.DataFrame({"k": np.arange(20) % 3, "x": np.arange(20)})
    r = eng.aggregate(
        eng.to_df(pdf),
        SPEC,
        [
            ff.sum(col("x")).alias("s"),
            ff.min(col("x")).alias("lo"),
            ff.max(col("x")).alias("hi"),
        ],
    )
    assert r.host_table is None
    got = r.as_pandas().sort_values("k").reset_index(drop=True)
    exp = (
        pdf.groupby("k")
        .agg(s=("x", "sum"), lo=("x", "min"), hi=("x", "max"))
        .reset_index()
    )
    assert (got.to_numpy() == exp.to_numpy()).all()
    assert str(r.schema["s"].type) == "int64"


def test_host_key_range_declines_masked_and_encoded_cols(eng):
    # host-side min/max skips NULLs, but the device column holds fill
    # values — the two probes would disagree, so the host path must
    # decline for masked/encoded columns (device probe stays authoritative)
    pdf = pd.DataFrame(
        {
            "k": pd.array([5, 10, None], dtype="Int64"),
            "s": ["a", "b", "c"],
            "p": [1, 2, 3],
        }
    )
    jdf = eng.to_df(pdf)
    assert jdf._host_key_range("k") is None
    assert jdf._host_key_range("s") is None
    assert jdf._host_key_range("p") == (1, 3)


def test_masked_int_values_keep_host_finish_exact(eng):
    # nullable int64 goes through the hi/lo host merge (device finish must
    # decline) and stays exact at 2^62 scale
    big = 1 << 62
    pdf = pd.DataFrame(
        {
            "k": [0, 0, 1, 1],
            "x": pd.array([big, 3, None, None], dtype="Int64"),
        }
    )
    r = eng.aggregate(eng.to_df(pdf), SPEC, [ff.sum(col("x")).alias("s")])
    got = r.as_pandas().sort_values("k").reset_index(drop=True)
    assert got["s"][0] == big + 3
    assert pd.isna(got["s"][1])
