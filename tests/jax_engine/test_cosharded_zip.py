"""Device co-sharded zip/comap: no blob serialization for device frames."""

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.dataframe import DataFrames
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.jax.zipped import ZippedJaxDataFrame


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


def test_zip_device_frames_produces_cosharded(engine):
    a = pd.DataFrame({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
    b = pd.DataFrame({"k": [2, 3, 4], "w": [20.0, 30.0, 40.0]})
    z = engine.zip(
        DataFrames([engine.to_df(a), engine.to_df(b)]),
        partition_spec=PartitionSpec(by=["k"]),
    )
    assert isinstance(z, ZippedJaxDataFrame)
    assert z.metadata["device_zip"] is True
    assert z.metadata["keys"] == ["k"]
    # the co-sharded frames preserved all rows
    assert sorted(z.zip_frames[0].as_pandas()["k"].tolist()) == [1, 2, 3]
    assert sorted(z.zip_frames[1].as_pandas()["k"].tolist()) == [2, 3, 4]


def test_comap_matches_oracle(engine, monkeypatch):
    rng = np.random.default_rng(0)
    a = pd.DataFrame({"k": rng.integers(0, 10, 200), "v": rng.random(200)})
    b = pd.DataFrame({"k": rng.integers(0, 12, 150), "w": rng.random(150)})

    def merge_stats(df1: pd.DataFrame, df2: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame(
            {
                "k": [df1["k"].iloc[0]],
                "sv": [df1["v"].sum()],
                "sw": [df2["w"].sum()],
            }
        )

    def run(eng):
        from fugue_tpu.workflow import FugueWorkflow

        dag = FugueWorkflow()
        z = dag.df(a).zip(dag.df(b), partition=dict(by=["k"]))
        z.transform(
            merge_stats, schema="k:long,sv:double,sw:double"
        ).yield_dataframe_as("r", as_local=True)
        res = dag.run(eng)
        return (
            res.yields["r"].result.as_pandas()
            .sort_values("k")
            .reset_index(drop=True)
        )

    exp = run(NativeExecutionEngine())
    # prove the device path: the jax engine must never build blob rows
    def _no_blobs(*a, **k):
        raise AssertionError("blob serialization used on the device zip path")

    monkeypatch.setattr(engine, "_serialize_by_partition", _no_blobs)
    got = run(engine)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_comap_outer_semantics(engine):
    a = pd.DataFrame({"k": [1, 2], "v": [1.0, 2.0]})
    b = pd.DataFrame({"k": [2, 3], "w": [20.0, 30.0]})

    def count_sides(df1: pd.DataFrame, df2: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({"n1": [len(df1)], "n2": [len(df2)]})

    from fugue_tpu.workflow import FugueWorkflow

    for how, expected in [
        ("inner", [(1, 1)]),
        ("left_outer", [(1, 0), (1, 1)]),
        ("full_outer", [(0, 1), (1, 0), (1, 1)]),
    ]:
        z = engine.zip(
            DataFrames([engine.to_df(a), engine.to_df(b)]),
            how=how,
            partition_spec=PartitionSpec(by=["k"]),
        )
        assert isinstance(z, ZippedJaxDataFrame), how
        dag = FugueWorkflow()
        dag.df(a).zip(dag.df(b), how=how, partition=dict(by=["k"])).transform(
            count_sides, schema="n1:int,n2:int"
        ).yield_dataframe_as("r", as_local=True)
        res = dag.run(engine).yields["r"].result.as_pandas()
        got = sorted(map(tuple, res[["n1", "n2"]].to_numpy().tolist()))
        assert got == sorted(expected), how


def test_zip_nanable_float_keys_fall_back_to_blob_protocol(engine):
    import pyarrow as pa

    # NaN float keys can't group across frames host-side → blob protocol
    a = pa.table(
        {
            "k": pa.array([1.0, float("nan")], pa.float64()),
            "v": pa.array([1.0, 2.0], pa.float64()),
        }
    )
    b = pd.DataFrame({"k": [1.0, 2.0], "w": [3.0, 4.0]})
    z = engine.zip(
        DataFrames([engine.to_df(a), engine.to_df(b)]),
        partition_spec=PartitionSpec(by=["k"]),
    )
    assert not isinstance(z, ZippedJaxDataFrame)
    assert z.metadata["serialized"] is True


def test_zipped_frame_materializes_for_non_comap_use(engine):
    a = pd.DataFrame({"k": [1, 2], "v": [1.0, 2.0]})
    b = pd.DataFrame({"k": [1, 2], "w": [3.0, 4.0]})
    z = engine.zip(
        DataFrames([engine.to_df(a), engine.to_df(b)]),
        partition_spec=PartitionSpec(by=["k"]),
    )
    assert isinstance(z, ZippedJaxDataFrame)
    tbl = z.as_arrow()  # blob fallback materialization
    assert tbl.num_rows == 4  # 2 keys × 2 frames
    assert z.count() == 4


def test_zip_string_keys_on_device(engine, monkeypatch):
    """String zip keys co-locate via a union dictionary — no blob path."""
    a = pd.DataFrame(
        {"s": ["x", "y", "z", None, "x"], "v": [1.0, 2.0, 3.0, 4.0, 5.0]}
    )
    b = pd.DataFrame({"s": ["y", "w", None, "x"], "w": [20.0, 40.0, 60.0, 10.0]})

    def stats(df1: pd.DataFrame, df2: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame(
            {
                "s": [df1["s"].iloc[0] if len(df1) else df2["s"].iloc[0]],
                "n1": [len(df1)],
                "n2": [len(df2)],
            }
        )

    def _no_blobs(*args, **kw):
        raise AssertionError("blob serialization used for string zip keys")

    monkeypatch.setattr(engine, "_serialize_by_partition", _no_blobs)
    from fugue_tpu.workflow import FugueWorkflow

    dag = FugueWorkflow()
    dag.df(a).zip(dag.df(b), how="full_outer", partition=dict(by=["s"])).transform(
        stats, schema="s:str,n1:int,n2:int"
    ).yield_dataframe_as("r", as_local=True)
    res = dag.run(engine).yields["r"].result.as_pandas()
    got = {
        (None if pd.isna(r["s"]) else r["s"]): (r["n1"], r["n2"])
        for _, r in res.iterrows()
    }
    assert got == {
        "x": (2, 1),
        "y": (1, 1),
        "z": (1, 0),
        "w": (0, 1),
        None: (1, 1),
    }
