"""Round-3 device-window closures: global (no PARTITION BY) windows,
RANGE frames with numeric value offsets, and bounded-frame MIN/MAX —
previously host fallbacks (STATUS known gaps), now lowered onto the
device sort + segment + sparse-table machinery with the host evaluator
poisoned to prove the device plan ran. Oracle = the native engine.
"""

import unittest.mock as mock

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


@pytest.fixture(scope="module")
def oracle():
    e = NativeExecutionEngine()
    yield e
    e.stop()


def _pd(res):
    return res.to_pandas() if hasattr(res, "to_pandas") else res


def _run_both(sql, df, engine, oracle, poison=True):
    import fugue_tpu.column.window as w

    def boom(*a, **k):  # pragma: no cover
        raise AssertionError("host window evaluator used on the jax engine")

    if poison:
        with mock.patch.object(w, "eval_window", boom):
            got = _pd(fa.fugue_sql(sql, df=df, engine=engine, as_local=True))
    else:
        got = _pd(fa.fugue_sql(sql, df=df, engine=engine, as_local=True))
    exp = _pd(fa.fugue_sql(sql, df=df, engine=oracle, as_local=True))
    sort_cols = list(exp.columns)
    g = got.sort_values(sort_cols).reset_index(drop=True)
    x = exp.sort_values(sort_cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(g, x, check_dtype=False)
    return got


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(29)
    n = 400
    v = rng.random(n)
    v[rng.random(n) < 0.15] = np.nan
    return pd.DataFrame(
        {
            "k": rng.integers(0, 7, n),
            "o": rng.integers(0, 40, n),
            "f": np.round(rng.random(n) * 20, 3),  # NaN-free float order key
            "r": rng.permutation(n).astype("int64"),
            "iv": rng.integers(-50, 50, n),
            "v": v,
        }
    )


def test_global_rank_and_running(engine, oracle, data):
    _run_both(
        """
        SELECT o, r, v,
          ROW_NUMBER() OVER (ORDER BY o, r) AS rn,
          RANK() OVER (ORDER BY o) AS rk,
          DENSE_RANK() OVER (ORDER BY o) AS dr,
          SUM(v) OVER (ORDER BY o, r
                       ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rs,
          LAG(v) OVER (ORDER BY o, r) AS lg
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_global_whole_frame_aggregates(engine, oracle, data):
    _run_both(
        """
        SELECT o, v,
          SUM(v) OVER () AS s,
          COUNT(v) OVER () AS c,
          AVG(v) OVER () AS a,
          MIN(v) OVER () AS lo,
          MAX(v) OVER () AS hi
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_global_peers_default_frame(engine, oracle, data):
    _run_both(
        "SELECT o, SUM(v) OVER (ORDER BY o) AS s, "
        "COUNT(v) OVER (ORDER BY o) AS c FROM df",
        data,
        engine,
        oracle,
    )


def test_range_numeric_offsets_sum_avg_count(engine, oracle, data):
    _run_both(
        """
        SELECT k, f, v,
          SUM(v) OVER (PARTITION BY k ORDER BY f
                       RANGE BETWEEN 2.5 PRECEDING AND CURRENT ROW) AS s,
          AVG(v) OVER (PARTITION BY k ORDER BY f
                       RANGE BETWEEN 1.0 PRECEDING AND 1.0 FOLLOWING) AS a,
          COUNT(v) OVER (PARTITION BY k ORDER BY f
                         RANGE BETWEEN CURRENT ROW AND 3.0 FOLLOWING) AS c
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_range_numeric_offsets_min_max(engine, oracle, data):
    _run_both(
        """
        SELECT k, f, v,
          MIN(v) OVER (PARTITION BY k ORDER BY f
                       RANGE BETWEEN 2.0 PRECEDING AND 2.0 FOLLOWING) AS lo,
          MAX(v) OVER (PARTITION BY k ORDER BY f
                       RANGE BETWEEN 1.5 PRECEDING AND CURRENT ROW) AS hi
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_range_numeric_offsets_desc(engine, oracle, data):
    _run_both(
        """
        SELECT k, f, v,
          MAX(v) OVER (PARTITION BY k ORDER BY f DESC
                       RANGE BETWEEN 1.5 PRECEDING AND CURRENT ROW) AS hi,
          SUM(v) OVER (PARTITION BY k ORDER BY f DESC
                       RANGE BETWEEN 2.0 PRECEDING AND 1.0 FOLLOWING) AS s
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_range_offsets_int_order_key(engine, oracle, data):
    _run_both(
        """
        SELECT k, o, v,
          SUM(v) OVER (PARTITION BY k ORDER BY o
                       RANGE BETWEEN 5 PRECEDING AND CURRENT ROW) AS s,
          MAX(v) OVER (PARTITION BY k ORDER BY o
                       RANGE BETWEEN CURRENT ROW AND 4 FOLLOWING) AS hi
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_range_empty_windows(engine, oracle, data):
    # frames strictly ahead of the current value can be empty → NULL/0
    _run_both(
        """
        SELECT k, f, v,
          SUM(v) OVER (PARTITION BY k ORDER BY f
                       RANGE BETWEEN 90.0 FOLLOWING AND 99.0 FOLLOWING) AS s,
          COUNT(v) OVER (PARTITION BY k ORDER BY f
                         RANGE BETWEEN 90.0 FOLLOWING AND 99.0 FOLLOWING) AS c
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_rows_bounded_min_max(engine, oracle, data):
    _run_both(
        """
        SELECT k, o, r, v,
          MIN(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN 3 PRECEDING AND CURRENT ROW) AS m1,
          MAX(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN 2 PRECEDING AND 2 FOLLOWING) AS m2,
          MIN(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) AS m3,
          MAX(v) OVER (PARTITION BY k ORDER BY o, r
                       ROWS BETWEEN 1 FOLLOWING AND 3 FOLLOWING) AS m4
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_bounded_frames_over_int_arg(engine, oracle, data):
    # host computes bounded frames in float64 then coerces to the declared
    # long type — the device must match (out_cast)
    _run_both(
        """
        SELECT k, o, r, iv,
          SUM(iv) OVER (PARTITION BY k ORDER BY o, r
                        ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS s,
          MIN(iv) OVER (PARTITION BY k ORDER BY o, r
                        ROWS BETWEEN 3 PRECEDING AND 1 PRECEDING) AS lo,
          MAX(iv) OVER (PARTITION BY k ORDER BY o
                        RANGE BETWEEN 2 PRECEDING AND CURRENT ROW) AS hi
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_global_range_offsets(engine, oracle, data):
    _run_both(
        """
        SELECT f, v,
          SUM(v) OVER (ORDER BY f RANGE BETWEEN 3.0 PRECEDING AND CURRENT ROW) AS s,
          MIN(v) OVER (ORDER BY f RANGE BETWEEN 1.0 PRECEDING AND 1.0 FOLLOWING) AS lo
        FROM df
        """,
        data,
        engine,
        oracle,
    )


def test_masked_arg_bounded_frames(engine, oracle):
    rng = np.random.default_rng(31)
    n = 300
    iv = rng.integers(0, 100, n).astype("float64")
    iv[rng.random(n) < 0.2] = np.nan
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 5, n),
            "o": rng.permutation(n).astype("int64"),
            "iv": pd.array(
                [None if np.isnan(x) else int(x) for x in iv], dtype="Int64"
            ),
        }
    )
    _run_both(
        """
        SELECT k, o, iv,
          SUM(iv) OVER (PARTITION BY k ORDER BY o
                        ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS s,
          MAX(iv) OVER (PARTITION BY k ORDER BY o
                        ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS hi
        FROM df
        """,
        df,
        engine,
        oracle,
    )


def test_zero_offset_range_peer_frames(engine, oracle):
    """RANGE frames bounded at CURRENT ROW on both sides = the peer group.
    Regression: the host evaluator used to compute peer boundaries on the
    GLOBAL order-key sort, merging peers across interleaved partitions —
    verified here against a brute-force per-partition expected value, and
    device/host parity on top."""
    rng = np.random.default_rng(47)
    n = 120
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 4, n),
            "o": rng.integers(0, 10, n),  # heavy ties across partitions
            "v": np.round(rng.random(n), 3),
        }
    )
    sql = """
    SELECT k, o, v,
      SUM(v) OVER (PARTITION BY k ORDER BY o
                   RANGE BETWEEN CURRENT ROW AND CURRENT ROW) AS s,
      COUNT(v) OVER (PARTITION BY k ORDER BY o
                     RANGE BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) AS c
    FROM df
    """
    got = _run_both(sql, df, engine, oracle)
    # brute force: s = sum of v over SAME (k, o); c = count of rows in the
    # partition with o >= this row's o
    exp_s = df.groupby(["k", "o"])["v"].transform("sum")
    exp_c = df.apply(
        lambda r: int(((df["k"] == r["k"]) & (df["o"] >= r["o"])).sum()),
        axis=1,
    )
    truth = (
        df.assign(s=exp_s, c=exp_c)
        .sort_values(["k", "o", "v", "s", "c"])
        .reset_index(drop=True)
    )
    g = got.sort_values(["k", "o", "v", "s", "c"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(
        g[["k", "o", "v", "s", "c"]], truth, check_dtype=False
    )


def test_fractional_range_offsets_are_exact(engine, oracle):
    """Regression: the parser truncated frame bounds to int, so RANGE
    BETWEEN 2.5 PRECEDING silently became 2 PRECEDING on BOTH engines
    (parity tests couldn't see it). Verified against a hand value."""
    df = pd.DataFrame({"o": [0.0, 2.4, 2.6], "v": [1.0, 10.0, 100.0]})
    sql = """
    SELECT o, v, SUM(v) OVER (ORDER BY o
        RANGE BETWEEN 2.5 PRECEDING AND CURRENT ROW) AS s FROM df
    """
    got = _run_both(sql, df, engine, oracle)
    # 2.4-2.5 <= 0.0 → 0.0 included; 2.6-2.5 > 0.0 → excluded (the old
    # truncation to "2 PRECEDING" included it: s was 111.0)
    exp = {0.0: 1.0, 2.4: 11.0, 2.6: 110.0}
    assert {o: s for o, s in zip(got["o"], got["s"])} == exp


def test_rows_fractional_offsets_raise(engine):
    from fugue_tpu.exceptions import FugueSQLSyntaxError

    with pytest.raises(FugueSQLSyntaxError):
        fa.fugue_sql(
            "SELECT o, SUM(v) OVER (ORDER BY o ROWS BETWEEN 1.5 PRECEDING "
            "AND CURRENT ROW) AS s FROM df YIELD LOCAL DATAFRAME AS r",
            df=pd.DataFrame({"o": [1.0], "v": [1.0]}),
            engine=engine,
        )


def test_bounded_int32_arg_keeps_declared_type(engine, oracle):
    """SUM over an int32 column in a bounded frame must come back as int32
    on BOTH engines (the device used to widen to long)."""
    from fugue_tpu.dataframe import PandasDataFrame

    df = pd.DataFrame(
        {"k": [1, 1, 2, 2], "o": [1, 2, 1, 2], "iv": [5, 6, 7, 8]}
    )
    fdf = PandasDataFrame(df, "k:long,o:long,iv:int")
    sql = """
    SELECT k, o, SUM(iv) OVER (PARTITION BY k ORDER BY o
        ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS s FROM df
    """
    import fugue_tpu.column.window as w

    def boom(*a, **k):  # pragma: no cover
        raise AssertionError("host window evaluator used on the jax engine")

    with mock.patch.object(w, "eval_window", boom):
        got = fa.fugue_sql(sql, df=fdf, engine=engine, as_local=True, as_fugue=True)
    exp = fa.fugue_sql(sql, df=fdf, engine=oracle, as_local=True, as_fugue=True)
    assert str(got.schema["s"].type) == str(exp.schema["s"].type) == "int32"
    g = _pd(got.as_pandas()).sort_values(["k", "o"]).reset_index(drop=True)
    x = _pd(exp.as_pandas()).sort_values(["k", "o"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(g, x, check_dtype=False)


def test_zero_offset_range_on_empty_frame(oracle):
    """Regression: the host peer branch indexed changed[0] on a 0-row
    frame."""
    df = pd.DataFrame({"o": pd.Series([], dtype="float64"),
                       "v": pd.Series([], dtype="float64")})
    res = fa.fugue_sql(
        "SELECT o, SUM(v) OVER (ORDER BY o RANGE BETWEEN CURRENT ROW AND "
        "CURRENT ROW) AS s FROM df YIELD LOCAL DATAFRAME AS r",
        df=df,
        engine=oracle,
        as_local=True,
    )
    res = _pd(res)
    assert len(res) == 0


def test_host_fallback_still_covers_nan_order_keys(engine, oracle, data):
    # RANGE offsets over a maybe-NaN order key must DECLINE to the host
    # path (no poison: we assert the fallback, not the plan)
    df = data.assign(fn=data["v"])  # v has NaNs
    _run_both(
        """
        SELECT k, fn, o,
          SUM(o) OVER (PARTITION BY k ORDER BY fn
                       RANGE BETWEEN 1.0 PRECEDING AND CURRENT ROW) AS s
        FROM df
        """,
        df,
        engine,
        oracle,
        poison=False,
    )


def test_masked_int64_running_windows_exact_at_2pow62(engine, oracle):
    """The host now computes masked-int64 running/whole/peer window
    aggregates exactly (Int64 extension ingestion); the device matches via
    hi/lo split sums and int-domain MIN/MAX — EXACT equality at 2^62,
    device plan proven used."""
    rng = np.random.default_rng(53)
    n = 300
    base = np.int64(2**62)
    vals = base + rng.integers(-1000, 1000, n).astype(np.int64)
    m = pd.array(
        np.where(rng.random(n) < 0.2, None, vals), dtype="Int64"
    )
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 4, n),
            "o": rng.permutation(n).astype("int64"),
            "ot": rng.integers(0, 8, n),  # ties → peers frames
            "m": m,
        }
    )
    got = _run_both(
        """
        SELECT k, o, m,
          SUM(m) OVER (PARTITION BY k ORDER BY o
                       ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rs,
          MIN(m) OVER (PARTITION BY k ORDER BY o
                       ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rmin,
          MAX(m) OVER (PARTITION BY k) AS wmax,
          AVG(m) OVER (PARTITION BY k ORDER BY o) AS ra
        FROM df
        """,
        df,
        engine,
        oracle,
    )
    # spot exactness against int arithmetic (not float): final running sum
    # of each partition == the exact python-int sum of its non-null values
    import numpy as _np

    for k in sorted(df["k"].unique()):
        sub = df[df["k"] == k]
        exact = int(
            _np.sum([int(x) for x in sub["m"].dropna()], dtype=object)
        )
        wrapped = (exact + 2**63) % 2**64 - 2**63  # int64 wrap like cumsum
        tail = got[got["k"] == k].sort_values("o")["rs"].iloc[-1]
        assert int(tail) == wrapped, (k, int(tail), wrapped)


def test_masked_int64_peers_frame_exact(engine, oracle):
    rng = np.random.default_rng(59)
    n = 200
    vals = np.int64(2**62) + rng.integers(-500, 500, n).astype(np.int64)
    m = pd.array(np.where(rng.random(n) < 0.15, None, vals), dtype="Int64")
    df = pd.DataFrame(
        {"k": rng.integers(0, 3, n), "o": rng.integers(0, 10, n), "m": m}
    )
    _run_both(
        """
        SELECT k, o, m,
          SUM(m) OVER (PARTITION BY k ORDER BY o
                       RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS ps
        FROM df
        """,
        df,
        engine,
        oracle,
    )
