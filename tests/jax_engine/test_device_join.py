"""Device hash-join tests: type matrix vs the pandas oracle, both
strategies (broadcast and shuffle), multi-key, NaN keys, fallbacks."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import fugue_tpu.ops.join as oj
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxDataFrame, JaxExecutionEngine


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


@pytest.fixture(scope="module")
def oracle():
    e = NativeExecutionEngine()
    yield e
    e.stop()


def _check(engine, oracle, df1, df2, how, on=None):
    got = engine.join(engine.to_df(df1), engine.to_df(df2), how=how, on=on)
    exp = oracle.join(oracle.to_df(df1), oracle.to_df(df2), how=how, on=on)
    g = got.as_pandas()
    e = exp.as_pandas()
    assert list(g.columns) == list(e.columns)
    order = list(g.columns)
    g = g.sort_values(order).reset_index(drop=True)
    e = e.sort_values(order).reset_index(drop=True)
    pd.testing.assert_frame_equal(g, e, check_dtype=False)
    return got


@pytest.fixture(scope="module")
def fact():
    rng = np.random.default_rng(0)
    return pd.DataFrame(
        {
            "k": rng.integers(0, 50, 500),
            "v": rng.random(500),
        }
    )


@pytest.fixture(scope="module")
def dim():
    # unique keys 0..39 → some fact keys miss
    rng = np.random.default_rng(1)
    return pd.DataFrame({"k": np.arange(40), "w": rng.random(40)})


def test_inner(engine, oracle, fact, dim):
    got = _check(engine, oracle, fact, dim, "inner")
    assert isinstance(got, JaxDataFrame) and got.host_table is None


def test_left_outer_float_values(engine, oracle, fact, dim):
    got = _check(engine, oracle, fact, dim, "left_outer")
    assert isinstance(got, JaxDataFrame)


def test_left_outer_int_values(engine, oracle, fact):
    dim_int = pd.DataFrame({"k": np.arange(40), "w": np.arange(40)})
    got = _check(engine, oracle, fact, dim_int, "left_outer")
    # stays on device: int misses carry a generated null mask
    assert isinstance(got, JaxDataFrame) and "w" in got.null_masks


def test_semi_anti(engine, oracle, fact, dim):
    _check(engine, oracle, fact, dim, "semi")
    _check(engine, oracle, fact, dim, "anti")


def test_multi_key(engine, oracle):
    rng = np.random.default_rng(2)
    left = pd.DataFrame(
        {
            "a": rng.integers(0, 6, 300),
            "b": rng.integers(0, 6, 300),
            "v": rng.random(300),
        }
    )
    pairs = [(a, b) for a in range(5) for b in range(5)]
    right = pd.DataFrame(
        {
            "a": [p[0] for p in pairs],
            "b": [p[1] for p in pairs],
            "w": np.linspace(0, 1, len(pairs)),
        }
    )
    for how in ["inner", "left_outer", "semi", "anti"]:
        _check(engine, oracle, left, right, how)


def test_float_key_and_nan_never_matches(engine, oracle):
    # arrow keeps NaN as a value → device-resident float key with NaN
    left = pa.table(
        {
            "k": pa.array([1.0, 2.0, np.nan, 4.0], pa.float64()),
            "v": pa.array([10.0, 20.0, 30.0, 40.0], pa.float64()),
        }
    )
    right = pa.table(
        {
            "k": pa.array([1.0, np.nan, 4.0], pa.float64()),
            "w": pa.array([0.1, 0.2, 0.4], pa.float64()),
        }
    )
    got = engine.join(engine.to_df(left), engine.to_df(right), how="inner")
    g = got.as_pandas().sort_values("k").reset_index(drop=True)
    # NaN keys never match (SQL NULL semantics)
    assert g["k"].tolist() == [1.0, 4.0]
    assert g["w"].tolist() == [0.1, 0.4]


def test_non_unique_right_falls_back(engine, oracle, fact):
    dup = pd.DataFrame({"k": [1, 1, 2], "w": [0.1, 0.2, 0.3]})
    _check(engine, oracle, fact, dup, "inner")  # host path, still correct


def test_shuffle_strategy(engine, oracle, monkeypatch):
    """Force the shuffle path with a tiny broadcast threshold."""
    monkeypatch.setattr(oj, "MAX_BROADCAST_ROWS", 8)
    rng = np.random.default_rng(3)
    left = pd.DataFrame(
        {
            "k": rng.integers(0, 200, 1000),
            "v": rng.random(1000),
        }
    )
    right = pd.DataFrame({"k": np.arange(150), "w": rng.random(150)})
    for how in ["inner", "left_outer", "semi", "anti"]:
        got = _check(engine, oracle, left, right, how)
        assert isinstance(got, JaxDataFrame) and got.host_table is None


def test_right_and_full_outer_on_host(engine, oracle, fact, dim):
    _check(engine, oracle, fact, dim, "right_outer")
    _check(engine, oracle, fact, dim, "full_outer")


class TestEncodedJoins:
    """String keys (dictionary unification), encoded/nullable value columns,
    and left_outer NULL-fill for every representation."""

    def test_string_key_inner_join(self, engine, oracle):
        left = pd.DataFrame(
            {
                "s": ["apple", "pear", "fig", "apple", None],
                "v": [1.0, 2.0, 3.0, 4.0, 5.0],
            }
        )
        right = pd.DataFrame(
            {"s": ["apple", "fig", "kiwi", None], "w": [0.1, 0.3, 0.9, 0.7]}
        )
        got = _check(engine, oracle, left, right, "inner")
        assert isinstance(got, JaxDataFrame) and got.host_table is None

    def test_string_key_all_types(self, engine, oracle):
        rng = np.random.default_rng(4)
        words = ["a", "bb", "ccc", "dddd", "e f", None]
        left = pd.DataFrame(
            {
                "s": rng.choice(words[:5], 300).tolist(),
                "v": rng.random(300),
            }
        )
        right = pd.DataFrame({"s": ["bb", "dddd", "zz"], "w": [1.0, 2.0, 3.0]})
        for how in ["inner", "left_outer", "semi", "anti"]:
            _check(engine, oracle, left, right, how)

    def test_left_outer_int_values_on_device(self, engine, oracle):
        left = pd.DataFrame({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
        right = pd.DataFrame({"k": [1, 3], "w": [10, 30]})  # int values
        got = _check(engine, oracle, left, right, "left_outer")
        # now stays on device: misses carry a generated null mask
        assert isinstance(got, JaxDataFrame) and "w" in got.null_masks

    def test_string_value_columns(self, engine, oracle):
        left = pd.DataFrame({"k": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0]})
        right = pd.DataFrame({"k": [1, 3], "name": ["one", "three"]})
        got = _check(engine, oracle, left, right, "inner")
        assert isinstance(got, JaxDataFrame)
        got2 = _check(engine, oracle, left, right, "left_outer")
        assert isinstance(got2, JaxDataFrame)
        assert got2.encodings.get("name", {}).get("kind") == "dict"

    def test_nullable_value_columns(self, engine, oracle):
        left = pd.DataFrame({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
        right = pd.DataFrame(
            {"k": [1, 2], "w": pd.array([10, None], dtype="Int32")}
        )
        for how in ["inner", "left_outer"]:
            got = _check(engine, oracle, left, right, how)
            assert isinstance(got, JaxDataFrame) and "w" in got.null_masks

    def test_nullable_int_key(self, engine, oracle):
        left = pd.DataFrame(
            {
                "k": pd.array([1, None, 3, 4], dtype="Int32"),
                "v": [1.0, 2.0, 3.0, 4.0],
            }
        )
        right = pd.DataFrame(
            {"k": pd.array([1, 4, None], dtype="Int32"), "w": [0.1, 0.4, 0.9]}
        )
        # NULL keys never match (SQL), even NULL vs NULL
        for how in ["inner", "left_outer", "semi", "anti"]:
            _check(engine, oracle, left, right, how)

    def test_datetime_key(self, engine, oracle):
        d = pd.to_datetime
        left = pd.DataFrame(
            {
                "t": d(["2020-01-01", "2020-02-01", "2020-03-01"]),
                "v": [1.0, 2.0, 3.0],
            }
        )
        right = pd.DataFrame(
            {"t": d(["2020-02-01", "2020-04-01"]), "w": [0.2, 0.4]}
        )
        for how in ["inner", "left_outer", "semi", "anti"]:
            got = _check(engine, oracle, left, right, how)
            assert isinstance(got, JaxDataFrame)


def test_join_mixed_key_dtypes_match_by_value():
    """Cross-dtype join keys coerce to the common type (pandas/SQL
    semantics): float 2.0 matches int 2; 1.5/2.7 match nothing; int32
    joins int64 exactly."""
    import numpy as np
    import pandas as pd

    from fugue_tpu.jax import JaxExecutionEngine

    eng = JaxExecutionEngine()
    try:
        big = pd.DataFrame({"k": [1.5, 2.0, 2.7], "v": [1.0, 2.0, 3.0]})
        dim = pd.DataFrame({"k": [1, 2], "w": [10.0, 20.0]})
        r = eng.join(eng.to_df(big), eng.to_df(dim), how="inner").as_pandas()
        assert len(r) == 1 and r["v"].iloc[0] == 2.0 and r["w"].iloc[0] == 20.0
        a = pd.DataFrame({"k": np.array([1, 2, 3], np.int32), "v": [1.0, 2.0, 3.0]})
        b = pd.DataFrame({"k": np.array([2, 3, 4], np.int64), "w": [5.0, 6.0, 7.0]})
        r2 = eng.join(eng.to_df(a), eng.to_df(b), how="inner").as_pandas()
        assert sorted(r2["v"]) == [2.0, 3.0]
        # left_outer keeps unmatched float keys with NULL payload
        r3 = (
            eng.join(eng.to_df(big), eng.to_df(dim), how="left_outer")
            .as_pandas()
            .sort_values("v")
        )
        assert len(r3) == 3 and list(r3["w"].isna()) == [True, False, True]
    finally:
        eng.stop_engine()
