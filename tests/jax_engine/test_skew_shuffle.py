"""Skew-safe multi-round shuffle: a hot key must not inflate the exchange
buffers (VERDICT r2 #6). The round capacity is forced tiny so the stress
runs many bounded rounds."""

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.ops.shuffle as S
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.jax import JaxExecutionEngine


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


def test_multiround_exchange_hot_key(engine, monkeypatch):
    # one hot key owns ~70% of rows; cap rounds at 256 rows/dest/round
    monkeypatch.setattr(S, "SINGLE_ROUND_MAX_CAPACITY", 256)
    rng = np.random.default_rng(0)
    n = 20_000
    k = rng.integers(0, 50, n)
    k[: int(n * 0.7)] = 7  # hot key
    pdf = pd.DataFrame({"k": k, "v": rng.random(n)})
    jdf = engine.to_df(pdf)
    out = engine.repartition(jdf, PartitionSpec(algo="hash", by=["k"]))
    got = out.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    exp = pdf.sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)
    # padded output stays near the true received max, not shards x hot size
    import jax

    arr = next(iter(out.device_cols.values()))
    per_shard = arr.shape[0] // 8
    hot = int((k == 7).sum())
    assert per_shard <= 2 * hot  # pow2 of max received, NOT 8x


def test_multiround_round_count(engine, monkeypatch):
    calls = {"n": 0}
    orig = S._get_compiled_round

    def counting(*a, **kw):
        fn = orig(*a, **kw)

        def wrapper(*args, **kwargs):
            calls["n"] += 1
            return fn(*args, **kwargs)

        return wrapper

    monkeypatch.setattr(S, "SINGLE_ROUND_MAX_CAPACITY", 128)
    monkeypatch.setattr(S, "_get_compiled_round", counting)
    pdf = pd.DataFrame({"k": [1] * 3000, "v": np.arange(3000.0)})
    jdf = engine.to_df(pdf)
    out = engine.repartition(jdf, PartitionSpec(algo="hash", by=["k"]))
    assert sorted(out.as_pandas()["v"]) == sorted(pdf["v"])
    # ~375 rows/shard to one dest at 128/round -> 3 bounded rounds
    assert calls["n"] >= 3


def test_multiround_with_masks_and_strings(engine, monkeypatch):
    monkeypatch.setattr(S, "SINGLE_ROUND_MAX_CAPACITY", 64)
    rng = np.random.default_rng(3)
    n = 2000
    pdf = pd.DataFrame(
        {
            "k": np.where(rng.random(n) < 0.8, 3, rng.integers(0, 10, n)),
            "s": rng.choice(["x", "y", "z"], n),
            "m": pd.array(
                np.where(rng.random(n) < 0.2, None, rng.integers(0, 99, n)),
                dtype="Int64",
            ),
        }
    )
    jdf = engine.to_df(pdf)
    out = engine.repartition(jdf, PartitionSpec(algo="hash", by=["k"]))
    got = out.as_pandas()
    g = got.sort_values(["k", "s", "m"], na_position="first").reset_index(drop=True)
    x = pdf.sort_values(["k", "s", "m"], na_position="first").reset_index(drop=True)
    pd.testing.assert_frame_equal(g, x, check_dtype=False)


def test_multiround_even_repartition(engine, monkeypatch):
    monkeypatch.setattr(S, "SINGLE_ROUND_MAX_CAPACITY", 64)
    pdf = pd.DataFrame({"v": np.arange(4000.0)})
    jdf = engine.to_df(pdf)
    # filter first so valid rows are unevenly spread, then rebalance
    from fugue_tpu.column import col, lit

    flt = engine.filter(jdf, col("v") < lit(1000.0))
    out = engine.repartition(flt, PartitionSpec(algo="even", num=8))
    assert sorted(out.as_pandas()["v"]) == sorted(range(1000))
