"""Nested list/struct columns on the jax engine (host-resident columns
riding device frames) and the empty/edge-partition matrix (VERDICT r2 #7:
static-shape XLA makes empty partitions the hard case — mask, don't
branch)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import fugue_tpu.api as fa
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff, lit
from fugue_tpu.dataframe import ArrowDataFrame
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.jax.dataframe import JaxDataFrame


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


# ---- nested types on the device engine ------------------------------------


def _nested_tbl():
    return pa.table(
        {
            "k": pa.array([1, 2, 3], type=pa.int64()),
            "tags": pa.array([[1, 2], [], [3]], type=pa.list_(pa.int64())),
            "info": pa.array(
                [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}, {"a": 3, "b": "z"}],
                type=pa.struct([("a", pa.int64()), ("b", pa.string())]),
            ),
        }
    )


def test_nested_ingestion_roundtrip(engine):
    jdf = engine.to_df(ArrowDataFrame(_nested_tbl()))
    assert isinstance(jdf, JaxDataFrame)
    assert "k" in jdf.device_cols  # numeric col on device
    assert jdf.host_table is not None  # nested cols stay host-resident
    out = jdf.as_arrow()
    assert out.column("tags").to_pylist() == [[1, 2], [], [3]]
    assert out.column("info").to_pylist()[0] == {"a": 1, "b": "x"}


def test_nested_filter_keeps_alignment(engine):
    jdf = engine.to_df(ArrowDataFrame(_nested_tbl()))
    flt = engine.filter(jdf, col("k") > lit(1))
    got = flt.as_arrow()
    assert got.column("k").to_pylist() == [2, 3]
    assert got.column("tags").to_pylist() == [[], [3]]
    assert got.column("info").to_pylist()[-1]["b"] == "z"


def test_nested_select_and_take(engine):
    jdf = engine.to_df(ArrowDataFrame(_nested_tbl()))
    sub = jdf[["k", "tags"]]
    assert sub.schema.names == ["k", "tags"]
    assert sub.as_arrow().column("tags").to_pylist() == [[1, 2], [], [3]]
    t = engine.take(jdf, 2, presort="k desc")
    got = t.as_pandas()
    assert got["k"].tolist() == [3, 2]
    assert got["tags"].tolist()[0] == [3]


def test_nested_transform_passthrough(engine):
    jdf = engine.to_df(ArrowDataFrame(_nested_tbl()))

    def first_tag(pdf: pd.DataFrame) -> pd.DataFrame:
        return pdf.assign(
            first=[t[0] if len(t) else -1 for t in pdf["tags"]]
        )[["k", "first"]]

    res = fa.transform(
        jdf,
        first_tag,
        schema="k:long,first:long",
        engine=engine,
        as_local=True,
    )
    if hasattr(res, "as_pandas"):
        got = res.as_pandas()
    elif hasattr(res, "to_pandas"):
        got = res.to_pandas()
    else:
        got = res
    assert sorted(got["first"]) == [-1, 1, 3]


def test_nested_parquet_roundtrip(engine, tmp_path):
    jdf = engine.to_df(ArrowDataFrame(_nested_tbl()))
    path = str(tmp_path / "nested.parquet")
    engine.save_df(jdf, path)
    back = engine.load_df(path)
    assert back.as_arrow().column("tags").to_pylist() == [[1, 2], [], [3]]


# ---- empty / edge partition matrix ----------------------------------------


def test_fully_filtered_frame_ops(engine):
    jdf = engine.to_df(pd.DataFrame({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]}))
    empty = engine.filter(jdf, col("v") > lit(100.0))
    assert empty.count() == 0
    agg = engine.aggregate(
        empty, PartitionSpec(by=["k"]), [ff.sum(col("v")).alias("s")]
    )
    assert agg.count() == 0
    d = engine.distinct(empty)
    assert d.count() == 0
    t = engine.take(empty, 5, presort="v")
    assert t.count() == 0


def test_empty_one_side_joins(engine):
    left = engine.to_df(pd.DataFrame({"k": [1, 2], "a": [1.0, 2.0]}))
    empty = engine.filter(
        engine.to_df(pd.DataFrame({"k": [9], "b": [9.0]})),
        col("k") < lit(0),
    )
    inner = engine.join(left, empty, how="inner", on=["k"])
    assert inner.count() == 0
    lo = engine.join(left, empty, how="left_outer", on=["k"])
    got = lo.as_pandas().sort_values("k")
    assert got["k"].tolist() == [1, 2]
    assert got["b"].isna().all()
    anti = engine.join(left, empty, how="left_anti", on=["k"])
    assert anti.count() == 2


def test_single_row_on_eight_shards(engine):
    # 1 valid row, 7+ all-padding shards: every op must mask, not branch
    jdf = engine.to_df(pd.DataFrame({"k": [5], "v": [1.5]}))
    rep = engine.repartition(jdf, PartitionSpec(algo="hash", by=["k"]))
    assert rep.as_pandas()["v"].tolist() == [1.5]
    agg = engine.aggregate(
        jdf, PartitionSpec(by=["k"]), [ff.avg(col("v")).alias("m")]
    ).as_pandas()
    assert agg["m"].tolist() == [1.5]
    u = engine.union(jdf, jdf, distinct=True)
    assert u.count() == 1


def test_skewed_valid_rows_window_and_group(engine):
    # filter empties most shards; window + groupby still exact
    rng = np.random.default_rng(4)
    pdf = pd.DataFrame(
        {"k": rng.integers(0, 5, 800), "v": rng.random(800)}
    )
    r = fa.fugue_sql(
        """
        SELECT k, ROW_NUMBER() OVER (PARTITION BY k ORDER BY v) AS rn
        FROM df WHERE v < 0.05
        """,
        df=pdf,
        engine=engine,
        as_local=True,
    )
    got = (r.to_pandas() if hasattr(r, "to_pandas") else r)
    sub = pdf[pdf["v"] < 0.05]
    assert len(got) == len(sub)
    assert got.groupby("k")["rn"].max().sum() == len(sub)


def test_empty_frame_through_workflow(engine):
    pdf = pd.DataFrame({"k": pd.array([], dtype="int64"), "v": pd.array([], dtype="float64")})

    def noop(df: pd.DataFrame) -> pd.DataFrame:
        return df

    res = fa.transform(
        pdf, noop, schema="*", partition={"by": ["k"]}, engine=engine,
        as_local=True,
    )
    got = (res.to_pandas() if hasattr(res, "to_pandas") else res)
    assert len(got) == 0
    assert list(got.columns) == ["k", "v"]
