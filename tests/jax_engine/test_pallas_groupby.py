"""One-hot MXU binned reductions (ops/pallas_groupby.py): the standalone
ops, the Pallas kernel in interpreter mode, and the engine-level backend
switch — all oracle-checked. On real TPUs the same kernels run compiled;
the backend default stays "scatter" until the on-chip A/B (BASELINE.md)."""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.ops.pallas_groupby import (
    bin_sum_count_pallas,
    bin_sum_count_xla,
    bin_sum_idx,
    bin_sum_pallas,
)
from fugue_tpu.ops.segment import set_dense_sum_backend


def _oracle(keys, vals, valid, buckets):
    s = np.zeros(buckets, np.float64)
    c = np.zeros(buckets, np.int64)
    for k, v, m in zip(keys, vals, valid):
        if m:
            s[k] += v
            c[k] += 1
    return s, c


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    n, buckets = 5_000, 256
    return (
        rng.integers(0, 200, n).astype(np.int32),
        rng.random(n).astype(np.float32),
        rng.random(n) > 0.1,
        buckets,
    )


def test_xla_onehot_matches_oracle(data):
    keys, vals, valid, buckets = data
    exp_s, exp_c = _oracle(keys, vals, valid, buckets)
    s, c = bin_sum_count_xla(
        jnp.asarray(keys), jnp.asarray(vals), jnp.asarray(valid), buckets
    )
    assert np.allclose(np.asarray(s), exp_s, atol=1e-3)
    assert (np.asarray(c) == exp_c).all()


def test_pallas_kernel_interpret_matches_oracle(data):
    keys, vals, valid, buckets = data
    exp_s, exp_c = _oracle(keys, vals, valid, buckets)
    s, c = bin_sum_count_pallas(
        jnp.asarray(keys),
        jnp.asarray(vals),
        jnp.asarray(valid),
        buckets,
        interpret=True,
    )
    assert np.allclose(np.asarray(s), exp_s, atol=1e-3)
    assert (np.asarray(c) == exp_c).all()


def test_sum_only_pallas_kernel(data):
    keys, vals, valid, buckets = data
    exp_s, _ = _oracle(keys, vals, valid, buckets)
    s = bin_sum_pallas(
        jnp.asarray(keys),
        jnp.asarray(vals),
        jnp.asarray(valid),
        buckets,
        interpret=True,
    )
    assert np.allclose(np.asarray(s), exp_s, atol=1e-3)


def test_bin_sum_idx_equals_scatter(data):
    keys, vals, valid, buckets = data
    masked = jnp.where(jnp.asarray(valid), jnp.asarray(vals), 0.0)
    scatter = jnp.zeros(buckets, jnp.float32).at[jnp.asarray(keys)].add(masked)
    onehot = bin_sum_idx(jnp.asarray(keys), masked, buckets, "onehot")
    assert np.allclose(np.asarray(scatter), np.asarray(onehot), atol=1e-3)


def test_engine_aggregate_under_onehot_backend():
    # the full device aggregate must produce identical results whichever
    # sum engine the dense kernel uses (f32 column → one-hot eligible)
    rng = np.random.default_rng(3)
    pdf = pd.DataFrame(
        {
            "k": rng.integers(0, 100, 20_000),
            "v": rng.random(20_000).astype(np.float32),
        }
    )
    eng = JaxExecutionEngine()
    spec = PartitionSpec(by=["k"])
    aggs = lambda: [  # noqa: E731
        ff.sum(col("v")).alias("s"),
        ff.count(col("v")).alias("n"),
    ]
    base = (
        eng.aggregate(eng.to_df(pdf), spec, aggs())
        .as_pandas()
        .sort_values("k")
        .reset_index(drop=True)
    )
    set_dense_sum_backend("onehot")
    try:
        got = (
            eng.aggregate(eng.to_df(pdf), spec, aggs())
            .as_pandas()
            .sort_values("k")
            .reset_index(drop=True)
        )
    finally:
        set_dense_sum_backend("scatter")
    assert (got["k"] == base["k"]).all()
    assert (got["n"].to_numpy() == base["n"].to_numpy()).all()
    assert np.allclose(got["s"], base["s"], rtol=1e-5)


def test_f64_columns_keep_scatter_even_under_onehot():
    # f64 exactness must never route through the f32 MXU path
    pdf = pd.DataFrame({"k": [0, 0, 1], "v": [1e-12, 1.0, 2.0]})
    eng = JaxExecutionEngine()
    set_dense_sum_backend("onehot")
    try:
        got = (
            eng.aggregate(
                eng.to_df(pdf),
                PartitionSpec(by=["k"]),
                [ff.sum(col("v")).alias("s")],
            )
            .as_pandas()
            .sort_values("k")
            .reset_index(drop=True)
        )
    finally:
        set_dense_sum_backend("scatter")
    # 1e-12 + 1.0 survives only in f64 accumulation (f32 rounds it away)
    assert got["s"][0] == 1.0 + 1e-12 and got["s"][0] != 1.0


def test_pallas_small_bucket_ranges_pad_to_lanes(data):
    # buckets < 128 (and non-multiples of 128) must still be correct:
    # the accumulator pads to the TPU's 128-lane tile and slices back
    keys, vals, valid, _ = data
    for buckets in (2, 5, 130, 200):
        small = np.clip(keys, 0, buckets - 1).astype(np.int32)
        exp_s, exp_c = _oracle(small, vals, valid, buckets)
        s, c = bin_sum_count_pallas(
            jnp.asarray(small),
            jnp.asarray(vals),
            jnp.asarray(valid),
            buckets,
            interpret=True,
        )
        assert s.shape == (buckets,) and c.shape == (buckets,)
        assert np.allclose(np.asarray(s), exp_s, atol=1e-3)
        assert (np.asarray(c) == exp_c).all()


def test_count_exactness_bound_documented():
    # the 2**24 f32 COUNT bound is a documented contract of these kernels
    import fugue_tpu.ops.pallas_groupby as pg

    assert "2**24" in pg.__doc__
