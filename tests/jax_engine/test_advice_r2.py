"""Regression tests for the round-2 advisor findings (ADVICE.md):

1. device zip/comap honors partition_spec.presort
2. broadcast() preserves an explicit valid mask (filtered frames)
3. NOT IN (SELECT ...) follows SQL three-valued logic when the subquery
   result contains NULLs
4. internal payload names (__mask__*, __key*, ...) never shadow user columns
5. CONNECT engine fallback surfaces real errors and stops the temp engine
"""

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.dataframe import DataFrames, PandasDataFrame
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.jax.dataframe import JaxDataFrame
from fugue_tpu.jax.zipped import ZippedJaxDataFrame


def _pd(res):
    return res.to_pandas() if hasattr(res, "to_pandas") else res


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


def test_comap_presort_device_path(engine):
    # values arrive deliberately unsorted within each key
    a = pd.DataFrame(
        {"k": [1, 1, 1, 2, 2], "v": [3.0, 1.0, 2.0, 9.0, 5.0]}
    )
    b = pd.DataFrame({"k": [1, 2], "w": [10.0, 20.0]})
    z = engine.zip(
        DataFrames([engine.to_df(a), engine.to_df(b)]),
        partition_spec=PartitionSpec(by=["k"], presort="v desc"),
    )
    assert isinstance(z, ZippedJaxDataFrame)  # device path, not blobs
    seen = {}

    def first_v(cursor, dfs):
        d1 = dfs[0].as_pandas()
        k = int(d1["k"].iloc[0])
        seen[k] = d1["v"].tolist()
        return PandasDataFrame(
            pd.DataFrame({"k": [k], "first_v": [d1["v"].iloc[0]]}),
            "k:long,first_v:double",
        )

    res = engine.comap(z, first_v, "k:long,first_v:double").as_pandas()
    assert seen[1] == [3.0, 2.0, 1.0]  # presort applied inside each group
    assert seen[2] == [9.0, 5.0]
    assert dict(zip(res["k"], res["first_v"])) == {1: 3.0, 2: 9.0}


def test_broadcast_preserves_filter_mask(engine):
    df = engine.to_df(pd.DataFrame({"a": [1, 2, 3, 4, 5, 6, 7, 8]}))
    from fugue_tpu.column import col, lit

    flt = engine.filter(df, col("a") > lit(4))
    assert isinstance(flt, JaxDataFrame)
    assert flt.valid_mask is not None  # hole-y mask, not tail padding
    b = engine.broadcast(flt)
    assert sorted(b.as_pandas()["a"].tolist()) == [5, 6, 7, 8]
    assert b.count() == 4


def test_not_in_subquery_with_nulls(engine):
    left = pd.DataFrame({"a": [1, 2, 3]})
    right = pd.DataFrame({"b": [2.0, None]})
    for eng in [NativeExecutionEngine(), engine]:
        res = fa.fugue_sql(
            """
            SELECT * FROM df WHERE a NOT IN (SELECT b FROM other)
            """,
            df=left,
            other=right,
            engine=eng,
            as_local=True,
        )
        # NULL in the set -> NOT IN is never TRUE
        assert len(_pd(res)) == 0, f"{type(eng).__name__}: {res}"
        res2 = fa.fugue_sql(
            "SELECT * FROM df WHERE a IN (SELECT b FROM other)",
            df=left,
            other=right,
            engine=eng,
            as_local=True,
        )
        assert _pd(res2)["a"].tolist() == [2]


def test_reserved_payload_name_collision(engine):
    # a user column literally named __mask__x next to a nullable column x
    pdf = pd.DataFrame(
        {
            "x": pd.array([1, None, 3, 4], dtype="Int64"),
            "__mask__x": [10, 20, 30, 40],
        }
    )
    jdf = engine.to_df(PandasDataFrame(pdf, "x:long,__mask__x:long"))
    out = engine.repartition(
        jdf, PartitionSpec(algo="hash", by=["__mask__x"], num=4)
    ).as_pandas()
    out = out.sort_values("__mask__x").reset_index(drop=True)
    assert out["__mask__x"].tolist() == [10, 20, 30, 40]
    assert out["x"].isna().tolist() == [False, True, False, False]

    # union with the same adversarial name
    u = engine.union(jdf, jdf, distinct=False).as_pandas()
    assert len(u) == 8
    assert u["x"].isna().sum() == 2

    # take with presort on the nullable column
    t = engine.take(jdf, 2, presort="x asc").as_pandas()
    assert t["x"].tolist()[0] == 1


def test_join_key_name_collision(engine):
    left = pd.DataFrame({"__key0__": [1, 2, 3], "k": [1, 2, 3]})
    right = pd.DataFrame({"k": [2, 3], "w": [20.0, 30.0]})
    res = (
        engine.join(engine.to_df(left), engine.to_df(right), how="inner", on=["k"])
        .as_pandas()
        .sort_values("k")
    )
    assert res["__key0__"].tolist() == [2, 3]
    assert res["w"].tolist() == [20.0, 30.0]


def test_connect_bad_engine_raises(engine):
    from fugue_tpu.exceptions import FuguePluginsRegistrationError

    with pytest.raises(Exception) as ei:
        fa.fugue_sql(
            """
            CONNECT not_a_real_engine SELECT * FROM df
            """,
            df=pd.DataFrame({"a": [1]}),
            engine=engine,
            as_local=True,
        )
    # the real registration error surfaces, not a masked fallback failure
    assert "not_a_real_engine" in str(ei.value)


def test_connect_fallback_engine_stops(engine):
    import fugue_tpu.execution.factory as factory

    stopped = []

    class _TrackEngine(NativeExecutionEngine):
        def stop_engine(self) -> None:
            stopped.append(True)
            super().stop_engine()

    factory.register_execution_engine(
        "tracknative", lambda conf, **kw: _TrackEngine(conf)
    )
    res = fa.fugue_sql(
        "CONNECT tracknative SELECT a+1 AS b FROM df",
        df=pd.DataFrame({"a": [1, 2]}),
        engine=engine,
        as_local=True,
    )
    assert _pd(res)["b"].tolist() == [2, 3]
    assert len(stopped) == 1
