"""Device-resident staged exchange rung (docs/shuffle.md
"device_exchange"): joins past the per-device budget but within
aggregate mesh memory move rows with the staged one-hop-at-a-time
``ppermute`` schedule — zero host round trips between partition and the
join kernel. Parity is judged against BOTH the spill path (the
bit-identical over-budget fallback) and the legacy ladder."""

import jax
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from fugue_tpu.constants import (
    FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET,
    FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED,
    FUGUE_TPU_CONF_SHUFFLE_DIR,
    FUGUE_TPU_CONF_SHUFFLE_ENABLED,
    FUGUE_TPU_CONF_SHUFFLE_EXCHANGE_STAGE_BYTES,
)
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.shuffle.strategy import choose_join_strategy, estimate_frame_bytes

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="staged exchange needs a multi-device mesh"
)

EX_HOWS = [
    "inner",
    "left_outer",
    "left_semi",
    "left_anti",
    "right_outer",
    "full_outer",
]


def _join_frames(n=3000, seed=0, nulls=True):
    """Dup keys (N:M expansion) and NULL keys in one pair of frames.
    Int32 so the NULL-masked keys stay device-kernel-eligible (the
    float64 null-view; 64-bit ints with NULLs are a standing device
    refusal and would fall back to spill on every rung)."""
    rng = np.random.default_rng(seed)
    lk = rng.integers(0, n // 8, n).astype(object)
    rk = rng.integers(0, n // 8, n).astype(object)
    if nulls:
        lk[::97] = None
        rk[::89] = None
    left = pd.DataFrame({"k": pd.array(lk, dtype="Int32"), "a": rng.normal(size=n)})
    right = pd.DataFrame({"k": pd.array(rk, dtype="Int32"), "b": rng.normal(size=n)})
    return left, right


def _norm(res):
    tbl = res.as_arrow() if not isinstance(res, pa.Table) else res
    pdf = tbl.replace_schema_metadata(None).to_pandas()
    return pdf.sort_values(list(pdf.columns)).reset_index(drop=True)


def _band_budget(left, right):
    """A budget that lands BOTH sides in the exchange band: past the
    per-device budget, within budget x shards (the estimate uses the real
    device representation, measured on a throwaway engine)."""
    probe = JaxExecutionEngine()
    both = estimate_frame_bytes(probe.to_df(left)) + estimate_frame_bytes(
        probe.to_df(right)
    )
    return max(1, both // 4)


def _engine(tmp_path, budget, enabled=True, **conf):
    return JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET: budget,
            FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED: enabled,
            FUGUE_TPU_CONF_SHUFFLE_DIR: str(tmp_path),
            **conf,
        }
    )


@pytest.fixture(scope="module")
def frames():
    return _join_frames()


@pytest.fixture(scope="module")
def budget(frames):
    return _band_budget(*frames)


@pytest.fixture(scope="module")
def eng_x(frames, budget, tmp_path_factory):
    e = _engine(tmp_path_factory.mktemp("xchg"), budget)
    yield e
    e.stop()


@pytest.fixture(scope="module")
def eng_spill(frames, budget, tmp_path_factory):
    e = _engine(tmp_path_factory.mktemp("spill"), budget, enabled=False)
    yield e
    e.stop()


@pytest.fixture(scope="module")
def eng_legacy(tmp_path_factory):
    e = JaxExecutionEngine({FUGUE_TPU_CONF_SHUFFLE_ENABLED: False})
    yield e
    e.stop()


@pytest.mark.parametrize("how", EX_HOWS)
def test_exchange_parity_vs_spill_and_legacy(
    frames, eng_x, eng_spill, eng_legacy, how
):
    """Every hash-partitionable join type, dup + NULL keys: the exchange
    rung routes (no spill) and its output is bit-identical to both the
    spill path at the same budget and the legacy ladder."""
    left, right = frames
    x_before = eng_x.stats()["shuffle"]["device_exchange_joins"]
    res = eng_x.join(eng_x.to_df(left), eng_x.to_df(right), how=how, on=["k"])
    got = _norm(res)
    st = eng_x.stats()["shuffle"]
    assert st["device_exchange_joins"] == x_before + 1, "exchange rung not used"
    assert st["joins_spill"] == 0
    sp = eng_spill.join(
        eng_spill.to_df(left), eng_spill.to_df(right), how=how, on=["k"]
    )
    spn = _norm(sp)[list(got.columns)]
    assert eng_spill.stats()["shuffle"]["joins_spill"] >= 1
    assert eng_spill.stats()["shuffle"]["device_exchange_joins"] == 0
    pd.testing.assert_frame_equal(got, spn)
    ref = eng_legacy.join(
        eng_legacy.to_df(left), eng_legacy.to_df(right), how=how, on=["k"]
    )
    pd.testing.assert_frame_equal(got, _norm(ref)[list(got.columns)])


def test_exchange_negative_zero_keys(tmp_path):
    """-0.0 and +0.0 keys match by value across the exchange, exactly as
    the join kernels and the spill partitioner treat them."""
    rng = np.random.default_rng(5)
    n = 2000
    lk = rng.integers(0, n // 8, n).astype(np.float64)
    rk = rng.integers(0, n // 8, n).astype(np.float64)
    lk[::7] = 0.0
    rk[::11] = -0.0  # must co-locate and match lk's +0.0 rows
    left = pd.DataFrame({"k": lk, "a": rng.normal(size=n)})
    right = pd.DataFrame({"k": rk, "b": rng.normal(size=n)})
    eng = _engine(tmp_path, _band_budget(left, right))
    res = eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"])
    got = _norm(res)
    assert eng.stats()["shuffle"]["device_exchange_joins"] == 1
    off = JaxExecutionEngine({FUGUE_TPU_CONF_SHUFFLE_ENABLED: False})
    ref = off.join(off.to_df(left), off.to_df(right), how="inner", on=["k"])
    pd.testing.assert_frame_equal(got, _norm(ref)[list(got.columns)])


def test_exchange_tz_aware_keys(tmp_path):
    """tz-aware timestamp keys keep value semantics through the banded
    rung (whether the exchange takes them or refuses into the spill
    fallback, the result must match the legacy ladder exactly)."""
    rng = np.random.default_rng(6)
    n = 2000
    base = pd.date_range("2024-01-01", periods=n // 8, freq="h", tz="US/Eastern")
    left = pd.DataFrame(
        {"k": base[rng.integers(0, len(base), n)], "a": rng.normal(size=n)}
    )
    right = pd.DataFrame(
        {"k": base[rng.integers(0, len(base), n)], "b": rng.normal(size=n)}
    )
    eng = _engine(tmp_path, _band_budget(left, right))
    res = eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"])
    got = _norm(res)
    off = JaxExecutionEngine({FUGUE_TPU_CONF_SHUFFLE_ENABLED: False})
    ref = off.join(off.to_df(left), off.to_df(right), how="inner", on=["k"])
    pd.testing.assert_frame_equal(got, _norm(ref)[list(got.columns)])


def test_kill_switch_bit_identity_and_span_multiset(frames, budget, tmp_path):
    """device_exchange.enabled=false restores the three-rung ladder
    bit-identically: same declared arrow schema + values, and the SAME
    engine-verb span multiset (the switch changes the shuffle transport,
    never the verb shape). The exchange run proves zero host round
    trips: shuffle.exchange spans present, zero shuffle.partition /
    shuffle.bucket spans."""
    from collections import Counter

    from fugue_tpu.obs import get_tracer

    left, right = frames
    tr = get_tracer()

    def run(enabled, sub):
        eng = _engine(tmp_path / sub, budget, enabled=enabled)
        tr.clear()
        tr.enable()
        try:
            res = eng.join(
                eng.to_df(left), eng.to_df(right), how="inner", on=["k"]
            )
            tbl = res.as_arrow().replace_schema_metadata(None)
            recs = tr.records()
        finally:
            tr.disable()
            tr.clear()
        return tbl, recs

    t_on, recs_on = run(True, "on")
    t_off, recs_off = run(False, "off")
    assert t_on.schema == t_off.schema
    a = _norm(t_on)
    b = _norm(t_off)
    pd.testing.assert_frame_equal(a, b)
    # engine-VERB multiset: identical across the switch. engine.to_df is
    # excluded — it is the ingest utility, and the spill transport calls
    # it internally per bucket (that per-bucket host round trip is
    # exactly what the exchange rung removes)
    verbs_on = Counter(
        r["name"]
        for r in recs_on
        if r["name"].startswith("engine.") and r["name"] != "engine.to_df"
    )
    verbs_off = Counter(
        r["name"]
        for r in recs_off
        if r["name"].startswith("engine.") and r["name"] != "engine.to_df"
    )
    assert verbs_on == verbs_off
    names_on = Counter(r["name"] for r in recs_on)
    names_off = Counter(r["name"] for r in recs_off)
    assert names_on["shuffle.exchange"] >= 1
    assert names_on["shuffle.partition"] == 0 and names_on["shuffle.bucket"] == 0
    assert names_off["shuffle.partition"] == 2 and names_off["shuffle.bucket"] > 0
    strat_on = [
        r["args"].get("strategy") for r in recs_on if r["name"] == "engine.join"
    ]
    strat_off = [
        r["args"].get("strategy") for r in recs_off if r["name"] == "engine.join"
    ]
    assert strat_on == ["device_exchange"]
    assert strat_off == ["shuffle_spill"]
    reasons = [
        r["args"].get("reason") for r in recs_on if r["name"] == "engine.join"
    ]
    assert "aggregate mesh memory" in (reasons[0] or "")


def test_over_budget_forces_spill_fallback(frames, tmp_path):
    """Past budget x shards the rung refuses even when enabled: the join
    spills, exactly as the three-rung ladder would."""
    left, right = frames
    budget = max(1, _band_budget(left, right) // 100)
    eng = _engine(tmp_path, budget, enabled=True)
    res = eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"])
    assert len(_norm(res)) > 0
    st = eng.stats()["shuffle"]
    assert st["joins_spill"] == 1
    assert st["device_exchange_joins"] == 0


def test_staged_schedule_peak_bytes_bound(frames, tmp_path):
    """The high-water gauge proves the staged schedule's memory model:
    per-stage collective payload never exceeds the configured stage cap,
    and a small cap means many stages (rounds x hops), not a bigger
    buffer."""
    left, right = frames
    stage = 4096
    eng = _engine(
        tmp_path,
        _band_budget(left, right),
        **{FUGUE_TPU_CONF_SHUFFLE_EXCHANGE_STAGE_BYTES: stage},
    )
    res = eng.join(eng.to_df(left), eng.to_df(right), how="inner", on=["k"])
    assert res.count() > 0
    st = eng.stats()["shuffle"]
    assert st["device_exchange_joins"] == 1
    peak = st["device_exchange_peak_stage_bytes"]
    assert 0 < peak <= stage, peak
    shards = len(jax.devices())
    assert st["device_exchange_stages"] > shards  # multiple rounds per hop
    assert st["device_budget_source"] == "conf"


def test_choose_join_strategy_band_edges():
    """The one strategy rule, at the rung's exact boundaries."""
    conf = {FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET: 1000}
    rows = 10**9  # far past broadcast_max_rows: broadcast never wins
    # inside the per-device budget: copartition, shards irrelevant
    assert (
        choose_join_strategy(conf, 400, 400, rows, n_shards=8).strategy
        == "copartition"
    )
    # the band: past budget, within budget x shards
    assert (
        choose_join_strategy(conf, 2000, 2000, rows, n_shards=8).strategy
        == "device_exchange"
    )
    # at the aggregate boundary (inclusive)
    assert (
        choose_join_strategy(conf, 4000, 4000, rows, n_shards=8).strategy
        == "device_exchange"
    )
    # past the aggregate: spill
    assert (
        choose_join_strategy(conf, 5000, 5000, rows, n_shards=8).strategy
        == "shuffle_spill"
    )
    # single device: the aggregate IS the budget — the historical ladder
    assert (
        choose_join_strategy(conf, 2000, 2000, rows, n_shards=1).strategy
        == "shuffle_spill"
    )
    # kill-switch off: the band spills
    off = dict(conf, **{FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED: False})
    assert (
        choose_join_strategy(off, 2000, 2000, rows, n_shards=8).strategy
        == "shuffle_spill"
    )


def test_mem_bucket_ingest_cache(tmp_path):
    """Satellite: a memory-resident bucket's decoded form is combined
    once and cached across reads (keyed by bucket id, ledger-accounted)
    — the second read is an ingest-cache hit serving ONE contiguous
    chunk, and release returns every byte."""
    from fugue_tpu.shuffle.partitioner import spill_partition
    from fugue_tpu.shuffle.pipeline import MemBucketLedger, SpillPipeline
    from fugue_tpu.shuffle.stats import ShuffleStats

    stats = ShuffleStats()
    rng = np.random.default_rng(0)
    n = 4000
    tbl = pa.Table.from_pandas(
        pd.DataFrame(
            {"k": rng.integers(0, 500, n), "v": rng.normal(size=n)}
        ),
        preserve_index=False,
    )
    chunks = [tbl.slice(s, 500) for s in range(0, n, 500)]
    pipe = SpillPipeline(MemBucketLedger(1 << 26), 4, stats)
    side = spill_partition(
        iter(chunks),
        tbl.schema,
        ["k"],
        ["i"],
        8,
        str(tmp_path),
        "left",
        stats=stats,
        replay=lambda: iter(chunks),
        pipeline=pipe,
    )
    assert len(side.mem_tables) == 8  # ample ledger: all buckets resident
    first = side.read_bucket(0, stats)
    again = side.read_bucket(0, stats)
    assert first is again  # the CACHED combined table, not a rebuild
    assert first.column(0).num_chunks == 1  # one contiguous chunk
    assert stats.get("mem_bucket_ingest_hits") == 1
    assert stats.get("mem_bucket_hits") == 2
    # budget accounting: the ledger tracked the combined form's delta and
    # release_mem returns every live byte
    side.release_mem()
    assert pipe.ledger.used_bytes == 0
