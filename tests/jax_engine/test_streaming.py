"""Streaming (out-of-core) device execution — `fugue_tpu/jax/streaming.py`.

The capability the round-3 VERDICT called the only road to the 1B-row
north star: aggregates and compiled maps over one-pass streams with
device memory bounded by the chunk size, not the dataset. Oracle checks
against pandas; the 100M-row tests PROVE the memory bound via
`streaming.last_run_stats` (peak live device bytes ≪ data size).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import jax.numpy as jnp

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
    FUGUE_TPU_CONF_STREAM_KEY_RANGE,
)
from fugue_tpu.dataframe import (
    ArrowDataFrame,
    IterableDataFrame,
    LocalDataFrameIterableDataFrame,
    PandasDataFrame,
)
from fugue_tpu.exceptions import FugueInvalidOperation
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.jax import streaming


AGGS = [
    ff.sum(col("v")).alias("sv"),
    ff.count(col("v")).alias("n"),
    ff.avg(col("v")).alias("m"),
    ff.min(col("v")).alias("lo"),
    ff.max(col("w")).alias("hi"),
]


def _oracle(pdf: pd.DataFrame) -> pd.DataFrame:
    g = pdf.groupby("k", as_index=False).agg(
        # engine contract: an all-NULL group sums to NULL, not 0
        sv=("v", lambda s: s.sum(min_count=1)),
        n=("v", "count"),
        m=("v", "mean"),
        lo=("v", "min"),
        hi=("w", "max"),
    )
    return g.sort_values("k").reset_index(drop=True)


def _chunk_stream(pdf: pd.DataFrame, n_chunks: int) -> LocalDataFrameIterableDataFrame:
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    step = max(1, (tbl.num_rows + n_chunks - 1) // n_chunks)
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(step, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, step)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


@pytest.fixture(scope="module")
def eng():
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 4096})
    yield e
    e.stop_engine()


def _frame(n: int, groups: int, seed: int = 0, with_nan: bool = False):
    rng = np.random.default_rng(seed)
    v = rng.random(n)
    if with_nan:
        v[rng.random(n) < 0.1] = np.nan
    return pd.DataFrame(
        {
            "k": rng.integers(0, groups, n),
            "v": v,
            "w": rng.integers(-50, 50, n),
        }
    )


def test_streaming_aggregate_matches_oracle(eng):
    pdf = _frame(50_000, 300, seed=1)
    res = eng.aggregate(_chunk_stream(pdf, 13), PartitionSpec(by=["k"]), AGGS)
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    exp = _oracle(pdf)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, atol=1e-9)
    assert streaming.last_run_stats["verb"] == "aggregate"
    assert streaming.last_run_stats["rows"] == 50_000
    assert streaming.last_run_stats["chunks"] >= 13


def test_streaming_aggregate_nan_nulls(eng):
    # NaN = NULL in v: excluded from sum/count/avg/min; all-NULL groups NULL
    pdf = _frame(20_000, 50, seed=2, with_nan=True)
    pdf.loc[pdf["k"] == 7, "v"] = np.nan  # one all-NULL group
    res = eng.aggregate(_chunk_stream(pdf, 7), PartitionSpec(by=["k"]), AGGS)
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    exp = _oracle(pdf)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, atol=1e-9)
    assert np.isnan(got.loc[got["k"] == 7, "sv"]).all()


def test_streaming_aggregate_key_range_conf_and_overflow(eng):
    pdf = pd.DataFrame(
        {"k": [5, 6, 900, 5], "v": [1.0, 2.0, 3.0, 4.0], "w": [1, 2, 3, 4]}
    )
    # first chunk sees only keys 5..6 -> probed range misses 900 -> raise
    with pytest.raises(FugueInvalidOperation, match="outside range"):
        eng.aggregate(_chunk_stream(pdf, 4), PartitionSpec(by=["k"]), AGGS)
    # declared conf range covers the whole stream
    e2 = JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 4096,
            FUGUE_TPU_CONF_STREAM_KEY_RANGE: "0,1000",
        }
    )
    try:
        res = e2.aggregate(_chunk_stream(pdf, 4), PartitionSpec(by=["k"]), AGGS)
        got = res.as_pandas().sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(
            got, _oracle(pdf), check_dtype=False, atol=1e-12
        )
    finally:
        e2.stop_engine()


def test_streaming_aggregate_null_int_raises(eng):
    pdf = pd.DataFrame(
        {
            "k": [1, 2, 1, 2],
            "v": [1.0, 2.0, 3.0, 4.0],
            "w": pd.array([1, None, 3, 4], dtype="Int64"),
        }
    )
    with pytest.raises(FugueInvalidOperation):
        eng.aggregate(_chunk_stream(pdf, 2), PartitionSpec(by=["k"]), AGGS)


def test_streaming_aggregate_empty_stream(eng):
    pdf = _frame(10, 3).iloc[:0]
    res = eng.aggregate(_chunk_stream(pdf, 1), PartitionSpec(by=["k"]), AGGS)
    assert res.count() == 0
    assert res.schema.names == ["k", "sv", "n", "m", "lo", "hi"]


def test_streaming_ineligible_plan_falls_back(eng):
    # string value column -> streaming ineligible -> materializing path
    # still answers correctly and the stream is NOT half-consumed
    pdf = pd.DataFrame({"k": [1, 1, 2], "s": ["a", "b", "c"]})
    res = eng.aggregate(
        _chunk_stream(pdf, 2),
        PartitionSpec(by=["k"]),
        [ff.count(col("s")).alias("n")],
    )
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    assert got["n"].tolist() == [2, 1]


def test_streaming_compiled_map_matches_direct(eng):
    from typing import Dict

    import jax

    import fugue_tpu.api as fa

    pdf = _frame(30_000, 10, seed=3)

    def fn(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {
            "k": cols["k"],
            "y": cols["v"] * 2.0 + jnp.abs(cols["w"].astype(jnp.float64)),
        }

    out = fa.transform(
        _chunk_stream(pdf, 9), fn, schema="k:long,y:double", engine=eng, as_fugue=True
    )
    got = out.as_pandas()
    exp = pd.DataFrame({"k": pdf["k"], "y": pdf["v"] * 2.0 + np.abs(pdf["w"])})
    pd.testing.assert_frame_equal(
        got.reset_index(drop=True), exp, check_dtype=False, atol=1e-12
    )
    assert streaming.last_run_stats["verb"] == "map"
    assert streaming.last_run_stats["chunks"] >= 8


@pytest.mark.slow
def test_streaming_aggregate_100m_rows_bounded_memory():
    """The VERDICT's done-bar: a 100M+-row aggregate on the 8-device mesh
    with peak device memory provably ≪ data size. The stream GENERATES
    chunks on the fly — data never exists in full anywhere."""
    n_chunks, chunk = 50, 2_000_000  # 100M rows
    groups = 1000
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_KEY_RANGE: f"0,{groups - 1}"})

    def gen():
        for i in range(n_chunks):
            rng = np.random.default_rng(i)
            yield pd.DataFrame(
                {
                    "k": rng.integers(0, groups, chunk),
                    "v": rng.random(chunk),
                    "w": rng.integers(-50, 50, chunk),
                }
            )

    try:
        sdf = LocalDataFrameIterableDataFrame(gen(), schema="k:long,v:double,w:long")
        res = e.aggregate(sdf, PartitionSpec(by=["k"]), AGGS)
        got = res.as_pandas().sort_values("k").reset_index(drop=True)
        assert len(got) == groups
        assert streaming.last_run_stats["rows"] == n_chunks * chunk
        data_bytes = n_chunks * chunk * 24  # 3 x 8-byte columns
        peak = streaming.last_run_stats["peak_device_bytes"]
        assert peak < data_bytes / 10, (peak, data_bytes)
        # oracle on a recomputed 10-chunk sample of the same generator
        sample = pd.concat([next(iter(gen()))]).groupby("k")["v"].count()
        assert sample.sum() == chunk
        # exact totals: sum of counts must equal row count
        assert int(got["n"].sum()) == n_chunks * chunk
    finally:
        e.stop_engine()


@pytest.mark.slow
def test_streaming_map_100m_rows_bounded_memory():
    n_chunks, chunk = 25, 2_000_000  # 50M rows in, 50M out
    e = JaxExecutionEngine({})

    def gen():
        for i in range(n_chunks):
            rng = np.random.default_rng(i)
            yield pd.DataFrame({"x": rng.random(chunk)})

    from typing import Dict

    import jax

    import fugue_tpu.api as fa

    def fn(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {"y": cols["x"] * 3.0}

    try:
        out = fa.transform(
            LocalDataFrameIterableDataFrame(gen(), schema="x:double"),
            fn,
            schema="y:double",
            engine=e,
            as_fugue=True,
        )
        assert isinstance(out, LocalDataFrameIterableDataFrame)
        # one-pass consumption: reduce chunks without materializing
        total_rows = 0
        checksum = 0.0
        for piece in out.native:
            p = piece.as_pandas()
            total_rows += len(p)
            checksum += float(p["y"].sum())
        assert total_rows == n_chunks * chunk
        data_bytes = n_chunks * chunk * 8
        peak = streaming.last_run_stats["peak_device_bytes"]
        # device working set is O((prefetch_depth + 2) x chunk) since the
        # ingest pipeline keeps decoded chunks in flight (docs/streaming.md)
        # — still ~8x under the data size, the out-of-core proof holds
        assert peak < data_bytes / 8, (peak, data_bytes)
        assert checksum > 0
    finally:
        e.stop_engine()


def test_stream_parquet_roundtrip(eng, tmp_path):
    import pyarrow.parquet as pq

    pdf = _frame(10_000, 20, seed=4)
    p = str(tmp_path / "data.parquet")
    pq.write_table(pa.Table.from_pandas(pdf, preserve_index=False), p)
    sdf = streaming.stream_parquet(p, chunk_rows=1024)
    res = eng.aggregate(sdf, PartitionSpec(by=["k"]), AGGS)
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, _oracle(pdf), check_dtype=False, atol=1e-9)
    assert streaming.last_run_stats["chunks"] >= 9


# --------------------------------------------------------------------------
# streaming broadcast-hash join
# --------------------------------------------------------------------------


def _join_stream(pdf: pd.DataFrame, n_chunks: int = 7):
    return _chunk_stream(pdf, n_chunks)


def _join_frames(n_stream: int = 20000, n_dim: int = 400, seed: int = 3):
    rng = np.random.default_rng(seed)
    big = pd.DataFrame(
        {"k": rng.integers(0, 500, n_stream), "v": rng.random(n_stream)}
    )
    dim = pd.DataFrame(
        {
            "k": np.arange(n_dim),
            "w": np.arange(n_dim) * 1.5,
            "c": np.arange(n_dim, dtype=np.int64) * 3,
            "flag": np.arange(n_dim) % 2 == 0,
        }
    )
    return big, dim


@pytest.mark.parametrize("how,p_how", [("inner", "inner"), ("left", "left")])
def test_streaming_join_stream_left(how, p_how):
    big, dim = _join_frames()
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 3000})
    try:
        res = e.join(_join_stream(big), e.to_df(dim), how=how)
        assert isinstance(res, LocalDataFrameIterableDataFrame)
        got = res.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        exp = big.merge(dim, on="k", how=p_how).sort_values(["k", "v"]).reset_index(drop=True)
        assert len(got) == len(exp)
        assert np.allclose(got["v"], exp["v"]) and (got["k"] == exp["k"]).all()
        for c in ("w", "c", "flag"):
            m = exp[c].notna().to_numpy()
            assert (got[c].isna().to_numpy() == ~m).all()
            assert (
                got[c][m].to_numpy(np.float64)
                == exp[c][m].to_numpy(np.float64)
            ).all()
        assert streaming.last_run_stats["verb"] == "join"
        assert streaming.last_run_stats["chunks"] >= 7
    finally:
        e.stop_engine()


def test_streaming_join_stream_right_outer():
    big, dim = _join_frames()
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 3000})
    try:
        res = e.join(e.to_df(dim), _join_stream(big), how="right")
        got = res.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        exp = dim.merge(big, on="k", how="right").sort_values(["k", "v"]).reset_index(drop=True)
        assert len(got) == len(exp)
        assert (got["w"].isna().to_numpy() == exp["w"].isna().to_numpy()).all()
    finally:
        e.stop_engine()


def test_streaming_join_nan_keys_never_match():
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 4})
    big = pd.DataFrame({"k": [1.0, np.nan, 2.0, np.nan, 9.0], "v": [1.0, 2, 3, 4, 5]})
    dim = pd.DataFrame({"k": [1.0, 2.0], "w": [10.0, 20.0]})
    try:
        inner = e.join(
            _join_stream(big, 2), e.to_df(dim), how="inner"
        ).as_pandas()
        assert sorted(inner["v"]) == [1.0, 3.0]
        left = (
            e.join(_join_stream(big, 2), e.to_df(dim), how="left")
            .as_pandas()
            .sort_values("v")
        )
        assert len(left) == 5 and list(left["w"].isna()) == [False, True, False, True, True]
    finally:
        e.stop_engine()


def test_streaming_join_fallback_materializes():
    """Duplicate build keys / unsupported types fall back (with a
    materializing warning) and still produce the right answer."""
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 1000})
    big = pd.DataFrame({"k": [1, 2, 2, 3], "v": [1.0, 2.0, 3.0, 4.0]})
    dup = pd.DataFrame({"k": [2, 2, 3], "w": [5.0, 6.0, 7.0]})
    try:
        res = e.join(_join_stream(big, 2), e.to_df(dup), how="inner")
        got = res.as_pandas().sort_values(["k", "v", "w"]).reset_index(drop=True)
        exp = big.merge(dup, on="k").sort_values(["k", "v", "w"]).reset_index(drop=True)
        assert len(got) == len(exp) and np.allclose(got["w"], exp["w"])
    finally:
        e.stop_engine()


def test_streaming_join_empty_build():
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 1000})
    big = pd.DataFrame({"k": [1, 2], "v": [1.0, 2.0]})
    empty = pd.DataFrame({"k": pd.Series(dtype=np.int64), "w": pd.Series(dtype=np.float64)})
    try:
        inner = e.join(_join_stream(big, 1), e.to_df(empty), how="inner").as_pandas()
        assert len(inner) == 0 and list(inner.columns) == ["k", "v", "w"]
        left = e.join(_join_stream(big, 1), e.to_df(empty), how="left").as_pandas()
        assert len(left) == 2 and left["w"].isna().all()
    finally:
        e.stop_engine()


@pytest.mark.slow
def test_streaming_join_100m_x_1m_bounded_memory():
    """VERDICT round-4 done-bar: a 100M-row stream joined against a 1M-row
    build table with peak device memory < data_bytes/10. Chunks are
    generated on the fly — the stream never exists in full."""
    n_chunks, chunk = 50, 2_000_000  # 100M probe rows
    n_dim = 1_000_000
    e = JaxExecutionEngine({})
    dim = pd.DataFrame(
        {
            "k": np.arange(n_dim, dtype=np.int64),
            "w": np.arange(n_dim, dtype=np.float64) * 0.5,
        }
    )

    def gen():
        for i in range(n_chunks):
            rng = np.random.default_rng(i)
            yield pd.DataFrame(
                {
                    # half the keyspace hits the dim table, half misses
                    "k": rng.integers(0, 2 * n_dim, chunk),
                    "v": rng.random(chunk),
                }
            )

    try:
        sdf = LocalDataFrameIterableDataFrame(gen(), schema="k:long,v:double")
        res = e.join(sdf, e.to_df(dim), how="inner")
        assert isinstance(res, LocalDataFrameIterableDataFrame)
        # one-pass consumption: count+checksum without materializing
        total, hitsum = 0, 0.0
        for part in res.native:
            p = part.as_pandas()
            total += len(p)
            hitsum += float(p["w"].sum())
            assert (p["k"] < n_dim).all()
        stats = streaming.last_run_stats
        assert stats["verb"] == "join"
        assert stats["rows"] == n_chunks * chunk
        # ~half the probe rows hit
        assert 0.45 * n_chunks * chunk < total < 0.55 * n_chunks * chunk
        data_bytes = n_chunks * chunk * 16 + n_dim * 16
        assert stats["peak_device_bytes"] < data_bytes / 10, (
            stats["peak_device_bytes"],
            data_bytes,
        )
    finally:
        e.stop_engine()


def test_streaming_join_string_and_nullable_payload():
    """Payload columns never touch the device: strings and nullable ints
    flow through with NULLs intact (only the key needs a device dtype)."""
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 3})
    big = pd.DataFrame(
        {
            "k": [1, 2, 3, 4, 2, 9],
            "v": pd.array([10, None, 30, 40, 50, 60], dtype="Int64"),
            "tag": ["a", "b", None, "d", "e", "f"],
        }
    )
    dim = pd.DataFrame(
        {
            "k": [1, 2, 3, 5],
            "name": ["one", "two", None, "five"],
            "c": pd.array([100, None, 300, 500], dtype="Int64"),
        }
    )
    try:
        sdf = _chunk_stream(big, 2)
        res = e.join(sdf, e.to_df(dim), how="left")
        assert isinstance(res, LocalDataFrameIterableDataFrame)
        got = res.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        exp = big.merge(dim, on="k", how="left").sort_values(["k", "v"]).reset_index(drop=True)
        assert len(got) == len(exp) == 6
        assert (got["name"].isna().to_numpy() == exp["name"].isna().to_numpy()).all()
        m = exp["name"].notna()
        assert list(got["name"][m]) == list(exp["name"][m])
        assert (got["c"].isna().to_numpy() == exp["c"].isna().to_numpy()).all()
        assert streaming.last_run_stats["verb"] == "join"
    finally:
        e.stop_engine()


# --------------------------------------------------------------------------
# streaming take / distinct
# --------------------------------------------------------------------------


def test_streaming_take_variants():
    rng = np.random.default_rng(5)
    pdf = pd.DataFrame(
        {"k": rng.integers(0, 6, 5000), "v": rng.random(5000)}
    )
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 700})
    try:
        # unsorted global take: early-stops (not all chunks consumed)
        r = e.take(_chunk_stream(pdf, 10), 100, presort="")
        assert r.count() == 100
        assert streaming.last_run_stats["rows"] < 5000
        # presorted global take
        r2 = e.take(_chunk_stream(pdf, 10), 5, presort="v desc").as_pandas()
        exp2 = pdf.sort_values("v", ascending=False).head(5).reset_index(drop=True)
        assert np.allclose(r2["v"], exp2["v"])
        assert streaming.last_run_stats["rows"] == 5000
        # per-key take with presort
        r3 = e.take(
            _chunk_stream(pdf, 10),
            2,
            presort="v",
            partition_spec=PartitionSpec(by=["k"]),
        ).as_pandas()
        exp3 = (
            pdf.sort_values("v").groupby("k", sort=False).head(2)
        )
        assert len(r3) == len(exp3)
        assert np.allclose(
            sorted(r3["v"]), sorted(exp3["v"])
        )
        assert streaming.last_run_stats["verb"] == "take"
    finally:
        e.stop_engine()


def test_streaming_distinct():
    pdf = pd.DataFrame(
        {
            "k": [1, 2, 1, 2, 3, np.nan, np.nan],
            "s": ["a", "b", "a", "b", "c", "d", "d"],
        }
    )
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2})
    try:
        r = e.distinct(_chunk_stream(pdf, 4)).as_pandas()
        # SQL DISTINCT: NaN == NaN, so 4 value rows + one NaN row
        assert len(r) == 4
        assert streaming.last_run_stats["verb"] == "distinct"
        assert streaming.last_run_stats["chunks"] >= 3
    finally:
        e.stop_engine()


# --------------------------------------------------------------------------
# streaming KEYED compiled map (running windows over key-clustered streams)
# --------------------------------------------------------------------------


def _clustered_frame(n_keys=40, seed=9):
    rng = np.random.default_rng(seed)
    pdf = pd.DataFrame({"k": np.repeat(np.arange(n_keys), rng.integers(5, 200, n_keys))})
    pdf["v"] = rng.random(len(pdf))
    return pdf


def _clustered_stream(pdf, step=333):
    def gen():
        for s in range(0, len(pdf), step):
            yield PandasDataFrame(pdf.iloc[s : s + step], "k:long,v:double")

    return LocalDataFrameIterableDataFrame(gen(), schema="k:long,v:double")


def _window_fn():
    from typing import Dict

    import jax

    from fugue_tpu.jax import group_ops as go

    def fn(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {
            "k": cols["k"],
            "rn": go.row_number(cols),
            "rs": go.running_sum(cols, cols["v"]),
        }

    return fn


def test_streaming_keyed_window():
    """ROW_NUMBER + running SUM over a key-clustered stream — groups are
    re-batched whole (chunks cut mid-key), one compilation for the whole
    stream, outputs match pandas cumcount/cumsum exactly."""
    import fugue_tpu.api as fa

    pdf = _clustered_frame()
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 512})
    try:
        out = fa.transform(
            _clustered_stream(pdf),
            _window_fn(),
            schema="k:long,rn:long,rs:double",
            partition=PartitionSpec(by=["k"], presort="v"),
            engine=e,
            as_fugue=True,
        )
        assert isinstance(out, LocalDataFrameIterableDataFrame)
        got = out.as_pandas().sort_values(["k", "rn"]).reset_index(drop=True)
        sp = pdf.sort_values(["k", "v"]).reset_index(drop=True)
        assert (got["rn"].to_numpy() == (sp.groupby("k").cumcount() + 1).to_numpy()).all()
        assert np.allclose(got["rs"], sp.groupby("k")["v"].cumsum())
        assert streaming.last_run_stats["verb"] == "keyed_map"
        assert streaming.last_run_stats["peak_device_bytes"] > 0
    finally:
        e.stop_engine()


def test_streaming_keyed_map_contract_violation():
    """A key reappearing after its batch closed (stream NOT clustered)
    raises with remediation, instead of silently wrong per-group results."""
    import fugue_tpu.api as fa

    pdf = pd.DataFrame(
        {"k": [1] * 50 + [2] * 50 + [1] * 50, "v": np.random.rand(150)}
    )
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 64})
    try:
        out = fa.transform(
            _clustered_stream(pdf, step=60),
            _window_fn(),
            schema="k:long,rn:long,rs:double",
            partition=PartitionSpec(by=["k"], presort="v"),
            engine=e,
            as_fugue=True,
        )
        with pytest.raises(FugueInvalidOperation, match="not key-clustered"):
            out.as_pandas()
    finally:
        e.stop_engine()


def test_streaming_keyed_map_key_run_exceeds_capacity():
    import fugue_tpu.api as fa

    pdf = pd.DataFrame({"k": [7] * 500 + [8] * 10, "v": np.random.rand(510)})
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 128})
    try:
        with pytest.raises(FugueInvalidOperation, match="exceeds the chunk capacity"):
            out = fa.transform(
                _clustered_stream(pdf, step=100),
                _window_fn(),
                schema="k:long,rn:long,rs:double",
                partition=PartitionSpec(by=["k"], presort="v"),
                engine=e,
                as_fugue=True,
            )
            out.as_pandas()
    finally:
        e.stop_engine()


def test_running_ops_reject_dense_plan():
    """running_sum/row_number need ordered shard-complete groups; the
    dense (unsorted, groups-span-shards) plan must refuse loudly."""
    import fugue_tpu.api as fa

    pdf = pd.DataFrame({"k": [1, 2, 1, 2], "v": [1.0, 2.0, 3.0, 4.0]})
    e = JaxExecutionEngine()
    try:
        with pytest.raises(Exception, match="sorted plan"):
            # no presort -> dense plan eligible -> running op must raise
            fa.transform(
                e.to_df(pdf),
                _window_fn(),
                schema="k:long,rn:long,rs:double",
                partition=PartitionSpec(by=["k"]),
                engine=e,
                as_fugue=True,
            )
    finally:
        e.stop_engine()


def test_streaming_keyed_map_rejects_nan_keys_and_strings():
    import fugue_tpu.api as fa

    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 64})
    try:
        nan_keys = pd.DataFrame({"k": [1.0, 1.0, np.nan, np.nan], "v": [1.0, 2, 3, 4]})

        def gen_nan():
            yield PandasDataFrame(nan_keys, "k:double,v:double")

        with pytest.raises(FugueInvalidOperation, match="NULL/NaN partition keys"):
            fa.transform(
                LocalDataFrameIterableDataFrame(gen_nan(), schema="k:double,v:double"),
                _window_fn(),
                schema="k:double,rn:long,rs:double",
                partition=PartitionSpec(by=["k"], presort="v"),
                engine=e,
                as_fugue=True,
            ).as_pandas()
        strs = pd.DataFrame({"k": [1, 1], "v": [1.0, 2.0], "s": ["a", "b"]})

        def gen_s():
            yield PandasDataFrame(strs, "k:long,v:double,s:str")

        with pytest.raises(FugueInvalidOperation, match="numeric/bool columns"):
            fa.transform(
                LocalDataFrameIterableDataFrame(gen_s(), schema="k:long,v:double,s:str"),
                _window_fn(),
                schema="k:long,rn:long,rs:double",
                partition=PartitionSpec(by=["k"], presort="v"),
                engine=e,
                as_fugue=True,
            ).as_pandas()
    finally:
        e.stop_engine()


def test_window_kernels_lag_lead_running_minmax():
    """The remaining window kernels over a key-clustered stream: LAG/LEAD
    and running MIN/MAX, validated against pandas shift/cummin/cummax."""
    from typing import Dict

    import jax

    import fugue_tpu.api as fa
    from fugue_tpu.jax import group_ops as go

    def fn(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {
            "k": cols["k"],
            "v": cols["v"],
            "lag1": go.lag(cols, cols["v"]),
            "lead2": go.lead(cols, cols["v"], n=2),
            "rmin": go.running_min(cols, cols["v"]),
            "rmax": go.running_max(cols, cols["v"]),
        }

    pdf = _clustered_frame(n_keys=25, seed=13)
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 400})
    try:
        out = fa.transform(
            _clustered_stream(pdf, step=271),
            fn,
            schema="k:long,v:double,lag1:double,lead2:double,rmin:double,rmax:double",
            partition=PartitionSpec(by=["k"], presort="v"),
            engine=e,
            as_fugue=True,
        )
        got = out.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        sp = pdf.sort_values(["k", "v"]).reset_index(drop=True)
        g = sp.groupby("k")["v"]
        exp_lag = g.shift(1)
        exp_lead = g.shift(-2)
        assert (got["lag1"].isna().to_numpy() == exp_lag.isna().to_numpy()).all()
        m = exp_lag.notna().to_numpy()
        assert np.allclose(got["lag1"].to_numpy()[m], exp_lag.to_numpy()[m])
        m2 = exp_lead.notna().to_numpy()
        assert (got["lead2"].isna().to_numpy() == exp_lead.isna().to_numpy()).all()
        assert np.allclose(got["lead2"].to_numpy()[m2], exp_lead.to_numpy()[m2])
        assert np.allclose(got["rmin"], g.cummin())
        assert np.allclose(got["rmax"], g.cummax())
    finally:
        e.stop_engine()


def test_running_minmax_skip_nan_and_int_lag_needs_fill():
    from typing import Dict

    import jax

    import fugue_tpu.api as fa
    from fugue_tpu.jax import group_ops as go

    def fn(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {
            "k": cols["k"],
            "rmin": go.running_min(cols, cols["v"]),
            "rmax": go.running_max(cols, cols["v"]),
        }

    # NaN (NULL) rows are skipped, not propagated (SQL window semantics)
    pdf = pd.DataFrame(
        {"k": [1, 1, 1, 1], "v": [5.0, np.nan, 3.0, 4.0], "o": [1.0, 2, 3, 4]}
    )
    e = JaxExecutionEngine()
    try:
        out = fa.transform(
            e.to_df(pdf),
            fn,
            schema="k:long,rmin:double,rmax:double",
            partition=PartitionSpec(by=["k"], presort="v"),
            engine=e,
            as_fugue=True,
        ).as_pandas()
        # sorted by v: NaN first (NULL), then 3,4,5
        assert np.allclose(
            sorted(out["rmin"].dropna()), [3.0, 3.0, 3.0]
        )
        assert np.allclose(sorted(out["rmax"].dropna()), [3.0, 4.0, 5.0])

        def bad(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            return {"k": cols["k"], "p": go.lag(cols, cols["k"])}

        with pytest.raises(Exception, match="explicit fill"):
            fa.transform(
                e.to_df(pdf),
                bad,
                schema="k:long,p:long",
                partition=PartitionSpec(by=["k"], presort="v"),
                engine=e,
                as_fugue=True,
            ).as_pandas()
    finally:
        e.stop_engine()


# --------------------------------------------------------------------------
# streaming zip/comap (key-SORTED streams)
# --------------------------------------------------------------------------


def _sorted_stream(pdf, schema, step):
    def gen():
        for s in range(0, len(pdf), step):
            yield PandasDataFrame(pdf.iloc[s : s + step], schema)

    return LocalDataFrameIterableDataFrame(gen(), schema=schema)


def _zip_merge():
    def merge(d1: pd.DataFrame, d2: pd.DataFrame) -> pd.DataFrame:
        k = int(d1["k"].iloc[0]) if len(d1) else int(d2["k"].iloc[0])
        return pd.DataFrame(
            {
                "k": [k],
                "n1": [len(d1)],
                "n2": [len(d2)],
                "sv": [float(d1["v"].sum()) if len(d1) else 0.0],
            }
        )

    return merge


def _zip_frames_sorted(seed=11):
    rng = np.random.default_rng(seed)
    a = pd.DataFrame(
        {"k": np.sort(rng.integers(0, 30, 900)), "v": rng.random(900)}
    )
    b = pd.DataFrame(
        {"k": np.sort(rng.integers(5, 35, 400)), "w": rng.random(400)}
    )
    return a, b


@pytest.mark.parametrize("how", ["inner", "left_outer"])
def test_streaming_zip_comap(how):
    from fugue_tpu import FugueWorkflow

    a, b = _zip_frames_sorted()
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 128})
    try:
        dag = FugueWorkflow()
        za = dag.df(_sorted_stream(a, "k:long,v:double", 97))
        zb = dag.df(_sorted_stream(b, "k:long,w:double", 61))
        res = dag.zip(za, zb, how=how, partition={"by": ["k"]}).transform(
            _zip_merge(), schema="k:long,n1:long,n2:long,sv:double"
        )
        res.yield_dataframe_as("r", as_local=True)
        dag.run(e)
        got = (
            dag.yields["r"].result.as_pandas().sort_values("k").reset_index(drop=True)
        )
        if how == "inner":
            exp_keys = sorted(set(a["k"]) & set(b["k"]))
        else:
            exp_keys = sorted(set(a["k"]))
        assert got["k"].tolist() == exp_keys
        ea, eb = a.groupby("k").size(), b.groupby("k").size()
        ev = a.groupby("k")["v"].sum()
        assert got.set_index("k")["n1"].to_dict() == {
            k: int(ea[k]) for k in exp_keys
        }
        assert got.set_index("k")["n2"].to_dict() == {
            k: int(eb.get(k, 0)) for k in exp_keys
        }
        assert np.allclose(
            got.set_index("k")["sv"], [ev[k] for k in exp_keys]
        )
        assert streaming.last_run_stats["verb"] == "comap"
        assert streaming.last_run_stats["chunks"] >= 10
    finally:
        e.stop_engine()


def test_streaming_zip_rejects_unsorted():
    from fugue_tpu import FugueWorkflow

    a = pd.DataFrame({"k": [3, 1, 2], "v": [1.0, 2.0, 3.0]})
    b = pd.DataFrame({"k": [1, 2], "w": [1.0, 2.0]})
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2})
    try:
        dag = FugueWorkflow()
        z = dag.zip(
            dag.df(_sorted_stream(a, "k:long,v:double", 2)),
            dag.df(_sorted_stream(b, "k:long,w:double", 2)),
            partition={"by": ["k"]},
        ).transform(_zip_merge(), schema="k:long,n1:long,n2:long,sv:double")
        z.yield_dataframe_as("r", as_local=True)
        with pytest.raises(Exception, match="not sorted ascending"):
            dag.run(e)
    finally:
        e.stop_engine()


def test_streaming_zip_bounded_dim_any_order():
    """A bounded co-input needs NO pre-sorting (it is host-sorted on
    entry); only actual streams carry the sorted contract."""
    from fugue_tpu import FugueWorkflow

    rng = np.random.default_rng(3)
    a = pd.DataFrame({"k": np.sort(rng.integers(0, 10, 200)), "v": rng.random(200)})
    dim = pd.DataFrame({"k": [3, 1, 2, 7], "w": [1.0, 2.0, 3.0, 4.0]})
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 32})
    try:
        dag = FugueWorkflow()
        r = dag.zip(
            dag.df(_sorted_stream(a, "k:long,v:double", 37)),
            dag.df(dim),
            partition={"by": ["k"]},
        ).transform(_zip_merge(), schema="k:long,n1:long,n2:long,sv:double")
        r.yield_dataframe_as("r", as_local=True)
        dag.run(e)
        got = dag.yields["r"].result.as_pandas().sort_values("k")
        assert got["k"].tolist() == sorted(set(a["k"]) & set(dim["k"]))
    finally:
        e.stop_engine()


def test_streaming_zip_force_drain_checks_order():
    """An unsorted chunk arriving through the force-progress drain (an
    input pinned at the horizon) still raises — not silent mis-grouping."""
    from fugue_tpu import FugueWorkflow

    A = pd.DataFrame({"k": [2, 2, 5, 5, 5, 2, 9], "v": [1.0] * 7})
    B = pd.DataFrame({"k": [2, 5, 9], "w": [1.0] * 3})
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2})
    try:
        dag = FugueWorkflow()
        r = dag.zip(
            dag.df(_sorted_stream(A, "k:long,v:double", 2)),
            dag.df(_sorted_stream(B, "k:long,w:double", 1)),
            partition={"by": ["k"]},
        ).transform(_zip_merge(), schema="k:long,n1:long,n2:long,sv:double")
        r.yield_dataframe_as("r", as_local=True)
        with pytest.raises(Exception, match="not sorted ascending"):
            dag.run(e)
    finally:
        e.stop_engine()
