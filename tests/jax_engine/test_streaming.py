"""Streaming (out-of-core) device execution — `fugue_tpu/jax/streaming.py`.

The capability the round-3 VERDICT called the only road to the 1B-row
north star: aggregates and compiled maps over one-pass streams with
device memory bounded by the chunk size, not the dataset. Oracle checks
against pandas; the 100M-row tests PROVE the memory bound via
`streaming.last_run_stats` (peak live device bytes ≪ data size).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import jax.numpy as jnp

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
    FUGUE_TPU_CONF_STREAM_KEY_RANGE,
)
from fugue_tpu.dataframe import (
    ArrowDataFrame,
    IterableDataFrame,
    LocalDataFrameIterableDataFrame,
)
from fugue_tpu.exceptions import FugueInvalidOperation
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.jax import streaming


AGGS = [
    ff.sum(col("v")).alias("sv"),
    ff.count(col("v")).alias("n"),
    ff.avg(col("v")).alias("m"),
    ff.min(col("v")).alias("lo"),
    ff.max(col("w")).alias("hi"),
]


def _oracle(pdf: pd.DataFrame) -> pd.DataFrame:
    g = pdf.groupby("k", as_index=False).agg(
        # engine contract: an all-NULL group sums to NULL, not 0
        sv=("v", lambda s: s.sum(min_count=1)),
        n=("v", "count"),
        m=("v", "mean"),
        lo=("v", "min"),
        hi=("w", "max"),
    )
    return g.sort_values("k").reset_index(drop=True)


def _chunk_stream(pdf: pd.DataFrame, n_chunks: int) -> LocalDataFrameIterableDataFrame:
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    step = max(1, (tbl.num_rows + n_chunks - 1) // n_chunks)
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(step, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, step)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


@pytest.fixture(scope="module")
def eng():
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 4096})
    yield e
    e.stop_engine()


def _frame(n: int, groups: int, seed: int = 0, with_nan: bool = False):
    rng = np.random.default_rng(seed)
    v = rng.random(n)
    if with_nan:
        v[rng.random(n) < 0.1] = np.nan
    return pd.DataFrame(
        {
            "k": rng.integers(0, groups, n),
            "v": v,
            "w": rng.integers(-50, 50, n),
        }
    )


def test_streaming_aggregate_matches_oracle(eng):
    pdf = _frame(50_000, 300, seed=1)
    res = eng.aggregate(_chunk_stream(pdf, 13), PartitionSpec(by=["k"]), AGGS)
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    exp = _oracle(pdf)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, atol=1e-9)
    assert streaming.last_run_stats["verb"] == "aggregate"
    assert streaming.last_run_stats["rows"] == 50_000
    assert streaming.last_run_stats["chunks"] >= 13


def test_streaming_aggregate_nan_nulls(eng):
    # NaN = NULL in v: excluded from sum/count/avg/min; all-NULL groups NULL
    pdf = _frame(20_000, 50, seed=2, with_nan=True)
    pdf.loc[pdf["k"] == 7, "v"] = np.nan  # one all-NULL group
    res = eng.aggregate(_chunk_stream(pdf, 7), PartitionSpec(by=["k"]), AGGS)
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    exp = _oracle(pdf)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, atol=1e-9)
    assert np.isnan(got.loc[got["k"] == 7, "sv"]).all()


def test_streaming_aggregate_key_range_conf_and_overflow(eng):
    pdf = pd.DataFrame(
        {"k": [5, 6, 900, 5], "v": [1.0, 2.0, 3.0, 4.0], "w": [1, 2, 3, 4]}
    )
    # first chunk sees only keys 5..6 -> probed range misses 900 -> raise
    with pytest.raises(FugueInvalidOperation, match="outside range"):
        eng.aggregate(_chunk_stream(pdf, 4), PartitionSpec(by=["k"]), AGGS)
    # declared conf range covers the whole stream
    e2 = JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 4096,
            FUGUE_TPU_CONF_STREAM_KEY_RANGE: "0,1000",
        }
    )
    try:
        res = e2.aggregate(_chunk_stream(pdf, 4), PartitionSpec(by=["k"]), AGGS)
        got = res.as_pandas().sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(
            got, _oracle(pdf), check_dtype=False, atol=1e-12
        )
    finally:
        e2.stop_engine()


def test_streaming_aggregate_null_int_raises(eng):
    pdf = pd.DataFrame(
        {
            "k": [1, 2, 1, 2],
            "v": [1.0, 2.0, 3.0, 4.0],
            "w": pd.array([1, None, 3, 4], dtype="Int64"),
        }
    )
    with pytest.raises(FugueInvalidOperation):
        eng.aggregate(_chunk_stream(pdf, 2), PartitionSpec(by=["k"]), AGGS)


def test_streaming_aggregate_empty_stream(eng):
    pdf = _frame(10, 3).iloc[:0]
    res = eng.aggregate(_chunk_stream(pdf, 1), PartitionSpec(by=["k"]), AGGS)
    assert res.count() == 0
    assert res.schema.names == ["k", "sv", "n", "m", "lo", "hi"]


def test_streaming_ineligible_plan_falls_back(eng):
    # string value column -> streaming ineligible -> materializing path
    # still answers correctly and the stream is NOT half-consumed
    pdf = pd.DataFrame({"k": [1, 1, 2], "s": ["a", "b", "c"]})
    res = eng.aggregate(
        _chunk_stream(pdf, 2),
        PartitionSpec(by=["k"]),
        [ff.count(col("s")).alias("n")],
    )
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    assert got["n"].tolist() == [2, 1]


def test_streaming_compiled_map_matches_direct(eng):
    from typing import Dict

    import jax

    import fugue_tpu.api as fa

    pdf = _frame(30_000, 10, seed=3)

    def fn(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {
            "k": cols["k"],
            "y": cols["v"] * 2.0 + jnp.abs(cols["w"].astype(jnp.float64)),
        }

    out = fa.transform(
        _chunk_stream(pdf, 9), fn, schema="k:long,y:double", engine=eng, as_fugue=True
    )
    got = out.as_pandas()
    exp = pd.DataFrame({"k": pdf["k"], "y": pdf["v"] * 2.0 + np.abs(pdf["w"])})
    pd.testing.assert_frame_equal(
        got.reset_index(drop=True), exp, check_dtype=False, atol=1e-12
    )
    assert streaming.last_run_stats["verb"] == "map"
    assert streaming.last_run_stats["chunks"] >= 8


@pytest.mark.slow
def test_streaming_aggregate_100m_rows_bounded_memory():
    """The VERDICT's done-bar: a 100M+-row aggregate on the 8-device mesh
    with peak device memory provably ≪ data size. The stream GENERATES
    chunks on the fly — data never exists in full anywhere."""
    n_chunks, chunk = 50, 2_000_000  # 100M rows
    groups = 1000
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_KEY_RANGE: f"0,{groups - 1}"})

    def gen():
        for i in range(n_chunks):
            rng = np.random.default_rng(i)
            yield pd.DataFrame(
                {
                    "k": rng.integers(0, groups, chunk),
                    "v": rng.random(chunk),
                    "w": rng.integers(-50, 50, chunk),
                }
            )

    try:
        sdf = LocalDataFrameIterableDataFrame(gen(), schema="k:long,v:double,w:long")
        res = e.aggregate(sdf, PartitionSpec(by=["k"]), AGGS)
        got = res.as_pandas().sort_values("k").reset_index(drop=True)
        assert len(got) == groups
        assert streaming.last_run_stats["rows"] == n_chunks * chunk
        data_bytes = n_chunks * chunk * 24  # 3 x 8-byte columns
        peak = streaming.last_run_stats["peak_device_bytes"]
        assert peak < data_bytes / 10, (peak, data_bytes)
        # oracle on a recomputed 10-chunk sample of the same generator
        sample = pd.concat([next(iter(gen()))]).groupby("k")["v"].count()
        assert sample.sum() == chunk
        # exact totals: sum of counts must equal row count
        assert int(got["n"].sum()) == n_chunks * chunk
    finally:
        e.stop_engine()


@pytest.mark.slow
def test_streaming_map_100m_rows_bounded_memory():
    n_chunks, chunk = 25, 2_000_000  # 50M rows in, 50M out
    e = JaxExecutionEngine({})

    def gen():
        for i in range(n_chunks):
            rng = np.random.default_rng(i)
            yield pd.DataFrame({"x": rng.random(chunk)})

    from typing import Dict

    import jax

    import fugue_tpu.api as fa

    def fn(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {"y": cols["x"] * 3.0}

    try:
        out = fa.transform(
            LocalDataFrameIterableDataFrame(gen(), schema="x:double"),
            fn,
            schema="y:double",
            engine=e,
            as_fugue=True,
        )
        assert isinstance(out, LocalDataFrameIterableDataFrame)
        # one-pass consumption: reduce chunks without materializing
        total_rows = 0
        checksum = 0.0
        for piece in out.native:
            p = piece.as_pandas()
            total_rows += len(p)
            checksum += float(p["y"].sum())
        assert total_rows == n_chunks * chunk
        data_bytes = n_chunks * chunk * 8
        peak = streaming.last_run_stats["peak_device_bytes"]
        assert peak < data_bytes / 10, (peak, data_bytes)
        assert checksum > 0
    finally:
        e.stop_engine()


def test_stream_parquet_roundtrip(eng, tmp_path):
    import pyarrow.parquet as pq

    pdf = _frame(10_000, 20, seed=4)
    p = str(tmp_path / "data.parquet")
    pq.write_table(pa.Table.from_pandas(pdf, preserve_index=False), p)
    sdf = streaming.stream_parquet(p, chunk_rows=1024)
    res = eng.aggregate(sdf, PartitionSpec(by=["k"]), AGGS)
    got = res.as_pandas().sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, _oracle(pdf), check_dtype=False, atol=1e-9)
    assert streaming.last_run_stats["chunks"] >= 9
