"""Apply the full contract suites to the jax engine on an 8-device CPU mesh —
the same pattern the reference uses to exercise distributed semantics on
local sessions (SURVEY §4)."""

from typing import Any

import pytest

from fugue_tpu.dataframe import DataFrame
from fugue_tpu.execution import ExecutionEngine
from fugue_tpu.jax import JaxDataFrame, JaxExecutionEngine
from fugue_tpu_test import BuiltInTests, DataFrameTests, ExecutionEngineTests


class TestJaxDataFrame(DataFrameTests.Tests):
    def df(self, data: Any = None, schema: Any = None) -> DataFrame:
        return JaxDataFrame(data, schema)


class TestJaxExecutionEngine(ExecutionEngineTests.Tests):
    def make_engine(self) -> ExecutionEngine:
        return JaxExecutionEngine(dict(test=True))


class TestJaxBuiltIn(BuiltInTests.Tests):
    def make_engine(self) -> ExecutionEngine:
        return JaxExecutionEngine(dict(test=True))


class TestJaxSpecific:
    """TPU-engine specific behavior beyond the shared contract."""

    def test_device_aggregate_matches_host(self):
        import numpy as np
        import pandas as pd

        from fugue_tpu.collections import PartitionSpec
        from fugue_tpu.column import col, functions as f

        e = JaxExecutionEngine()
        pdf = pd.DataFrame(
            {"k": np.random.randint(0, 7, 500), "v": np.random.rand(500)}
        )
        jdf = e.to_df(pdf)
        res = e.aggregate(
            jdf,
            PartitionSpec(by=["k"]),
            [f.sum(col("v")).alias("s"), f.avg(col("v")).alias("m")],
        )
        got = res.as_pandas().sort_values("k").reset_index(drop=True)
        exp = (
            pdf.groupby("k")
            .agg(s=("v", "sum"), m=("v", "mean"))
            .reset_index()
            .sort_values("k")
            .reset_index(drop=True)
        )
        assert np.allclose(got[["s", "m"]], exp[["s", "m"]])
        e.stop()

    def test_device_aggregate_nan_is_null(self):
        """NaN floats on device are NULLs: excluded from every aggregate and
        all-NULL groups yield NULL — independent of shard layout (both the
        dense-bucket and sort+segment kernels)."""
        import numpy as np
        import pyarrow as pa

        from fugue_tpu.collections import PartitionSpec
        from fugue_tpu.column import col, functions as f

        e = JaxExecutionEngine()
        # arrow keeps NaN as a value (null_count==0) → column goes to device
        for keys in ([1, 1, 2, 2, 3, 3], [1, 1, 2, 2, 10**9, 10**9]):
            tbl = pa.table(
                {
                    "k": pa.array(keys, pa.int64()),
                    "v": pa.array(
                        [1.0, np.nan, np.nan, np.nan, 2.0, 4.0], pa.float64()
                    ),
                }
            )
            jdf = e.to_df(tbl)
            assert "v" in jdf.device_cols  # precondition: device path
            res = e.aggregate(
                jdf,
                PartitionSpec(by=["k"]),
                [
                    f.sum(col("v")).alias("s"),
                    f.count(col("v")).alias("n"),
                    f.min(col("v")).alias("lo"),
                    f.max(col("v")).alias("hi"),
                    f.avg(col("v")).alias("m"),
                ],
            )
            got = res.as_pandas().sort_values("k").reset_index(drop=True)
            assert got["n"].tolist() == [1, 0, 2]
            assert got["s"][0] == 1.0 and np.isnan(got["s"][1]) and got["s"][2] == 6.0
            assert np.isnan(got["lo"][1]) and np.isnan(got["hi"][1])
            assert got["lo"][2] == 2.0 and got["hi"][2] == 4.0
            assert got["m"][0] == 1.0 and np.isnan(got["m"][1]) and got["m"][2] == 3.0
        e.stop()

    def test_compiled_shard_map_transform(self):
        from typing import Dict

        import jax
        import numpy as np
        import pandas as pd

        import fugue_tpu.api as fa

        e = JaxExecutionEngine()
        pdf = pd.DataFrame({"a": np.arange(100, dtype=np.int64)})
        jdf = e.to_df(pdf)

        def plus_one(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            return {"a": cols["a"] + 1}

        out = fa.transform(jdf, plus_one, schema="a:long", engine=e, as_fugue=True)
        assert isinstance(out, JaxDataFrame)
        assert out.as_pandas()["a"].tolist() == list(range(1, 101))
        e.stop()

    def test_validate_compiled_catches_mask_ignoring_udf(self):
        """fugue.tpu.validate_compiled: a per-shard reduction that ignores
        the __valid__ mask reads padding rows — the debug cross-check
        raises instead of silently corrupting results."""
        from typing import Dict

        import jax
        import jax.numpy as jnp
        import numpy as np
        import pandas as pd

        import fugue_tpu.api as fa
        from fugue_tpu.exceptions import FugueInvalidOperation

        e = JaxExecutionEngine({"fugue.tpu.validate_compiled": True})
        # 10 rows over 8 shards → padding rows exist
        pdf = pd.DataFrame({"a": np.arange(10, dtype=np.float64) + 1.0})
        jdf = e.to_df(pdf)

        def bad_mean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            return {"s": cols["a"].mean()[None]}  # ignores __valid__

        with pytest.raises(FugueInvalidOperation, match="__valid__"):
            fa.transform(jdf, bad_mean, schema="s:double", engine=e, as_fugue=True)

        def good_sum(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            import jax.numpy as jnp

            v = jnp.where(cols["__valid__"], cols["a"], 0.0)
            return {"s": v.sum()[None]}

        out = fa.transform(jdf, good_sum, schema="s:double", engine=e, as_fugue=True)
        assert float(out.as_pandas()["s"].sum()) == float(pdf["a"].sum())
        # elementwise UDFs pass the check untouched
        def plus(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            return {"a": cols["a"] + 1}

        out2 = fa.transform(jdf, plus, schema="a:double", engine=e, as_fugue=True)
        assert sorted(out2.as_pandas()["a"].tolist()) == [
            float(x) for x in range(2, 12)
        ]
        e.stop()

    def test_broadcast_replicates(self):
        import pandas as pd

        e = JaxExecutionEngine()
        df = e.to_df(pd.DataFrame({"a": [1, 2]}))
        b = e.broadcast(df)
        assert b.count() == 2
        e.stop()

    def test_engine_registered_by_name(self):
        from fugue_tpu.execution import make_execution_engine

        e = make_execution_engine("jax")
        assert isinstance(e, JaxExecutionEngine)
        e.stop()

    def test_engine_inferred_from_frame(self):
        import pandas as pd

        from fugue_tpu.execution import make_execution_engine

        df = JaxDataFrame(pd.DataFrame({"a": [1]}))
        e = make_execution_engine(infer_by=[df])
        assert isinstance(e, JaxExecutionEngine)
        e.stop()
