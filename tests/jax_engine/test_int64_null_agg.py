"""Exact device SUM/AVG/MIN/MAX/COUNT over null-masked 64-bit ints
(VERDICT r2 #8): hi/lo 32-bit split accumulation preserves exactness at
2^62 magnitudes, where a float64 NaN view (and the pandas oracle, which
ingests nullable ints as float64) rounds.
"""

import numpy as np
import pandas as pd
import pytest

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.dataframe import PandasDataFrame
from fugue_tpu.jax import JaxExecutionEngine


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


def _aggs():
    return [
        ff.sum(col("v")).alias("s"),
        ff.avg(col("v")).alias("m"),
        ff.min(col("v")).alias("lo"),
        ff.max(col("v")).alias("hi"),
        ff.count(col("v")).alias("c"),
    ]


def test_int64_null_aggregates_exact_at_2pow62(engine):
    rng = np.random.default_rng(0)
    n = 5000
    base = np.int64(2**62)
    vals = base + rng.integers(-1000, 1000, n).astype(np.int64)
    mask = rng.random(n) < 0.2
    v = pd.array(np.where(mask, None, vals), dtype="Int64")
    pdf = pd.DataFrame({"k": rng.integers(0, 19, n), "v": v})
    extra = pd.DataFrame(
        {"k": [19, 19], "v": pd.array([None, None], dtype="Int64")}
    )
    pdf = pd.concat([pdf, extra], ignore_index=True)
    fdf = PandasDataFrame(pdf, "k:long,v:long")
    jdf = engine.to_df(fdf)
    assert "v" in jdf.null_masks  # masked int64 stayed device-resident
    got = (
        engine.aggregate(jdf, PartitionSpec(by=["k"]), _aggs())
        .as_pandas()
        .sort_values("k")
        .reset_index(drop=True)
    )
    # exact python-int ground truth (the pandas oracle is float64-lossy
    # for nullable int64 — the device path is strictly more faithful)
    grp = pdf.groupby("k")["v"]
    sums = grp.sum(min_count=1)
    mins, maxs, cnts = grp.min(), grp.max(), grp.count()
    for _, row in got.iterrows():
        k = int(row["k"])
        if k == 19:  # all-NULL group
            assert pd.isna(row["s"]) and pd.isna(row["lo"]) and pd.isna(row["hi"])
            assert pd.isna(row["m"]) and int(row["c"]) == 0
            continue
        assert int(row["s"]) == int(sums[k]), k
        assert int(row["lo"]) == int(mins[k]), k
        assert int(row["hi"]) == int(maxs[k]), k
        assert int(row["c"]) == int(cnts[k]), k
        # true mean via python bigints (the int64 SUM wraps identically on
        # both paths, but AVG assembles hi/lo in float BEFORE any wrap)
        vals_k = [int(x) for x in pdf[pdf["k"] == k]["v"].dropna()]
        assert np.isclose(row["m"], sum(vals_k) / len(vals_k)), k


def test_int64_null_sum_negative_and_mixed(engine):
    pdf = pd.DataFrame(
        {
            "k": [1, 1, 1, 2, 2],
            "v": pd.array(
                [-(2**62), 2**62, None, -5, 7], dtype="Int64"
            ),
        }
    )
    jdf = engine.to_df(PandasDataFrame(pdf, "k:long,v:long"))
    got = (
        engine.aggregate(
            jdf,
            PartitionSpec(by=["k"]),
            [ff.sum(col("v")).alias("s"), ff.min(col("v")).alias("lo")],
        )
        .as_pandas()
        .sort_values("k")
        .reset_index(drop=True)
    )
    assert int(got["s"].iloc[0]) == 0  # -(2^62) + 2^62 exactly
    assert int(got["s"].iloc[1]) == 2
    assert int(got["lo"].iloc[0]) == -(2**62)
    assert int(got["lo"].iloc[1]) == -5


def test_int64_extreme_values_with_nulls(engine):
    # values AT the int64 extremes coexist with NULLs (fill-identity check)
    pdf = pd.DataFrame(
        {
            "k": [1, 1, 1],
            "v": pd.array(
                [np.iinfo(np.int64).max, np.iinfo(np.int64).min, None],
                dtype="Int64",
            ),
        }
    )
    jdf = engine.to_df(PandasDataFrame(pdf, "k:long,v:long"))
    got = engine.aggregate(
        jdf,
        PartitionSpec(by=["k"]),
        [
            ff.min(col("v")).alias("lo"),
            ff.max(col("v")).alias("hi"),
            ff.count(col("v")).alias("c"),
        ],
    ).as_pandas()
    assert int(got["lo"].iloc[0]) == np.iinfo(np.int64).min
    assert int(got["hi"].iloc[0]) == np.iinfo(np.int64).max
    assert int(got["c"].iloc[0]) == 2


def test_uint64_null_falls_back_to_host(engine):
    # uint64 >= 2^63 has no faithful device post-processing — host engine
    # must compute it (and exactly)
    pdf = pd.DataFrame(
        {
            "k": [1, 1, 1],
            "v": pd.array([2**63 + 5, 2**63 + 9, None], dtype="UInt64"),
        }
    )
    jdf = engine.to_df(PandasDataFrame(pdf, "k:long,v:ulong"))
    got = engine.aggregate(
        jdf,
        PartitionSpec(by=["k"]),
        [ff.max(col("v")).alias("hi"), ff.count(col("v")).alias("c")],
    ).as_pandas()
    assert int(got["hi"].iloc[0]) == 2**63 + 9
    assert int(got["c"].iloc[0]) == 2


def test_oracle_now_matches_device_exactness(engine):
    """Round-3 fidelity closure: the host oracle used to ingest nullable
    int64 as float64 (lossy past 2^53); with arrow-backed Int64 ingestion
    (``_utils/arrow.py``) the oracle's SUM/MIN/MAX are exact at 2^62 and
    AGREE with the device hi/lo-split path, NULLs included."""
    from fugue_tpu.execution import NativeExecutionEngine

    rng = np.random.default_rng(3)
    n = 2000
    base = np.int64(2**62)
    vals = base + rng.integers(-1000, 1000, n).astype(np.int64)
    mask = rng.random(n) < 0.2
    v = pd.array(np.where(mask, None, vals), dtype="Int64")
    pdf = pd.DataFrame({"k": rng.integers(0, 7, n), "v": v})
    fdf = PandasDataFrame(pdf, "k:long,v:long")

    oracle = NativeExecutionEngine()
    try:
        host_in = oracle.to_df(fdf).as_pandas()
        # ingestion no longer widens to float64
        assert str(host_in["v"].dtype) == "Int64", host_in["v"].dtype
        spec = PartitionSpec(by=["k"])
        exp = (
            oracle.aggregate(oracle.to_df(fdf), spec, _aggs())
            .as_pandas()
            .sort_values("k")
            .reset_index(drop=True)
        )
        got = (
            engine.aggregate(engine.to_df(fdf), spec, _aggs())
            .as_pandas()
            .sort_values("k")
            .reset_index(drop=True)
        )
        # SUM/MIN/MAX/COUNT exact equality (not allclose) at 2^62 scale
        for c in ("s", "lo", "hi", "c"):
            assert got[c].tolist() == exp[c].tolist(), c
        truth = pdf.dropna(subset=["v"]).groupby("k")["v"].sum()
        assert exp.set_index("k")["s"].astype("int64").to_dict() == {
            k: int(x) for k, x in truth.items()
        }
    finally:
        oracle.stop()
