"""Scale-hardening tests: cross-shard merged dense tables, the distinct
cardinality guard, and a large randomized-schema stress run vs the oracle
(env-gated: FUGUE_TPU_STRESS=1)."""

import os

import numpy as np
import pandas as pd
import pytest

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as f
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


def test_dense_table_is_cross_shard_merged(engine):
    """The dense kernel's outputs are replicated (one table), not
    per-shard — host transfer is O(buckets)."""
    from fugue_tpu.ops.segment import _dedupe_cols, _get_compiled_dense

    import jax

    pdf = pd.DataFrame(
        {"k": np.arange(1000, dtype=np.int64) % 16, "v": np.ones(1000)}
    )
    jdf = engine.to_df(pdf)
    sig, arrays = _dedupe_cols([("s", "sum", jdf.device_cols["v"], False)])
    compiled = _get_compiled_dense(engine.mesh, 32, sig)
    outs = compiled(
        jdf.device_cols["k"], np.int64(0), *arrays, jdf.device_valid_mask()
    )
    present = np.asarray(jax.device_get(outs[0]))
    assert present.shape == (32,)  # replicated, not (shards*32,)
    assert present[:16].sum() == 1000  # globally merged counts
    sums = np.asarray(jax.device_get(outs[1]))
    assert np.allclose(sums[:16], np.bincount(np.arange(1000) % 16))


def test_distinct_cardinality_guard(engine):
    """Near-unique frames fall back to the host path instead of pushing
    every row through the partial-transfer machinery."""
    n = 5000
    pdf = pd.DataFrame({"a": np.arange(n, dtype=np.int64) + 10**9})
    e = JaxExecutionEngine({"fugue.tpu.max_partial_rows": 100})
    try:
        res = e.distinct(e.to_df(pdf))
        assert res.count() == n  # correct via host fallback
    finally:
        e.stop()


@pytest.mark.skipif(
    os.environ.get("FUGUE_TPU_STRESS", "") != "1",
    reason="large stress run; set FUGUE_TPU_STRESS=1",
)
def test_stress_randomized_schema_vs_oracle(engine):
    """≥50M rows, randomized schema/cardinalities, device vs oracle."""
    rng = np.random.default_rng(7)
    n = 50_000_000
    n_groups = int(rng.integers(10, 100_000))
    pdf = pd.DataFrame(
        {
            "k": rng.integers(0, n_groups, n),
            "v": rng.random(n),
            "w": rng.integers(-1000, 1000, n).astype(np.int64),
        }
    )
    # sprinkle NULLs into a float col via arrow-null-free NaN values
    nan_idx = rng.integers(0, n, n // 100)
    pdf.loc[nan_idx, "v"] = np.nan
    import pyarrow as pa

    tbl = pa.table(
        {
            "k": pa.array(pdf["k"].to_numpy()),
            "v": pa.array(pdf["v"].to_numpy(), from_pandas=False),
            "w": pa.array(pdf["w"].to_numpy()),
        }
    )
    spec = PartitionSpec(by=["k"])
    aggs = [
        f.sum(col("v")).alias("sv"),
        f.count(col("v")).alias("nv"),
        f.min(col("w")).alias("lw"),
        f.max(col("w")).alias("hw"),
        f.avg(col("v")).alias("mv"),
    ]
    got = (
        engine.aggregate(engine.to_df(tbl), spec, aggs)
        .as_pandas()
        .sort_values("k")
        .reset_index(drop=True)
    )
    exp = (
        pdf.groupby("k")
        .agg(
            sv=("v", lambda s: s.sum(min_count=1)),
            nv=("v", "count"),
            lw=("w", "min"),
            hw=("w", "max"),
            mv=("v", "mean"),
        )
        .reset_index()
    )
    assert len(got) == len(exp)
    assert np.allclose(got["sv"], exp["sv"], equal_nan=True)
    assert (got["nv"] == exp["nv"]).all()
    assert (got["lw"] == exp["lw"]).all() and (got["hw"] == exp["hw"]).all()
    assert np.allclose(got["mv"], exp["mv"], equal_nan=True)
