"""Keyed compiled maps: groupby-apply that never leaves the device.

The device-native answer to the reference's group-map path
(fugue_spark/execution_engine.py:192). Two physical plans behind ONE UDF
contract (fugue_tpu.jax.group_ops):

- dense: integer keys, bounded range, no presort — no exchange, no sort;
  group tables merge across shards inside the fn (psum via group_ops).
- sorted: hash co-location + shard sort — used for presort / wide ranges.
"""

from typing import Dict

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.jax import JaxExecutionEngine, group_ops as go
from fugue_tpu.jax.dataframe import JaxDataFrame


@pytest.fixture(scope="module")
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


def _demean(cols):
    m = go.mean(cols, cols["v"])
    return {"k": cols["k"], "v": cols["v"], "d": cols["v"] - go.per_row(cols, m)}


def test_keyed_compiled_demean_matches_oracle(engine):
    import jax

    rng = np.random.default_rng(5)
    pdf = pd.DataFrame(
        {"k": rng.integers(0, 37, 10_000), "v": rng.random(10_000)}
    )
    jdf = engine.to_df(pdf)

    def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return _demean(cols)

    out = fa.transform(
        jdf,
        demean,
        schema="k:long,v:double,d:double",
        partition={"by": ["k"]},
        engine=engine,
        as_fugue=True,
    )
    assert isinstance(out, JaxDataFrame)  # stayed on device
    got = out.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    exp = pdf.assign(d=pdf["v"] - pdf.groupby("k")["v"].transform("mean"))
    exp = exp.sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_keyed_compiled_wide_range_sorted_plan(engine):
    import jax

    # keys spread over a huge range -> dense plan ineligible -> sorted plan
    rng = np.random.default_rng(6)
    ks = rng.integers(0, 2**40, 17)
    pdf = pd.DataFrame(
        {"k": np.repeat(ks, 100), "v": rng.random(1700)}
    )

    def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return _demean(cols)

    out = fa.transform(
        engine.to_df(pdf),
        demean,
        schema="k:long,v:double,d:double",
        partition={"by": ["k"]},
        engine=engine,
        as_fugue=True,
    )
    assert isinstance(out, JaxDataFrame)
    got = out.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    exp = pdf.assign(d=pdf["v"] - pdf.groupby("k")["v"].transform("mean"))
    exp = exp.sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_keyed_compiled_multi_key_and_presort(engine):
    import jax
    import jax.numpy as jnp

    pdf = pd.DataFrame(
        {
            "a": [1, 1, 1, 2, 2, 2, 1, 1],
            "b": [0, 0, 1, 0, 0, 1, 1, 0],
            "v": [5.0, 3.0, 9.0, 2.0, 8.0, 1.0, 7.0, 4.0],
        }
    )
    jdf = engine.to_df(pdf)

    def gap_to_max(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        # per (a,b) group: distance to the group's max (presort forces the
        # sorted plan; group_ops stays correct there too)
        mx = go.segment_max(cols, cols["v"])
        return {
            "a": cols["a"],
            "b": cols["b"],
            "gap": go.per_row(cols, mx) - cols["v"],
        }

    out = fa.transform(
        jdf,
        gap_to_max,
        schema="a:long,b:long,gap:double",
        partition={"by": ["a", "b"], "presort": "v desc"},
        engine=engine,
        as_fugue=True,
    )
    assert isinstance(out, JaxDataFrame)
    got = out.as_pandas()
    exp = pdf.assign(
        gap=pdf.groupby(["a", "b"])["v"].transform("max") - pdf["v"]
    )
    m_got = got.sort_values(["a", "b", "gap"]).reset_index(drop=True)
    m_exp = exp[["a", "b", "gap"]].sort_values(["a", "b", "gap"]).reset_index(
        drop=True
    )
    pd.testing.assert_frame_equal(m_got, m_exp, check_dtype=False)


def test_keyed_compiled_multi_key_dense(engine):
    import jax

    rng = np.random.default_rng(7)
    pdf = pd.DataFrame(
        {
            "a": rng.integers(0, 10, 5000),
            "b": rng.integers(100, 140, 5000),
            "v": rng.random(5000),
        }
    )

    def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        m = go.mean(cols, cols["v"])
        return {
            "a": cols["a"],
            "b": cols["b"],
            "d": cols["v"] - go.per_row(cols, m),
        }

    out = fa.transform(
        engine.to_df(pdf),
        demean,
        schema="a:long,b:long,d:double",
        partition={"by": ["a", "b"]},
        engine=engine,
        as_fugue=True,
    )
    got = out.as_pandas().sort_values(["a", "b", "d"]).reset_index(drop=True)
    exp = pdf.assign(
        d=pdf["v"] - pdf.groupby(["a", "b"])["v"].transform("mean")
    )[["a", "b", "d"]].sort_values(["a", "b", "d"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_keyed_compiled_padding_isolation(engine):
    import jax

    # 10 rows over 8 shards -> padding rows on most shards; per-group counts
    # must not include padding
    pdf = pd.DataFrame({"k": [1] * 5 + [2] * 5, "v": [1.0] * 10})
    jdf = engine.to_df(pdf)

    def group_count(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        cnt = go.segment_count(cols)
        return {"k": cols["k"], "n": go.per_row(cols, cnt)}

    out = fa.transform(
        jdf,
        group_count,
        schema="k:long,n:double",
        partition={"by": ["k"]},
        engine=engine,
        as_fugue=True,
    )
    got = out.as_pandas()
    assert len(got) == 10
    assert got.groupby("k")["n"].first().tolist() == [5.0, 5.0]


def test_keyed_compiled_min_sum_helpers(engine):
    import jax

    pdf = pd.DataFrame(
        {"k": [1, 1, 2, 2, 2], "v": [4.0, 2.0, 10.0, 30.0, 20.0]}
    )

    def stats(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        s = go.segment_sum(cols, cols["v"])
        lo = go.segment_min(cols, cols["v"])
        return {
            "k": cols["k"],
            "s": go.per_row(cols, s),
            "lo": go.per_row(cols, lo),
        }

    out = fa.transform(
        engine.to_df(pdf),
        stats,
        schema="k:long,s:double,lo:double",
        partition={"by": ["k"]},
        engine=engine,
        as_fugue=True,
    )
    got = out.as_pandas().drop_duplicates("k").sort_values("k")
    assert got["s"].tolist() == [6.0, 60.0]
    assert got["lo"].tolist() == [2.0, 10.0]


def _str_key_frame(n=6000, nulls=False, seed=11):
    rng = np.random.default_rng(seed)
    cities = np.array(["osaka", "lima", "oslo", "pune", "kiel", "bern"])
    k = cities[rng.integers(0, len(cities), n)].astype(object)
    if nulls:
        k[rng.random(n) < 0.1] = None
    return pd.DataFrame({"k": pd.Series(k, dtype="str"), "v": rng.random(n)})


def _expected_demean(pdf):
    exp = pdf.assign(d=pdf["v"] - pdf.groupby("k", dropna=False)["v"].transform("mean"))
    return exp.sort_values(["k", "v"]).reset_index(drop=True)


def test_keyed_compiled_string_keys_dense(engine):
    """Dictionary-encoded partition keys run compiled: the UDF groups by
    the codes (opaque, passed through) and the engine reattaches the
    dictionary — dense plan (code range is static metadata, no probe)."""
    import jax

    pdf = _str_key_frame()
    jdf = engine.to_df(pdf)

    def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return _demean(cols)

    out = fa.transform(
        jdf,
        demean,
        schema="k:str,v:double,d:double",
        partition={"by": ["k"]},
        engine=engine,
        as_fugue=True,
    )
    assert isinstance(out, JaxDataFrame)  # stayed on device
    assert out.encodings.get("k", {}).get("kind") == "dict"  # reattached
    got = out.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, _expected_demean(pdf), check_dtype=False
    )


def test_keyed_compiled_string_keys_sorted_plan_and_nulls(engine):
    """Presort forces the sorted plan; NULL string keys (-1 code) form
    their own group, matching the oracle's dropna=False grouping."""
    import jax

    pdf = _str_key_frame(nulls=True, seed=17)
    jdf = engine.to_df(pdf)

    def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return _demean(cols)

    out = fa.transform(
        jdf,
        demean,
        schema="k:str,v:double,d:double",
        partition={"by": ["k"], "presort": "v"},
        engine=engine,
        as_fugue=True,
    )
    assert isinstance(out, JaxDataFrame)
    got = out.as_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, _expected_demean(pdf), check_dtype=False
    )


def test_keyed_compiled_mixed_string_int_keys(engine):
    import jax

    rng = np.random.default_rng(23)
    n = 4000
    pdf = pd.DataFrame(
        {
            "g": pd.Series(
                np.array(["x", "y", "z"])[rng.integers(0, 3, n)], dtype="str"
            ),
            "k": rng.integers(0, 11, n),
            "v": rng.random(n),
        }
    )
    jdf = engine.to_df(pdf)

    def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        m = go.mean(cols, cols["v"])
        return {
            "g": cols["g"],
            "k": cols["k"],
            "d": cols["v"] - go.per_row(cols, m),
        }

    out = fa.transform(
        jdf,
        demean,
        schema="g:str,k:long,d:double",
        partition={"by": ["g", "k"]},
        engine=engine,
        as_fugue=True,
    )
    assert isinstance(out, JaxDataFrame)
    got = (
        out.as_pandas()
        .groupby(["g", "k"])["d"]
        .mean()
        .abs()
        .max()
    )
    assert got < 1e-12


def test_keyed_compiled_string_keys_bad_shapes_raise(engine):
    import jax

    pdf = pd.DataFrame(
        {
            "k": pd.Series(["a", "a", "b"], dtype="str"),
            "s": pd.Series(["p", "q", "r"], dtype="str"),
            "v": [1.0, 2.0, 3.0],
        }
    )
    jdf = engine.to_df(pdf)

    def f(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:  # pragma: no cover
        return cols

    # a non-key encoded column: the UDF would see meaningless codes
    with pytest.raises(Exception):
        fa.transform(
            jdf, f, schema="k:str,s:str,v:double",
            partition={"by": ["k"]}, engine=engine, as_fugue=True,
        )
    # encoded key changing type in the output schema: codes can't become
    # longs — must raise, not silently emit code values
    jdf2 = engine.to_df(pdf[["k", "v"]])
    with pytest.raises(Exception):
        fa.transform(
            jdf2, f, schema="k:long,v:double",
            partition={"by": ["k"]}, engine=engine, as_fugue=True,
        )
