"""Device-pipeline tests: mask filters, select decomposition, SQL lowering."""

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import SelectColumns, col, functions as f, lit
from fugue_tpu.jax import JaxDataFrame, JaxExecutionEngine


@pytest.fixture
def engine():
    e = JaxExecutionEngine()
    yield e
    e.stop()


@pytest.fixture
def pdf():
    rng = np.random.default_rng(7)
    return pd.DataFrame({"k": rng.integers(0, 10, 5003), "v": rng.random(5003)})


class TestDeviceFilter:
    def test_filter_is_mask_only(self, engine, pdf):
        jdf = engine.to_df(pdf)
        flt = engine.filter(jdf, col("v") > 0.5)
        assert isinstance(flt, JaxDataFrame)
        assert flt.valid_mask is not None
        # the underlying device buffers are the SAME objects — no data moved
        assert flt.device_cols["v"] is jdf.device_cols["v"]
        exp = pdf[pdf["v"] > 0.5]
        assert flt.count() == len(exp)

    def test_filter_roundtrip_values(self, engine, pdf):
        flt = engine.filter(engine.to_df(pdf), col("v") > 0.9)
        exp = pdf[pdf["v"] > 0.9].reset_index(drop=True)
        got = flt.as_pandas().reset_index(drop=True)
        assert np.allclose(got["v"], exp["v"])

    def test_chained_filters(self, engine, pdf):
        e1 = engine.filter(engine.to_df(pdf), col("v") > 0.3)
        e2 = engine.filter(e1, col("k") < 5)
        exp = pdf[(pdf["v"] > 0.3) & (pdf["k"] < 5)]
        assert e2.count() == len(exp)

    def test_filter_none_pass(self, engine, pdf):
        flt = engine.filter(engine.to_df(pdf), col("v") > 2.0)
        assert flt.count() == 0
        assert flt.as_pandas().shape[0] == 0

    def test_filtered_aggregate(self, engine, pdf):
        flt = engine.filter(engine.to_df(pdf), col("v") > 0.5)
        agg = engine.aggregate(
            flt, PartitionSpec(by=["k"]),
            [f.sum(col("v")).alias("s"), f.count(col("v")).alias("n")],
        )
        g = agg.as_pandas().sort_values("k").reset_index(drop=True)
        x = (
            pdf[pdf["v"] > 0.5]
            .groupby("k")
            .agg(s=("v", "sum"), n=("v", "count"))
            .reset_index()
        )
        assert np.allclose(g["s"], x["s"]) and (g["n"] == x["n"]).all()

    def test_filtered_projection(self, engine, pdf):
        flt = engine.filter(engine.to_df(pdf), col("k") == 3)
        proj = engine.select(flt, SelectColumns(col("k"), (col("v") * 2).alias("v2")))
        exp = pdf[pdf["k"] == 3]
        assert proj.count() == len(exp)
        assert np.allclose(
            np.sort(proj.as_pandas()["v2"]), np.sort(exp["v"] * 2)
        )

    def test_filtered_compiled_map(self, engine, pdf):
        from typing import Dict

        import jax

        def double(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            return {"v2": cols["v"] * 2.0}

        flt = engine.filter(engine.to_df(pdf), col("v") > 0.5)
        out = fa.transform(flt, double, schema="v2:double", engine=engine, as_fugue=True)
        exp = pdf[pdf["v"] > 0.5]
        assert out.count() == len(exp)


class TestSelectDecomposition:
    def test_where_groupby_having_on_device(self, engine, pdf):
        res = engine.select(
            engine.to_df(pdf),
            SelectColumns(col("k"), f.sum(col("v")).alias("s"), f.count(col("v")).alias("n")),
            where=col("v") > 0.5,
            having=col("n") > 100,
        )
        exp = pdf[pdf["v"] > 0.5].groupby("k").agg(s=("v", "sum"), n=("v", "count")).reset_index()
        exp = exp[exp["n"] > 100]
        g = res.as_pandas().sort_values("k").reset_index(drop=True)
        assert np.allclose(g["s"], exp.sort_values("k")["s"])

    def test_sql_full_pipeline(self, pdf):
        r = fa.fugue_sql(
            "SELECT k, SUM(v) AS s FROM pdf WHERE k < 5 GROUP BY k ORDER BY k",
            engine="jax",
            as_fugue=True,
        )
        g = r.as_pandas()
        exp = pdf[pdf["k"] < 5].groupby("k").agg(s=("v", "sum")).reset_index()
        assert np.allclose(g["s"], exp["s"])


class TestDeviceDistinct:
    def test_single_int_col(self, engine):
        pdf = pd.DataFrame({"k": np.random.default_rng(0).integers(0, 50, 10000)})
        d = engine.distinct(engine.to_df(pdf))
        assert sorted(d.as_pandas()["k"]) == sorted(pdf["k"].drop_duplicates())

    def test_multi_int_cols(self, engine):
        rng = np.random.default_rng(1)
        pdf = pd.DataFrame({"a": rng.integers(0, 5, 3000), "b": rng.integers(0, 5, 3000)})
        d = engine.distinct(engine.to_df(pdf))
        assert len(d.as_pandas()) == len(pdf.drop_duplicates())

    def test_after_filter(self, engine, pdf):
        flt = engine.filter(engine.to_df(pdf[["k"]]), col("k") < 4)
        d = engine.distinct(flt)
        assert sorted(d.as_pandas()["k"]) == [0, 1, 2, 3]

    def test_host_fallback_for_strings(self, engine):
        d = engine.distinct(engine.to_df(pd.DataFrame({"s": ["a", "b", "a"]})))
        assert sorted(d.as_pandas()["s"]) == ["a", "b"]


class TestDeviceJoin:
    def _frames(self, engine):
        rng = np.random.default_rng(5)
        fact = pd.DataFrame({"k": rng.integers(0, 50, 20001), "v": rng.random(20001)})
        dim = pd.DataFrame({"k": np.arange(0, 40), "w": np.arange(0, 40) * 1.0})
        return fact, dim, engine.to_df(fact), engine.to_df(dim)

    def test_inner_broadcast_join(self, engine):
        fact, dim, jf, jd = self._frames(engine)
        res = engine.join(jf, jd, "inner", on=["k"])
        assert isinstance(res, JaxDataFrame) and res.valid_mask is not None
        exp = fact.merge(dim, on="k", how="inner")
        assert res.count() == len(exp)
        g = res.as_pandas()
        assert np.allclose(sorted(g["w"] + g["v"]), sorted(exp["w"] + exp["v"]))

    def test_join_then_aggregate_on_device(self, engine):
        fact, dim, jf, jd = self._frames(engine)
        res = engine.join(jf, jd, "inner", on=["k"])
        agg = engine.aggregate(
            res, PartitionSpec(by=["k"]), [f.sum(col("w")).alias("sw")]
        )
        exp = fact.merge(dim, on="k").groupby("k").agg(sw=("w", "sum")).reset_index()
        g = agg.as_pandas().sort_values("k").reset_index(drop=True)
        assert np.allclose(g["sw"], exp["sw"])

    def test_filtered_fact_join(self, engine):
        fact, dim, jf, jd = self._frames(engine)
        flt = engine.filter(jf, col("v") > 0.5)
        res = engine.join(flt, jd, "inner", on=["k"])
        assert res.count() == len(fact[fact["v"] > 0.5].merge(dim, on="k"))

    def test_non_unique_dim_falls_back(self, engine):
        fact, _, jf, _ = self._frames(engine)
        dim2 = pd.DataFrame({"k": [1, 1, 2], "x": [1.0, 2.0, 3.0]})
        res = engine.join(jf, engine.to_df(dim2), "inner", on=["k"])
        assert res.count() == len(fact.merge(dim2, on="k"))

    def test_no_match_join(self, engine):
        fact, _, jf, _ = self._frames(engine)
        dim3 = pd.DataFrame({"k": np.arange(1000, 1010), "y": np.arange(10) * 1.0})
        res = engine.join(jf, engine.to_df(dim3), "inner", on=["k"])
        assert res.count() == 0

    def test_left_join_host_path(self, engine):
        fact, dim, jf, jd = self._frames(engine)
        res = engine.join(jf, jd, "left_outer", on=["k"])
        assert res.count() == len(fact)


class TestDeviceSampleTake:
    def test_frac_sample_mask_only(self, engine, pdf):
        s = engine.sample(engine.to_df(pdf), frac=0.2, seed=7)
        assert isinstance(s, JaxDataFrame) and s.valid_mask is not None
        assert 0.1 * len(pdf) < s.count() < 0.3 * len(pdf)
        # deterministic
        assert engine.sample(engine.to_df(pdf), frac=0.2, seed=7).count() == s.count()

    def test_take_topn_device(self, engine, pdf):
        t = engine.take(engine.to_df(pdf), 4, presort="v desc")
        exp = pdf.sort_values("v", ascending=False).head(4)
        assert np.allclose(sorted(t.as_pandas()["v"]), sorted(exp["v"]))

    def test_take_keyed_fallback(self, engine, pdf):
        t = engine.take(
            engine.to_df(pdf), 1, presort="v desc",
            partition_spec=PartitionSpec(by=["k"]),
        )
        assert t.count() == pdf["k"].nunique()

    def test_sample_after_filter(self, engine, pdf):
        flt = engine.filter(engine.to_df(pdf), col("v") > 0.5)
        s = engine.sample(flt, frac=0.5, seed=3)
        assert s.count() <= flt.count()


class TestDeviceTake:
    """Sort-based device take: multi-key, int64 full range, NaN tails."""

    @pytest.fixture(scope="class")
    def eng(self):
        from fugue_tpu.jax import JaxExecutionEngine

        e = JaxExecutionEngine()
        yield e
        e.stop()

    def test_multi_key_presort(self, eng):
        pdf = pd.DataFrame(
            {"a": [1, 1, 2, 2, 1], "b": [9.0, 1.0, 5.0, 0.5, 3.0]}
        )
        res = eng.take(eng.to_df(pdf), 3, presort="a,b desc")
        assert res.as_array() == [[1, 9.0], [1, 3.0], [1, 1.0]]

    def test_large_int64_keys(self, eng):
        big = 1 << 60
        pdf = pd.DataFrame({"a": [big + 3, big + 1, big + 2, -big]})
        res = eng.take(eng.to_df(pdf), 2, presort="a desc")
        assert res.as_array() == [[big + 3], [big + 2]]

    def test_nan_fills_tail(self, eng):
        import pyarrow as pa

        # NaN as device value (arrow keeps it): top-3 of 2 numbers + NaNs
        tbl = pa.table(
            {"a": pa.array([2.0, float("nan"), 1.0, float("nan")], pa.float64())}
        )
        res = eng.take(eng.to_df(tbl), 3, presort="a")
        vals = [r[0] for r in res.as_array()]
        assert vals[0] == 1.0 and vals[1] == 2.0
        assert len(vals) == 3 and (vals[2] is None or vals[2] != vals[2])

    def test_take_after_filter_skewed_mask(self, eng):
        from fugue_tpu.column import col

        pdf = pd.DataFrame({"a": np.arange(1000, dtype=np.int64)})
        f = eng.filter(eng.to_df(pdf), col("a") < 10)  # only low shards valid
        res = eng.take(f, 8, presort="a desc")
        assert [r[0] for r in res.as_array()] == list(range(9, 1, -1))


class TestDeviceSetOps:
    @pytest.fixture(scope="class")
    def eng(self):
        from fugue_tpu.jax import JaxExecutionEngine

        e = JaxExecutionEngine()
        yield e
        e.stop()

    @pytest.fixture(scope="class")
    def oracle(self):
        from fugue_tpu.execution import NativeExecutionEngine

        e = NativeExecutionEngine()
        yield e
        e.stop()

    def _cmp(self, eng, oracle, op, a, b, **kw):
        got = getattr(eng, op)(eng.to_df(a), eng.to_df(b), **kw).as_pandas()
        exp = getattr(oracle, op)(
            oracle.to_df(a), oracle.to_df(b), **kw
        ).as_pandas()
        cols = list(got.columns)
        pd.testing.assert_frame_equal(
            got.sort_values(cols).reset_index(drop=True),
            exp.sort_values(cols).reset_index(drop=True),
            check_dtype=False,
        )

    def test_union_device(self, eng, oracle):
        rng = np.random.default_rng(0)
        a = pd.DataFrame({"k": rng.integers(0, 20, 300), "v": rng.integers(0, 3, 300)})
        b = pd.DataFrame({"k": rng.integers(0, 20, 200), "v": rng.integers(0, 3, 200)})
        self._cmp(eng, oracle, "union", a, b, distinct=True)
        self._cmp(eng, oracle, "union", a, b, distinct=False)
        got = eng.union(eng.to_df(a), eng.to_df(b), distinct=False)
        assert isinstance(got, JaxDataFrame) and got.count() == 500

    def test_union_after_filter(self, eng, oracle):
        a = pd.DataFrame({"x": np.arange(100, dtype=np.int64)})
        b = pd.DataFrame({"x": np.arange(50, 150, dtype=np.int64)})
        fa_ = eng.filter(eng.to_df(a), col("x") < 30)
        fb = eng.filter(eng.to_df(b), col("x") >= 120)
        got = eng.union(fa_, fb, distinct=False).as_pandas()
        assert sorted(got["x"]) == list(range(30)) + list(range(120, 150))

    def test_subtract_intersect_device(self, eng, oracle):
        rng = np.random.default_rng(1)
        a = pd.DataFrame({"k": rng.integers(0, 15, 200), "v": rng.integers(0, 2, 200)})
        b = pd.DataFrame({"k": rng.integers(0, 15, 150), "v": rng.integers(0, 2, 150)})
        self._cmp(eng, oracle, "subtract", a, b, distinct=True)
        self._cmp(eng, oracle, "intersect", a, b, distinct=True)
        got = eng.subtract(eng.to_df(a), eng.to_df(b))
        assert isinstance(got, JaxDataFrame) and got.host_table is None

    def test_distinct_nan_keys_group_once(self, eng, oracle):
        import pyarrow as pa

        tbl = pa.table(
            {"v": pa.array([1.0, float("nan"), float("nan"), 1.0], pa.float64())}
        )
        got = eng.distinct(eng.to_df(tbl)).as_pandas()
        # oracle semantics: NaN/NULL is one distinct value
        assert len(got) == 2
        assert got["v"].isna().sum() == 1

    def test_groupby_nan_float_key(self, eng, oracle):
        import pyarrow as pa

        from fugue_tpu.collections import PartitionSpec
        from fugue_tpu.column import functions as ff

        tbl = pa.table(
            {
                "k": pa.array([1.0, float("nan"), float("nan")], pa.float64()),
                "v": pa.array([1.0, 2.0, 3.0], pa.float64()),
            }
        )
        got = (
            eng.aggregate(
                eng.to_df(tbl),
                PartitionSpec(by=["k"]),
                [ff.sum(col("v")).alias("s")],
            )
            .as_pandas()
            .sort_values("k", na_position="last")
            .reset_index(drop=True)
        )
        assert got["s"].tolist() == [1.0, 5.0]  # one NULL group
        assert got["k"].isna().tolist() == [False, True]


class TestEncodedUnion:
    @pytest.fixture(scope="class")
    def eng(self):
        from fugue_tpu.jax import JaxExecutionEngine

        e = JaxExecutionEngine()
        yield e
        e.stop()

    @pytest.fixture(scope="class")
    def oracle(self):
        from fugue_tpu.execution import NativeExecutionEngine

        e = NativeExecutionEngine()
        yield e
        e.stop()

    def test_union_string_columns_on_device(self, eng, oracle):
        a = pd.DataFrame({"s": ["x", "y", None], "v": [1.0, 2.0, 3.0]})
        b = pd.DataFrame({"s": ["y", "z", None], "v": [2.0, 4.0, 3.0]})
        got = eng.union(eng.to_df(a), eng.to_df(b), distinct=True)
        assert isinstance(got, JaxDataFrame) and got.host_table is None
        g = got.as_pandas()
        e = oracle.union(
            oracle.to_df(a), oracle.to_df(b), distinct=True
        ).as_pandas()
        key = lambda d: d.sort_values(  # noqa: E731
            ["s", "v"], na_position="last"
        ).reset_index(drop=True)
        pd.testing.assert_frame_equal(key(g), key(e), check_dtype=False)
        # union dictionary is sorted → downstream string sorts still work
        assert got.encodings["s"].get("sorted") is True
        res = eng.take(got, 2, presort="s")
        assert [r[0] for r in res.as_array()] == ["x", "y"]

    def test_union_nullable_and_datetime(self, eng, oracle):
        a = pd.DataFrame(
            {
                "n": pd.array([1, None], dtype="Int32"),
                "t": pd.to_datetime(["2020-01-01", "2020-02-01"]),
            }
        )
        b = pd.DataFrame(
            {
                "n": pd.array([None, 3], dtype="Int32"),
                "t": pd.to_datetime(["2020-02-01", None]),
            }
        )
        got = eng.union(eng.to_df(a), eng.to_df(b), distinct=False)
        assert isinstance(got, JaxDataFrame)
        g = got.as_pandas()
        e = oracle.union(
            oracle.to_df(a), oracle.to_df(b), distinct=False
        ).as_pandas()
        key = lambda d: d.sort_values(  # noqa: E731
            ["n", "t"], na_position="last"
        ).reset_index(drop=True)
        pd.testing.assert_frame_equal(key(g), key(e), check_dtype=False)


def test_union_one_sided_null_mask():
    """Union when only one side carries a null mask for a column."""
    from fugue_tpu.execution import NativeExecutionEngine
    from fugue_tpu.jax import JaxExecutionEngine

    eng = JaxExecutionEngine()
    oracle = NativeExecutionEngine()
    try:
        a = pd.DataFrame({"n": pd.array([1, None, 2], dtype="Int32")})
        b = pd.DataFrame({"n": pd.array([3, 4], dtype="Int32")})  # no nulls
        for d1, d2 in [(a, b), (b, a)]:
            got = eng.union(eng.to_df(d1), eng.to_df(d2), distinct=False)
            assert isinstance(got, JaxDataFrame)
            g = got.as_pandas()["n"]
            e = oracle.union(
                oracle.to_df(d1), oracle.to_df(d2), distinct=False
            ).as_pandas()["n"]
            assert sorted(g.dropna()) == sorted(e.dropna())
            assert g.isna().sum() == e.isna().sum() == 1
    finally:
        eng.stop()
        oracle.stop()
