import os

# force JAX onto a virtual 8-device CPU mesh BEFORE any jax import, mirroring
# how the reference tests distributed semantics on local sessions (SURVEY §4)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
