import os

# virtual 8-device CPU mesh BEFORE any jax computation, mirroring how the
# reference tests distributed semantics on local sessions (SURVEY §4).
# NOTE: the axon TPU plugin overrides JAX_PLATFORMS env, so the config update
# after import is the authoritative switch.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# adaptive-tuning store isolation: the default path is the COMMITTED
# fugue_tpu/ops/_tuned.json — tests must neither dirty the repo nor
# inherit plans an earlier pytest session learned (chunk sizes would
# drift run to run). One fresh store per session; tests that exercise
# the store explicitly pass fugue.tpu.tuning.path themselves.
if "FUGUE_TPU_TUNING_PATH" not in os.environ:
    import tempfile

    os.environ["FUGUE_TPU_TUNING_PATH"] = os.path.join(
        tempfile.mkdtemp(prefix="fugue_tpu_tuning_"), "_tuned.json"
    )
