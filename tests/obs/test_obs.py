"""The unified observability subsystem (``fugue_tpu/obs``) — ISSUE 3.

Covers the satellite test checklist:

- span-tree shape for a transform+join+aggregate workflow;
- Chrome-trace export golden structure (Perfetto-loadable);
- disabled-path overhead guard: <2% of a small streaming aggregate's wall
  even if EVERY span call cost the measured worst case;
- fork-boundary round trip: worker spans and counter deltas recorded in a
  forked pool worker land in the driver tracer / registry;
- the MetricsRegistry lifecycle: stats()/reset_stats()/snapshot()/delta()
  and the legacy ``engine.*_stats`` shims.
"""

import json
import os
import time
from collections import Counter

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_MAP_PARALLEL_MIN_ROWS,
    FUGUE_TPU_CONF_MAP_PARALLELISM,
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
    FUGUE_TPU_CONF_TRACE_ENABLED,
)
from fugue_tpu.dataframe import ArrowDataFrame, LocalDataFrameIterableDataFrame
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.obs import (
    MetricsRegistry,
    get_tracer,
    render_report,
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)
from fugue_tpu.obs.tracer import NULL_SPAN


@pytest.fixture
def tracer():
    """Enabled tracer with a clean buffer; restores disabled+clear after."""
    tr = get_tracer()
    tr.clear()
    tr.enable()
    yield tr
    tr.disable()
    tr.clear()


def _frame(n=30_000, groups=64, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {"k": rng.integers(0, groups, n), "v": rng.random(n)}
    )


def _stream(pdf: pd.DataFrame, step: int = 2048):
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(step, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, step)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


def _ancestor_names(rec, by_id):
    names = []
    while rec is not None:
        names.append(rec["name"])
        rec = by_id.get(rec["parent"])
    return names


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------


def test_disabled_span_is_shared_null_object():
    tr = get_tracer()
    tr.disable()
    s1 = tr.span("x", rows=1)
    s2 = tr.span("y")
    assert s1 is NULL_SPAN and s2 is NULL_SPAN
    with s1 as sp:
        sp.set(anything=1)  # no-op, no error
    assert tr.records() == [] or all(r["name"] not in ("x", "y") for r in tr.records())


def test_span_nesting_args_and_error(tracer):
    with tracer.span("outer", cat="t", a=1) as so:
        so.set(b=2)
        with tracer.span("inner", cat="t"):
            pass
        with pytest.raises(ValueError):
            with tracer.span("boom", cat="t"):
                raise ValueError("x")
    recs = {r["name"]: r for r in tracer.records()}
    assert recs["inner"]["parent"] == recs["outer"]["id"]
    assert recs["boom"]["parent"] == recs["outer"]["id"]
    assert recs["outer"]["args"] == {"a": 1, "b": 2}
    assert recs["boom"]["args"]["error"] == "ValueError"
    assert all(r["dur"] >= 0 and r["ts"] > 0 for r in recs.values())
    tree = tracer.span_tree()
    assert [n["name"] for n in tree] == ["outer"]
    assert sorted(c["name"] for c in tree[0]["children"]) == ["boom", "inner"]


def test_fork_boundary_protocol_mark_take_ingest(tracer):
    m = tracer.mark()
    with tracer.span("w1"):
        pass
    shipped = tracer.take_since(m)
    assert [r["name"] for r in shipped] == ["w1"]
    tracer.clear()
    tracer.ingest(shipped)
    assert [r["name"] for r in tracer.records()] == ["w1"]


# ---------------------------------------------------------------------------
# span tree over a real workflow: transform + join + aggregate
# ---------------------------------------------------------------------------


def test_span_tree_transform_join_aggregate(tracer):
    from typing import Dict

    import jax

    def tf(df: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {"k": df["k"], "v": df["v"] + 1.0}

    pdf = _frame(4000, 16)
    dim = pd.DataFrame({"k": np.arange(16), "name": [f"g{i}" for i in range(16)]})
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 1024})
    try:
        dag = FugueWorkflow()
        a = dag.df(pdf).transform(tf, schema="k:long,v:double")
        j = a.join(dag.df(dim), how="inner", on=["k"])
        agg = j.partition_by("k").aggregate(ff.sum(col("v")).alias("s"))
        agg.yield_dataframe_as("r", as_local=True)
        dag.run(e)
        assert len(dag.yields["r"].result.as_pandas()) == 16
    finally:
        e.stop_engine()
    recs = tracer.records()
    names = Counter(r["name"] for r in recs)
    assert names["workflow.run"] == 1
    assert names["workflow.task"] >= 4  # 2 creates + transform + join + agg
    assert names["engine.transform"] >= 1
    assert names["engine.join"] >= 1
    assert names["engine.aggregate"] >= 1
    by_id = {r["id"]: r for r in recs}
    # every engine verb span sits under a workflow task under the run
    for r in recs:
        if r["name"].startswith("engine."):
            chain = _ancestor_names(r, by_id)
            assert "workflow.task" in chain, chain
            assert chain[-1] == "workflow.run", chain


def test_span_tree_streaming_chunks_nest_in_verb(tracer):
    pdf = _frame(20_000, 32)
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2048})
    try:
        dag = FugueWorkflow()
        res = (
            dag.df(_stream(pdf))
            .partition_by("k")
            .aggregate(ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n"))
        )
        res.yield_dataframe_as("r", as_local=True)
        dag.run(e)
        assert len(dag.yields["r"].result.as_pandas()) == 32
    finally:
        e.stop_engine()
    recs = tracer.records()
    chunks = [r for r in recs if r["name"] == "stream.chunk"]
    assert len(chunks) >= 2
    by_id = {r["id"]: r for r in recs}
    chain = _ancestor_names(chunks[0], by_id)
    # the acceptance nesting: workflow task → engine verb → streaming chunk
    assert chain[0] == "stream.chunk"
    assert "engine.aggregate" in chain
    assert "workflow.task" in chain
    assert chain[-1] == "workflow.run"
    # rows/bytes in-out attributes ride the chunk spans
    assert all(c["args"].get("rows", 0) > 0 for c in chunks)
    assert sum(c["args"]["rows"] for c in chunks) == len(pdf)


# ---------------------------------------------------------------------------
# chrome trace export
# ---------------------------------------------------------------------------


def test_chrome_trace_export_golden(tracer, tmp_path):
    with tracer.span("workflow.task", cat="workflow", task="t0"):
        with tracer.span("engine.aggregate", cat="engine"):
            with tracer.span("stream.chunk", cat="stream", rows=10, chunk=0):
                pass
    doc = to_chrome_trace(tracer.records())
    # golden structure: the trace-event envelope Perfetto loads
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in evs] == [
        "stream.chunk",
        "engine.aggregate",
        "workflow.task",
    ]  # completion order
    for e in evs:
        # "id" rode in with ISSUE 18: the cluster-unique span id survives
        # export so cross-process assembly can dedup re-published spools
        assert set(e) == {
            "name", "cat", "ph", "ts", "dur", "pid", "tid", "args", "id",
        }
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
    chunk, agg, task = evs
    # nesting is encoded by time containment on one (pid, tid) track
    assert task["ts"] <= agg["ts"] and agg["ts"] <= chunk["ts"]
    assert agg["ts"] + agg["dur"] <= task["ts"] + task["dur"] + 1e-6
    assert chunk["args"] == {"rows": 10, "chunk": 0}
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert meta and meta[0]["args"]["name"].startswith("fugue-tpu")
    p = write_chrome_trace(str(tmp_path / "t.json"), tracer.records())
    with open(p) as f:
        assert json.load(f) == json.loads(json.dumps(doc))
    s = validate_chrome_trace(p)
    assert s["spans"] == 3 and "stream.chunk" in s["names"]


def test_validate_rejects_garbage(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"nope": []}))
    with pytest.raises(AssertionError):
        validate_chrome_trace(str(p))


def test_render_report_top_n(tracer):
    for _ in range(3):
        with tracer.span("engine.aggregate", cat="engine"):
            with tracer.span("stream.chunk", cat="stream"):
                pass
    txt = render_report(tracer.records(), {"resilience": {"a": 1}}, top_n=5)
    assert "engine.aggregate" in txt and "stream.chunk" in txt
    assert "[resilience]" in txt and "a: 1" in txt


# ---------------------------------------------------------------------------
# disabled-path overhead guard
# ---------------------------------------------------------------------------


def test_disabled_overhead_under_2_percent():
    """The <2% contract: run a small streaming aggregate with the tracer
    DISABLED and measure its wall; separately measure the worst-case cost
    of a disabled instrumented call site, and the number of spans the same
    run would record when enabled. Even charging every span at the
    measured per-call cost, the instrumentation budget must stay under 2%
    of the measured wall."""
    tr = get_tracer()
    tr.disable()
    tr.clear()
    pdf = _frame(30_000, 64, seed=1)
    aggs = lambda: [  # noqa: E731
        ff.sum(col("v")).alias("s"),
        ff.count(col("v")).alias("n"),
    ]
    spec = PartitionSpec(by=["k"])

    def run():
        e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2048})
        try:
            res = e.aggregate(_stream(pdf), spec, aggs())
            return len(res.as_pandas())
        finally:
            e.stop_engine()

    assert run() == 64  # warmup (compiles cached in-process)
    t0 = time.perf_counter()
    assert run() == 64
    wall_disabled = time.perf_counter() - t0

    # per-call cost of the disabled instrumented site
    n_calls = 100_000
    t0 = time.perf_counter()
    for _ in range(n_calls):
        with tr.span("x", cat="engine", rows=1):
            pass
    per_call = (time.perf_counter() - t0) / n_calls

    # span count of the identical run when enabled
    tr.enable()
    try:
        tr.clear()
        assert run() == 64
        n_spans = len(tr.records())
    finally:
        tr.disable()
        tr.clear()
    assert n_spans > 0
    overhead = n_spans * per_call
    assert overhead < 0.02 * wall_disabled, (
        f"{n_spans} spans x {per_call * 1e6:.2f}µs = {overhead * 1e3:.3f}ms "
        f"vs wall {wall_disabled * 1e3:.1f}ms"
    )


# ---------------------------------------------------------------------------
# fork boundary: worker spans + counter deltas ship home
# ---------------------------------------------------------------------------


@pytest.mark.skipif(
    os.name != "posix", reason="fork pool requires posix fork"
)
def test_fork_worker_spans_and_counters_round_trip(tracer):
    from fugue_tpu.execution.parallel_map import fork_available

    if not fork_available():
        pytest.skip("no fork start method")
    import fugue_tpu.api as fa

    pdf = _frame(8000, 8, seed=2)

    def demean(df: pd.DataFrame) -> pd.DataFrame:
        df["v"] = df["v"] - df["v"].mean()
        return df

    e = JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_MAP_PARALLELISM: 2,
            FUGUE_TPU_CONF_MAP_PARALLEL_MIN_ROWS: 0,
        }
    )
    try:
        out = fa.transform(
            pdf, demean, schema="*", partition=PartitionSpec(by=["k"]), engine=e
        )
        assert len(out) == len(pdf)
        recs = tracer.records()
        worker_chunks = [r for r in recs if r["name"] == "map.worker_chunk"]
        worker_parts = [r for r in recs if r["name"] == "map.partition"]
        assert worker_chunks, "no worker spans shipped home"
        driver_pid = os.getpid()
        assert all(r["pid"] != driver_pid for r in worker_chunks)
        # worker spans parent onto the driver's map.parallel span
        by_id = {r["id"]: r for r in recs}
        parallel = [r for r in recs if r["name"] == "map.parallel"]
        assert len(parallel) == 1 and parallel[0]["pid"] == driver_pid
        assert all(
            r["parent"] == parallel[0]["id"] for r in worker_chunks
        )
        assert all(
            by_id[r["parent"]]["name"] == "map.worker_chunk"
            for r in worker_parts
        )
        assert sum(r["args"]["rows_out"] for r in worker_parts) == len(pdf)
        # counter deltas merged into the driver registry
        rs = e.resilience_stats.as_dict()
        assert rs.get("map.worker_chunks", 0) >= 2
        assert rs.get("map.worker_partitions", 0) == 8
        assert rs.get("map.worker_rows_out", 0) == len(pdf)
        assert rs.get("map.chunks_ok", 0) >= 2
    finally:
        e.stop_engine()


# ---------------------------------------------------------------------------
# metrics registry + lifecycle + shims
# ---------------------------------------------------------------------------


def test_registry_unit():
    class Src:
        def __init__(self):
            self.n = 0

        def as_dict(self):
            return {"n": self.n, "nested": {"m": self.n * 2}, "tag": "x"}

        def reset(self):
            self.n = 0

    reg = MetricsRegistry()
    s = Src()
    reg.register("s", s)
    reg.register("lazy", lambda: s)
    before = reg.snapshot()
    s.n = 5
    d = reg.delta(before)
    assert d["s"] == {"n": 5, "nested": {"m": 10}, "tag": "x"}
    assert d["lazy"]["n"] == 5
    reg.reset()
    assert reg.as_dict()["s"]["n"] == 0


def test_engine_stats_surface_and_shims():
    from fugue_tpu.constants import FUGUE_TPU_CONF_STREAM_PREFETCH_DEPTH

    e = JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2048,
            # force the prefetcher on so pipeline_stats records a run even
            # on a single-core host (whose adaptive default is serial)
            FUGUE_TPU_CONF_STREAM_PREFETCH_DEPTH: 2,
        }
    )
    try:
        st = e.stats()
        assert set(st) == {
            "resilience",
            "pipeline",
            "jit_cache",
            "plan",
            "analysis",
            "cache",
            "tuning",
            "shuffle",
            "latency",
            "telemetry",
        }
        # the deprecation shims delegate to the SAME objects the registry holds
        assert e.pipeline_stats is e.metrics.get("pipeline")
        assert e.resilience_stats is e.metrics.get("resilience")
        assert e.jit_cache_stats == e.metrics.get("jit_cache").as_dict()
        # exercise the engine, then prove one consistent reset
        pdf = _frame(6000, 8, seed=3)
        res = e.aggregate(
            _stream(pdf),
            PartitionSpec(by=["k"]),
            [ff.sum(col("v")).alias("s")],
        )
        assert len(res.as_pandas()) == 8
        st = e.stats()
        assert st["jit_cache"]["misses"] > 0
        assert st["pipeline"]["runs"] >= 1
        e.resilience_stats.inc("map.chunk_retries")
        before = e.metrics.snapshot()
        e.resilience_stats.inc("map.chunk_retries", 2)
        assert e.metrics.delta(before)["resilience"]["map.chunk_retries"] == 2
        e.reset_stats()
        st = e.stats()
        assert st["resilience"] == {}
        assert st["pipeline"]["runs"] == 0
        assert st["jit_cache"]["hits"] == 0 and st["jit_cache"]["misses"] == 0
        # compiled entries survive the reset by design (no forced recompiles)
        assert st["jit_cache"]["entries"] > 0
    finally:
        e.stop_engine()


def test_trace_conf_enables_and_env_overrides(monkeypatch):
    tr = get_tracer()
    tr.disable()
    e = JaxExecutionEngine({FUGUE_TPU_CONF_TRACE_ENABLED: True})
    try:
        assert tr.enabled
    finally:
        e.stop_engine()
        tr.disable()
    monkeypatch.setenv("FUGUE_TPU_TRACE", "0")
    e = JaxExecutionEngine({FUGUE_TPU_CONF_TRACE_ENABLED: True})
    try:
        assert not tr.enabled  # env wins over conf
    finally:
        e.stop_engine()
        tr.disable()
        tr.clear()


def test_workflow_trace_dir_auto_export(tmp_path, tracer):
    from fugue_tpu.constants import FUGUE_TPU_CONF_TRACE_DIR

    e = JaxExecutionEngine({FUGUE_TPU_CONF_TRACE_DIR: str(tmp_path)})
    try:
        dag = FugueWorkflow()
        dag.df(_frame(200, 4)).yield_dataframe_as("r", as_local=True)
        dag.run(e)
    finally:
        e.stop_engine()
    files = [f for f in os.listdir(tmp_path) if f.startswith("fugue_trace_")]
    assert len(files) == 1
    s = validate_chrome_trace(str(tmp_path / files[0]))
    assert "workflow.run" in s["names"]
