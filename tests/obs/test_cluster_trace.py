"""Cluster-wide tracing (ISSUE 18, docs/observability.md): cross-process
trace propagation, the per-process span spool + trace assembler, the
flight recorder, and metrics federation.

Covers the satellite test checklist:

- a forked map worker's spans land under the submitting run's trace id,
  with the run's results bit-identical to an untraced run;
- a REAL HTTP hop (``/serve/submit``) lands the server-side execution's
  spans under the submitting client's trace id, results bit-identical;
- flight-recorder completeness: every counted lease steal has exactly one
  ``lease.steal`` journal record (and every dead-holder steal exactly one
  ``hb.expired``);
- federated metrics: the merged histogram's per-series count equals the
  SUM of the per-replica counts, and the fleet exposition passes
  ``validate_prometheus_text``;
- the host+pid span-id collision fix: ``validate_chrome_trace`` rejects a
  duplicate (pid, span id) pair.
"""

import json
import os
import time

import numpy as np
import pandas as pd
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_EVENTS_DIR,
    FUGUE_TPU_CONF_EVENTS_ENABLED,
    FUGUE_TPU_CONF_MAP_PARALLEL_MIN_ROWS,
    FUGUE_TPU_CONF_MAP_PARALLELISM,
    FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT,
)
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.obs import (
    EVENT_TYPES,
    assemble_trace,
    current_trace_id,
    get_event_log,
    get_span_metrics,
    get_tracer,
    mint_trace_id,
    proc_ident,
    publish_spool,
    read_events,
    read_spools,
    render_timeline,
    to_chrome_trace,
    to_prometheus_text,
    trace_carrier,
    trace_scope,
    validate_chrome_trace,
    validate_prometheus_text,
)
from fugue_tpu.obs.metrics import SpanMetrics
from fugue_tpu.serve import EngineServer, FleetClient, ServeHttpClient


@pytest.fixture
def tracer():
    tr = get_tracer()
    tr.clear()
    tr.enable()
    yield tr
    tr.disable()
    tr.clear()


@pytest.fixture
def events(tmp_path):
    """Flight recorder pointed at a fresh dir; disabled + closed after."""
    log = get_event_log()
    d = str(tmp_path / "events")
    log.configure(d, True)
    yield d
    log.configure(d, False)
    log.close()


def _frame(n=8000, groups=8, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({"k": rng.integers(0, groups, n), "v": rng.random(n)})


# ---------------------------------------------------------------------------
# trace context: mint / scope / carrier
# ---------------------------------------------------------------------------


def test_trace_scope_sets_and_restores():
    assert current_trace_id() is None
    tid = mint_trace_id()
    assert len(tid) == 16 and int(tid, 16) >= 0
    with trace_scope(tid):
        assert current_trace_id() == tid
        assert trace_carrier()["trace"] == tid
        # a nested scope with no args mints a FRESH trace (a new run)
        with trace_scope():
            inner = current_trace_id()
            assert inner is not None and inner != tid
        assert current_trace_id() == tid
    assert current_trace_id() is None


def test_remote_hop_reparents_under_carrier(tracer):
    """The propagation contract: a span opened in a scope restored from a
    carrier (the HTTP-header / task-spec hop) records the submitting
    run's trace id and parents onto the submitting span."""
    tid = mint_trace_id()
    with trace_scope(tid):
        with tracer.span("serve.submit") as sp:  # noqa: F841
            carrier = trace_carrier()
    assert carrier["trace"] == tid and carrier["parent"]
    # "the other process": only the carrier crosses the wire
    with trace_scope(carrier["trace"], carrier["parent"]):
        with tracer.span("dist.task"):
            pass
    recs = {r["name"]: r for r in tracer.records()}
    assert recs["dist.task"]["trace"] == tid
    assert recs["dist.task"]["parent"] == recs["serve.submit"]["id"]
    assert recs["serve.submit"]["trace"] == tid


def test_span_ids_are_host_pid_prefixed(tracer):
    with tracer.span("x"):
        pass
    (rec,) = tracer.records()
    assert rec["id"].startswith(proc_ident() + ":")


def test_validate_rejects_duplicate_pid_span_id(tmp_path, tracer):
    with tracer.span("a"):
        pass
    (rec,) = tracer.records()
    clone = dict(rec)  # same pid, same span id — the cross-host collision
    doc = to_chrome_trace([rec, clone])
    p = tmp_path / "dup.json"
    p.write_text(json.dumps(doc))
    with pytest.raises(AssertionError, match="duplicate"):
        validate_chrome_trace(str(p))


# ---------------------------------------------------------------------------
# span spool + assembler
# ---------------------------------------------------------------------------


def test_spool_publish_idempotent_and_torn_skipped(tmp_path, tracer):
    with tracer.span("engine.aggregate", rows=10):
        pass
    d = str(tmp_path / "spool")
    p1 = publish_spool(d, stats={"n": 1}, label="worker w0")
    p2 = publish_spool(d, stats={"n": 2}, label="worker w0")
    assert p1 == p2  # one file per process; last write wins
    (tmp_path / "spool" / "ghost.spool.json").write_text('{"spans": [')  # torn
    docs = read_spools(d)
    assert len(docs) == 1
    assert docs[0]["proc"] == proc_ident() and docs[0]["stats"] == {"n": 2}
    assert [r["name"] for r in docs[0]["spans"]] == ["engine.aggregate"]


def test_spool_carries_sampler_ring(tmp_path, tracer):
    """Satellite fix: the remote sampler ring ships through the spool and
    renders as a counter track on that process's assembled track."""
    with tracer.span("w"):
        pass
    d = str(tmp_path / "spool")
    publish_spool(d, counters=[(time.perf_counter_ns(), {"host_rss_bytes": 1.0})])
    (doc,) = read_spools(d)
    assert doc["counters"] and doc["counters"][0][1] == {"host_rss_bytes": 1.0}


def _fake_spool(spool_dir, proc, label, spans):
    doc = {
        "version": 1,
        "proc": proc,
        "pid": 123,
        "label": label,
        "spans": spans,
        "counters": [],
        "stats": {},
    }
    os.makedirs(spool_dir, exist_ok=True)
    with open(os.path.join(spool_dir, proc + ".spool.json"), "w") as f:
        json.dump(doc, f)


def _span(proc, seq, name, trace=None, parent=None):
    return {
        "name": name,
        "cat": "dist",
        "ts": time.perf_counter_ns(),
        "dur": 1000,
        "pid": 123,  # raw OS pid — identical across "hosts" on purpose
        "tid": 1,
        "id": f"{proc}:{seq}",
        "parent": parent,
        "proc": proc,
        "trace": trace,
        "args": {},
    }


def test_assemble_dedups_remaps_and_names_tracks(tmp_path, tracer):
    tid = mint_trace_id()
    with trace_scope(tid):
        with tracer.span("workflow.run"):
            pass
    d = str(tmp_path / "spool")
    # two "hosts" whose raw pids collide; w0's first span ALSO appears in
    # the driver-ingested copy (same proc + span id → deduplicated)
    s0 = _span("hostA-123", 1, "dist.task", trace=tid)
    _fake_spool(d, "hostA-123", "worker w0", [s0, _span("hostA-123", 2, "dist.fetch")])
    _fake_spool(d, "hostB-123", "worker w1", [_span("hostB-123", 1, "dist.task", trace=tid)])
    out = str(tmp_path / "trace.json")
    summary = assemble_trace(d, out, local_records=tracer.records() + [s0])
    assert summary["processes"] == 3
    assert summary["spans"] == 4  # 1 driver + 2 w0 (deduped) + 1 w1
    assert summary["process_spans"]["hostA-123"] == 2
    assert summary["process_names"][proc_ident()] == "fugue-tpu driver"
    assert summary["process_names"]["hostB-123"] == "fugue-tpu worker w1 hostB-123"
    assert summary["traces"] == [tid]
    with open(out) as f:
        doc = json.load(f)
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert pids == {1, 2, 3}  # dense synthetic pids, driver first
    # trace filter: only the run's spans survive
    summary = assemble_trace(
        d, out, local_records=tracer.records(), trace_id=tid
    )
    assert summary["spans"] == 3 and summary["traces"] == [tid]


# ---------------------------------------------------------------------------
# forked map workers inherit the run's trace id (bit-identical results)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(os.name != "posix", reason="fork pool requires posix fork")
def test_fork_map_worker_spans_under_run_trace(tracer):
    from fugue_tpu.execution.parallel_map import fork_available

    if not fork_available():
        pytest.skip("no fork start method")
    import fugue_tpu.api as fa

    pdf = _frame(6000, 8, seed=2)

    def demean(df: pd.DataFrame) -> pd.DataFrame:
        df["v"] = df["v"] - df["v"].mean()
        return df

    def run():
        e = JaxExecutionEngine(
            {
                FUGUE_TPU_CONF_MAP_PARALLELISM: 2,
                FUGUE_TPU_CONF_MAP_PARALLEL_MIN_ROWS: 0,
            }
        )
        try:
            return fa.transform(
                pdf, demean, schema="*", partition=PartitionSpec(by=["k"]), engine=e
            )
        finally:
            e.stop_engine()

    tid = mint_trace_id()
    with trace_scope(tid):
        traced = run()
    worker = [r for r in tracer.records() if r["name"] == "map.worker_chunk"]
    assert worker, "no worker spans shipped home"
    assert all(r.get("trace") == tid for r in worker)
    assert all(r["pid"] != os.getpid() for r in worker)
    # the instrumentation changed nothing: untraced run is bit-identical
    tracer.disable()
    tracer.clear()
    untraced = run()
    pd.testing.assert_frame_equal(traced, untraced)


# ---------------------------------------------------------------------------
# a REAL HTTP hop: /serve/submit propagates the client's trace id
# ---------------------------------------------------------------------------


def _agg_dag(seed: int = 0, rows: int = 64) -> FugueWorkflow:
    dag = FugueWorkflow()
    (
        dag.df(
            pd.DataFrame(
                {
                    "k": [i % 4 for i in range(rows)],
                    "v": [float(i + seed) for i in range(rows)],
                }
            )
        )
        .partition_by("k")
        .aggregate(ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n"))
        .yield_dataframe_as("r", as_local=True)
    )
    return dag


@pytest.fixture
def http_serve():
    eng = NativeExecutionEngine(
        {
            "fugue.rpc.server": "fugue_tpu.rpc.http.HttpRPCServer",
            FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT: 1,
        }
    )
    rpc = eng.rpc_server
    rpc.start()
    srv = EngineServer(eng).start()
    rpc.bind_serve(srv)
    try:
        yield eng, rpc, srv
    finally:
        srv.stop()
        rpc.stop()


def test_http_submit_lands_spans_under_client_trace(http_serve, tracer):
    eng, rpc, srv = http_serve
    cl = ServeHttpClient(rpc.host, rpc.port)
    tid = mint_trace_id()
    with trace_scope(tid):
        sub = cl.submit(lambda: _agg_dag(seed=5), tenant="acme")
        frames = cl.result(sub["id"], timeout=60)
    served = frames["r"].sort_values("k").reset_index(drop=True)
    # the server-side execution ran in ANOTHER thread with no inherited
    # context — only the X-Fugue-Trace header links it to this run
    runs = [r for r in tracer.records() if r["name"] == "workflow.run"]
    assert runs and any(r.get("trace") == tid for r in runs)
    # bit-identical to running the same dag directly
    local = (
        _agg_dag(seed=5)
        .run(NativeExecutionEngine({}))
        .yields["r"]
        .result.as_pandas()
        .sort_values("k")
        .reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(served, local)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_event_log_emit_read_render(events):
    log = get_event_log()
    tid = mint_trace_id()
    with trace_scope(tid):
        log.emit("lease.steal", task="t1", owner="w1", prev_owner="w0",
                 reason="worker_lost")
    log.emit("chaos.inject", fault="SIGKILL", target="w0")
    assert os.path.exists(log.path())
    evs = read_events(events)
    assert {e["type"] for e in evs} == {"lease.steal", "chaos.inject"}
    (steal,) = [e for e in evs if e["type"] == "lease.steal"]
    assert steal["trace"] == tid and steal["proc"] == proc_ident()
    assert set(e["type"] for e in evs) <= EVENT_TYPES
    txt = render_timeline(evs, trace=tid)
    # trace filter keeps trace-LESS records (the injection) alongside
    assert "stolen by w1 from w0 (worker_lost)" in txt
    assert "SIGKILL injected into w0" in txt


def test_event_log_disabled_is_silent(tmp_path):
    log = get_event_log()
    before = log.as_dict()["emitted"]
    log.emit("lease.acquire", task="t", owner="w")  # disabled: no-op
    assert log.as_dict()["emitted"] == before


def test_events_conf_enables_and_env_overrides(tmp_path, monkeypatch):
    d = str(tmp_path / "ev")
    log = get_event_log()
    try:
        e = NativeExecutionEngine(
            {FUGUE_TPU_CONF_EVENTS_ENABLED: True, FUGUE_TPU_CONF_EVENTS_DIR: d}
        )
        assert log.enabled
        log.emit("serve.journal_replay", replica="r0", entries=2)
        assert read_events(d)[0]["type"] == "serve.journal_replay"
        # env kill-switch wins over conf (the tracer's contract)
        monkeypatch.setenv("FUGUE_TPU_EVENTS", "0")
        e2 = NativeExecutionEngine(
            {FUGUE_TPU_CONF_EVENTS_ENABLED: True, FUGUE_TPU_CONF_EVENTS_DIR: d}
        )
        assert not log.enabled
        del e, e2
    finally:
        log.configure(d, False)
        log.close()


def test_lease_steal_journal_completeness(tmp_path, events):
    """Every COUNTED recovery event has exactly one journal record: run
    the lease matrix (clean grant, expiry steal, dead-holder steal) and
    reconcile the stats counters against the event log."""
    from fugue_tpu.dist import HeartbeatWriter, LeaseBoard

    class Stats:
        def __init__(self):
            self.d = {}

        def inc(self, k, n=1):
            self.d[k] = self.d.get(k, 0) + n

    st = Stats()
    hb_dir = str(tmp_path / "hb")
    lb = LeaseBoard(
        str(tmp_path / "leases"), hb_dir=hb_dir, hb_stale_s=0.3, stats=st
    )
    # clean grant → lease.acquire
    assert lb.try_acquire("t1", "w0", lease_s=0.05)[0]
    # expiry steal (no heartbeat evidence) → lease.steal(reason=expired)
    time.sleep(0.1)
    assert lb.try_acquire("t1", "w1", lease_s=30.0)[0]
    # dead-holder steal: fresh-then-stale heartbeat → hb.expired + steal
    HeartbeatWriter(hb_dir, "w2", interval_s=0.05).beat()
    assert lb.try_acquire("t2", "w2", lease_s=30.0)[0]
    time.sleep(0.4)  # the heartbeat goes provably stale
    assert lb.try_acquire("t2", "w3", lease_s=30.0)[0]

    evs = read_events(events)
    by_type = {}
    for e in evs:
        by_type.setdefault(e["type"], []).append(e)
    assert len(by_type.get("lease.steal", [])) == st.d["leases_stolen"] == 2
    assert (
        len(by_type.get("hb.expired", []))
        == st.d["leases_stolen_dead"]
        == 1
    )
    assert st.d["leases_stolen_expired"] == 1
    steal_dead = [
        e for e in by_type["lease.steal"] if e["reason"] == "worker_lost"
    ]
    assert len(steal_dead) == 1 and steal_dead[0]["prev_owner"] == "w2"
    # the expiry record precedes its steal and names the same task
    exp = by_type["hb.expired"][0]
    assert exp["holder"] == "w2" and exp["task"] == "t2"
    assert exp["ts"] <= steal_dead[0]["ts"]
    # clean grants: one lease.acquire per non-steal grant
    n_clean = st.d["leases_acquired"] - st.d["leases_stolen"]
    assert len(by_type.get("lease.acquire", [])) == n_clean == 2


# ---------------------------------------------------------------------------
# metrics federation
# ---------------------------------------------------------------------------


def _latency_count(sm: SpanMetrics, span: str) -> int:
    return sum(
        h.count
        for labels, h in sm.latency.series()
        if labels.get("span") == span
    )


def _obs(sm: SpanMetrics, span: str, n: int, dur_ns: int = 2_000_000) -> None:
    for _ in range(n):
        sm.observe_record({"name": span, "dur": dur_ns, "args": {"rows": 10}})


def test_federated_merge_counts_are_exact_sums():
    a, b = SpanMetrics(), SpanMetrics()
    _obs(a, "engine.aggregate", 3)
    _obs(b, "engine.aggregate", 5, dur_ns=8_000_000)
    _obs(b, "engine.join", 2)
    merged = SpanMetrics()
    merged.merge(a.snapshot())
    merged.merge(b.snapshot())
    assert _latency_count(merged, "engine.aggregate") == 8  # 3 + 5, exactly
    assert _latency_count(merged, "engine.join") == 2
    # merge is order-independent (associative + commutative encoding)
    merged2 = SpanMetrics()
    merged2.merge(b.snapshot())
    merged2.merge(a.snapshot())
    assert merged2.snapshot() == merged.snapshot()
    text = to_prometheus_text(span_metrics=merged)
    summary = validate_prometheus_text(text)
    assert any(
        n.startswith("fugue_tpu_span_latency_seconds") for n in summary["names"]
    )
    # the merged count is in the exposition itself, not just the object
    assert 'span="engine.aggregate"' in text and " 8" in text


def test_fleet_client_federates_over_http(http_serve, tracer):
    eng, rpc, srv = http_serve
    cl = ServeHttpClient(rpc.host, rpc.port)
    sub = cl.submit(lambda: _agg_dag(seed=7))
    cl.result(sub["id"], timeout=60)
    # the replica now has live span histograms; federate through the wire
    fc = FleetClient([(rpc.host, rpc.port)])
    merged, replicas = fc.federated_span_metrics()
    assert len(replicas) == 1
    want = _latency_count(get_span_metrics(), "workflow.run")
    assert want >= 1
    assert _latency_count(merged, "workflow.run") == want
    text = fc.federated_metrics()
    summary = validate_prometheus_text(text)
    assert any(
        n.startswith("fugue_tpu_span_latency_seconds") for n in summary["names"]
    )
    assert fc.stats()["metrics_federations"] == 2
