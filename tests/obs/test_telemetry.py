"""Live engine telemetry (``fugue_tpu/obs`` histograms + sampler +
exposure surfaces) — ISSUE 6.

Covers the satellite test checklist:

- histogram quantile estimation (p50/p95/p99 inside the true bucket,
  clamped to observed min/max) and the mergeable encoding's associativity;
- span-close auto-feed: every span name gets a latency distribution,
  rows/bytes attrs feed throughput histograms, run labels attach;
- fork-boundary histogram merging: worker-recorded distributions arrive
  home through the ``_harvest_chunk`` channel and merge associatively,
  keyed by labels (pid-collision-free by construction);
- sampler start/stop idempotency, bounded ring, probe lifecycle;
- the metric lifecycle fix: ``engine.reset_stats()`` resets histograms
  and sampler rings under the JitCache keep-entries contract;
- Prometheus exposition format validity and the /metrics | /healthz |
  /stats HTTP endpoints scraped while a workflow run is in flight;
- Perfetto counter tracks riding the Chrome trace export;
- a disabled-path overhead guard mirroring the tracer's.
"""

import json
import math
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.constants import (
    FUGUE_TPU_CONF_MAP_PARALLEL_MIN_ROWS,
    FUGUE_TPU_CONF_MAP_PARALLELISM,
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
    FUGUE_TPU_CONF_TELEMETRY_ENABLED,
    FUGUE_TPU_CONF_TELEMETRY_INTERVAL,
    FUGUE_TPU_CONF_TELEMETRY_RING,
)
from fugue_tpu.dataframe import ArrowDataFrame, LocalDataFrameIterableDataFrame
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.obs import (
    MetricsRegistry,
    get_sampler,
    get_span_metrics,
    get_tracer,
    render_report,
    to_chrome_trace,
    to_prometheus_text,
    validate_chrome_trace,
    validate_prometheus_text,
)
from fugue_tpu.obs.metrics import (
    DEFAULT_SIZE_BOUNDS,
    Histogram,
    HistogramFamily,
    run_labels,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture
def tracer():
    """Enabled tracer + clean span-metric store; restores both after."""
    tr = get_tracer()
    tr.clear()
    get_span_metrics().clear()
    tr.enable()
    yield tr
    tr.disable()
    tr.clear()
    get_span_metrics().clear()


@pytest.fixture
def sampler():
    """The global sampler, guaranteed stopped+clean before and after."""
    s = get_sampler()
    s.stop()
    s.clear()
    yield s
    s.stop()
    s.clear()


def _frame(n=20_000, groups=32, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({"k": rng.integers(0, groups, n), "v": rng.random(n)})


def _stream(pdf: pd.DataFrame, step: int = 2048):
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    return LocalDataFrameIterableDataFrame(
        (
            ArrowDataFrame(tbl.slice(s, min(step, tbl.num_rows - s)))
            for s in range(0, tbl.num_rows, step)
        ),
        schema=ArrowDataFrame(tbl).schema,
    )


# ---------------------------------------------------------------------------
# histogram core
# ---------------------------------------------------------------------------


def test_histogram_quantiles_land_in_true_bucket():
    h = Histogram()
    for _ in range(50):
        h.observe(0.001)
    for _ in range(45):
        h.observe(0.1)
    for _ in range(5):
        h.observe(1.0)
    assert h.count == 100
    assert h.min == 0.001 and h.max == 1.0
    assert abs(h.sum - (50 * 0.001 + 45 * 0.1 + 5 * 1.0)) < 1e-9
    # each quantile estimate must land inside the bucket holding the true
    # quantile value (the histogram's resolution contract)
    for q, true_v in ((0.50, 0.001), (0.95, 0.1), (0.99, 1.0)):
        est = h.quantile(q)
        lo = max(b for b in h.bounds if b < true_v)
        hi = min(b for b in h.bounds if b >= true_v)
        assert lo < est <= hi + 1e-12, (q, est, lo, hi)
    # clamped to the observed range
    assert h.quantile(0.0) >= h.min and h.quantile(1.0) <= h.max
    assert Histogram().quantile(0.5) is None


def test_histogram_merge_is_associative_and_commutative():
    vals_a = [0.002, 0.004, 1.5, 0.03]
    vals_b = [0.9, 0.00015, 0.03, 7.0, 0.03]
    direct = Histogram()
    for v in vals_a + vals_b:
        direct.observe(v)
    ha, hb = Histogram(), Histogram()
    for v in vals_a:
        ha.observe(v)
    for v in vals_b:
        hb.observe(v)
    ab, ba = Histogram(), Histogram()
    ab.merge(ha.encode())
    ab.merge(hb.encode())
    ba.merge(hb.encode())
    ba.merge(ha.encode())
    want = direct.encode()
    for m in (ab, ba):
        got = m.encode()
        assert got["counts"] == want["counts"]
        assert got["count"] == want["count"]
        assert got["min"] == want["min"] and got["max"] == want["max"]
        assert got["sum"] == pytest.approx(want["sum"])  # fp addition order
    # merging an empty delta is the identity
    before = ab.encode()
    ab.merge(Histogram().encode())
    assert ab.encode() == before


def test_histogram_subtract_gives_delta():
    h = Histogram()
    h.observe(0.01)
    snap = h.encode()
    h.observe(0.5)
    h.observe(0.5)
    d = h.subtract(snap)
    assert d["count"] == 2 and abs(d["sum"] - 1.0) < 1e-9
    fresh = Histogram()
    fresh.merge(snap)
    fresh.merge(d)
    assert fresh.encode()["count"] == 3
    assert fresh.counts == h.counts


def test_family_labels_and_keep_entries_reset():
    fam = HistogramFamily("t_lat")
    fam.observe(0.1, span="a", run="r1")
    fam.observe(0.2, span="a", run="r2")
    fam.observe(0.3, span="b", run="r1")
    assert len(fam.series()) == 3
    assert fam.get(span="a", run="r1").count == 1
    d = fam.as_dict()
    assert set(d) == {"run=r1,span=a", "run=r2,span=a", "run=r1,span=b"}
    # reset zeroes observations but KEEPS the registered series
    fam.reset()
    assert fam.as_dict() == {}  # zero-count series don't report...
    assert len(fam.series()) == 3  # ...but stay registered (keep-entries)
    fam.clear()
    assert len(fam.series()) == 0


def test_registry_family_registers_as_source():
    reg = MetricsRegistry()
    fam = reg.family("latency_ms", bounds=DEFAULT_SIZE_BOUNDS)
    assert reg.family("latency_ms") is fam  # create-or-get
    fam.observe(12, op="x")
    assert reg.as_dict()["latency_ms"]["op=x"]["count"] == 1
    reg.reset()
    assert reg.as_dict()["latency_ms"] == {}


# ---------------------------------------------------------------------------
# span-close auto-feed + run labels
# ---------------------------------------------------------------------------


def test_span_close_feeds_latency_and_rows_histograms(tracer):
    with tracer.span("engine.x", cat="engine", rows=500, bytes=4096):
        time.sleep(0.002)
    sm = get_span_metrics()
    h = sm.latency.get(span="engine.x")
    assert h is not None and h.count == 1
    dur_s = tracer.records()[0]["dur"] / 1e9
    assert h.min == h.max == pytest.approx(dur_s)
    # the quantile estimate must agree with the recorded duration's bucket
    assert h.min <= h.quantile(0.5) <= h.max
    assert sm.rows.get(span="engine.x").sum == 500
    assert sm.bytes.get(span="engine.x").sum == 4096
    # summary view (engine.stats()["latency"]) carries ms percentiles
    s = sm.summary()["engine.x"]
    assert s["count"] == 1 and s["p50_ms"] >= 2.0


def test_run_labels_attach_and_restore(tracer):
    sm = get_span_metrics()
    with run_labels(workflow="wfX", run="r1"):
        with tracer.span("engine.y"):
            pass
    with tracer.span("engine.y"):
        pass
    assert sm.latency.get(span="engine.y", workflow="wfX", run="r1").count == 1
    assert sm.latency.get(span="engine.y").count == 1  # label ctx restored


def test_workflow_run_gets_workflow_and_run_labels(tracer):
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2048})
    try:
        for _ in range(2):
            dag = FugueWorkflow()
            dag.df(_frame(500, 4)).yield_dataframe_as("r", as_local=True)
            dag.run(e)
    finally:
        e.stop_engine()
    runs = [
        labels
        for labels, h in get_span_metrics().latency.series()
        if labels.get("span") == "workflow.run" and h.count
    ]
    assert len(runs) == 2
    # same dag shape => same stable workflow label; distinct run ids
    assert len({r["workflow"] for r in runs}) in (1, 2)
    assert all(r["workflow"].startswith("wf-") for r in runs)
    assert len({r["run"] for r in runs}) == 2
    # the engine surface aggregates across runs per span name
    assert e.stats()["latency"]["workflow.run"]["count"] == 2
    # and the report table carries the quantile columns
    txt = e.report()
    assert "p50_ms" in txt and "p99_ms" in txt and "workflow.run" in txt


def test_concurrent_run_labels_do_not_cross_contaminate(tracer):
    """Two runs on different threads each label their own samples — the
    context-local scope (and its token-based restore) never leaks one
    run's labels into the other or leaves stale labels active after."""
    sm = get_span_metrics()
    barrier = threading.Barrier(2, timeout=10)

    def one_run(run_id):
        with run_labels(workflow="wfC", run=run_id):
            barrier.wait()  # both label scopes active simultaneously
            for _ in range(3):
                with tracer.span("engine.z"):
                    pass
            barrier.wait()

    threads = [
        threading.Thread(target=one_run, args=(r,)) for r in ("rA", "rB")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    for r in ("rA", "rB"):
        h = sm.latency.get(span="engine.z", workflow="wfC", run=r)
        assert h is not None and h.count == 3, r
    # no unlabeled or cross-labeled series, and no labels linger
    assert sm.latency.get(span="engine.z") is None
    from fugue_tpu.obs import active_run_labels, current_run_labels

    assert current_run_labels() == {} and active_run_labels() == []


def test_run_label_series_cardinality_is_bounded(tracer):
    """A long-lived process must not accumulate one histogram series per
    run forever: only the most recent MAX_RUN_SERIES run ids keep series."""
    sm = get_span_metrics()
    cap = sm.MAX_RUN_SERIES
    n_runs = cap + 7
    for i in range(n_runs):
        with run_labels(workflow="wfR", run=f"run{i:04d}"):
            with tracer.span("engine.r"):
                pass
    runs_kept = {
        labels["run"]
        for labels, _ in sm.latency.series()
        if labels.get("workflow") == "wfR"
    }
    assert len(runs_kept) == cap
    # the newest runs survive, the oldest were pruned
    assert runs_kept == {f"run{i:04d}" for i in range(n_runs - cap, n_runs)}
    # the per-span summary still reports (merged across surviving runs)
    assert sm.summary()["engine.r"]["count"] == cap


# ---------------------------------------------------------------------------
# fork boundary: worker histogram deltas merge home
# ---------------------------------------------------------------------------


@pytest.mark.skipif(os.name != "posix", reason="fork pool requires posix fork")
def test_fork_worker_histograms_merge_home(tracer):
    from fugue_tpu.execution.parallel_map import fork_available

    if not fork_available():
        pytest.skip("no fork start method")
    import fugue_tpu.api as fa

    pdf = _frame(8000, 8, seed=2)

    def demean(df: pd.DataFrame) -> pd.DataFrame:
        df["v"] = df["v"] - df["v"].mean()
        return df

    e = JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_MAP_PARALLELISM: 2,
            FUGUE_TPU_CONF_MAP_PARALLEL_MIN_ROWS: 0,
        }
    )
    try:
        out = fa.transform(
            pdf, demean, schema="*", partition=PartitionSpec(by=["k"]), engine=e
        )
        assert len(out) == len(pdf)
    finally:
        e.stop_engine()
    recs = tracer.records()
    worker_chunks = [r for r in recs if r["name"] == "map.worker_chunk"]
    assert worker_chunks and all(r["pid"] != os.getpid() for r in worker_chunks)
    sm = get_span_metrics()
    # every worker-recorded span observation arrived home and merged: the
    # histogram totals equal the ingested span counts exactly
    summary = sm.summary()
    assert summary["map.worker_chunk"]["count"] == len(worker_chunks)
    parts = [r for r in recs if r["name"] == "map.partition"]
    assert summary["map.partition"]["count"] == len(parts) == 8
    # rows attrs fed the throughput family through the same channel
    rows_sum = sum(
        h.sum for labels, h in sm.rows.series() if labels["span"] == "map.partition"
    )
    assert rows_sum == len(pdf)
    # label-keyed merging: no series carries a pid label (collisions are
    # impossible by construction — two workers' equal-label series add)
    for fam in sm.families():
        for labels, _ in fam.series():
            assert "pid" not in labels and "worker" not in labels


# ---------------------------------------------------------------------------
# resource sampler
# ---------------------------------------------------------------------------


def test_sampler_start_stop_idempotent_and_ring_bounded(sampler):
    assert not sampler.running
    sampler.start(interval=0.005, ring_size=8)
    t1 = sampler._thread
    sampler.start()  # second start: same thread, no-op
    assert sampler._thread is t1 and sampler.running
    deadline = time.time() + 2.0
    while len(sampler.series()) < 10 and time.time() < deadline:
        time.sleep(0.01)
    assert 0 < len(sampler.series()) <= 8  # bounded ring
    sampler.stop()
    sampler.stop()  # idempotent
    assert not sampler.running
    # deterministic one-shot sampling without the thread
    vals = sampler.sample_once()
    assert vals["host_rss_bytes"] > 0
    assert "device_bytes" in vals
    ts, last = sampler.series()[-1]
    assert last == vals and ts > 0


def test_sampler_probe_lifecycle(sampler):
    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        return 42.0

    sampler.register_probe("custom_gauge", probe)
    assert "custom_gauge" in sampler.probe_names()
    assert sampler.sample_once()["custom_gauge"] == 42.0
    # a probe whose subject died unregisters itself
    from fugue_tpu.obs.sampler import ProbeGone

    def gone():
        raise ProbeGone()

    sampler.register_probe("dead", gone)
    sampler.sample_once()
    assert "dead" not in sampler.probe_names()
    # a probe that merely errors is kept but skipped for the tick
    def flaky():
        raise ValueError("x")

    sampler.register_probe("flaky", flaky)
    vals = sampler.sample_once()
    assert "flaky" not in vals and "flaky" in sampler.probe_names()
    sampler.unregister_probe("custom_gauge")
    sampler.unregister_probe("flaky")


def test_engine_conf_starts_sampler_and_registers_probes(sampler, monkeypatch):
    e = JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_TELEMETRY_ENABLED: True,
            FUGUE_TPU_CONF_TELEMETRY_INTERVAL: 0.01,
            FUGUE_TPU_CONF_TELEMETRY_RING: 16,
        }
    )
    try:
        assert sampler.running
        names = set(sampler.probe_names())
        assert {
            "host_rss_bytes",
            "device_bytes",
            "jit_cache_entries",
            "overlap_fraction",
            "result_cache_mem_bytes",
        } <= names
        vals = sampler.sample_once()
        assert vals["overlap_fraction"] >= 0.0
        # env var wins over conf, in both directions (tracer contract)
        monkeypatch.setenv("FUGUE_TPU_TELEMETRY", "0")
        e2 = JaxExecutionEngine({FUGUE_TPU_CONF_TELEMETRY_ENABLED: True})
        try:
            assert not sampler.running
        finally:
            e2.stop_engine()
    finally:
        e.stop_engine()


# ---------------------------------------------------------------------------
# the lifecycle satellite: reset_stats under the keep-entries contract
# ---------------------------------------------------------------------------


def test_reset_stats_resets_histograms_and_sampler_ring(tracer, sampler):
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2048})
    try:
        res = e.aggregate(
            _stream(_frame(6000, 8, seed=3)),
            PartitionSpec(by=["k"]),
            [ff.sum(col("v")).alias("s")],
        )
        assert len(res.as_pandas()) == 8
        sampler.sample_once()
        st = e.stats()
        assert st["latency"]  # distributions recorded
        assert st["telemetry"]["samples"] == 1
        assert st["jit_cache"]["entries"] > 0
        n_series = len(get_span_metrics().latency.series())
        probes_before = sampler.probe_names()
        e.reset_stats()
        st = e.stats()
        # observations zero everywhere...
        assert st["latency"] == {}
        assert st["telemetry"]["samples"] == 0
        assert st["jit_cache"]["hits"] == 0 and st["jit_cache"]["misses"] == 0
        # ...under the SAME keep-entries contract the JitCache uses:
        # compiled entries, histogram series, and sampler probes survive
        assert st["jit_cache"]["entries"] > 0
        assert len(get_span_metrics().latency.series()) == n_series > 0
        assert sampler.probe_names() == probes_before
    finally:
        e.stop_engine()


# ---------------------------------------------------------------------------
# exposure surfaces
# ---------------------------------------------------------------------------


def test_prometheus_exposition_valid_and_coherent(tracer, sampler):
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2048})
    try:
        res = e.aggregate(
            _stream(_frame(6000, 8, seed=4)),
            PartitionSpec(by=["k"]),
            [ff.sum(col("v")).alias("s")],
        )
        assert len(res.as_pandas()) == 8
        sampler.sample_once()
        text = to_prometheus_text(engine=e)
    finally:
        e.stop_engine()
    summary = validate_prometheus_text(text)  # grammar + bucket coherence
    assert summary["histogram_series"] > 0
    assert "fugue_tpu_span_latency_seconds_bucket" in summary["names"]
    assert "fugue_tpu_resource_host_rss_bytes" in summary["names"]
    assert "fugue_tpu_jit_cache_entries" in summary["names"]  # engine counters
    # per-program jit entries are ONE labeled gauge family, never a new
    # metric NAME per label (segment fingerprints would be unbounded)
    assert "fugue_tpu_jit_cache_entries_by_label" in summary["names"]
    assert not any("by_label_" in n for n in summary["names"]), summary["names"]
    # label values escape correctly and carry the span name
    assert 'span="engine.aggregate"' in text
    # histogram count line equals the recorded observations
    h = get_span_metrics().latency.get(span="engine.aggregate")
    assert (
        f'fugue_tpu_span_latency_seconds_count{{span="engine.aggregate"}} {h.count}'
        in text
    )


def test_validate_prometheus_rejects_garbage():
    with pytest.raises(AssertionError):
        validate_prometheus_text("this is{not metrics\n")
    with pytest.raises(AssertionError):
        validate_prometheus_text("")  # no samples


def test_validate_prometheus_rejects_duplicates():
    # duplicate TYPE line — Prometheus's parser rejects the whole page
    with pytest.raises(AssertionError, match="duplicate TYPE"):
        validate_prometheus_text(
            "# TYPE m gauge\nm 1\n# TYPE m gauge\nm 2\n"
        )
    # duplicate (name, label-set) sample
    with pytest.raises(AssertionError, match="duplicate sample"):
        validate_prometheus_text('m{a="x"} 1\nm{a="x"} 2\n')
    # same name, different labels is fine
    validate_prometheus_text('m{a="x"} 1\nm{a="y"} 2\n')


def test_metrics_page_unique_with_engine_and_running_sampler(tracer, sampler):
    """The exact configuration the PR advertises — engine bound AND the
    sampler active — must render each telemetry meta series exactly once
    (regression: the engine-stats flatten used to re-emit
    fugue_tpu_telemetry_samples/_running with a second TYPE line)."""
    e = JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_TELEMETRY_ENABLED: True,
            FUGUE_TPU_CONF_TELEMETRY_INTERVAL: 0.01,
            FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2048,
        }
    )
    try:
        res = e.aggregate(
            _stream(_frame(6000, 8, seed=6)),
            PartitionSpec(by=["k"]),
            [ff.sum(col("v")).alias("s")],
        )
        assert len(res.as_pandas()) == 8
        sampler.sample_once()
        text = to_prometheus_text(engine=e)
    finally:
        e.stop_engine()
    validate_prometheus_text(text)  # now includes the duplicate gates
    for name in ("fugue_tpu_telemetry_samples", "fugue_tpu_telemetry_running"):
        sample_lines = [
            ln for ln in text.splitlines() if ln.startswith(name + " ")
        ]
        type_lines = [
            ln for ln in text.splitlines() if ln.startswith(f"# TYPE {name} ")
        ]
        assert len(sample_lines) == 1, sample_lines
        assert len(type_lines) == 1, type_lines


def test_http_endpoints_scrape_live_run(tracer, sampler):
    from fugue_tpu.rpc.http import HttpRPCServer

    e = JaxExecutionEngine(
        {
            FUGUE_TPU_CONF_TELEMETRY_ENABLED: True,
            FUGUE_TPU_CONF_TELEMETRY_INTERVAL: 0.01,
        }
    )
    server = HttpRPCServer(e.conf)
    e.set_rpc_server(server)  # binds the engine for /metrics and /stats
    server.start()
    base = f"http://{server.host}:{server.port}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=5) as resp:
            return resp.status, resp.read().decode()

    def slow(df: pd.DataFrame) -> pd.DataFrame:
        time.sleep(0.05)
        return df

    inflight = []
    done = threading.Event()

    def scraper():
        while not done.is_set():
            try:
                code, body = get("/metrics")
                if code == 200 and "fugue_tpu_span_latency_seconds" in body:
                    inflight.append(body)
            except Exception:
                pass
            time.sleep(0.01)

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    try:
        dag = FugueWorkflow()
        d = dag.df(_frame(400, 4))
        for _ in range(4):  # ~0.8s of wall across 4 tasks x 4 partitions
            d = d.partition_by("k").transform(slow, schema="*")
        d.yield_dataframe_as("r", as_local=True)
        dag.run(e)
    finally:
        done.set()
        t.join(timeout=5)
    try:
        # scrapes landed WHILE the run was in flight, and parsed
        assert inflight, "no successful /metrics scrape during the run"
        validate_prometheus_text(inflight[-1])
        assert 'workflow="wf-' in inflight[-1]  # labeled mid-run
        # final state: all three endpoints
        code, body = get("/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        code, body = get("/metrics")
        assert code == 200
        validate_prometheus_text(body)
        assert "fugue_tpu_resource_device_bytes" in body
        code, body = get("/stats")
        stats = json.loads(body)
        assert stats["engine"]["jit_cache"] is not None
        assert stats["latency"]["workflow.run"]["count"] == 1
        assert stats["telemetry"]["running"] is True
        with pytest.raises(urllib.error.HTTPError):
            get("/nope")
    finally:
        server.stop()
        e.stop_engine()


def test_counter_tracks_ride_chrome_trace(tracer, sampler, tmp_path):
    e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2048})
    try:
        with tracer.span("engine.aggregate", cat="engine"):
            sampler.sample_once()
        sampler.sample_once()
        doc = to_chrome_trace(tracer.records(), counters=sampler.series())
        cs = [ev for ev in doc["traceEvents"] if ev["ph"] == "C"]
        assert cs and all(
            isinstance(ev["args"]["value"], (int, float)) for ev in cs
        )
        names = {ev["name"] for ev in cs}
        assert {"device_bytes", "overlap_fraction", "host_rss_bytes"} <= names
        # counter timestamps share the span clock (µs, same epoch)
        span_ev = next(ev for ev in doc["traceEvents"] if ev["ph"] == "X")
        first_c = min(ev["ts"] for ev in cs)
        assert span_ev["ts"] <= first_c <= span_ev["ts"] + span_ev["dur"] + 1e4
        # write path picks the sampler ring up automatically + validator
        from fugue_tpu.obs import write_chrome_trace

        p = write_chrome_trace(str(tmp_path / "t.json"), tracer.records())
        s = validate_chrome_trace(p)
        assert s["counters"] == len(cs)
        assert "device_bytes" in s["counter_names"]
    finally:
        e.stop_engine()


# ---------------------------------------------------------------------------
# disabled-path overhead guard (mirrors the tracer's)
# ---------------------------------------------------------------------------


def test_disabled_telemetry_overhead_under_2_percent():
    """With telemetry fully disabled there is no sampler thread at all and
    the span sites still cost ~an attribute check — charging every span
    the measured worst-case disabled cost must stay under 2% of a small
    streaming aggregate's wall (the tracer guard, re-proven on top of the
    histogram-feeding code paths this PR added to span close)."""
    tr = get_tracer()
    tr.disable()
    tr.clear()
    s = get_sampler()
    s.stop()
    assert not s.running  # disabled telemetry = no thread, no samples
    pdf = _frame(30_000, 64, seed=5)
    spec = PartitionSpec(by=["k"])
    aggs = lambda: [ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n")]  # noqa: E731

    def run():
        e = JaxExecutionEngine({FUGUE_TPU_CONF_STREAM_CHUNK_ROWS: 2048})
        try:
            return len(e.aggregate(_stream(pdf), spec, aggs()).as_pandas())
        finally:
            e.stop_engine()

    assert run() == 64  # warmup
    t0 = time.perf_counter()
    assert run() == 64
    wall_disabled = time.perf_counter() - t0
    n_calls = 100_000
    t0 = time.perf_counter()
    for _ in range(n_calls):
        with tr.span("x", cat="engine", rows=1):
            pass
    per_call = (time.perf_counter() - t0) / n_calls
    tr.enable()
    try:
        tr.clear()
        assert run() == 64
        n_spans = len(tr.records())
    finally:
        tr.disable()
        tr.clear()
        get_span_metrics().clear()
    overhead = n_spans * per_call
    assert overhead < 0.02 * wall_disabled, (
        f"{n_spans} spans x {per_call * 1e6:.2f}µs = {overhead * 1e3:.3f}ms "
        f"vs wall {wall_disabled * 1e3:.1f}ms"
    )


# ---------------------------------------------------------------------------
# bench --compare (pure JSON diff; heavy imports only, nothing re-runs)
# ---------------------------------------------------------------------------


def test_bench_compare_flags_regressions(tmp_path):
    base = tmp_path / "base.json"
    base.write_text(
        json.dumps(
            {
                "value": 100.0,
                "vs_baseline": 1.0,
                "plan_pruning": {"speedup_vs_unoptimized": 2.0},
                "wall_s": 30,  # not a compared key
            }
        )
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(cur):
        p = tmp_path / "cur.json"
        p.write_text(json.dumps(cur))
        return subprocess.run(
            [sys.executable, "bench.py", "--compare", str(base), str(p)],
            cwd=REPO_ROOT,
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )

    ok = run(
        {
            "value": 95.0,
            "vs_baseline": 0.99,
            "plan_pruning": {"speedup_vs_unoptimized": 1.9},
        }
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "REGRESSION" not in ok.stdout
    assert '"compared": 3' in ok.stdout
    bad = run(
        {
            "value": 50.0,  # 0.5x < 0.8 threshold
            "vs_baseline": 0.99,
            "plan_pruning": {"speedup_vs_unoptimized": 1.9},
        }
    )
    assert bad.returncode == 8, bad.stdout + bad.stderr
    assert "REGRESSION" in bad.stdout and "compare value:" in bad.stdout
