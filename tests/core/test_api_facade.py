"""fa.* facade coverage: every engine verb through the functional API."""

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.column import col, functions as f


@pytest.fixture
def pdf():
    return pd.DataFrame({"a": [1, 2, 2, None], "b": ["x", "y", "y", "z"]})


class TestFacadeVerbs:
    def test_dataset_utils(self, pdf):
        assert fa.count(pdf) == 4
        assert not fa.is_empty(pdf)
        assert fa.is_local(pdf) and fa.is_bounded(pdf)
        assert fa.get_column_names(pdf) == ["a", "b"]
        assert str(fa.get_schema(pdf)) == "a:double,b:str"

    def test_frame_utils(self, pdf):
        r = fa.rename(pdf, {"a": "aa"})
        assert list(r.columns) == ["aa", "b"]
        assert list(fa.drop_columns(pdf, ["a"]).columns) == ["b"]
        assert list(fa.select_columns(pdf, ["b"]).columns) == ["b"]
        assert fa.head(pdf, 2).shape[0] == 2
        assert fa.peek_dict(pdf) == {"a": 1.0, "b": "x"}
        assert len(fa.as_dicts(pdf)) == 4

    def test_relational_verbs(self, pdf):
        assert len(fa.distinct(pdf)) == 3
        assert len(fa.dropna(pdf)) == 3
        assert fa.fillna(pdf, 0.0, subset=["a"])["a"].tolist() == [1, 2, 2, 0]
        assert len(fa.sample(pdf, n=2, seed=1)) == 2
        assert fa.take(pdf, 1, presort="a desc")["b"].tolist() == ["y"]

    def test_select_filter_assign_aggregate(self, pdf):
        s = fa.select(pdf, "b", (col("a") * 2).alias("a2"))
        assert list(s.columns) == ["b", "a2"]
        flt = fa.filter(pdf, col("a").not_null())
        assert len(flt) == 3
        asg = fa.assign(pdf, c=col("a") + 1)
        assert "c" in asg.columns
        agg = fa.aggregate(pdf, partition_by="b", n=f.count(col("a")))
        # COUNT skips nulls: group z has a=None -> 0
        assert sorted(agg["n"].tolist()) == [0, 1, 2]

    def test_joins_setops(self):
        d1 = pd.DataFrame({"k": [1, 2]})
        d2 = pd.DataFrame({"k": [2, 3]})
        assert fa.union(d1, d2)["k"].tolist() == [1, 2, 3]
        assert fa.intersect(d1, d2)["k"].tolist() == [2]
        assert fa.subtract(d1, d2)["k"].tolist() == [1]
        d3 = pd.DataFrame({"k": [2], "v": ["x"]})
        lj = fa.left_outer_join(d1, d3)
        assert lj["k"].tolist() == [1, 2]
        assert lj["v"].isna().tolist() == [True, False]
        assert len(fa.cross_join(d1, d2.rename(columns={"k": "j"}))) == 4

    def test_save_load_roundtrip(self, tmp_path, pdf):
        p = str(tmp_path / "x.parquet")
        fa.save(pdf, p)
        back = fa.load(p, as_fugue=True)
        assert back.count() == 4

    def test_engine_context_nesting(self):
        with fa.engine_context("native") as e1:
            with fa.engine_context("pandas") as e2:
                assert fa.get_context_engine() is e2
            assert fa.get_context_engine() is e1

    def test_global_engine(self):
        e = fa.set_global_engine("native")
        try:
            assert fa.get_context_engine() is e
        finally:
            fa.clear_global_engine()

    def test_parallelism(self):
        assert fa.get_current_parallelism(engine="native") == 1


def test_dev_facade_exports():
    """`fugue_tpu.dev` mirrors the reference's extension-developer facade
    (`fugue/dev.py`): one import for backend authors."""
    import fugue_tpu.dev as dev

    for name in (
        "AnnotatedParam",
        "DataFrameFunctionWrapper",
        "EngineFacet",
        "ExecutionEngine",
        "ExecutionEngineParam",
        "MapEngine",
        "SQLEngine",
        "PandasMapEngine",
        "PartitionCursor",
        "PartitionSpec",
        "StructuredRawSQL",
        "TempTableName",
        "Yielded",
        "PhysicalYielded",
        "RPCServer",
        "RPCHandler",
        "make_rpc_server",
        "register_execution_engine",
        "register_sql_engine",
        "make_execution_engine",
        "FugueWorkflow",
        "WorkflowDataFrame",
        "WorkflowDataFrames",
        "FugueWorkflowContext",
        "module",
        "DialectProfile",
        "WarehouseProfile",
    ):
        assert hasattr(dev, name), name


def test_workflow_dataframes_container():
    from fugue_tpu import FugueWorkflow
    from fugue_tpu.workflow.workflow import WorkflowDataFrames

    dag = FugueWorkflow()
    a = dag.df([[1]], "a:int")
    b = dag.df([[2]], "b:int")
    arr = WorkflowDataFrames(a, b)
    assert not arr.has_key and arr["_0"] is a and arr["_1"] is b
    named = WorkflowDataFrames(x=a, y=b)
    assert named.has_key and named.workflow is dag
    import pytest as _pytest

    from fugue_tpu.exceptions import FugueWorkflowCompileError

    with _pytest.raises(FugueWorkflowCompileError):
        WorkflowDataFrames(a, FugueWorkflow().df([[3]], "c:int"))
    with _pytest.raises(FugueWorkflowCompileError):
        WorkflowDataFrames(123)


def test_as_fugue_engine_df():
    """`fa.as_fugue_engine_df` converts any dataframe-like object to the
    engine's native frame (reference `execution/api.py:125`)."""
    import pandas as pd

    import fugue_tpu.api as fa
    from fugue_tpu.execution import NativeExecutionEngine

    e = NativeExecutionEngine()
    d = fa.as_fugue_engine_df(e, pd.DataFrame({"a": [1, 2]}))
    assert d.schema.names == ["a"] and d.count() == 2
    d2 = fa.as_fugue_engine_df(e, pd.DataFrame({"a": [1]}), schema="a:int")
    assert str(d2.schema) == "a:int"
