"""Fault-injection suite for the resilience layer (fugue_tpu/resilience).

Every test here configures the conf/env-driven FaultInjector to break the
system at a named site and asserts BOTH that the run still produces correct
results AND that the engine's resilience counters report the recovery that
happened — the graceful-degradation order (parallel → retry → serial →
raise) is observable, never silent. See docs/resilience.md.
"""

import os
import socket
import time

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.execution.parallel_map import fork_available
from fugue_tpu.resilience import (
    ChunkTimeoutError,
    Deadline,
    FailureCategory,
    FaultInjector,
    InjectedFaultError,
    ParallelMapError,
    RetryPolicy,
    WorkerLostError,
    classify_failure,
)

PARENT_PID = os.getpid()

PAR_CONF = {
    "fugue.tpu.map.parallelism": 2,
    "fugue.tpu.map.parallel_min_rows": 0,
    "fugue.tpu.retry.base": 0.02,
}

fork_only = pytest.mark.skipif(not fork_available(), reason="no fork")


def _demean(pdf: pd.DataFrame) -> pd.DataFrame:
    return pdf.assign(d=pdf["v"] - pdf["v"].mean())


def _frame(n_keys: int = 16, rows: int = 4000) -> pd.DataFrame:
    rng = np.random.default_rng(7)
    return pd.DataFrame(
        {"k": rng.integers(0, n_keys, rows), "v": rng.random(rows)}
    )


def _transform(df: pd.DataFrame, engine) -> pd.DataFrame:
    res = fa.transform(
        df,
        _demean,
        schema="k:long,v:double,d:double",
        partition={"by": ["k"]},
        engine=engine,
        as_local=True,
    )
    return pd.DataFrame(res).sort_values(["k", "v"]).reset_index(drop=True)


# ---------------------------------------------------------------------------
# policy / taxonomy units
# ---------------------------------------------------------------------------
class TestPolicy:
    def test_classification(self):
        assert classify_failure(ConnectionRefusedError()) is FailureCategory.TRANSIENT
        assert classify_failure(InjectedFaultError()) is FailureCategory.TRANSIENT
        assert classify_failure(TimeoutError()) is FailureCategory.TIMEOUT
        assert classify_failure(ChunkTimeoutError()) is FailureCategory.TIMEOUT
        assert classify_failure(WorkerLostError()) is FailureCategory.WORKER_LOST
        assert classify_failure(ValueError("bad udf")) is FailureCategory.POISON
        assert classify_failure(KeyboardInterrupt()) is FailureCategory.FATAL

    def test_retry_policy_bounds_and_determinism(self):
        p = RetryPolicy(max_attempts=3, base_delay=0.1, multiplier=2.0, jitter=0.5)
        assert p.should_retry(FailureCategory.TRANSIENT, 1)
        assert p.should_retry(FailureCategory.WORKER_LOST, 2)
        assert not p.should_retry(FailureCategory.TRANSIENT, 3)  # exhausted
        assert not p.should_retry(FailureCategory.POISON, 1)  # never retried
        assert not p.should_retry(FailureCategory.FATAL, 1)
        # exponential growth + deterministic jitter
        d1, d2 = p.delay(1, seed="x"), p.delay(2, seed="x")
        assert d2 > d1
        assert p.delay(2, seed="x") == d2  # same seed, same schedule
        assert p.delay(2, seed="y") != d2  # distinct seeds de-synchronize

    def test_retry_policy_from_conf(self):
        from fugue_tpu._utils.params import ParamDict

        p = RetryPolicy.from_conf(
            ParamDict({"fugue.tpu.retry.attempts": 5, "fugue.tpu.retry.jitter": 0})
        )
        assert p.max_attempts == 5 and p.jitter == 0

    def test_deadline(self):
        assert Deadline.after(None).unbounded
        assert Deadline.after(0).unbounded
        assert not Deadline.after(None).expired
        d = Deadline.after(0.01)
        time.sleep(0.03)
        assert d.expired and d.remaining() == 0.0
        with pytest.raises(ChunkTimeoutError):
            d.raise_if_expired("chunk")


class TestFaultInjector:
    def test_plan_parsing_and_budget(self):
        inj = FaultInjector("a.site=error:ValueError@2; b.site=delay:0")
        with pytest.raises(ValueError):
            inj.fire("a.site")
        with pytest.raises(ValueError):
            inj.fire("a.site")
        inj.fire("a.site")  # budget spent → inert
        inj.fire("b.site")  # 0s delay → no-op
        inj.fire("unknown.site")  # no rule → no-op

    def test_kill_in_driver_degrades_to_raise(self):
        inj = FaultInjector("x=kill")
        with pytest.raises(InjectedFaultError):
            inj.fire("x")  # must NOT SIGKILL the test process

    def test_bad_plans_rejected(self):
        with pytest.raises(ValueError):
            FaultInjector("site=explode")
        with pytest.raises(ValueError):
            FaultInjector("just-garbage")

    def test_disabled_without_plan(self):
        from fugue_tpu._utils.params import ParamDict

        assert not FaultInjector.from_conf(ParamDict()).enabled


# ---------------------------------------------------------------------------
# fork-pool recovery (the acceptance scenario and its neighbours)
# ---------------------------------------------------------------------------
@fork_only
class TestForkPoolRecovery:
    def test_worker_sigkill_recovers_bit_identical(self):
        """Acceptance: with the injector SIGKILLing one fork worker per map,
        a 16-partition transform returns bit-identical results to the
        unfaulted run, and the counters report the recovery."""
        df = _frame(n_keys=16)
        baseline = _transform(df, NativeExecutionEngine(PAR_CONF))
        e = NativeExecutionEngine({**PAR_CONF, "fugue.tpu.fault.plan": "map.chunk=kill"})
        out = _transform(df, e)
        pd.testing.assert_frame_equal(baseline, out)
        stats = e.resilience_stats.as_dict()
        assert stats.get("map.worker_lost", 0) >= 1
        assert stats.get("map.chunk_retries", 0) >= 1
        assert stats.get("map.pool_rebuilds", 0) >= 1

    def test_chunk_deadline_expiry_recovers(self):
        """An injected in-chunk stall blows the per-chunk deadline; the
        supervisor tears the wave down and the retry (budget spent) runs
        clean."""
        df = _frame(n_keys=8, rows=2000)
        baseline = _transform(df, NativeExecutionEngine(PAR_CONF))
        e = NativeExecutionEngine(
            {
                **PAR_CONF,
                "fugue.tpu.fault.plan": "map.chunk=delay:10",
                "fugue.tpu.map.chunk_timeout": 0.6,
            }
        )
        t0 = time.perf_counter()
        out = _transform(df, e)
        wall = time.perf_counter() - t0
        pd.testing.assert_frame_equal(baseline, out)
        assert e.resilience_stats.get("map.deadline_expiries") >= 1
        assert wall < 8, wall  # never waited out the injected 10s stall

    def test_poison_partition_quarantined_to_serial(self):
        """A partition that fails deterministically in workers must fall
        back to in-driver serial execution (where it happens to succeed —
        e.g. it needed driver-process state) without failing the map."""
        df = _frame(n_keys=8, rows=2000)

        def child_poison(pdf: pd.DataFrame) -> pd.DataFrame:
            if os.getpid() != PARENT_PID and pdf["k"].iloc[0] == 3:
                raise ValueError("poison in worker")
            return pdf.assign(d=1.0)

        e = NativeExecutionEngine(PAR_CONF)
        res = fa.transform(
            df,
            child_poison,
            schema="k:long,v:double,d:double",
            partition={"by": ["k"]},
            engine=e,
            as_local=True,
        )
        assert len(pd.DataFrame(res)) == len(df)
        stats = e.resilience_stats.as_dict()
        assert stats.get("map.quarantined_chunks", 0) >= 1
        assert stats.get("map.quarantined_partitions", 0) >= 1
        assert stats.get("map.serial_fallbacks", 0) >= 1

    def test_unrecoverable_poison_raises_partition_report(self):
        """When the serial fallback fails too, the map raises a
        ParallelMapError naming the exact poison partitions."""
        df = _frame(n_keys=6, rows=1200)

        def always_poison(pdf: pd.DataFrame) -> pd.DataFrame:
            if pdf["k"].iloc[0] == 2:
                raise ValueError("always poison")
            return pdf.assign(d=1.0)

        e = NativeExecutionEngine(PAR_CONF)
        with pytest.raises(Exception) as ei:
            fa.transform(
                df,
                always_poison,
                schema="k:long,v:double,d:double",
                partition={"by": ["k"]},
                engine=e,
                as_local=True,
            )
        # the report survives the workflow's exception rewrapping
        msg = str(ei.value)
        assert "partition" in msg and "always poison" in msg

    def test_single_chunk_short_circuits_pool(self, monkeypatch):
        """A map whose partitions collapse into one chunk must skip pool
        setup entirely (~100ms) and run serially in-driver."""
        import pyarrow as pa

        from fugue_tpu.execution import parallel_map as pm

        def no_pool(*a, **k):  # pragma: no cover - failing is the assert
            raise AssertionError("pool must not be created for a single chunk")

        monkeypatch.setattr(pm, "_make_pool", no_pool)

        class Cur:
            def set(self, *a):
                pass

        pdf = pd.DataFrame({"a": np.arange(101, dtype=np.int64)})
        # sizes [1, 100] collapse into one chunk under the quantile cuts
        tables = pm.run_partitions_forked(
            pdf,
            None,
            [slice(0, 1), slice(1, 101)],
            lambda cursor, part: part,
            Cur(),
            None,
            n_workers=2,
            wrap_df=lambda sub, schema: sub,
            to_arrow=lambda res, schema: pa.Table.from_pandas(res),
        )
        assert sum(t.num_rows for t in tables) == 101
        assert pm.run_partitions_forked(
            pdf, None, [], lambda c, p: p, Cur(), None, 2,
            wrap_df=lambda s, sc: s,
            to_arrow=lambda r, sc: pa.Table.from_pandas(r),
        ) == []


# ---------------------------------------------------------------------------
# RPC retry / timeouts
# ---------------------------------------------------------------------------
class TestRPCResilience:
    def _free_port(self) -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def test_retry_exhaustion_counts_and_raises(self):
        from fugue_tpu.resilience import ResilienceStats
        from fugue_tpu.rpc.http import HttpRPCClient

        stats = ResilienceStats()
        client = HttpRPCClient(
            "127.0.0.1",
            self._free_port(),
            "key",
            policy=RetryPolicy(max_attempts=3, base_delay=0.01, jitter=0),
            idempotent=True,
            stats=stats,
        )
        with pytest.raises(ConnectionError):
            client("payload")
        assert stats.get("rpc.retries") == 2  # 3 attempts = 2 retries

    def test_connect_phase_failures_retry_even_when_not_idempotent(self):
        """A refused connection means the server never saw the request —
        always safe to retry regardless of idempotency."""
        from fugue_tpu.resilience import ResilienceStats
        from fugue_tpu.rpc.http import HttpRPCClient

        stats = ResilienceStats()
        client = HttpRPCClient(
            "127.0.0.1",
            self._free_port(),
            "key",
            policy=RetryPolicy(max_attempts=2, base_delay=0.01, jitter=0),
            idempotent=False,
            stats=stats,
        )
        with pytest.raises(ConnectionError):
            client("payload")
        assert stats.get("rpc.retries") == 1

    def test_server_conf_timeouts_reach_clients(self):
        from fugue_tpu._utils.params import ParamDict
        from fugue_tpu.rpc.http import HttpRPCServer

        srv = HttpRPCServer(
            ParamDict(
                {
                    "fugue.rpc.http_client.connect_timeout": 1.5,
                    "fugue.rpc.http_client.read_timeout": 7.5,
                    "fugue.tpu.retry.rpc.attempts": 4,
                }
            )
        )
        c = srv.create_client("k")
        assert c._connect_timeout == 1.5
        assert c._timeout == 7.5
        assert c._policy.max_attempts == 4

    def test_client_stub_survives_pickle(self):
        import cloudpickle

        from fugue_tpu._utils.params import ParamDict
        from fugue_tpu.rpc.http import HttpRPCServer

        srv = HttpRPCServer(ParamDict({"fugue.rpc.http_server.port": 0}))
        srv.start()
        try:
            key = srv.register(lambda x: x + 1)
            stub = cloudpickle.loads(cloudpickle.dumps(srv.create_client(key)))
            assert stub(41) == 42
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# workflow: task retry + checkpoint-aware replay + atomic checkpoints
# ---------------------------------------------------------------------------
class TestWorkflowResilience:
    def test_injected_task_failure_retried(self):
        from fugue_tpu.workflow import FugueWorkflow

        def make() -> pd.DataFrame:
            return pd.DataFrame({"a": [1, 2]})

        e = NativeExecutionEngine(
            {
                "fugue.tpu.fault.plan": "task.execute=error",
                "fugue.tpu.retry.task.attempts": 2,
                "fugue.tpu.retry.base": 0.01,
            }
        )
        dag = FugueWorkflow()
        dag.create(make).yield_dataframe_as("out", as_local=True)
        res = dag.run(e)
        assert res["out"].result.as_array() == [[1], [2]]
        assert e.resilience_stats.get("workflow.task_retries") == 1

    def test_poison_task_not_retried(self):
        from fugue_tpu.workflow import FugueWorkflow

        calls = []

        def bad() -> pd.DataFrame:
            calls.append(1)
            raise ValueError("deterministic user bug")

        e = NativeExecutionEngine(
            {"fugue.tpu.retry.task.attempts": 3, "fugue.tpu.retry.base": 0.01}
        )
        dag = FugueWorkflow()
        dag.create(bad).yield_dataframe_as("out", as_local=True)
        with pytest.raises(Exception):
            dag.run(e)
        assert len(calls) == 1  # POISON is never retried

    def test_checkpoint_aware_replay_runs_upstream_once(self, tmp_path):
        """Across a failed run + retry run, the checkpointed upstream task
        body executes exactly once — the retry replays it from disk."""
        from fugue_tpu.workflow import FugueWorkflow

        calls = []
        fail = [True]

        def upstream() -> pd.DataFrame:
            calls.append(1)
            return pd.DataFrame({"a": [1, 2, 3]})

        def downstream(df: pd.DataFrame) -> pd.DataFrame:
            if fail[0]:
                raise RuntimeError("transient downstream failure")
            return df.assign(b=df["a"] * 2)

        def build() -> FugueWorkflow:
            dag = FugueWorkflow()
            a = dag.create(upstream).deterministic_checkpoint()
            a.transform(downstream, schema="a:long,b:long").yield_dataframe_as(
                "out", as_local=True
            )
            return dag

        e = NativeExecutionEngine(
            {"fugue.workflow.checkpoint.path": str(tmp_path)}
        )
        with pytest.raises(Exception):
            build().run(e)
        assert len(calls) == 1
        fail[0] = False
        res = build().run(e)
        assert len(calls) == 1  # replayed from disk, not recomputed
        assert res["out"].result.as_array() == [[1, 2], [2, 4], [3, 6]]
        assert e.resilience_stats.get("workflow.checkpoint_replays") >= 1

    def test_interrupted_checkpoint_write_leaves_no_torn_file(self, tmp_path):
        """A fault between the checkpoint's data write and its atomic
        publish must leave nothing at the final path — the next run
        recomputes instead of resuming from a torn file."""
        from fugue_tpu.workflow import FugueWorkflow

        calls = []

        def upstream() -> pd.DataFrame:
            calls.append(1)
            return pd.DataFrame({"a": [7]})

        def build() -> FugueWorkflow:
            dag = FugueWorkflow()
            dag.create(upstream).deterministic_checkpoint().yield_dataframe_as(
                "out", as_local=True
            )
            return dag

        e_faulted = NativeExecutionEngine(
            {
                "fugue.workflow.checkpoint.path": str(tmp_path),
                "fugue.tpu.fault.plan": "checkpoint.save=error",
            }
        )
        with pytest.raises(Exception):
            build().run(e_faulted)
        # neither a final checkpoint nor a stray temp file anywhere
        assert list(tmp_path.rglob("*.parquet")) == []
        e_clean = NativeExecutionEngine(
            {"fugue.workflow.checkpoint.path": str(tmp_path)}
        )
        res = build().run(e_clean)
        assert len(calls) == 2  # torn write was NOT mistaken for a checkpoint
        assert res["out"].result.as_array() == [[7]]
        assert len(list(tmp_path.rglob("*.parquet"))) == 1
