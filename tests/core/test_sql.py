"""SQL layer tests: standard SQL on both engines + FugueSQL."""

import os

import numpy as np
import pandas as pd
import pytest

from fugue_tpu.collections.sql import StructuredRawSQL
from fugue_tpu.dataframe import DataFrames
from fugue_tpu.exceptions import FugueSQLSyntaxError
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.sql import fugue_sql, fugue_sql_flow
from fugue_tpu.workflow import raw_sql


def _q(engine, dfs, sql):
    return engine.sql_engine.select(
        DataFrames(dfs), StructuredRawSQL([(False, sql)], dialect="spark")
    ).as_array(type_safe=True)


@pytest.fixture
def engine():
    e = NativeExecutionEngine()
    yield e
    e.stop()


@pytest.fixture
def dfs(engine):
    a = engine.to_df(
        [[1, "x", 10.0], [2, "y", 20.0], [1, "z", 5.0], [3, None, None]],
        "k:long,s:str,v:double",
    )
    b = engine.to_df([[1, "A"], [3, "C"]], "k:long,t:str")
    return {"a": a, "b": b}


class TestStandardSQL:
    def test_projection_filter(self, engine, dfs):
        assert _q(engine, dfs, "SELECT k, v*2 AS vv FROM a WHERE v >= 10") == [
            [1, 20.0], [2, 40.0],
        ]

    def test_group_by(self, engine, dfs):
        assert _q(
            engine, dfs,
            "SELECT k, SUM(v) AS s, COUNT(*) AS n FROM a GROUP BY k ORDER BY k",
        ) == [[1, 15.0, 2], [2, 20.0, 1], [3, None, 1]]

    def test_having(self, engine, dfs):
        assert _q(
            engine, dfs,
            "SELECT k, COUNT(*) AS n FROM a GROUP BY k HAVING n > 1",
        ) == [[1, 2]]

    def test_joins(self, engine, dfs):
        assert _q(
            engine, dfs,
            "SELECT a.k, s, t FROM a INNER JOIN b ON a.k = b.k ORDER BY s",
        ) == [[3, None, "C"], [1, "x", "A"], [1, "z", "A"]]
        assert (
            len(_q(engine, dfs, "SELECT a.k, s, t FROM a LEFT JOIN b ON a.k = b.k"))
            == 4
        )

    def test_set_ops(self, engine, dfs):
        assert _q(
            engine, dfs, "SELECT k FROM a UNION SELECT k FROM b ORDER BY k"
        ) == [[1], [2], [3]]
        assert _q(
            engine, dfs, "SELECT k FROM a EXCEPT SELECT k FROM b ORDER BY k"
        ) == [[2]]

    def test_case_in_like_between(self, engine, dfs):
        assert _q(
            engine, dfs,
            "SELECT k, CASE WHEN v >= 10 THEN 'hi' ELSE 'lo' END AS c "
            "FROM a WHERE k IN (1, 2) ORDER BY k, c",
        ) == [[1, "hi"], [1, "lo"], [2, "hi"]]
        assert _q(
            engine, dfs, "SELECT k FROM a WHERE s LIKE 'x%' OR k BETWEEN 3 AND 3 ORDER BY k"
        ) == [[1], [3]]

    def test_subquery_distinct_limit(self, engine, dfs):
        assert _q(
            engine, dfs,
            "SELECT DISTINCT k FROM (SELECT k FROM a WHERE v IS NOT NULL) t ORDER BY k LIMIT 2",
        ) == [[1], [2]]

    def test_scalar_functions(self, engine, dfs):
        assert _q(
            engine, dfs,
            "SELECT UPPER(s) AS u FROM a WHERE s IS NOT NULL ORDER BY u",
        ) == [["X"], ["Y"], ["Z"]]

    def test_syntax_error(self, engine, dfs):
        with pytest.raises(FugueSQLSyntaxError):
            _q(engine, dfs, "SELEC k FROM a")

    def test_missing_table(self, engine, dfs):
        with pytest.raises(Exception):
            _q(engine, dfs, "SELECT * FROM nope")


class TestRawSQLAPI:
    def test_raw_sql(self):
        pdf = pd.DataFrame({"a": [1, 2, 3]})
        res = raw_sql("SELECT SUM(a) AS s FROM ", pdf)
        assert res.values.tolist() == [[6]]


class TestFugueSQL:
    def test_capture_local_var(self):
        src = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
        r = fugue_sql("SELECT k, SUM(v) AS s FROM src GROUP BY k ORDER BY k")
        assert r["s"].tolist() == [3.0, 3.0]

    def test_multi_statement_transform(self):
        src = pd.DataFrame({"k": [1, 2], "v": [1.0, 2.0]})

        def double(df: pd.DataFrame) -> pd.DataFrame:
            df["v"] = df["v"] * 2
            return df

        r = fugue_sql(
            """
            a = SELECT * FROM src WHERE v > 1
            TRANSFORM a USING double SCHEMA *
            """
        )
        assert r.values.tolist() == [[2, 4.0]]

    def test_create_take_print(self, capsys):
        r = fugue_sql(
            """
            x = CREATE [[0,"a"],[1,"b"],[2,"c"]] SCHEMA n:long,s:str
            PRINT 2 ROWS FROM x TITLE "demo"
            TAKE 2 ROWS FROM x PRESORT n DESC
            """
        )
        assert r["n"].tolist() == [2, 1]
        assert "demo" in capsys.readouterr().out

    def test_save_load(self, tmp_path):
        path = os.path.join(str(tmp_path), "x.parquet")
        fugue_sql_flow(
            f"""
            a = CREATE [[1,"x"],[2,"y"]] SCHEMA id:long,s:str
            SAVE a OVERWRITE PARQUET "{path}"
            """
        ).run()
        r = fugue_sql(
            f"""
            b = LOAD PARQUET "{path}"
            SELECT * FROM b WHERE id = 2
            """
        )
        assert r.values.tolist() == [[2, "y"]]

    def test_yields(self):
        dag = fugue_sql_flow(
            """
            a = CREATE [[1],[2]] SCHEMA z:long
            YIELD DATAFRAME AS out
            """
        )
        res = dag.run()
        assert res.yields["out"].result.as_array() == [[1], [2]]

    def test_jinja_template(self):
        threshold = 1
        src = pd.DataFrame({"a": [1, 2, 3]})
        r = fugue_sql("SELECT * FROM src WHERE a > {{threshold}}", threshold=threshold)
        assert r["a"].tolist() == [2, 3]

    def test_drop_fill_rename_alter_sample(self):
        src = pd.DataFrame({"a": [1.0, None, 3.0], "b": ["x", "y", None]})
        r = fugue_sql("DROP ROWS IF ANY NULL FROM src")
        assert r.values.tolist() == [[1.0, "x"]]
        r2 = fugue_sql("FILL NULLS PARAMS a:0 FROM src")
        assert r2["a"].tolist() == [1.0, 0.0, 3.0]
        r3 = fugue_sql("RENAME COLUMNS a:aa FROM src")
        assert list(r3.columns) == ["aa", "b"]
        r4 = fugue_sql("ALTER COLUMNS a:str FROM src", as_fugue=True)
        assert str(r4.schema) == "a:str,b:str"

    def test_fsql_on_jax_engine(self):
        src = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
        # on the jax engine the native result is the distributed frame
        r = fugue_sql(
            "SELECT k, SUM(v) AS s FROM src GROUP BY k ORDER BY k",
            engine="jax",
            as_fugue=True,
        ).as_pandas()
        assert r["s"].tolist() == [3.0, 3.0]


def _make_df_for_fsql(n: int = 3) -> pd.DataFrame:
    return pd.DataFrame({"a": range(n)})


class TestFugueSQLStatements:
    """The statement forms beyond SELECT/TRANSFORM."""

    def test_create_using(self):
        r = fugue_sql("CREATE USING _make_df_for_fsql(n=5)", as_fugue=True)
        assert r.count() == 5

    def test_process_output(self):
        def double(df: pd.DataFrame) -> pd.DataFrame:
            df["a"] = df["a"] * 2
            return df

        seen = []

        def sink(df: pd.DataFrame) -> None:
            seen.append(len(df))

        r = fugue_sql(
            """
            x = CREATE USING _make_df_for_fsql(n=4)
            y = PROCESS x USING double SCHEMA a:long
            OUTPUT y USING sink
            SELECT * FROM y WHERE a > 2
            """,
            as_fugue=True,
        )
        assert seen == [4]
        assert r.as_array() == [[4], [6]]

    def test_outtransform_prepartition(self):
        counts = []

        def tally(df: pd.DataFrame) -> None:
            counts.append(len(df))

        fugue_sql_flow(
            """
            x = CREATE [[1],[1],[2]] SCHEMA k:long
            OUTTRANSFORM x PREPARTITION BY k USING tally
            """
        ).run()
        assert sorted(counts) == [1, 2]

    def test_transform_presort(self):
        def first_row(df: pd.DataFrame) -> pd.DataFrame:
            return df.head(1)

        r = fugue_sql(
            """
            x = CREATE [[1,5],[1,9],[2,3]] SCHEMA k:long,v:long
            TRANSFORM x PREPARTITION BY k PRESORT v DESC USING first_row SCHEMA *
            """,
            as_fugue=True,
        )
        assert sorted(r.as_array()) == [[1, 9], [2, 3]]

    def test_sample_statement(self):
        r = fugue_sql(
            """
            x = CREATE USING _make_df_for_fsql(n=100)
            SAMPLE 10 ROWS SEED 42 FROM x
            """,
            as_fugue=True,
        )
        assert r.count() == 10

    def test_yield_file(self, tmp_path):
        dag = fugue_sql_flow(
            """
            x = CREATE [[7]] SCHEMA z:long
            YIELD FILE AS saved
            """
        )
        res = dag.run("native", {"fugue.workflow.checkpoint.path": str(tmp_path / "ck")})
        assert res.yields["saved"].storage_type == "file"
        assert os.path.exists(res.yields["saved"].name)

    def test_print_without_title(self, capsys):
        fugue_sql_flow("x = CREATE [[1]] SCHEMA z:long\nPRINT x").run()
        out = capsys.readouterr().out
        assert "None" not in out and "z:long" in out


class TestWindowFunctions:
    @pytest.fixture
    def wdf(self):
        return pd.DataFrame({"k": [1, 1, 1, 2, 2], "v": [10.0, 30.0, 20.0, 5.0, 15.0]})

    def test_row_number(self, wdf):
        r = fugue_sql(
            "SELECT k, v, ROW_NUMBER() OVER (PARTITION BY k ORDER BY v DESC) AS rn "
            "FROM wdf ORDER BY k, rn"
        )
        assert r.values.tolist() == [
            [1, 30.0, 1], [1, 20.0, 2], [1, 10.0, 3], [2, 15.0, 1], [2, 5.0, 2],
        ]

    def test_rank_dense_rank(self):
        t = pd.DataFrame({"s": [10, 10, 5]})
        r = fugue_sql(
            "SELECT s, RANK() OVER (ORDER BY s DESC) AS r, "
            "DENSE_RANK() OVER (ORDER BY s DESC) AS dr FROM t ORDER BY s DESC"
        )
        assert r.values.tolist() == [[10, 1, 1], [10, 1, 1], [5, 3, 2]]

    def test_lag_lead(self, wdf):
        r = fugue_sql(
            "SELECT k, v, LAG(v, 1, -1.0) OVER (PARTITION BY k ORDER BY v) AS prev "
            "FROM wdf ORDER BY k, v"
        )
        assert r["prev"].tolist() == [-1.0, 10.0, 20.0, -1.0, 5.0]

    def test_windowed_aggregate(self, wdf):
        r = fugue_sql(
            "SELECT k, v, SUM(v) OVER (PARTITION BY k) AS total FROM wdf ORDER BY k, v"
        )
        assert r["total"].tolist() == [60.0] * 3 + [20.0] * 2

    def test_where_applies_before_window(self, wdf):
        r = fugue_sql(
            "SELECT k, ROW_NUMBER() OVER (PARTITION BY k ORDER BY v) AS rn "
            "FROM wdf WHERE v > 10 ORDER BY k, rn"
        )
        assert r.values.tolist() == [[1, 1], [1, 2], [2, 1]]

    def test_nested_window_rejected(self, wdf):
        with pytest.raises(NotImplementedError):
            fugue_sql("SELECT SUM(v) OVER (PARTITION BY k) + 1 AS x FROM wdf")

    def test_window_with_groupby_rejected(self, wdf):
        with pytest.raises(NotImplementedError):
            fugue_sql(
                "SELECT k, ROW_NUMBER() OVER (ORDER BY k) AS rn FROM wdf GROUP BY k"
            )

    def test_running_aggregate(self):
        t = pd.DataFrame({"k": [1, 1, 1], "v": [1.0, 2.0, 3.0]})
        r = fugue_sql(
            "SELECT v, SUM(v) OVER (PARTITION BY k ORDER BY v) AS s FROM t ORDER BY v"
        )
        assert r["s"].tolist() == [1.0, 3.0, 6.0]

    def test_lag_default_only_outside_partition(self):
        t = pd.DataFrame({"id": [1, 2, 3], "v": [10.0, None, 20.0]})
        r = fugue_sql(
            "SELECT id, LAG(v, 1, -1.0) OVER (ORDER BY id) AS p FROM t ORDER BY id"
        )
        got = [None if pd.isna(x) else x for x in r["p"]]
        assert got == [-1.0, 10.0, None]

    def test_rank_null_order_key(self):
        t = pd.DataFrame({"s": [10.0, None, 5.0]})
        r = fugue_sql("SELECT s, RANK() OVER (ORDER BY s) AS r FROM t ORDER BY r")
        assert r["r"].tolist() == [1, 2, 3]

    def test_distinct_in_window_rejected(self):
        t = pd.DataFrame({"k": [1], "v": [1.0]})
        with pytest.raises(FugueSQLSyntaxError):
            fugue_sql("SELECT SUM(DISTINCT v) OVER (PARTITION BY k) AS s FROM t")

    def test_running_agg_skips_nulls(self):
        t = pd.DataFrame({"id": [1, 2, 3], "v": [1.0, None, 2.0]})
        r = fugue_sql(
            "SELECT id, SUM(v) OVER (ORDER BY id) AS s FROM t ORDER BY id"
        )
        assert r["s"].tolist() == [1.0, 1.0, 3.0]

    def test_multi_column_rank(self):
        t = pd.DataFrame({"a": [1, 1, 2], "b": [5, 5, 1]})
        r = fugue_sql(
            "SELECT RANK() OVER (ORDER BY a, b) AS r, "
            "DENSE_RANK() OVER (ORDER BY a, b) AS dr FROM t ORDER BY r"
        )
        assert r["r"].tolist() == [1, 1, 3]
        assert r["dr"].tolist() == [1, 1, 2]

    def test_first_value_includes_null(self):
        t = pd.DataFrame({"k": [1, 1], "id": [1, 2], "v": [None, 5.0]})
        r = fugue_sql(
            "SELECT FIRST(v) OVER (PARTITION BY k ORDER BY id) AS f FROM t"
        )
        assert all(pd.isna(x) for x in r["f"])

    def test_order_by_unprojected_column(self):
        t = pd.DataFrame({"id": [3, 1, 2], "v": [30.0, 10.0, 20.0]})
        r = fugue_sql("SELECT v FROM t ORDER BY id")
        assert r["v"].tolist() == [10.0, 20.0, 30.0]
        assert list(r.columns) == ["v"]

    def test_rank_interleaved_partitions(self):
        t = pd.DataFrame({"k": ["A", "B", "A", "B"], "v": [1, 1, 2, 2]})
        r = fugue_sql(
            "SELECT k, v, RANK() OVER (PARTITION BY k ORDER BY v) AS r FROM t "
            "ORDER BY k, v"
        )
        assert r["r"].tolist() == [1, 2, 1, 2]

    def test_running_min_datetime_null(self):
        t = pd.DataFrame(
            {"id": [1, 2, 3],
             "d": pd.to_datetime(["2020-01-02", None, "2020-01-01"])}
        )
        r = fugue_sql("SELECT id, MIN(d) OVER (ORDER BY id) AS m FROM t ORDER BY id")
        assert str(r["m"].iloc[1])[:10] == "2020-01-02"
        assert str(r["m"].iloc[2])[:10] == "2020-01-01"

    def test_empty_input_window(self):
        t = pd.DataFrame({"a": [1.0]})
        r = fugue_sql("SELECT RANK() OVER (ORDER BY a) AS r FROM t WHERE a > 5")
        assert len(r) == 0


class TestScalarFunctions:
    def test_modulo_and_friends(self):
        t = pd.DataFrame({"a": [1, 2, 3, 4], "s": ["ab", "cd", "ef", "gh"]})
        assert fugue_sql("SELECT a FROM t WHERE a % 2 = 0")["a"].tolist() == [2, 4]
        assert fugue_sql("SELECT MOD(a, 3) AS m FROM t")["m"].tolist() == [1, 2, 0, 1]
        assert fugue_sql("SELECT POWER(a, 2) AS p FROM t")["p"].tolist() == [1, 4, 9, 16]
        assert fugue_sql("SELECT REPLACE(s, 'a', 'x') AS r FROM t")["r"].tolist() == [
            "xb", "cd", "ef", "gh",
        ]


class TestTokenizerParity:
    """The Python and C++ tokenizers must produce identical tokens — the same
    SQL must not parse differently depending on whether the native lib built."""

    EDGE_INPUTS = [
        "SELECT 1e5, 2E+3, 3e-2 FROM t",
        "SELECT 1e FROM t",  # digit-less exponent: NUMBER '1' + IDENT 'e'
        "SELECT 2e+ FROM t",  # NUMBER '2' + IDENT 'e' + OP '+'
        "SELECT .5e2, 1.5e, x FROM t",
        "SELECT a1e2 FROM t",  # identifier, not number
        "SELECT 'it''s', `odd col` FROM t WHERE a <> 1 AND b != 2",
        "SELECT * FROM t -- comment\nWHERE a >= 1 /* block */ OR b <= 2",
    ]

    def test_python_digitless_exponent(self):
        from fugue_tpu.sql.parser import _tokenize_py

        toks = _tokenize_py("1e")
        assert [(t.kind, t.value) for t in toks[:2]] == [("NUMBER", "1"), ("IDENT", "e")]
        toks = _tokenize_py("2e+")
        assert [(t.kind, t.value) for t in toks[:3]] == [
            ("NUMBER", "2"),
            ("IDENT", "e"),
            ("OP", "+"),
        ]

    def test_native_matches_python(self):
        from fugue_tpu.native import native_available, tokenize_native
        from fugue_tpu.sql.parser import _tokenize_py

        if not native_available():
            pytest.skip("native tokenizer unavailable")
        for sql in self.EDGE_INPUTS:
            py = _tokenize_py(sql)
            nat = tokenize_native(sql)
            assert nat is not None
            assert [(t.kind, t.value, t.pos) for t in py] == [
                (t.kind, t.value, t.pos) for t in nat
            ], sql


class TestWindowFrames:
    """Explicit ROWS/RANGE frames + the SQL default RANGE-with-peers."""

    def test_default_range_includes_peers(self):
        # duplicate order keys: peers share the running value (SQL default
        # frame is RANGE UNBOUNDED PRECEDING .. CURRENT ROW)
        t = pd.DataFrame({"k": [1, 1, 1], "o": [1, 2, 2], "v": [1.0, 2.0, 4.0]})
        r = fugue_sql(
            "SELECT o, SUM(v) OVER (PARTITION BY k ORDER BY o) AS s FROM t"
        )
        assert r["s"].tolist() == [1.0, 7.0, 7.0]  # peers at o=2 both see 7

    def test_rows_frame_excludes_peers(self):
        t = pd.DataFrame({"k": [1, 1, 1], "o": [1, 2, 2], "v": [1.0, 2.0, 4.0]})
        r = fugue_sql(
            "SELECT o, SUM(v) OVER (PARTITION BY k ORDER BY o "
            "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS s FROM t"
        )
        assert r["s"].tolist() == [1.0, 3.0, 7.0]

    def test_rows_sliding_window(self):
        t = pd.DataFrame({"o": [1, 2, 3, 4, 5], "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
        r = fugue_sql(
            "SELECT o, SUM(v) OVER (ORDER BY o "
            "ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS s FROM t ORDER BY o"
        )
        exp = t["v"].rolling(3, min_periods=1, center=True).sum()
        assert r["s"].tolist() == exp.tolist()
        r2 = fugue_sql(
            "SELECT o, AVG(v) OVER (ORDER BY o ROWS 2 PRECEDING) AS m "
            "FROM t ORDER BY o"
        )
        exp2 = t["v"].rolling(3, min_periods=1).mean()
        assert r2["m"].tolist() == exp2.tolist()

    def test_range_value_window(self):
        # RANGE offsets are VALUE distances over the order key, not rows
        t = pd.DataFrame({"o": [1, 2, 4, 7, 8], "v": [1.0, 1.0, 1.0, 1.0, 1.0]})
        r = fugue_sql(
            "SELECT o, COUNT(v) OVER (ORDER BY o "
            "RANGE BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS n FROM t ORDER BY o"
        )
        # windows: o=1→{1,2}, o=2→{1,2}, o=4→{4}, o=7→{7,8}, o=8→{7,8}
        assert r["n"].tolist() == [2, 2, 1, 2, 2]

    def test_frames_with_nulls_and_min_max(self):
        t = pd.DataFrame(
            {"o": [1, 2, 3, 4], "v": [3.0, None, 1.0, 2.0]}
        )
        r = fugue_sql(
            "SELECT o, MIN(v) OVER (ORDER BY o ROWS 1 PRECEDING) AS lo, "
            "MAX(v) OVER (ORDER BY o ROWS 1 PRECEDING) AS hi FROM t ORDER BY o"
        )
        assert r["lo"].tolist() == [3.0, 3.0, 1.0, 1.0]
        assert r["hi"].tolist() == [3.0, 3.0, 1.0, 2.0]


class TestConnectStatement:
    """FugueSQL CONNECT: one statement runs on a different engine."""

    def test_connect_engine_switch(self):
        t = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
        r = fugue_sql(
            """
            a = CONNECT jax SELECT k, SUM(v) AS s FROM t GROUP BY k
            SELECT k, s + 1 AS s1 FROM a ORDER BY k
            """
        )
        assert r["s1"].tolist() == [4.0, 4.0]

    def test_connect_registered_sql_engine(self):
        t = pd.DataFrame({"a": [3, 1, 2]})
        r = fugue_sql("CONNECT local SELECT a FROM t ORDER BY a")
        assert r["a"].tolist() == [1, 2, 3]

    def test_connect_unknown_engine_raises(self):
        t = pd.DataFrame({"a": [1]})
        with pytest.raises(Exception):
            fugue_sql("CONNECT no_such_engine SELECT a FROM t")

    def test_connect_requires_select(self):
        t = pd.DataFrame({"a": [1]})
        with pytest.raises(FugueSQLSyntaxError):
            fugue_sql("CONNECT jax PRINT FROM t")


class TestGroupByDecoupled:
    """GROUP BY no longer has to match the projection."""

    def test_groupby_key_not_projected(self):
        t = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
        r = fugue_sql("SELECT SUM(v) AS s FROM t GROUP BY k ORDER BY s")
        assert r["s"].tolist() == [3.0, 3.0]

    def test_groupby_transformed_key(self):
        t = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
        r = fugue_sql(
            "SELECT k + 100 AS kk, SUM(v) AS s FROM t GROUP BY k ORDER BY kk"
        )
        assert r["kk"].tolist() == [101, 102]
        assert r["s"].tolist() == [3.0, 3.0]

    def test_groupby_superset_of_projection(self):
        t = pd.DataFrame(
            {"k": [1, 1, 2], "k2": [1, 2, 3], "v": [1.0, 2.0, 3.0]}
        )
        r = fugue_sql("SELECT k, SUM(v) AS s FROM t GROUP BY k, k2 ORDER BY s")
        assert r["k"].tolist() == [1, 1, 2]
        assert r["s"].tolist() == [1.0, 2.0, 3.0]

    def test_groupby_no_aggs_pure_grouping(self):
        t = pd.DataFrame({"k": [1, 1, 2], "k2": [5, 5, 6]})
        r = fugue_sql("SELECT k FROM t GROUP BY k, k2 ORDER BY k")
        assert r["k"].tolist() == [1, 2]

    def test_expression_over_aggregates(self):
        t = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
        r = fugue_sql(
            "SELECT SUM(v) / COUNT(v) AS m FROM t GROUP BY k ORDER BY m"
        )
        assert r["m"].tolist() == [1.5, 3.0]

    def test_having_with_decoupled_groupby(self):
        t = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
        r = fugue_sql(
            "SELECT SUM(v) AS s FROM t GROUP BY k HAVING COUNT(v) > 1"
        )
        assert r["s"].tolist() == [3.0]

    def test_ungrouped_column_raises(self):
        t = pd.DataFrame({"k": [1], "v": [1.0]})
        with pytest.raises(Exception, match="GROUP BY"):
            fugue_sql("SELECT v, SUM(v) AS s FROM t GROUP BY k")


class TestNonEquiJoins:
    def test_theta_join_inner(self):
        lo = pd.DataFrame({"a": [1, 5, 9]})
        hi = pd.DataFrame({"b": [4, 6]})
        r = fugue_sql(
            "SELECT a, b FROM lo JOIN hi ON lo.a < hi.b ORDER BY a, b"
        )
        assert r.values.tolist() == [[1, 4], [1, 6], [5, 6]]

    def test_equi_plus_residual(self):
        t1 = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 5.0, 2.0]})
        t2 = pd.DataFrame({"k": [1, 2], "w": [3.0, 1.0]})
        r = fugue_sql(
            "SELECT k, v, w FROM t1 INNER JOIN t2 ON t1.k = t2.k AND v > w "
            "ORDER BY k, v"
        )
        assert r.values.tolist() == [[1, 5.0, 3.0], [2, 2.0, 1.0]]

    def test_non_equi_outer_raises(self):
        t1 = pd.DataFrame({"k": [1], "v": [1.0]})
        t2 = pd.DataFrame({"k": [1], "w": [2.0]})
        with pytest.raises(Exception):
            fugue_sql(
                "SELECT * FROM t1 LEFT JOIN t2 ON t1.k = t2.k AND v > w"
            )


class TestWindowFrameEdges:
    def test_range_current_row_bounds_use_all_order_keys(self):
        # peers = equal on ALL order keys, not just the first
        t = pd.DataFrame(
            {"a": [1, 1, 1], "b": [1, 2, 2], "v": [1.0, 2.0, 4.0]}
        )
        r = fugue_sql(
            "SELECT b, SUM(v) OVER (ORDER BY a, b "
            "RANGE BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) AS s "
            "FROM t ORDER BY b, s"
        )
        # row (a=1,b=1): frame starts at its peer group → 7.0
        # rows (a=1,b=2): their peer group starts after b=1 → 6.0
        assert r["s"].tolist() == [7.0, 6.0, 6.0]

    def test_range_current_row_with_string_order_key(self):
        t = pd.DataFrame({"s": ["x", "x", "y"], "v": [1.0, 2.0, 3.0]})
        r = fugue_sql(
            "SELECT s, SUM(v) OVER (ORDER BY s "
            "RANGE BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) AS c "
            "FROM t ORDER BY s, c"
        )
        assert r["c"].tolist() == [6.0, 6.0, 3.0]

    def test_invalid_frame_bound_order_raises(self):
        t = pd.DataFrame({"a": [1.0]})
        with pytest.raises(FugueSQLSyntaxError):
            fugue_sql(
                "SELECT SUM(a) OVER (ORDER BY a "
                "ROWS BETWEEN UNBOUNDED FOLLOWING AND CURRENT ROW) AS s FROM t"
            )
        with pytest.raises(FugueSQLSyntaxError):
            fugue_sql(
                "SELECT SUM(a) OVER (ORDER BY a "
                "ROWS BETWEEN CURRENT ROW AND UNBOUNDED PRECEDING) AS s FROM t"
            )

    def test_having_with_in_over_aggregate(self):
        t = pd.DataFrame({"k": [1, 1, 2], "v": [1, 2, 3]})
        r = fugue_sql(
            "SELECT k, COUNT(v) AS n FROM t GROUP BY k HAVING COUNT(v) IN (2)"
        )
        assert r.values.tolist() == [[1, 2]]


class TestSubqueries:
    def test_scalar_subquery_in_where(self):
        t = pd.DataFrame({"k": [1, 2, 3, 4], "v": [10.0, 20.0, 30.0, 40.0]})
        r = fugue_sql(
            "SELECT k FROM t WHERE v > (SELECT AVG(v) FROM t) ORDER BY k"
        )
        assert r["k"].tolist() == [3, 4]

    def test_scalar_subquery_in_projection(self):
        t = pd.DataFrame({"v": [1.0, 2.0, 3.0]})
        r = fugue_sql("SELECT v, (SELECT MAX(v) FROM t) AS mx FROM t")
        assert r["mx"].tolist() == [3.0, 3.0, 3.0]

    def test_scalar_subquery_no_from(self):
        t = pd.DataFrame({"v": [5.0, 7.0]})
        r = fugue_sql("SELECT (SELECT SUM(v) FROM t) AS s", as_fugue=True)
        assert r.as_array() == [[12.0]]

    def test_in_subquery(self):
        t = pd.DataFrame({"k": [1, 2, 3, 4]})
        good = pd.DataFrame({"k": [2, 4, 9]})
        r = fugue_sql(
            "SELECT k FROM t WHERE k IN (SELECT k FROM good) ORDER BY k"
        )
        assert r["k"].tolist() == [2, 4]
        r2 = fugue_sql(
            "SELECT k FROM t WHERE k NOT IN (SELECT k FROM good) ORDER BY k"
        )
        assert r2["k"].tolist() == [1, 3]

    def test_scalar_subquery_multirow_raises(self):
        t = pd.DataFrame({"v": [1.0, 2.0]})
        with pytest.raises(Exception, match="one row|one column"):
            fugue_sql("SELECT (SELECT v FROM t) AS s")


def test_group_by_expression():
    """GROUP BY over computed expressions (reference gets this from
    backend SQL; here the key materializes as a helper column)."""
    import fugue_tpu.api as fa

    df = pd.DataFrame(
        {"s": ["apple", "avocado", "banana", "blueberry"], "v": [1.0, 2.0, 3.0, 4.0]}
    )
    r = fa.fugue_sql(
        "SELECT SUBSTRING(s,1,1) AS c, SUM(v) AS t FROM df "
        "GROUP BY SUBSTRING(s,1,1)",
        df=df,
        engine="native",
        as_fugue=True,
    ).as_pandas().sort_values("c").reset_index(drop=True)
    assert r["c"].tolist() == ["a", "b"] and r["t"].tolist() == [3.0, 7.0]
    # mixed named + computed keys, WHERE before grouping, HAVING after
    df2 = pd.DataFrame({"k": [1, 1, 2, 2, 2], "x": [1.0, 2.0, 3.0, 4.0, 10.0]})
    r2 = fa.fugue_sql(
        "SELECT k, x > 2.5 AS hi, COUNT(*) AS n FROM df2 WHERE x < 9 "
        "GROUP BY k, x > 2.5 HAVING COUNT(*) > 1",
        df2=df2,
        engine="native",
        as_fugue=True,
    ).as_pandas().sort_values("k").reset_index(drop=True)
    assert r2["n"].tolist() == [2, 2]
    assert r2["hi"].tolist() == [False, True]
    # HAVING referencing the grouped expression rewrites to the output col
    r3 = fa.fugue_sql(
        "SELECT SUBSTRING(s,1,1) AS c, SUM(v) AS t FROM df "
        "GROUP BY SUBSTRING(s,1,1) HAVING SUBSTRING(s,1,1) <> 'a'",
        df=df,
        engine="native",
        as_fugue=True,
    ).as_pandas()
    assert r3["c"].tolist() == ["b"] and r3["t"].tolist() == [7.0]
    # an unaliased grouped projection gets a readable derived name
    r4 = fa.fugue_sql(
        "SELECT SUBSTRING(s,1,1), SUM(v) AS t FROM df GROUP BY SUBSTRING(s,1,1)",
        df=df,
        engine="native",
        as_fugue=True,
    )
    assert r4.schema.names == ["SUBSTRING(s,1,1)", "t"]
    # SELECT * with a computed key never leaks the helper columns
    r5 = fa.fugue_sql(
        "SELECT * FROM df2 GROUP BY k, x, x > 2.5",
        df2=df2,
        engine="native",
        as_fugue=True,
    )
    assert r5.schema.names == ["k", "x"]


def test_order_by_expression():
    """ORDER BY over computed expressions — projected-column inputs,
    dropped-source-column inputs, and mixed plain+expression sorts."""
    import fugue_tpu.api as fa

    df = pd.DataFrame({"s": ["bb", "za", "ccc"], "v": [1.0, 2.0, 3.0]})
    r = fa.fugue_sql(
        "SELECT s FROM df ORDER BY SUBSTRING(s,2,1) DESC",
        df=df, engine="native", as_fugue=True,
    ).as_pandas()
    assert r["s"].tolist() == ["ccc", "bb", "za"]
    assert r.columns.tolist() == ["s"]  # helper columns never leak
    r2 = fa.fugue_sql(
        "SELECT s FROM df ORDER BY v * -1",
        df=df, engine="native", as_fugue=True,
    ).as_pandas()
    assert r2["s"].tolist() == ["ccc", "za", "bb"]
    r3 = fa.fugue_sql(
        "SELECT s, v FROM df ORDER BY SUBSTRING(s,1,1), v DESC",
        df=df, engine="native", as_fugue=True,
    ).as_pandas()
    assert r3["s"].tolist() == ["bb", "ccc", "za"]
    # an aggregated select can still order by an expression over outputs
    r4 = fa.fugue_sql(
        "SELECT s, SUM(v) AS t FROM df GROUP BY s ORDER BY t * -1",
        df=df, engine="native", as_fugue=True,
    ).as_pandas()
    assert r4["t"].tolist() == [3.0, 2.0, 1.0]


def test_order_by_ordinal_and_cast():
    import fugue_tpu.api as fa
    import pytest as _pytest

    df = pd.DataFrame(
        {"s": ["bb", "za", "ccc"], "v": [1.0, 2.0, 3.0], "x": ["10", "2", "1"]}
    )
    # SQL positional ordering
    r = fa.fugue_sql(
        "SELECT s, v FROM df ORDER BY 2 DESC", df=df, engine="native", as_fugue=True
    ).as_pandas()
    assert r["s"].tolist() == ["ccc", "za", "bb"]
    # CAST sort keys don't collide with the plain column
    r2 = fa.fugue_sql(
        "SELECT x FROM df ORDER BY CAST(x AS int)",
        df=df, engine="native", as_fugue=True,
    ).as_pandas()
    assert r2["x"].tolist() == ["1", "2", "10"]
    # constants and out-of-range positions raise typed errors
    with _pytest.raises(Exception, match="constant"):
        fa.fugue_sql("SELECT s FROM df ORDER BY 'q'", df=df, engine="native")
    with _pytest.raises(Exception, match="out of range"):
        fa.fugue_sql("SELECT s FROM df ORDER BY 5", df=df, engine="native")
    # aggregated selects give the crafted error for dropped-column exprs
    with _pytest.raises(Exception, match="order by projected"):
        fa.fugue_sql(
            "SELECT s, SUM(v) AS t FROM df GROUP BY s ORDER BY v * 2",
            df=df, engine="native",
        )
    # aliases survive substitution on rebuilt compound projections
    df2 = pd.DataFrame({"k": [1, 1, 2], "x": [1.0, 3.0, 4.0]})
    r3 = fa.fugue_sql(
        "SELECT k + 1 AS k1, x > 2.5 AS hi, COUNT(*) AS n FROM df2 "
        "GROUP BY k + 1, x > 2.5",
        df2=df2, engine="native", as_fugue=True,
    ).as_pandas()
    assert set(r3.columns) == {"k1", "hi", "n"}


def test_order_by_edge_cases_round2():
    """Review-found edges: hidden sort helpers don't satisfy ordinals;
    CAST of a grouped expression matches and keeps its cast; alias+dropped
    -source mixes raise typed errors."""
    import fugue_tpu.api as fa
    import pytest as _pytest

    df = pd.DataFrame(
        {"s": ["bb", "za", "ccc"], "v": [1.0, 2.0, 3.0], "x": ["10", "2", "1"]}
    )
    with _pytest.raises(Exception, match="out of range"):
        fa.fugue_sql("SELECT s FROM df ORDER BY v, 2", df=df, engine="native")
    df2 = pd.DataFrame({"k": [1, 1, 2], "x": [1.0, 3.0, 4.0]})
    r = fa.fugue_sql(
        "SELECT CAST(k+1 AS int) AS k1, COUNT(*) AS n FROM df2 GROUP BY k+1",
        df2=df2, engine="native", as_fugue=True,
    ).as_pandas()
    assert sorted(r["k1"].tolist()) == [2, 3]
    assert str(r.dtypes["k1"]) in ("int32", "Int32")
    with _pytest.raises(Exception, match="mixes projection aliases"):
        fa.fugue_sql(
            "SELECT v AS w, s FROM df ORDER BY w * x", df=df, engine="native"
        )
