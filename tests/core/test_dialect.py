"""SQL dialect transpiler (`fugue_tpu/sql/dialect.py`) — the sqlglot role.

Golden tests pin the emitted SQL text per dialect pair; the plugin test
proves `StructuredRawSQL.construct(dialect=...)` routes through it
(reference behavior: `/root/reference/fugue/collections/sql.py:25-45`).
"""

import pytest

from fugue_tpu.collections.sql import StructuredRawSQL, transpile_sql
from fugue_tpu.exceptions import FugueSQLSyntaxError
from fugue_tpu.sql import DialectProfile, register_dialect, transpile


def test_quoting_conversions():
    # spark/fugue: backtick idents, double-quoted strings
    assert (
        transpile('SELECT `a b` FROM t WHERE x = "hi"', "fugue", "sqlite")
        == "SELECT \"a b\" FROM t WHERE x = 'hi'"
    )
    # postgres double-quoted identifiers -> fugue backticks
    assert (
        transpile('SELECT "a b" FROM t', "postgres", "fugue")
        == "SELECT `a b` FROM t"
    )
    # mssql brackets
    assert (
        transpile("SELECT [a b] FROM t", "mssql", "postgres")
        == 'SELECT "a b" FROM t'
    )
    # embedded quotes escape by doubling in the target convention
    assert (
        transpile('SELECT `we``ird` FROM t', "fugue", "postgres")
        == 'SELECT "we`ird" FROM t'
    )
    assert (
        transpile("SELECT a FROM t WHERE s = 'it''s'", "fugue", "postgres")
        == "SELECT a FROM t WHERE s = 'it''s'"
    )


def test_cast_type_mapping():
    assert (
        transpile("SELECT CAST(x AS double) FROM t", "fugue", "postgres")
        == "SELECT CAST(x AS DOUBLE PRECISION) FROM t"
    )
    assert (
        transpile("SELECT CAST(x AS double) FROM t", "fugue", "sqlite")
        == "SELECT CAST(x AS REAL) FROM t"
    )
    assert (
        transpile(
            "SELECT CAST(x AS DOUBLE PRECISION) FROM t", "postgres", "fugue"
        )
        == "SELECT CAST(x AS double) FROM t"
    )
    assert (
        transpile("SELECT CAST(b AS bool) FROM t", "fugue", "mssql")
        == "SELECT CAST(b AS BIT) FROM t"
    )
    # nested cast inside a function call
    assert (
        transpile("SELECT SUM(CAST(x AS long)) AS s FROM t", "fugue", "postgres")
        == "SELECT SUM(CAST(x AS BIGINT)) AS s FROM t"
    )


def test_function_renames_round_trip():
    assert (
        transpile("SELECT SUBSTRING(s, 1, 2) FROM t", "fugue", "sqlite")
        == "SELECT SUBSTR(s, 1, 2) FROM t"
    )
    assert (
        transpile("SELECT SUBSTR(s, 1, 2) FROM t", "sqlite", "fugue")
        == "SELECT SUBSTRING(s, 1, 2) FROM t"
    )
    assert (
        transpile("SELECT STRING_AGG(s) FROM t", "fugue", "mysql")
        == "SELECT GROUP_CONCAT(s) FROM t"
    )
    # a column NAMED like a function is not renamed (no call parens)
    assert (
        transpile("SELECT SUBSTRING FROM t", "fugue", "sqlite")
        == "SELECT SUBSTRING FROM t"
    )


def test_limit_top_conversion():
    assert (
        transpile("SELECT a FROM t LIMIT 10", "fugue", "mssql")
        == "SELECT TOP 10 a FROM t"
    )
    assert (
        transpile("SELECT TOP 3 a FROM t", "mssql", "fugue")
        == "SELECT a FROM t LIMIT 3"
    )
    # LIMIT inside a subquery is not top-level: left in place
    out = transpile(
        "SELECT * FROM (SELECT a FROM t LIMIT 5) q", "fugue", "postgres"
    )
    assert "LIMIT 5" in out


def test_bool_literals():
    assert (
        transpile("SELECT * FROM t WHERE ok = TRUE AND bad = FALSE", "fugue", "sqlite")
        == "SELECT * FROM t WHERE ok = 1 AND bad = 0"
    )
    # postgres keeps the keywords
    assert (
        transpile("SELECT * FROM t WHERE ok = TRUE", "fugue", "postgres")
        == "SELECT * FROM t WHERE ok = TRUE"
    )


def test_same_dialect_is_identity():
    sql = "SeLeCt   weird    , spacing FROM t"
    assert transpile(sql, "fugue", "fugue") == sql


def test_unknown_dialect_raises():
    with pytest.raises(FugueSQLSyntaxError):
        transpile("SELECT 1", "fugue", "nope")


def test_custom_dialect_registration():
    register_dialect(
        DialectProfile(
            name="testql",
            ident_quote=("<", ">"),
            func_map={"SUBSTRING": "SLICE"},
        )
    )
    assert (
        transpile("SELECT `a b`, SUBSTRING(s, 1) FROM t", "fugue", "testql")
        == "SELECT <a b>, SLICE(s, 1) FROM t"
    )


def test_structured_raw_sql_routes_through_plugin():
    s = StructuredRawSQL.from_expr(
        'SELECT `a b`, CAST(x AS double) AS y FROM <tmpdf:t0> LIMIT 2',
        dialect="fugue",
    )
    out = s.construct(name_map={"t0": "real_table"}, dialect="sqlite")
    assert out == (
        'SELECT "a b", CAST(x AS REAL) AS y FROM real_table LIMIT 2'
    )
    # plugin callable directly
    assert (
        transpile_sql("SELECT CAST(x AS str) FROM t", "fugue", "postgres")
        == "SELECT CAST(x AS TEXT) FROM t"
    )
    # same dialect: untouched
    assert s.construct(name_map={"t0": "z"}, dialect="fugue").startswith("SELECT `a b`")


def test_fugue_sql_foreign_compile_dialect():
    """FugueSQL written in a foreign dialect executes via the conf
    ``fugue.sql.compile.dialect`` (reference: sqlglot behind
    ``fugue/constants.py:9``): SELECT text transpiles to the in-tree
    dialect before table discovery and execution."""
    import pandas as pd

    import fugue_tpu.api as fa
    from fugue_tpu.constants import register_global_conf
    from fugue_tpu.sql import FugueSQLWorkflow

    df = pd.DataFrame(
        {"k": [1, 2, 2], "v": [1.0, 2.0, 3.0], "ok": [True, True, False]}
    )
    register_global_conf({"fugue.sql.compile.dialect": "postgres"})
    try:
        r = fa.fugue_sql(
            "SELECT k, SUM(CAST(v AS DOUBLE PRECISION)) AS s FROM df "
            "WHERE ok = TRUE GROUP BY k",
            df=df,
            engine="native",
        )
        got = r.sort_values("k").reset_index(drop=True)
        assert got["s"].tolist() == [1.0, 2.0]
    finally:
        register_global_conf({"fugue.sql.compile.dialect": "spark"})
    # per-workflow compile conf: mssql TOP syntax
    dag = FugueSQLWorkflow(compile_conf={"fugue.sql.compile.dialect": "mssql"})
    dag("SELECT TOP 2 k, v FROM df ORDER BY v YIELD DATAFRAME AS r2", df=df)
    dag.run("native")
    out = dag.yields["r2"].result.as_pandas()
    assert out["v"].tolist() == [1.0, 2.0]


def test_round_trip_preserves_token_stream():
    """Property: fugue → D → fugue returns a token-identical query (modulo
    whitespace) for every registered dialect D — quoting, strings with
    embedded quotes, function renames, bools, operators and LIMIT; CAST
    types restricted per dialect to its collapse-free subset (sqlite has
    one int type and no bool, so those castings are inherently lossy —
    same with sqlglot)."""
    from fugue_tpu.sql.dialect import DIALECTS, _tokenize, get_dialect

    queries = [
        "SELECT a, `b c` FROM t WHERE s = 'it''s' LIMIT 7",
        "SELECT SUBSTRING(s, 1, 2), COALESCE(a, 0), COUNT(*) FROM `my tbl` GROUP BY k",
        "SELECT * FROM t WHERE ok = TRUE AND x <> 1.5e3 OR s = \"quoted\"",
        "SELECT t.a, u.`b b` FROM t INNER JOIN u ON t.k = u.k ORDER BY t.a",
        "SELECT k << 2, a & 7, b || 'x' FROM t",
    ]
    # CAST types that survive the round trip per dialect (a dialect with
    # one storage class for several logical types can't round-trip them)
    safe_casts = {
        "sqlite": ["long", "double", "str", "bytes"],
        "postgres": ["int", "long", "float", "double", "str", "bool", "datetime", "date", "bytes"],
        "mysql": ["long", "double", "str", "bool", "datetime", "bytes"],
        "mssql": ["long", "float", "double", "str", "bool", "datetime"],
        "spark": ["int", "long", "float", "double", "str", "bool", "datetime", "bytes"],
    }
    fugue = get_dialect("fugue")

    def toks(sql):
        return [(t.kind, t.value.upper()) for t in _tokenize(sql, fugue)]

    builtin = ["spark", "sqlite", "postgres", "mysql", "mssql"]
    assert all(n in DIALECTS for n in builtin)
    for name in builtin:
        qs = list(queries)
        if DIALECTS[name].bool_literals is not None:
            # TRUE -> 1 is a one-way lowering (1 cannot read back as TRUE)
            qs = [q for q in qs if "TRUE" not in q]
        for tp in safe_casts.get(name, []):
            qs.append(f"SELECT CAST(x AS {tp}) AS y FROM t")
        for q in qs:
            there = transpile(q, "fugue", name)
            back = transpile(there, name, "fugue")
            assert toks(back) == toks(q), (name, q, there, back)
