"""Entry-point plugin discovery (reference setup.py:104-111 /
fugue/_utils/registry.py:9-10): an installed-but-never-imported
distribution exposing the ``fugue_tpu.plugins`` entry-point group gets
loaded on first registry use, so its engine resolves by name in
``make_execution_engine`` with no explicit import anywhere.
"""

import sys
import textwrap

import pytest

from fugue_tpu._utils import registry
from fugue_tpu.execution.factory import make_execution_engine
from fugue_tpu.exceptions import FuguePluginsRegistrationError

_MODULE = textwrap.dedent(
    '''
    """Synthetic third-party backend package (test fixture)."""
    from fugue_tpu.execution.factory import register_execution_engine
    from fugue_tpu.execution.native_execution_engine import NativeExecutionEngine


    class ExtEngine(NativeExecutionEngine):
        marker = "loaded-via-entry-point"


    register_execution_engine("extengine", lambda conf, **k: ExtEngine(conf))
    '''
)


@pytest.fixture()
def synthetic_dist(tmp_path):
    site = tmp_path / "site"
    dist = site / "my_fugue_ext-0.1.dist-info"
    dist.mkdir(parents=True)
    (site / "my_fugue_ext.py").write_text(_MODULE)
    (dist / "METADATA").write_text(
        "Metadata-Version: 2.1\nName: my-fugue-ext\nVersion: 0.1\n"
    )
    (dist / "entry_points.txt").write_text(
        "[fugue_tpu.plugins]\nextengine = my_fugue_ext\n"
    )
    sys.path.insert(0, str(site))
    prior = registry._EP_STATE["loaded"]
    registry._EP_STATE["loaded"] = False
    try:
        yield site
    finally:
        sys.path.remove(str(site))
        registry._EP_STATE["loaded"] = prior
        sys.modules.pop("my_fugue_ext", None)
        from fugue_tpu.execution import factory

        factory._EXECUTION_ENGINE_REGISTRY.pop("extengine", None)


def test_engine_resolves_without_import(synthetic_dist):
    assert "my_fugue_ext" not in sys.modules
    e = make_execution_engine("extengine")
    assert getattr(e, "marker", "") == "loaded-via-entry-point"
    assert "my_fugue_ext" in sys.modules  # loaded by discovery, not by us
    e.stop_engine()


def test_load_is_idempotent(synthetic_dist):
    loaded = registry.load_entry_point_plugins()
    assert "extengine" in loaded
    again = registry.load_entry_point_plugins()
    assert again == []  # second call is a no-op


def test_unknown_engine_still_raises(synthetic_dist):
    with pytest.raises(FuguePluginsRegistrationError):
        make_execution_engine("definitely-not-registered")
