"""Auxiliary subsystems: module, http rpc, traceback surgery, test harness."""

import pandas as pd
import pytest

from fugue_tpu import FugueWorkflow
from fugue_tpu.workflow import module
from fugue_tpu.workflow.workflow import WorkflowDataFrame


class TestModule:
    def test_module_compose(self):
        @module
        def create(wf: FugueWorkflow, n: int = 1) -> WorkflowDataFrame:
            return wf.df([[n]], "a:long")

        @module
        def doubled(df: WorkflowDataFrame) -> WorkflowDataFrame:
            def d(pdf: pd.DataFrame) -> pd.DataFrame:
                pdf["a"] = pdf["a"] * 2
                return pdf

            return df.transform(d, schema="*")

        dag = FugueWorkflow()
        x = create(dag, n=5)
        doubled(x).yield_dataframe_as("r", as_local=True)
        dag.run()
        assert dag.yields["r"].result.as_array() == [[10]]

    def test_module_bad_first_arg(self):
        @module
        def bad(df: int) -> None:
            pass

        with pytest.raises(Exception):
            bad(1)


class TestHttpRPC:
    def test_roundtrip(self):
        from fugue_tpu.rpc.http import HttpRPCServer

        server = HttpRPCServer({"fugue.rpc.http_server.port": 0})
        server.start()
        try:
            hits = []
            client = server.make_client(lambda x: hits.append(x) or x * 2)
            import pickle

            client2 = pickle.loads(pickle.dumps(client))  # survives pickling
            assert client2(21) == 42
            assert hits == [21]
        finally:
            server.stop()

    def test_error_propagates(self):
        from fugue_tpu.rpc.http import HttpRPCServer

        server = HttpRPCServer({})
        server.start()
        try:
            def boom(x):
                raise ValueError("nope")

            client = server.make_client(boom)
            with pytest.raises(ValueError):
                client(1)
        finally:
            server.stop()


class TestTracebackSurgery:
    def test_user_frames_survive(self):
        def user_fn(df: pd.DataFrame) -> pd.DataFrame:
            raise RuntimeError("user error")

        dag = FugueWorkflow()
        dag.df([[1]], "a:long").transform(user_fn, schema="*").show()
        with pytest.raises(RuntimeError) as info:
            dag.run()
        # the user's own frame must still be in the pruned traceback
        frames = []
        tb = info.value.__traceback__
        while tb is not None:
            frames.append(tb.tb_frame.f_globals.get("__name__", ""))
            tb = tb.tb_next
        assert any(f == __name__ for f in frames)
        # only the single re-raise boundary frame may remain (python appends
        # the raising frame after pruning); the internal bulk must be gone
        assert sum(1 for f in frames if f.startswith("fugue_tpu.")) <= 1, frames


class TestHarnessPlugins:
    def test_suite_binding(self):
        from fugue_tpu.test import fugue_test_suite

        @fugue_test_suite("native")
        class MySuite:
            pass

        engine = MySuite().make_engine()
        assert engine.get_current_parallelism() == 1
        engine.stop()

    def test_with_backend(self):
        from fugue_tpu.test import with_backend

        seen = []

        @with_backend("native", "pandas")
        def check(backend_engine):
            seen.append(type(backend_engine).__name__)

        # run as pytest would: call for each param
        from fugue_tpu.test.plugins import get_test_backend

        for b in ("native", "pandas"):
            with get_test_backend(b).engine_context() as e:
                seen.append(type(e).__name__)
        assert len(seen) == 2


class TestWorkflowDeterminism:
    """uuid stability — the foundation of deterministic checkpoints
    (reference ``tests/fugue/workflow/test_workflow_determinism.py``)."""

    def test_same_dag_same_uuid(self):
        import pandas as pd

        def make() -> pd.DataFrame:
            return pd.DataFrame({"a": [1]})

        def build():
            dag = FugueWorkflow()
            x = dag.create(make)
            return dag, x.drop(["a"], if_exists=True)

        d1, a1 = build()
        d2, a2 = build()
        assert a1.spec_uuid() == a2.spec_uuid()
        assert d1.spec_uuid() == d2.spec_uuid()

    def test_param_changes_uuid(self):
        import pandas as pd

        def make(n: int = 1) -> pd.DataFrame:
            return pd.DataFrame({"a": [n]})

        dag = FugueWorkflow()
        a = dag.create(make, params={"n": 1})
        b = dag.create(make, params={"n": 2})
        c = dag.create(make, params={"n": 1})
        assert a.spec_uuid() != b.spec_uuid()
        assert a.spec_uuid() == c.spec_uuid()

    def test_partition_changes_uuid(self):
        import pandas as pd

        def ident(df: pd.DataFrame) -> pd.DataFrame:
            return df

        dag = FugueWorkflow()
        src = dag.df([[1]], "a:long")
        t1 = src.partition_by("a").transform(ident, schema="*")
        t2 = src.transform(ident, schema="*")
        assert t1.spec_uuid() != t2.spec_uuid()


class TestConfDrivenRPC:
    def test_engine_uses_conf_server(self):
        from fugue_tpu.execution import NativeExecutionEngine
        from fugue_tpu.rpc.http import HttpRPCServer

        e = NativeExecutionEngine(
            {"fugue.rpc.server": "fugue_tpu.rpc.http.HttpRPCServer"}
        )
        assert isinstance(e.rpc_server, HttpRPCServer)
        e.stop()

    def test_callback_over_conf_http(self):
        import pandas as pd

        from fugue_tpu.execution import NativeExecutionEngine
        from fugue_tpu.workflow import transform

        e = NativeExecutionEngine(
            {"fugue.rpc.server": "fugue_tpu.rpc.http.HttpRPCServer"}
        )
        hits = []

        def report(df: pd.DataFrame, cb: callable) -> pd.DataFrame:
            cb(len(df))
            return df

        transform(
            pd.DataFrame({"a": [1, 1, 2]}),
            report,
            schema="*",
            partition={"by": ["a"]},
            callback=lambda n: hits.append(n),
            engine=e,
        )
        assert sorted(hits) == [1, 2]
        e.stop()


class TestAutoPersist:
    def test_multi_consumer_auto_persist(self):
        import pandas as pd

        from fugue_tpu import FugueWorkflow
        from fugue_tpu.workflow._checkpoint import WeakCheckpoint

        calls = []

        def make() -> pd.DataFrame:
            calls.append(1)
            return pd.DataFrame({"a": [1], "b": [2]})

        dag = FugueWorkflow()
        a = dag.create(make)
        a.drop(["a"]).show()
        a.rename({"a": "aa"}).show()
        dag.run("native", {"fugue.workflow.auto_persist": True})
        # the shared node got a weak checkpoint applied
        assert isinstance(a._task.checkpoint, WeakCheckpoint)
        assert len(calls) == 1


class TestNotebookIntegration:
    """%%fsql magic + the Jupyter HTML display chain.

    Runs in a subprocess: starting IPython in-process would permanently
    register the Jupyter display candidate and change how every later
    test's .show() renders.
    """

    def test_magic_display_and_highlight(self):
        import subprocess
        import sys

        pytest.importorskip("IPython")
        code = """
from IPython.testing.globalipapp import start_ipython
ip = start_ipython()
import fugue_tpu.notebook as nb
assert nb.setup()
import pandas as pd
ip.user_ns["src"] = pd.DataFrame({"a": [1, 2, 3]})
cell = chr(10).join(["SELECT a FROM src WHERE a > 1", "YIELD DATAFRAME AS res"])
ip.run_cell_magic("fsql", "", cell)
assert ip.user_ns["res"].result.as_array() == [[2], [3]]
from fugue_tpu.dataframe import ArrayDataFrame
h = ArrayDataFrame([[1, "x"]], "a:long,b:str")._repr_html_()
assert "<" in h and "a:long,b:str" in h
from fugue_tpu.notebook import NotebookSetup
assert "fsql" in NotebookSetup().highlight_js
print("NB_OK")
"""
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=240
        )
        assert proc.returncode == 0 and b"NB_OK" in proc.stdout, proc.stderr
