"""SQL surface closure (round 3): EXISTS / NOT EXISTS, equality-correlated
subqueries (decorrelated to device joins), GROUPING SETS / ROLLUP / CUBE.

The reference accepts these everywhere because raw SQL goes to DuckDB/Spark
(fugue_duckdb/execution_engine.py:95-105); here they run on the engine-verb
executor, identically on the oracle and the jax engine.
"""

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.execution import NativeExecutionEngine
from fugue_tpu.jax import JaxExecutionEngine


@pytest.fixture(
    scope="module", params=["native", "jax"], ids=["oracle", "device"]
)
def engine(request):
    e = (
        NativeExecutionEngine()
        if request.param == "native"
        else JaxExecutionEngine()
    )
    yield e
    e.stop()


def _run(sql, eng, **dfs):
    r = fa.fugue_sql(sql, engine=eng, as_local=True, **dfs)
    return r.to_pandas() if hasattr(r, "to_pandas") else r


@pytest.fixture(scope="module")
def ab():
    a = pd.DataFrame({"k": [1, 2, 3, 4], "v": [10.0, 20.0, 30.0, 40.0]})
    b = pd.DataFrame({"k": [2, 2, 3], "w": [1.0, 2.0, 9.0]})
    return a, b


def test_correlated_exists(engine, ab):
    a, b = ab
    r = _run(
        "SELECT * FROM a WHERE EXISTS (SELECT 1 FROM b WHERE b.k = a.k)",
        engine, a=a, b=b,
    )
    assert sorted(r["k"]) == [2, 3]


def test_correlated_not_exists(engine, ab):
    a, b = ab
    r = _run(
        "SELECT * FROM a WHERE NOT EXISTS (SELECT 1 FROM b WHERE b.k = a.k)",
        engine, a=a, b=b,
    )
    assert sorted(r["k"]) == [1, 4]


def test_correlated_exists_with_residual(engine, ab):
    a, b = ab
    r = _run(
        "SELECT * FROM a WHERE EXISTS "
        "(SELECT 1 FROM b WHERE b.k = a.k AND w > 5)",
        engine, a=a, b=b,
    )
    assert sorted(r["k"]) == [3]


def test_exists_combined_with_other_predicates(engine, ab):
    a, b = ab
    r = _run(
        "SELECT * FROM a WHERE v < 25 AND EXISTS "
        "(SELECT 1 FROM b WHERE b.k = a.k)",
        engine, a=a, b=b,
    )
    assert sorted(r["k"]) == [2]


def test_uncorrelated_exists(engine, ab):
    a, b = ab
    assert len(_run(
        "SELECT * FROM a WHERE EXISTS (SELECT 1 FROM b WHERE w > 100)",
        engine, a=a, b=b,
    )) == 0
    assert len(_run(
        "SELECT * FROM a WHERE EXISTS (SELECT 1 FROM b WHERE w > 5)",
        engine, a=a, b=b,
    )) == 4


def test_correlated_scalar_in_projection(engine, ab):
    a, b = ab
    r = _run(
        "SELECT k, v, (SELECT SUM(w) FROM b WHERE b.k = a.k) AS tw FROM a",
        engine, a=a, b=b,
    ).sort_values("k")
    assert r["tw"].fillna(-1).tolist() == [-1.0, 3.0, 9.0, -1.0]


def test_correlated_scalar_in_where(engine, ab):
    a, b = ab
    r = _run(
        "SELECT k FROM a WHERE v > (SELECT SUM(w) FROM b WHERE b.k = a.k)",
        engine, a=a, b=b,
    )
    assert sorted(r["k"]) == [2, 3]


def test_correlated_scalar_min_max(engine, ab):
    a, b = ab
    r = _run(
        "SELECT k, (SELECT MAX(w) FROM b WHERE b.k = a.k) AS mw FROM a",
        engine, a=a, b=b,
    ).sort_values("k")
    assert r["mw"].fillna(-1).tolist() == [-1.0, 2.0, 9.0, -1.0]


def test_rollup(engine):
    df = pd.DataFrame(
        {"k": [1, 1, 2, 2, 3], "g": ["a", "a", "b", "b", "b"],
         "v": [1.0, 2.0, 3.0, 4.0, 5.0]}
    )
    r = _run(
        "SELECT k, g, SUM(v) AS s FROM df GROUP BY ROLLUP(k, g)",
        engine, df=df,
    )
    # 3 (k,g) + 3 (k) + 1 () = 7 rows
    assert len(r) == 7
    grand = r[r["k"].isna() & r["g"].isna()]
    assert len(grand) == 1 and np.isclose(grand["s"].iloc[0], 15.0)
    konly = r[r["k"].notna() & r["g"].isna()].sort_values("k")
    assert konly["s"].tolist() == [3.0, 7.0, 5.0]


def test_cube(engine):
    df = pd.DataFrame(
        {"x": [1, 1, 2], "y": ["a", "b", "b"], "v": [1.0, 2.0, 3.0]}
    )
    r = _run(
        "SELECT x, y, SUM(v) AS s FROM df GROUP BY CUBE(x, y)",
        engine, df=df,
    )
    # (x,y):3 + (x):2 + (y):2 + ():1 = 8
    assert len(r) == 8
    yonly = r[r["x"].isna() & r["y"].notna()].sort_values("y")
    assert yonly["s"].tolist() == [1.0, 5.0]


def test_grouping_sets_explicit(engine):
    df = pd.DataFrame(
        {"x": [1, 1, 2], "y": ["a", "b", "b"], "v": [1.0, 2.0, 3.0]}
    )
    r = _run(
        "SELECT x, y, SUM(v) AS s FROM df "
        "GROUP BY GROUPING SETS ((x, y), (x), ())",
        engine, df=df,
    )
    assert len(r) == 6
    assert np.isclose(r[r["x"].isna()]["s"].iloc[0], 6.0)


def test_rollup_with_where_and_having(engine):
    df = pd.DataFrame(
        {"k": [1, 1, 2, 2, 3], "v": [1.0, 2.0, 3.0, 4.0, 100.0]}
    )
    r = _run(
        "SELECT k, SUM(v) AS s FROM df WHERE v < 50 "
        "GROUP BY ROLLUP(k) HAVING SUM(v) > 2",
        engine, df=df,
    )
    # groups: k=1 s=3, k=2 s=7 (both >2); grand total dropped (HAVING on
    # the empty set is unsupported -> it would raise; ensure keyed sets ok
    assert sorted(x for x in r["k"] if not pd.isna(x)) == [1, 2]


def test_alias_qualified_correlation(engine, ab):
    a, b = ab
    r = _run(
        "SELECT * FROM a AS x WHERE EXISTS "
        "(SELECT 1 FROM b WHERE b.k = x.k)",
        engine, a=a, b=b,
    )
    assert sorted(r["k"]) == [2, 3]


def test_exists_under_or_raises(engine, ab):
    # unsupported positions must error loudly, never silently mis-bind
    a, b = ab
    with pytest.raises(NotImplementedError):
        _run(
            "SELECT * FROM a WHERE v < 15 OR EXISTS "
            "(SELECT 1 FROM b WHERE b.k = a.k)",
            engine, a=a, b=b,
        )


def test_correlated_count_zero_not_null(engine, ab):
    a, b = ab
    r = _run(
        "SELECT k, (SELECT COUNT(*) FROM b WHERE b.k = a.k) AS c FROM a",
        engine, a=a, b=b,
    ).sort_values("k")
    assert r["c"].tolist() == [0, 2, 1, 0]


def test_correlated_scalar_inside_in(engine, ab):
    a, b = ab
    r = _run(
        "SELECT k FROM a WHERE (SELECT SUM(w) FROM b WHERE b.k = a.k) "
        "IN (3, 9)",
        engine, a=a, b=b,
    )
    assert sorted(r["k"]) == [2, 3]


def test_exists_with_order_and_limit(engine, ab):
    a, b = ab
    r = _run(
        "SELECT * FROM a WHERE EXISTS "
        "(SELECT 1 FROM b WHERE b.k = a.k LIMIT 1)",
        engine, a=a, b=b,
    )
    assert sorted(r["k"]) == [2, 3]


def test_join_on_correlation_refused(engine, ab):
    from fugue_tpu.exceptions import FugueSQLSyntaxError

    a, b = ab
    c = pd.DataFrame({"j": [2, 3], "z": [1.0, 2.0]})
    with pytest.raises((NotImplementedError, FugueSQLSyntaxError)):
        _run(
            "SELECT * FROM a WHERE EXISTS "
            "(SELECT 1 FROM b JOIN c ON c.j = a.k)",
            engine, a=a, b=b, c=c,
        )


def test_exists_with_group_by_having(engine, ab):
    a, b = ab
    r = _run(
        "SELECT * FROM a WHERE EXISTS "
        "(SELECT k FROM b GROUP BY k HAVING SUM(w) > 1)",
        engine, a=a, b=b,
    )
    assert len(r) == 4


def test_exists_without_from(engine, ab):
    a, _ = ab
    assert len(_run(
        "SELECT * FROM a WHERE EXISTS (SELECT 1)", engine, a=a
    )) == 4


def test_derived_table_hides_inner_scope(engine, ab):
    from fugue_tpu.exceptions import FugueSQLSyntaxError

    a, b = ab
    with pytest.raises((NotImplementedError, FugueSQLSyntaxError)):
        _run(
            "SELECT * FROM (SELECT k FROM a) t WHERE EXISTS "
            "(SELECT 1 FROM b WHERE b.k = a.k)",
            engine, a=a, b=b,
        )


def test_grouped_key_projection_with_agg_having(engine, ab):
    _, b = ab
    r = _run(
        "SELECT k FROM b GROUP BY k HAVING SUM(w) > 1", engine, b=b
    )
    assert sorted(r["k"]) == [2, 3]
