"""Multi-host runtime: a REAL two-process jax.distributed run on CPU.

Two worker processes coordinate through jax's distributed service, build
one mesh spanning both processes' devices, and run a cross-host psum —
the same initialization path a TPU pod uses (SURVEY §5.8).
"""

import os
import socket
import subprocess
import sys

import pytest

# the baked-in jaxlib cannot run cross-process collectives on the CPU
# backend ("Multiprocess computations aren't implemented on the CPU
# backend") — these tests pass on jax builds with the CPU collectives
# (gloo) plugin and on real multi-host TPU meshes. Triage: STATUS.md
# (tier-1 carried failures).
pytestmark = pytest.mark.xfail(
    reason=(
        "baked-in jaxlib lacks CPU-backend multiprocess collectives; "
        "requires a gloo-enabled jax build or a real TPU pod"
    ),
    strict=False,
)


def _run_two_workers(tmp_path, template, token, timeout=150, n=2):
    """Shared two-process launcher: free port, write the worker script,
    spawn ``n`` coordinated processes, assert every one prints its
    ``token`` line."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    worker = os.path.join(str(tmp_path), "worker.py")
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    with open(worker, "w") as f:
        f.write(template.format(repo=repo))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
        )
        for i in range(n)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0 and f"{token} {i}".encode() in out, err.decode()[-3000:]
    return outs


_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]
sys.path.insert(0, {repo!r})
from fugue_tpu.parallel.distributed import (
    initialize_distributed, is_multihost, process_info,
)
initialize_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
# idempotency: a second call must be a no-op, not an error
initialize_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
info = process_info()
assert info["process_count"] == 2, info
assert info["global_device_count"] == 4, info
assert info["local_device_count"] == 2, info
assert is_multihost()
import numpy as np, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from fugue_tpu.parallel.mesh import ROW_AXIS, build_mesh
mesh = build_mesh()  # spans BOTH processes' devices
assert mesh.shape[ROW_AXIS] == 4
local = np.arange(pid * 8, (pid + 1) * 8, dtype=np.float64)
x = jax.make_array_from_process_local_data(NamedSharding(mesh, P(ROW_AXIS)), local)
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
assert float(total) == float(sum(range(16))), float(total)
print("MH_OK", pid, flush=True)
"""


def test_two_process_distributed_mesh(tmp_path):
    _run_two_workers(tmp_path, _WORKER, "MH_OK")


_COMAP_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]
sys.path.insert(0, {repo!r})
from fugue_tpu.parallel.distributed import initialize_distributed
initialize_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
import numpy as np, pandas as pd
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.dataframe import DataFrames, PandasDataFrame
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.jax.zipped import ZippedJaxDataFrame

e = JaxExecutionEngine()
rng = np.random.default_rng(3)
a = pd.DataFrame({{"k": rng.integers(0, 12, 400), "v": rng.random(400)}})
b = pd.DataFrame({{"k": rng.integers(0, 12, 300), "w": rng.random(300)}})
z = e.zip(
    DataFrames([e.to_df(a), e.to_df(b)]),
    partition_spec=PartitionSpec(by=["k"]),
)
assert isinstance(z, ZippedJaxDataFrame), type(z)
executed = []

def merge(cursor, dfs):
    d1, d2 = dfs[0].as_pandas(), dfs[1].as_pandas()
    k = int(d1["k"].iloc[0]) if len(d1) else int(d2["k"].iloc[0])
    executed.append(k)
    # string output: exercises the cross-process dictionary union
    return PandasDataFrame(
        pd.DataFrame({{"k": [k], "label": [f"g{{k:02d}}"],
                       "sv": [d1["v"].sum()], "sw": [d2["w"].sum()]}}),
        "k:long,label:str,sv:double,sw:double",
    )

res = e.comap(z, merge, "k:long,label:str,sv:double,sw:double")
# per-host execution proof: this process only ran its LOCAL shards' keys
from jax.experimental import multihost_utils
mine = np.zeros(12, dtype=np.int64); mine[executed] = 1
both = np.asarray(multihost_utils.process_allgather(mine))
assert both.shape[0] == 2
overlap = (both.sum(axis=0) > 1).sum()
assert overlap == 0, f"keys executed on both hosts: {{both}}"
inner = set(a["k"]) & set(b["k"])
assert set(np.nonzero(both.sum(axis=0))[0].tolist()) == inner
# global result correctness, checked per host over its local rows
local = res.as_pandas_local()
for _, row in local.iterrows():
    k = int(row["k"])
    assert row["label"] == f"g{{k:02d}}", row["label"]
    assert np.isclose(row["sv"], a[a["k"] == k]["v"].sum()), k
    assert np.isclose(row["sw"], b[b["k"] == k]["w"].sum()), k
assert res.count() == len(inner)
# the union dictionary must be IDENTICAL on every process (divergent
# metadata desynchronizes later jitted programs)
enc = res.encodings.get("label")
assert enc is not None and enc["kind"] == "dict", enc
import hashlib
h = hashlib.sha1("|".join(enc["dictionary"].to_pylist()).encode()).digest()[:8]
hv = np.frombuffer(h, dtype=np.int64)
hs = np.asarray(multihost_utils.process_allgather(hv)).reshape(-1)
assert (hs == hs[0]).all(), hs
# and the global frame must decode everywhere: a device filter on the
# string column still works after reassembly
from fugue_tpu.column import col
flt = e.filter(res, col("label") == "g05")
assert flt.count() == (1 if 5 in inner else 0)
print("MHC_OK", pid, len(executed), flush=True)
"""


def test_two_process_per_host_comap(tmp_path):
    outs = _run_two_workers(tmp_path, _COMAP_WORKER, "MHC_OK")
    executed_counts = [int(out.decode().strip().split()[-1]) for _, out, _ in outs]
    # both hosts did real work (keys hash-spread over both processes)
    assert all(c > 0 for c in executed_counts), executed_counts


_ENGINE_SUITE_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]
sys.path.insert(0, {repo!r})
from fugue_tpu.parallel.distributed import initialize_distributed
initialize_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
import numpy as np, pandas as pd
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from typing import Dict
import fugue_tpu.api as fa
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.jax import JaxExecutionEngine, group_ops as go

# the engine-verb slice of the execution contract on a REAL 2-process x
# 2-device mesh (VERDICT r4 #8): aggregate, compiled keyed map, join,
# repartition. Every process ingests the same global frame; correctness
# is asserted through REPLICATED device checksums (a device_get of a
# non-addressable shard would be invalid multi-process).
e = JaxExecutionEngine()
rep = NamedSharding(e.mesh, P())

def rsum(frame, name):
    # masked, cross-shard replicated sum of one column -> float on every host
    arr = frame.device_cols[name]
    m = frame.device_valid_mask()
    s = jax.jit(
        lambda a, mm: jnp.sum(jnp.where(mm, a, 0.0)), out_shardings=rep
    )(arr.astype(jnp.float64), m)
    return float(s)

rng = np.random.default_rng(7)
pdf = pd.DataFrame({{"k": rng.integers(0, 40, 4000), "v": rng.random(4000)}})
jdf = e.to_df(pdf)

# 1) aggregate (dense fused, device-resident result)
agg = e.aggregate(
    jdf, PartitionSpec(by=["k"]),
    [ff.sum(col("v")).alias("s"), ff.count(col("v")).alias("n")],
)
exp = pdf.groupby("k")["v"].sum()
assert abs(rsum(agg, "s") - float(exp.sum())) < 1e-8
assert abs(rsum(agg, "n") - float(len(pdf))) < 1e-8

# 2) compiled keyed map (demean per key)
def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    m = go.mean(cols, cols["v"])
    return {{"k": cols["k"], "v": cols["v"] - go.per_row(cols, m)}}

out = fa.transform(
    jdf, demean, schema="k:long,v:double",
    partition=PartitionSpec(by=["k"]), engine=e, as_fugue=True,
)
exp_dm = pdf["v"] - pdf.groupby("k")["v"].transform("mean")
assert abs(rsum(out, "v") - float(exp_dm.sum())) < 1e-6

# 3) device join
dim = pd.DataFrame({{"k": np.arange(30), "w": np.arange(30) * 0.5}})
joined = e.join(jdf, e.to_df(dim), how="inner")
exp_j = pdf.merge(dim, on="k", how="inner")
assert abs(rsum(joined, "w") - float(exp_j["w"].sum())) < 1e-8
assert abs(rsum(joined, "v") - float(exp_j["v"].sum())) < 1e-8

# 4) repartition (hash exchange) preserves content
rp = e.repartition(jdf, PartitionSpec(by=["k"], num=4))
assert abs(rsum(rp, "v") - float(pdf["v"].sum())) < 1e-8

print("MH_ENGINE_OK", pid, flush=True)
"""


@pytest.mark.slow
def test_two_process_engine_suite(tmp_path):
    """Engine verbs (aggregate/keyed map/join/repartition) across a real
    2-process mesh — the multihost slice of the execution contract."""
    _run_two_workers(tmp_path, _ENGINE_SUITE_WORKER, "MH_ENGINE_OK", timeout=300)
