"""Multi-host runtime: a REAL two-process jax.distributed run on CPU.

Two worker processes coordinate through jax's distributed service, build
one mesh spanning both processes' devices, and run a cross-host psum —
the same initialization path a TPU pod uses (SURVEY §5.8).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]
sys.path.insert(0, {repo!r})
from fugue_tpu.parallel.distributed import (
    initialize_distributed, is_multihost, process_info,
)
initialize_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
# idempotency: a second call must be a no-op, not an error
initialize_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
info = process_info()
assert info["process_count"] == 2, info
assert info["global_device_count"] == 4, info
assert info["local_device_count"] == 2, info
assert is_multihost()
import numpy as np, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from fugue_tpu.parallel.mesh import ROW_AXIS, build_mesh
mesh = build_mesh()  # spans BOTH processes' devices
assert mesh.shape[ROW_AXIS] == 4
local = np.arange(pid * 8, (pid + 1) * 8, dtype=np.float64)
x = jax.make_array_from_process_local_data(NamedSharding(mesh, P(ROW_AXIS)), local)
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
assert float(total) == float(sum(range(16))), float(total)
print("MH_OK", pid, flush=True)
"""


def test_two_process_distributed_mesh(tmp_path):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    worker = os.path.join(str(tmp_path), "worker.py")
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    with open(worker, "w") as f:
        f.write(_WORKER.format(repo=repo))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=150)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0 and f"MH_OK {i}".encode() in out, err.decode()[-2000:]


_COMAP_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]
sys.path.insert(0, {repo!r})
from fugue_tpu.parallel.distributed import initialize_distributed
initialize_distributed(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
import numpy as np, pandas as pd
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.dataframe import DataFrames, PandasDataFrame
from fugue_tpu.jax import JaxExecutionEngine
from fugue_tpu.jax.zipped import ZippedJaxDataFrame

e = JaxExecutionEngine()
rng = np.random.default_rng(3)
a = pd.DataFrame({{"k": rng.integers(0, 12, 400), "v": rng.random(400)}})
b = pd.DataFrame({{"k": rng.integers(0, 12, 300), "w": rng.random(300)}})
z = e.zip(
    DataFrames([e.to_df(a), e.to_df(b)]),
    partition_spec=PartitionSpec(by=["k"]),
)
assert isinstance(z, ZippedJaxDataFrame), type(z)
executed = []

def merge(cursor, dfs):
    d1, d2 = dfs[0].as_pandas(), dfs[1].as_pandas()
    k = int(d1["k"].iloc[0]) if len(d1) else int(d2["k"].iloc[0])
    executed.append(k)
    # string output: exercises the cross-process dictionary union
    return PandasDataFrame(
        pd.DataFrame({{"k": [k], "label": [f"g{{k:02d}}"],
                       "sv": [d1["v"].sum()], "sw": [d2["w"].sum()]}}),
        "k:long,label:str,sv:double,sw:double",
    )

res = e.comap(z, merge, "k:long,label:str,sv:double,sw:double")
# per-host execution proof: this process only ran its LOCAL shards' keys
from jax.experimental import multihost_utils
mine = np.zeros(12, dtype=np.int64); mine[executed] = 1
both = np.asarray(multihost_utils.process_allgather(mine))
assert both.shape[0] == 2
overlap = (both.sum(axis=0) > 1).sum()
assert overlap == 0, f"keys executed on both hosts: {{both}}"
inner = set(a["k"]) & set(b["k"])
assert set(np.nonzero(both.sum(axis=0))[0].tolist()) == inner
# global result correctness, checked per host over its local rows
local = res.as_pandas_local()
for _, row in local.iterrows():
    k = int(row["k"])
    assert row["label"] == f"g{{k:02d}}", row["label"]
    assert np.isclose(row["sv"], a[a["k"] == k]["v"].sum()), k
    assert np.isclose(row["sw"], b[b["k"] == k]["w"].sum()), k
assert res.count() == len(inner)
# the union dictionary must be IDENTICAL on every process (divergent
# metadata desynchronizes later jitted programs)
enc = res.encodings.get("label")
assert enc is not None and enc["kind"] == "dict", enc
import hashlib
h = hashlib.sha1("|".join(enc["dictionary"].to_pylist()).encode()).digest()[:8]
hv = np.frombuffer(h, dtype=np.int64)
hs = np.asarray(multihost_utils.process_allgather(hv)).reshape(-1)
assert (hs == hs[0]).all(), hs
# and the global frame must decode everywhere: a device filter on the
# string column still works after reassembly
from fugue_tpu.column import col
flt = e.filter(res, col("label") == "g05")
assert flt.count() == (1 if 5 in inner else 0)
print("MHC_OK", pid, len(executed), flush=True)
"""


def test_two_process_per_host_comap(tmp_path):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    worker = os.path.join(str(tmp_path), "comap_worker.py")
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    with open(worker, "w") as f:
        f.write(_COMAP_WORKER.format(repo=repo))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=150)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    executed_counts = []
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0 and f"MHC_OK {i}".encode() in out, err.decode()[-3000:]
        executed_counts.append(
            int(out.decode().strip().split()[-1])
        )
    # both hosts did real work (keys hash-spread over both processes)
    assert all(c > 0 for c in executed_counts), executed_counts
