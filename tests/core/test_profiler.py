"""Tracing/profiling subsystem tests (SURVEY §5.1).

The reference has no built-in tracer; fugue_tpu adds JAX profiler hooks
(`fugue_tpu/parallel/profiler.py`). These tests prove the hooks actually
capture traces: ``profile`` writes trace artifacts into the target dir,
``annotate`` nests inside an active trace, and
``profiled_engine_context`` activates on the ``fugue.tpu.profile.dir``
conf and stays inert without it.
"""

import os

import jax.numpy as jnp

from fugue_tpu.parallel.profiler import (
    FUGUE_TPU_CONF_PROFILE_DIR,
    annotate,
    profile,
    profiled_engine_context,
)


def _tree_files(root: str):
    out = []
    for base, _, files in os.walk(root):
        out.extend(os.path.join(base, f) for f in files)
    return out


def test_profile_writes_trace(tmp_path):
    log_dir = str(tmp_path / "trace")
    with profile(log_dir):
        (jnp.arange(16.0) * 2).sum().block_until_ready()
    files = _tree_files(log_dir)
    assert len(files) > 0, "profiler trace produced no artifacts"
    # the JAX profiler writes xplane protobufs under plugins/profile/<run>/
    assert any("plugins" in f or f.endswith(".pb") for f in files)


def test_annotate_inside_trace(tmp_path):
    log_dir = str(tmp_path / "trace")
    with profile(log_dir):
        with annotate("fugue-tpu-test-region"):
            jnp.ones((8, 8)).sum().block_until_ready()
    assert len(_tree_files(log_dir)) > 0


def test_annotate_without_trace_is_noop():
    # annotations outside an active trace must not raise
    with annotate("no-trace-active"):
        assert float(jnp.asarray(1.0)) == 1.0


def test_profiled_engine_context_activates_on_conf(tmp_path):
    log_dir = str(tmp_path / "engine_trace")
    with profiled_engine_context(
        "native", conf={FUGUE_TPU_CONF_PROFILE_DIR: log_dir}
    ) as e:
        assert e.conf.get(FUGUE_TPU_CONF_PROFILE_DIR, "") == log_dir
        jnp.arange(32.0).sum().block_until_ready()
    assert len(_tree_files(log_dir)) > 0, "conf-activated trace wrote nothing"


def test_profiled_engine_context_inert_without_conf(tmp_path):
    marker = str(tmp_path / "should_not_exist")
    with profiled_engine_context("native") as e:
        assert e.conf.get(FUGUE_TPU_CONF_PROFILE_DIR, "") == ""
    assert not os.path.exists(marker)
