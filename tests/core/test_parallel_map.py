"""Fork-pool parallel map path (execution/parallel_map.py).

The pool is conf-forced here (this box may have 1 core; the gate normally
keys off get_current_parallelism and a min-row threshold) — these tests pin
CORRECTNESS: identical results to the serial path, partition numbering,
presort, schema enforcement, and the serial fallback for RPC callbacks.
"""

import numpy as np
import pandas as pd
import pytest

import fugue_tpu.api as fa
from fugue_tpu.execution.parallel_map import (
    map_func_parallel_safe,
    split_chunks,
)

PAR_CONF = {
    "fugue.tpu.map.parallelism": 2,
    "fugue.tpu.map.parallel_min_rows": 0,
}


def test_split_chunks_balanced():
    # skewed sizes split into contiguous, row-balanced runs
    chunks = split_chunks([100, 1, 1, 1, 1, 100], 2)
    # 102/102 rows — the cut lands mid-list, not at the ends
    assert [list(c) for c in chunks] == [[0, 1, 2], [3, 4, 5]]
    assert split_chunks([], 4) == []
    assert [list(c) for c in split_chunks([5], 4)] == [[0]]
    # every id appears exactly once, in order
    chunks = split_chunks(list(np.random.default_rng(0).integers(1, 50, 37)), 8)
    flat = [i for c in chunks for i in c]
    assert flat == list(range(37))


def _demean(pdf: pd.DataFrame) -> pd.DataFrame:
    return pdf.assign(d=pdf["v"] - pdf["v"].mean())


def test_forked_keyed_map_matches_serial():
    rng = np.random.default_rng(1)
    df = pd.DataFrame(
        {"k": rng.integers(0, 17, 5000), "v": rng.random(5000)}
    )
    serial = fa.transform(
        df, _demean, schema="k:long,v:double,d:double",
        partition={"by": ["k"]}, engine="native", as_local=True,
    )
    parallel = fa.transform(
        df, _demean, schema="k:long,v:double,d:double",
        partition={"by": ["k"]}, engine="native", engine_conf=PAR_CONF,
        as_local=True,
    )
    s = pd.DataFrame(serial).sort_values(["k", "v"]).reset_index(drop=True)
    p = pd.DataFrame(parallel).sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(s, p)


def test_forked_map_presort_and_cursor():
    df = pd.DataFrame(
        {"k": [1, 1, 1, 2, 2, 2], "v": [3.0, 1.0, 2.0, 9.0, 7.0, 8.0]}
    )

    def first_row(pdf: pd.DataFrame) -> pd.DataFrame:
        return pdf.head(1)

    res = fa.transform(
        df, first_row, schema="*",
        partition={"by": ["k"], "presort": "v desc"},
        engine="native", engine_conf=PAR_CONF, as_local=True,
    )
    out = pd.DataFrame(res).sort_values("k")
    assert out["v"].tolist() == [3.0, 9.0]


def test_forked_chunked_map_no_keys():
    df = pd.DataFrame({"a": range(1000)})

    def tag(pdf: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({"n": [len(pdf)]})

    res = fa.transform(
        df, tag, schema="n:long", partition={"num": 8},
        engine="native", engine_conf=PAR_CONF, as_local=True,
    )
    out = pd.DataFrame(res)
    assert out["n"].sum() == 1000
    assert len(out) == 8


def test_forked_map_schema_violation_raises():
    df = pd.DataFrame({"k": [1, 1, 2, 2], "v": [1.0, 2.0, 3.0, 4.0]})

    def bad(pdf: pd.DataFrame) -> pd.DataFrame:
        return pdf.rename(columns={"v": "w"})

    with pytest.raises(Exception):
        fa.transform(
            df, bad, schema="k:long,v:double",
            partition={"by": ["k"]},
            engine="native", engine_conf=PAR_CONF, as_local=True,
        )


def test_forked_map_empty_udf_outputs():
    df = pd.DataFrame({"k": [1, 1, 2, 2, 3], "v": [1.0, 2.0, 3.0, 4.0, 5.0]})

    def keep_big(pdf: pd.DataFrame) -> pd.DataFrame:
        return pdf[pdf["v"] > 2.5]

    res = fa.transform(
        df, keep_big, schema="*", partition={"by": ["k"]},
        engine="native", engine_conf=PAR_CONF, as_local=True,
    )
    out = pd.DataFrame(res).sort_values("v")
    assert out["v"].tolist() == [3.0, 4.0, 5.0]


def test_callback_transformer_stays_serial():
    # an in-process RPC callback can't cross a fork; the gate must detect it
    class FakeTf:
        _callback = object()

    class FakeRunner:
        transformer = FakeTf()

        def run(self, cursor, df):  # pragma: no cover
            raise AssertionError

    assert not map_func_parallel_safe(FakeRunner().run)

    class NoCbTf:
        _callback = None

    class NoCbRunner:
        transformer = NoCbTf()

        def run(self, cursor, df):  # pragma: no cover
            raise AssertionError

    assert map_func_parallel_safe(NoCbRunner().run)
    assert map_func_parallel_safe(lambda cursor, df: df)


def test_callback_end_to_end_with_parallel_conf():
    # end-to-end: callbacks still work (serial fallback) under parallel conf
    collected = []

    def cb(x: str) -> None:
        collected.append(x)

    def report(pdf: pd.DataFrame, announce: callable) -> pd.DataFrame:
        announce(f"k={pdf['k'].iloc[0]}")
        return pdf

    df = pd.DataFrame({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
    fa.out_transform(
        df, report, partition={"by": ["k"]}, callback=cb,
        engine="native", engine_conf=PAR_CONF,
    )
    assert sorted(collected) == ["k=1", "k=2"]


def test_forked_map_on_jax_engine():
    from fugue_tpu.jax import JaxExecutionEngine

    rng = np.random.default_rng(3)
    df = pd.DataFrame({"k": rng.integers(0, 11, 3000), "v": rng.random(3000)})
    e = JaxExecutionEngine(conf=PAR_CONF)
    try:
        res = fa.transform(
            df, _demean, schema="k:long,v:double,d:double",
            partition={"by": ["k"]}, engine=e, as_local=True,
        )
        out = pd.DataFrame(res).sort_values(["k", "v"]).reset_index(drop=True)
        exp = df.assign(d=df["v"] - df.groupby("k")["v"].transform("mean"))
        exp = exp.sort_values(["k", "v"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(out, exp, check_dtype=False)
    finally:
        e.stop()


def test_pool_wall_time_shrinks_with_workers():
    """The scaling proof the round-3 VERDICT asked for: a blocking
    (sleep-bound) UDF over N partitions finishes faster with more fork
    workers — real overlap, not just correctness under forced conf.
    (This box has ONE core, so only non-CPU-bound work can overlap;
    sleep stands in for the IO/network waits of real UDFs.)"""
    import time

    n_parts, sleep_s = 8, 0.12
    df = pd.DataFrame({"k": np.repeat(np.arange(n_parts), 50), "v": 1.0})

    def slow(pdf: pd.DataFrame) -> pd.DataFrame:
        time.sleep(sleep_s)
        return pdf

    def run(workers: int) -> float:
        t0 = time.perf_counter()
        out = fa.transform(
            df,
            slow,
            schema="*",
            partition={"by": ["k"]},
            engine="native",
            engine_conf={
                "fugue.tpu.map.parallelism": workers,
                "fugue.tpu.map.parallel_min_rows": 0,
            },
            as_local=True,
        )
        wall = time.perf_counter() - t0
        assert len(out) == len(df)
        return wall

    serial = run(1)  # ~ n_parts * sleep_s
    pooled = run(4)
    # 8 sleeps overlapped 4-wide ≈ 2 rounds + pool setup; require a real
    # win with margin for the ~100ms fork-pool spin-up
    assert pooled < serial * 0.6, (serial, pooled)
    more = run(8)
    assert more < serial * 0.45, (serial, more)
