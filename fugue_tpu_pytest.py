"""pytest11 entry-point shim for fugue-tpu.

Keeps pytest startup safe and cheap-ish: the heavy fugue_tpu import happens
inside pytest_configure behind a guard, so a broken accelerator stack in the
environment can never prevent unrelated pytest runs from starting. Opt out
entirely with FUGUE_TPU_DISABLE_PYTEST_PLUGIN=1.
"""

import os


def pytest_configure(config):  # noqa: ANN001
    if os.environ.get("FUGUE_TPU_DISABLE_PYTEST_PLUGIN", "") == "1":
        return
    try:
        from fugue_tpu.test.plugins import pytest_configure as impl
    except Exception as e:  # never break pytest startup for other projects
        import warnings

        warnings.warn(f"fugue-tpu pytest plugin disabled: {e!r}", stacklevel=1)
        return
    impl(config)
