"""Bag contract suite (reference ``fugue_test/bag_suite.py``)."""

from typing import Any

import pytest

from fugue_tpu.bag.bag import Bag
from fugue_tpu.exceptions import FugueDatasetEmptyError


class BagTests:
    """Subclass ``BagTests.Tests`` and implement ``bag()``."""

    class Tests:
        def bag(self, data: Any = None) -> Bag:
            raise NotImplementedError

        def test_init(self):
            b = self.bag([1, "x", None])
            assert not b.empty
            assert b.count() == 3
            assert b.is_local and b.is_bounded

        def test_empty(self):
            b = self.bag([])
            assert b.empty
            with pytest.raises(FugueDatasetEmptyError):
                b.peek()

        def test_peek_as_array(self):
            b = self.bag([5, 6])
            assert b.peek() == 5
            assert b.as_array() == [5, 6]

        def test_head(self):
            b = self.bag(list(range(10)))
            h = b.head(3)
            assert h.as_array() == [0, 1, 2]
            assert h.is_bounded
