"""Bag contract suite (reference ``fugue_test/bag_suite.py``)."""

from typing import Any

import pytest

from fugue_tpu.bag.bag import Bag
from fugue_tpu.exceptions import FugueDatasetEmptyError


class BagTests:
    """Subclass ``BagTests.Tests`` and implement ``bag()``."""

    class Tests:
        def bag(self, data: Any = None) -> Bag:
            raise NotImplementedError

        def test_init(self):
            b = self.bag([1, "x", None])
            assert not b.empty
            assert b.count() == 3
            assert b.is_local and b.is_bounded

        def test_empty(self):
            b = self.bag([])
            assert b.empty
            with pytest.raises(FugueDatasetEmptyError):
                b.peek()

        def test_peek_as_array(self):
            b = self.bag([5, 6])
            assert b.peek() == 5
            assert b.as_array() == [5, 6]

        def test_head(self):
            b = self.bag(list(range(10)))
            h = b.head(3)
            assert h.as_array() == [0, 1, 2]
            assert h.is_bounded

        def test_head_edges(self):
            b = self.bag([1, 2])
            assert b.head(0).as_array() == []
            assert b.head(10).as_array() == [1, 2]

        def test_special_values(self):
            data = [None, float("nan"), "", 0, False, b"\x00"]
            b = self.bag(list(data))
            arr = b.as_array()
            assert len(arr) == 6
            assert arr[0] is None and arr[2] == "" and arr[3] == 0

        def test_mixed_object_types(self):
            data = [dict(a=1), [1, 2], ("t", 1), {3, 4}]
            b = self.bag(list(data))
            arr = b.as_array()
            assert dict(a=1) in arr and [1, 2] in arr

        def test_as_local_identity(self):
            b = self.bag([1, 2, 3])
            lb = b.as_local()
            assert lb.is_local
            assert lb.as_array() == [1, 2, 3]

        def test_num_partitions_and_metadata(self):
            b = self.bag([1])
            assert b.num_partitions >= 1
            assert not b.has_metadata
            b.reset_metadata({"k": "v"})
            assert b.metadata["k"] == "v"
            b.reset_metadata(None)
            assert not b.has_metadata

        def test_show(self):
            self.bag([1, "x", None]).show()
            self.bag([]).show()

        def test_large_bag(self):
            n = 10_000
            b = self.bag(list(range(n)))
            assert b.count() == n
            assert b.head(5).as_array() == [0, 1, 2, 3, 4]
