"""Workflow-level contract suite.

Modeled on the reference's ``fugue_test/builtin_suite.py`` coverage
(``:70-1743``): create/show/assert, transforms in every interfaceless form,
cotransform, partitioning + presort, checkpoints, yields, RPC callbacks,
validation rules, ignore_errors, io through the workflow.
"""

import os
from typing import Any, Dict, Iterable, List, Optional

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from fugue_tpu import (
    ArrayDataFrame,
    DataFrame,
    DataFrames,
    FugueWorkflow,
    PandasDataFrame,
    Schema,
    Transformer,
)
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.dataframe import LocalDataFrame
from fugue_tpu.exceptions import (
    FugueInterfacelessError,
    FugueWorkflowCompileValidationError,
    FugueWorkflowError,
)
from fugue_tpu.execution import ExecutionEngine
from fugue_tpu.workflow import out_transform, transform


class BuiltInTests:
    """Subclass ``BuiltInTests.Tests``; provide ``make_engine``."""

    class Tests:
        @pytest.fixture(autouse=True)
        def _setup_engine(self, tmp_path):
            self.engine: ExecutionEngine = self.make_engine()
            self.tmpdir = str(tmp_path)
            yield
            self.engine.stop()

        def make_engine(self) -> ExecutionEngine:
            raise NotImplementedError

        # -- basics ----------------------------------------------------------
        def test_create_show(self):
            with FugueWorkflow() as dag:
                dag.df([[0]], "a:long").show()
            dag.run(self.engine)

        def test_create_process_output(self):
            def double(df: pd.DataFrame) -> pd.DataFrame:
                df["a"] = df["a"] * 2
                return df

            collected: List[Any] = []

            def sink(df: pd.DataFrame) -> None:
                collected.append(df["a"].tolist())

            dag = FugueWorkflow()
            a = dag.df([[1], [2]], "a:long")
            b = dag.process(a, using=double, schema="a:long")
            dag.output(b, using=sink)
            dag.run(self.engine)
            assert collected == [[2, 4]]

        def test_assert_eq(self):
            dag = FugueWorkflow()
            a = dag.df([[0]], "a:long")
            a.assert_eq(dag.df([[0]], "a:long"))
            dag.run(self.engine)

            dag2 = FugueWorkflow()
            a2 = dag2.df([[0]], "a:long")
            a2.assert_eq(dag2.df([[1]], "a:long"))
            with pytest.raises(AssertionError):
                dag2.run(self.engine)

        def test_creator_interfaceless(self):
            def make() -> pd.DataFrame:
                return pd.DataFrame({"a": [1, 2]})

            # schema: a:long
            def make2() -> List[List[Any]]:
                return [[5]]

            dag = FugueWorkflow()
            dag.create(make).assert_eq(dag.df([[1], [2]], "a:long"))
            dag.create(make2).assert_eq(dag.df([[5]], "a:long"))
            dag.run(self.engine)

        # -- transform forms -------------------------------------------------
        def test_transform_annotation_forms(self):
            data = [[1, "a"], [2, "b"]]

            def f_pandas(df: pd.DataFrame) -> pd.DataFrame:
                return df

            def f_arrow(df: pa.Table) -> pa.Table:
                return df

            def f_iter_list(rows: Iterable[List[Any]]) -> Iterable[List[Any]]:
                for r in rows:
                    yield r

            def f_list_dict(rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
                return rows

            def f_ldf(df: LocalDataFrame) -> LocalDataFrame:
                return df

            dag = FugueWorkflow()
            src = dag.df(data, "a:long,b:str")
            for fn in [f_pandas, f_arrow, f_ldf, f_iter_list, f_list_dict]:
                src.transform(fn, schema="*").assert_eq(src)
            dag.run(self.engine)

        def test_transform_schema_expressions(self):
            def with_col(df: pd.DataFrame) -> pd.DataFrame:
                df["c"] = 1
                return df

            def drop_col(rows: Iterable[List[Any]]) -> Iterable[List[Any]]:
                for r in rows:
                    yield r[:-1]

            dag = FugueWorkflow()
            src = dag.df([[1, "a"]], "a:long,b:str")
            src.transform(with_col, schema="*,c:long").assert_eq(
                dag.df([[1, "a", 1]], "a:long,b:str,c:long")
            )
            src.transform(drop_col, schema="*,-b").assert_eq(dag.df([[1]], "a:long"))
            dag.run(self.engine)

        def test_transform_schema_comment(self):
            # schema: a:long,n:long
            def counter(df: pd.DataFrame) -> pd.DataFrame:
                return pd.DataFrame({"a": [df["a"].iloc[0]], "n": [len(df)]})

            dag = FugueWorkflow()
            src = dag.df([[1], [1], [2]], "a:long")
            src.partition_by("a").transform(counter).assert_eq(
                dag.df([[1, 2], [2, 1]], "a:long,n:long")
            )
            dag.run(self.engine)

        def test_transform_by_string_name(self):
            dag = FugueWorkflow()
            src = dag.df([[1]], "a:long")
            src.transform("_string_ref_transformer", schema="a:long").assert_eq(src)
            dag.run(self.engine)

        def test_transformer_class(self):
            class MyTransformer(Transformer):
                def get_output_schema(self, df: DataFrame) -> Any:
                    return df.schema + "n:long"

                def transform(self, df: LocalDataFrame) -> LocalDataFrame:
                    rows = [r + [len(r)] for r in df.as_array()]
                    return ArrayDataFrame(rows, self.output_schema)

            dag = FugueWorkflow()
            src = dag.df([[1, "a"]], "a:long,b:str")
            src.transform(MyTransformer).assert_eq(
                dag.df([[1, "a", 2]], "a:long,b:str,n:long")
            )
            dag.run(self.engine)

        def test_local_instance_as_extension(self):
            """Bound methods of a local object as transformers, with
            ``# schema:`` comments on the METHOD (reference
            ``builtin_suite.py`` test_local_instance_as_extension) —
            exercises interfaceless conversion over instance methods."""

            class _Mock(object):
                # schema: *
                def t1(self, df: pd.DataFrame) -> pd.DataFrame:
                    return df

                def t2(self, df: pd.DataFrame) -> pd.DataFrame:
                    return df

                def run_inner(self, engine: Any) -> None:
                    dag_ = FugueWorkflow()
                    a = dag_.df([[0], [1]], "a:int")
                    b = a.transform(self.t1)
                    b.assert_eq(a)
                    dag_.run(engine)

            m = _Mock()
            m.run_inner(self.engine)
            dag = FugueWorkflow()
            a = dag.df([[0], [1]], "a:int")
            b = a.transform(m.t1).transform(m.t2, schema="*")
            b.assert_eq(a)
            dag.run(self.engine)

        def test_create_df_equivalence(self):
            """``dag.df(x)`` and ``dag.create(x)`` compile to the SAME
            deterministic spec uuid for an engine-native frame (reference
            test_create_df_equivalence) — checkpoint determinism depends
            on this equivalence."""
            ndf = self.engine.to_df(pd.DataFrame([[0]], columns=["a"]))
            dag1 = FugueWorkflow()
            dag1.df(ndf).show()
            dag2 = FugueWorkflow()
            dag2.create(ndf).show()
            assert dag1.spec_uuid() == dag2.spec_uuid()
            # and both spellings actually run on the engine
            dag1.run(self.engine)
            dag2.run(self.engine)

        def test_transform_iterable_chunks(self):
            def chunks(dfs: Iterable[pd.DataFrame]) -> Iterable[pd.DataFrame]:
                for c in dfs:
                    yield c

            dag = FugueWorkflow()
            src = dag.df([[1], [2]], "a:long")
            src.transform(chunks, schema="*").assert_eq(src)
            dag.run(self.engine)

        def test_transform_binary(self):
            def roundtrip(df: pd.DataFrame) -> pd.DataFrame:
                return df

            dag = FugueWorkflow()
            src = dag.df([[b"\x01\x02"]], "a:bytes")
            src.transform(roundtrip, schema="*").assert_eq(src)
            dag.run(self.engine)

        def test_transform_ignore_errors(self):
            def fail_on_2(df: pd.DataFrame) -> pd.DataFrame:
                if df["a"].iloc[0] == 2:
                    raise NotImplementedError("boom")
                return df

            dag = FugueWorkflow()
            src = dag.df([[1], [2]], "a:long")
            src.partition_by("a").transform(
                fail_on_2, schema="*", ignore_errors=[NotImplementedError]
            ).assert_eq(dag.df([[1]], "a:long"))
            dag.run(self.engine)

            dag2 = FugueWorkflow()
            src2 = dag2.df([[2]], "a:long")
            src2.partition_by("a").transform(fail_on_2, schema="*").show()
            with pytest.raises(NotImplementedError):
                dag2.run(self.engine)

        def test_out_transform(self):
            counts: List[int] = []

            def sink(df: pd.DataFrame) -> None:
                counts.append(len(df))

            dag = FugueWorkflow()
            src = dag.df([[1], [1], [2]], "a:long")
            src.partition_by("a").out_transform(sink)
            dag.run(self.engine)
            assert sorted(counts) == [1, 2]

        # -- cotransform -----------------------------------------------------
        def test_cotransform(self):
            def merge(d1: pd.DataFrame, d2: pd.DataFrame) -> pd.DataFrame:
                return pd.DataFrame(
                    {"k": [d1["k"].iloc[0]], "n1": [len(d1)], "n2": [len(d2)]}
                )

            dag = FugueWorkflow()
            a = dag.df([[1, "a"], [1, "b"], [2, "c"]], "k:long,v:str")
            b = dag.df([[1, 1.0]], "k:long,w:double")
            dag.zip(a, b, partition={"by": ["k"]}).transform(
                merge, schema="k:long,n1:long,n2:long"
            ).assert_eq(dag.df([[1, 2, 1]], "k:long,n1:long,n2:long"))
            dag.run(self.engine)

        def test_cotransform_left(self):
            def merge(d1: pd.DataFrame, d2: pd.DataFrame) -> pd.DataFrame:
                return pd.DataFrame(
                    {"k": [d1["k"].iloc[0]], "n1": [len(d1)], "n2": [len(d2)]}
                )

            dag = FugueWorkflow()
            a = dag.df([[1, "a"], [2, "c"]], "k:long,v:str")
            b = dag.df([[1, 1.0]], "k:long,w:double")
            dag.zip(a, b, how="left_outer", partition={"by": ["k"]}).transform(
                merge, schema="k:long,n1:long,n2:long"
            ).assert_eq(dag.df([[1, 1, 1], [2, 1, 0]], "k:long,n1:long,n2:long"))
            dag.run(self.engine)

        def test_cotransform_named_inputs(self):
            """zip with dict inputs: the cotransformer sees frames by name."""

            def merge(dfs: DataFrames) -> pd.DataFrame:
                left, right = dfs["left"], dfs["right"]
                return pd.DataFrame(
                    {
                        "k": [left.as_array()[0][0]],
                        "n": [left.count() + right.count()],
                    }
                )

            dag = FugueWorkflow()
            a = dag.df([[1, "x"], [1, "y"], [2, "z"]], "k:long,v:str")
            b = dag.df([[1, 9.0], [2, 8.0]], "k:long,w:double")
            z = dag.zip({"left": a, "right": b}, partition={"by": ["k"]})
            z.transform(merge, schema="k:long,n:long").yield_dataframe_as(
                "out", as_local=True
            )
            dag.run(self.engine)
            assert sorted(dag.yields["out"].result.as_array()) == [[1, 3], [2, 2]]

        # -- workflow ops ----------------------------------------------------
        def test_workflow_relational_ops(self):
            dag = FugueWorkflow()
            a = dag.df([[1, "a"], [2, "b"], [2, "b"]], "x:long,y:str")
            a.distinct().assert_eq(dag.df([[1, "a"], [2, "b"]], "x:long,y:str"))
            a.drop(["y"]).assert_eq(dag.df([[1], [2], [2]], "x:long"))
            a.rename({"x": "xx"}).assert_eq(
                dag.df([[1, "a"], [2, "b"], [2, "b"]], "xx:long,y:str")
            )
            a.alter_columns("x:double").assert_eq(
                dag.df([[1.0, "a"], [2.0, "b"], [2.0, "b"]], "x:double,y:str")
            )
            a[["y"]].assert_eq(dag.df([["a"], ["b"], ["b"]], "y:str"))
            b = dag.df([[2, "b"]], "x:long,y:str")
            a.union(b, distinct=False).assert_eq(
                dag.df(
                    [[1, "a"], [2, "b"], [2, "b"], [2, "b"]], "x:long,y:str"
                )
            )
            a.subtract(b).assert_eq(dag.df([[1, "a"]], "x:long,y:str"))
            a.intersect(b).assert_eq(dag.df([[2, "b"]], "x:long,y:str"))
            a.inner_join(dag.df([[1, 5.0]], "x:long,z:double")).assert_eq(
                dag.df([[1, "a", 5.0]], "x:long,y:str,z:double")
            )
            a.take(1, presort="y desc").assert_eq(dag.df([[2, "b"]], "x:long,y:str"))
            dag.run(self.engine)

        def test_workflow_dropna_fillna_sample(self):
            dag = FugueWorkflow()
            a = dag.df([[1.0, "a"], [None, None]], "x:double,y:str")
            a.dropna().assert_eq(dag.df([[1.0, "a"]], "x:double,y:str"))
            a.fillna(0.0, subset=["x"]).assert_eq(
                dag.df([[1.0, "a"], [0.0, None]], "x:double,y:str")
            )
            s = dag.df([[i] for i in range(50)], "x:long").sample(n=5, seed=0)
            dag.run(self.engine)
            assert s.result.count() == 5

        # -- checkpoints & yields -------------------------------------------
        def test_checkpoint_requires_conf(self):
            dag = FugueWorkflow()
            dag.df([[0]], "a:long").checkpoint()
            with pytest.raises(FugueWorkflowError):
                dag.run(self.engine)

        def test_checkpoint(self):
            self.engine.conf["fugue.workflow.checkpoint.path"] = os.path.join(
                self.tmpdir, "ck"
            )
            dag = FugueWorkflow()
            a = dag.df([[0]], "a:long").checkpoint()
            dag.df([[0]], "a:long").assert_eq(a)
            dag.run(self.engine)

        def test_deterministic_checkpoint(self):
            self.engine.conf["fugue.workflow.checkpoint.path"] = os.path.join(
                self.tmpdir, "ck"
            )
            temp_file = os.path.join(self.tmpdir, "t.parquet")

            def mock_create(dummy: int = 1) -> pd.DataFrame:
                return pd.DataFrame(np.random.rand(3, 2), columns=["a", "b"])

            # strong checkpoint: not cross-execution
            dag = FugueWorkflow()
            a = dag.create(mock_create).strong_checkpoint()
            a.save(temp_file)
            dag.run(self.engine)
            dag = FugueWorkflow()
            a = dag.create(mock_create).strong_checkpoint()
            dag.load(temp_file).assert_not_eq(a)
            dag.run(self.engine)

            # deterministic checkpoint: cross-execution resume
            dag = FugueWorkflow()
            a = dag.create(mock_create).deterministic_checkpoint()
            id1 = a.spec_uuid()
            a.save(temp_file)
            dag.run(self.engine)
            dag = FugueWorkflow()
            a = dag.create(mock_create).deterministic_checkpoint()
            dag.load(temp_file).assert_eq(a)
            dag.run(self.engine)
            # checkpoint spec doesn't change determinism
            dag = FugueWorkflow()
            a = dag.create(mock_create).deterministic_checkpoint(
                partition=PartitionSpec(num=2)
            )
            id2 = a.spec_uuid()
            dag.load(temp_file).assert_eq(a)
            dag.run(self.engine)
            # dependency change does
            dag = FugueWorkflow()
            a = dag.create(mock_create, params={"dummy": 2}).deterministic_checkpoint()
            id3 = a.spec_uuid()
            dag.load(temp_file).assert_not_eq(a)
            dag.run(self.engine)
            assert id1 == id2
            assert id1 != id3

        def test_deterministic_checkpoint_table(self):
            # table-storage deterministic checkpoints resume across runs too
            self.engine.conf["fugue.workflow.checkpoint.path"] = os.path.join(
                self.tmpdir, "ckt"
            )
            calls: List[int] = []

            def mock_create(dummy: int = 1) -> pd.DataFrame:
                calls.append(1)
                return pd.DataFrame([[1, 2]], columns=["a", "b"])

            dag = FugueWorkflow()
            dag.create(mock_create).deterministic_checkpoint(storage_type="table")
            dag.run(self.engine)
            n1 = len(calls)
            assert n1 >= 1
            dag = FugueWorkflow()
            a = dag.create(mock_create).deterministic_checkpoint(storage_type="table")
            a.assert_eq(dag.df([[1, 2]], "a:long,b:long"))
            dag.run(self.engine)
            assert len(calls) == n1  # creator skipped: resumed from the table

        def test_yield_dataframe(self):
            dag = FugueWorkflow()
            dag.df([[1]], "a:long").yield_dataframe_as("x", as_local=True)
            res = dag.run(self.engine)
            assert res.yields["x"].result.as_array() == [[1]]

        def test_yield_file(self):
            self.engine.conf["fugue.workflow.checkpoint.path"] = os.path.join(
                self.tmpdir, "ck"
            )
            dag = FugueWorkflow()
            dag.df([[1]], "a:long").yield_file_as("x")
            res = dag.run(self.engine)
            dag2 = FugueWorkflow()
            dag2.df(res.yields["x"]).assert_eq(dag2.df([[1]], "a:long"))
            dag2.run(self.engine)

        # -- validation ------------------------------------------------------
        def test_partition_validation(self):
            # partitionby_has: a
            def need_a(df: pd.DataFrame) -> pd.DataFrame:
                return df

            dag = FugueWorkflow()
            src = dag.df([[1, 2]], "a:long,b:long")
            src.partition_by("a").transform(need_a, schema="*")
            with pytest.raises(FugueWorkflowCompileValidationError):
                dag2 = FugueWorkflow()
                src2 = dag2.df([[1, 2]], "a:long,b:long")
                src2.partition_by("b").transform(need_a, schema="*")
            dag.run(self.engine)

        def test_input_validation(self):
            # input_has: a
            def need_col(df: pd.DataFrame) -> pd.DataFrame:
                return df

            dag = FugueWorkflow()
            dag.df([[1]], "x:long").transform(need_col, schema="*")
            with pytest.raises(Exception):
                dag.run(self.engine)

        # -- callbacks -------------------------------------------------------
        def test_rpc_callback(self):
            from fugue_tpu.rpc.base import RPCHandler

            class Collector(RPCHandler):
                def __init__(self):
                    super().__init__()
                    self.values: List[int] = []

                def __call__(self, value: int) -> str:
                    self.values.append(value)
                    return "ok"

            collector = Collector()

            def report(df: pd.DataFrame, cb: callable) -> pd.DataFrame:
                cb(int(df["a"].sum()))
                return df

            dag = FugueWorkflow()
            src = dag.df([[1], [2]], "a:long")
            src.partition_by("a").transform(report, schema="*", callback=collector).show()
            dag.run(self.engine)
            assert sorted(collector.values) == [1, 2]

        def test_per_row_transform(self):
            def one(df: pd.DataFrame) -> pd.DataFrame:
                assert len(df) == 1
                return df

            dag = FugueWorkflow()
            src = dag.df([[1], [2], [3]], "a:long")
            src.per_row().transform(one, schema="*").assert_eq(src)
            dag.run(self.engine)

        def test_optional_callback_unset(self):
            from typing import Callable, Optional

            def f(df: pd.DataFrame, cb: Optional[Callable] = None) -> pd.DataFrame:
                assert cb is None
                return df

            dag = FugueWorkflow()
            src = dag.df([[1]], "a:long")
            src.transform(f, schema="*").assert_eq(src)
            dag.run(self.engine)

        def test_engine_param_in_creator(self):
            from fugue_tpu.execution import ExecutionEngine

            def make(e: ExecutionEngine) -> pd.DataFrame:
                assert isinstance(e, ExecutionEngine)
                return pd.DataFrame({"a": [e.get_current_parallelism()]})

            dag = FugueWorkflow()
            dag.create(make).yield_dataframe_as("x", as_local=True)
            dag.run(self.engine)
            assert dag.yields["x"].result.as_array()[0][0] >= 1

        # -- io through workflow --------------------------------------------
        def test_workflow_save_load(self):
            path = os.path.join(self.tmpdir, "wf.parquet")
            dag = FugueWorkflow()
            dag.df([[1, "a"]], "a:long,b:str").save(path)
            dag.run(self.engine)
            dag2 = FugueWorkflow()
            dag2.load(path).assert_eq(dag2.df([[1, "a"]], "a:long,b:str"))
            dag2.run(self.engine)

        # -- single-op api ---------------------------------------------------
        def test_transform_api(self):
            def f(df: pd.DataFrame) -> pd.DataFrame:
                df["b"] = 1
                return df

            res = transform(
                pd.DataFrame({"a": [1, 2]}),
                f,
                schema="*,b:long",
                engine=self.engine,
            )
            assert res.values.tolist() == [[1, 1], [2, 1]]

        def test_out_transform_api(self):
            hits: List[int] = []

            def f(df: pd.DataFrame) -> None:
                hits.append(len(df))

            out_transform(pd.DataFrame({"a": [1, 2]}), f, engine=self.engine)
            assert hits == [2]

        # -- parity additions (reference builtin_suite analogs) --------------
        def test_workflows(self):
            # multiple DAGs compute independently on one engine
            a = FugueWorkflow()
            a.df([[0]], "a:long").yield_dataframe_as("x", as_local=True)
            b = FugueWorkflow()
            b.df([[1]], "a:long").yield_dataframe_as("x", as_local=True)
            ra = a.run(self.engine)
            rb = b.run(self.engine)
            assert ra.yields["x"].result.as_array() == [[0]]
            assert rb.yields["x"].result.as_array() == [[1]]

        def test_datetime_in_workflow(self):
            import datetime

            # schema: a:date,b:datetime
            def t1(df: pd.DataFrame) -> pd.DataFrame:
                df["b"] = "2020-01-02"
                df["b"] = pd.to_datetime(df["b"])
                return df

            class T2(Transformer):
                def get_output_schema(self, df):
                    return df.schema

                def transform(self, df):
                    return PandasDataFrame(df.as_pandas())

            dag = FugueWorkflow()
            a = dag.df([["2020-01-01"]], "a:date").transform(t1)
            b = dag.df(
                [[datetime.date(2020, 1, 1), datetime.datetime(2020, 1, 2)]],
                "a:date,b:datetime",
            )
            b.assert_eq(a)
            c = dag.df(
                [["2020-01-01", "2020-01-01 00:00:00"]], "a:date,b:datetime"
            )
            c.transform(T2).assert_eq(c)
            c.partition(by=["a"]).transform(T2).assert_eq(c)
            dag.run(self.engine)

        def test_any_column_name(self):
            import fugue_tpu.api as fa
            from fugue_tpu.column import col

            f_parquet = os.path.join(self.tmpdir, "odd.parquet")

            # schema: *,`c *`:long
            def tr(df: pd.DataFrame) -> pd.DataFrame:
                return df.assign(**{"c *": 2})

            with fa.engine_context(self.engine):
                df1 = pd.DataFrame([[0, 1], [2, 3]], columns=["a b", " "])
                df2 = pd.DataFrame([[0, 10], [20, 3]], columns=["a b", "d"])
                r = fa.inner_join(df1, df2, as_fugue=True)
                assert r.as_array() == [[0, 1, 10]]
                assert str(r.schema) == "`a b`:long,` `:long,d:long"
                r = fa.transform(r, tr, as_fugue=True)
                assert r.as_array() == [[0, 1, 10, 2]]
                r = fa.select(
                    r,
                    col("a b").alias("a b "),
                    col(" ").alias("x y"),
                    col("d"),
                    col("c *"),
                    as_fugue=True,
                )
                assert str(r.schema) == "`a b `:long,`x y`:long,d:long,`c *`:long"
                r = fa.rename(r, {"a b ": "a b"}, as_fugue=True)
                fa.save(r, f_parquet)
                back = fa.load(
                    f_parquet, columns=["x y", "d", "c *"], as_fugue=True
                )
                assert back.as_array() == [[1, 10, 2]]

        def test_out_cotransform(self):
            from fugue_tpu import (
                CoTransformer,
                OutputCoTransformer,
                cotransformer,
            )

            hits: List[str] = []

            def t1(df: pd.DataFrame, df2: pd.DataFrame) -> pd.DataFrame:
                hits.append("t1")
                return df

            def t2(dfs: DataFrames) -> None:
                hits.append("t2")

            @cotransformer("a:double,b:long")
            def t4(df: pd.DataFrame, df2: pd.DataFrame) -> pd.DataFrame:
                hits.append("t4")
                return df

            class T6(CoTransformer):
                def get_output_schema(self, dfs):
                    return dfs[0].schema

                def transform(self, dfs):
                    hits.append("T6")
                    return dfs[0]

            class T7(OutputCoTransformer):
                def process(self, dfs):
                    hits.append("T7")

            def t8(df: pd.DataFrame, df2: pd.DataFrame) -> pd.DataFrame:
                hits.append("t8")
                raise NotImplementedError

            dag = FugueWorkflow()
            a0 = dag.df([[1.0, 2], [3.0, 4]], "a:double,b:long")
            a1 = dag.df([[1.0, 2], [3.0, 4]], "aa:double,b:long")
            a = a0.zip(a1)
            a.out_transform(t1)
            a.out_transform(t2)
            a.out_transform(t4)
            a.out_transform(T6)
            a.out_transform(T7)
            a.out_transform(t8, ignore_errors=[NotImplementedError])
            dag.run(self.engine)
            assert len(hits) >= 6
            for name in ["t1", "t2", "t4", "T6", "T7", "t8"]:
                assert name in hits

        def test_df_select(self):
            from fugue_tpu.column import col, functions as ff, lit

            dag = FugueWorkflow()
            a = dag.df([[1, 10], [2, 20], [3, 30]], "x:long,y:long")
            a.select("*").assert_eq(a)
            b = dag.df(
                [[1, 10, 11, "x"], [2, 20, 22, "x"], [3, 30, 33, "x"]],
                "x:long,y:long,c:long,d:str",
            )
            a.select(
                "*", (col("x") + col("y")).cast("int64").alias("c"), lit("x", "d")
            ).assert_eq(b)
            # distinct
            c = dag.df([[1, 10], [2, 20], [1, 10]], "x:long,y:long")
            d = dag.df([[1, 10], [2, 20]], "x:long,y:long")
            c.select("*", distinct=True).assert_eq(d)
            # aggregation + where/having
            e = dag.df([[1, 10], [1, 20], [3, 35], [3, 40]], "x:long,y:long")
            g = dag.df([[3, 35]], "x:long,z:long")
            e.select(
                "x",
                ff.sum(col("y")).alias("z").cast("int64"),
                where=col("y") < 40,
                having=ff.sum(col("y")) > 30,
            ).assert_eq(g)
            dag.run(self.engine)

        def test_df_filter(self):
            from fugue_tpu.column import col

            dag = FugueWorkflow()
            a = dag.df([[1, 10], [2, 20], [3, 30]], "x:long,y:long")
            b = dag.df([[2, 20]], "x:long,y:long")
            a.filter((col("y") > 15) & (col("y") < 25)).assert_eq(b)
            dag.run(self.engine)

        def test_df_assign(self):
            from fugue_tpu.column import col, lit

            dag = FugueWorkflow()
            a = dag.df([[1, 10], [2, 20], [3, 30]], "x:long,y:long")
            b = dag.df([[1, "x"], [2, "x"], [3, "x"]], "x:long,y:str")
            a.assign(y="x").assert_eq(b)
            c = dag.df([[1, 10], [2, 20], [3, 30]], "x:long,y:long")
            d = dag.df(
                [[1, "x", 11.0], [2, "x", 21.0], [3, "x", 31.0]],
                "x:long,y:str,z:double",
            )
            c.assign(lit("x").alias("y"), z=(col("y") + 1).cast(float)).assert_eq(d)
            dag.run(self.engine)

        def test_col_ops(self):
            dag = FugueWorkflow()
            a = dag.df([[1, 10, "x"]], "a:long,b:long,c:str")
            a.rename({"a": "aa"}).assert_eq(
                dag.df([[1, 10, "x"]], "aa:long,b:long,c:str")
            )
            a.drop(["c"]).assert_eq(dag.df([[1, 10]], "a:long,b:long"))
            a.drop(["c", "nope"], if_exists=True).assert_eq(
                dag.df([[1, 10]], "a:long,b:long")
            )
            a[["b", "c"]].assert_eq(dag.df([[10, "x"]], "b:long,c:str"))
            a.alter_columns("b:str").assert_eq(
                dag.df([[1, "10", "x"]], "a:long,b:str,c:str")
            )
            dag.run(self.engine)

        def test_extension_registry(self):
            from fugue_tpu.plugins import (
                parse_creator,
                parse_outputter,
                parse_processor,
                parse_transformer,
            )

            @parse_creator.candidate(
                lambda obj, **kw: isinstance(obj, str) and obj == "_reg_creator"
            )
            def _pc(obj: str):
                def _make() -> pd.DataFrame:
                    return pd.DataFrame({"a": [7]})

                return _make

            dag = FugueWorkflow()
            dag.create("_reg_creator", params=dict()).assert_eq(
                dag.df([[7]], "a:long")
            )
            dag.run(self.engine)

        def test_deterministic_checkpoint_complex_dag(self):
            self.engine.conf["fugue.workflow.checkpoint.path"] = os.path.join(
                self.tmpdir, "ckx"
            )
            calls: List[str] = []

            def src_a() -> pd.DataFrame:
                calls.append("a")
                return pd.DataFrame({"k": [1, 2], "v": [1.0, 2.0]})

            def src_b() -> pd.DataFrame:
                calls.append("b")
                return pd.DataFrame({"k": [1, 2], "w": [10.0, 20.0]})

            def build() -> FugueWorkflow:
                dag = FugueWorkflow()
                a = dag.create(src_a).deterministic_checkpoint()
                b = dag.create(src_b).deterministic_checkpoint()
                j = a.inner_join(b)
                j.deterministic_checkpoint().yield_dataframe_as(
                    "res", as_local=True
                )
                return dag

            r1 = build().run(self.engine).yields["res"].result.as_array()
            n1 = len(calls)
            r2 = build().run(self.engine).yields["res"].result.as_array()
            assert sorted(r1) == sorted(r2)
            # every creator resumed from its checkpoint on the second run
            assert len(calls) == n1


def _string_ref_transformer(df: pd.DataFrame) -> pd.DataFrame:
    return df
