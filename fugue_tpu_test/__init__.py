"""Reusable contract test suites for fugue-tpu backends.

Parity with the reference's ``fugue_test`` package (SURVEY.md §4): the same
suite classes run against every engine/frame implementation — in-tree and
third-party — so distributed semantics are exercised uniformly.
"""

from .bag_suite import BagTests
from .builtin_suite import BuiltInTests
from .dataframe_suite import DataFrameTests
from .execution_suite import ExecutionEngineTests, WarehouseSuiteOverrides

__all__ = [
    "BagTests",
    "BuiltInTests",
    "DataFrameTests",
    "ExecutionEngineTests",
    "WarehouseSuiteOverrides",
]
