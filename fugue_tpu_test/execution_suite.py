"""ExecutionEngine contract suite.

Modeled on the reference's ``fugue_test/execution_suite.py`` coverage
(``:35-1271``): to_df, map with every partition shape, joins of all types
with null keys, set ops, distinct/dropna/fillna, sample/take, zip/comap,
select/filter/assign/aggregate, save/load in all formats.
"""

import os
from datetime import datetime
from typing import Any, List

import pandas as pd
import pytest

from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff, lit, SelectColumns
from fugue_tpu.dataframe import (
    ArrayDataFrame,
    DataFrame,
    DataFrames,
    LocalDataFrame,
    PandasDataFrame,
)
from fugue_tpu.dataframe.utils import _df_eq
from fugue_tpu.execution import ExecutionEngine


class ExecutionEngineTests:
    """Subclass ``ExecutionEngineTests.Tests``; provide ``make_engine``."""

    class Tests:
        @pytest.fixture(autouse=True)
        def _setup_engine(self, tmp_path):
            self.engine: ExecutionEngine = self.make_engine()
            self.tmpdir = str(tmp_path)
            yield
            self.engine.stop()

        def make_engine(self) -> ExecutionEngine:
            raise NotImplementedError

        def df(self, data: Any, schema: Any) -> DataFrame:
            return self.engine.to_df(data, schema)

        # -- to_df -----------------------------------------------------------
        def test_to_df(self):
            e = self.engine
            assert _df_eq(e.to_df([[1, "a"]], "a:long,b:str"), [[1, "a"]], "a:long,b:str", throw=True)
            pdf = pd.DataFrame({"a": [1], "b": ["a"]})
            assert _df_eq(e.to_df(pdf), [[1, "a"]], "a:long,b:str", throw=True)
            fdf = ArrayDataFrame([[1, "a"]], "a:long,b:str")
            assert _df_eq(e.to_df(fdf), [[1, "a"]], "a:long,b:str", throw=True)

        # -- map -------------------------------------------------------------
        def test_map_no_partition(self):
            e = self.engine

            def m(cursor, df: LocalDataFrame) -> LocalDataFrame:
                rows = df.as_array(type_safe=True)
                return ArrayDataFrame([[len(rows)]], "ct:long")

            df = self.df([[i] for i in range(7)], "a:long")
            res = e.map_engine.map_dataframe(df, m, "ct:long", PartitionSpec())
            total = sum(r[0] for r in res.as_array(type_safe=True))
            assert total == 7

        def test_map_with_keys(self):
            e = self.engine

            def m(cursor, df: LocalDataFrame) -> LocalDataFrame:
                key = cursor.key_value_dict["a"]
                n = len(df.as_array())
                return ArrayDataFrame([[key, n]], "a:long,ct:long")

            df = self.df([[1, "x"], [2, "y"], [1, "z"], [None, "w"]], "a:double,b:str")
            res = e.map_engine.map_dataframe(
                df, m, "a:double,ct:long", PartitionSpec(by=["a"])
            )
            assert _df_eq(
                res, [[1, 2], [2, 1], [None, 1]], "a:double,ct:long", throw=True
            )

        def test_map_with_presort(self):
            e = self.engine

            def m(cursor, df: LocalDataFrame) -> LocalDataFrame:
                first = df.peek_array()
                return ArrayDataFrame([first], cursor.row_schema)

            df = self.df([[1, 3], [1, 1], [2, 5], [2, 9]], "a:long,b:long")
            res = e.map_engine.map_dataframe(
                df, m, "a:long,b:long", PartitionSpec(by=["a"], presort="b desc")
            )
            assert _df_eq(res, [[1, 3], [2, 9]], "a:long,b:long", throw=True)

        def test_map_empty_input(self):
            e = self.engine

            def m(cursor, df: LocalDataFrame) -> LocalDataFrame:
                return df

            df = self.df([], "a:long")
            res = e.map_engine.map_dataframe(df, m, "a:long", PartitionSpec(by=["a"]))
            assert res.as_array() == []

        def test_map_with_special_values(self):
            e = self.engine

            def m(cursor, df: LocalDataFrame) -> LocalDataFrame:
                return df

            data = [
                [1, "a", datetime(2020, 1, 1), b"\x00"],
                [2, None, None, None],
            ]
            df = self.df(data, "a:long,b:str,c:datetime,d:bytes")
            res = e.map_engine.map_dataframe(
                df, m, "a:long,b:str,c:datetime,d:bytes", PartitionSpec()
            )
            assert _df_eq(res, data, "a:long,b:str,c:datetime,d:bytes", throw=True)

        def test_map_with_dict_col(self):
            e = self.engine

            def m(cursor, df: LocalDataFrame) -> LocalDataFrame:
                return df

            data = [[dict(a=1, b="x")]]
            df = self.df(data, "m:{a:long,b:str}")
            res = e.map_engine.map_dataframe(df, m, "m:{a:long,b:str}", PartitionSpec())
            assert res.as_array(type_safe=True) == data

        def test_map_on_init(self):
            e = self.engine
            counter = []

            def on_init(no: int, df: Any) -> None:
                counter.append(no)

            def m(cursor, df: LocalDataFrame) -> LocalDataFrame:
                return df

            df = self.df([[1], [2]], "a:long")
            res = e.map_engine.map_dataframe(
                df, m, "a:long", PartitionSpec(by=["a"]), on_init=on_init
            )
            res.as_local_bounded()
            assert len(counter) >= 1

        # -- joins -----------------------------------------------------------
        def _join_dfs(self):
            df1 = self.df([[1, "a"], [2, "b"], [None, "c"]], "x:double,y:str")
            df2 = self.df([[1, 10.0], [3, 30.0], [None, 40.0]], "x:double,z:double")
            return df1, df2

        def test_inner_join(self):
            df1, df2 = self._join_dfs()
            res = self.engine.join(df1, df2, how="inner", on=["x"])
            assert _df_eq(res, [[1, "a", 10.0]], "x:double,y:str,z:double", throw=True)

        def test_left_outer_join(self):
            df1, df2 = self._join_dfs()
            res = self.engine.join(df1, df2, how="left_outer", on=["x"])
            assert _df_eq(
                res,
                [[1, "a", 10.0], [2, "b", None], [None, "c", None]],
                "x:double,y:str,z:double",
                throw=True,
            )

        def test_right_outer_join(self):
            df1, df2 = self._join_dfs()
            res = self.engine.join(df1, df2, how="right_outer", on=["x"])
            assert _df_eq(
                res,
                [[1, "a", 10.0], [3, None, 30.0], [None, None, 40.0]],
                "x:double,y:str,z:double",
                throw=True,
            )

        def test_full_outer_join(self):
            df1, df2 = self._join_dfs()
            res = self.engine.join(df1, df2, how="full_outer", on=["x"])
            assert res.count() == 5

        def test_semi_join(self):
            df1, df2 = self._join_dfs()
            res = self.engine.join(df1, df2, how="semi", on=["x"])
            assert _df_eq(res, [[1, "a"]], "x:double,y:str", throw=True)

        def test_anti_join(self):
            df1, df2 = self._join_dfs()
            res = self.engine.join(df1, df2, how="anti", on=["x"])
            assert _df_eq(res, [[2, "b"], [None, "c"]], "x:double,y:str", throw=True)

        def test_cross_join(self):
            df1 = self.df([[1], [2]], "a:long")
            df2 = self.df([["x"], ["y"]], "b:str")
            res = self.engine.join(df1, df2, how="cross")
            assert res.count() == 4

        def test_multi_key_join(self):
            df1 = self.df([[1, 1, "a"], [1, 2, "b"]], "x:long,y:long,v:str")
            df2 = self.df([[1, 1, "c"]], "x:long,y:long,w:str")
            res = self.engine.join(df1, df2, how="inner", on=["x", "y"])
            assert _df_eq(res, [[1, 1, "a", "c"]], "x:long,y:long,v:str,w:str", throw=True)

        # -- set ops ---------------------------------------------------------
        def test_union(self):
            df1 = self.df([[1], [2], [2]], "a:long")
            df2 = self.df([[2], [3]], "a:long")
            assert _df_eq(
                self.engine.union(df1, df2), [[1], [2], [3]], "a:long", throw=True
            )
            assert _df_eq(
                self.engine.union(df1, df2, distinct=False),
                [[1], [2], [2], [2], [3]],
                "a:long",
                throw=True,
            )

        def test_set_ops_with_nulls(self):
            # set-op semantics treat NULL = NULL (unlike join matching)
            df1 = self.df(
                [[1, "x"], [None, "y"], [None, "y"], [2, None]],
                "a:double,b:str",
            )
            df2 = self.df([[None, "y"], [2, None]], "a:double,b:str")
            assert _df_eq(
                self.engine.union(df1, df2),
                [[1, "x"], [None, "y"], [2, None]],
                "a:double,b:str",
                throw=True,
            )
            assert _df_eq(
                self.engine.subtract(df1, df2),
                [[1, "x"]],
                "a:double,b:str",
                throw=True,
            )
            assert _df_eq(
                self.engine.intersect(df1, df2),
                [[None, "y"], [2, None]],
                "a:double,b:str",
                throw=True,
            )

        def test_subtract_intersect(self):
            df1 = self.df([[1], [2], [2], [3]], "a:long")
            df2 = self.df([[2], [4]], "a:long")
            assert _df_eq(
                self.engine.subtract(df1, df2), [[1], [3]], "a:long", throw=True
            )
            assert _df_eq(
                self.engine.intersect(df1, df2), [[2]], "a:long", throw=True
            )

        def test_subtract(self):
            df1 = self.df([[1], [2], [2]], "a:long")
            df2 = self.df([[2]], "a:long")
            assert _df_eq(self.engine.subtract(df1, df2), [[1]], "a:long", throw=True)

        def test_intersect(self):
            df1 = self.df([[1], [2], [2]], "a:long")
            df2 = self.df([[2], [3]], "a:long")
            assert _df_eq(self.engine.intersect(df1, df2), [[2]], "a:long", throw=True)

        def test_distinct(self):
            df = self.df([[1, None], [1, None], [2, "x"]], "a:long,b:str")
            assert _df_eq(
                self.engine.distinct(df), [[1, None], [2, "x"]], "a:long,b:str", throw=True
            )

        # -- dropna/fillna ---------------------------------------------------
        def test_dropna(self):
            df = self.df([[1, "a"], [None, "b"], [None, None]], "a:double,b:str")
            assert self.engine.dropna(df).count() == 1
            assert self.engine.dropna(df, how="all").count() == 2
            assert self.engine.dropna(df, subset=["a"]).count() == 1
            assert self.engine.dropna(df, thresh=1).count() == 2

        def test_fillna(self):
            df = self.df([[1.0, "a"], [None, None]], "a:double,b:str")
            res = self.engine.fillna(df, value=0, subset=["a"])
            assert _df_eq(res, [[1.0, "a"], [0.0, None]], "a:double,b:str", throw=True)
            res2 = self.engine.fillna(df, value=dict(a=0.0, b="?"))
            assert _df_eq(res2, [[1.0, "a"], [0.0, "?"]], "a:double,b:str", throw=True)
            with pytest.raises(Exception):
                self.engine.fillna(df, value=None)

        # -- sample/take -----------------------------------------------------
        def test_sample(self):
            df = self.df([[i] for i in range(100)], "a:long")
            res = self.engine.sample(df, n=10, seed=0)
            assert res.count() == 10
            res2 = self.engine.sample(df, frac=0.1, seed=0)
            assert 0 < res2.count() < 50
            with pytest.raises(Exception):
                self.engine.sample(df, n=10, frac=0.1)

        def test_take(self):
            df = self.df(
                [[1, 5], [1, 3], [2, 9], [2, 2], [None, 1]], "a:double,b:long"
            )
            res = self.engine.take(df, 1, presort="b desc", partition_spec=PartitionSpec(by=["a"]))
            assert _df_eq(
                res, [[1, 5], [2, 9], [None, 1]], "a:double,b:long", throw=True
            )
            res2 = self.engine.take(df, 2, presort="b")
            assert _df_eq(res2, [[None, 1], [2, 2]], "a:double,b:long", throw=True)

        # -- zip/comap -------------------------------------------------------
        def test_zip_comap(self):
            e = self.engine
            df1 = self.df([[1, "a"], [1, "b"], [2, "c"]], "k:long,v:str")
            df2 = self.df([[1, 10.0], [3, 30.0]], "k:long,w:double")
            z = e.zip(DataFrames(df1, df2), how="inner", partition_spec=PartitionSpec(by=["k"]))

            def cm(cursor, dfs: DataFrames) -> LocalDataFrame:
                k = cursor.key_value_array[0]
                return ArrayDataFrame(
                    [[k, dfs[0].count(), dfs[1].count()]], "k:long,n1:long,n2:long"
                )

            res = e.comap(z, cm, "k:long,n1:long,n2:long")
            assert _df_eq(res, [[1, 2, 1]], "k:long,n1:long,n2:long", throw=True)

        def test_zip_comap_left(self):
            e = self.engine
            df1 = self.df([[1, "a"], [2, "c"]], "k:long,v:str")
            df2 = self.df([[1, 10.0]], "k:long,w:double")
            z = e.zip(
                DataFrames(df1, df2), how="left_outer", partition_spec=PartitionSpec(by=["k"])
            )

            def cm(cursor, dfs: DataFrames) -> LocalDataFrame:
                k = cursor.key_value_array[0]
                return ArrayDataFrame(
                    [[k, dfs[0].count(), dfs[1].count()]], "k:long,n1:long,n2:long"
                )

            res = e.comap(z, cm, "k:long,n1:long,n2:long")
            assert _df_eq(res, [[1, 1, 1], [2, 1, 0]], "k:long,n1:long,n2:long", throw=True)

        # -- derived ops -----------------------------------------------------
        def test_select(self):
            df = self.df([[1, 10.0], [2, 20.0], [2, 5.0]], "a:long,b:double")
            res = self.engine.select(
                df, SelectColumns(col("a"), (col("b") * lit(2)).cast(float).alias("bb"))
            )
            assert _df_eq(
                res, [[1, 20.0], [2, 40.0], [2, 10.0]], "a:long,bb:double", throw=True
            )

        def test_filter(self):
            df = self.df([[1, 10.0], [2, None]], "a:long,b:double")
            res = self.engine.filter(df, col("b").not_null())
            assert _df_eq(res, [[1, 10.0]], "a:long,b:double", throw=True)

        def test_assign(self):
            df = self.df([[1, "x"]], "a:long,b:str")
            res = self.engine.assign(df, [lit(5).alias("c"), (col("a") + 1).cast("long").alias("a")])
            assert _df_eq(res, [[2, "x", 5]], "a:long,b:str,c:long", throw=True)

        def test_aggregate(self):
            df = self.df([[1, 10.0], [1, 20.0], [2, 5.0]], "a:long,b:double")
            res = self.engine.aggregate(
                df,
                PartitionSpec(by=["a"]),
                [ff.sum(col("b")).alias("s"), ff.count(col("b")).alias("n")],
            )
            assert _df_eq(
                res, [[1, 30.0, 2], [2, 5.0, 1]], "a:long,s:double,n:long",
                check_schema=False, throw=True,
            )

        def test_aggregate_no_keys(self):
            df = self.df([[1, 10.0], [1, 20.0]], "a:long,b:double")
            res = self.engine.aggregate(df, None, [ff.max(col("b")).alias("m")])
            assert _df_eq(res, [[20.0]], "m:double", check_schema=False, throw=True)

        # -- io --------------------------------------------------------------
        @pytest.mark.parametrize("fmt", ["parquet", "csv", "json"])
        def test_save_load(self, fmt):
            e = self.engine
            path = os.path.join(self.tmpdir, f"x.{fmt}")
            df = self.df([[1, "a"], [2, "b"]], "a:long,b:str")
            kw = dict(header=True) if fmt == "csv" else {}
            e.save_df(df, path, **kw)
            res = e.load_df(path, columns="a:long,b:str", **(dict(header=True, infer_schema=True) if fmt == "csv" else {}))
            assert _df_eq(res, [[1, "a"], [2, "b"]], "a:long,b:str", throw=True)

        def test_save_mode(self):
            e = self.engine
            path = os.path.join(self.tmpdir, "y.parquet")
            df = self.df([[1]], "a:long")
            e.save_df(df, path)
            with pytest.raises(Exception):
                e.save_df(df, path, mode="error")
            e.save_df(df, path, mode="overwrite")

        # -- io matrix (reference execution_suite :1018-1271) ----------------
        def test_save_single_and_load_parquet(self):
            e = self.engine
            path = os.path.join(self.tmpdir, "a", "b")
            os.makedirs(path, exist_ok=True)
            # overwrite a folder with a single file
            b = self.df([[6, 1], [2, 7]], "c:int,a:long")
            e.save_df(b, path, format_hint="parquet", force_single=True)
            assert os.path.isfile(path)
            c = e.load_df(path, format_hint="parquet", columns=["a", "c"])
            assert _df_eq(c, [[1, 6], [7, 2]], "a:long,c:int", throw=True)
            # overwrite the single file again
            b2 = self.df([[60, 1], [20, 7]], "c:int,a:long")
            e.save_df(b2, path, format_hint="parquet", mode="overwrite")
            c = e.load_df(path, format_hint="parquet", columns=["a", "c"])
            assert _df_eq(c, [[1, 60], [7, 20]], "a:long,c:int", throw=True)

        def test_load_parquet_folder(self):
            e = self.engine
            path = os.path.join(self.tmpdir, "a", "b")
            os.makedirs(path, exist_ok=True)
            e.save_df(self.df([[6, 1]], "c:int,a:long"), os.path.join(path, "a.parquet"))
            e.save_df(
                self.df([[2, 7], [4, 8]], "c:int,a:long"),
                os.path.join(path, "b.parquet"),
            )
            open(os.path.join(path, "_SUCCESS"), "w").close()
            c = e.load_df(path, format_hint="parquet", columns=["a", "c"])
            assert _df_eq(
                c, [[1, 6], [7, 2], [8, 4]], "a:long,c:int", throw=True
            )

        def test_load_parquet_files(self):
            e = self.engine
            path = os.path.join(self.tmpdir, "a", "b")
            f1, f2 = os.path.join(path, "a.parquet"), os.path.join(path, "b.parquet")
            e.save_df(self.df([[6, 1]], "c:int,a:long"), f1)
            e.save_df(self.df([[2, 7], [4, 8]], "c:int,a:long"), f2)
            c = e.load_df([f1, f2], format_hint="parquet", columns=["a", "c"])
            assert _df_eq(
                c, [[1, 6], [7, 2], [8, 4]], "a:long,c:int", throw=True
            )

        def test_save_single_and_load_csv(self):
            e = self.engine
            path = os.path.join(self.tmpdir, "a", "b")
            os.makedirs(path, exist_ok=True)
            b = self.df([[6.1, 1.1], [2.1, 7.1]], "c:double,a:double")
            e.save_df(b, path, format_hint="csv", header=True, force_single=True)
            assert os.path.isfile(path)
            c = e.load_df(
                path,
                format_hint="csv",
                header=True,
                infer_schema=True,
                columns=["a", "c"],
            )
            assert _df_eq(
                c, [[1.1, 6.1], [7.1, 2.1]], "a:double,c:double", throw=True
            )

        def test_save_single_and_load_csv_no_header(self):
            e = self.engine
            path = os.path.join(self.tmpdir, "nh.csv")
            b = self.df([[6.1, 1.1], [2.1, 7.1]], "c:double,a:double")
            e.save_df(b, path, format_hint="csv", header=False)
            c = e.load_df(
                path, format_hint="csv", header=False, columns="c:double,a:double"
            )
            assert _df_eq(
                c, [[6.1, 1.1], [2.1, 7.1]], "c:double,a:double", throw=True
            )

        def test_load_csv_folder(self):
            e = self.engine
            path = os.path.join(self.tmpdir, "a", "b")
            os.makedirs(path, exist_ok=True)
            e.save_df(
                self.df([[6.1, 1.1]], "c:double,a:double"),
                os.path.join(path, "a.csv"),
                format_hint="csv",
                header=True,
            )
            e.save_df(
                self.df([[2.1, 7.1], [4.1, 8.1]], "c:double,a:double"),
                os.path.join(path, "b.csv"),
                format_hint="csv",
                header=True,
            )
            open(os.path.join(path, "_SUCCESS"), "w").close()
            c = e.load_df(
                path,
                format_hint="csv",
                header=True,
                infer_schema=True,
                columns=["a", "c"],
            )
            assert _df_eq(
                c,
                [[1.1, 6.1], [7.1, 2.1], [8.1, 4.1]],
                "a:double,c:double",
                throw=True,
            )

        def test_save_single_and_load_json(self):
            e = self.engine
            path = os.path.join(self.tmpdir, "a", "b")
            os.makedirs(path, exist_ok=True)
            b = self.df([[6, 1], [2, 7]], "c:long,a:long")
            e.save_df(b, path, format_hint="json", force_single=True)
            assert os.path.isfile(path)
            c = e.load_df(path, format_hint="json", columns=["a", "c"])
            assert _df_eq(c, [[1, 6], [7, 2]], "a:long,c:long", throw=True)

        def test_load_json_folder(self):
            e = self.engine
            path = os.path.join(self.tmpdir, "a", "b")
            os.makedirs(path, exist_ok=True)
            e.save_df(
                self.df([[6, 1], [3, 4]], "c:long,a:long"),
                os.path.join(path, "a.json"),
                format_hint="json",
            )
            e.save_df(
                self.df([[2, 7], [4, 8]], "c:long,a:long"),
                os.path.join(path, "b.json"),
                format_hint="json",
            )
            open(os.path.join(path, "_SUCCESS"), "w").close()
            c = e.load_df(path, format_hint="json", columns=["a", "c"])
            assert _df_eq(
                c, [[1, 6], [7, 2], [4, 3], [8, 4]], "a:long,c:long", throw=True
            )

        # -- persist/broadcast/repartition ----------------------------------
        def test_persist_broadcast(self):
            e = self.engine
            df = self.df([[1]], "a:long")
            assert _df_eq(e.persist(df), [[1]], "a:long", throw=True)
            assert _df_eq(e.broadcast(df), [[1]], "a:long", throw=True)
            assert _df_eq(
                e.repartition(df, PartitionSpec(num=2)), [[1]], "a:long", throw=True
            )

        def test_engine_context_api(self):
            from fugue_tpu.execution.api import engine_context, get_context_engine

            with engine_context(self.engine) as e:
                assert get_context_engine() is e

        # -- additional contract behaviors ----------------------------------
        def test_union_schema_mismatch_raises(self):
            df1 = self.df([[1]], "a:long")
            df2 = self.df([["x"]], "a:str")
            with pytest.raises(Exception):
                self.engine.union(df1, df2)

        def test_take_na_position_first(self):
            df = self.df([[1.0], [None], [3.0]], "a:double")
            res = self.engine.take(df, 1, presort="a", na_position="first")
            assert res.as_array(type_safe=True) == [[None]]

        def test_map_per_row(self):
            from fugue_tpu.dataframe import ArrayDataFrame

            def m(cursor, df):
                rows = df.as_array()
                assert len(rows) == 1
                return ArrayDataFrame([[rows[0][0] * 10]], "a:long")

            df = self.df([[1], [2], [3]], "a:long")
            res = self.engine.map_engine.map_dataframe(
                df, m, "a:long", PartitionSpec("per_row")
            )
            assert sorted(res.as_array()) == [[10], [20], [30]]

        def test_select_with_cast(self):
            df = self.df([[1]], "a:long")
            res = self.engine.select(
                df, SelectColumns(col("a").cast("str").alias("s"))
            )
            assert res.as_array(type_safe=True) == [["1"]]

        def test_comap_multiple_frames(self):
            e = self.engine
            d1 = self.df([[1, "a"]], "k:long,v:str")
            d2 = self.df([[1, 1.0], [1, 2.0]], "k:long,w:double")
            d3 = self.df([[1, True]], "k:long,b:bool")
            z = e.zip(
                DataFrames(d1, d2, d3), how="inner",
                partition_spec=PartitionSpec(by=["k"]),
            )

            def cm(cursor, dfs):
                assert len(dfs) == 3
                return ArrayDataFrame(
                    [[cursor.key_value_array[0], dfs[0].count(), dfs[1].count(), dfs[2].count()]],
                    "k:long,a:long,b:long,c:long",
                )

            res = e.comap(z, cm, "k:long,a:long,b:long,c:long")
            assert res.as_array() == [[1, 1, 2, 1]]
        # -- round-3 coverage: duplicate-key joins, outer/cross, SQL surface -
        def test_join_duplicate_keys(self):
            e = self.engine
            left = self.df([[1, 10.0], [2, 20.0], [3, 30.0]], "x:long,a:double")
            right = self.df(
                [[1, 1.0], [1, 2.0], [2, 3.0], [9, 9.0]], "x:long,b:double"
            )
            res = e.join(left, right, how="inner", on=["x"])
            assert _df_eq(
                res,
                [[1, 10.0, 1.0], [1, 10.0, 2.0], [2, 20.0, 3.0]],
                "x:long,a:double,b:double",
                throw=True,
            )
            lo = e.join(left, right, how="left_outer", on=["x"])
            assert lo.count() == 4
            semi = e.join(left, right, how="left_semi", on=["x"])
            assert sorted(r[0] for r in semi.as_array()) == [1, 2]
            anti = e.join(left, right, how="left_anti", on=["x"])
            assert sorted(r[0] for r in anti.as_array()) == [3]

        def test_right_and_full_outer_join(self):
            e = self.engine
            left = self.df([[1, 1.0], [2, 2.0]], "x:long,a:double")
            right = self.df([[2, 20.0], [3, 30.0]], "x:long,b:double")
            ro = e.join(left, right, how="right_outer", on=["x"])
            rows = sorted(ro.as_array(type_safe=True))
            assert rows == [[2, 2.0, 20.0], [3, None, 30.0]]
            fo = e.join(left, right, how="full_outer", on=["x"])
            rows = sorted(
                fo.as_array(type_safe=True), key=lambda r: (r[0] is None, r)
            )
            assert len(rows) == 3
            assert [1, 1.0, None] in rows and [3, None, 30.0] in rows

        def test_cross_join(self):
            e = self.engine
            a = self.df([[1], [2]], "x:long")
            b = self.df([["p"], ["q"], ["r"]], "y:str")
            res = e.join(a, b, how="cross")
            assert res.count() == 6
            assert sorted(res.as_array()) == sorted(
                [[i, s] for i in (1, 2) for s in ("p", "q", "r")]
            )

        def test_sql_grouping_sets(self):
            e = self.engine
            from fugue_tpu.collections.sql import StructuredRawSQL

            df = self.df(
                [[1, "a", 1.0], [1, "b", 2.0], [2, "b", 3.0]],
                "x:long,y:str,v:double",
            )
            res = e.sql_engine.select(
                DataFrames(t=df),
                StructuredRawSQL.from_expr(
                    "SELECT x, y, SUM(v) AS s FROM <tmpdf:t> GROUP BY ROLLUP(x, y)"
                ),
            )
            rows = res.as_array(type_safe=True)
            assert len(rows) == 3 + 2 + 1
            assert [None, None, 6.0] in rows

        def test_sql_correlated_exists(self):
            e = self.engine
            from fugue_tpu.collections.sql import StructuredRawSQL

            a = self.df([[1], [2], [3]], "x:long")
            b = self.df([[2], [2], [3]], "x:long")
            res = e.sql_engine.select(
                DataFrames(a=a, b=b),
                StructuredRawSQL.from_expr(
                    "SELECT * FROM <tmpdf:a> WHERE EXISTS "
                    "(SELECT 1 FROM <tmpdf:b> WHERE <tmpdf:b>.x = <tmpdf:a>.x)"
                ),
            )
            assert sorted(r[0] for r in res.as_array()) == [2, 3]

        def test_sql_window_over_strings(self):
            e = self.engine
            from fugue_tpu.collections.sql import StructuredRawSQL

            df = self.df(
                [["a", 3.0], ["a", 1.0], ["b", 2.0]], "g:str,v:double"
            )
            res = e.sql_engine.select(
                DataFrames(t=df),
                StructuredRawSQL.from_expr(
                    "SELECT g, ROW_NUMBER() OVER "
                    "(PARTITION BY g ORDER BY v) AS rn FROM <tmpdf:t>"
                ),
            )
            rows = sorted(res.as_array())
            assert rows == [["a", 1], ["a", 2], ["b", 1]]



class WarehouseSuiteOverrides:
    """Engine-suite cases a sqlite-backed warehouse engine legitimately
    can't serve, skipped with reasons — mix into suite subclasses (the
    reference pattern: backend test files subclass the suites and
    override/skip, reference tests/fugue/execution/test_naive_execution_engine.py:14-31).
    """

    def test_map_with_dict_col(self):
        pytest.skip("nested (struct/list) columns have no sqlite storage class")

    def test_sql_grouping_sets(self):
        pytest.skip(
            "sqlite has no ROLLUP/GROUPING SETS; the in-tree SQL executor "
            "serves those on non-warehouse engines"
        )
