"""DataFrame contract suite — run against every frame type.

Modeled on the reference's ``fugue_test/dataframe_suite.py`` coverage: init,
conversions, nulls, nested types, binary, datetimes, alter/rename/drop/head,
and iteration semantics.
"""

from datetime import date, datetime
from typing import Any

import pandas as pd
import pytest

from fugue_tpu.dataframe import DataFrame
from fugue_tpu.dataframe.utils import _df_eq
from fugue_tpu.exceptions import (
    FugueDataFrameOperationError,
    FugueDatasetEmptyError,
)


class DataFrameTests:
    """Subclass ``DataFrameTests.Tests`` and implement ``df()``."""

    class Tests:
        def df(self, data: Any = None, schema: Any = None) -> DataFrame:
            raise NotImplementedError

        # -- init & basics ---------------------------------------------------
        def test_init_basic(self):
            df = self.df([[1, "a"], [2, "b"]], "x:long,y:str")
            assert df.schema == "x:long,y:str"
            assert [x.name for x in df.schema.fields] == ["x", "y"]
            assert df.columns == ["x", "y"]
            assert not df.empty
            if df.is_bounded:
                assert df.count() == 2

        def test_peek(self):
            df = self.df([[1, "a"]], "x:long,y:str")
            assert df.peek_array() == [1, "a"]
            assert df.peek_dict() == dict(x=1, y="a")
            edf = self.df([], "x:long,y:str")
            with pytest.raises(FugueDatasetEmptyError):
                edf.peek_array()

        def test_empty(self):
            df = self.df([], "x:long")
            assert df.empty
            assert df.as_array() == []

        def test_as_array(self):
            # one-pass frames are single-consumption: rebuild per assertion
            assert self.df([[1, "a"], [2, None]], "x:long,y:str").as_array() == [
                [1, "a"], [2, None],
            ]
            assert self.df([[1, "a"], [2, None]], "x:long,y:str").as_array(
                columns=["y", "x"]
            ) == [["a", 1], [None, 2]]
            assert list(
                self.df([[1, "a"], [2, None]], "x:long,y:str").as_array_iterable()
            ) == [[1, "a"], [2, None]]

        def test_as_dicts(self):
            assert self.df([[1, "a"]], "x:long,y:str").as_dicts() == [dict(x=1, y="a")]
            assert list(self.df([[1, "a"]], "x:long,y:str").as_dict_iterable()) == [
                dict(x=1, y="a")
            ]

        def test_nulls(self):
            df = self.df([[None, None]], "x:double,y:str")
            assert df.as_array(type_safe=True) == [[None, None]]

        def test_bool_nulls(self):
            df = self.df([[True], [None], [False]], "x:bool")
            assert df.as_array(type_safe=True) == [[True], [None], [False]]

        def test_binary(self):
            df = self.df([[b"\x01\x02"]], "x:bytes")
            assert df.as_array(type_safe=True) == [[b"\x01\x02"]]

        def test_datetimes(self):
            d = date(2020, 1, 2)
            ts = datetime(2020, 1, 2, 3, 4, 5)
            df = self.df([[d, ts]], "x:date,y:datetime")
            row = df.as_array(type_safe=True)[0]
            assert row[0] == d
            assert row[1] == ts

        def test_nested_types(self):
            df = self.df([[[1, 2], dict(a=1)]], "x:[long],y:{a:long}")
            row = df.as_array(type_safe=True)[0]
            assert row[0] == [1, 2]
            assert row[1] == dict(a=1)

        def test_map_type(self):
            df = self.df([[[("a", 1), ("b", 2)]]], "x:<str,long>")
            row = df.as_array(type_safe=True)[0]
            assert sorted(row[0]) == [("a", 1), ("b", 2)]

        # -- conversions ----------------------------------------------------
        def test_as_pandas(self):
            df = self.df([[1, "a"], [2, "b"]], "x:long,y:str")
            pdf = df.as_pandas()
            assert isinstance(pdf, pd.DataFrame)
            assert pdf.values.tolist() == [[1, "a"], [2, "b"]]

        def test_as_arrow(self):
            df = self.df([[1, "a"]], "x:long,y:str")
            tbl = df.as_arrow()
            assert tbl.num_rows == 1
            assert tbl.column_names == ["x", "y"]

        def test_as_local(self):
            df = self.df([[1]], "x:long")
            local = df.as_local()
            assert local.is_local
            assert _df_eq(local, [[1]], "x:long", throw=True)

        # -- ops ------------------------------------------------------------
        def test_rename(self):
            df = self.df([[1, "a"]], "x:long,y:str")
            r = df.rename({"x": "xx"})
            assert r.schema == "xx:long,y:str"
            assert r.as_array() == [[1, "a"]]
            with pytest.raises(Exception):
                df.rename({"not_exist": "z"})

        def test_drop_select(self):
            df = self.df([[1, "a", 2.0]], "x:long,y:str,z:double")
            assert df.drop(["y"]).schema == "x:long,z:double"
            assert df[["z", "x"]].schema == "z:double,x:long"
            with pytest.raises(FugueDataFrameOperationError):
                df.drop(["x", "y", "z"])
            with pytest.raises(FugueDataFrameOperationError):
                df.drop(["not_exist"])

        def test_alter_columns(self):
            df = self.df([[1, "2"]], "x:long,y:str")
            r = df.alter_columns("x:double,y:int")
            assert r.schema == "x:double,y:int"
            assert r.as_array(type_safe=True) == [[1.0, 2]]
            same = df.alter_columns("x:long")
            assert same.schema == df.schema

        def test_head(self):
            df = self.df([[i] for i in range(5)], "x:long")
            h = df.head(3)
            assert h.is_local and h.is_bounded
            assert h.count() == 3
            h2 = self.df([[i, "a"] for i in range(5)], "x:long,y:str").head(
                2, columns=["y"]
            )
            assert h2.schema == "y:str"

        def test_show(self, capsys: Any = None):
            df = self.df([[1, "a"]], "x:long,y:str")
            df.show()

        def test_alter_columns_invalid(self):
            df = self.df([["a"]], "x:str")
            with pytest.raises(Exception):
                r = df.alter_columns("x:[long]")
                r.as_array()

        def test_as_array_special_values(self):
            # NaN / None / NaT mixtures survive typed extraction: type-safe
            # extraction renders float NaN as NULL (None)
            rows = self.df(
                [[1.0, None], [float("nan"), "x"]], "a:double,b:str"
            ).as_array(type_safe=True)
            assert rows[0] == [1.0, None]
            assert rows[1][1] == "x" and (
                rows[1][0] is None or rows[1][0] != rows[1][0]
            )
            rows = self.df(
                [[1.0, None], [None, "x"]], "a:double,b:str"
            ).as_array(type_safe=True)
            assert rows[0] == [1.0, None]
            assert rows[1][1] == "x" and rows[1][0] is None
            ts = datetime(2021, 5, 6, 7, 8)
            rows = self.df([[ts], [None]], "t:datetime").as_array(
                type_safe=True
            )
            assert rows[0][0] == ts and rows[1][0] is None

        def test_as_dict_iterable_specials(self):
            rows = list(
                self.df(
                    [[1, None], [None, "b"]], "x:long,y:str"
                ).as_dict_iterable()
            )
            assert rows == [dict(x=1, y=None), dict(x=None, y="b")]

        def test_rename_invalid(self):
            df = self.df([[1]], "x:long")
            with pytest.raises(Exception):
                df.rename({"nonexistent": "y"})

        def test_get_column_names(self):
            from fugue_tpu.dataframe.api import get_column_names

            df = self.df([[1, "a", 2.0]], "x:long,y:str,z:double")
            assert get_column_names(df) == ["x", "y", "z"]

        def test_rename_any_names(self):
            df = self.df([[1, "a"]], "x:long,y:str")
            r = df.rename({"x": "a b", "y": "c.d"})
            assert r.schema.names == ["a b", "c.d"]
            assert r.as_array() == [[1, "a"]]

        def test_deep_nested_types(self):
            # structs of lists and lists of structs round-trip
            df = self.df(
                [[dict(a=[1, 2], b="x")]], "c:{a:[long],b:str}"
            )
            row = df.as_array(type_safe=True)[0]
            assert row[0] == dict(a=[1, 2], b="x")
            df2 = self.df([[[dict(a=1), dict(a=2)]]], "c:[{a:long}]")
            row2 = df2.as_array(type_safe=True)[0]
            assert row2[0] == [dict(a=1), dict(a=2)]
