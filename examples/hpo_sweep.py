"""Distributed hyperparameter sweep (BASELINE config #5).

Each hyperparameter configuration is one logical partition; ``transform``
fits/evaluates per partition in parallel across the engine — the same
pattern the reference uses with sklearn/XGBoost per Spark/Ray worker, here
with a numpy model so the example runs anywhere.

Run: python examples/hpo_sweep.py
"""

import os
import sys

# allow running the example straight from a checkout
if "__file__" in globals():
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd

import fugue_tpu.api as fa

rng = np.random.default_rng(0)
X = rng.normal(size=(512, 4))
w_true = np.array([1.0, -2.0, 0.5, 3.0])
y = X @ w_true + rng.normal(scale=0.1, size=512)


# schema: lr:double,steps:long,mse:double
def fit_eval(df: pd.DataFrame) -> pd.DataFrame:
    lr = float(df["lr"].iloc[0])
    steps = int(df["steps"].iloc[0])
    w = np.zeros(4)
    for _ in range(steps):  # plain gradient descent as the stand-in trainer
        grad = X.T @ (X @ w - y) / len(y)
        w -= lr * grad
    mse = float(np.mean((X @ w - y) ** 2))
    return pd.DataFrame({"lr": [lr], "steps": [steps], "mse": [mse]})


def main() -> None:
    grid = pd.DataFrame(
        [(lr, s) for lr in (0.01, 0.05, 0.1) for s in (50, 200)],
        columns=["lr", "steps"],
    )
    res = fa.transform(grid, fit_eval, partition={"by": ["lr", "steps"]})
    best = res.sort_values("mse").head(3)
    print(best.to_string(index=False))


if __name__ == "__main__":
    main()
