"""Out-of-core streaming: a dataset bigger than memory, end to end.

The pipeline is the north-star shape (`python bench.py --north-star` runs
it at a literal 1B rows): group means via the STREAMING dense aggregate
(device-resident accumulators), a broadcast-hash join of the stream
against the means table, and a compiled subtract — device memory stays
O(chunk), independent of the dataset. Then the same stream goes through
a keyed running-window UDF (``group_ops.row_number``/``running_sum``).

Run:  python examples/streaming_pipeline.py [--cpu] [--rows N]
(--cpu forces the 8-device virtual mesh; default rows = 10M so the
example finishes in seconds.)
"""

import argparse
import os
import sys
import time
from typing import Dict

parser = argparse.ArgumentParser()
parser.add_argument("--cpu", action="store_true", help="8-device virtual CPU mesh")
parser.add_argument("--rows", type=int, default=10_000_000)
parser.add_argument("--groups", type=int, default=10_000)
parser.add_argument("--chunk", type=int, default=1_000_000)
args = parser.parse_args()

if args.cpu:
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
import jax

if args.cpu:
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pandas as pd

import fugue_tpu.api as fa
from fugue_tpu.collections import PartitionSpec
from fugue_tpu.column import col, functions as ff
from fugue_tpu.dataframe import LocalDataFrameIterableDataFrame, PandasDataFrame
from fugue_tpu.jax import JaxExecutionEngine, group_ops as go, streaming

N, GROUPS, CHUNK = args.rows, args.groups, args.chunk
n_chunks = (N + CHUNK - 1) // CHUNK


def stream() -> LocalDataFrameIterableDataFrame:
    """Chunks are GENERATED on the fly — the dataset never exists in full."""

    def gen():
        for i in range(n_chunks):
            rng = np.random.default_rng(i)
            n = min(CHUNK, N - i * CHUNK)
            yield PandasDataFrame(
                pd.DataFrame(
                    {"k": rng.integers(0, GROUPS, n), "v": rng.random(n)}
                ),
                "k:long,v:double",
            )

    return LocalDataFrameIterableDataFrame(gen(), schema="k:long,v:double")


eng = JaxExecutionEngine(
    {
        "fugue.tpu.stream.key_range": f"0,{GROUPS - 1}",
        "fugue.tpu.stream.chunk_rows": CHUNK,
    }
)
print(f"mesh: {len(jax.devices())} x {jax.devices()[0].platform}; "
      f"{N:,} rows in {n_chunks} chunks")

# ---- pass 1: group means (streaming dense aggregate) ----------------------
t0 = time.perf_counter()
means = eng.aggregate(
    stream(), PartitionSpec(by=["k"]), [ff.avg(col("v")).alias("m")]
)
print(f"streaming aggregate: {GROUPS:,} groups in "
      f"{time.perf_counter() - t0:.1f}s  (peak device bytes "
      f"{streaming.last_run_stats['peak_device_bytes']:,})")

# ---- pass 2: broadcast join + compiled subtract (groupby-demean) ----------


def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    return {"k": cols["k"], "d": cols["v"] - cols["m"]}


joined = eng.join(stream(), means, how="inner")
out = fa.transform(joined, demean, schema="k:long,d:double", engine=eng, as_fugue=True)
rows, total = 0, 0.0
for part in out.native:  # one-pass consumption
    p = part.as_pandas()
    rows += len(p)
    total += float(p["d"].sum())
wall = time.perf_counter() - t0
assert rows == N and abs(total) < 1.0  # each group's demeaned values sum to ~0
print(f"north-star pipeline: {N:,} rows in {wall:.1f}s = {N / wall:,.0f} rows/s")

# ---- running windows over a key-clustered stream --------------------------
clustered = pd.DataFrame({"k": np.repeat(np.arange(200), 500)})
clustered["v"] = np.random.default_rng(0).random(len(clustered))


def windows(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    return {
        "k": cols["k"],
        "rn": go.row_number(cols),
        "rs": go.running_sum(cols, cols["v"]),
        "prev": go.lag(cols, cols["v"]),
    }


def clustered_stream():
    def gen():
        for s in range(0, len(clustered), 7_000):
            yield PandasDataFrame(clustered.iloc[s : s + 7_000], "k:long,v:double")

    return LocalDataFrameIterableDataFrame(gen(), schema="k:long,v:double")


w = fa.transform(
    clustered_stream(),
    windows,
    schema="k:long,rn:long,rs:double,prev:double",
    partition=PartitionSpec(by=["k"], presort="v"),
    engine=eng,
    as_fugue=True,
).as_pandas()
sp = clustered.sort_values(["k", "v"]).reset_index(drop=True)
assert np.allclose(
    w.sort_values(["k", "rn"])["rs"].to_numpy(),
    sp.groupby("k")["v"].cumsum().to_numpy(),
)
print(f"streaming windows: ROW_NUMBER/running SUM/LAG over "
      f"{len(clustered):,} key-clustered rows ok")
sys.exit(0)
