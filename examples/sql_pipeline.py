"""FugueSQL pipeline showing round-2 capabilities:

- mixed-engine scripts (CONNECT runs one statement on another engine),
- window frames (ROWS/RANGE, SQL-standard RANGE-with-peers default),
- string/nullable columns staying device-resident on the jax engine.

Run: python examples/sql_pipeline.py   (uses the 8-device CPU mesh when no
TPU is reachable; same code drives a real TPU mesh unchanged)
"""

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

try:  # fall back to the virtual CPU mesh when no TPU is attached
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import numpy as np
import pandas as pd

from fugue_tpu.sql import fugue_sql

rng = np.random.default_rng(0)
orders = pd.DataFrame(
    {
        "region": rng.choice(["north", "south", "east", None], 10_000).tolist(),
        "day": rng.integers(1, 31, 10_000),
        "amount": rng.random(10_000) * 100,
    }
)

result = fugue_sql(
    """
    -- groupby with a transformed, unprojected key on the DEVICE engine
    daily = CONNECT jax SELECT region, day, SUM(amount) AS total
            FROM orders WHERE region IS NOT NULL GROUP BY region, day

    -- running totals per region: SQL-standard RANGE frame with peers
    SELECT region, day, total,
           SUM(total) OVER (PARTITION BY region ORDER BY day) AS running,
           AVG(total) OVER (PARTITION BY region ORDER BY day
                            ROWS BETWEEN 6 PRECEDING AND CURRENT ROW) AS avg7d
    FROM daily
    ORDER BY region, day
    """
)

print(result.head(10).to_string(index=False))
