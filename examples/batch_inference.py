"""Batch embedding inference with a compiled transformer (BASELINE config #4).

The flagship ML-inference pattern: wrap a jax model's forward pass as a
``Dict[str, jax.Array]`` transformer; ``transform()`` runs it as ONE
``shard_map`` across the TPU mesh — each shard computes its rows' embeddings
on its own chip, with zero per-row Python.

Run: python examples/batch_inference.py [--cpu]
(--cpu forces an 8-device virtual CPU mesh; the TPU plugin overrides the
JAX_PLATFORMS env var, so the flag is the reliable switch)
"""

import os
import sys

if "--cpu" in sys.argv:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

# allow running the example straight from a checkout
if "__file__" in globals():
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

import fugue_tpu.api as fa

D_IN, D_HIDDEN, D_OUT = 8, 64, 4

# a stand-in encoder: in real use this is a flax/haiku model's apply fn
rng = np.random.default_rng(0)
W1 = jnp.asarray(rng.normal(size=(D_IN, D_HIDDEN)), dtype=jnp.float32)
W2 = jnp.asarray(rng.normal(size=(D_HIDDEN, D_OUT)), dtype=jnp.float32)


def embed(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    x = jnp.stack([cols[f"f{i}"] for i in range(D_IN)], axis=1).astype(jnp.float32)
    h = jax.nn.relu(x @ W1)  # weights are closure constants → replicated
    e = h @ W2
    out = {"id": cols["id"]}
    for i in range(D_OUT):
        out[f"e{i}"] = e[:, i].astype(jnp.float64)
    return out


def main() -> None:
    n = 10_000
    df = pd.DataFrame({"id": np.arange(n)})
    for i in range(D_IN):
        df[f"f{i}"] = rng.normal(size=n)

    schema = "id:long," + ",".join(f"e{i}:double" for i in range(D_OUT))
    res = fa.transform(df, embed, schema=schema, engine="tpu")
    print(res.head(3))
    print(f"embedded {len(res)} rows -> {D_OUT}-dim")


if __name__ == "__main__":
    main()
