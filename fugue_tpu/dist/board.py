"""The shared task board: durable dispatch state on a shared filesystem.

Everything the worker tier coordinates through lives under one root::

    <root>/tasks/<tid>.task.json     task specs (supervisor writes once)
    <root>/done/<tid>.done.json      done records — O_CREAT|O_EXCL, so the
                                     FIRST publisher wins and a speculative
                                     or steal-raced duplicate loses cleanly
    <root>/fail/<tid>.<uuid>.json    one record per failed attempt
                                     (category from the PR 1 taxonomy)
    <root>/spec/<tid>.spec           straggler hints (supervisor marks,
                                     idle workers volunteer)
    <root>/leases/                   task leases (:mod:`.lease`)
    <root>/hb/                       worker heartbeats (:mod:`.heartbeat`)
    <root>/store/                    the shared content-addressed
                                     ArtifactStore (reduce outputs)
    <root>/jobs/<jid>.job.json       job manifests (supervisor restart)
    <root>/workers/<wid>/            per-worker data dirs (shuffle
                                     fragments — served over HTTP when the
                                     filesystem is NOT shared)

A task is *runnable* when it has a spec, no done record, and every dep's
done record exists. Every mutation is an atomic create or rename, so any
process (or any restart of one) reads a consistent board: the recovery
story is "look at the files", not "replay my memory".
"""

import base64
import hashlib
import json
import os
import uuid as _uuid
from typing import Any, Dict, List, Optional

import cloudpickle

from ..workflow._checkpoint import _best_effort_remove

__all__ = ["TaskBoard", "spec_fingerprint", "dump_fn", "load_fn"]


def dump_fn(fn: Any) -> Optional[str]:
    """A callable as base64 cloudpickle (None stays None)."""
    if fn is None:
        return None
    return base64.b64encode(cloudpickle.dumps(fn)).decode()


def load_fn(blob: Optional[str]) -> Any:
    if not blob:
        return None
    return cloudpickle.loads(base64.b64decode(blob))


def spec_fingerprint(*parts: Any) -> str:
    """Deterministic content address for a task's output: md5 over the
    json-stable parts (input file tokens, function payloads, bucket ids…)
    — speculative duplicates and steal re-runs compute the SAME id, so
    the artifact store dedups their publishes by construction."""
    h = hashlib.md5()
    for p in parts:
        h.update(json.dumps(p, sort_keys=True, default=str).encode())
        h.update(b"\x00")
    return h.hexdigest()


class TaskBoard:
    """File-backed task state under one shared root."""

    def __init__(self, root: str):
        self.root = root
        self.tasks_dir = os.path.join(root, "tasks")
        self.done_dir = os.path.join(root, "done")
        self.fail_dir = os.path.join(root, "fail")
        self.spec_dir = os.path.join(root, "spec")
        self.leases_dir = os.path.join(root, "leases")
        self.hb_dir = os.path.join(root, "hb")
        self.store_dir = os.path.join(root, "store")
        self.jobs_dir = os.path.join(root, "jobs")
        self.workers_dir = os.path.join(root, "workers")
        for d in (
            self.tasks_dir,
            self.done_dir,
            self.fail_dir,
            self.spec_dir,
            self.leases_dir,
            self.hb_dir,
            self.store_dir,
            self.jobs_dir,
            self.workers_dir,
        ):
            os.makedirs(d, exist_ok=True)

    # -- paths ---------------------------------------------------------------
    def _task(self, tid: str) -> str:
        return os.path.join(self.tasks_dir, f"{tid}.task.json")

    def _done(self, tid: str) -> str:
        return os.path.join(self.done_dir, f"{tid}.done.json")

    def _spec_mark(self, tid: str) -> str:
        return os.path.join(self.spec_dir, f"{tid}.spec")

    def _job(self, jid: str) -> str:
        return os.path.join(self.jobs_dir, f"{jid}.job.json")

    def worker_data_dir(self, worker_id: str) -> str:
        d = os.path.join(self.workers_dir, worker_id)
        os.makedirs(d, exist_ok=True)
        return d

    # -- atomic json ---------------------------------------------------------
    @staticmethod
    def _write_json(final: str, payload: Dict[str, Any]) -> None:
        tmp = f"{final}.__tmp_{_uuid.uuid4().hex}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, final)

    @staticmethod
    def _read_json(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except Exception:
            return None  # torn mid-replace read: retry next scan

    # -- tasks ---------------------------------------------------------------
    def put_task(self, tid: str, spec: Dict[str, Any]) -> None:
        doc = dict(spec, id=tid)
        if "trace" not in doc:
            # cluster tracing (ISSUE 18): a spec written inside a traced
            # run carries the run's {"trace", "parent"} so the executing
            # worker's spans attach under the submitting run
            from ..obs.tracer import trace_carrier

            carrier = trace_carrier()
            if carrier:
                doc["trace"] = carrier
        self._write_json(self._task(tid), doc)

    def read_task(self, tid: str) -> Optional[Dict[str, Any]]:
        return self._read_json(self._task(tid))

    def list_tasks(self) -> List[str]:
        try:
            names = os.listdir(self.tasks_dir)
        except OSError:
            return []
        return sorted(
            n[: -len(".task.json")] for n in names if n.endswith(".task.json")
        )

    # -- done records (first publish wins) -----------------------------------
    def publish_done(self, tid: str, payload: Dict[str, Any]) -> bool:
        """O_CREAT|O_EXCL: exactly one executor's record survives. False
        = another executor (speculative twin, steal racer) already
        published — the caller's work was redundant, not wrong; its
        artifact publishes were deduped by content address."""
        path = self._done(tid)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            return False
        try:
            data = json.dumps(dict(payload, task=tid)).encode()
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        return True

    def read_done(self, tid: str) -> Optional[Dict[str, Any]]:
        return self._read_json(self._done(tid))

    def invalidate_done(self, tid: str) -> bool:
        """Orphaned-output recovery: a consumer that PROVED a done
        record's outputs unreachable (dead producer, torn fragment)
        deletes the record — the task becomes runnable again and a live
        worker re-executes it. Deterministic tasks re-produce identical
        bytes, so consumers that already read the old outputs stay
        consistent with consumers of the new ones."""
        path = self._done(tid)
        existed = os.path.exists(path)
        _best_effort_remove(path)
        return existed

    def done_count(self, tids: List[str]) -> int:
        return sum(1 for t in tids if os.path.exists(self._done(t)))

    # -- failure records -----------------------------------------------------
    def record_failure(
        self, tid: str, worker: str, category: str, error: str
    ) -> None:
        path = os.path.join(
            self.fail_dir, f"{tid}.{_uuid.uuid4().hex[:8]}.json"
        )
        self._write_json(
            path,
            {"task": tid, "worker": worker, "category": category, "error": error},
        )

    def failures(self, tid: str) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        try:
            names = os.listdir(self.fail_dir)
        except OSError:
            return out
        for n in sorted(names):
            if n.startswith(tid + ".") and n.endswith(".json"):
                rec = self._read_json(os.path.join(self.fail_dir, n))
                if rec is not None:
                    out.append(rec)
        return out

    # -- speculation ---------------------------------------------------------
    def mark_speculative(self, tid: str) -> bool:
        path = self._spec_mark(tid)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            return True
        except OSError:
            return False

    def is_speculative(self, tid: str) -> bool:
        return os.path.exists(self._spec_mark(tid))

    # -- job manifests -------------------------------------------------------
    def put_job(self, jid: str, manifest: Dict[str, Any]) -> None:
        self._write_json(self._job(jid), dict(manifest, id=jid))

    def read_job(self, jid: str) -> Optional[Dict[str, Any]]:
        return self._read_json(self._job(jid))
