"""Fault-tolerant multi-host worker tier (docs/distributed.md).

N engine processes (:class:`DistWorker`) coordinate over a shared task
board + the HTTP layer; a :class:`DistSupervisor` plans distributed
load → shuffle → reduce jobs, watches leases/heartbeats, and recovers
dead workers by re-dispatch. ``fugue.tpu.dist.enabled=false`` restores
single-process execution bit-identically.
"""

from .board import TaskBoard, dump_fn, load_fn, spec_fingerprint
from .heartbeat import (
    DEFAULT_INTERVAL_S,
    DEFAULT_STALE_AFTER_S,
    HeartbeatWriter,
    heartbeat_age_s,
    holder_alive,
    read_heartbeat,
)
from .lease import LeaseBoard
from .stats import DistStats
from .supervisor import DistJobError, DistSupervisor
from .worker import BucketUnavailableError, DistWorker

__all__ = [
    "BucketUnavailableError",
    "DEFAULT_INTERVAL_S",
    "DEFAULT_STALE_AFTER_S",
    "DistJobError",
    "DistStats",
    "DistSupervisor",
    "DistWorker",
    "HeartbeatWriter",
    "LeaseBoard",
    "TaskBoard",
    "dump_fn",
    "heartbeat_age_s",
    "holder_alive",
    "load_fn",
    "read_heartbeat",
    "spec_fingerprint",
]
