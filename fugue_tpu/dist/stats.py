"""Worker-tier counters — a ``MetricsRegistry`` source.

One :class:`DistStats` lives on the supervisor's engine (registered as
``engine.stats()["dist"]``) and one inside each worker. Workers ship
their snapshot home inside every heartbeat (``stats`` key) and every done
record, so the supervisor's ``as_dict()`` can fold a ``workers``
breakdown in without any extra channel — the same ship-home shape fork
workers use for span/histogram deltas. ``reset()`` zeroes counters and
keeps the worker breakdown's identities (the JitCache contract).
"""

import threading
from typing import Any, Dict

__all__ = ["DistStats"]

_COUNTERS = (
    "jobs",
    "jobs_failed",
    "map_tasks",
    "reduce_tasks",
    "tasks_completed",
    "tasks_failed",
    "leases_acquired",
    "leases_renewed",
    "leases_stolen",
    "redispatch_worker_lost",
    "redispatch_transient",
    "speculative_marks",
    "speculative_wins",
    "speculative_losses",
    "fragments_written",
    "fragments_local",
    "fragments_remote",
    "fetch_failures",
    "orphaned_outputs_recovered",
    "artifacts_published",
    "rows_in",
    "rows_out",
    # distributed WORKFLOW jobs (run_workflow_job): one fragment of a
    # workflow DAG routed through the board. Dispatch/steal/speculative/
    # invalidation activity observed while a workflow job is in flight is
    # attributed to that job (before/after deltas — approximate only if
    # unrelated jobs run concurrently on the same supervisor).
    "workflow_jobs",
    "workflow_tasks_dispatched",
    "workflow_tasks_re_dispatched",
    "workflow_tasks_stolen",
    "workflow_tasks_speculative",
    "workflow_fragments_invalidated",
    "workflow_partitions_delta_skipped",
)


class DistStats:
    """Thread-safe counters + a per-worker snapshot breakdown."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._c: Dict[str, float] = {}
        self._workers: Dict[str, Dict[str, Any]] = {}

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n

    def get(self, name: str) -> float:
        with self._lock:
            return self._c.get(name, 0)

    def note_worker(self, worker_id: str, snapshot: Dict[str, Any]) -> None:
        """Fold one shipped-home counter snapshot for one worker. The
        worker's counters are MONOTONIC lifetime totals, so snapshots
        from different channels (heartbeats, done records) merge by
        element-wise max — a lagging beat can never roll a fresher
        done-record snapshot back."""
        snap = {k: v for k, v in snapshot.items() if k != "workers"}
        with self._lock:
            cur = self._workers.setdefault(worker_id, {})
            for k, v in snap.items():
                if isinstance(v, (int, float)) and isinstance(
                    cur.get(k), (int, float)
                ):
                    cur[k] = max(cur[k], v)
                else:
                    cur[k] = v

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {k: self._c.get(k, 0) for k in _COUNTERS}
            for k, v in self._c.items():
                if k not in out:
                    out[k] = v
            if self._workers:
                out["workers"] = {w: dict(s) for w, s in self._workers.items()}
        # re-dispatch classification is decided at the steal site (the
        # worker's LeaseBoard, where the liveness evidence is) and shipped
        # home; the supervisor-facing totals fold the worker breakdown in
        for w in out.get("workers", {}).values():
            out["redispatch_worker_lost"] += w.get("leases_stolen_dead", 0)
            out["redispatch_transient"] += w.get("leases_stolen_expired", 0)
        return out

    def reset(self) -> None:
        with self._lock:
            self._c = {}
