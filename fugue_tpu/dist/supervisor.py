"""The supervisor: plans distributed jobs, watches the board, recovers.

:class:`DistSupervisor` makes a multi-worker run look like one engine
call (the Cylon execution-environment shape, arXiv:2301.07896): a *job*
is a distributed load → network-partitioned shuffle → per-bucket reduce
→ combine, expressed as plain pandas functions and executed by however
many :class:`~fugue_tpu.dist.worker.DistWorker` processes are watching
the shared board. The supervisor itself never executes tasks (except on
the kill-switch path) — it writes task specs, watches done/fail/lease
state, classifies re-dispatches under the PR 1 taxonomy, marks
stragglers speculative, and combines the content-addressed reduce
artifacts into the final frame.

Recovery ladder (docs/distributed.md), all of it observable in
``engine.stats()["dist"]``:

1. an attempt that RAISES records a categorized failure and releases its
   lease — TRANSIENT/TIMEOUT/WORKER_LOST re-dispatch to any live worker;
   POISON (deterministic user-code failure) aborts the job with the
   per-task report; attempts are bounded by ``fugue.tpu.retry.dist.*``;
2. a worker that DIES mid-task stops heartbeating — its lease reads
   stealable and a live worker re-executes (``redispatch_worker_lost``);
3. a completed task whose OUTPUT became unreachable (producer SIGKILLed
   before consumers fetched, torn fragment) is invalidated by the
   consumer and re-runs (``orphaned_outputs_recovered``);
4. a LIVE owner that straggles past ``fugue.tpu.dist.speculative_after_s``
   gets a speculative twin; the first done-record publish wins and the
   loser's artifact publishes dedup by content address.

Kill-switch: ``fugue.tpu.dist.enabled=false`` routes ``run_*`` through
``_run_serial`` — the SAME map/bucket/reduce/combine functions, the same
bucket order, in this process — bit-identical by construction.
"""

import os
import time
import uuid as _uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import pandas as pd
import pyarrow as pa

from ..obs.events import get_event_log
from ..obs.tracer import proc_ident
from ..resilience import RetryPolicy
from ..shuffle.partitioner import bucket_ids, canonical_key_kinds
from .board import TaskBoard, dump_fn, load_fn, spec_fingerprint
from .heartbeat import DEFAULT_STALE_AFTER_S, read_heartbeat
from .lease import LeaseBoard
from .stats import DistStats
from .worker import _empty_frame, apply_map, read_source_paths

__all__ = ["DistSupervisor", "DistJobError"]


class DistJobError(RuntimeError):
    """Terminal job failure (poison task, attempts exhausted, timeout).
    Carries a per-task ``report``."""

    def __init__(self, message: str, report: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.report = dict(report or {})


def _default_combine(partials: List[pd.DataFrame]) -> pd.DataFrame:
    if not partials:
        return pd.DataFrame()
    return pd.concat(partials, ignore_index=True)


def _chunk(paths: List[str], per_task: int) -> List[List[str]]:
    per_task = max(1, int(per_task))
    return [paths[i : i + per_task] for i in range(0, len(paths), per_task)]


def _file_token(path: str) -> List[Any]:
    try:
        st = os.stat(path)
        return [path, int(st.st_size), int(st.st_mtime_ns)]
    except OSError:
        return [path, 0, 0]


def _fields(schema: pa.Schema) -> Dict[str, Any]:
    """Name-indexable view of an arrow schema (what
    ``canonical_key_kinds`` expects — fugue Schemas index by name; this
    pyarrow build's ``Schema.__getitem__`` is position-only)."""
    return {n: schema.field(n) for n in schema.names}


class DistSupervisor:
    """Location-transparent job execution over the worker tier."""

    def __init__(
        self,
        root: str,
        engine: Any = None,
        conf: Optional[Dict[str, Any]] = None,
    ):
        from ..constants import (
            FUGUE_TPU_CONF_DIST_BUCKETS,
            FUGUE_TPU_CONF_DIST_ENABLED,
            FUGUE_TPU_CONF_DIST_HB_STALE_S,
            FUGUE_TPU_CONF_DIST_POLL_S,
            FUGUE_TPU_CONF_DIST_SPECULATIVE_AFTER_S,
        )

        if engine is None:
            from ..execution import NativeExecutionEngine

            engine = NativeExecutionEngine(dict(conf or {}))
        self.engine = engine
        # explicit conf overlays the engine's: workflow.run passes its
        # RUN-scoped merge here so workflow-level dist knobs apply without
        # writing through to the engine
        c = dict(engine.conf)
        c.update(dict(conf or {}))
        self.board = TaskBoard(root)
        self.enabled = bool(c.get(FUGUE_TPU_CONF_DIST_ENABLED, True))
        self.default_buckets = int(c.get(FUGUE_TPU_CONF_DIST_BUCKETS, 8))
        self.poll_s = max(0.005, float(c.get(FUGUE_TPU_CONF_DIST_POLL_S, 0.05)))
        self.speculative_after_s = float(
            c.get(FUGUE_TPU_CONF_DIST_SPECULATIVE_AFTER_S, 0.0)
        )
        self.hb_stale_s = float(
            c.get(FUGUE_TPU_CONF_DIST_HB_STALE_S, DEFAULT_STALE_AFTER_S)
        )
        self.stats = DistStats()
        self.retry_policy = RetryPolicy.from_conf(
            c, prefix="fugue.tpu.retry.dist", default_attempts=4
        )
        self.leases = LeaseBoard(
            self.board.leases_dir,
            hb_dir=self.board.hb_dir,
            hb_stale_s=self.hb_stale_s,
        )
        # the supervisor's counters ride its engine's unified registry:
        # engine.stats()["dist"] (with a per-worker breakdown shipped
        # home in heartbeats/done records)
        engine.metrics.register("dist", self.stats)

    # -- planning ------------------------------------------------------------
    def _probe_side(
        self, paths: List[str], fn_blob: Optional[str]
    ) -> Tuple[Dict[str, str], pa.Schema]:
        """Post-map column dtypes + arrow schema of one side, probed on an
        EMPTY typed frame so planning never runs user code over real rows
        (map functions should tolerate empty frames; one that doesn't is
        probed on a small head instead — documented caveat). A function
        that fails BOTH probes degrades to the pre-map schema: planning
        never raises user-code errors — those surface at task time where
        the POISON ladder owns them."""
        sample = read_source_paths(paths[:1])
        fn = load_fn(fn_blob)
        empty = sample.head(0)
        if fn is not None:
            try:
                empty = fn(sample.head(0).copy()).head(0)
            except Exception:
                try:
                    empty = fn(sample.head(8).copy()).head(0)
                except Exception:
                    empty = sample.head(0)
        columns = {c: str(empty[c].dtype) for c in empty.columns}
        return columns, pa.Table.from_pandas(empty, preserve_index=False).schema

    def plan_join_job(
        self,
        left_paths: List[str],
        right_paths: Optional[List[str]],
        keys: List[str],
        reduce_fn: Callable[..., pd.DataFrame],
        combine_fn: Optional[Callable[[List[pd.DataFrame]], pd.DataFrame]] = None,
        map_left: Optional[Callable[[pd.DataFrame], pd.DataFrame]] = None,
        map_right: Optional[Callable[[pd.DataFrame], pd.DataFrame]] = None,
        buckets: Optional[int] = None,
        paths_per_task: int = 1,
        job_id: Optional[str] = None,
    ) -> str:
        """Write one job to the board: per-range map tasks (distributed
        Load) for each side, one reduce task per bucket depending on all
        of them. Returns the job id; workers start the moment specs land.
        The manifest (cloudpickled functions included) persists under
        ``jobs/`` so a restarted supervisor resumes with ``wait_job``."""
        jid = job_id or "j" + _uuid.uuid4().hex[:10]
        n_buckets = int(buckets or self.default_buckets)
        sides: List[Dict[str, Any]] = [
            {"name": "left", "paths": list(left_paths), "fn": dump_fn(map_left)}
        ]
        if right_paths is not None:
            sides.append(
                {"name": "right", "paths": list(right_paths), "fn": dump_fn(map_right)}
            )
        schemas: List[pa.Schema] = []
        for side in sides:
            side["ranges"] = _chunk(side["paths"], paths_per_task)
            side["columns"], schema = self._probe_side(side["paths"], side["fn"])
            schemas.append(schema)
        kinds = canonical_key_kinds(
            _fields(schemas[0]), _fields(schemas[-1]), list(keys)
        )
        if kinds is None:
            raise DistJobError(
                f"join keys {list(keys)} have no canonical hashable dtype "
                "across the sides (decimal/binary/nested, or string vs "
                "numeric) — the distributed exchange cannot co-bucket them"
            )
        reduce_blob = dump_fn(reduce_fn)
        combine_blob = dump_fn(combine_fn or _default_combine)
        map_tids: List[str] = []
        for side in sides:
            tids = []
            for i, rng in enumerate(side["ranges"]):
                tid = f"{jid}-m-{side['name']}-{i:04d}"
                self.board.put_task(
                    tid,
                    {
                        "kind": "map",
                        "job": jid,
                        "paths": rng,
                        "fn": side["fn"],
                        "fp": spec_fingerprint(
                            jid, "map", side["name"], [_file_token(p) for p in rng]
                        ),
                        "shuffle": {
                            "exchange": side["name"],
                            "keys": list(keys),
                            "kinds": kinds,
                            "buckets": n_buckets,
                        },
                        "deps": [],
                    },
                )
                tids.append(tid)
            side["map_tids"] = tids
            map_tids.extend(tids)
        reduce_tids: List[str] = []
        all_columns = {s["name"]: s["columns"] for s in sides}
        for b in range(n_buckets):
            tid = f"{jid}-r-{b:04d}"
            self.board.put_task(
                tid,
                {
                    "kind": "reduce",
                    "job": jid,
                    "bucket": b,
                    "fn": reduce_blob,
                    "columns": all_columns,
                    "exchanges": {
                        s["name"]: {"producers": s["map_tids"]} for s in sides
                    },
                    "fp": spec_fingerprint(jid, "reduce", b, map_tids),
                    "deps": list(map_tids),
                },
            )
            reduce_tids.append(tid)
        self.board.put_job(
            jid,
            {
                "buckets": n_buckets,
                "keys": list(keys),
                "kinds": kinds,
                "sides": [
                    {
                        "name": s["name"],
                        "ranges": s["ranges"],
                        "fn": s["fn"],
                        "map_tids": s["map_tids"],
                        "columns": s["columns"],
                    }
                    for s in sides
                ],
                "reduce_tids": reduce_tids,
                "reduce_fn": reduce_blob,
                "combine": combine_blob,
                "created": time.time(),
            },
        )
        self.stats.inc("jobs")
        self.stats.inc("map_tasks", len(map_tids))
        self.stats.inc("reduce_tasks", len(reduce_tids))
        return jid

    # -- workflow jobs (fugue_tpu/plan/distribute.py routes through here) ----
    def plan_workflow_job(
        self,
        left_paths: List[str],
        right_paths: Optional[List[str]],
        keys: List[str],
        reduce_fn: Callable[..., pd.DataFrame],
        combine_fn: Optional[Callable[[List[pd.DataFrame]], pd.DataFrame]] = None,
        map_left: Optional[Callable[[pd.DataFrame], pd.DataFrame]] = None,
        map_right: Optional[Callable[[pd.DataFrame], pd.DataFrame]] = None,
        buckets: Optional[int] = None,
        paths_per_task: int = 1,
        tokens: Optional[Dict[str, str]] = None,
    ) -> Tuple[str, List[str]]:
        """Plan one WORKFLOW fragment as a board job. Identical spec and
        manifest shapes to :meth:`plan_join_job` (so the whole recovery
        ladder, ``wait_job`` and ``audit_job`` apply unchanged), but task
        ids and artifact fps are CONTENT-ADDRESSED — a deterministic
        fingerprint over the fragment's logic token (the planner's
        description of map/reduce steps) and each partition range's file
        tokens (path, size, mtime) instead of a fresh job uuid. A warm
        rerun therefore finds done records already on the board for every
        unchanged partition and delta-skips them: only map tasks over new
        or changed files (and the reduces downstream of the changed map
        set) execute. Returns ``(jid, all_tids)``; the count of reused
        done records lands in ``workflow_partitions_delta_skipped``."""
        toks = dict(tokens or {})
        n_buckets = int(buckets or self.default_buckets)
        sides: List[Dict[str, Any]] = [
            {"name": "left", "paths": list(left_paths), "fn": dump_fn(map_left)}
        ]
        if right_paths is not None:
            sides.append(
                {
                    "name": "right",
                    "paths": list(right_paths),
                    "fn": dump_fn(map_right),
                }
            )
        schemas: List[pa.Schema] = []
        for side in sides:
            side["ranges"] = _chunk(side["paths"], paths_per_task)
            side["columns"], schema = self._probe_side(side["paths"], side["fn"])
            schemas.append(schema)
        kinds = canonical_key_kinds(
            _fields(schemas[0]), _fields(schemas[-1]), list(keys)
        )
        if kinds is None:
            raise DistJobError(
                f"shuffle keys {list(keys)} have no canonical hashable dtype "
                "across the sides — the distributed exchange cannot "
                "co-bucket them"
            )
        reduce_blob = dump_fn(reduce_fn)
        combine_blob = dump_fn(combine_fn or _default_combine)
        reduce_token = toks.get("reduce", "")
        map_tids: List[str] = []
        skipped = 0
        for side in sides:
            side_token = toks.get(side["name"], "")
            tids = []
            for rng in side["ranges"]:
                tid = "wfm-" + spec_fingerprint(
                    "map",
                    side["name"],
                    side_token,
                    list(keys),
                    kinds,
                    n_buckets,
                    [_file_token(p) for p in rng],
                )[:20]
                skipped += int(self.board.read_done(tid) is not None)
                self.board.put_task(
                    tid,
                    {
                        "kind": "map",
                        # fragment rel paths embed the content-addressed
                        # tid, so a constant job dir keeps reruns pointing
                        # at the same (reusable) fragments
                        "job": "wf",
                        "paths": rng,
                        "fn": side["fn"],
                        "fp": spec_fingerprint("wf-map-art", tid),
                        "shuffle": {
                            "exchange": side["name"],
                            "keys": list(keys),
                            "kinds": kinds,
                            "buckets": n_buckets,
                        },
                        "deps": [],
                    },
                )
                tids.append(tid)
            side["map_tids"] = tids
            map_tids.extend(tids)
        reduce_tids: List[str] = []
        all_columns = {s["name"]: s["columns"] for s in sides}
        for b in range(n_buckets):
            tid = "wfr-" + spec_fingerprint(
                "reduce", reduce_token, b, map_tids
            )[:20]
            skipped += int(self.board.read_done(tid) is not None)
            self.board.put_task(
                tid,
                {
                    "kind": "reduce",
                    "job": "wf",
                    "bucket": b,
                    "fn": reduce_blob,
                    "columns": all_columns,
                    "exchanges": {
                        s["name"]: {"producers": s["map_tids"]} for s in sides
                    },
                    "fp": spec_fingerprint("wf-reduce-art", tid),
                    "deps": list(map_tids),
                },
            )
            reduce_tids.append(tid)
        jid = "wfj" + spec_fingerprint(
            reduce_token,
            [toks.get(s["name"], "") for s in sides],
            map_tids,
            reduce_tids,
        )[:16]
        self.board.put_job(
            jid,
            {
                "buckets": n_buckets,
                "keys": list(keys),
                "kinds": kinds,
                "sides": [
                    {
                        "name": s["name"],
                        "ranges": s["ranges"],
                        "fn": s["fn"],
                        "map_tids": s["map_tids"],
                        "columns": s["columns"],
                    }
                    for s in sides
                ],
                "reduce_tids": reduce_tids,
                "reduce_fn": reduce_blob,
                "combine": combine_blob,
                "created": time.time(),
            },
        )
        all_tids = map_tids + reduce_tids
        self.stats.inc("jobs")
        self.stats.inc("map_tasks", len(map_tids))
        self.stats.inc("reduce_tasks", len(reduce_tids))
        self.stats.inc("workflow_jobs")
        self.stats.inc("workflow_tasks_dispatched", len(all_tids) - skipped)
        self.stats.inc("workflow_partitions_delta_skipped", skipped)
        return jid, all_tids

    def run_workflow_job(
        self,
        left_paths: List[str],
        right_paths: Optional[List[str]],
        keys: List[str],
        reduce_fn: Callable[..., pd.DataFrame],
        *,
        combine_fn: Optional[Callable[[List[pd.DataFrame]], pd.DataFrame]] = None,
        map_left: Optional[Callable[[pd.DataFrame], pd.DataFrame]] = None,
        map_right: Optional[Callable[[pd.DataFrame], pd.DataFrame]] = None,
        buckets: Optional[int] = None,
        paths_per_task: int = 1,
        tokens: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ) -> pd.DataFrame:
        """One workflow fragment end to end: plan (content-addressed,
        delta-skipping) + wait, with the job's recovery activity
        attributed to the ``workflow_*`` counters. The kill-switch
        (``fugue.tpu.dist.enabled=false``) runs the identical plan
        serially in this process — bit-identical by construction."""
        if not self.enabled:
            return self._run_serial(
                left_paths,
                right_paths,
                keys,
                reduce_fn,
                combine_fn=combine_fn,
                map_left=map_left,
                map_right=map_right,
                buckets=buckets,
                paths_per_task=paths_per_task,
            )
        before = self.stats.as_dict()
        jid, all_tids = self.plan_workflow_job(
            left_paths,
            right_paths,
            keys,
            reduce_fn,
            combine_fn=combine_fn,
            map_left=map_left,
            map_right=map_right,
            buckets=buckets,
            paths_per_task=paths_per_task,
            tokens=tokens,
        )
        fails_before = sum(
            1
            for t in all_tids
            for f in self.board.failures(t)
            if f.get("category") != "poison"
        )
        try:
            return self.wait_job(jid, timeout=timeout)
        finally:
            self._account_workflow(all_tids, before, fails_before)

    def _account_workflow(
        self,
        tids: List[str],
        before: Dict[str, Any],
        fails_before: int,
    ) -> None:
        """Fold the recovery activity observed while a workflow job was
        in flight into the workflow counters (before/after deltas over
        the folded supervisor+worker totals — attributed to the observing
        job, approximate only when unrelated jobs share the supervisor)."""
        after = self.stats.as_dict()

        def total(d: Dict[str, Any], name: str, fold_workers: bool) -> int:
            t = int(d.get(name, 0) or 0)
            if fold_workers:
                for w in (d.get("workers") or {}).values():
                    t += int(w.get(name, 0) or 0)
            return t

        for counter, name, fold in (
            # steal classification is already folded into the redispatch
            # totals by as_dict; orphan/speculative need the worker fold
            ("workflow_tasks_stolen", "redispatch_worker_lost", False),
            ("workflow_tasks_stolen", "redispatch_transient", False),
            ("workflow_fragments_invalidated", "orphaned_outputs_recovered", True),
            ("workflow_tasks_speculative", "speculative_marks", True),
        ):
            d = total(after, name, fold) - total(before, name, fold)
            if d > 0:
                self.stats.inc(counter, d)
        fails_now = sum(
            1
            for t in tids
            for f in self.board.failures(t)
            if f.get("category") != "poison"
        )
        if fails_now > fails_before:
            self.stats.inc(
                "workflow_tasks_re_dispatched", fails_now - fails_before
            )

    # -- monitoring / recovery ----------------------------------------------
    def _abort(self, jid: str, why: str, tids: List[str]) -> None:
        report = {
            t: [f"{r['category']}: {r['error']}" for r in self.board.failures(t)]
            for t in tids
            if self.board.failures(t)
        }
        self.stats.inc("jobs_failed")
        raise DistJobError(f"dist job {jid} failed: {why}", report)

    def _watch_once(self, jid: str, tids: List[str]) -> None:
        """One monitoring pass: bound failures, mark stragglers
        speculative. (Re-dispatch classification happens at the steal
        site, inside whichever worker's LeaseBoard stole the lease, and
        ships home in its counters — a fast steal between two supervisor
        polls is never missed.)"""
        now = time.time()
        for tid in tids:
            if self.board.read_done(tid) is not None:
                continue
            fails = self.board.failures(tid)
            poison = [f for f in fails if f.get("category") == "poison"]
            if poison:
                self._abort(
                    jid, f"task {tid} failed deterministically (poison)", tids
                )
            if len(fails) >= self.retry_policy.max_attempts:
                self._abort(
                    jid,
                    f"task {tid} exhausted {len(fails)} attempts "
                    f"(max {self.retry_policy.max_attempts})",
                    tids,
                )
            lease = self.leases.read(tid)
            if lease is None:
                continue
            if (
                self.speculative_after_s > 0
                and not self.board.is_speculative(tid)
                and not self.leases.stealable(lease)
            ):
                acquired = float(lease.get("acquired_ts", lease.get("ts", now)))
                if now - acquired > self.speculative_after_s:
                    if self.board.mark_speculative(tid):
                        self.stats.inc("speculative_marks")
                        get_event_log().emit(
                            "task.speculative",
                            task=tid,
                            holder=lease.get("owner"),
                            held_s=round(now - acquired, 3),
                        )

    def wait_job(self, jid: str, timeout: Optional[float] = None) -> pd.DataFrame:
        """Block until every reduce task is done, then combine their
        artifacts (in bucket order). Safe to call from a RESTARTED
        supervisor: all job state — manifest, specs, leases, done
        records — lives on the board, so in-flight leases simply
        continue (or expire and re-dispatch) under the new watcher."""
        from ..cache.store import ArtifactStore
        from ..obs import get_tracer

        manifest = self.board.read_job(jid)
        if manifest is None:
            raise DistJobError(f"unknown dist job {jid!r} (no manifest)")
        reduce_tids: List[str] = manifest["reduce_tids"]
        all_tids = [
            t for s in manifest["sides"] for t in s["map_tids"]
        ] + reduce_tids
        deadline = None if timeout is None else time.monotonic() + timeout
        store = ArtifactStore(self.board.store_dir, cap_bytes=0)
        tracer = get_tracer()
        with tracer.span("dist.job", cat="dist", job=jid, tasks=len(all_tids)):
            while True:
                while self.board.done_count(reduce_tids) < len(reduce_tids):
                    self._watch_once(jid, all_tids)
                    if deadline is not None and time.monotonic() > deadline:
                        self._abort(
                            jid, f"timed out after {timeout}s", all_tids
                        )
                    time.sleep(self.poll_s)
                partials: List[pd.DataFrame] = []
                missing = None
                for tid in reduce_tids:
                    rec = self.board.read_done(tid)
                    if rec is None:
                        missing = tid
                        break
                    loaded = store.load(rec["fp"], self.engine)
                    if loaded is None:
                        # torn/evicted artifact: recovery ladder rung 3 —
                        # invalidate and let a live worker re-produce it
                        self.board.invalidate_done(tid)
                        self.stats.inc("orphaned_outputs_recovered")
                        get_event_log().emit(
                            "task.orphan",
                            task=tid,
                            why="torn/evicted reduce artifact",
                            producer=rec.get("worker"),
                        )
                        missing = tid
                        break
                    partials.append(loaded[0].as_pandas())
                if missing is None:
                    break
        # fold worker-shipped counters home from BOTH channels — map/
        # reduce done records and the latest heartbeats. Counters are
        # monotonic and note_worker merges by max, so channel lag (a
        # GIL-starved beat thread) can never under-report
        for tid in all_tids:
            rec = self.board.read_done(tid)
            if rec is not None:
                self._ingest_done(rec, tracer)
        for name in os.listdir(self.board.hb_dir):
            if name.endswith(".hb.json"):
                hb = read_heartbeat(self.board.hb_dir, name[: -len(".hb.json")])
                if hb is not None and isinstance(hb.get("stats"), dict):
                    self.stats.note_worker(str(hb.get("name")), hb["stats"])
        combine = load_fn(manifest["combine"]) or _default_combine
        self.stats.inc("tasks_completed", len(all_tids))
        return combine(partials)

    def _ingest_done(self, rec: Dict[str, Any], tracer: Any) -> None:
        """Worker-shipped observability, the fork-worker protocol shape:
        spans ingest into this process's tracer, counters land in the
        per-worker breakdown of ``engine.stats()["dist"]``."""
        spans = rec.get("spans")
        if spans and tracer.enabled:
            # an IN-process worker (thread-pool tests, single-host runs)
            # shares this tracer and already emitted its spans — only
            # foreign PROCESSES' records are new information. Identity is
            # host+pid (proc_ident): a bare pid match would wrongly drop a
            # remote host's spans that happen to share this pid
            me = proc_ident()
            spans = [
                s for s in spans if (s.get("proc") or s.get("pid")) not in (me, os.getpid())
            ]
            tracer.ingest(spans)
        m = rec.get("metrics")
        if (
            isinstance(m, dict)
            and m.get("delta")
            and m.get("proc") not in (proc_ident(), None)
        ):
            # metrics federation (ISSUE 18): a remote worker's span-
            # histogram delta merges into the driver's families with the
            # associative encoding — driver /metrics covers the fleet.
            # An in-process worker shares these families (its proc is
            # ours) and is skipped: its observations already landed.
            from ..obs import get_span_metrics

            get_span_metrics().merge(m["delta"])
        if isinstance(rec.get("stats"), dict) and rec.get("worker"):
            self.stats.note_worker(str(rec["worker"]), rec["stats"])

    def run_join_job(self, *args: Any, timeout: Optional[float] = None, **kwargs: Any) -> pd.DataFrame:
        """Plan + wait — or, with ``fugue.tpu.dist.enabled=false``, run
        the identical job serially in this process (bit-identical)."""
        if not self.enabled:
            return self._run_serial(*args, **kwargs)
        jid = self.plan_join_job(*args, **kwargs)
        return self.wait_job(jid, timeout=timeout)

    # -- the kill-switch path ------------------------------------------------
    def _run_serial(
        self,
        left_paths: List[str],
        right_paths: Optional[List[str]],
        keys: List[str],
        reduce_fn: Callable[..., pd.DataFrame],
        combine_fn: Optional[Callable[[List[pd.DataFrame]], pd.DataFrame]] = None,
        map_left: Optional[Callable[[pd.DataFrame], pd.DataFrame]] = None,
        map_right: Optional[Callable[[pd.DataFrame], pd.DataFrame]] = None,
        buckets: Optional[int] = None,
        paths_per_task: int = 1,
        job_id: Optional[str] = None,
    ) -> pd.DataFrame:
        """Single-process execution of the SAME plan: same per-range map
        application, same hash bucketing, same per-bucket reduce in the
        same bucket order, same combine — so the distributed result is
        bit-identical to this one whenever the job functions are
        partition-local (the distributed contract)."""
        import numpy as np

        n_buckets = int(buckets or self.default_buckets)
        sides = [("left", left_paths, map_left)]
        if right_paths is not None:
            sides.append(("right", right_paths, map_right))
        probed: List[Tuple[List[pa.Table], List[Any], Dict[str, str]]] = []
        schemas: List[pa.Schema] = []
        for _name, paths, fn in sides:
            frames = [apply_map(rng, fn) for rng in _chunk(paths, paths_per_task)]
            tbls = [
                pa.Table.from_pandas(f, preserve_index=False) for f in frames
            ]
            columns = (
                {c: str(frames[0][c].dtype) for c in frames[0].columns}
                if frames
                else {}
            )
            probed.append((tbls, frames, columns))
            schemas.append(
                tbls[0].schema if tbls else pa.schema([])
            )
        kinds = canonical_key_kinds(
            _fields(schemas[0]), _fields(schemas[-1]), list(keys)
        )
        if kinds is None:
            raise DistJobError(
                f"join keys {list(keys)} have no canonical hashable dtype"
            )
        ids_per_side = [
            [bucket_ids(t, list(keys), kinds, n_buckets) for t in tbls]
            for tbls, _f, _c in probed
        ]
        partials: List[pd.DataFrame] = []
        for b in range(n_buckets):
            inputs: List[pd.DataFrame] = []
            for (tbls, _frames, columns), ids_list in zip(probed, ids_per_side):
                picked: List[pd.DataFrame] = []
                for tbl, ids in zip(tbls, ids_list):
                    (sel,) = np.nonzero(ids == b)
                    if len(sel) == 0:
                        continue
                    picked.append(
                        tbl.take(pa.array(sel, type=pa.int64())).to_pandas()
                    )
                if picked:
                    pdf = (
                        picked[0].reset_index(drop=True)
                        if len(picked) == 1
                        else pd.concat(picked, ignore_index=True)
                    )
                else:
                    pdf = _empty_frame(columns)
                inputs.append(pdf)
            partials.append(reduce_fn(*inputs).reset_index(drop=True))
        return (combine_fn or _default_combine)(partials)

    # -- the artifact/bucket audit -------------------------------------------
    def audit_job(self, jid: str) -> Dict[str, Any]:
        """Zero-lost / zero-double-counted proof over the shuffle: every
        row a (current) map done record declared into a bucket was
        consumed by that bucket's reduce exactly once. Run AFTER the job
        completes; the chaos gate fails on any nonzero loss/double."""
        manifest = self.board.read_job(jid)
        if manifest is None:
            raise DistJobError(f"unknown dist job {jid!r} (no manifest)")
        declared: Dict[Tuple[str, str, int], int] = {}
        for side in manifest["sides"]:
            for tid in side["map_tids"]:
                rec = self.board.read_done(tid)
                if rec is None:
                    continue
                for b, frag in (rec.get("fragments") or {}).items():
                    declared[(side["name"], tid, int(b))] = int(frag["rows"])
        consumed: Dict[Tuple[str, str, int], int] = {}
        reduces_done = 0
        for tid in manifest["reduce_tids"]:
            rec = self.board.read_done(tid)
            if rec is None:
                continue
            reduces_done += 1
            b = int(self.board.read_task(tid)["bucket"])
            for sname, per_prod in (rec.get("consumed") or {}).items():
                for ptid, rows in per_prod.items():
                    if int(rows) > 0:
                        consumed[(sname, ptid, b)] = (
                            consumed.get((sname, ptid, b), 0) + int(rows)
                        )
        lost = double = 0
        for key, rows in declared.items():
            got = consumed.get(key, 0)
            lost += max(0, rows - got)
            double += max(0, got - rows)
        for key, got in consumed.items():
            if key not in declared:
                double += got
        return {
            "map_done": sum(
                1
                for s in manifest["sides"]
                for t in s["map_tids"]
                if self.board.read_done(t) is not None
            ),
            "reduce_done": reduces_done,
            "fragments_declared": len(declared),
            "rows_declared": sum(declared.values()),
            "rows_consumed": sum(consumed.values()),
            "rows_lost": lost,
            "rows_double_counted": double,
        }
