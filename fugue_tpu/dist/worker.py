"""One worker of the distributed tier: lease → execute → publish.

A :class:`DistWorker` is a standalone engine process (its own
:class:`~fugue_tpu.execution.NativeExecutionEngine`, its own HTTP
surface) that pulls work from the shared :class:`~fugue_tpu.dist.board.TaskBoard`:

- scan for runnable tasks (spec present, no done record, deps done),
- acquire the task lease (:mod:`.lease`; renewed at ``lease_s/3`` while
  the task body runs, so only a dead/wedged owner's lease expires),
- execute — **map** tasks read a partition range of source files, apply
  the job's row-local function and hash-split the rows into per-bucket
  arrow-IPC *fragments* under this worker's own data dir (the PR 8
  exchange, network-partitioned); **reduce** tasks gather one bucket's
  fragments from every producer (local read or HTTP ``/dist/fetch`` from
  the producer's server), run the job's reduce function, and publish the
  output as a PR 5 content-addressed artifact in the SHARED store — so
  any worker (and the supervisor) can serve any other's output,
- publish the done record **first-wins** (``O_CREAT|O_EXCL``): a
  speculative twin or a steal racer that finishes second loses the
  record, and its artifact publishes were already deduped by content
  address — at-least-once execution, exactly-once observation.

Failure ladder (the PR 1 taxonomy, docs/resilience.md): an attempt that
raises records a failure (category attached) and releases the lease —
TRANSIENT/TIMEOUT/WORKER_LOST re-dispatch to any live worker, POISON
aborts the job at the supervisor. A fragment that cannot be fetched
(producer SIGKILLed, torn file) is *orphaned-output recovery*: the
consumer deletes the producer's done record — re-running it on a live
worker — and retries, extending the PR 8 torn-bucket recovery to the
remote-fetch path.

``python -m fugue_tpu.dist.worker --root <board> --id w0`` runs one.
"""

import argparse
import http.client
import io
import json
import os
import sys
import time
import threading
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

import pandas as pd
import pyarrow as pa

from ..obs.events import get_event_log
from ..resilience import (
    SITE_DIST_BOARD,
    SITE_DIST_LEASE,
    Deadline,
    FailureCategory,
    FaultInjector,
    RetryPolicy,
    WorkerLostError,
    classify_failure,
)
from ..shuffle.partitioner import bucket_ids
from ..workflow._checkpoint import _atomic_publish, _best_effort_remove
from .board import TaskBoard, load_fn
from .heartbeat import (
    DEFAULT_INTERVAL_S,
    DEFAULT_STALE_AFTER_S,
    HeartbeatWriter,
    holder_alive,
)
from .lease import LeaseBoard
from .stats import DistStats

__all__ = ["DistWorker", "BucketUnavailableError", "read_source_paths", "apply_map"]


class BucketUnavailableError(ConnectionError):
    """A shuffle fragment could not be served by its producer (dead
    worker, torn file). Subclasses ConnectionError so the PR 1 taxonomy
    classifies it TRANSIENT — the attempt is re-dispatched after the
    producer's done record was invalidated for re-execution."""


def read_source_paths(paths: List[str]) -> pd.DataFrame:
    """One partition range of source files → one pandas frame (format by
    extension, concatenated in path order — the same order a
    single-process load would read them)."""
    frames: List[pd.DataFrame] = []
    for p in paths:
        ext = os.path.splitext(p)[1].lower()
        if ext in (".parquet", ".pq"):
            frames.append(pd.read_parquet(p))
        elif ext == ".csv":
            frames.append(pd.read_csv(p))
        elif ext == ".json":
            frames.append(pd.read_json(p, lines=True))
        else:
            raise ValueError(f"unsupported source extension {ext!r} ({p})")
    if not frames:
        return pd.DataFrame()
    if len(frames) == 1:
        return frames[0].reset_index(drop=True)
    return pd.concat(frames, ignore_index=True)


def apply_map(paths: List[str], fn: Any) -> pd.DataFrame:
    """The map-task body shared VERBATIM by workers and the supervisor's
    serial (kill-switch) path — bit-identity between the two is by
    construction, not by parallel maintenance."""
    pdf = read_source_paths(paths)
    if fn is not None:
        pdf = fn(pdf)
        if not isinstance(pdf, pd.DataFrame):
            raise TypeError(
                f"dist map function must return a pandas DataFrame, got "
                f"{type(pdf).__name__}"
            )
        pdf = pdf.reset_index(drop=True)
    return pdf


def _empty_frame(columns: Optional[Dict[str, str]]) -> pd.DataFrame:
    if not columns:
        return pd.DataFrame()
    import numpy as np

    return pd.DataFrame(
        {c: pd.Series(dtype=np.dtype(d)) for c, d in columns.items()}
    )


class _LeaseKeeper:
    """Renews one lease at ``lease_s/3`` while the task body runs."""

    def __init__(self, leases: LeaseBoard, lease_id: str, owner: str, lease_s: float):
        self._leases = leases
        self._lease_id = lease_id
        self._owner = owner
        self._lease_s = lease_s
        self._stop = threading.Event()
        self.lost = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self) -> None:
        period = max(0.05, self._lease_s / 3.0)
        while not self._stop.wait(period):
            if not self._leases.renew(self._lease_id, self._owner, self._lease_s):
                self.lost.set()
                return

    def start(self) -> "_LeaseKeeper":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


class DistWorker:
    """One engine process of the worker tier."""

    def __init__(
        self,
        root: str,
        worker_id: str,
        conf: Optional[Dict[str, Any]] = None,
        start_http: bool = True,
    ):
        from ..constants import (
            FUGUE_TPU_CONF_DIST_FETCH,
            FUGUE_TPU_CONF_DIST_FETCH_PREFETCH_DEPTH,
            FUGUE_TPU_CONF_DIST_HB_INTERVAL_S,
            FUGUE_TPU_CONF_DIST_HB_STALE_S,
            FUGUE_TPU_CONF_DIST_LEASE_S,
            FUGUE_TPU_CONF_DIST_POLL_S,
            FUGUE_TPU_CONF_TRACE_SPOOL_DIR,
        )
        from ..execution import NativeExecutionEngine

        self.worker_id = worker_id
        self.board = TaskBoard(root)
        self.engine = NativeExecutionEngine(dict(conf or {}))
        c = self.engine.conf
        # cluster tracing (ISSUE 18): with a spool dir configured, every
        # task attempt ends with an atomic publish of this worker's whole
        # span buffer + sampler ring to <spool>/<host>-<pid>.spool.json
        self.spool_dir = str(c.get(FUGUE_TPU_CONF_TRACE_SPOOL_DIR, ""))
        self.lease_s = float(c.get(FUGUE_TPU_CONF_DIST_LEASE_S, 15.0))
        self.poll_s = max(0.005, float(c.get(FUGUE_TPU_CONF_DIST_POLL_S, 0.05)))
        self.fetch_mode = str(c.get(FUGUE_TPU_CONF_DIST_FETCH, "auto"))
        # reduce-side fragment prefetch (docs/distributed.md): fetch of
        # fragment i+1 (HTTP /dist/fetch or local read) overlaps the
        # decode+reduce of fragment i; <=0 restores serial fetches
        self.fetch_prefetch_depth = int(
            c.get(FUGUE_TPU_CONF_DIST_FETCH_PREFETCH_DEPTH, 2)
        )
        hb_interval = float(
            c.get(FUGUE_TPU_CONF_DIST_HB_INTERVAL_S, DEFAULT_INTERVAL_S)
        )
        self.hb_stale_s = float(
            c.get(FUGUE_TPU_CONF_DIST_HB_STALE_S, DEFAULT_STALE_AFTER_S)
        )
        self.stats = DistStats()
        self._injector = FaultInjector.from_conf(c)
        self.retry_policy = RetryPolicy.from_conf(
            c, prefix="fugue.tpu.retry.dist", default_attempts=4
        )
        from ..constants import FUGUE_TPU_CONF_RETRY_DIST_DEADLINE_S

        # wall-clock budget across ALL attempts of one fragment fetch;
        # <=0/unset = unbounded (the attempt budget alone bounds it)
        self.fetch_deadline_s = float(
            c.get(FUGUE_TPU_CONF_RETRY_DIST_DEADLINE_S, 20.0)
        )
        self.leases = LeaseBoard(
            self.board.leases_dir,
            hb_dir=self.board.hb_dir,
            hb_stale_s=self.hb_stale_s,
            stats=self.stats,
        )
        self.data_dir = self.board.worker_data_dir(worker_id)
        self._addr: Optional[List[Any]] = None
        self._rpc: Any = None
        self._start_http = start_http
        self.heartbeat = HeartbeatWriter(
            self.board.hb_dir,
            worker_id,
            interval_s=hb_interval,
            extra=self._hb_extra,
            injector=self._injector,
            log=self.engine.log,
        )
        # the engine's unified registry carries the worker's own counters
        # (scrapeable over this worker's /metrics like any engine source)
        self.engine.metrics.register("dist", self.stats)

    def _hb_extra(self) -> Dict[str, Any]:
        # the heartbeat doubles as the ship-home channel for worker
        # metrics: the supervisor reads liveness AND counters in one file
        return {"addr": self._addr, "stats": self.stats.as_dict()}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "DistWorker":
        if self._start_http and self._rpc is None:
            from ..rpc.http import HttpRPCServer

            self._rpc = HttpRPCServer(self.engine.conf)
            self._rpc.start_server()
            self._rpc.bind_engine(self.engine)
            self._rpc.bind_dist(self)
            self._addr = [self._rpc.host, self._rpc.port]
        self.heartbeat.start()
        return self

    def stop(self) -> None:
        self.heartbeat.stop(remove=True)
        if self._rpc is not None:
            self._rpc.stop_server()
            self._rpc = None

    @property
    def addr(self) -> Optional[List[Any]]:
        return self._addr

    # -- the /dist/fetch surface (rpc/http.py binds this) --------------------
    def read_blob(self, rel: str) -> Optional[bytes]:
        """Bytes of one file under THIS worker's data dir, or None. The
        path is jailed to the data dir — the fetch route can never serve
        an arbitrary host file."""
        full = os.path.realpath(os.path.join(self.data_dir, rel))
        base = os.path.realpath(self.data_dir)
        if not full.startswith(base + os.sep):
            return None
        try:
            with open(full, "rb") as f:
                return f.read()
        except OSError:
            return None

    # -- the scan loop -------------------------------------------------------
    def _deps_done(self, spec: Dict[str, Any]) -> bool:
        return all(
            self.board.read_done(d) is not None for d in spec.get("deps", ())
        )

    def _exhausted(self, tid: str) -> bool:
        """A task no live worker should touch again: a POISON failure
        (deterministic — retrying wastes time, the supervisor aborts the
        job) or the retry budget spent."""
        fails = self.board.failures(tid)
        if any(f.get("category") == FailureCategory.POISON.value for f in fails):
            return True
        return len(fails) >= self.retry_policy.max_attempts

    def poll_once(self) -> bool:
        """One scan over the board; True when a task was attempted."""
        for tid in self.board.list_tasks():
            if self.board.read_done(tid) is not None:
                continue
            spec = self.board.read_task(tid)
            if spec is None or not self._deps_done(spec):
                continue
            if self._exhausted(tid):
                continue
            holder = self.leases.read(tid)
            if (
                holder is not None
                and holder.get("owner") != self.worker_id
                and not self.leases.stealable(holder)
            ):
                # a live owner holds it — volunteer as the speculative
                # twin only when the supervisor marked it a straggler
                if self.board.is_speculative(tid):
                    if self.run_task(tid, speculative=True):
                        return True
                continue
            if self.run_task(tid):
                return True
        return False

    def serve_forever(self, stop_file: Optional[str] = None) -> None:
        while True:
            if stop_file is not None and os.path.exists(stop_file):
                return
            if not self.poll_once():
                time.sleep(self.poll_s)

    # -- task execution ------------------------------------------------------
    def run_task(self, tid: str, speculative: bool = False) -> bool:
        """Lease → execute → first-wins publish. False when the lease was
        not acquired or the attempt failed (failure recorded; a live
        worker — possibly this one — retries on a later scan)."""
        from contextlib import nullcontext

        from ..obs import get_tracer, trace_scope

        spec = self.board.read_task(tid)
        if spec is None:
            return False
        lease_id = f"{tid}.spec" if speculative else tid
        prev_holder = self.leases.read(lease_id)
        owned, _holder = self.leases.try_acquire(
            lease_id, self.worker_id, self.lease_s
        )
        if not owned:
            return False
        # categorized re-dispatch record (flight recorder): this attempt
        # follows a steal (previous holder displaced) or a recorded failure
        stolen = prev_holder is not None and prev_holder.get("owner") not in (
            None,
            self.worker_id,
        )
        n_fails = len(self.board.failures(tid))
        if stolen or n_fails > 0:
            get_event_log().emit(
                "task.redispatch",
                task=tid,
                owner=self.worker_id,
                reason="stolen" if stolen else "failed_retry",
                attempts=n_fails,
                trace=(spec.get("trace") or {}).get("trace"),
            )
        keeper = _LeaseKeeper(
            self.leases, lease_id, self.worker_id, self.lease_s
        ).start()
        tracer = get_tracer()
        # adopt the submitting run's trace context carried on the spec:
        # this task's spans land under the run's trace id, parented on the
        # supervisor-side dist.job span instead of floating as local roots
        carrier = spec.get("trace") or {}
        tctx = (
            trace_scope(carrier.get("trace"), carrier.get("parent"))
            if (tracer.enabled and carrier)
            else nullcontext()
        )
        try:
            # the dist.lease fault site sits between lease acquisition
            # and the task body: an `error` rule unwinds through the
            # release below (TRANSIENT re-dispatch), a `kill` leaves an
            # orphaned lease for a live worker to steal
            self._injector.fire(SITE_DIST_LEASE)
            mark = tracer.mark() if tracer.enabled else 0
            msnap = None
            if tracer.enabled:
                from ..obs import get_span_metrics

                msnap = get_span_metrics().snapshot()
            t0 = time.time()
            with tctx, tracer.span(
                "dist.task",
                cat="dist",
                task=tid,
                kind=spec.get("kind", "?"),
                worker=self.worker_id,
                speculative=speculative,
            ):
                payload = self._execute(spec)
            payload.update(
                worker=self.worker_id,
                addr=self._addr,
                data_dir=self.data_dir,
                speculative=speculative,
                ts0=t0,
                ts1=time.time(),
            )
            if carrier.get("trace"):
                payload["trace"] = carrier["trace"]
            if tracer.enabled:
                # ship spans home like fork workers do: the supervisor
                # ingests these when it collects the done record
                payload["spans"] = tracer.take_since(mark)
                # … and the span-HISTOGRAM delta (metrics federation,
                # ISSUE 18): the driver's /metrics then covers remote
                # task latencies too. Keyed by proc identity so an
                # in-process worker's delta is never merged twice.
                from ..obs import get_span_metrics, proc_ident

                delta = get_span_metrics().delta_since(msnap or {})
                if delta:
                    payload["metrics"] = {"proc": proc_ident(), "delta": delta}
            payload["stats"] = self.stats.as_dict()
            # the dist.board fault site sits in the torn-publish window:
            # every output is already durable (fragments / artifact) but
            # the done record is not yet on the board — `kill` here leaves
            # orphaned outputs for the steal + invalidation ladder to
            # cover, `error` unwinds to a TRANSIENT re-dispatch whose
            # re-publishes dedup by content address
            self._injector.fire(SITE_DIST_BOARD)
            won = self.board.publish_done(tid, payload)
            self.stats.inc("tasks_completed")
            if speculative:
                self.stats.inc(
                    "speculative_wins" if won else "speculative_losses"
                )
            elif not won:
                self.stats.inc("duplicate_publishes")
            return True
        except BaseException as e:
            cat = classify_failure(e)
            self.board.record_failure(
                tid, self.worker_id, cat.value, f"{type(e).__name__}: {e}"
            )
            get_event_log().emit(
                "task.failed",
                task=tid,
                worker=self.worker_id,
                category=cat.value,
                error=f"{type(e).__name__}: {e}"[:200],
                trace=carrier.get("trace"),
            )
            self.stats.inc("tasks_failed")
            if cat is FailureCategory.FATAL:
                raise
            return False
        finally:
            keeper.stop()
            self.leases.release(lease_id, self.worker_id)
            self._maybe_publish_spool(tracer)

    def _maybe_publish_spool(self, tracer: Any) -> None:
        """Atomic publish of this worker's span buffer + sampler ring +
        stats to the shared spool (cluster tracing); best-effort — a full
        disk must not fail the task that already published its result."""
        if not self.spool_dir or not tracer.enabled:
            return
        try:
            from ..obs import publish_spool

            publish_spool(
                self.spool_dir,
                stats=self.stats.as_dict(),
                label=f"worker {self.worker_id}",
            )
        except Exception as ex:
            self.engine.log.warning("span spool publish failed: %s", ex)

    def _execute(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        kind = spec.get("kind")
        if kind == "map":
            return self._execute_map(spec)
        if kind == "reduce":
            return self._execute_reduce(spec)
        raise ValueError(f"unknown dist task kind {kind!r}")

    # -- map: partition range → bucket fragments (or an artifact) ------------
    def _execute_map(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        pdf = apply_map(spec["paths"], load_fn(spec.get("fn")))
        self.stats.inc("rows_in", len(pdf))
        shuffle = spec.get("shuffle")
        if not shuffle:
            fp = spec["fp"]
            self._publish_artifact(fp, pdf)
            return {"kind": "map", "fp": fp, "rows_out": len(pdf)}
        tbl = pa.Table.from_pandas(pdf, preserve_index=False)
        n_buckets = int(shuffle["buckets"])
        ids = bucket_ids(tbl, shuffle["keys"], shuffle["kinds"], n_buckets)
        frag_dir = os.path.join(
            "shuffle", str(spec.get("job", "job")), str(shuffle["exchange"])
        )
        os.makedirs(os.path.join(self.data_dir, frag_dir), exist_ok=True)
        import numpy as np

        fragments: Dict[str, Dict[str, Any]] = {}
        for b in range(n_buckets):
            (sel,) = np.nonzero(ids == b)
            if len(sel) == 0:
                continue
            part = tbl.take(pa.array(sel, type=pa.int64()))
            rel = os.path.join(frag_dir, f"b{b:04d}_{spec['id']}.arrow")
            final = os.path.join(self.data_dir, rel)
            tmp = final + ".tmp"
            with pa.OSFile(tmp, "wb") as sink:
                with pa.ipc.new_stream(sink, tbl.schema) as writer:
                    writer.write_table(part)
            _atomic_publish(tmp, final)
            fragments[str(b)] = {"rel": rel, "rows": int(part.num_rows)}
            self.stats.inc("fragments_written")
        return {
            "kind": "map",
            "fragments": fragments,
            "rows_out": int(tbl.num_rows),
        }

    # -- reduce: gather one bucket from every producer, reduce, publish ------
    def _execute_reduce(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        bucket = int(spec["bucket"])
        fn = load_fn(spec["fn"])
        columns = spec.get("columns", {})
        sides: List[pd.DataFrame] = []
        consumed: Dict[str, Dict[str, int]] = {}
        remote = local = 0
        for side, ex in spec["exchanges"].items():
            frames: List[pd.DataFrame] = []
            consumed[side] = {}
            # fragment fetches flow through the PR 2 prefetcher: the
            # producer thread pulls fragment i+1 over /dist/fetch (or
            # reads it locally) while this thread decodes and reduces
            # fragment i — network wait overlaps reduce compute. Fetch
            # failures (BucketUnavailableError and friends) re-raise
            # here with their original traceback; depth<=0 is the serial
            # pre-pipeline shape.
            from ..jax.pipeline import maybe_prefetch

            def fetch(producers: List[str]) -> Any:
                for ptid in producers:
                    rec = self.board.read_done(ptid)
                    if rec is None:
                        # the producer was invalidated after our dep
                        # check — transient by definition, re-scan will
                        # wait on it
                        raise BucketUnavailableError(
                            f"producer {ptid} has no done record "
                            "(invalidated mid-read); re-dispatching"
                        )
                    frag = (rec.get("fragments") or {}).get(str(bucket))
                    if frag is None:
                        yield ptid, None, False
                        continue
                    tbl, was_remote = self._fetch_fragment(rec, frag, ptid)
                    yield ptid, tbl, was_remote

            it = maybe_prefetch(
                fetch(list(ex["producers"])),
                self.fetch_prefetch_depth,
                verb="dist.fetch",
            )
            try:
                for ptid, tbl, was_remote in it:
                    if tbl is None:
                        consumed[side][ptid] = 0
                        continue
                    frames.append(tbl.to_pandas())
                    consumed[side][ptid] = int(tbl.num_rows)
                    remote += int(was_remote)
                    local += int(not was_remote)
            finally:
                it.close()
            if frames:
                pdf = (
                    frames[0].reset_index(drop=True)
                    if len(frames) == 1
                    else pd.concat(frames, ignore_index=True)
                )
            else:
                pdf = _empty_frame(columns.get(side))
            sides.append(pdf)
        self.stats.inc("fragments_local", local)
        self.stats.inc("fragments_remote", remote)
        out = fn(*sides)
        if not isinstance(out, pd.DataFrame):
            raise TypeError(
                "dist reduce function must return a pandas DataFrame, got "
                f"{type(out).__name__}"
            )
        out = out.reset_index(drop=True)
        fp = spec["fp"]
        self._publish_artifact(fp, out)
        self.stats.inc("rows_out", len(out))
        return {
            "kind": "reduce",
            "fp": fp,
            "rows_out": len(out),
            "consumed": consumed,
            "remote_fetches": remote,
            "local_reads": local,
        }

    def _publish_artifact(self, fp: str, pdf: pd.DataFrame) -> None:
        """Content-addressed publish to the SHARED store: speculative
        twins and steal re-runs compute the same fp, so the second
        publish is a no-op (``exists`` short-circuits) and racing renames
        both land a complete identical artifact."""
        from ..cache.store import ArtifactStore

        store = ArtifactStore(self.board.store_dir, cap_bytes=0)
        edf = self.engine.to_df(pdf)
        written = store.publish(fp, edf, self.engine, str(edf.schema))
        if written > 0:
            self.stats.inc("artifacts_published")

    # -- fragment fetch (local / remote, with orphan recovery) ---------------
    def _fetch_fragment(
        self, rec: Dict[str, Any], frag: Dict[str, Any], ptid: str
    ) -> Tuple[pa.Table, bool]:
        """One producer's fragment for one bucket, validated against its
        declared row count. Tries the local filesystem and/or the
        producer's HTTP route per ``fugue.tpu.dist.fetch``; a fragment
        that can't be served intact ORPHANS the producer's done record
        (it re-runs on a live worker) and raises TRANSIENT."""
        own = rec.get("worker") == self.worker_id
        rel = frag["rel"]
        want_rows = int(frag["rows"])
        local_path = os.path.join(str(rec.get("data_dir", "")), rel)
        try_local = self.fetch_mode == "local" or self.fetch_mode == "auto" or own
        if try_local:
            tbl = self._read_fragment_file(local_path, want_rows)
            if tbl is not None:
                return tbl, False
            if self.fetch_mode == "local" or own:
                return self._orphan(ptid, rec, f"local fragment {rel} unreadable")
        # remote: the producer serves its own dir over /dist/fetch. The
        # retry loop is the shared RetryPolicy (conf fugue.tpu.retry.dist.*)
        # under a wall-clock Deadline (fugue.tpu.retry.dist.deadline_s) —
        # backoff/jitter/attempt budget come from conf, not ad-hoc sleeps.
        addr = rec.get("addr")
        if not addr:
            return self._orphan(ptid, rec, "producer has no fetch address")
        deadline = Deadline.after(self.fetch_deadline_s)
        failures = 0
        last: Optional[BaseException] = None
        while True:
            try:
                blob = self._http_fetch(addr[0], int(addr[1]), rel)
            except ConnectionRefusedError:
                # nothing is listening on the producer's advertised port:
                # the process is gone, not slow — orphan immediately and
                # classify the re-dispatch WORKER_LOST instead of burning
                # the TRANSIENT backoff budget on a dead peer
                return self._orphan(
                    ptid,
                    rec,
                    f"connection refused fetching {rel} from {addr}",
                    err_type=WorkerLostError,
                )
            except Exception as e:
                last = e
            else:
                tbl = self._decode_fragment(blob, want_rows)
                if tbl is not None:
                    return tbl, True
                break  # complete transfer, bad content: torn at source
            failures += 1
            if deadline.expired or not self.retry_policy.should_retry(
                classify_failure(last), failures
            ):
                break
            pause = self.retry_policy.delay(failures, seed=rel)
            rem = deadline.remaining()
            time.sleep(pause if rem is None else min(pause, rem))
        return self._orphan(
            ptid,
            rec,
            f"remote fetch of {rel} from {addr} failed after "
            f"{failures} attempt(s) (last: {last})",
        )

    @staticmethod
    def _read_fragment_file(path: str, want_rows: int) -> Optional[pa.Table]:
        if not os.path.exists(path):
            return None
        try:
            with pa.ipc.open_stream(path) as reader:
                tbl = reader.read_all()
        except Exception:
            return None
        return tbl if tbl.num_rows == want_rows else None

    @staticmethod
    def _decode_fragment(blob: bytes, want_rows: int) -> Optional[pa.Table]:
        try:
            with pa.ipc.open_stream(io.BytesIO(blob)) as reader:
                tbl = reader.read_all()
        except Exception:
            return None
        return tbl if tbl.num_rows == want_rows else None

    def _http_fetch(self, host: str, port: int, rel: str) -> bytes:
        """One GET against the producer's /dist/fetch route. Raises on
        any transport failure — ConnectionRefusedError propagates intact
        so the caller can prove the producer WORKER_LOST — and a non-200
        status raises TRANSIENT (producer alive, fragment unservable)."""
        from ..rpc.http import trace_headers

        conn = http.client.HTTPConnection(host, port, timeout=2.0)
        try:
            conn.request(
                "GET",
                "/dist/fetch?path=" + urllib.parse.quote(rel, safe=""),
                headers=trace_headers(),
            )
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise BucketUnavailableError(
                    f"/dist/fetch {rel} from {host}:{port} -> "
                    f"HTTP {resp.status}"
                )
            return body
        finally:
            conn.close()

    def _orphan(
        self,
        ptid: str,
        rec: Dict[str, Any],
        why: str,
        err_type: type = BucketUnavailableError,
    ) -> Any:
        """The remote-fetch extension of PR 8's torn-bucket recovery: the
        consumer proves the output unreachable, deletes the producer's
        done record (any live worker re-executes it — deterministic, so
        bit-identical fragments reappear) and re-raises — TRANSIENT by
        default, WORKER_LOST when the evidence is a refused connection."""
        self.stats.inc("fetch_failures")
        alive = holder_alive(
            str(rec.get("worker") or ""), self.board.hb_dir, self.hb_stale_s
        )
        if self.board.invalidate_done(ptid):
            self.stats.inc("orphaned_outputs_recovered")
            get_event_log().emit(
                "task.orphan", task=ptid, why=why[:200], producer=rec.get("worker")
            )
        raise err_type(
            f"{why}; producer {rec.get('worker')!r} "
            f"{'alive' if alive else 'dead/unknown'}; done record "
            f"invalidated for re-dispatch"
        )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="fugue-tpu dist worker")
    ap.add_argument("--root", required=True, help="shared board root dir")
    ap.add_argument("--id", required=True, help="worker id (heartbeat name)")
    ap.add_argument("--conf", default="{}", help="json conf overrides")
    ap.add_argument("--stop-file", default=None, help="exit when this appears")
    args = ap.parse_args(argv)
    worker = DistWorker(args.root, args.id, conf=json.loads(args.conf))
    worker.start()
    try:
        worker.serve_forever(stop_file=args.stop_file)
    finally:
        worker.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(main())
