"""Leased task dispatch: at-most-one-live-executor per task, on files.

A *lease* is the worker tier's unit of mutual exclusion: before running a
task a worker must hold ``<dir>/<task_id>.lease.json``, created through
the same ``O_CREAT|O_EXCL`` claim primitive the PR 13 fleet uses
(:func:`~fugue_tpu.cache.store.try_claim_file`). Ownership is bounded,
not permanent:

- the owner renews the lease at ``lease_s / 3`` while executing
  (``ts`` advances; ``acquired_ts`` — what straggler detection reads —
  does not);
- a lease whose ``ts`` is past ``lease_s`` is stealable (expired: the
  owner is wedged or gone);
- a lease whose owner's heartbeat is STALE is stealable immediately —
  cross-host death needs no lease wait (:mod:`.heartbeat`); a FRESH
  heartbeat never pins an *expired* lease (a live-but-wedged owner must
  not block the job);
- with no heartbeat evidence, the same-host dead-pid probe is the
  fallback, exactly as in the fleet claim protocol.

Steal races settle by re-read-after-atomic-rewrite; a released or stolen
owner's late ``release``/``renew`` is owner-checked and becomes a no-op.
First-publish-wins *done records* (:mod:`.board`) make the residual
two-executors window (steal of a live-but-slow owner, speculation) safe:
both may execute, at most one result is ever observed.
"""

import os
import socket
import time
from typing import Any, Dict, Optional, Tuple

from ..cache.store import (
    read_claim_file,
    release_claim_file,
    try_claim_file,
)
from ..obs.events import get_event_log
from .heartbeat import DEFAULT_STALE_AFTER_S, holder_alive

__all__ = ["LeaseBoard"]


class LeaseBoard:
    """Task leases under one directory (shared filesystem = the board)."""

    def __init__(
        self,
        path: str,
        hb_dir: Optional[str] = None,
        hb_stale_s: float = DEFAULT_STALE_AFTER_S,
        stats: Any = None,
    ):
        self.path = path
        self.hb_dir = hb_dir or None
        self.hb_stale_s = float(hb_stale_s)
        self._stats = stats
        os.makedirs(path, exist_ok=True)

    def _lease(self, task_id: str) -> str:
        return os.path.join(self.path, f"{task_id}.lease.json")

    def _inc(self, name: str, n: int = 1) -> None:
        if self._stats is not None:
            self._stats.inc(name, n)

    # -- liveness ------------------------------------------------------------
    def steal_reason(self, holder: Dict[str, Any]) -> Optional[str]:
        """Why (if at all) ``holder``'s lease may be stolen — the PR 1
        taxonomy's re-dispatch split, decided AT the steal site:
        ``"worker_lost"`` (owner provably dead: stale heartbeat, or dead
        same-host pid), ``"expired"`` (lease ran out under a live or
        unknown owner — TRANSIENT), or None (held fast)."""
        alive = holder_alive(
            str(holder.get("owner") or ""), self.hb_dir, self.hb_stale_s
        )
        if alive is False:
            return "worker_lost"
        if alive is None:
            # no heartbeat evidence: same-host dead-pid fallback
            pid = holder.get("pid")
            if pid and holder.get("host") == socket.gethostname():
                try:
                    os.kill(int(pid), 0)
                except ProcessLookupError:
                    return "worker_lost"
                except OSError:
                    pass
        ts = float(holder.get("ts", 0.0))
        lease = float(holder.get("lease_s", 0.0))
        if ts + lease <= time.time():
            # a FRESH heartbeat never pins an expired lease: a live-but-
            # wedged owner must not block the job
            return "expired"
        return None

    def stealable(self, holder: Dict[str, Any]) -> bool:
        return self.steal_reason(holder) is not None

    # -- the protocol --------------------------------------------------------
    def try_acquire(
        self, task_id: str, owner: str, lease_s: float
    ) -> Tuple[bool, Optional[Dict[str, Any]]]:
        """(owned, holder). ``owned`` means ``owner`` holds the lease now
        (fresh, re-entered, or stolen from a dead/expired holder)."""
        now = time.time()
        payload = {
            "owner": owner,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "ts": now,
            "acquired_ts": now,
            "lease_s": float(lease_s),
        }
        holder = self.read(task_id)
        owned, cur = try_claim_file(self._lease(task_id), payload, self.stealable)
        if owned:
            self._inc("leases_acquired")
            if (
                holder is not None
                and holder.get("owner") not in (None, owner)
                and cur is not None
                and cur.get("owner") == owner
            ):
                # classify the steal HERE, where the evidence is: the
                # supervisor folds these shipped-home counters into
                # redispatch_worker_lost / redispatch_transient
                self._inc("leases_stolen")
                reason = self.steal_reason(holder) or "expired"
                self._inc(
                    "leases_stolen_dead"
                    if reason == "worker_lost"
                    else "leases_stolen_expired"
                )
                log = get_event_log()
                if log.enabled:
                    if reason == "worker_lost":
                        # the heartbeat (or dead-pid probe) proved the
                        # holder gone — record the expiry as its own event
                        # so the timeline shows expiry BEFORE the steal
                        log.emit(
                            "hb.expired",
                            holder=holder.get("owner"),
                            task=task_id,
                            age_s=round(now - float(holder.get("ts", now)), 3),
                        )
                    log.emit(
                        "lease.steal",
                        task=task_id,
                        owner=owner,
                        prev_owner=holder.get("owner"),
                        reason=reason,
                    )
            else:
                get_event_log().emit("lease.acquire", task=task_id, owner=owner)
        return owned, cur

    def renew(self, task_id: str, owner: str, lease_s: float) -> bool:
        """Advance the lease clock if ``owner`` still holds it. False
        means the lease was stolen (or released) — the executor should
        abandon its attempt; its publish would lose the done-record race
        anyway."""
        path = self._lease(task_id)
        cur = read_claim_file(path)
        if cur is None or cur.get("owner") != owner:
            return False
        cur["ts"] = time.time()
        cur["lease_s"] = float(lease_s)
        try:
            tmp = f"{path}.__tmp_renew_{os.getpid()}"
            import json as _json

            with open(tmp, "w") as f:
                _json.dump(cur, f)
            os.replace(tmp, path)
        except OSError:
            return False
        # the rename races a stealer's rename; whoever's payload survived
        # owns it — re-read to learn the truth
        after = read_claim_file(path)
        renewed = after is not None and after.get("owner") == owner
        if renewed:
            self._inc("leases_renewed")
            get_event_log().emit("lease.renew", task=task_id, owner=owner)
        return renewed

    def release(self, task_id: str, owner: str) -> bool:
        return release_claim_file(self._lease(task_id), owner)

    def read(self, task_id: str) -> Optional[Dict[str, Any]]:
        return read_claim_file(self._lease(task_id))
