"""Heartbeat-file liveness: cross-host proof of life for workers/replicas.

The PR 13 claim protocol proved a dead owner with a same-host pid probe
(``os.kill(pid, 0)``) — explicitly useless across hosts. The worker tier
replaces it with a *heartbeat file*: every worker (and every
:class:`~fugue_tpu.serve.EngineServer` replica with
``fugue.tpu.dist.heartbeat.dir`` set) rewrites
``<dir>/<id>.hb.json`` every ``interval_s`` through the same
temp-write + atomic-rename publish as every other store artifact, so a
reader sees either the previous complete beat or the next one — never a
torn file. Liveness is then a pure data question any host can answer:

- beat younger than ``stale_after_s``  → provably ALIVE;
- beat older than ``stale_after_s``    → provably DEAD (the writer loop
  runs at several beats per stale window — missing all of them means the
  process, its host, or its disk is gone);
- no beat file at all                  → UNKNOWN (the owner predates the
  heartbeat dir, or never joined it) — callers fall back to the pid
  probe / lease expiry they used before.

Wall-clock ``time.time()`` is deliberately the beat timestamp: it is the
only clock shared across hosts, and the stale windows (seconds) dwarf
realistic NTP skew. The reader additionally takes ``max(ts, mtime)`` so
a writer with a skewed-backwards clock is still judged by when the file
actually landed.

The ``dist.heartbeat`` fault site fires before each write: an ``error``
rule SKIPS that beat (a simulated network partition — enough skipped
beats and the worker reads as dead to stealers), ``delay`` widens the
gap the same way.
"""

import json
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, Optional

from ..resilience import SITE_DIST_HEARTBEAT, FaultInjector, NULL_INJECTOR

__all__ = [
    "HeartbeatWriter",
    "read_heartbeat",
    "heartbeat_age_s",
    "holder_alive",
    "DEFAULT_INTERVAL_S",
    "DEFAULT_STALE_AFTER_S",
]

DEFAULT_INTERVAL_S = 0.5
DEFAULT_STALE_AFTER_S = 3.0


def _hb_path(hb_dir: str, name: str) -> str:
    return os.path.join(hb_dir, f"{name}.hb.json")


def read_heartbeat(hb_dir: str, name: str) -> Optional[Dict[str, Any]]:
    """The latest complete beat payload for ``name``, or None. A torn or
    unreadable file reads as absent (UNKNOWN, never a crash)."""
    path = _hb_path(hb_dir, name)
    try:
        with open(path) as f:
            payload = json.load(f)
        st = os.stat(path)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict):
        return None
    # a writer with a backwards-skewed clock is judged by when the file
    # actually landed on the shared filesystem
    payload["_observed_ts"] = max(float(payload.get("ts", 0.0)), st.st_mtime)
    return payload


def heartbeat_age_s(payload: Dict[str, Any], now: Optional[float] = None) -> float:
    if now is None:
        now = time.time()
    return max(0.0, now - float(payload.get("_observed_ts", payload.get("ts", 0.0))))


def holder_alive(
    owner: str,
    hb_dir: Optional[str],
    stale_after_s: float = DEFAULT_STALE_AFTER_S,
    now: Optional[float] = None,
) -> Optional[bool]:
    """Tri-state cross-host liveness of ``owner``:

    - ``True``  — fresh beat: provably alive;
    - ``False`` — stale beat: provably dead;
    - ``None``  — no heartbeat dir configured or no beat file: unknown,
      the caller falls back to its pre-heartbeat probe (same-host pid).
    """
    if not hb_dir or not owner:
        return None
    payload = read_heartbeat(hb_dir, owner)
    if payload is None:
        return None
    return heartbeat_age_s(payload, now=now) <= float(stale_after_s)


class HeartbeatWriter:
    """A daemon thread keeping ``<dir>/<name>.hb.json`` fresh.

    ``extra`` (a zero-arg callable returning a json-able dict) is merged
    into every beat — workers ship their address and live counters home
    this way, so the supervisor reads per-worker stats from the same file
    it reads liveness from. ``beat()`` writes one beat synchronously
    (start() does this too, so a started writer is immediately alive).
    """

    def __init__(
        self,
        hb_dir: str,
        name: str,
        interval_s: float = DEFAULT_INTERVAL_S,
        extra: Optional[Callable[[], Dict[str, Any]]] = None,
        injector: Optional[FaultInjector] = None,
        log: Any = None,
    ):
        self.hb_dir = hb_dir
        self.name = name
        self.interval_s = max(0.05, float(interval_s))
        self._extra = extra
        self._injector = injector or NULL_INJECTOR
        self._log = log
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._seq = 0
        self._skipped = 0
        os.makedirs(hb_dir, exist_ok=True)

    @property
    def path(self) -> str:
        return _hb_path(self.hb_dir, self.name)

    @property
    def skipped(self) -> int:
        """Beats the fault site (or a write failure) suppressed."""
        with self._lock:
            return self._skipped

    def beat(self) -> bool:
        """Write one beat now; False when the beat was skipped (injected
        partition or a write error — liveness must never crash a worker)."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        payload: Dict[str, Any] = {
            "name": self.name,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "ts": time.time(),
            "interval_s": self.interval_s,
            "seq": seq,
        }
        if self._extra is not None:
            try:
                payload.update(self._extra())
            except Exception:
                pass  # stats are a passenger, never the reason a beat dies
        final = self.path
        tmp = f"{final}.__tmp_{os.getpid()}_{seq}"
        try:
            self._injector.fire(SITE_DIST_HEARTBEAT)
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, final)
            return True
        except Exception as ex:
            try:
                os.remove(tmp)
            except OSError:
                pass
            with self._lock:
                self._skipped += 1
            if self._log is not None:
                self._log.warning(
                    "heartbeat %s beat skipped (%s: %s)",
                    self.name,
                    type(ex).__name__,
                    ex,
                )
            return False

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat()

    def start(self) -> "HeartbeatWriter":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"fugue-hb-{self.name}", daemon=True
            )
        self.beat()  # alive from the first instant, not interval_s later
        self._thread.start()
        return self

    def stop(self, remove: bool = False) -> None:
        """Stop beating; ``remove=True`` also deletes the beat file (an
        ORDERLY departure reads as UNKNOWN, not as a death to steal from
        — a crash, by definition, leaves its last beat to go stale)."""
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5.0)
        if remove:
            try:
                os.remove(self.path)
            except OSError:
                pass
