"""ViewService: the one object the serving tier holds for ISSUE 20.

Facade over the registry (durable specs + heads on the shared store),
the maintainer (the watch/refresh loop), and the counters — constructed
by :class:`~fugue_tpu.serve.EngineServer` only when
``fugue.tpu.views.enabled`` is on AND a shared store is mounted, and
registered with the engine metrics registry as the ``views`` stats
group (``engine.stats()["views"]`` → ``fugue_tpu_views_*`` on
``/metrics``). Serving reads (:meth:`describe`, :meth:`result`) go
straight to the shared store, so ANY replica answers for every view
regardless of which one holds the watch lease.
"""

import time
from typing import Any, Dict, List, Optional

from ..constants import FUGUE_TPU_CONF_VIEWS_MAX
from ..serve.fleet import parse_view_result_name, view_result_key
from .maintainer import ViewMaintainer
from .registry import ViewRegistry, ViewSpec
from .stats import ViewStats

__all__ = ["ViewService"]


class ViewService:
    def __init__(self, server: Any):
        self._server = server
        self._fleet = server._fleet
        self.stats = ViewStats()
        c = server.engine.conf
        self.registry = ViewRegistry(
            self._fleet.store.root,
            journal=server._journal,
            stats=self.stats,
            injector=server._injector,
            log=server.engine.log,
            max_views=int(c.get(FUGUE_TPU_CONF_VIEWS_MAX, 64)),
        )
        self.maintainer = ViewMaintainer(server, self.registry, self.stats)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        # close the register crash window from this replica's WAL before
        # the first tick (a spec restored here is maintained like any)
        self.registry.replay()
        self.maintainer.start()

    def stop(self, timeout: float = 5.0) -> None:
        self.maintainer.stop(timeout)

    # -- registration API (what /serve/register etc. call) -------------------
    def register(
        self,
        view_id: str,
        factory: Any,
        source: str,
        fmt: str = "",
        tenant: str = "default",
    ) -> Dict[str, Any]:
        spec = self.registry.register(view_id, tenant, source, fmt, factory)
        return self.describe(spec.id) or spec.to_payload()

    def unregister(self, view_id: str) -> bool:
        spec = self.registry.get(view_id)
        if spec is None:
            return False
        gens = self._generations(view_id)
        ok = self.registry.unregister(view_id)
        # retire the view's published payloads; its lease is released by
        # the holder's next tick (spec gone), or expires
        for g in gens:
            self._fleet.remove_result(view_result_key(view_id, g))
        return ok

    def _generations(self, view_id: str) -> List[int]:
        import os

        out = []
        try:
            names = os.listdir(self._fleet.results_dir)
        except OSError:
            return out
        for n in names:
            parsed = parse_view_result_name(n)
            if parsed is not None and parsed[0] == view_id:
                out.append(parsed[1])
        return sorted(out)

    # -- serving reads -------------------------------------------------------
    def describe(self, view_id: str) -> Optional[Dict[str, Any]]:
        spec = self.registry.get(view_id)
        if spec is None:
            return None
        head = self.registry.head(view_id)
        out: Dict[str, Any] = {
            "id": spec.id,
            "tenant": spec.tenant,
            "source": spec.source,
            "format": spec.fmt,
            "created_ts": spec.created_ts,
            "generation": int(head["gen"]) if head else 0,
            "maintainer": self.maintainer.holder(view_id),
        }
        if head is not None:
            out["as_of"] = float(head.get("as_of", 0.0))
            out["staleness_s"] = round(
                max(0.0, time.time() - out["as_of"]), 6
            )
            out["mode"] = head.get("mode")
            out["partitions"] = len(head.get("tokens") or ())
        return out

    def list(self) -> List[Dict[str, Any]]:
        out = []
        for spec in self.registry.list():
            d = self.describe(spec.id)
            if d is not None:
                out.append(d)
        return out

    def result(self, view_id: str) -> Optional[Dict[str, Any]]:
        """The view's latest published generation, from the shared store:
        ``{view, generation, as_of, staleness_s, frames, schemas}`` with
        ``frames`` as ``{yield_name: pandas}``. None before the first
        publish (or for an unknown id — callers distinguish via
        :meth:`describe`)."""
        head = self.registry.head(view_id)
        if head is None:
            return None
        payload = self._fleet.load_result(head["key"])
        if payload is None:
            return None
        frames = {name: item[0] for name, item in payload.items()}
        schemas = {name: item[1] for name, item in payload.items()}
        as_of = float(head.get("as_of", 0.0))
        return {
            "view": view_id,
            "generation": int(head["gen"]),
            "as_of": as_of,
            "staleness_s": round(max(0.0, time.time() - as_of), 6),
            "mode": head.get("mode"),
            "frames": frames,
            "schemas": schemas,
        }

    # -- observability (the "views" metrics source) ---------------------------
    def health(self) -> Dict[str, Any]:
        h = self.maintainer.health()
        h["views_active"] = len(self.registry.list())
        return h

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = self.stats.as_dict()
        specs = self.registry.list()
        out["views_active"] = len(specs)
        max_staleness = 0.0
        by_view: Dict[str, Dict[str, Any]] = {}
        now = time.time()
        for spec in specs:
            head = self.registry.head(spec.id)
            if head is None:
                by_view[spec.id] = {"generation": 0}
                continue
            lag = max(0.0, now - float(head.get("as_of", now)))
            max_staleness = max(max_staleness, lag)
            by_view[spec.id] = {
                "generation": int(head.get("gen", 0)),
                "lag_s": round(lag, 3),
            }
        out["max_staleness_s"] = round(max_staleness, 3)
        out["by_view"] = by_view
        return out

    def reset(self) -> None:
        self.stats.reset()
