"""Continuous-view counters — the ``views`` stats group.

Counter-exact parity with the flight recorder (ISSUE 20 satellite):
``registered`` == ``view.register`` events, ``lease_steals`` ==
``view.lease.steal``, ``refreshes`` == ``view.refresh``,
``generations_published`` == ``view.publish``, ``slo_breaches`` ==
``view.slo_breach``, ``unregistered`` == ``view.unregister`` — the
parity test holds each pair equal so a timeline reconstructed from the
event log alone tells the same story the counters do.

``steady_*`` counters exclude each view's cold first generation (which
is full by definition) so the steady-state delta ``skip_fraction`` —
``1 - steady_partitions_fresh / steady_partitions_total`` — measures
what the chaos gate actually asserts (≥ 0.9).
"""

import threading
from typing import Dict

__all__ = ["ViewStats"]

_COUNTERS = (
    "registered",
    "unregistered",
    "refreshes",
    "refresh_failures",
    "generations_published",
    "partitions_fresh",
    "partitions_total",
    "steady_partitions_fresh",
    "steady_partitions_total",
    "full_recomputes",
    "delta_refusals",
    "lease_acquires",
    "lease_steals",
    "lease_losses",
    "slo_boosts",
    "slo_breaches",
    "loop_ticks",
    "watch_errors",
    "superseded_evicted",
)


class ViewStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {k: 0 for k in _COUNTERS}

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def reset(self) -> None:
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
