"""Continuous views (ISSUE 20, docs/views.md): standing workflows with
incremental view maintenance, served by the fleet.

A tenant registers a workflow factory plus a watched source; the fleet
journals the registration through the serve WAL, exactly one replica
advances the view under a per-view watch lease (PR 14 claim + heartbeat
primitive), fresh partitions ride the PR 9 delta path through the normal
admission queue, and every replica serves the latest published
generation with ``as_of``/staleness metadata. Default OFF
(``fugue.tpu.views.enabled``).
"""

from .maintainer import ViewMaintainer, probe_name
from .registry import ViewRegistry, ViewSpec
from .service import ViewService
from .stats import ViewStats
from .watcher import (
    FileSourceWatcher,
    Observation,
    SourceWatcher,
    WatchError,
    classify_tokens,
    make_watcher,
)

__all__ = [
    "ViewService",
    "ViewRegistry",
    "ViewSpec",
    "ViewMaintainer",
    "ViewStats",
    "SourceWatcher",
    "FileSourceWatcher",
    "Observation",
    "WatchError",
    "classify_tokens",
    "make_watcher",
    "probe_name",
]
