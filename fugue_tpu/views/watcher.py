"""Source watchers: how a standing view notices that its input grew.

The watcher interface is deliberately tiny — :meth:`SourceWatcher.observe`
returns the source's current partition-token list (the same
``{path, size, mtime_ns}`` tokens the PR 9 delta manifests are keyed by)
plus the observation wall-clock, and :func:`classify_tokens` turns two
observations into one of three verdicts:

- ``unchanged`` — token lists identical; nothing to do.
- ``append`` — the previous list is a prefix of the current one (new
  partition files after it, or — for appendable csv/json — the last
  file grew in place). Exactly what the delta path serves incrementally.
- ``rewrite`` — anything else: a historical partition mutated, shrank,
  or vanished. The refusal ladder's steady-state rule applies: the view
  degrades to a FULL recompute for that generation — never to silent
  staleness — and the refusal is counted and reasoned in stats.

:class:`FileSourceWatcher` is the file/directory implementation riding
:func:`~fugue_tpu.cache.delta.list_source_partitions` — the exact
discovery the delta loader itself uses, so watcher and cache agree on
what a "partition" is. When that discovery REFUSES the layout
(hive/nested dirs, avro, schema sidecars), the watcher falls back to a
coarse recursive walk: change detection keeps working, every change just
classifies as ``rewrite`` (mode ``full``), with the refusal reason
carried on the observation. A different arrival surface (a log stream,
an object-store notification feed) slots in by subclassing
:class:`SourceWatcher`; the maintainer only ever talks to the interface.
"""

import glob as _glob
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..cache.delta import (
    _APPENDABLE_FORMATS,
    _DeltaRefused,
    _token,
    _tokens_equal,
    list_source_partitions,
)

__all__ = [
    "Observation",
    "SourceWatcher",
    "FileSourceWatcher",
    "WatchError",
    "classify_tokens",
    "make_watcher",
]


class WatchError(Exception):
    """The source could not be observed at all (missing, unreadable)."""


class Observation:
    """One look at a watched source: partition tokens in load order,
    resolved format, wall-clock of the look (what ``as_of`` means), and
    the delta-refusal reason when the layout is not delta-eligible."""

    __slots__ = ("tokens", "fmt", "ts", "refusal")

    def __init__(
        self,
        tokens: List[Dict[str, Any]],
        fmt: str,
        ts: float,
        refusal: Optional[str] = None,
    ):
        self.tokens = tokens
        self.fmt = fmt
        self.ts = ts
        self.refusal = refusal


def classify_tokens(
    prev: List[Dict[str, Any]],
    cur: List[Dict[str, Any]],
    fmt: str,
) -> Tuple[str, int]:
    """(verdict, fresh_partitions) between two token lists — mirrors the
    delta manifest matcher's append rules so the watcher's ``mode``
    prediction and the cache's actual behavior agree."""
    n = len(prev)
    if len(cur) < n:
        return "rewrite", len(cur)
    head = max(0, n - 1)
    for a, b in zip(prev[:head], cur[:head]):
        if not _tokens_equal(a, b):
            return "rewrite", len(cur)
    if n > 0:
        a, b = prev[n - 1], cur[n - 1]
        if not _tokens_equal(a, b):
            grown_in_place = (
                a.get("path") == b.get("path")
                and int(b.get("size", 0)) > int(a.get("size", 0))
                and fmt in _APPENDABLE_FORMATS
            )
            if not grown_in_place:
                return "rewrite", len(cur)
            return "append", len(cur) - n + 1
    fresh = len(cur) - n
    return ("append", fresh) if fresh > 0 else ("unchanged", 0)


class SourceWatcher:
    """Pluggable watcher interface. Implementations observe one source;
    the maintainer owns the polling cadence and the verdicts."""

    def observe(self) -> Observation:
        raise NotImplementedError

    def classify(
        self, prev_tokens: List[Dict[str, Any]], obs: Observation
    ) -> Tuple[str, int]:
        if obs.refusal is not None and prev_tokens != obs.tokens:
            # a non-delta-eligible layout that changed: always a full
            # recompute, whatever shape the change took
            return "rewrite", len(obs.tokens)
        return classify_tokens(prev_tokens, obs.tokens, obs.fmt)


class FileSourceWatcher(SourceWatcher):
    """Watches a file/directory/glob source through the delta loader's
    own partition discovery."""

    def __init__(self, source: str, fmt: str = ""):
        self.source = source
        self.fmt = fmt

    def observe(self) -> Observation:
        ts = time.time()
        try:
            tokens, fmt, _single = list_source_partitions(self.source, self.fmt)
            return Observation(tokens, fmt, ts)
        except _DeltaRefused as ex:
            return Observation(
                self._coarse_tokens(), self.fmt or "", ts, refusal=ex.reason
            )

    def _coarse_tokens(self) -> List[Dict[str, Any]]:
        """Fallback discovery for delta-refused layouts: every regular
        file under the source, in a deterministic order. Good enough to
        DETECT change; never used to load incrementally."""
        src = self.source
        if os.path.isfile(src):
            return [_token(src)]
        if os.path.isdir(src):
            out: List[Dict[str, Any]] = []
            for root, dirs, names in os.walk(src):
                dirs.sort()
                for n in sorted(names):
                    full = os.path.join(root, n)
                    if os.path.isfile(full):
                        out.append(_token(full))
            return out
        matched = sorted(f for f in _glob.glob(src) if os.path.isfile(f))
        if matched:
            return [_token(f) for f in matched]
        raise WatchError(f"watched source {src} does not exist")


def make_watcher(source: str, fmt: str = "") -> SourceWatcher:
    """Watcher factory — the one place a future non-file source type
    (e.g. a log stream) gets dispatched from."""
    return FileSourceWatcher(source, fmt)
