"""Fleet-wide view registry: durable specs + generation heads on the
shared store.

Layout, under ``<store_root>/views/``:

- ``<id>.view.json`` — the registration spec (tenant, watched source,
  format, base64-cloudpickled factory, creation epoch). Atomically
  published; its presence IS the registration, fleet-wide — every
  replica's maintainer loop discovers specs by scanning this directory,
  and every replica can serve the view.
- ``<id>.head.json`` — the monotonically versioned generation head:
  generation number, ``as_of`` (the source-observation wall-clock the
  generation reflects), the fleet result key holding the frames, the
  source tokens the generation was built from, and the refresh mode.
  Atomically replaced by the maintainer on every publish.
- ``<id>.tombstone.json`` — an unregistration marker. Registration WALs
  through the registering replica's fsync'd submission journal BEFORE
  the spec publish (the ``view.register`` fault site sits exactly in
  that window), so a replica SIGKILLed mid-register re-publishes the
  spec from its own WAL on restart. But the WAL is per-replica: a view
  registered on replica A and unregistered via replica B leaves A's WAL
  record unfinished forever, and A's replay would RESURRECT the view.
  The tombstone closes that hole — replay skips (and journals done for)
  any record older than a standing tombstone; a genuine re-registration
  clears it.

The registry never runs workflows and never takes leases — it is the
durable-state half of the subsystem; :class:`~fugue_tpu.views.maintainer.
ViewMaintainer` is the active half.
"""

import base64
import json
import os
import re
import time
from typing import Any, Dict, List, Optional

from ..obs.events import get_event_log
from ..resilience.fault import SITE_VIEW_REGISTER
from ..workflow._checkpoint import _atomic_publish, _best_effort_remove
from ..workflow.factory import validate_view_factory

__all__ = ["ViewSpec", "ViewRegistry", "VIEWS_SUBDIR"]

VIEWS_SUBDIR = "views"
_SPEC_SUFFIX = ".view.json"
_HEAD_SUFFIX = ".head.json"
_TOMB_SUFFIX = ".tombstone.json"

# filename-safe, and no "--": the fleet result key grammar
# (view--<id>--g<gen>) must parse back unambiguously
_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.\-]{0,63}$")


class ViewSpec:
    """One registered view, as serialized in ``<id>.view.json``."""

    __slots__ = ("id", "tenant", "source", "fmt", "factory_b64", "created_ts")

    def __init__(
        self,
        view_id: str,
        tenant: str,
        source: str,
        fmt: str,
        factory_b64: str,
        created_ts: float,
    ):
        self.id = view_id
        self.tenant = tenant
        self.source = source
        self.fmt = fmt
        self.factory_b64 = factory_b64
        self.created_ts = float(created_ts)

    @property
    def sid(self) -> str:
        """The WAL sid of this registration epoch."""
        from ..serve.journal import SubmissionJournal

        return SubmissionJournal.view_sid(self.id, self.created_ts)

    def build_factory(self) -> Any:
        import cloudpickle

        return cloudpickle.loads(base64.b64decode(self.factory_b64))

    def to_payload(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "tenant": self.tenant,
            "source": self.source,
            "format": self.fmt,
            "factory": self.factory_b64,
            "created_ts": self.created_ts,
        }

    @classmethod
    def from_payload(cls, payload: Dict[str, Any]) -> "ViewSpec":
        return cls(
            str(payload["id"]),
            str(payload.get("tenant", "default")),
            str(payload["source"]),
            str(payload.get("format", "")),
            str(payload["factory"]),
            float(payload.get("created_ts", 0.0)),
        )


class ViewRegistry:
    def __init__(
        self,
        store_root: str,
        journal: Any = None,
        stats: Any = None,
        injector: Any = None,
        log: Any = None,
        max_views: int = 64,
    ):
        self.dir = os.path.join(store_root, VIEWS_SUBDIR)
        self._journal = journal
        self._stats = stats
        self._injector = injector
        self._log = log
        self.max_views = int(max_views)

    # -- json-on-shared-disk plumbing ----------------------------------------
    def _path(self, view_id: str, suffix: str) -> str:
        return os.path.join(self.dir, view_id + suffix)

    def _write_json(self, path: str, payload: Dict[str, Any]) -> None:
        os.makedirs(self.dir, exist_ok=True)
        tmp = f"{path}.__tmp_{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True)
        _atomic_publish(tmp, path)

    @staticmethod
    def _read_json(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- registration --------------------------------------------------------
    def register(
        self,
        view_id: str,
        tenant: str,
        source: str,
        fmt: str,
        factory: Any,
    ) -> ViewSpec:
        """Durably register a standing view. WAL first, spec publish
        second — the crash between them is exactly what :meth:`replay`
        covers. Re-registering an identical (tenant, source, format) is
        idempotent; a conflicting re-use of a live id raises."""
        if not _ID_RE.match(view_id or "") or "--" in view_id:
            raise ValueError(
                f"invalid view id {view_id!r}: need filename-safe "
                f"[A-Za-z0-9_.-], <= 64 chars, no '--'"
            )
        existing = self.get(view_id)
        if existing is not None:
            if (
                existing.tenant == tenant
                and existing.source == source
                and existing.fmt == (fmt or "")
            ):
                return existing  # idempotent re-register (e.g. a client retry)
            raise ValueError(
                f"view {view_id!r} is already registered by tenant "
                f"{existing.tenant!r} on {existing.source!r}"
            )
        if self.max_views > 0 and len(self.list()) >= self.max_views:
            raise ValueError(
                f"view cap reached ({self.max_views}; fugue.tpu.views.max)"
            )
        validate_view_factory(factory)
        import cloudpickle

        spec = ViewSpec(
            view_id,
            tenant,
            source,
            fmt or "",
            base64.b64encode(cloudpickle.dumps(factory)).decode(),
            time.time(),
        )
        if self._journal is not None:
            self._journal.view_register(spec.sid, spec.to_payload())
        if self._injector is not None:
            self._injector.fire(SITE_VIEW_REGISTER)
        self._publish_spec(spec)
        get_event_log().emit(
            "view.register", view=view_id, tenant=tenant, source=source
        )
        if self._stats is not None:
            self._stats.inc("registered")
        return spec

    def _publish_spec(self, spec: ViewSpec) -> None:
        _best_effort_remove(self._path(spec.id, _TOMB_SUFFIX))
        self._write_json(self._path(spec.id, _SPEC_SUFFIX), spec.to_payload())

    def unregister(self, view_id: str) -> bool:
        """Retire a view: tombstone (so no replica's WAL replay can
        resurrect it), journal the terminal record, drop spec + head.
        Returns False for an unknown id."""
        spec = self.get(view_id)
        if spec is None:
            return False
        self._write_json(
            self._path(view_id, _TOMB_SUFFIX),
            {"id": view_id, "ts": time.time(), "created_ts": spec.created_ts},
        )
        if self._journal is not None:
            self._journal.view_unregister(spec.sid)
        _best_effort_remove(self._path(view_id, _SPEC_SUFFIX))
        _best_effort_remove(self._path(view_id, _HEAD_SUFFIX))
        get_event_log().emit("view.unregister", view=view_id, tenant=spec.tenant)
        if self._stats is not None:
            self._stats.inc("unregistered")
        return True

    def replay(self) -> int:
        """Close the register crash window from this replica's WAL:
        re-publish any journaled registration whose spec never became
        visible. Tombstoned (unregistered-elsewhere) records are closed
        out in this WAL instead. Returns how many specs were restored."""
        if self._journal is None:
            return 0
        restored = 0
        for rec in self._journal.view_unfinished():
            try:
                spec = ViewSpec.from_payload(rec.get("view") or {})
            except (KeyError, TypeError, ValueError):
                continue
            if self.get(spec.id) is not None:
                continue
            tomb = self._read_json(self._path(spec.id, _TOMB_SUFFIX))
            if tomb is not None and float(tomb.get("ts", 0.0)) >= spec.created_ts:
                self._journal.view_unregister(spec.sid)
                continue
            self._publish_spec(spec)
            get_event_log().emit(
                "view.register",
                view=spec.id,
                tenant=spec.tenant,
                source=spec.source,
                replayed=True,
            )
            if self._stats is not None:
                self._stats.inc("registered")
            restored += 1
            if self._log is not None:
                self._log.info(
                    "views: registration of %r replayed from the WAL "
                    "(spec publish never landed)",
                    spec.id,
                )
        return restored

    # -- read side -----------------------------------------------------------
    def get(self, view_id: str) -> Optional[ViewSpec]:
        payload = self._read_json(self._path(view_id, _SPEC_SUFFIX))
        if payload is None:
            return None
        try:
            return ViewSpec.from_payload(payload)
        except (KeyError, TypeError, ValueError):
            return None

    def list(self) -> List[ViewSpec]:
        out: List[ViewSpec] = []
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return out
        for name in names:
            if not name.endswith(_SPEC_SUFFIX):
                continue
            spec = self.get(name[: -len(_SPEC_SUFFIX)])
            if spec is not None:
                out.append(spec)
        return out

    # -- generation heads ----------------------------------------------------
    def head(self, view_id: str) -> Optional[Dict[str, Any]]:
        return self._read_json(self._path(view_id, _HEAD_SUFFIX))

    def publish_head(self, view_id: str, head: Dict[str, Any]) -> None:
        self._write_json(self._path(view_id, _HEAD_SUFFIX), head)
