"""ViewMaintainer: the loop that keeps standing views fresh.

One daemon thread per replica, ticking every ``fugue.tpu.views.poll_s``
seconds over every registered spec on the shared store:

1. **Lease** — a per-view watch lease (the PR 14
   :class:`~fugue_tpu.dist.lease.LeaseBoard` O_CREAT|O_EXCL claim +
   heartbeat primitive, under ``<store>/views/.leases``) guarantees
   exactly one replica advances each view; every replica still serves
   every view from the shared head + result store. A SIGKILLed
   maintainer's lease goes stealable once its heartbeat is provably
   stale (or its lease expires), and the survivor's next tick takes the
   view over — ``view.lease.steal`` in the flight recorder.
2. **Observe** — the view's :class:`~fugue_tpu.views.watcher.SourceWatcher`
   re-lists the source's partition tokens (the PR 9 delta manifest
   discovery) and classifies against the tokens the current generation
   was built from: ``unchanged`` / ``append`` (delta-served) /
   ``rewrite`` (the refusal ladder at steady state — FULL recompute for
   this generation, counted in ``delta_refusals``, never silent
   staleness).
3. **Refresh** — the view's factory is submitted through the NORMAL
   admission queue under the tenant's policy (interactive traffic still
   wins); a refresh whose wait puts the tenant's ``freshness_s`` SLO at
   risk is boosted by ``fugue.tpu.views.slo_boost`` priority points, and
   a breach emits ``view.slo_breach`` once per pending generation.
4. **Publish** — the yielded frames land in the fleet result store
   under ``view--<id>--g<gen>`` (monotonic generation), the head file
   flips atomically, superseded generations beyond
   ``keep_generations`` are deleted (the latest is pinned from the
   fleet's request-scoped LRU), and ``view.publish`` records it.
"""

import os
import re
import threading
import time
from typing import Any, Dict, Optional

from ..constants import (
    FUGUE_TPU_CONF_DIST_HB_DIR,
    FUGUE_TPU_CONF_DIST_HB_STALE_S,
    FUGUE_TPU_CONF_VIEWS_KEEP_GENERATIONS,
    FUGUE_TPU_CONF_VIEWS_LEASE_S,
    FUGUE_TPU_CONF_VIEWS_POLL_S,
    FUGUE_TPU_CONF_VIEWS_REFRESH_TIMEOUT_S,
    FUGUE_TPU_CONF_VIEWS_SLO_BOOST,
    FUGUE_TPU_CONF_VIEWS_SLO_RISK_FRACTION,
)
from ..dist.heartbeat import DEFAULT_STALE_AFTER_S
from ..dist.lease import LeaseBoard
from ..obs.events import get_event_log
from .registry import ViewRegistry, ViewSpec
from .watcher import WatchError, make_watcher

__all__ = ["ViewMaintainer", "probe_name"]


def probe_name(view_id: str) -> str:
    """Sampler-probe (→ prometheus gauge) name for one view's lag."""
    return "view_lag_s_" + re.sub(r"[^A-Za-z0-9_]", "_", view_id)


class ViewMaintainer:
    def __init__(self, server: Any, registry: ViewRegistry, stats: Any):
        self._server = server
        self._registry = registry
        self._stats = stats
        c = server.engine.conf
        self.owner = server.replica_id
        self.poll_s = float(c.get(FUGUE_TPU_CONF_VIEWS_POLL_S, 1.0))
        self.lease_s = float(c.get(FUGUE_TPU_CONF_VIEWS_LEASE_S, 15.0))
        self.keep_generations = max(
            1, int(c.get(FUGUE_TPU_CONF_VIEWS_KEEP_GENERATIONS, 2))
        )
        self.slo_boost = int(c.get(FUGUE_TPU_CONF_VIEWS_SLO_BOOST, 2))
        self.slo_risk_fraction = float(
            c.get(FUGUE_TPU_CONF_VIEWS_SLO_RISK_FRACTION, 0.8)
        )
        self.refresh_timeout_s = float(
            c.get(FUGUE_TPU_CONF_VIEWS_REFRESH_TIMEOUT_S, 600.0)
        )
        hb_dir = str(c.get(FUGUE_TPU_CONF_DIST_HB_DIR, "")) or None
        self._board = LeaseBoard(
            os.path.join(registry.dir, ".leases"),
            hb_dir=hb_dir,
            hb_stale_s=float(
                c.get(FUGUE_TPU_CONF_DIST_HB_STALE_S, DEFAULT_STALE_AFTER_S)
            ),
        )
        self._lock = threading.Lock()
        self._held: Dict[str, bool] = {}  # view id -> currently maintaining
        self._pending_since: Dict[str, float] = {}  # change observed, not published
        self._breached: Dict[str, int] = {}  # view id -> gen already breach-logged
        self._probes: Dict[str, bool] = {}
        self._last_tick = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._log = server.engine.log

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, name="fugue-view-maintainer", daemon=True
            )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop_evt.set()
        if thread is not None:
            thread.join(timeout)
        # release held leases so a peer replica takes over immediately
        # instead of waiting out the lease; unregister this process's
        # lag probes (the views themselves live on)
        with self._lock:
            held = list(self._held)
            self._held.clear()
            probes = list(self._probes)
            self._probes.clear()
        for vid in held:
            self._board.release(vid, self.owner)
        from ..obs import get_sampler

        for name in probes:
            get_sampler().unregister_probe(name)

    def halt_for_test(self) -> None:
        """Stop the loop WITHOUT releasing leases — simulates a wedged
        (or killed) maintainer so lease-steal paths can be exercised
        in-process."""
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop_evt.set()
        if thread is not None:
            thread.join(5.0)

    def health(self) -> Dict[str, Any]:
        with self._lock:
            alive = self._thread is not None and self._thread.is_alive()
            last = self._last_tick
            held = sorted(self._held)
        return {
            "loop_alive": alive,
            "last_tick_age_s": (
                round(time.monotonic() - last, 3) if last else None
            ),
            "maintaining": held,
        }

    def holder(self, view_id: str) -> Optional[str]:
        cur = self._board.read(view_id)
        return cur.get("owner") if cur else None

    # -- the loop ------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.tick_once()
            except Exception as ex:  # the loop must survive anything
                self._stats.inc("watch_errors")
                self._log.warning("views: maintainer tick failed: %s", ex)
            self._stop_evt.wait(self.poll_s)

    def tick_once(self) -> None:
        """One synchronous maintenance pass (the loop body; also the
        test hook — deterministic, no thread needed)."""
        self._stats.inc("loop_ticks")
        with self._lock:
            self._last_tick = time.monotonic()
        specs = self._registry.list()
        ids = {s.id for s in specs}
        # views unregistered elsewhere: drop their leases + local state
        # (their lag probes self-remove via ProbeGone on the next sample)
        with self._lock:
            gone = [vid for vid in self._held if vid not in ids]
            for vid in gone:
                self._held.pop(vid, None)
            for vid in list(self._pending_since):
                if vid not in ids:
                    self._pending_since.pop(vid, None)
                    self._breached.pop(vid, None)
        for vid in gone:
            self._board.release(vid, self.owner)
        for spec in specs:
            if self._stop_evt.is_set():
                return
            try:
                self._maintain(spec)
            except WatchError as ex:
                self._stats.inc("watch_errors")
                self._log.warning("views: %s unobservable: %s", spec.id, ex)
            except Exception as ex:
                self._stats.inc("refresh_failures")
                self._log.warning("views: refresh of %s failed: %s", spec.id, ex)

    # -- per-view work -------------------------------------------------------
    def _maintain(self, spec: ViewSpec) -> None:
        self._ensure_probe(spec.id)
        if not self._acquire(spec.id):
            return
        obs = make_watcher(spec.source, spec.fmt).observe()
        head = self._registry.head(spec.id)
        now = time.time()
        reason: Optional[str] = None
        if head is None:
            if not obs.tokens:
                return  # registered over an empty source: wait for data
            mode, fresh, total = "full", len(obs.tokens), len(obs.tokens)
            if obs.refusal is not None:
                reason = obs.refusal
        else:
            verdict, fresh = make_watcher(spec.source, spec.fmt).classify(
                head.get("tokens") or [], obs
            )
            if verdict == "unchanged":
                with self._lock:
                    self._pending_since.pop(spec.id, None)
                return
            total = len(obs.tokens)
            if verdict == "append" and obs.refusal is None:
                mode = "delta"
            else:
                mode = "full"
                fresh = total
                reason = obs.refusal or "historical partition changed (rewrite)"
                self._stats.inc("delta_refusals")
                self._stats.inc("full_recomputes")
        with self._lock:
            self._pending_since.setdefault(spec.id, now)
            pending_since = self._pending_since[spec.id]
        gen = (int(head["gen"]) if head else 0) + 1
        prio, boosted = self._priority(spec, gen, now - pending_since)
        get_event_log().emit(
            "view.refresh",
            view=spec.id,
            gen=gen,
            mode=mode,
            fresh=fresh,
            total=total,
            priority=prio,
            reason=reason,
        )
        self._stats.inc("refreshes")
        self._stats.inc("partitions_fresh", fresh)
        self._stats.inc("partitions_total", total)
        if head is not None:
            # steady-state counters exclude the cold first generation so
            # skip_fraction measures what delta actually saves
            self._stats.inc("steady_partitions_fresh", fresh)
            self._stats.inc("steady_partitions_total", total)
        self._refresh(spec, gen, obs, mode, prio, boosted, reason)

    def _acquire(self, view_id: str) -> bool:
        """Hold (or take) the view's watch lease. Emits the typed
        view.lease.* events only on transitions, with counter parity."""
        with self._lock:
            held = view_id in self._held
        if held:
            if self._board.renew(view_id, self.owner, self.lease_s):
                return True
            with self._lock:
                self._held.pop(view_id, None)
            self._stats.inc("lease_losses")
            return False
        prev = self._board.read(view_id)
        owned, _cur = self._board.try_acquire(view_id, self.owner, self.lease_s)
        if not owned:
            return False
        with self._lock:
            self._held[view_id] = True
        prev_owner = prev.get("owner") if prev else None
        if prev_owner not in (None, self.owner):
            self._stats.inc("lease_steals")
            get_event_log().emit(
                "view.lease.steal",
                view=view_id,
                owner=self.owner,
                prev_owner=prev_owner,
                reason=self._board.steal_reason(prev) or "expired",
            )
        else:
            self._stats.inc("lease_acquires")
            get_event_log().emit(
                "view.lease.acquire", view=view_id, owner=self.owner
            )
        return True

    def _priority(
        self, spec: ViewSpec, gen: int, lag_s: float
    ) -> "tuple[int, bool]":
        pol = self._server._policy(spec.tenant)
        base = (
            pol.priority if pol.priority is not None
            else self._server.default_priority
        )
        slo = pol.freshness_s
        if slo is None or slo <= 0:
            return int(base), False
        boosted = lag_s >= self.slo_risk_fraction * slo
        if boosted:
            self._stats.inc("slo_boosts")
        if lag_s > slo:
            with self._lock:
                first = self._breached.get(spec.id) != gen
                self._breached[spec.id] = gen
            if first:
                self._stats.inc("slo_breaches")
                get_event_log().emit(
                    "view.slo_breach",
                    view=spec.id,
                    tenant=spec.tenant,
                    gen=gen,
                    lag_s=round(lag_s, 3),
                    slo_s=slo,
                )
        return (max(0, int(base) - self.slo_boost) if boosted else int(base)), boosted

    def _refresh(
        self,
        spec: ViewSpec,
        gen: int,
        obs: Any,
        mode: str,
        prio: int,
        boosted: bool,
        reason: Optional[str],
    ) -> None:
        from ..serve.fleet import view_result_key

        sub = self._server.submit(
            spec.build_factory(),
            tenant=spec.tenant,
            priority=prio,
            idempotency_key=f"view:{spec.id}:g{gen}",
        )
        result = sub.result(timeout=self.refresh_timeout_s)
        frames = self._server._extract_frames(result)
        if frames is None:
            self._stats.inc("refresh_failures")
            self._log.warning(
                "views: %s generation %d yielded unpublishable frames "
                "(unbounded/device-resident); view head NOT advanced",
                spec.id,
                gen,
            )
            return
        # the publish gate: still the maintainer? A stolen lease means a
        # peer may already be building this generation — publishing now
        # could double-publish a generation number
        if not self._board.renew(spec.id, self.owner, self.lease_s):
            with self._lock:
                self._held.pop(spec.id, None)
            self._stats.inc("lease_losses")
            return
        key = view_result_key(spec.id, gen)
        fleet = self._server._fleet
        fleet.publish_result(key, frames)
        self._registry.publish_head(
            spec.id,
            {
                "id": spec.id,
                "gen": gen,
                "as_of": obs.ts,
                "key": key,
                "tokens": obs.tokens,
                "mode": mode,
                "reason": reason,
                "slo_boosted": boosted,
                "published_ts": time.time(),
                "maintainer": self.owner,
            },
        )
        get_event_log().emit(
            "view.publish",
            view=spec.id,
            gen=gen,
            key=key,
            as_of=round(obs.ts, 6),
            mode=mode,
        )
        self._stats.inc("generations_published")
        with self._lock:
            self._pending_since.pop(spec.id, None)
            self._breached.pop(spec.id, None)
        # retention: superseded generations beyond keep_generations go;
        # the latest is additionally PINNED from the fleet's own LRU
        # (fleet.py), so this is the only eviction path for view results
        cutoff = gen - self.keep_generations
        for g in range(max(1, cutoff - 8), cutoff + 1):
            if fleet.remove_result(view_result_key(spec.id, g)):
                self._stats.inc("superseded_evicted")

    # -- observability -------------------------------------------------------
    def _ensure_probe(self, view_id: str) -> None:
        name = probe_name(view_id)
        with self._lock:
            if name in self._probes:
                return
            self._probes[name] = True
        from ..obs import get_sampler
        from ..obs.sampler import ProbeGone

        registry = self._registry

        def lag() -> float:
            head = registry.head(view_id)
            if registry.get(view_id) is None:
                raise ProbeGone()
            if head is None:
                return 0.0
            return max(0.0, time.time() - float(head.get("as_of", 0.0)))

        get_sampler().register_probe(name, lag)
