"""Device-resident staged exchange — the ``device_exchange`` strategy rung.

Joins whose sides exceed the PER-DEVICE budget but fit AGGREGATE mesh
memory (budget × shards) do not need the spill path's host detour: the
rows are already device-resident, only their *placement* is wrong. This
module moves them with the memory-efficient staged redistribution
schedule of arXiv:2112.01075 — one hop at a time around the mesh ring —
instead of the single-shot ``all_to_all`` the copartition rung uses:

1. destinations come from the same splitmix64 key hash
   (``ops/shuffle.compute_dest``) and the same per-destination rank /
   count negotiation as the in-device exchange, so chain steps and
   bucketing share ONE compiled program family;
2. every shard sorts its rows ONCE by hop distance (stable, so within-
   destination order survives), after which the rows destined ``k``
   shards ahead are a contiguous block and each stage's send buffer is a
   ``cap``-row slice of it — no per-stage O(rows) scatter — where
   ``cap`` is sized so the buffer's bytes stay under the per-stage
   payload cap (``fugue.tpu.shuffle.device_exchange.stage_bytes``,
   default 1/8 of ``fugue.tpu.shuffle.device_budget_bytes``);
3. ONE ``ppermute`` ring shift moves each shard's stage buffer ``k``
   hops forward — peak in-flight collective payload is a single stage
   buffer per device, never the ``shards × cap`` of an all-to-all;
4. received rows compact-append into output buffers sized by the true
   max received total; hops whose block exceeds ``cap`` run multiple
   bounded rounds.

The whole schedule is device-to-device: zero host decode, zero H2D
round trips between partition and join kernel (the acceptance criterion
the spill path's mem tier cannot meet). Spill remains the bit-identical
fallback past aggregate memory or behind the
``fugue.tpu.shuffle.device_exchange.enabled`` kill-switch.
"""

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..parallel.mesh import ROW_AXIS, num_row_shards, row_sharding
from ..ops import collectives
from ..ops.shuffle import (
    _get_compiled_counts,
    _get_compiled_lenmask,
    compute_dest,
)
from .._utils.jax_compat import shard_map

__all__ = [
    "stage_capacity_rows",
    "staged_exchange_rows",
    "staged_copartition_by_keys",
]

_COMPILE_CACHE: Dict[Any, Any] = {}


def _pow2_ceil(n: int) -> int:
    return 1 << (max(1, int(n)) - 1).bit_length()


def _pow2_floor(n: int) -> int:
    return 1 << (max(1, int(n)).bit_length() - 1)


def _row_bytes(arrays: Dict[str, Any]) -> int:
    """Bytes one row occupies in the stage buffers: every payload array's
    itemsize plus the validity bool that travels with it."""
    return 1 + sum(np.dtype(a.dtype).itemsize for a in arrays.values())


def stage_capacity_rows(stage_bytes: int, row_bytes: int) -> int:
    """Stage-buffer row capacity under the per-stage byte cap, rounded
    DOWN to a pow2 (rounding up could overshoot the budget; rounding down
    keeps compiled variants reusable AND the payload provably bounded)."""
    return _pow2_floor(max(1, int(stage_bytes) // max(1, int(row_bytes))))


# fused-schedule unroll ceiling: shards × rounds stages trace into ONE
# program below this, so the whole schedule costs a single dispatch; past
# it (tiny stage caps on big meshes) compile time would balloon, and the
# per-stage dispatch loop takes over
_MAX_FUSED_STAGES = 64


def _sorted_prep(shards: int, cap: int, dest: Any, valid: Any, arrs: Any):
    """Sort a shard's rows ONCE by hop distance — stable, so within-
    destination order (the rank) survives — turning every stage's send
    block into a contiguous slice. The per-stage alternative (scatter the
    window's rows into the stage buffer) costs O(rows) EVERY stage; with
    rows >> cap that scatter dominated the whole schedule. Invalid rows
    sort past every real hop; the sorted arrays are padded by ``cap``
    rows so a window starting at the block tail never clamps back into
    live rows. Returns the hop block offsets (``shards + 1`` entries:
    ``offs[k]`` = first sorted position with hop ``k``) plus the sorted,
    padded arrays. Shared by the fused schedule and the per-stage prep
    kernel so the two dispatch modes can never drift."""
    import jax.numpy as jnp
    from jax import lax

    n = dest.shape[0]
    me = lax.axis_index(ROW_AXIS)
    hop = lax.rem(
        dest.astype(jnp.int32) - me + np.int32(shards), np.int32(shards)
    )
    big_hop = jnp.where(valid, hop, np.int32(shards))
    iota = lax.iota(jnp.int32, n)
    sorted_hop, perm = lax.sort((big_hop, iota), num_keys=1)
    counts = jnp.zeros(shards + 1, dtype=jnp.int32).at[sorted_hop].add(1)
    offs = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(counts[:shards])]
    )
    pad = [
        jnp.concatenate([a[perm], jnp.zeros(cap, dtype=a.dtype)])
        for a in arrs
    ]
    return offs, pad


def _stage_body(
    k: int,
    lo: Any,
    cap: int,
    out_cap: int,
    offs: Any,
    sarrs: Any,
    out_len: Any,
    bufs: Any,
) -> Tuple[Any, list]:
    """ONE stage of the staged schedule: the ``[lo, lo+cap)`` window of
    the hop-``k`` block (rows pre-sorted by ``_sorted_prep``, so the
    window is ONE ``dynamic_slice``), ONE ``ppermute`` ring shift
    delivers it, and received rows compact-append into the output
    buffers. Peak collective payload = one stage buffer (``cap`` rows),
    independent of both skew and shard count; ``k == 0`` is the local hop
    (no comm). Shared by the per-stage kernel and the fused schedule so
    the two dispatch modes can never drift."""
    import jax.numpy as jnp
    from jax import lax

    start = offs[k] + lo
    cnt = jnp.clip(offs[k + 1] - start, 0, np.int32(cap))
    send_valid = lax.iota(jnp.int32, cap) < cnt
    # pack the stage into ONE contiguous byte payload — the validity lane
    # plus every array's window slice bitcast to bytes — so each stage is
    # exactly ONE collective. Per-collective sync dominates a stage on
    # mesh backends; per-array ppermutes multiplied that by the column
    # count. The payload is cap × row_bytes: the exact quantity
    # ``stage_capacity_rows`` budgets and ``peak_exchange`` records.
    lanes = [send_valid.astype(jnp.uint8)]
    for a in sarrs:
        send = lax.dynamic_slice_in_dim(a, start, cap)
        if np.dtype(a.dtype).itemsize == 1:
            lanes.append(send.astype(jnp.uint8))
        else:
            lanes.append(lax.bitcast_convert_type(send, jnp.uint8).reshape(-1))
    recv = collectives.ppermute(jnp.concatenate(lanes), ROW_AXIS, k)
    recv_valid = recv[:cap].astype(bool)
    cum = jnp.cumsum(recv_valid.astype(jnp.int32))
    pos = out_len[0] + cum - 1
    idx = jnp.where(recv_valid, pos, out_cap)
    new_bufs = []
    off = cap
    for a, buf in zip(sarrs, bufs):
        itemsize = np.dtype(a.dtype).itemsize
        chunk = recv[off : off + cap * itemsize]
        off += cap * itemsize
        if itemsize == 1:
            got = chunk.astype(a.dtype)
        else:
            got = lax.bitcast_convert_type(
                chunk.reshape(cap, itemsize), a.dtype
            )
        new_bufs.append(buf.at[idx].set(got, mode="drop"))
    new_len = out_len[0] + cum[-1]
    return new_len[None], new_bufs


def _get_compiled_prep(mesh: Any, dtypes: Tuple[Any, ...], cap: int):
    """Standalone sort-by-hop prep for the per-stage dispatch mode:
    returns the hop block offsets plus the sorted, ``cap``-padded arrays
    the hop kernels slice from. (The fused schedule inlines
    ``_sorted_prep`` instead — one dispatch covers prep AND stages.)"""
    import jax
    from jax.sharding import PartitionSpec as P

    shards = num_row_shards(mesh)
    cache_key = ("xprep", mesh, dtypes, cap)
    if cache_key not in _COMPILE_CACHE:

        def kernel(dest: Any, valid: Any, *arrs: Any):
            offs, pad = _sorted_prep(shards, cap, dest, valid, arrs)
            return (offs,) + tuple(pad)

        row = P(ROW_AXIS)
        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=(row, row) + tuple(row for _ in dtypes),
                out_specs=tuple(row for _ in range(1 + len(dtypes))),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_hop(
    mesh: Any, dtypes: Tuple[Any, ...], cap: int, out_cap: int, k: int
):
    """Per-stage dispatch variant: one jitted program per hop distance,
    round window passed as a replicated scalar, send blocks sliced from
    the ``_get_compiled_prep`` output. Used when the schedule is too long
    to unroll (``> _MAX_FUSED_STAGES`` stages)."""
    import jax
    from jax.sharding import PartitionSpec as P

    cache_key = ("xhop", mesh, dtypes, cap, out_cap, k)
    if cache_key not in _COMPILE_CACHE:

        def kernel(offs: Any, out_len: Any, r: Any, *rest: Any):
            sarrs = rest[: len(dtypes)]
            bufs = rest[len(dtypes) :]
            new_len, new_bufs = _stage_body(
                k, r[0] * cap, cap, out_cap, offs, sarrs, out_len, bufs
            )
            return (new_len,) + tuple(new_bufs)

        row = P(ROW_AXIS)
        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=(row, row, P())
                + tuple(row for _ in range(2 * len(dtypes))),
                out_specs=tuple(row for _ in range(1 + len(dtypes))),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_schedule(
    mesh: Any, dtypes: Tuple[Any, ...], cap: int, out_cap: int, rounds: int
):
    """Fused variant: the WHOLE staged schedule — every hop distance ×
    every round window, unrolled at trace time — as one jitted program,
    so a side's exchange costs a single dispatch instead of
    ``shards × rounds`` (the dominant cost on dispatch-bound meshes). An
    ``optimization_barrier`` seals every stage's full state before the
    next stage's ops, so XLA cannot overlap two stages' collectives — the
    one-stage-buffer in-flight payload bound survives the fusion."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    shards = num_row_shards(mesh)
    cache_key = ("xsched", mesh, dtypes, cap, out_cap, rounds)
    if cache_key not in _COMPILE_CACHE:

        def kernel(dest: Any, valid: Any, out_len: Any, *rest: Any):
            n = len(dtypes)
            offs, sarrs = _sorted_prep(
                shards, cap, dest, valid, rest[:n]
            )
            bufs = list(rest[n:])
            for k in range(shards):
                for r in range(rounds):
                    out_len, bufs = _stage_body(
                        k, np.int32(r * cap), cap, out_cap,
                        offs, sarrs, out_len, bufs,
                    )
                    # seal the stage: every value the next stage reads
                    # passes through the barrier, so none of its sends
                    # can be hoisted before this stage's receives land
                    sealed = lax.optimization_barrier(
                        tuple([out_len] + bufs + sarrs + [offs])
                    )
                    out_len = sealed[0]
                    bufs = list(sealed[1 : 1 + n])
                    sarrs = list(sealed[1 + n : 1 + 2 * n])
                    offs = sealed[1 + 2 * n]
            return (out_len,) + tuple(bufs)

        row = P(ROW_AXIS)
        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=(row, row, row)
                + tuple(row for _ in range(2 * len(dtypes))),
                out_specs=tuple(row for _ in range(1 + len(dtypes))),
            )
        )
    return _COMPILE_CACHE[cache_key]


def staged_exchange_rows(
    mesh: Any,
    arrays: Dict[str, Any],
    valid: Any,
    dest: Any,
    stage_bytes: int,
    stats: Optional[Any] = None,
) -> Tuple[Dict[str, Any], Any, int]:
    """Move rows to their destination shards with the staged one-hop-at-
    a-time schedule. Same contract as ``ops.shuffle.exchange_rows`` —
    returns ``(new_arrays, new_valid_mask, received_row_count)`` — but
    per-stage collective payload never exceeds ``stage_bytes`` per device
    (the high-water lands on ``stats.device_exchange_peak_stage_bytes``).
    """
    import jax

    shards = num_row_shards(mesh)
    mx, total, mr = jax.device_get(_get_compiled_counts(mesh)(dest, valid))
    need = int(mx[0])
    row_bytes = _row_bytes(arrays)
    cap = min(_pow2_ceil(need), stage_capacity_rows(stage_bytes, row_bytes))
    rounds = max(1, -(-need // cap))  # ceil; 1 even when nothing moves
    out_cap = _pow2_ceil(int(mr[0]))
    dtypes = tuple(str(a.dtype) for a in arrays.values())
    sharding = row_sharding(mesh)
    out_len = jax.device_put(np.zeros(shards, dtype=np.int32), sharding)
    bufs = [
        jax.device_put(np.zeros(shards * out_cap, dtype=a.dtype), sharding)
        for a in arrays.values()
    ]
    if shards * rounds <= _MAX_FUSED_STAGES:
        # one dispatch for the whole schedule (sort-by-hop prep plus
        # hops × rounds unrolled, stage order identical to the loop below)
        outs = _get_compiled_schedule(mesh, dtypes, cap, out_cap, rounds)(
            dest, valid, out_len, *arrays.values(), *bufs
        )
        out_len = outs[0]
        bufs = list(outs[1:])
    else:
        prepped = _get_compiled_prep(mesh, dtypes, cap)(
            dest, valid, *arrays.values()
        )
        offs, sarrs = prepped[0], prepped[1:]
        for k in range(shards):
            step = _get_compiled_hop(mesh, dtypes, cap, out_cap, k)
            for r in range(rounds):
                outs = step(
                    offs,
                    out_len,
                    np.asarray([r], dtype=np.int32),
                    *sarrs,
                    *bufs,
                )
                out_len = outs[0]
                bufs = list(outs[1:])
    new_valid = _get_compiled_lenmask(mesh, out_cap)(out_len)
    if stats is not None:
        stats.inc("device_exchange_stages", shards * rounds)
        stats.inc("device_exchange_rows", int(total[0]))
        stats.inc("device_exchange_bytes", int(total[0]) * row_bytes)
        stats.peak_exchange(cap * row_bytes)
    new_arrays = {n: b for n, b in zip(arrays.keys(), bufs)}
    return new_arrays, new_valid, int(total[0])


def staged_copartition_by_keys(
    mesh: Any,
    left_cols: Dict[str, Any],
    left_valid: Any,
    left_key_names: List[str],
    right_keys: List[Any],
    right_values: List[Tuple[str, Any, Any]],
    right_valid: Any,
    stage_bytes: int,
    stats: Optional[Any] = None,
) -> Tuple[Dict[str, Any], Any, List[Any], List[Tuple[str, Any, Any]], Any]:
    """Co-partition both join sides by key hash with the STAGED exchange
    (one schedule per side) — the device_exchange analogue of
    ``ops.join.copartition_by_keys``, shared the same way by the
    unique-probe and expansion joins so a dup-key fallback never repeats
    the exchange."""
    n_keys = len(left_key_names)
    l_dest = compute_dest(
        mesh, "hash", [left_cols[k] for k in left_key_names], left_valid
    )
    r_dest = compute_dest(mesh, "hash", list(right_keys), right_valid)
    left_cols, left_valid, _ = staged_exchange_rows(
        mesh, dict(left_cols), left_valid, l_dest, stage_bytes, stats
    )
    r_payload = {f"__k{i}__": a for i, a in enumerate(right_keys)}
    r_payload.update({f"__v__{n}": a for n, a, _ in right_values})
    r_payload, right_valid, _ = staged_exchange_rows(
        mesh, r_payload, right_valid, r_dest, stage_bytes, stats
    )
    right_keys = [r_payload[f"__k{i}__"] for i in range(n_keys)]
    right_values = [
        (n, r_payload[f"__v__{n}"], f) for n, _, f in right_values
    ]
    return left_cols, left_valid, right_keys, right_values, right_valid
